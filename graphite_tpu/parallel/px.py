"""The packed shard_map exchange context (``ParallelCtx``).

The multi-chip form of the engine runs ONE program per device under
``jax.shard_map``: big per-tile arrays (trace, cache meta words, the
directory, branch-predictor bits, miss-type bitmaps) live block-local —
each device holds rows ``[i*Tl, (i+1)*Tl)`` of the tile axis — while every
per-lane ``[T]`` control vector, the ``[T, T]`` mailbox matrices, the sync
tables and the NoC state stay REPLICATED and are recomputed identically on
every device (integer math, deterministic, so the replicas cannot diverge).

Cross-device data motion is then exactly the engine's phase structure:
each protocol phase gathers its lanes' rows from the block-local arrays,
packs every gathered field into ONE ``[Tl, K]`` int64 descriptor, and
all-gathers it — a handful of collectives per subquantum iteration instead
of the ~270 tiny per-scatter collectives GSPMD inserts for the same
program (PERF.md "Multi-device step wall-clock"; the reference's analog of
this exchange is the process-striped directory traffic over
`common/transport/socktransport.cc`, one TCP message per protocol hop).

``ParallelCtx`` is threaded through `engine/step.py` and
`memory/engine.py`; the default ``IDENT`` context makes every operation an
identity, so the single-device path compiles to exactly the program it
always was.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

I64 = jnp.int64


@dataclasses.dataclass(frozen=True)
class ParallelCtx:
    """Identity (single-device) or shard_map (per-device block) context.

    axis: mesh axis name the tile dimension is sharded over, or None.
    n_dev: number of devices on that axis.
    """

    axis: str | None = None
    n_dev: int = 1

    @property
    def sharded(self) -> bool:
        # n_dev > 1: a 1-device tile axis needs no exchange — ag()/lo()
        # must be identities so solo programs lower to ZERO collective
        # equations and provably pay no fabric tax (the comms analyzer
        # pins this; a size-1 all_gather would still round-trip every
        # field through the int64 descriptor packing)
        return self.axis is not None and self.n_dev > 1

    # -- local block addressing ------------------------------------------

    def lo(self, tree):
        """Slice full [T, ...] arrays down to this device's [Tl, ...] block
        (identity when single-device).  Works on pytrees."""
        if not self.sharded:
            return tree

        def f(x):
            T = x.shape[0]
            Tl = T // self.n_dev
            i = jax.lax.axis_index(self.axis)
            return jax.lax.dynamic_slice_in_dim(x, i * Tl, Tl, axis=0)

        return jax.tree.map(f, tree)

    # -- the packed exchange ---------------------------------------------

    def ag(self, tree):
        """All-gather local [Tl, ...] arrays to full [T, ...] via ONE
        packed [Tl, K] int64 collective (identity when single-device).

        Every leaf is flattened to [Tl, k_i], widened to int64, and
        concatenated; the single tiled all_gather moves the whole
        descriptor; leaves are then split back out and narrowed.  One
        collective per call regardless of how many fields ride it —
        per-collective latency, not bytes, is what the virtual mesh (and
        real ICI) charges for."""
        if not self.sharded:
            return tree
        leaves, tdef = jax.tree.flatten(tree)
        if not leaves:
            return tree
        cols = []
        meta = []
        for leaf in leaves:
            k = 1
            for d in leaf.shape[1:]:
                k *= d
            meta.append((leaf.shape, leaf.dtype, k))
            flat = leaf.reshape(leaf.shape[0], k)
            if leaf.dtype == jnp.uint32:
                # widen via uint64 so values >= 2^31 survive the round trip
                flat = flat.astype(jnp.uint64).astype(I64)
            else:
                flat = flat.astype(I64)
            cols.append(flat)
        buf = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=1)
        full = jax.lax.all_gather(buf, self.axis, axis=0, tiled=True)
        out = []
        off = 0
        for shape, dtype, k in meta:
            piece = full[:, off:off + k]
            off += k
            if dtype == jnp.uint32:
                piece = piece.astype(jnp.uint64).astype(dtype)
            elif dtype == jnp.bool_:
                piece = piece != 0
            else:
                piece = piece.astype(dtype)
            out.append(piece.reshape((full.shape[0],) + tuple(shape[1:])))
        return jax.tree.unflatten(tdef, out)

    def lo_const(self, x):
        """lo() for compile-time per-tile constants: ints and None pass
        through, [T]-shaped tables are sliced (e.g. heterogeneous cache
        set moduli)."""
        if x is None or isinstance(x, int) or not hasattr(x, "shape"):
            return x
        if len(getattr(x, "shape", ())) == 0:
            return x
        return self.lo(jnp.asarray(x))

    # -- local per-lane writes (operands already block-local) ------------

    def lane_col_add(self, arr, col, delta):
        """``arr[t, col[t]] += delta[t]`` on this device's rows; arr is
        block-local [Tl, K] and col/delta are block-local [Tl] (callers
        px.lo replicated operands first)."""
        lt = jnp.arange(arr.shape[0], dtype=jnp.int32)
        return arr.at[lt, col].add(delta.astype(arr.dtype))

    def entry_set(self, arr, sets, way, mask, value):
        """``arr[t, sets[t], way[t]] = value[t] where mask[t]`` on this
        device's rows; arr is block-local [Tl, S, W] and every operand is
        block-local [Tl] (callers px.lo replicated operands first; value
        may be a scalar).  Written add-a-delta so the scatter aliases in
        place (per-lane rows are unique)."""
        lt = jnp.arange(arr.shape[0], dtype=jnp.int32)
        cur = arr[lt, sets, way]
        value = jnp.broadcast_to(jnp.asarray(value, arr.dtype), cur.shape)
        return arr.at[lt, sets, way].add(
            jnp.where(mask, value - cur, jnp.zeros_like(cur)),
            unique_indices=True, indices_are_sorted=True)


IDENT = ParallelCtx()
