"""Multi-chip distribution: the tile axis sharded over the device mesh.

Graphite distributes by striping target tiles across host processes
connected by TCP (`common/misc/config.cc:198-228`, `[process_map]`
`carbon_sim.cfg:119-139`, `common/transport/socktransport.cc`).  The
TPU-native equivalent (SURVEY §2.10): the SoA tile axis is sharded over a
`jax.sharding.Mesh`; coherence/user messages become sharded scatter/gather
(XLA inserts the ICI collectives); the emesh block process-mapping
(`network_model_emesh_hop_by_hop.cc:366-433`) becomes the sharding layout
that keeps neighbor exchanges on adjacent devices.
"""

from graphite_tpu.parallel.mesh import (
    TILE_AXIS,
    make_tile_mesh,
    shard_sim,
    state_shardings,
    trace_shardings,
)

__all__ = [
    "TILE_AXIS",
    "make_tile_mesh",
    "shard_sim",
    "state_shardings",
    "trace_shardings",
]
