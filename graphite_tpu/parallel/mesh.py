"""Device-mesh construction and state/trace sharding rules.

Sharding policy: every array whose leading dimension is the tile count is
sharded on that axis (`PartitionSpec("tiles")`); everything else (sync-object
tables, scalars) is replicated.  The mailbox tensor [dst, src, depth] is
sharded on dst — a tile's inbox lives with its shard, like Graphite's
per-tile `_netQueue` living in the owning process (`network.cc:358-460`) —
and cross-shard sends become XLA scatter collectives over ICI, replacing the
full-mesh TCP of `socktransport.cc`.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from graphite_tpu.engine.state import DeviceTrace, SimState

TILE_AXIS = "tiles"


def _shard_map(f, *, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: new API (jax >= 0.5,
    check_vma) when present, else jax.experimental.shard_map
    (check_rep).  Both checkers are disabled for the same reason (see
    make_shard_map_runner): control state is replicated by construction
    and the checker cannot see it."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as sm_exp

    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_tile_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1D mesh over the tile axis.

    On a real multi-chip slice this is the ICI ring/torus; in tests it is
    the virtual 8-device CPU platform.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (TILE_AXIS,))


def _tile_spec(leaf: jax.Array) -> P:
    return P(TILE_AXIS, *([None] * (leaf.ndim - 1)))


# Fields whose leading axis is NOT the tile axis and must be replicated:
# the sync-object tables (the MCP SyncServer analog, `sync_server.h:86-114`)
# and global scalars.
_REPLICATED_STATE_FIELDS = {
    "barrier_count", "barrier_arrived", "barrier_time_ps",
    "barrier_gen", "barrier_release_ps",
    "mutex_locked", "mutex_owner", "mutex_time_ps",
    "cond_sig_time_ps", "cond_bcast_time_ps",
    "cond_sig_seq", "cond_sig_seq_ps",
    "models_enabled", "overflow",
    # functional word store: a global address space, replicated (the
    # coherence protocol serializes conflicting writes)
    "func_mem", "func_errors",
    # gate observability: the [6] per-phase skip-count vector is global
    # control state (and at 6-tile counts would otherwise be mistaken
    # for a tile-major array by the shape heuristic below)
    "phase_skips",
}


def state_shardings(state: SimState, mesh: Mesh, n_tiles: int):
    def spec_for(path, leaf):
        name = path[-1].name if path else ""
        if (
            name in _REPLICATED_STATE_FIELDS
            or leaf.ndim == 0
            # Anything not tile-major is replicated — e.g. the hop-by-hop
            # NoC per-port queue arrays, which are [n_tiles*ports+1] flat
            # (router state is small; replication trades memory for the
            # scatter locality of contention updates)
            or leaf.shape[0] != n_tiles
        ):
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, _tile_spec(leaf))

    return jax.tree_util.tree_map_with_path(spec_for, state)


def trace_shardings(trace: DeviceTrace, mesh: Mesh, n_tiles: int):
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _tile_spec(leaf)), trace
    )


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place the state alone on the mesh (streamed runs: the trace
    arrives later as per-window uploads sharded by shard_window)."""
    n_tiles = state.core.clock_ps.shape[0]
    n_dev = mesh.devices.size
    if n_tiles % n_dev != 0:
        raise ValueError(
            f"tile count {n_tiles} not divisible by mesh size {n_dev}"
        )
    return jax.device_put(state, state_shardings(state, mesh, n_tiles))


def shard_window(window: DeviceTrace, mesh: Mesh, bases) -> tuple:
    """Shard one streamed [T, W] trace window + its per-tile base vector
    onto the mesh (row t of the window lives with tile t's shard)."""
    n_tiles = window.op.shape[0]
    window = jax.device_put(
        window, trace_shardings(window, mesh, n_tiles))
    import jax.numpy as jnp

    bases = jax.device_put(
        jnp.asarray(bases), NamedSharding(mesh, P(TILE_AXIS)))
    return window, bases


# --------------------------------------------------------------------------
# The packed shard_map path (the default multi-chip runner).
#
# Unlike the GSPMD specs above — which shard every tile-major array and let
# the partitioner insert one small collective per scatter (~270/iteration,
# measured 16x SLOWER than single-device at 8 devices; PERF.md) — the
# shard_map program keeps exactly the BIG per-tile arrays block-local and
# recomputes all [T]-vector control state replicated on every device, so
# the only collectives are the engine's packed per-phase row exchanges
# (parallel/px.py; ~7 per subquantum iteration).  This is the TPU-native
# form of the reference's process striping: big state partitioned like the
# per-process tile models (`config.cc` computeProcessToTileMapping), small
# control traffic exchanged like its TCP messages (`socktransport.cc`).

# state leaves that are block-local under shard_map (dotted field paths);
# everything else is replicated
_SHARD_MAP_LOCAL = {
    "core.bp_bits",
    "mem.l1i.meta", "mem.l1d.meta", "mem.l2.meta",
    "mem.l2_cloc", "mem.l2_util", "mem.mt",
    "mem.directory.entry", "mem.directory.sharers",
    # round-12 per-HOME-LANE staging rows: lane-local by construction,
    # so they shard with the directory they stage for
    "mem.directory.skey", "mem.directory.sval", "mem.directory.sn",
    # shared-L2 engine: the L2-slice-embedded directory (engine_shl2)
    "mem.dir.word", "mem.dir.sharers",
}


def _path_name(path) -> str:
    names = []
    for p in path:
        n = getattr(p, "name", None)
        if n is not None:
            names.append(str(n))
    return ".".join(names)


def shard_map_state_specs(state: SimState):
    """PartitionSpec tree for the shard_map path: big arrays block-local
    on the tile axis, everything else replicated."""

    def spec(path, leaf):
        if _path_name(path) in _SHARD_MAP_LOCAL:
            return P(TILE_AXIS, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, state)


def shard_map_trace_specs(trace: DeviceTrace):
    return jax.tree.map(lambda leaf: P(TILE_AXIS, None), trace)


def place_shard_map(state: SimState, mesh: Mesh, trace=None):
    """Device-put state (and optionally the trace) with the shard_map
    layout so the jitted runner starts without a resharding pass."""
    n_tiles = state.core.clock_ps.shape[0]
    n_dev = mesh.devices.size
    if n_tiles % n_dev != 0:
        raise ValueError(
            f"tile count {n_tiles} not divisible by mesh size {n_dev}")
    state = jax.device_put(state, jax.tree.map(
        lambda s: NamedSharding(mesh, s), shard_map_state_specs(state),
        is_leaf=lambda x: isinstance(x, P)))
    if trace is None:
        return state
    trace = jax.device_put(trace, jax.tree.map(
        lambda s: NamedSharding(mesh, s), shard_map_trace_specs(trace),
        is_leaf=lambda x: isinstance(x, P)))
    return state, trace


def place_shard_map_window(window: DeviceTrace, mesh: Mesh, bases):
    """Place one streamed [T, W] trace window (block-local rows) + its
    per-tile base vector (replicated control state — the engine lo()s it
    for local reads) for the shard_map runner."""
    import jax.numpy as jnp

    window = jax.device_put(window, jax.tree.map(
        lambda s: NamedSharding(mesh, s), shard_map_trace_specs(window),
        is_leaf=lambda x: isinstance(x, P)))
    bases = jax.device_put(jnp.asarray(bases), NamedSharding(mesh, P()))
    return window, bases


def make_shard_map_runner(params, quantum_ps, max_quanta: int, mesh: Mesh,
                          state_example: SimState, trace_example,
                          streamed: bool = False):
    """The jitted multi-chip runner: run_simulation under jax.shard_map
    with the packed px exchange.  Takes (state, trace[, trace_base]) —
    the trace is an argument (not a closure) so streamed windows shard.

    check_vma=False: control state is replicated by construction (same
    deterministic integer math from identical inputs on every device) and
    the big arrays' collectives are the explicit px exchanges — the
    varying-axis checker cannot see either invariant."""
    from graphite_tpu.engine.step import run_simulation
    from graphite_tpu.parallel.px import ParallelCtx

    px = ParallelCtx(axis=TILE_AXIS, n_dev=int(mesh.devices.size))
    state_specs = shard_map_state_specs(state_example)
    trace_specs = shard_map_trace_specs(trace_example)

    if streamed:
        def body(st, tr, base):
            return run_simulation(params, tr, st, quantum_ps, max_quanta,
                                  trace_base=base, px=px)

        sm = _shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, trace_specs, P()),
            out_specs=(state_specs, P(), P(), P()))
        return jax.jit(sm)

    def body(st, tr):
        return run_simulation(params, tr, st, quantum_ps, max_quanta, px=px)

    sm = _shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, trace_specs),
        out_specs=(state_specs, P(), P(), P()))
    return jax.jit(sm)


# --------------------------------------------------------------------------
# The 2D batch x tile campaign layout (round 18).
#
# A Mesh(('batch', 'tile')) program: each device holds a TILE BLOCK of a
# SUBSET of sims — the batch axis stays embarrassingly parallel (the
# round-7 campaign semantics per cell) while the tile axis runs the
# round-12 packed per-phase exchange (parallel/px.py: one working-set
# gather + one merged scatter per iteration) WITHIN each batch cell.
# This is Graphite's process striping (config.cc
# computeProcessToTileMapping) crossed with campaign batching: one
# compiled artifact serving pod-sized grids of sims too big for one
# device's budget.  Specs follow the shard_map policy above — the big
# per-tile arrays (_SHARD_MAP_LOCAL) are block-local on the tile axis,
# control state is replicated per batch cell — plus the round-16
# per-tile profile ring, whose [S, T, m] tile axis shards with the
# directory (obs/profile.profile_tick slices the row to local lanes).

BATCH_AXIS = "batch"
TILE_AXIS_2D = "tile"

# ProfileState leaves whose tile axis shards under the 2D layout, and
# WHICH axis of the unbatched leaf it is (buf is [S, T, m]; prev is
# [T, m]); the [S] times ring and the scalar cursors stay replicated.
_PROFILE_TILE_AXES = {"profile.buf": 1, "profile.prev": 0}

# The round-21 latency-histogram ring: a PER-TILE [T, H, B] buffer
# shards its tile axis (obs/hist._scatter lo()s the masks to local
# lanes); the aggregate [H, B] buffer stays replicated — the commit
# masks are the replicated full-[T] control vectors, so every shard
# accumulates the identical fleet-wide counts.  Distinguished by ndim
# (3 = per-tile) since both layouts share the leaf name.
_HIST_TILE_AXES = {"hist.buf": 0}


def make_batch_tile_mesh(batch_shards: int, tile_shards: int,
                         devices=None, abstract: bool = False):
    """A Mesh(('batch', 'tile')) over batch_shards x tile_shards
    devices.  `abstract=True` returns a device-less AbstractMesh — the
    tracing form `SweepRunner.lower()` uses so the 2D program can be
    audited/fingerprinted on any host (including 1-device CI) without
    the forced-device platform the execution mesh needs."""
    db, dt = int(batch_shards), int(tile_shards)
    if db < 1 or dt < 1:
        raise ValueError(
            f"mesh shards must be positive (got batch={db}, tile={dt})")
    if abstract:
        from jax.sharding import AbstractMesh

        return AbstractMesh(((BATCH_AXIS, db), (TILE_AXIS_2D, dt)))
    if devices is None:
        devices = jax.devices()
    if len(devices) < db * dt:
        raise ValueError(
            f"2D campaign layout needs {db}x{dt}={db * dt} devices but "
            f"only {len(devices)} are visible — force more with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N on "
            f"CPU, or shrink the layout")
    return Mesh(np.asarray(devices[:db * dt]).reshape(db, dt),
                (BATCH_AXIS, TILE_AXIS_2D))


def campaign_state_specs(state: SimState):
    """PartitionSpec tree for a BATCHED [B, ...] state under the 2D
    layout, built from the UNBATCHED per-sim example: every leaf gains
    a leading 'batch' axis; the big per-tile arrays additionally shard
    their tile axis (the same _SHARD_MAP_LOCAL policy as the 1D
    multi-chip runner); the profile ring's tile axis shards with them;
    everything else — control vectors, sync tables, the telemetry ring
    (scalar series, replicated-identical on every tile shard) — rides
    the batch axis only."""

    def spec(path, leaf):
        name = _path_name(path)
        if name in _SHARD_MAP_LOCAL:
            return P(BATCH_AXIS, TILE_AXIS_2D,
                     *([None] * (leaf.ndim - 1)))
        t_axis = _PROFILE_TILE_AXES.get(name)
        if t_axis is None and name in _HIST_TILE_AXES and leaf.ndim == 3:
            t_axis = _HIST_TILE_AXES[name]
        if t_axis is not None:
            dims = [None] * leaf.ndim
            dims[t_axis] = TILE_AXIS_2D
            return P(BATCH_AXIS, *dims)
        return P(BATCH_AXIS)

    return jax.tree_util.tree_map_with_path(spec, state)


def campaign_trace_specs(trace: DeviceTrace):
    """Specs for the packed [B, T, L] campaign traces: each device
    holds its batch cells' tile-block rows."""
    return jax.tree.map(lambda leaf: P(BATCH_AXIS, TILE_AXIS_2D, None),
                        trace)


def shard_split_bytes(state: SimState) -> "dict[str, int]":
    """Split one sim's state bytes into the 2D layout's residency
    classes: {'tile_local': bytes of the _SHARD_MAP_LOCAL arrays (each
    device holds 1/tile_shards of them), 'replicated': everything else
    (every tile shard holds a full copy)}.  Telemetry/profile/hist ring
    leaves are excluded — they are priced separately through their
    specs' own ring_bytes (the one size model)."""
    from graphite_tpu.analysis.walk import aval_bytes

    out = {"tile_local": 0, "replicated": 0}

    def visit(path, leaf):
        name = _path_name(path)
        if name.startswith("telemetry.") or name.startswith("profile.") \
                or name.startswith("hist."):
            return
        b = aval_bytes(leaf)
        if name in _SHARD_MAP_LOCAL:
            out["tile_local"] += b
        else:
            out["replicated"] += b

    jax.tree_util.tree_map_with_path(visit, state)
    return out


def shard_sim(
    state: SimState, trace: DeviceTrace, mesh: Mesh
) -> tuple[SimState, DeviceTrace]:
    """Place state + trace on the mesh, tile axis sharded.

    The tile count must divide the mesh size.  Returns device-placed
    pytrees; subsequent jitted steps follow the input shardings, with XLA
    inserting the cross-shard collectives for mailbox scatters (the
    TPU-native replacement for SockTransport's TCP full mesh).
    """
    n_tiles = state.core.clock_ps.shape[0]
    n_dev = mesh.devices.size
    if n_tiles % n_dev != 0:
        raise ValueError(
            f"tile count {n_tiles} not divisible by mesh size {n_dev}"
        )
    state = jax.device_put(state, state_shardings(state, mesh, n_tiles))
    trace = jax.device_put(trace, trace_shardings(trace, mesh, n_tiles))
    return state, trace
