"""Simulation state: struct-of-arrays pytrees over the tile axis.

The reference scatters this state across per-tile C++ objects
(`Tile`/`Core`/`CoreModel`/`Network` — `common/tile/tile.cc:15-37`); here it
is a pytree of dense arrays with leading dimension n_tiles so one XLA step
advances every tile.  Checkpoint/resume (absent in the reference, SURVEY §5)
falls out for free: the state pytree *is* the checkpoint.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.trace.schema import TraceBatch

# ring depth for per-generation barrier-release / cond-signal times: the
# split rendezvous ops are generation-exact while a joiner lags at most
# GEN_RING releases/signals behind (far beyond the one-generation bound
# the frontend's usage patterns give)
GEN_RING = 8


@struct.dataclass
class CoreState:
    """Per-tile core-model state (`common/tile/core/core_model.h:19-146`)."""

    clock_ps: jax.Array          # int64[T] — CoreModel::_curr_time
    idx: jax.Array               # int32[T] — next trace record
    freq_mhz: jax.Array          # int32[T] — per-tile core frequency
    # counters (`core_model.cc:90-115` outputSummary)
    instruction_count: jax.Array     # int64[T]
    memory_stall_ps: jax.Array       # int64[T]
    execution_stall_ps: jax.Array    # int64[T]
    recv_instructions: jax.Array     # int64[T]
    recv_stall_ps: jax.Array         # int64[T]
    sync_instructions: jax.Array     # int64[T]
    sync_stall_ps: jax.Array         # int64[T]
    # branch predictor (`branch_predictors/one_bit_branch_predictor.cc`)
    bp_bits: jax.Array           # uint8[T, bp_size]
    bp_correct: jax.Array        # int64[T]
    bp_incorrect: jax.Array      # int64[T]


@struct.dataclass
class UserNetState:
    """The USER network (`packet_type.h:40-56`) as per-pair mailbox rings.

    Replaces the reference's per-tile `_netQueue` + condition variable
    (`network.cc:358-460`) and the TCP transport underneath: slot
    [dst, k, src] holds the k-th in-flight packet from src to dst.  Each
    sender lane writes only its own src column, so scatters never collide.
    The slot axis sits OUTSIDE the src axis so the minor dimension is the
    tile count: a [T, T, D] layout pads D up to the 128-lane tile on TPU
    (64x physical blowup at depth 2 — PERF.md "array padding").
    """

    time_ps: jax.Array     # int64[T, D, T] — arrival time at receiver
    lat_ps: jax.Array      # int32[T, D, T] — zero-load delay (for stats)
    head: jax.Array        # int32[T, T] — total pushes (mod D write slot)
    count: jax.Array       # int32[T, T] — in-flight entries
    overflow: jax.Array    # bool[]     — any ring exceeded D (sim invalid)
    # receive-side counters (`network_model.cc` updateReceiveCounters)
    packets_sent: jax.Array      # int64[T]
    packets_received: jax.Array  # int64[T]
    total_latency_ps: jax.Array  # int64[T]


@struct.dataclass
class SyncState:
    """Simulated sync objects (`common/system/sync_server.h:86-114`).

    The MCP SyncServer's SimBarrier/SimMutex tables become dense arrays
    indexed by object id; arrivals use scatter-adds, releases are computed
    globally per subquantum iteration.
    """

    barrier_count: jax.Array     # int32[NB] — participant count (init)
    barrier_arrived: jax.Array   # int32[NB]
    barrier_time_ps: jax.Array   # int64[NB] — max arrival time
    barrier_waiting: jax.Array   # bool[T] — this tile has joined its barrier
    # co-located split form (BARRIER_ARRIVE/BARRIER_SYNC): release
    # generation counter + a GEN_RING-deep ring of per-generation release
    # times (generation-exact for rendezvous lag <= GEN_RING releases)
    barrier_gen: jax.Array       # int32[NB]
    barrier_release_ps: jax.Array  # int64[NB, GEN_RING]
    # published cond signals (COND_SIGNAL aux1>0 / COND_JOIN): sequence
    # counter + per-sequence time ring
    cond_sig_seq: jax.Array      # int32[NC]
    cond_sig_seq_ps: jax.Array   # int64[NC, GEN_RING]
    mutex_locked: jax.Array      # int32[NM] — 0 free / 1 held
    mutex_owner: jax.Array       # int32[NM]
    mutex_time_ps: jax.Array     # int64[NM] — time of last lock/unlock
    mutex_waiting: jax.Array     # bool[T] — tile has a pending lock request
    # condition variables (`sync_server.cc` SimCond): a tile at a COND_WAIT
    # record is either waiting (in the FIFO, mutex released), or signaled
    # (woken, re-acquiring the mutex).  Signals/broadcasts park in per-cond
    # pending slots stamped with their simulated time and are delivered in
    # simulated-time order — to a waiter whose wait began at or before the
    # signal — or dropped once provably lost (pthread lost-signal
    # semantics), regardless of engine-iteration arrival order.
    cond_waiting: jax.Array      # bool[T]
    cond_signaled: jax.Array     # bool[T]
    cond_arrival_ps: jax.Array   # int64[T] — wait arrival (FIFO order key)
    cond_wake_ps: jax.Array      # int64[T] — signal/broadcast time
    cond_sig_time_ps: jax.Array  # int64[NC, K] — pending signals (FAR=empty)
    cond_bcast_time_ps: jax.Array  # int64[NC] — pending broadcast (FAR=none)


@struct.dataclass
class DvfsState:
    """Per-tile per-domain frequency/voltage (`dvfs_manager.h:19-88`).

    The CORE domain's frequency is mirrored authoritatively in
    CoreState.freq_mhz (every cost conversion uses it); non-CORE domains
    are tracked for the get/set API, with their model frequencies static
    per run (documented divergence: the reference retunes cache/network
    timing mid-run on those domains too)."""

    freq_mhz: jax.Array     # int32[T, ND]
    voltage_mv: jax.Array   # int32[T, ND]
    errors: jax.Array       # int64[T] — failed in-trace DVFS_SET events


@struct.dataclass
class SimState:
    core: CoreState
    net: UserNetState
    sync: SyncState
    models_enabled: jax.Array    # bool[] — CarbonEnableModels/DisableModels
    done: jax.Array              # bool[T] — thread exited (THREAD_EXIT)
    # memory subsystem (None when enable_shared_mem=false, the reference's
    # `general/enable_shared_mem` knob — `carbon_sim.cfg:40-44`)
    mem: "object" = None
    # USER-network hop-by-hop port-contention state (None unless
    # network/user = emesh_hop_by_hop)
    noc_user: "object" = None
    # iocoom core-model state (None unless core type = iocoom)
    ioc: "object" = None
    # per-domain DVFS state (always populated by Simulator; the None path
    # exists only for direct engine-level construction in tests)
    dvfs: "object" = None
    # lax_p2p pairing round counter (drives the pseudorandom partner draw;
    # carried unconditionally — one int32 scalar)
    p2p_round: "jax.Array" = None
    # device-resident telemetry ring (obs/telemetry.TelemetryState) when
    # the run records a timeline; None (no pytree leaves — the program
    # lowers bit-identically to one with no telemetry at all) otherwise
    telemetry: "object" = None
    # device-resident per-tile profile ring (obs/profile.ProfileState)
    # when the run records the spatial profiler; None (no pytree leaves
    # — same bit-identity contract as telemetry) otherwise
    profile: "object" = None
    # runtime DVFS manager carry (dvfs/runtime.DvfsRtState): chip-global
    # per-domain operating point + governor cursors when a DvfsSpec is
    # attached; None (no pytree leaves — same bit-identity contract as
    # telemetry/profile) otherwise
    dvfs_rt: "object" = None
    # device-resident latency-histogram ring (obs/hist.HistState) when
    # the run records distributions; None (no pytree leaves — same
    # bit-identity contract as telemetry/profile) otherwise
    hist: "object" = None


@struct.dataclass
class DeviceTrace:
    """TraceBatch resident on device, one array per field, [T, L]."""

    op: jax.Array
    flags: jax.Array
    pc: jax.Array
    addr0: jax.Array
    addr1: jax.Array
    size0: jax.Array
    size1: jax.Array
    aux0: jax.Array
    aux1: jax.Array
    dyn_ps: jax.Array
    rreg0: jax.Array
    rreg1: jax.Array
    wreg: jax.Array

    @classmethod
    def from_batch(cls, batch: TraceBatch) -> "DeviceTrace":
        return cls(
            **{
                f.name: jnp.asarray(getattr(batch, f.name))
                for f in dataclasses.fields(batch)
            }
        )

    @classmethod
    def window(cls, batch: TraceBatch, bases: "np.ndarray",
               length: int) -> "DeviceTrace":
        """A [T, length] window with PER-TILE start records `bases[t]`,
        NOP-padded past each stream's end — the unit of host->HBM
        streaming.  Per-tile bases let lanes skew arbitrarily (a leader
        pausing at its window edge never forces the window away from a
        laggard).  Rows are cut host-side so only `length` records per
        tile ever travel to the device."""
        import numpy as np

        from graphite_tpu.trace.schema import Op

        L = batch.length
        cols = bases[:, None] + np.arange(length)[None, :]   # [T, W]
        valid = cols < L
        cols = np.minimum(cols, L - 1)
        fields = {}
        for f in dataclasses.fields(batch):
            arr = np.take_along_axis(getattr(batch, f.name), cols, axis=1)
            if f.name == "op":
                arr = np.where(valid, arr, np.uint8(Op.NOP))
            fields[f.name] = jnp.asarray(arr)
        return cls(**fields)

    @property
    def length(self) -> int:
        return self.op.shape[1]


def init_state(
    n_tiles: int,
    *,
    core_freq_mhz: int | np.ndarray,
    bp_size: int = 1024,
    mailbox_depth: int = 8,
    n_barriers: int = 64,
    n_mutexes: int = 64,
    n_conds: int = 64,
    n_pending_signals: int = 4,
    models_enabled: bool = True,
) -> SimState:
    T, D = n_tiles, mailbox_depth
    i64 = jnp.int64
    core = CoreState(
        clock_ps=jnp.zeros(T, i64),
        idx=jnp.zeros(T, jnp.int32),
        freq_mhz=jnp.broadcast_to(
            jnp.asarray(core_freq_mhz, jnp.int32), (T,)
        ).copy(),
        instruction_count=jnp.zeros(T, i64),
        memory_stall_ps=jnp.zeros(T, i64),
        execution_stall_ps=jnp.zeros(T, i64),
        recv_instructions=jnp.zeros(T, i64),
        recv_stall_ps=jnp.zeros(T, i64),
        sync_instructions=jnp.zeros(T, i64),
        sync_stall_ps=jnp.zeros(T, i64),
        bp_bits=jnp.zeros((T, bp_size), jnp.uint8),
        bp_correct=jnp.zeros(T, i64),
        bp_incorrect=jnp.zeros(T, i64),
    )
    net = UserNetState(
        time_ps=jnp.zeros((T, D, T), i64),
        lat_ps=jnp.zeros((T, D, T), jnp.int32),
        head=jnp.zeros((T, T), jnp.int32),
        count=jnp.zeros((T, T), jnp.int32),
        overflow=jnp.zeros((), jnp.bool_),
        packets_sent=jnp.zeros(T, i64),
        packets_received=jnp.zeros(T, i64),
        total_latency_ps=jnp.zeros(T, i64),
    )
    sync = SyncState(
        barrier_count=jnp.zeros(n_barriers, jnp.int32),
        barrier_arrived=jnp.zeros(n_barriers, jnp.int32),
        barrier_time_ps=jnp.zeros(n_barriers, i64),
        barrier_waiting=jnp.zeros(T, jnp.bool_),
        barrier_gen=jnp.zeros(n_barriers, jnp.int32),
        barrier_release_ps=jnp.zeros((n_barriers, GEN_RING), i64),
        cond_sig_seq=jnp.zeros(n_conds, jnp.int32),
        cond_sig_seq_ps=jnp.zeros((n_conds, GEN_RING), i64),
        mutex_locked=jnp.zeros(n_mutexes, jnp.int32),
        mutex_owner=jnp.full(n_mutexes, -1, jnp.int32),
        mutex_time_ps=jnp.zeros(n_mutexes, i64),
        mutex_waiting=jnp.zeros(T, jnp.bool_),
        cond_waiting=jnp.zeros(T, jnp.bool_),
        cond_signaled=jnp.zeros(T, jnp.bool_),
        cond_arrival_ps=jnp.zeros(T, i64),
        cond_wake_ps=jnp.zeros(T, i64),
        cond_sig_time_ps=jnp.full((n_conds, n_pending_signals), 2**62, i64),
        cond_bcast_time_ps=jnp.full(n_conds, 2**62, i64),
    )
    return SimState(
        core=core,
        net=net,
        sync=sync,
        models_enabled=jnp.asarray(models_enabled, jnp.bool_),
        done=jnp.zeros(T, jnp.bool_),
        p2p_round=jnp.zeros((), jnp.int32),
    )
