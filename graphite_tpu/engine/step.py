"""The vectorized subquantum step: every tile advances one trace record.

This replaces Graphite's per-instruction host control flow — Pin callback →
`CoreModel::queueInstruction/iterate` (`pin/instruction_modeling.cc:13-21`,
`common/tile/core/models/simple_core_model.cc:37-97`) and the blocking
netRecv / MCP sync-server round trips (`network.cc:358-460`,
`common/system/sync_server.cc:27-160`) — with a masked SoA state machine:

 - one `lax.scan` iteration processes (at most) one trace record per tile,
   all tiles in parallel;
 - blocked operations (recv with no matching packet, barrier not full,
   mutex held) simply do not advance `idx`; they retry next iteration, when
   messages pushed by other tiles in earlier iterations have landed;
 - sends scatter into per-(dst,src) mailbox rings — each sender lane owns
   its own src column, so writes never collide;
 - barrier arrivals/releases use scatter-add/scatter-max plus a global
   release mask, reproducing SimBarrier's max-arrival-time release
   (`sync_server.cc:133-160`);
 - mutex grants pick the earliest-simulated-time waiter via a segmented
   min over (clock, tile) keys, reproducing SimMutex handoff-at-unlock-time
   (`sync_server.cc:27-57,185-240`) deterministically (the reference's FIFO
   is host-arrival-order and racy).

Timing semantics per record mirror the reference exactly:
 - static instruction cost from the `[core/static_instruction_costs]` table
   (`core_model.cc:65-76`), converted at the tile's DVFS frequency;
 - branch cost 1 cycle on correct prediction else the mispredict penalty,
   one-bit predictor indexed by pc (`instruction.cc:47-70`,
   `one_bit_branch_predictor.cc:13-24`, `carbon_sim.cfg:202-205`);
 - dynamic instruction cost carried in the record (`instruction.h:149-198`);
 - netRecv: clock = max(clock, arrival); a RecvInstruction is accounted only
   when arrival > clock (`network.cc:443-453`);
 - barrier release at max arrival time with a SyncInstruction only when the
   wait was positive (`sync_server.cc:141-144`, `sync_client.cc:83-87`);
 - models-disabled ⇒ zero cost and no counters, but full functional effect
   (`simulator.cc:399-413`, `core_model.h` _enabled gate).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from graphite_tpu.intmath import nn_mod

from graphite_tpu.engine.state import SimState, DeviceTrace
from graphite_tpu.models.network_user import UserNetworkParams, route_latency_ps
from graphite_tpu.parallel.px import IDENT, ParallelCtx
from graphite_tpu.trace.schema import (
    FLAG_BRANCH_TAKEN,
    Op,
)
from graphite_tpu.time_types import cycles_to_ps

I64 = jnp.int64
FAR_FUTURE_PS = 2**62  # python int: folds to an inline literal, never a device-constant buffer
ANY_SENDER = -1

# Measured-safe ceiling for plain-run batching: the [T, KX] follow-on
# gather goes superlinear past this (PERF.md unroll sweep on the
# 1024-tile per-instruction ring: 8 -> 1.06M, 16 -> 1.76M, 32 -> 0.79M
# instr/s).  The engine clamps the effective unroll here; the Simulator
# warns when a config asks for more.
PLAIN_UNROLL_MAX = 16


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Static compile-time parameters of the step function."""

    n_tiles: int
    static_cost_cycles: tuple  # 20 ints (`carbon_sim.cfg:189-200`)
    net: UserNetworkParams
    bp_enabled: bool = True
    bp_size: int = 1024
    bp_mispredict_penalty: int = 14
    mailbox_depth: int = 8
    inner_block: int = 32      # trace records per tile per scan
    n_conds: int = 64          # cond-variable id space (sync tables)
    syscall_rt_ps: int = 2000  # SYSTEM-net round trip to the MCP (2 cyc @1GHz)
    # iocoom core model (None = simple 1-IPC in-order model)
    iocoom: "object" = None    # IocoomParams | None
    # heterogeneous cores (`[tile] model_list`, `config.cc:365-472`): which
    # tiles run the iocoom model (None = all, when iocoom is set); the rest
    # use the simple 1-IPC path
    iocoom_tiles: "tuple | None" = None
    # DVFS tables (always set by Simulator; the None fallback — a raw
    # frequency poke without validation — serves direct engine-level use)
    dvfs: "object" = None      # DvfsParams | None
    # memory subsystem (None = enable_shared_mem false: memory operands
    # cost nothing, like the reference's disabled shared-mem knob)
    mem: "object" = None       # MemParams | None
    # USER network full hop-by-hop model with per-port contention
    user_hbh: "object" = None  # HopByHopParams | None
    # USER network ATAC optical model (clusters + hubs + waveguide)
    user_atac: "object" = None  # AtacParams | None
    # Gate the memory engine behind a "any memory work this iteration"
    # lax.cond (big win on mixed compute/memory traces).  XLA double-
    # buffers the cond's carried outputs, so the Simulator disables the
    # gate when the memory state (directory sharer maps dominate at large
    # tile counts) exceeds its (config-driven) mem_gate_bytes ceiling —
    # above it the engines' PER-PHASE gating (MemParams.phase_gate,
    # conds carrying only small state) takes over.
    mem_gate: bool = True
    # Commit up to this many consecutive PLAIN records (static
    # non-branch instruction costs — no machinery, memory, or predictor
    # state) per lane per iteration: runtime BBLOCK compression for
    # per-instruction streams, bit-exact by construction (each follow-on
    # stays quantum-bounded like the per-iteration active check).
    # Simple-core memoryless runs only; 1 = off.
    plain_unroll: int = 1
    # Run the net/barrier/mutex/pub/join machinery unconditionally
    # instead of behind their any-lane-active lax.conds.  The conds are a
    # pure wall-clock optimization (skip scatter kernels on quiet
    # iterations); disabling them works around an XLA TPU kernel fault
    # observed at 1024 tiles x full directory on send-heavy traces
    # (PERF.md "Known limitation").
    block_gates: bool = True
    # lax_p2p clock-skew scheme (`lax_p2p_sync_client.h:13-83`): when set,
    # each iteration every tile draws a pseudorandom partner and advances
    # only if its clock is within `slack` of the partner's — the
    # random-pairwise clamping of the reference, minus the raciness (our
    # sync decisions are simulated-time-ordered, so unlike the reference
    # the scheme changes scheduling, not results)
    p2p_slack_ps: "int | None" = None


def _gather_field(field: jax.Array, idx: jax.Array) -> jax.Array:
    return jnp.take_along_axis(field, idx[:, None], axis=1)[:, 0]


def _elect_min(mask, gid, key, n_groups):
    """Per-group minimum of `key` over lanes with `mask`, via a scatter-min
    into group buckets (bucket n_groups collects masked-off lanes).
    Returns int64[n_groups]; empty groups hold 2**62.  A lane wins its
    group's election iff mask & (key == result[gid])."""
    best = (
        jnp.full((n_groups + 1,), 2**62, I64)
        .at[jnp.where(mask, gid, n_groups)]
        .min(jnp.where(mask, key, jnp.asarray(2**62, I64)))
    )
    return best[:n_groups]




def subquantum_iteration(
    params: EngineParams,
    trace: DeviceTrace,
    state: SimState,
    quantum_end_ps: jax.Array,
    trace_base: jax.Array | None = None,
    px: ParallelCtx = IDENT,
    knobs=None,
    dvfs=None,
    hist=None,
) -> tuple[SimState, jax.Array]:
    """Process one trace record per tile; returns (state, tiles_advanced).

    With `dvfs` (a resolved `dvfs.DvfsSpec`) and the `SimState.dvfs_rt`
    carry attached, the memory/network/DRAM timing conversions read the
    CARRIED per-domain frequencies instead of the constant-folded
    MemParams values, and in-trace DVFS_SET events elect new chip-global
    operating points (dvfs/runtime.py).  None — the default — keeps the
    historical program bit-identical (the `dvfs-off` audit rule).

    With `knobs` (a sweep.Knobs pytree) set, the memory engines read
    their timing scalars — DRAM latency, directory access cycles, NoC
    hop latency, DVFS sync delay — from its TRACED leaves instead of
    the static params, so one compiled program serves every timing
    point of a sweep (sweep/knobs.py).  None keeps the historical
    constant-folded program bit-identically.

    With `trace_base` (int32[T]) set, `trace` is a [T, W] WINDOW of the
    full record stream, row t starting at global record index
    `trace_base[t]` (host->HBM streaming, the Pin-pipe analog —
    `pin/instruction_modeling.cc` streams continuously).  Lanes whose
    global idx has run past their window's end simply pause (wall-time
    only; clocks and all protocol state carry over) until the host
    slides their window.

    With a sharded `px` (shard_map multi-chip), `trace` and
    `core.bp_bits` hold only this device's block of tile rows; every
    other input is replicated.  Block-local reads are packed into one
    all-gather here (and one per memory-engine phase); all decision
    logic then runs replicated, and block-local arrays take their lanes'
    writes locally (see parallel/px.py).
    """
    T = params.n_tiles
    D = params.mailbox_depth
    core, net, sync = state.core, state.net, state.sync
    tiles = np.arange(T, dtype=np.int32)
    if trace_base is None:
        idx = jnp.minimum(core.idx, trace.length - 1)
        in_window = None
    else:
        idx = jnp.clip(core.idx - trace_base, 0, trace.length - 1)
        in_window = core.idx < trace_base + trace.length

    # Record fetch: per-row gathers on the [T, L] trace cost ~0.25 ms each
    # on TPU (gather lowers poorly), so when every tile is at the SAME
    # column — the common case for lockstep stretches — read the column
    # with one dynamic_slice instead.  The gather path runs only when tiles
    # have diverged (blocked on sync/messages).  Under a sharded px the
    # trace and bp_bits rows are block-local: the reads below see only
    # this device's lanes and ONE packed all-gather replicates them.
    gather_fields = (trace.op, trace.flags, trace.pc, trace.aux0, trace.aux1,
                     trace.dyn_ps) + (
        (trace.addr0, trace.addr1) if params.mem is not None else ()) + (
        (trace.rreg0, trace.rreg1, trace.wreg)
        if params.iocoom is not None else ())
    uniform = jnp.all(idx == idx[0])
    idx_l = px.lo(idx)

    def _read_uniform(_):
        return tuple(
            lax.dynamic_slice_in_dim(f, idx[0], 1, axis=1)[:, 0]
            for f in gather_fields
        )

    def _read_gather(_):
        return tuple(_gather_field(f, idx_l) for f in gather_fields)

    fetched_l = lax.cond(uniform, _read_uniform, _read_gather, None)
    # branch prediction reads ride the same exchange (bp_bits block-local)
    bp_index_l = nn_mod(fetched_l[2], params.bp_size).astype(jnp.int32)
    bp_pred_l = jnp.take_along_axis(
        core.bp_bits, bp_index_l[:, None], axis=1)[:, 0]
    agd = px.ag(fetched_l + (bp_pred_l,))
    fetched, bp_pred = agd[:-1], agd[-1]
    op = fetched[0].astype(jnp.int32)
    flags = fetched[1].astype(jnp.int32)
    pc = fetched[2]
    aux0 = fetched[3]
    aux1 = fetched[4]
    dyn_ps = fetched[5]

    enabled = state.models_enabled
    stream_end = (op == Op.NOP) | (op == Op.THREAD_EXIT)
    if in_window is not None:
        # a paused lane's fetched record is the clipped window edge —
        # it must neither latch done nor execute
        stream_end = stream_end & in_window
    done = state.done | stream_end
    active = (~done) & (core.clock_ps < quantum_end_ps)
    if in_window is not None:
        active = active & in_window

    # lax_p2p random pairwise clamping (`lax_p2p_sync_client.h:13-83`):
    # each tile draws a pseudorandom partner this round and holds if it is
    # more than `slack` ahead of a still-running partner.  The globally
    # minimum-clock lane can never hold (its partner's clock is >= its
    # own), so some lane always advances — no scheme-induced deadlock.
    if params.p2p_slack_ps is not None:
        rnd = (state.p2p_round.astype(jnp.uint32) * jnp.uint32(747796405)
               + tiles.astype(jnp.uint32) * jnp.uint32(2891336453))
        rnd = (rnd ^ (rnd >> 13)) * jnp.uint32(1103515245)
        # a random partner OTHER than self (self-pairing would be a no-op
        # check and weakens the bound badly at small tile counts)
        partner = ((tiles.astype(jnp.uint32) + 1
                    + rnd % jnp.uint32(max(T - 1, 1)))
                   % jnp.uint32(T)).astype(jnp.int32)
        ahead = core.clock_ps > (
            core.clock_ps[partner] + jnp.asarray(params.p2p_slack_ps, I64))
        active = active & ~(ahead & ~done[partner])
        p2p_round = state.p2p_round + 1
    else:
        p2p_round = state.p2p_round

    def _gate(pred):
        # block_gates=False forces every machinery cond down its live
        # branch (constant predicate folds the cond away entirely)
        return pred if params.block_gates else jnp.asarray(True)

    # --- memory subsystem (caches + coherence protocol) ------------------
    # Runs every iteration: requester lanes start/advance their record's
    # memory slots; home/sharer machinery serves protocol messages even for
    # tiles past the quantum boundary (like the reference's sim threads).
    if params.mem is not None:
        from graphite_tpu.memory.engine import (
            RecView, mem_idle_out, memory_engine_step, slots_present,
        )

        if params.mem.protocol.startswith("pr_l1_sh_l2"):
            from graphite_tpu.memory.engine_shl2 import shl2_engine_step
            engine_step = shl2_engine_step
        else:
            engine_step = memory_engine_step
        # knob lifting: swap the timing-scalar fields for the (traced)
        # sweep knobs; geometry and every other static field untouched
        mem_p = params.mem if knobs is None else knobs.apply_mem(params.mem)
        if dvfs is not None and state.dvfs_rt is not None:
            # runtime DVFS: the memory-network and directory frequencies
            # come from the carried operating point (same replace lift)
            from graphite_tpu.dvfs.runtime import apply_rt_mem

            mem_p = apply_rt_mem(params.dvfs, mem_p, state.dvfs_rt)
        addr0, addr1 = fetched[6], fetched[7]
        rec = RecView(op=op, flags=flags, pc=pc, addr0=addr0, addr1=addr1,
                      aux0=aux0, aux1=aux1)
        # Skip the whole engine (hundreds of small kernels) on iterations
        # with provably no memory work: no live protocol state and no
        # active lane whose record carries memory slots.  Compute-heavy
        # stretches (bblock runs) then pay ~nothing for the memory model.
        # Sharded px runs ungated: the engine's per-phase all-gathers must
        # not sit inside a lax.cond (and the sharded workloads are
        # coherence-dense, so the gate would rarely skip anyway).
        # per-call miss-fill events only materialize when the histograms
        # ask for them — fill_events=False keeps MemStepOut leaf-free and
        # the hist-off trace byte-identical (PROGRAMS.lock fingerprints)
        fill_ev = hist is not None
        if params.mem_gate and not px.sharded:
            need_mem = state.mem.live | jnp.any(
                active & slots_present(mem_p, rec, enabled).any(axis=1))
            mem_out = lax.cond(
                need_mem,
                lambda _: engine_step(mem_p, state.mem, rec,
                                      core.clock_ps, core.freq_mhz,
                                      active, enabled,
                                      fill_events=fill_ev),
                lambda _: mem_idle_out(mem_p, state.mem, rec, enabled,
                                       fill_events=fill_ev),
                None)
        else:
            mem_out = engine_step(
                mem_p, state.mem, rec, core.clock_ps, core.freq_mhz,
                active, enabled, px=px, fill_events=fill_ev)
        mem_state = mem_out.ms
        mem_ok = mem_out.mem_complete
        mem_acc_ps = mem_out.acc_ps
        mem_progress = mem_out.progress
    else:
        mem_state = state.mem
        mem_ok = jnp.ones((T,), jnp.bool_)
        mem_acc_ps = jnp.zeros((T,), I64)
        mem_progress = jnp.zeros((), jnp.int32)

    # --- classify -------------------------------------------------------
    is_branch = op == Op.BRANCH
    is_static = (op < Op.DYNAMIC_MISC) & ~is_branch      # 0-14 minus branch
    is_dynamic = (op >= Op.DYNAMIC_MISC) & (op < 20)     # 15-19
    is_spawn_instr = op == Op.SPAWN
    is_send = op == Op.SEND
    is_recv = op == Op.NET_RECV
    is_binit = op == Op.BARRIER_INIT
    is_bwait = op == Op.BARRIER_WAIT
    # co-located split forms (see schema): non-blocking arrival + blocking
    # rendezvous on the release generation / published signal sequence
    is_barrive = op == Op.BARRIER_ARRIVE
    is_bsync = op == Op.BARRIER_SYNC
    is_cjoin = op == Op.COND_JOIN
    is_minit = op == Op.MUTEX_INIT
    is_mlock = op == Op.MUTEX_LOCK
    is_munlock = op == Op.MUTEX_UNLOCK
    is_join = op == Op.THREAD_JOIN
    is_bblock = op == Op.BBLOCK
    # Events that always complete in one iteration:
    is_syscall = op == Op.SYSCALL
    is_simple_event = (
        (op == Op.THREAD_SPAWN)
        | is_binit | is_minit | is_munlock
        | (op == Op.ENABLE_MODELS) | (op == Op.DISABLE_MODELS)
        | (op == Op.DVFS_SET) | (op == Op.DVFS_GET)
        | is_syscall  # blocking round trip to the MCP, charged as cost_ps
        | (op == Op.COND_INIT)  # effects applied in the mutex+cond block
        # COND_SIGNAL/COND_BROADCAST commit conditionally (cond_post_commit):
        # surplus same-iteration posters retry, so they are NOT simple
    )

    # --- static + dynamic instruction costs ------------------------------
    cost_table = jnp.asarray(params.static_cost_cycles, dtype=I64)
    static_cycles = cost_table[jnp.clip(op, 0, 19)]

    bp_index = nn_mod(pc, params.bp_size).astype(jnp.int32)  # bp_pred: fetch ag
    taken = ((flags & FLAG_BRANCH_TAKEN) != 0).astype(jnp.uint8)
    bp_correct_now = bp_pred == taken
    if params.bp_enabled:
        branch_cycles = jnp.where(bp_correct_now, 1, params.bp_mispredict_penalty)
    else:
        branch_cycles = jnp.ones((T,), I64)

    cycles = jnp.where(is_branch, branch_cycles, static_cycles)
    cost_ps = cycles_to_ps(cycles, core.freq_mhz.astype(I64))
    cost_ps = jnp.where(is_dynamic, dyn_ps, cost_ps)
    cost_ps = jnp.where(op < 20, cost_ps, 0)  # events carry no direct cost
    # ... except syscalls and DVFS queries: the app thread blocks for a
    # round trip — to the MCP's SyscallServer over the SYSTEM network
    # (`syscall_model.cc` marshalling) or to the target DVFS manager over
    # the DVFS network (`dvfs_manager.cc` remote get).  Both networks are
    # always magic (`config.cc:484-485` → 1 cycle each way).
    cost_ps = jnp.where(is_syscall | (op == Op.DVFS_GET),
                        jnp.asarray(params.syscall_rt_ps, I64), cost_ps)
    # compressed run: aux1 = total cycles for aux0 instructions
    cost_ps = jnp.where(
        is_bblock,
        cycles_to_ps(aux1.astype(I64), core.freq_mhz.astype(I64)),
        cost_ps,
    )
    cost_ps = jnp.where(enabled, cost_ps, 0)

    # The network / barrier / mutex / join machinery each runs under a
    # lax.cond keyed on "any lane has such an op right now" — compute-heavy
    # stretches then skip the scatter-heavy machinery entirely (a TPU
    # scatter costs ~0.2-0.9 ms regardless of how many lanes are masked on).
    dst = jnp.clip(aux0, 0, T - 1)
    send_now = active & is_send

    # --- SEND + RECV: (dst, src) mailbox rings ---------------------------
    def _net_block(_):
        if params.user_hbh is not None:
            from graphite_tpu.models.network_hop_by_hop import route_hop_by_hop
            from graphite_tpu.models.network_user import user_packet_bits

            noc_user, arrival_ps, _, _ = route_hop_by_hop(
                params.user_hbh, state.noc_user, tiles, dst,
                user_packet_bits(aux1), core.clock_ps, send_now, enabled)
            lat_ps = arrival_ps - core.clock_ps
        elif params.user_atac is not None:
            from graphite_tpu.models.network_atac import route_atac
            from graphite_tpu.models.network_user import user_packet_bits

            noc_user, arrival_ps, _ = route_atac(
                params.user_atac, state.noc_user, tiles, dst,
                user_packet_bits(aux1), core.clock_ps, send_now, enabled)
            lat_ps = arrival_ps - core.clock_ps
        else:
            noc_user = state.noc_user
            lat_ps = route_latency_ps(params.net, tiles, dst, aux1, enabled)
            arrival_ps = core.clock_ps + lat_ps
        slot = nn_mod(net.head[dst, tiles], D).astype(jnp.int32)
        # Write under mask: redirect masked-off lanes to their own (t, t)
        # cell at a dummy slot; since each lane writes a distinct src
        # column, no collisions occur either way.  Updates are add-a-delta
        # so the scatter is the array's ONLY remaining use — XLA then
        # updates the loop-carried mailbox buffers in place instead of
        # copying ~100MB per iteration.
        w_dst = jnp.where(send_now, dst, tiles)
        old_time = net.time_ps[w_dst, slot, tiles]
        old_lat = net.lat_ps[w_dst, slot, tiles]
        time_ps_new = net.time_ps.at[w_dst, slot, tiles].add(
            jnp.where(send_now, arrival_ps - old_time, 0)
        )
        lat_arr_new = net.lat_ps.at[w_dst, slot, tiles].add(
            jnp.where(send_now, lat_ps.astype(jnp.int32) - old_lat, 0)
        )
        head_new = net.head.at[w_dst, tiles].add(jnp.where(send_now, 1, 0))
        count_sent = net.count.at[w_dst, tiles].add(
            jnp.where(send_now, 1, 0))

        # RECV matches against the POST-send arrays: a packet sent this
        # iteration is immediately visible (its timestamp carries the
        # arrival time, so simulated timing is unchanged — this only
        # removes retry iterations and lets the send scatters alias).
        # Specific-sender receives only touch their own (dst, src) ring:
        # O(T) gathers.  The earliest-across-all-senders scan for
        # ANY_SENDER receives is O(T^2) and runs under its own cond.
        is_any_recv = is_recv & (aux0 == ANY_SENDER)

        def _any_src(_):
            tail = nn_mod(head_new - count_sent, D).astype(jnp.int32)  # [T, T]
            tail_times = jnp.take_along_axis(
                time_ps_new, tail[:, None, :], axis=1)[:, 0, :]
            masked_times = jnp.where(
                count_sent > 0, tail_times, FAR_FUTURE_PS)
            return jnp.argmin(masked_times, axis=1).astype(jnp.int32)

        any_src = lax.cond(
            jnp.any(active & is_any_recv),
            _any_src, lambda _: jnp.zeros((T,), jnp.int32), None)
        want_src = jnp.where(is_any_recv, any_src, jnp.clip(aux0, 0, T - 1))
        sel_count = count_sent[tiles, want_src]
        sel_tail = nn_mod(head_new[tiles, want_src] - sel_count,
                          D).astype(jnp.int32)
        matched = sel_count > 0
        recv_time = jnp.where(
            matched, time_ps_new[tiles, sel_tail, want_src], FAR_FUTURE_PS)
        recv_lat = lat_arr_new[tiles, sel_tail, want_src]
        recv_now = active & is_recv & matched
        # pop (count -1)
        count_new = count_sent.at[tiles, want_src].add(
            jnp.where(recv_now, -1, 0))
        # only a send can overflow its ring; check just the written cells
        overflow = net.overflow | jnp.any(
            send_now & (count_sent[w_dst, tiles] > D))
        return (time_ps_new, lat_arr_new, head_new, count_new, overflow,
                noc_user, recv_now, recv_time, recv_lat)

    def _net_skip(_):
        return (net.time_ps, net.lat_ps, net.head, net.count, net.overflow,
                state.noc_user, jnp.zeros((T,), jnp.bool_),
                jnp.full((T,), FAR_FUTURE_PS, I64), jnp.zeros((T,), jnp.int32))

    (time_ps_new, lat_arr_new, head_new, count_new, overflow, noc_user,
     recv_now, recv_time, recv_lat) = lax.cond(
        _gate(jnp.any(send_now | (active & is_recv))), _net_block, _net_skip,
        None)
    recv_wait_ps = jnp.maximum(recv_time - core.clock_ps, 0)
    recv_wait_ps = jnp.where(recv_now, recv_wait_ps, 0)

    # --- BARRIER ---------------------------------------------------------
    def _barrier_block(_):
        # Masked scatter-updates use the add-a-delta idiom: masked-off
        # lanes contribute +0, so duplicate dummy indices cannot clobber a
        # live update (a plain masked .set would).
        bar = jnp.clip(aux0, 0, sync.barrier_count.shape[0] - 1)
        binit_now = active & is_binit
        # several tiles may init the same barrier in one iteration (the
        # vectorized trace generators do); elect one writer per id so the
        # add-a-delta stays idempotent instead of summing every lane's delta
        n_bars = sync.barrier_count.shape[0]
        init_best = _elect_min(binit_now, bar, tiles.astype(I64), n_bars)
        init_win = binit_now & (tiles.astype(I64) == init_best[bar])
        barrier_count = sync.barrier_count.at[bar].add(
            jnp.where(init_win, aux1 - sync.barrier_count[bar], 0)
        )
        # arrivals: blocking waits joining the rendezvous, plus the
        # co-located split form's non-blocking BARRIER_ARRIVE records
        arrive_only = active & is_barrive
        new_arrival = (active & is_bwait & ~sync.barrier_waiting
                       ) | arrive_only
        arr_tgt = jnp.where(new_arrival, bar, 0)
        barrier_arrived = sync.barrier_arrived.at[arr_tgt].add(
            jnp.where(new_arrival, 1, 0)
        )
        barrier_time = sync.barrier_time_ps.at[arr_tgt].max(
            jnp.where(new_arrival, core.clock_ps, 0)
        )
        release_bar = (barrier_count > 0) & (barrier_arrived >= barrier_count)
        participant = is_bwait & (sync.barrier_waiting | new_arrival) & ~done
        released = participant & release_bar[bar]
        release_time = barrier_time[bar]
        barrier_waiting = ((sync.barrier_waiting
                            | (new_arrival & ~arrive_only)) & ~released)
        # the split form's rendezvous: wait for the given release
        # generation, then take THAT generation's release time (per-gen
        # ring; see state.GEN_RING)
        from graphite_tpu.engine.state import GEN_RING

        barrier_gen = sync.barrier_gen + release_bar.astype(jnp.int32)
        slot = nn_mod(barrier_gen, GEN_RING).astype(jnp.int32)
        n_bars_r = jnp.arange(n_bars, dtype=jnp.int32)
        cur_slot = sync.barrier_release_ps[n_bars_r, slot]
        barrier_release = sync.barrier_release_ps.at[n_bars_r, slot].set(
            jnp.where(release_bar, barrier_time, cur_slot))
        bsync_now = active & is_bsync & (barrier_gen[bar] >= aux1)
        bsync_time = barrier_release[
            bar, (aux1 % GEN_RING).astype(jnp.int32)]
        # reset released barriers
        barrier_arrived = jnp.where(release_bar, 0, barrier_arrived)
        barrier_time = jnp.where(release_bar, 0, barrier_time)
        return (barrier_count, barrier_arrived, barrier_time,
                barrier_waiting, released, release_time,
                barrier_gen, barrier_release, arrive_only, bsync_now,
                bsync_time)

    def _barrier_skip(_):
        return (sync.barrier_count, sync.barrier_arrived,
                sync.barrier_time_ps, sync.barrier_waiting,
                jnp.zeros((T,), jnp.bool_), jnp.zeros((T,), I64),
                sync.barrier_gen, sync.barrier_release_ps,
                jnp.zeros((T,), jnp.bool_), jnp.zeros((T,), jnp.bool_),
                jnp.zeros((T,), I64))

    (barrier_count, barrier_arrived, barrier_time, barrier_waiting,
     released, release_time, barrier_gen, barrier_release_ps,
     barrive_now, bsync_now, bsync_time) = lax.cond(
        _gate(jnp.any(active & (is_binit | is_bwait | is_barrive | is_bsync))),
        _barrier_block, _barrier_skip, None)
    barrier_wait_ps = jnp.maximum(release_time - core.clock_ps, 0)
    barrier_wait_ps = jnp.where(released, barrier_wait_ps, 0)
    bsync_wait_ps = jnp.where(
        bsync_now, jnp.maximum(bsync_time - core.clock_ps, 0), 0)

    # --- MUTEX + COND ----------------------------------------------------
    # One gated block: condition variables interlock with mutexes
    # (COND_WAIT releases its mutex; a signaled waiter re-acquires it —
    # `sync_server.cc` SimCond::wait/signal/broadcast + SimMutex).
    NM = sync.mutex_locked.shape[0]
    NC = params.n_conds
    is_cwait = op == Op.COND_WAIT
    is_csig = op == Op.COND_SIGNAL
    is_cbcast = op == Op.COND_BROADCAST
    is_cinit = op == Op.COND_INIT
    BIG = jnp.asarray(2**62, I64)

    def _mutex_cond_block(_):
        mux = jnp.clip(aux0, 0, NM - 1)       # mutex ops' mutex id
        cw_mux = jnp.clip(aux1, 0, NM - 1)    # COND_WAIT's mutex id (aux1)
        cid = jnp.clip(aux0, 0, NC - 1)       # cond ops'/waiters' cond id
        minit_now = active & is_minit
        mutex_locked = sync.mutex_locked.at[mux].add(
            jnp.where(minit_now, -sync.mutex_locked[mux], 0)
        )
        # COND_WAIT arrival: join the FIFO (key = arrival time) and release
        # the mutex below (`SimCond::wait` pushes the waiter then unlocks)
        cwait_arrive = (active & is_cwait
                        & ~sync.cond_waiting & ~sync.cond_signaled)
        cond_waiting = sync.cond_waiting | cwait_arrive
        cond_arrival = jnp.where(
            cwait_arrive, core.clock_ps, sync.cond_arrival_ps)

        # --- signal/broadcast posting --------------------------------------
        # Engine-iteration order is NOT simulated-time order (a tile can be
        # behind in records yet ahead in time), so signals park in per-cond
        # pending slots stamped with their simulated time; delivery below
        # resolves them in simulated-time order.  One signal per cond per
        # iteration is accepted (the earliest by (time, tile)); surplus
        # same-iteration signalers simply do not commit their record and
        # retry next iteration (clock unchanged — timing unaffected).
        psig = sync.cond_sig_time_ps            # [NC, K], FAR = empty
        pbc = sync.cond_bcast_time_ps           # [NC],    FAR = none
        # COND_INIT resets the cond's pending state
        cinit_now = active & is_cinit
        init_cond = jnp.zeros((NC,), jnp.bool_).at[cid].max(cinit_now)
        psig = jnp.where(init_cond[:, None], BIG, psig)
        pbc = jnp.where(init_cond, BIG, pbc)
        # published (aux1>0) signals use the co-located split machinery
        # below, not the pending-slot delivery
        sig_now = active & is_csig & (aux1 <= 0)
        bcast_now = active & is_cbcast & (aux1 <= 0)
        post_key = core.clock_ps * jnp.asarray(T, I64) + tiles.astype(I64)
        sbest = _elect_min(sig_now, cid, post_key, NC)
        sig_elect = sig_now & (post_key == sbest[cid])
        free = psig >= FAR_FUTURE_PS            # [NC, K]
        have_free = free.any(axis=1)
        free_k = jnp.argmax(free, axis=1).astype(jnp.int32)
        sig_post = sig_elect & have_free[cid]
        psig = psig.at[cid, free_k[cid]].min(
            jnp.where(sig_post, core.clock_ps, BIG))
        bbest = _elect_min(bcast_now, cid, post_key, NC)
        bc_elect = bcast_now & (post_key == bbest[cid])
        bc_post = bc_elect & (pbc[cid] >= FAR_FUTURE_PS)
        pbc = pbc.at[cid].min(jnp.where(bc_post, core.clock_ps, BIG))

        # --- delivery / drop, in simulated-time order ----------------------
        # A pending signal S wakes the earliest eligible waiter (wait began
        # at W <= S).  Resolution waits until engine order can no longer
        # contradict simulated-time order: deliver when the chosen waiter's
        # W is at or before every still-running tile's clock (a later
        # registrant could at best tie, and simultaneous wait/signal is a
        # race even in the reference), and drop as LOST when every
        # still-running tile has reached S with no eligible waiter.
        # Comparisons are NON-strict: a tile pinned exactly at the post time
        # (e.g. the poster blocked on a join) must not hold delivery forever.
        # A pending broadcast and pending signals on one cond resolve in
        # simulated-time order, one per iteration — the earlier wakes first
        # and the later re-evaluates against the remaining waiters.
        runner = ~done & ~cond_waiting & ~sync.cond_signaled
        min_active = jnp.min(jnp.where(runner, core.clock_ps, BIG))
        S = jnp.min(psig, axis=1)               # [NC] earliest pending
        s_k = jnp.argmin(psig, axis=1).astype(jnp.int32)
        bc_time = pbc                           # [NC]
        have_sig = (S < FAR_FUTURE_PS) & (S < bc_time)  # signal resolves 1st
        bc_first = (bc_time < FAR_FUTURE_PS) & (bc_time <= S)
        elig = cond_waiting & (cond_arrival <= S[cid])
        wake_key = cond_arrival * jnp.asarray(T, I64) + tiles.astype(I64)
        ckey = jnp.where(elig, wake_key, BIG)
        cbest = _elect_min(elig, cid, ckey, NC)
        any_elig = cbest < BIG
        best_arrival = cbest // jnp.asarray(T, I64)
        safe_deliver = have_sig & any_elig & (best_arrival <= min_active)
        lost = have_sig & ~any_elig & (min_active >= S)
        woken_s = elig & safe_deliver[cid] & (ckey == cbest[cid])
        clear_slot = safe_deliver | lost
        psig = psig.at[jnp.arange(NC), s_k].max(
            jnp.where(clear_slot, BIG, 0))
        # pending broadcast: wakes every waiter with W <= S_bcast
        bc_ready = bc_first & (min_active >= bc_time)
        woken_b = (cond_waiting & bc_ready[cid]
                   & (cond_arrival <= bc_time[cid]) & ~woken_s)
        pbc = jnp.where(bc_ready, BIG, pbc)

        woken = woken_b | woken_s
        cond_wake = jnp.where(
            woken_b, bc_time[cid],
            jnp.where(woken_s, S[cid], sync.cond_wake_ps))
        cond_signaled = sync.cond_signaled | woken
        cond_waiting = cond_waiting & ~woken

        # lock candidates: MUTEX_LOCK lanes + signaled COND_WAIT lanes
        # re-acquiring their mutex (`SimCond::signal` → `SimMutex::lock`)
        relock = is_cwait & ~done & cond_signaled
        plain_lock = is_mlock & ~done & (sync.mutex_waiting | active)
        lock_candidate = plain_lock | relock
        lmux = jnp.where(relock, cw_mux, mux)
        eff_clock = jnp.where(
            relock, jnp.maximum(core.clock_ps, cond_wake), core.clock_ps)
        grant_key = eff_clock * jnp.asarray(T, I64) + tiles.astype(I64)
        best_key = _elect_min(lock_candidate, lmux, grant_key, NM)
        grantable = mutex_locked == 0
        # Time-order completeness guard (mirrors cond delivery): a grant
        # may only commit when nothing can still produce an earlier
        # (time, tile) request for ANY mutex:
        #  - lanes at non-blocking records will request at >= their current
        #    clock (conservatively keyed with tile 0);
        #  - candidates on other FREE mutexes could commit and re-emerge at
        #    their own (earlier) clock — so only the earliest candidate
        #    among grantable ones commits per iteration;
        #  - candidates on LOCKED mutexes re-emerge no earlier than their
        #    holder's future unlock (>= the holder's current clock), so
        #    they are bounded transitively through the holder and may be
        #    excluded — excluding them is also what keeps lock-ordered
        #    nesting deadlock-free (a waiter on a held mutex must not veto
        #    the holder's own acquisition of its next lock);
        #  - recv/join/barrier-parked lanes re-emerge at wake times bounded
        #    below by some running lane's clock, so they are covered by
        #    the advancing-lane bound transitively.
        # split-form rendezvous ops block too: their lanes re-emerge at
        # wake times bounded below by the publisher's clock, so they are
        # covered by the advancing-lane bound transitively (like recv)
        cur_blocking = (is_recv | is_join | is_bwait | is_mlock | is_cwait
                        | is_bsync | is_cjoin)
        advancing = ~done & ~cur_blocking
        min_adv_key = jnp.min(jnp.where(
            advancing, core.clock_ps * jnp.asarray(T, I64), BIG))
        free_cand_min = jnp.min(jnp.where(
            lock_candidate & grantable[lmux], grant_key, BIG))
        granted = (lock_candidate & grantable[lmux]
                   & (grant_key == best_key[lmux])
                   & (grant_key == free_cand_min)
                   & (grant_key <= min_adv_key))
        mutex_grab_time = sync.mutex_time_ps[lmux]
        # wait until: the mutex handoff, and for woken waiters the signal
        # time — clock_new = clock + wait = max(clock, wake, grab)
        wait_until = jnp.where(
            relock, jnp.maximum(mutex_grab_time, cond_wake),
            mutex_grab_time)
        mutex_wait_ps = jnp.maximum(wait_until - core.clock_ps, 0)
        mutex_wait_ps = jnp.where(granted, mutex_wait_ps, 0)
        # grant is unique per mutex (key includes tile id), unlock unique
        # per mutex (single owner), so add-deltas cannot double-apply
        mutex_locked = mutex_locked.at[lmux].add(jnp.where(granted, 1, 0))
        mutex_owner = sync.mutex_owner.at[lmux].add(
            jnp.where(granted, tiles - sync.mutex_owner[lmux], 0)
        )
        mutex_waiting = (plain_lock & ~granted) | (
            sync.mutex_waiting & ~is_mlock
        )
        cond_signaled = cond_signaled & ~granted  # commit clears the flag
        # unlock: explicit MUTEX_UNLOCK, or COND_WAIT arrival releasing its
        # mutex; stamp the handoff time (`sync_server.cc:211-240`)
        unlock_now = active & is_munlock
        un_do = unlock_now | cwait_arrive
        un_mux = jnp.where(cwait_arrive, cw_mux, mux)
        mutex_locked = mutex_locked.at[un_mux].add(jnp.where(un_do, -1, 0))
        mutex_owner = mutex_owner.at[un_mux].add(
            jnp.where(un_do, -1 - mutex_owner[un_mux], 0)
        )
        mutex_time = sync.mutex_time_ps.at[un_mux].add(
            jnp.where(un_do, core.clock_ps - sync.mutex_time_ps[un_mux], 0)
        )
        return (mutex_locked, mutex_owner, mutex_time, mutex_waiting,
                granted, mutex_wait_ps, cond_waiting, cond_signaled,
                cond_arrival, cond_wake, psig, pbc,
                sig_post | bc_post)

    def _mutex_cond_skip(_):
        return (sync.mutex_locked, sync.mutex_owner, sync.mutex_time_ps,
                sync.mutex_waiting, jnp.zeros((T,), jnp.bool_),
                jnp.zeros((T,), I64), sync.cond_waiting, sync.cond_signaled,
                sync.cond_arrival_ps, sync.cond_wake_ps,
                sync.cond_sig_time_ps, sync.cond_bcast_time_ps,
                jnp.zeros((T,), jnp.bool_))

    (mutex_locked, mutex_owner, mutex_time, mutex_waiting, granted,
     mutex_wait_ps, cond_waiting, cond_signaled, cond_arrival_ps,
     cond_wake_ps, cond_sig_time_ps, cond_bcast_time_ps,
     cond_post_commit) = lax.cond(
        _gate(jnp.any((active & (is_minit | is_munlock | is_csig
                               | is_cbcast | is_cinit))
                      | (is_mlock & ~done & (sync.mutex_waiting | active))
                      | (is_cwait & ~done))),
        _mutex_cond_block, _mutex_cond_skip, None)

    # --- published cond signals + COND_JOIN (co-located split form) ------
    # A publishing signal/broadcast bumps the cond's signal sequence and
    # stamps its time; COND_JOIN(k) waits for sequence >= k and takes the
    # stamped time (the waiter's wake).  The mutex dance around it uses
    # plain MUTEX_UNLOCK / MUTEX_LOCK records (see schema).
    # Same-iteration race contract: when two lanes publish to one cond in
    # the SAME subquantum iteration, both lanes read the post-scatter-add
    # sequence, so only the final sequence's ring slot is stamped (with
    # the max of both clocks) and the intermediate slot keeps its stale
    # time — a COND_JOIN on the intermediate sequence then takes a
    # bounded-stale timestamp.  Same class as the reference's racy
    # same-instant signal ordering (its MCP serves them in host-arrival
    # order); recorded traces order same-cond publishes through the
    # recording app's own locking, so the window is one engine iteration.
    pub_now = active & (is_csig | is_cbcast) & (aux1 > 0)

    def _pub_block(_):
        from graphite_tpu.engine.state import GEN_RING

        cid = jnp.clip(aux0, 0, NC - 1)
        # cond ids are allocated once per app run, so COND_INIT does not
        # reset the sequence (a publish record on another lane may replay
        # before a later-positioned init on the creator's lane)
        seq = sync.cond_sig_seq.at[jnp.where(pub_now, cid, 0)].add(
            jnp.where(pub_now, 1, 0))
        slot = nn_mod(seq[cid], GEN_RING).astype(jnp.int32)
        seq_ps = sync.cond_sig_seq_ps.at[
            jnp.where(pub_now, cid, 0),
            jnp.where(pub_now, slot, 0)].max(
            jnp.where(pub_now, core.clock_ps, 0))
        cjoin_now = active & is_cjoin & (seq[cid] >= aux1)
        cjoin_t = seq_ps[cid, (aux1 % GEN_RING).astype(jnp.int32)]
        return seq, seq_ps, cjoin_now, cjoin_t

    (cond_sig_seq, cond_sig_seq_ps, cjoin_now, cjoin_time) = lax.cond(
        _gate(jnp.any(pub_now | (active & is_cjoin))),
        _pub_block,
        lambda _: (sync.cond_sig_seq, sync.cond_sig_seq_ps,
                   jnp.zeros((T,), jnp.bool_), jnp.zeros((T,), I64)),
        None)
    cjoin_wait_ps = jnp.where(
        cjoin_now, jnp.maximum(cjoin_time - core.clock_ps, 0), 0)

    # --- JOIN ------------------------------------------------------------
    # The target's liveness is read off its own fetched record (every
    # lane's current op is already in hand — same clipped index the fetch
    # used), so the old per-target trace re-gather is gone; a paused
    # streaming target's window-edge record must not read as THREAD_EXIT.
    at_exit = op == Op.THREAD_EXIT
    if in_window is not None:
        at_exit = at_exit & in_window

    def _join_block(_):
        join_target = jnp.clip(aux0, 0, T - 1)
        target_done = state.done[join_target] | at_exit[join_target]
        join_now = active & is_join & target_done
        join_time = jnp.maximum(core.clock_ps, core.clock_ps[join_target])
        return join_now, join_time

    join_now, join_time = lax.cond(
        _gate(jnp.any(active & is_join)), _join_block,
        lambda _: (jnp.zeros((T,), jnp.bool_), core.clock_ps), None)

    # --- commit: advance mask, clocks, counters --------------------------
    # Instruction records with memory operands commit only once all their
    # memory slots completed (`simple_core_model.cc:53-90`: the per-operand
    # latencies and the execution cost land on the clock together).
    instr_like = is_static | is_branch
    advance = active & (
        ((instr_like | is_bblock) & mem_ok) | (is_dynamic & ~is_spawn_instr)
        | is_simple_event | is_send
    )
    advance = advance | recv_now | released | (active & is_spawn_instr)
    advance = advance | granted | join_now | cond_post_commit
    advance = advance | barrive_now | bsync_now | cjoin_now | pub_now

    clock = core.clock_ps
    if params.iocoom is not None:
        # IOCOOM: instruction-like records go through the scoreboard /
        # load-store queue pipeline algebra; everything else (events,
        # dynamic, bblock) keeps the simple cost accumulation (the
        # reference adds dynamic costs directly, `iocoom_core_model.cc:88`)
        from graphite_tpu.models.iocoom import iocoom_commit

        slot_lat = (mem_out.slot_lat_ps if params.mem is not None
                    else jnp.zeros((T, 3), I64))
        # heterogeneous tiles: non-iocoom lanes take the simple path below
        ioc_tiles = (jnp.asarray(params.iocoom_tiles, jnp.bool_)
                     if params.iocoom_tiles is not None
                     else jnp.ones((T,), jnp.bool_))
        ioc_commit_mask = advance & instr_like & ioc_tiles
        new_ioc, ioc_clock, ioc_mem_stall, ioc_exec_stall = iocoom_commit(
            params.iocoom, state.ioc,
            commit=ioc_commit_mask,
            clock_ps=core.clock_ps,
            freq_mhz=core.freq_mhz.astype(I64),
            cost_ps=cost_ps,
            flags=flags,
            rreg0=fetched[-3].astype(jnp.int32),
            rreg1=fetched[-2].astype(jnp.int32),
            wreg=fetched[-1].astype(jnp.int32),
            addr0=(fetched[6] if params.mem is not None
                   else jnp.zeros((T,), jnp.uint32)),
            addr1=(fetched[7] if params.mem is not None
                   else jnp.zeros((T,), jnp.uint32)),
            slot_lat_ps=slot_lat,
            enabled=enabled,
        )
        simple_instr = instr_like & ~ioc_tiles
        clock = jnp.where(advance & (is_bblock
                                     | (is_dynamic & ~is_spawn_instr)
                                     | is_simple_event | is_send
                                     | simple_instr),
                          clock + cost_ps
                          + jnp.where(is_bblock | simple_instr,
                                      mem_acc_ps, 0),
                          clock)
        clock = jnp.where(ioc_commit_mask, ioc_clock, clock)
    else:
        new_ioc = state.ioc
        ioc_mem_stall = None
        ioc_exec_stall = None
        clock = jnp.where(advance & (instr_like | is_bblock
                                     | (is_dynamic & ~is_spawn_instr)
                                     | is_simple_event | is_send),
                          clock + cost_ps
                          + jnp.where(instr_like | is_bblock, mem_acc_ps, 0),
                          clock)
    clock = jnp.where(active & is_spawn_instr,
                      jnp.maximum(clock, dyn_ps), clock)
    clock = jnp.where(recv_now, jnp.maximum(clock, recv_time), clock)
    clock = jnp.where(released, jnp.maximum(clock, release_time), clock)
    clock = jnp.where(granted, clock + mutex_wait_ps, clock)
    clock = jnp.where(join_now, join_time, clock)
    clock = jnp.where(bsync_now, jnp.maximum(clock, bsync_time), clock)
    clock = jnp.where(cjoin_now, jnp.maximum(clock, cjoin_time), clock)

    # DVFS_SET retunes the target domain's frequency, validated against the
    # voltage/frequency tables (`DVFSManager::getVoltage`, technology
    # levels): AUTO picks the minimum voltage for the frequency; HOLD
    # (encoded aux1 < 0) fails if the frequency exceeds the current
    # voltage's maximum; invalid requests count into dvfs errors and leave
    # state unchanged (`dvfs.h` rc codes -2/-4/-5).
    is_dvfs_set = op == Op.DVFS_SET
    # runtime DVFS (round 19): with a spec + carry attached, successful
    # DVFS_SET requests additionally elect the chip-global per-domain
    # operating point — the dmask cond output exists ONLY then (python-
    # level gate), so dvfs=None lowers the historical cond byte-identically
    want_rt = dvfs is not None and state.dvfs_rt is not None
    new_rt = state.dvfs_rt
    if params.dvfs is not None and state.dvfs is not None:
        dvp = params.dvfs
        ND = dvp.n_domains

        def _dvfs_block(_):
            req = jnp.abs(aux1)
            hold = aux1 < 0
            dom = jnp.clip(aux0, 0, ND - 1)
            valid_dom = (aux0 >= 0) & (aux0 < ND)
            volts = jnp.asarray(dvp.voltages_mv, jnp.int32)   # [L] desc
            maxf = jnp.asarray(dvp.max_freq_mhz, jnp.int32)   # [L] desc
            L = len(dvp.voltages_mv)
            ok_levels = req[:, None] <= maxf[None, :]         # [T, L]
            freq_ok = ok_levels.any(axis=1) & (req > 0)
            # minimum voltage = last satisfying level (descending tables)
            lvl = (L - 1) - jnp.argmax(
                ok_levels[:, ::-1], axis=1).astype(jnp.int32)
            auto_v = volts[jnp.clip(lvl, 0, L - 1)]
            cur_v = state.dvfs.voltage_mv[tiles, dom]
            cur_lvl = jnp.argmax(
                volts[None, :] == cur_v[:, None], axis=1).astype(jnp.int32)
            hold_ok = req <= maxf[cur_lvl]
            attempt = active & is_dvfs_set
            ok = attempt & valid_dom & freq_ok & (~hold | hold_ok)
            err = attempt & ~(valid_dom & freq_ok & (~hold | hold_ok))
            new_v = jnp.where(hold, cur_v, auto_v)
            dmask = (dom[:, None] == jnp.arange(ND, dtype=jnp.int32)[None, :]
                     ) & ok[:, None]
            freq2 = jnp.where(dmask, req[:, None], state.dvfs.freq_mhz)
            volt2 = jnp.where(dmask, new_v[:, None], state.dvfs.voltage_mv)
            errs2 = state.dvfs.errors + err.astype(I64)
            core_set = ok & (dom == dvp.core_domain)
            out = (freq2, volt2, errs2, core_set, req)
            if want_rt:
                out = out + (dmask,)
            return out

        def _dvfs_skip(_):
            out = (state.dvfs.freq_mhz, state.dvfs.voltage_mv,
                   state.dvfs.errors, jnp.zeros((T,), jnp.bool_),
                   jnp.zeros((T,), aux1.dtype))
            if want_rt:
                out = out + (jnp.zeros((T, ND), jnp.bool_),)
            return out

        dvfs_out = lax.cond(
            jnp.any(active & is_dvfs_set), _dvfs_block, _dvfs_skip, None)
        (dv_freq, dv_volt, dv_errs, dvfs_core_set, dvfs_req) = dvfs_out[:5]
        new_dvfs = state.dvfs.replace(
            freq_mhz=dv_freq, voltage_mv=dv_volt, errors=dv_errs)
        if want_rt:
            from graphite_tpu.dvfs.runtime import (
                core_freq_tiles, elect_domains,
            )

            new_rt = elect_domains(dvp, state.dvfs_rt, dvfs_req,
                                   dvfs_out[5])
            # chip-global CORE domain: the elected frequency broadcasts
            # to every tile (the per-tile table above stays the legacy
            # get/set view)
            freq_mhz = core_freq_tiles(dvp, new_rt, core.freq_mhz)
        else:
            freq_mhz = jnp.where(
                dvfs_core_set, dvfs_req.astype(core.freq_mhz.dtype),
                core.freq_mhz)
    else:
        new_dvfs = state.dvfs
        dvfs_set_now = active & is_dvfs_set & (aux0 == 0) & (aux1 > 0)
        freq_mhz = jnp.where(dvfs_set_now, aux1, core.freq_mhz)

    # --- plain-run batching (per-instruction streams) --------------------
    # A lane whose record committed may commit up to plain_unroll-1
    # FOLLOW-ON records in the same iteration when they are PLAIN static
    # costs (op <= MFENCE, not BRANCH): no machinery, no memory slots, no
    # predictor state — pure additive cost, so batching is bit-exact (per
    # record ceil cycles->ps conversion, accumulated clock must stay
    # before qend exactly like the per-iteration `active` check; a DVFS
    # retune is an event, so the batch always runs at one frequency).
    # This is runtime BBLOCK compression for externally captured
    # per-instruction traces — the streamed replay's floor (PERF.md).
    # (lax_p2p excluded: its pairwise clamp is a PER-ITERATION hold, so
    # batching extra records would overrun the slack bound)
    if (params.plain_unroll > 1 and params.mem is None
            and params.iocoom is None and params.p2p_slack_ps is None
            and trace.length > 1):
        # short traces (compressed benchmark skeletons) bound the window;
        # PLAIN_UNROLL_MAX clamps configs past the measured-safe ceiling
        # (the follow-on gather regresses superlinearly above it)
        KX = min(params.plain_unroll - 1, PLAIN_UNROLL_MAX - 1,
                 trace.length - 1)
        offs = np.arange(1, KX + 1, dtype=np.int32)
        pos_l = jnp.minimum(idx_l[:, None] + offs[None, :],
                            trace.length - 1)
        # lockstep fast path (same trick as the record fetch): one
        # dynamic column slice instead of a per-row gather; the gather
        # runs when lanes diverged or the slice would clamp at the edge
        ok_uniform = uniform & (idx[0] + 1 + KX <= trace.length)
        ops_x_l = lax.cond(
            ok_uniform,
            lambda _: lax.dynamic_slice_in_dim(
                trace.op, idx[0] + 1, KX, axis=1),
            lambda _: jnp.take_along_axis(trace.op, pos_l, axis=1),
            None)
        ops_x = px.ag(ops_x_l).astype(jnp.int32)
        valid = (idx[:, None] + offs[None, :]) < trace.length
        plain = valid & (ops_x <= int(Op.MFENCE)) & (
            ops_x != int(Op.BRANCH))
        cycles_x = cost_table[jnp.clip(ops_x, 0, 19)]
        cost_x = cycles_to_ps(cycles_x, freq_mhz.astype(I64)[:, None])
        # the CURRENT record may be an ENABLE/DISABLE_MODELS event — its
        # follow-ons run under the POST-event model state (same formula
        # the commit applies to state.models_enabled below)
        en_post = jnp.where(
            jnp.any(active & (op == Op.DISABLE_MODELS)), False,
            jnp.where(jnp.any(active & (op == Op.ENABLE_MODELS)), True,
                      enabled))
        cost_x = jnp.where(en_post, cost_x, 0)
        cum_before = clock[:, None] + jnp.cumsum(cost_x, axis=1) - cost_x
        commit_x = (plain & (cum_before < quantum_end_ps)
                    & advance[:, None])
        commit_x = jnp.cumprod(commit_x.astype(jnp.int32), axis=1) > 0
        extra_n = commit_x.sum(axis=1).astype(jnp.int32)
        extra_charged = jnp.where(en_post, extra_n, 0)
        extra_cost = jnp.where(commit_x, cost_x, 0).sum(axis=1)
        clock = clock + extra_cost
    else:
        extra_n = jnp.zeros((T,), jnp.int32)
        extra_charged = extra_n
        extra_cost = jnp.zeros((T,), I64)

    instr_now = advance & (is_static | is_branch
                           | (is_dynamic & ~is_spawn_instr))
    recv_charged = recv_now & (recv_wait_ps > 0) & enabled
    sync_charged = (released & (barrier_wait_ps > 0) | granted
                    & (mutex_wait_ps > 0)
                    | (bsync_now & (bsync_wait_ps > 0))
                    | (cjoin_now & (cjoin_wait_ps > 0))) & enabled

    # --- latency histograms (round 21): commit-site scatter-add ----------
    # Python-level gate: hist=None adds zero ops and zero carry leaves,
    # so the off program lowers byte-identically (the hist-off lint).
    # The recording masks are the counter-increment masks above — the
    # conservation invariant obs/hist.conservation_totals documents.
    new_hist = state.hist
    if hist is not None:
        from graphite_tpu.obs.hist import hist_commit_update

        mem_kw = {}
        if params.mem is not None:
            mem_kw = dict(
                present=slots_present(mem_p, rec, enabled),
                slot_lat_ps=mem_out.slot_lat_ps,
                # per-call miss completions from the engine's phase-6
                # fill delta (MemStepOut.fill_now) — an entry/exit phase
                # comparison would miss transactions that start AND fill
                # within one engine call
                miss_now=mem_out.fill_now & enabled,
                miss_lat_ps=mem_out.fill_lat_ps,
            )
        new_hist = hist_commit_update(
            hist, state.hist,
            advance=advance, enabled=enabled,
            recv_now=recv_now, recv_lat_ps=recv_lat,
            recv_charged=recv_charged, recv_wait_ps=recv_wait_ps,
            sync_charged=sync_charged,
            sync_wait_ps=(barrier_wait_ps + mutex_wait_ps
                          + bsync_wait_ps + cjoin_wait_ps),
            px=px, **mem_kw)

    new_core = core.replace(
        clock_ps=clock,
        freq_mhz=freq_mhz,
        idx=core.idx + advance.astype(jnp.int32) + extra_n,
        instruction_count=core.instruction_count
        + (instr_now & enabled).astype(I64)
        + extra_charged.astype(I64)
        + jnp.where(advance & is_bblock & enabled, aux0.astype(I64), 0)
        + recv_charged.astype(I64)
        + sync_charged.astype(I64),
        memory_stall_ps=core.memory_stall_ps
        + (jnp.where(advance & (is_bblock | simple_instr), mem_acc_ps, 0)
           + ioc_mem_stall
           if params.iocoom is not None else
           jnp.where(advance & (instr_like | is_bblock), mem_acc_ps, 0)),
        execution_stall_ps=core.execution_stall_ps + extra_cost
        + (jnp.where(advance & (is_bblock | simple_instr), cost_ps, 0)
           + ioc_exec_stall
           if params.iocoom is not None else
           jnp.where(advance & (is_static | is_branch | is_bblock),
                     cost_ps, 0)),
        recv_instructions=core.recv_instructions + recv_charged.astype(I64),
        recv_stall_ps=core.recv_stall_ps
        + jnp.where(recv_charged, recv_wait_ps, 0),
        sync_instructions=core.sync_instructions + sync_charged.astype(I64),
        sync_stall_ps=core.sync_stall_ps
        + jnp.where(released & enabled, barrier_wait_ps, 0)
        + jnp.where(granted & enabled, mutex_wait_ps, 0)
        + jnp.where(enabled, bsync_wait_ps + cjoin_wait_ps, 0),
        # delta-add (uint8 modular): old + (taken - old) == taken; avoids a
        # second gather of bp_bits inside the scatter so the buffer updates
        # in place ((tiles, bp_index) pairs are unique per lane); applied
        # block-local under a sharded px
        bp_bits=px.lane_col_add(
            core.bp_bits, *px.lo((
                bp_index,
                jnp.where(active & is_branch & enabled, taken - bp_pred, 0)
                .astype(jnp.uint8)))),
        bp_correct=core.bp_correct
        + (active & is_branch & bp_correct_now & enabled).astype(I64),
        bp_incorrect=core.bp_incorrect
        + (active & is_branch & ~bp_correct_now & enabled).astype(I64),
    )
    new_net = net.replace(
        time_ps=time_ps_new,
        lat_ps=lat_arr_new,
        head=head_new,
        count=count_new,
        overflow=overflow,
        packets_sent=net.packets_sent + send_now.astype(I64),
        packets_received=net.packets_received + recv_now.astype(I64),
        total_latency_ps=net.total_latency_ps
        + jnp.where(recv_now, recv_lat.astype(I64), 0),
    )
    new_sync = sync.replace(
        barrier_count=barrier_count,
        barrier_arrived=barrier_arrived,
        barrier_time_ps=barrier_time,
        barrier_waiting=barrier_waiting,
        barrier_gen=barrier_gen,
        barrier_release_ps=barrier_release_ps,
        cond_sig_seq=cond_sig_seq,
        cond_sig_seq_ps=cond_sig_seq_ps,
        mutex_locked=mutex_locked,
        mutex_owner=mutex_owner,
        mutex_time_ps=mutex_time,
        mutex_waiting=mutex_waiting,
        cond_waiting=cond_waiting,
        cond_signaled=cond_signaled,
        cond_arrival_ps=cond_arrival_ps,
        cond_wake_ps=cond_wake_ps,
        cond_sig_time_ps=cond_sig_time_ps,
        cond_bcast_time_ps=cond_bcast_time_ps,
    )
    enable_now = jnp.any(active & (op == Op.ENABLE_MODELS))
    disable_now = jnp.any(active & (op == Op.DISABLE_MODELS))
    models_enabled = jnp.where(
        disable_now, False, jnp.where(enable_now, True, state.models_enabled)
    )
    if params.mem is not None:
        # reset the per-record slot machinery on commit
        mem_state = mem_state.replace(req=mem_state.req.replace(
            slot=jnp.where(advance, 0, mem_state.req.slot),
            acc_ps=jnp.where(advance, 0, mem_state.req.acc_ps),
            slot_lat_ps=jnp.where(
                advance[:, None], 0, mem_state.req.slot_lat_ps),
        ))
    new_state = SimState(
        core=new_core,
        net=new_net,
        sync=new_sync,
        models_enabled=models_enabled,
        done=done,
        mem=mem_state,
        noc_user=noc_user,
        ioc=new_ioc,
        dvfs=new_dvfs,
        p2p_round=p2p_round,
        # telemetry + profile rings ride the carry untouched here; the
        # OUTER quantum loop appends rows (obs.telemetry_tick /
        # obs.profile_tick) — None adds no leaves
        telemetry=state.telemetry,
        profile=state.profile,
        dvfs_rt=new_rt,
        hist=new_hist,
    )
    return new_state, jnp.sum(advance, dtype=jnp.int32) + mem_progress


def _quantum_loop(params, trace, state, qend, trace_base=None, px=IDENT,
                  knobs=None, dvfs=None, hist=None):
    """Blocks of `inner_block` iterations until no tile makes progress.
    Returns (state, total_progress, n_iterations)."""

    def block(state, progress):
        # Bounded while_loop, NOT a lax.scan: both lower to the same HLO
        # While with a static trip count, but a scan's body is multiplied
        # by `length` in the static cost model's dense-iteration view
        # (analysis/cost.py) — the budgeted kernels_per_iter then priced
        # a 32-iteration BLOCK, not the protocol iteration it is named
        # for.  The while form makes the per-iteration base the unit the
        # budget ratchet tracks.  Trip count, flush cadence, and every
        # carried value are identical to the scan, so the swap is
        # bit-exact (regress rung + golden interpreters pin it).
        def body(carry):
            st, prog, i = carry
            st, adv = subquantum_iteration(params, trace, st, qend,
                                           trace_base, px=px, knobs=knobs,
                                           dvfs=dvfs, hist=hist)
            return st, prog + adv, i + 1

        state, progress, _ = lax.while_loop(
            lambda c: c[2] < params.inner_block, body,
            (state, progress, jnp.asarray(0, jnp.int32)),
        )
        if (params.mem is not None
                and getattr(params.mem, "dir_stage_cap", 0)):
            # One amortized dense pass applies the block's staged
            # directory writes (memory/engine.dir_stage_flush); capacity
            # covers a full block, so flushing here is always in time.
            # Deliberately UNCONDITIONAL (no lax.cond on sn > 0): a cond
            # would double-buffer the multi-GB sharers store in HBM —
            # the same pathology that disables mem_gate at this scale —
            # and in the big configs where staging auto-enables, the
            # direct path paid its three full-array dense passes every
            # iteration even with all-false write masks, so an empty
            # flush per block is already the cheap case.
            from graphite_tpu.memory.engine import dir_stage_flush

            state = state.replace(mem=state.mem.replace(
                directory=dir_stage_flush(state.mem.directory)))
        return state, progress

    def cond(carry):
        _, _, blk_prog, _ = carry
        return blk_prog > 0

    def body(carry):
        st, total, _, iters = carry
        st, blk = block(st, jnp.asarray(0, jnp.int32))
        return st, total + blk, blk, iters + params.inner_block

    state, total, _, iters = lax.while_loop(
        cond, body,
        (state, jnp.asarray(0, jnp.int32), jnp.asarray(1, jnp.int32),
         jnp.asarray(0, jnp.int64)))
    return state, total, iters


def run_quantum(
    params: EngineParams, trace: DeviceTrace, state: SimState, qend: jax.Array
) -> SimState:
    """Run one lax-barrier quantum as a single compiled XLA region.

    Runs blocks of `inner_block` subquantum iterations under a while_loop
    until no tile makes progress (all done, all past the quantum boundary,
    or — transiently — all blocked on messages that can only arrive next
    quantum).  This is the quantum of `clock_skew_management/lax_barrier`
    (`carbon_sim.cfg:92-97`).  Deliberately NOT a module-level
    `jit(static_argnums=0)`: jitting here with dataclass static args hits a
    jax-0.9 dispatch bug (constant-buffer miscount after topology changes);
    callers jit a closure instead (see `make_simulation_runner`).
    """
    state, _, _ = _quantum_loop(params, trace, state, qend)
    return state


def run_simulation(
    params: EngineParams,
    trace: DeviceTrace,
    state: SimState,
    quantum_ps: "int | jax.Array | None",
    max_quanta: int = 1_000_000,
    trace_base: jax.Array | None = None,
    px: ParallelCtx = IDENT,
    knobs=None,
    telemetry=None,
    profile=None,
    dvfs=None,
    hist=None,
):
    """The whole simulation as ONE compiled region: an outer while_loop over
    lax-barrier quanta (the MCP barrier loop, `lax_barrier_sync_server.h`)
    wrapping the per-quantum progress loop.

    `quantum_ps` may be a TRACED int64 scalar (the sweep's quantum knob):
    boundary math is pure arithmetic, so a per-point quantum rides the
    same compiled program.  `knobs` (sweep.Knobs) likewise threads traced
    timing scalars into the memory engines; see subquantum_iteration.

    Device-driven on purpose: every host↔device round trip costs ~100 ms
    over a tunneled chip, so the host loop's per-quantum control reads made
    quanta 5x slower than the quantum itself.  Loop control (next quantum
    boundary, zero-progress/deadlock detection, overflow) is computed on
    device; the host reads back one final state.

    Returns (state, n_quanta, deadlock flag) — deadlock means a quantum made
    zero progress while some tile was eligible to run (same condition the
    reference debugs with its progress trace, `pin/progress_trace.cc`).

    `telemetry` (a RESOLVED obs.TelemetrySpec; state.telemetry must hold
    the matching TelemetryState) appends one row to the device-resident
    timeline ring whenever a quantum crosses a `sample_interval_ps`
    simulated-time boundary — the reference's statistics-thread sampling
    points, recorded with zero host sync.  None (the default) lowers a
    bit-identical program (the round-7 knobs=None contract; enforced by
    the telemetry-off audit lint).

    `profile` (a RESOLVED obs.ProfileSpec; state.profile must hold the
    matching ProfileState) appends one [T, m] per-tile row to the
    spatial profile ring on the SAME simulated-time boundaries — the
    second ring of the round-16 spatial profiler.  None (the default)
    lowers a bit-identical program (the `profile-off` audit lint).

    `dvfs` (a RESOLVED dvfs.DvfsSpec; state.dvfs_rt must hold the
    matching DvfsRtState) turns on the runtime DVFS manager: carried
    per-domain frequencies feed the timing conversions, in-trace
    DVFS_SET events retune, the optional governor steps the V/f ladder
    at quantum boundaries, and (with scale_energy) the energy series
    prices each domain at its current V²·f operating point.  None (the
    default) lowers a bit-identical program (the `dvfs-off` audit lint).

    `hist` (a RESOLVED obs.HistSpec; state.hist must hold the matching
    HistState) records the latency histograms: the commit-site sources
    scatter inside `subquantum_iteration` and the boundary sources
    (clock skew, energy deltas) sample here every executed quantum.
    None (the default) lowers a bit-identical program (the `hist-off`
    audit lint).
    """
    if telemetry is not None:
        from graphite_tpu.obs.telemetry import telemetry_tick
    if profile is not None:
        from graphite_tpu.obs.profile import profile_tick
    if hist is not None:
        from graphite_tpu.obs.hist import hist_boundary_tick
    if dvfs is not None:
        from graphite_tpu.dvfs.runtime import core_freq_tiles, governor_tick
    # energy terms price at the carried operating point only when asked
    dvfs_energy = (params.dvfs
                   if dvfs is not None and dvfs.scale_energy else None)
    INF_QEND = jnp.asarray(2**61, I64)
    if quantum_ps is None:
        qps = None
    elif isinstance(quantum_ps, jax.Array):
        qps = quantum_ps          # traced sweep knob (int64 scalar)
    else:
        qps = int(quantum_ps)

    def next_boundary(clock):
        return (clock // qps + 1) * qps

    def cond(carry):
        st, qend, n, deadlock, stalled, _ = carry
        return (
            ~jnp.all(st.done)
            & ~st.net.overflow
            & ~deadlock
            & ~stalled
            & (n < max_quanta)
        )

    def body(carry):
        st, prev_qend, n, deadlock, stalled, iters = carry
        clocks = st.core.clock_ps
        not_done = ~st.done
        min_pending = jnp.min(jnp.where(not_done, clocks, jnp.asarray(2**62, I64)))
        if qps is None:
            qend = INF_QEND
        else:
            qend = jnp.maximum(prev_qend + qps, next_boundary(min_pending))
        st2, progress, blk_iters = _quantum_loop(params, trace, st, qend,
                                                 trace_base, px=px,
                                                 knobs=knobs, dvfs=dvfs,
                                                 hist=hist)
        if dvfs is not None and dvfs.governor is not None:
            # reactive governor: step the governed domains' V/f level on
            # the utilization window — masked arithmetic only (the
            # telemetry_tick pattern), evaluated at the quantum boundary
            rt2 = governor_tick(dvfs.governor, params.dvfs,
                                st2.dvfs_rt, st2)
            st2 = st2.replace(
                dvfs_rt=rt2,
                core=st2.core.replace(freq_mhz=core_freq_tiles(
                    params.dvfs, rt2, st2.core.freq_mhz)))
        if telemetry is not None:
            st2 = st2.replace(telemetry=telemetry_tick(
                telemetry, st2, progress=progress, blk_iters=blk_iters,
                dvfs=dvfs_energy))
        if profile is not None:
            # same boundary arithmetic as the telemetry tick — with
            # equal intervals XLA CSEs the shared scalar reductions, so
            # the two rings cost one boundary test per quantum; under a
            # tile-sharded px the [S, T, m] ring is block-local and the
            # tick appends only this device's lanes (obs/profile.py)
            st2 = st2.replace(profile=profile_tick(profile, st2, px=px,
                                                   dvfs=dvfs_energy))
        if hist is not None:
            # boundary sources sample EVERY executed quantum (each one
            # is a whole-fleet skew observation — the four-scheme
            # study's instrument); under a tile-sharded px the per-tile
            # ring appends only this device's lanes (obs/hist.py)
            st2 = st2.replace(hist=hist_boundary_tick(hist, st2, px=px,
                                                      dvfs=dvfs_energy))
        # Zero progress: if some non-done tile sits beyond qend (it crossed
        # the boundary executing one long record), jump the window up to it
        # — blocked peers may wait on its future sends.  Only when every
        # non-done tile was already eligible is this a genuine deadlock.
        zero = (progress == 0) & jnp.any(~st2.done)
        if trace_base is not None:
            # streaming: lanes past the window end are merely paused;
            # zero progress with a paused lane returns to the host for a
            # window slide instead of flagging deadlock
            paused = jnp.any(
                ~st2.done
                & (st2.core.idx >= trace_base + trace.length))
        else:
            paused = jnp.asarray(False)
        if qps is not None:
            ahead_clock = jnp.min(jnp.where(
                ~st2.done & (st2.core.clock_ps >= qend),
                st2.core.clock_ps, jnp.asarray(2**62, I64)))
            have_ahead = ahead_clock < 2**62
            qend_next = jnp.where(
                zero & have_ahead, next_boundary(ahead_clock) - qps, qend)
            deadlock = zero & ~have_ahead & ~paused
            stalled = zero & ~have_ahead & paused
        else:
            qend_next = qend
            deadlock = zero & ~paused
            stalled = zero & paused
        return st2, qend_next, n + 1, deadlock, stalled, iters + blk_iters

    state, _, n_quanta, deadlock, _, n_iters = lax.while_loop(
        cond, body,
        (state, jnp.asarray(0, I64), jnp.asarray(0, jnp.int32),
         jnp.asarray(False), jnp.asarray(False), jnp.asarray(0, jnp.int64)))
    return state, n_quanta, deadlock, n_iters


def barrier_host_batch(
    params: EngineParams,
    trace: DeviceTrace,
    state: SimState,
    prev_qend: jax.Array,     # int64[] qend of the previous quantum
    quantum_ps: int,
    max_quanta: jax.Array,    # int32[] quanta budget for THIS dispatch
    telemetry=None,
    profile=None,
    dvfs=None,
    hist=None,
):
    """Up to `max_quanta` lax_barrier quanta as ONE compiled region — the
    batched form of the host-driven barrier loop (Simulator.barrier_host).

    The per-quantum host dispatch costs ~100 ms of tunnel overhead each
    (896 quanta = the 8.3 s config-5 wall, PERF.md round 5); this bounded
    device-side while_loop amortizes it ~K per dispatch and EARLY-EXITS
    back to the host exactly when a quantum raises host-visible work:
    every tile done, a mailbox overflow, or a genuine deadlock (zero
    progress with no tile beyond the boundary).  Quantum semantics are
    identical to the per-quantum host loop: next boundary above the
    laggard tile, empty quanta skipped via the prev_qend floor, and a
    zero-progress quantum with a tile beyond the boundary jumps the
    window up to it (`lax_barrier_sync_server.h:12-36`).

    Returns (state, prev_qend, n_quanta, deadlock, n_iterations); the
    host threads prev_qend into the next dispatch so boundary progression
    is seamless across batches.

    `telemetry` / `profile` sample the device-resident rings exactly as
    in `run_simulation`; the sampling cursors ride the state carry, so
    recording is seamless across dispatches too.
    """
    if telemetry is not None:
        from graphite_tpu.obs.telemetry import telemetry_tick
    if profile is not None:
        from graphite_tpu.obs.profile import profile_tick
    if hist is not None:
        from graphite_tpu.obs.hist import hist_boundary_tick
    if dvfs is not None:
        from graphite_tpu.dvfs.runtime import core_freq_tiles, governor_tick
    dvfs_energy = (params.dvfs
                   if dvfs is not None and dvfs.scale_energy else None)
    qps = int(quantum_ps)

    def next_boundary(clock):
        return (clock // qps + 1) * qps

    def cond(carry):
        st, _, n, deadlock, _ = carry
        return (
            ~jnp.all(st.done)
            & ~st.net.overflow
            & ~deadlock
            & (n < max_quanta)
        )

    def body(carry):
        st, prev, n, deadlock, iters = carry
        clocks = st.core.clock_ps
        min_pending = jnp.min(jnp.where(~st.done, clocks,
                                        jnp.asarray(2**62, I64)))
        qend = jnp.maximum(prev + qps, next_boundary(min_pending))
        st2, progress, blk_iters = _quantum_loop(params, trace, st, qend,
                                                 dvfs=dvfs, hist=hist)
        if dvfs is not None and dvfs.governor is not None:
            rt2 = governor_tick(dvfs.governor, params.dvfs,
                                st2.dvfs_rt, st2)
            st2 = st2.replace(
                dvfs_rt=rt2,
                core=st2.core.replace(freq_mhz=core_freq_tiles(
                    params.dvfs, rt2, st2.core.freq_mhz)))
        if telemetry is not None:
            st2 = st2.replace(telemetry=telemetry_tick(
                telemetry, st2, progress=progress, blk_iters=blk_iters,
                dvfs=dvfs_energy))
        if profile is not None:
            st2 = st2.replace(profile=profile_tick(profile, st2,
                                                   dvfs=dvfs_energy))
        if hist is not None:
            st2 = st2.replace(hist=hist_boundary_tick(hist, st2,
                                                      dvfs=dvfs_energy))
        zero = (progress == 0) & jnp.any(~st2.done)
        ahead_clock = jnp.min(jnp.where(
            ~st2.done & (st2.core.clock_ps >= qend),
            st2.core.clock_ps, jnp.asarray(2**62, I64)))
        have_ahead = ahead_clock < 2**62
        # a tile crossed the boundary executing one long record: jump the
        # window so the NEXT quantum's floor lands just below it
        qend_next = jnp.where(zero & have_ahead,
                              next_boundary(ahead_clock) - qps, qend)
        deadlock = zero & ~have_ahead
        return st2, qend_next, n + 1, deadlock, iters + blk_iters

    state, prev_qend, n, deadlock, iters = lax.while_loop(
        cond, body,
        (state, jnp.asarray(prev_qend, I64), jnp.asarray(0, jnp.int32),
         jnp.asarray(False), jnp.asarray(0, jnp.int64)))
    return state, prev_qend, n, deadlock, iters


def make_simulation_runner(params: EngineParams, trace: DeviceTrace,
                           quantum_ps: int | None, max_quanta: int,
                           donate: bool = False, telemetry=None,
                           profile=None, dvfs=None, hist=None):
    """`donate=True` hands the input state's buffers to XLA (halves the
    protocol state's HBM residency — the 1024-tile directory is 2.4 GB,
    and without donation input + output + scatter staging exceeds the
    chip; see PERF.md).  The caller's old state object is consumed."""
    def run(state: SimState):
        return run_simulation(params, trace, state, quantum_ps, max_quanta,
                              telemetry=telemetry, profile=profile,
                              dvfs=dvfs, hist=hist)

    return jax.jit(run, donate_argnums=(0,) if donate else ())
