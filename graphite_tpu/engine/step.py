"""The vectorized subquantum step: every tile advances one trace record.

This replaces Graphite's per-instruction host control flow — Pin callback →
`CoreModel::queueInstruction/iterate` (`pin/instruction_modeling.cc:13-21`,
`common/tile/core/models/simple_core_model.cc:37-97`) and the blocking
netRecv / MCP sync-server round trips (`network.cc:358-460`,
`common/system/sync_server.cc:27-160`) — with a masked SoA state machine:

 - one `lax.scan` iteration processes (at most) one trace record per tile,
   all tiles in parallel;
 - blocked operations (recv with no matching packet, barrier not full,
   mutex held) simply do not advance `idx`; they retry next iteration, when
   messages pushed by other tiles in earlier iterations have landed;
 - sends scatter into per-(dst,src) mailbox rings — each sender lane owns
   its own src column, so writes never collide;
 - barrier arrivals/releases use scatter-add/scatter-max plus a global
   release mask, reproducing SimBarrier's max-arrival-time release
   (`sync_server.cc:133-160`);
 - mutex grants pick the earliest-simulated-time waiter via a segmented
   min over (clock, tile) keys, reproducing SimMutex handoff-at-unlock-time
   (`sync_server.cc:27-57,185-240`) deterministically (the reference's FIFO
   is host-arrival-order and racy).

Timing semantics per record mirror the reference exactly:
 - static instruction cost from the `[core/static_instruction_costs]` table
   (`core_model.cc:65-76`), converted at the tile's DVFS frequency;
 - branch cost 1 cycle on correct prediction else the mispredict penalty,
   one-bit predictor indexed by pc (`instruction.cc:47-70`,
   `one_bit_branch_predictor.cc:13-24`, `carbon_sim.cfg:202-205`);
 - dynamic instruction cost carried in the record (`instruction.h:149-198`);
 - netRecv: clock = max(clock, arrival); a RecvInstruction is accounted only
   when arrival > clock (`network.cc:443-453`);
 - barrier release at max arrival time with a SyncInstruction only when the
   wait was positive (`sync_server.cc:141-144`, `sync_client.cc:83-87`);
 - models-disabled ⇒ zero cost and no counters, but full functional effect
   (`simulator.cc:399-413`, `core_model.h` _enabled gate).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from graphite_tpu.engine.state import SimState, DeviceTrace
from graphite_tpu.models.network_user import UserNetworkParams, route_latency_ps
from graphite_tpu.trace.schema import (
    FLAG_BRANCH_TAKEN,
    Op,
)
from graphite_tpu.time_types import cycles_to_ps

I64 = jnp.int64
FAR_FUTURE_PS = 2**62  # python int: folds to an inline literal, never a device-constant buffer
ANY_SENDER = -1


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Static compile-time parameters of the step function."""

    n_tiles: int
    static_cost_cycles: tuple  # 20 ints (`carbon_sim.cfg:189-200`)
    net: UserNetworkParams
    bp_enabled: bool = True
    bp_size: int = 1024
    bp_mispredict_penalty: int = 14
    mailbox_depth: int = 8
    inner_block: int = 32      # trace records per tile per scan
    # memory subsystem (None = enable_shared_mem false: memory operands
    # cost nothing, like the reference's disabled shared-mem knob)
    mem: "object" = None       # MemParams | None
    # USER network full hop-by-hop model with per-port contention
    user_hbh: "object" = None  # HopByHopParams | None


def _gather_field(field: jax.Array, idx: jax.Array) -> jax.Array:
    return jnp.take_along_axis(field, idx[:, None], axis=1)[:, 0]




def subquantum_iteration(
    params: EngineParams,
    trace: DeviceTrace,
    state: SimState,
    quantum_end_ps: jax.Array,
) -> tuple[SimState, jax.Array]:
    """Process one trace record per tile; returns (state, tiles_advanced)."""
    T = params.n_tiles
    D = params.mailbox_depth
    core, net, sync = state.core, state.net, state.sync
    tiles = jnp.arange(T, dtype=jnp.int32)
    idx = jnp.minimum(core.idx, trace.length - 1)

    op = _gather_field(trace.op, idx).astype(jnp.int32)
    flags = _gather_field(trace.flags, idx).astype(jnp.int32)
    pc = _gather_field(trace.pc, idx)
    aux0 = _gather_field(trace.aux0, idx)
    aux1 = _gather_field(trace.aux1, idx)
    dyn_ps = _gather_field(trace.dyn_ps, idx)

    enabled = state.models_enabled
    done = state.done | (op == Op.NOP) | (op == Op.THREAD_EXIT)
    active = (~done) & (core.clock_ps < quantum_end_ps)

    # --- memory subsystem (caches + coherence protocol) ------------------
    # Runs every iteration: requester lanes start/advance their record's
    # memory slots; home/sharer machinery serves protocol messages even for
    # tiles past the quantum boundary (like the reference's sim threads).
    if params.mem is not None:
        from graphite_tpu.memory.engine import RecView, memory_engine_step

        addr0 = _gather_field(trace.addr0, idx)
        addr1 = _gather_field(trace.addr1, idx)
        rec = RecView(op=op, flags=flags, pc=pc, addr0=addr0, addr1=addr1,
                      aux0=aux0, aux1=aux1)
        mem_out = memory_engine_step(
            params.mem, state.mem, rec, core.clock_ps, core.freq_mhz,
            active, enabled)
        mem_state = mem_out.ms
        mem_ok = mem_out.mem_complete
        mem_acc_ps = mem_out.acc_ps
        mem_progress = mem_out.progress
    else:
        mem_state = state.mem
        mem_ok = jnp.ones((T,), jnp.bool_)
        mem_acc_ps = jnp.zeros((T,), I64)
        mem_progress = jnp.zeros((), jnp.int32)

    # --- classify -------------------------------------------------------
    is_branch = op == Op.BRANCH
    is_static = (op < Op.DYNAMIC_MISC) & ~is_branch      # 0-14 minus branch
    is_dynamic = (op >= Op.DYNAMIC_MISC) & (op < 20)     # 15-19
    is_spawn_instr = op == Op.SPAWN
    is_send = op == Op.SEND
    is_recv = op == Op.NET_RECV
    is_binit = op == Op.BARRIER_INIT
    is_bwait = op == Op.BARRIER_WAIT
    is_minit = op == Op.MUTEX_INIT
    is_mlock = op == Op.MUTEX_LOCK
    is_munlock = op == Op.MUTEX_UNLOCK
    is_join = op == Op.THREAD_JOIN
    # Events that always complete in one iteration:
    is_simple_event = (
        (op == Op.THREAD_SPAWN)
        | is_binit | is_minit | is_munlock
        | (op == Op.ENABLE_MODELS) | (op == Op.DISABLE_MODELS)
        | (op == Op.DVFS_SET) | (op == Op.DVFS_GET)
        | (op == Op.COND_INIT)  # cond signal/broadcast/wait handled in sync engine
        | (op == Op.COND_SIGNAL) | (op == Op.COND_BROADCAST)
    )

    # --- static + dynamic instruction costs ------------------------------
    cost_table = jnp.asarray(params.static_cost_cycles, dtype=I64)
    static_cycles = cost_table[jnp.clip(op, 0, 19)]

    bp_index = (pc % params.bp_size).astype(jnp.int32)
    bp_pred = jnp.take_along_axis(core.bp_bits, bp_index[:, None], axis=1)[:, 0]
    taken = ((flags & FLAG_BRANCH_TAKEN) != 0).astype(jnp.uint8)
    bp_correct_now = bp_pred == taken
    if params.bp_enabled:
        branch_cycles = jnp.where(bp_correct_now, 1, params.bp_mispredict_penalty)
    else:
        branch_cycles = jnp.ones((T,), I64)

    cycles = jnp.where(is_branch, branch_cycles, static_cycles)
    cost_ps = cycles_to_ps(cycles, core.freq_mhz.astype(I64))
    cost_ps = jnp.where(is_dynamic, dyn_ps, cost_ps)
    cost_ps = jnp.where(op < 20, cost_ps, 0)  # events carry no direct cost
    cost_ps = jnp.where(enabled, cost_ps, 0)

    # --- SEND: push into (dst, src) mailbox ring -------------------------
    dst = jnp.clip(aux0, 0, T - 1)
    send_now = active & is_send
    if params.user_hbh is not None:
        from graphite_tpu.models.network_hop_by_hop import route_hop_by_hop
        from graphite_tpu.models.network_user import user_packet_bits

        noc_user, arrival_ps, _, _ = route_hop_by_hop(
            params.user_hbh, state.noc_user, tiles, dst,
            user_packet_bits(aux1), core.clock_ps, send_now, enabled)
        lat_ps = arrival_ps - core.clock_ps
    else:
        noc_user = state.noc_user
        lat_ps = route_latency_ps(params.net, tiles, dst, aux1, enabled)
        arrival_ps = core.clock_ps + lat_ps
    slot = (net.head[dst, tiles] % D).astype(jnp.int32)
    # Write under mask: redirect masked-off lanes to their own (t, t) cell
    # at a dummy slot; since each lane writes a distinct src column, no
    # collisions occur either way.
    w_dst = jnp.where(send_now, dst, tiles)
    time_ps_new = net.time_ps.at[w_dst, tiles, slot].set(
        jnp.where(send_now, arrival_ps, net.time_ps[w_dst, tiles, slot])
    )
    lat_arr_new = net.lat_ps.at[w_dst, tiles, slot].set(
        jnp.where(send_now, lat_ps.astype(jnp.int32),
                  net.lat_ps[w_dst, tiles, slot])
    )
    head_new = net.head.at[w_dst, tiles].add(jnp.where(send_now, 1, 0))

    # --- RECV: match earliest in-flight packet ---------------------------
    tail = ((net.head - net.count) % D).astype(jnp.int32)  # [T, T]
    tail_times = jnp.take_along_axis(net.time_ps, tail[:, :, None], axis=2)[:, :, 0]
    tail_lats = jnp.take_along_axis(net.lat_ps, tail[:, :, None], axis=2)[:, :, 0]
    avail = net.count > 0
    masked_times = jnp.where(avail, tail_times, FAR_FUTURE_PS)
    any_src = jnp.argmin(masked_times, axis=1).astype(jnp.int32)     # [T]
    want_src = jnp.where(aux0 == ANY_SENDER, any_src, jnp.clip(aux0, 0, T - 1))
    recv_time = masked_times[tiles, want_src]
    recv_lat = tail_lats[tiles, want_src]
    matched = recv_time < FAR_FUTURE_PS
    recv_now = active & is_recv & matched
    recv_wait_ps = jnp.maximum(recv_time - core.clock_ps, 0)
    # pop (count -1); sends above add +1 — combine as two scatter-adds
    count_new = (
        net.count.at[w_dst, tiles].add(jnp.where(send_now, 1, 0))
        .at[tiles, want_src].add(jnp.where(recv_now, -1, 0))
    )
    overflow = net.overflow | jnp.any(count_new > D)

    # --- BARRIER ---------------------------------------------------------
    # Masked scatter-updates below use the add-a-delta idiom: masked-off
    # lanes contribute +0, so duplicate dummy indices cannot clobber a live
    # update (a plain masked .set would).
    bar = jnp.clip(aux0, 0, sync.barrier_count.shape[0] - 1)
    binit_now = active & is_binit
    barrier_count = sync.barrier_count.at[bar].add(
        jnp.where(binit_now, aux1 - sync.barrier_count[bar], 0)
    )
    new_arrival = active & is_bwait & ~sync.barrier_waiting
    arr_tgt = jnp.where(new_arrival, bar, 0)
    barrier_arrived = sync.barrier_arrived.at[arr_tgt].add(
        jnp.where(new_arrival, 1, 0)
    )
    barrier_time = sync.barrier_time_ps.at[arr_tgt].max(
        jnp.where(new_arrival, core.clock_ps, 0)
    )
    release_bar = (barrier_count > 0) & (barrier_arrived >= barrier_count)
    participant = is_bwait & (sync.barrier_waiting | new_arrival) & ~done
    released = participant & release_bar[bar]
    release_time = barrier_time[bar]
    barrier_waiting = (sync.barrier_waiting | new_arrival) & ~released
    # reset released barriers
    barrier_arrived = jnp.where(release_bar, 0, barrier_arrived)
    barrier_time = jnp.where(release_bar, 0, barrier_time)
    barrier_wait_ps = jnp.maximum(release_time - core.clock_ps, 0)

    # --- MUTEX -----------------------------------------------------------
    NM = sync.mutex_locked.shape[0]
    mux = jnp.clip(aux0, 0, NM - 1)
    minit_now = active & is_minit
    mutex_locked = sync.mutex_locked.at[mux].add(
        jnp.where(minit_now, -sync.mutex_locked[mux], 0)
    )
    # candidates: tiles at MUTEX_LOCK (waiting from before, or arriving now)
    lock_candidate = is_mlock & ~done & (sync.mutex_waiting | active)
    cand_mux = jnp.where(lock_candidate, mux, NM)  # NM = "no mutex" bucket
    grant_key = core.clock_ps * jnp.asarray(T, I64) + tiles.astype(I64)
    masked_key = jnp.where(lock_candidate, grant_key, jnp.asarray(2**62, I64))
    best_key = (
        jnp.full((NM + 1,), 2**62, I64).at[cand_mux].min(masked_key)
    )[:NM]
    grantable = mutex_locked == 0
    granted = lock_candidate & grantable[mux] & (masked_key == best_key[mux])
    mutex_grab_time = sync.mutex_time_ps[mux]
    mutex_wait_ps = jnp.maximum(mutex_grab_time - core.clock_ps, 0)
    mutex_wait_ps = jnp.where(granted, mutex_wait_ps, 0)
    # grant is unique per mutex (key includes tile id), unlock unique per
    # mutex (single owner), so add-deltas below cannot double-apply
    mutex_locked = mutex_locked.at[mux].add(jnp.where(granted, 1, 0))
    mutex_owner = sync.mutex_owner.at[mux].add(
        jnp.where(granted, tiles - sync.mutex_owner[mux], 0)
    )
    mutex_waiting = (lock_candidate & ~granted) | (
        sync.mutex_waiting & ~is_mlock
    )
    # unlock: free + stamp handoff time (`sync_server.cc:211-240`)
    unlock_now = active & is_munlock
    mutex_locked = mutex_locked.at[mux].add(jnp.where(unlock_now, -1, 0))
    mutex_owner = mutex_owner.at[mux].add(
        jnp.where(unlock_now, -1 - mutex_owner[mux], 0)
    )
    mutex_time = sync.mutex_time_ps.at[mux].add(
        jnp.where(unlock_now, core.clock_ps - sync.mutex_time_ps[mux], 0)
    )

    # --- JOIN ------------------------------------------------------------
    join_target = jnp.clip(aux0, 0, T - 1)
    target_idx = jnp.minimum(core.idx[join_target], trace.length - 1)
    target_done = state.done[join_target] | (
        trace.op[join_target, target_idx] == Op.THREAD_EXIT
    )
    join_now = active & is_join & target_done
    join_time = jnp.maximum(core.clock_ps, core.clock_ps[join_target])

    # --- commit: advance mask, clocks, counters --------------------------
    # Instruction records with memory operands commit only once all their
    # memory slots completed (`simple_core_model.cc:53-90`: the per-operand
    # latencies and the execution cost land on the clock together).
    instr_like = is_static | is_branch
    advance = active & (
        (instr_like & mem_ok) | (is_dynamic & ~is_spawn_instr)
        | is_simple_event | is_send
    )
    advance = advance | recv_now | released | (active & is_spawn_instr)
    advance = advance | granted | join_now

    clock = core.clock_ps
    clock = jnp.where(advance & (instr_like
                                 | (is_dynamic & ~is_spawn_instr)
                                 | is_simple_event | is_send),
                      clock + cost_ps
                      + jnp.where(instr_like, mem_acc_ps, 0),
                      clock)
    clock = jnp.where(active & is_spawn_instr,
                      jnp.maximum(clock, dyn_ps), clock)
    clock = jnp.where(recv_now, jnp.maximum(clock, recv_time), clock)
    clock = jnp.where(released, jnp.maximum(clock, release_time), clock)
    clock = jnp.where(granted, clock + mutex_wait_ps, clock)
    clock = jnp.where(join_now, join_time, clock)

    # DVFS_SET on the CORE domain (domain 0) retunes this tile's clock;
    # the full DVFSManager (voltage levels, remote get/set over the DVFS
    # network, `dvfs_manager.h:19-88`) is layered on in models/dvfs.
    dvfs_set_now = active & (op == Op.DVFS_SET) & (aux0 == 0) & (aux1 > 0)
    freq_mhz = jnp.where(dvfs_set_now, aux1, core.freq_mhz)

    instr_now = advance & (is_static | is_branch
                           | (is_dynamic & ~is_spawn_instr))
    recv_charged = recv_now & (recv_wait_ps > 0) & enabled
    sync_charged = (released & (barrier_wait_ps > 0) | granted
                    & (mutex_wait_ps > 0)) & enabled

    new_core = core.replace(
        clock_ps=clock,
        freq_mhz=freq_mhz,
        idx=core.idx + advance.astype(jnp.int32),
        instruction_count=core.instruction_count
        + (instr_now & enabled).astype(I64)
        + recv_charged.astype(I64)
        + sync_charged.astype(I64),
        memory_stall_ps=core.memory_stall_ps
        + jnp.where(advance & instr_like, mem_acc_ps, 0),
        execution_stall_ps=core.execution_stall_ps
        + jnp.where(advance & (is_static | is_branch), cost_ps, 0),
        recv_instructions=core.recv_instructions + recv_charged.astype(I64),
        recv_stall_ps=core.recv_stall_ps
        + jnp.where(recv_charged, recv_wait_ps, 0),
        sync_instructions=core.sync_instructions + sync_charged.astype(I64),
        sync_stall_ps=core.sync_stall_ps
        + jnp.where(released & enabled, barrier_wait_ps, 0)
        + jnp.where(granted & enabled, mutex_wait_ps, 0),
        bp_bits=core.bp_bits.at[tiles, bp_index].set(
            jnp.where(active & is_branch & enabled, taken,
                      core.bp_bits[tiles, bp_index])
        ),
        bp_correct=core.bp_correct
        + (active & is_branch & bp_correct_now & enabled).astype(I64),
        bp_incorrect=core.bp_incorrect
        + (active & is_branch & ~bp_correct_now & enabled).astype(I64),
    )
    new_net = net.replace(
        time_ps=time_ps_new,
        lat_ps=lat_arr_new,
        head=head_new,
        count=count_new,
        overflow=overflow,
        packets_sent=net.packets_sent + send_now.astype(I64),
        packets_received=net.packets_received + recv_now.astype(I64),
        total_latency_ps=net.total_latency_ps
        + jnp.where(recv_now, recv_lat.astype(I64), 0),
    )
    new_sync = sync.replace(
        barrier_count=barrier_count,
        barrier_arrived=barrier_arrived,
        barrier_time_ps=barrier_time,
        barrier_waiting=barrier_waiting,
        mutex_locked=mutex_locked,
        mutex_owner=mutex_owner,
        mutex_time_ps=mutex_time,
        mutex_waiting=mutex_waiting,
    )
    enable_now = jnp.any(active & (op == Op.ENABLE_MODELS))
    disable_now = jnp.any(active & (op == Op.DISABLE_MODELS))
    models_enabled = jnp.where(
        disable_now, False, jnp.where(enable_now, True, state.models_enabled)
    )
    if params.mem is not None:
        # reset the per-record slot machinery on commit
        mem_state = mem_state.replace(req=mem_state.req.replace(
            slot=jnp.where(advance, 0, mem_state.req.slot),
            acc_ps=jnp.where(advance, 0, mem_state.req.acc_ps),
        ))
    new_state = SimState(
        core=new_core,
        net=new_net,
        sync=new_sync,
        models_enabled=models_enabled,
        done=done,
        mem=mem_state,
        noc_user=noc_user,
    )
    return new_state, jnp.sum(advance, dtype=jnp.int32) + mem_progress


def run_quantum(
    params: EngineParams, trace: DeviceTrace, state: SimState, qend: jax.Array
) -> SimState:
    """Run one lax-barrier quantum as a single compiled XLA region.

    Runs blocks of `inner_block` subquantum iterations under a while_loop
    until no tile makes progress (all done, all past the quantum boundary,
    or — transiently — all blocked on messages that can only arrive next
    quantum).  This is the quantum of `clock_skew_management/lax_barrier`
    (`carbon_sim.cfg:92-97`).  Deliberately NOT a module-level
    `jit(static_argnums=0)`: jitting here with dataclass static args hits a
    jax-0.9 dispatch bug (constant-buffer miscount after topology changes);
    callers jit a closure instead (`make_quantum_step`).
    """

    def block(state: SimState):
        def body(carry, _):
            st, prog = carry
            st, adv = subquantum_iteration(params, trace, st, qend)
            return (st, prog + adv), None

        (state, progress), _ = lax.scan(
            body, (state, jnp.asarray(0, jnp.int32)), None,
            length=params.inner_block,
        )
        return state, progress

    def cond(carry):
        _, prog = carry
        return prog > 0

    def body(carry):
        st, _ = carry
        return block(st)

    state, _ = lax.while_loop(cond, body, (state, jnp.asarray(1, jnp.int32)))
    return state


def make_quantum_step(params: EngineParams, trace: DeviceTrace):
    """Bind params/trace into a per-instance jitted step for the host loop."""

    @jax.jit
    def step(state: SimState, qend: jax.Array) -> SimState:
        return run_quantum(params, trace, state, qend)

    return step
