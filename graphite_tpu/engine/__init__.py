"""The quantum-stepped simulation engine.

Graphite runs 2 host threads per tile (app + sim) synchronized by
locks/semaphores and TCP transport (`common/system/sim_thread.cc`,
`common/transport/socktransport.cc`), with lax clock-skew schemes bounding
drift (`common/system/clock_skew_management_schemes/`).  This engine inverts
that: all tile state is a struct-of-arrays pytree, and one compiled XLA step
advances every tile through one lax-barrier quantum (`carbon_sim.cfg:92-97`)
as a masked vectorized state machine.  Blocking operations (netRecv, barrier
waits — reference `network.cc:358-460`, `sync_server.cc`) become explicit
retry states resolved by messages delivered between subquantum rounds.
"""

from graphite_tpu.engine.simulator import Simulator, SimResults

__all__ = ["Simulator", "SimResults"]
