"""Host-side simulation orchestration (the Simulator/MCP analog).

Reference: `common/system/simulator.{h,cc}` boots transport, managers, and
per-tile threads (`simulator.cc:83-133`); the MCP thread serves centralized
requests (`mcp.cc:59-146`); the lax-barrier loop synchronizes every quantum
(`lax_barrier_sync_client.cc:31-68`).  Here the Simulator builds the engine
parameters from the parsed config, owns the device state, and drives the
compiled quantum step in a host loop; everything the MCP did between quanta
(deadlock detection, stats sampling, shutdown) happens here.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from graphite_tpu.config.config_file import ConfigFile
from graphite_tpu.config.simconfig import SimConfig
from graphite_tpu.engine.state import DeviceTrace, SimState, init_state
from graphite_tpu.engine.step import EngineParams
from graphite_tpu.models.dvfs import module_freq_mhz
from graphite_tpu.models.network_user import UserNetworkParams
from graphite_tpu.time_types import cycles_to_ps, ns_to_ps, ps_to_ns
from graphite_tpu.trace.schema import STATIC_COST_KEYS, Op, TraceBatch

class DeadlockError(RuntimeError):
    pass


class MailboxOverflowError(RuntimeError):
    pass


@dataclasses.dataclass
class SimResults:
    """Final counters, mirroring the `sim.out` summary structure
    (`core_model.cc:90-115`, `tile.cc:105-123`)."""

    n_tiles: int
    completion_time_ps: int
    instruction_count: np.ndarray
    clock_ps: np.ndarray
    memory_stall_ps: np.ndarray
    execution_stall_ps: np.ndarray
    recv_instructions: np.ndarray
    recv_stall_ps: np.ndarray
    sync_instructions: np.ndarray
    sync_stall_ps: np.ndarray
    bp_correct: np.ndarray
    bp_incorrect: np.ndarray
    packets_sent: np.ndarray
    packets_received: np.ndarray
    total_packet_latency_ps: np.ndarray
    n_quanta: int
    # memory-subsystem counters (per-tile arrays), None when no memory model
    mem_counters: "dict | None" = None
    func_errors: int = 0
    # iocoom detailed stall breakdown (`iocoom_core_model.cc:64-77`),
    # None for the simple core model
    detailed_stalls: "dict | None" = None
    # device-recorded telemetry timeline (obs.Timeline) when the run was
    # built with a TelemetrySpec, else None.  Pure observability: a
    # telemetry-enabled run's other fields are bit-equal to its
    # telemetry=None twin (pinned in tests/test_telemetry.py)
    telemetry: "object | None" = None
    # device-recorded per-tile profile (obs.TileProfile) when the run
    # was built with a ProfileSpec, else None.  Same pure-observability
    # contract as telemetry (pinned in tests/test_profile.py)
    profile: "object | None" = None
    # device-recorded latency histograms (obs.Hist) when the run was
    # built with a HistSpec, else None.  Same pure-observability
    # contract (pinned in tests/test_hist.py)
    hist: "object | None" = None

    @property
    def total_instructions(self) -> int:
        return int(self.instruction_count.sum())

    def summary(self) -> str:
        """sim.out-style per-tile summary (`simulator.cc:152-170`)."""
        out = []
        out.append("Simulation Summary")
        out.append(f"Target Completion Time (in nanoseconds): "
                   f"{ps_to_ns(self.completion_time_ps)}")
        out.append(f"Total Instructions: {self.total_instructions}")
        for t in range(self.n_tiles):
            out.append(f"Tile {t} Summary:")
            out.append("  Core Summary:")
            out.append(f"    Total Instructions: {int(self.instruction_count[t])}")
            out.append("    Completion Time (in nanoseconds): "
                       f"{ps_to_ns(int(self.clock_ps[t]))}")
            out.append(f"    Synchronization Stalls: {int(self.sync_instructions[t])}")
            out.append(f"    Network Recv Stalls: {int(self.recv_instructions[t])}")
            out.append("    Stall Time Breakdown (in nanoseconds): ")
            out.append(f"      Memory: {ps_to_ns(int(self.memory_stall_ps[t]))}")
            out.append("      Execution Unit: "
                       f"{ps_to_ns(int(self.execution_stall_ps[t]))}")
            out.append("      Synchronization: "
                       f"{ps_to_ns(int(self.sync_stall_ps[t]))}")
            out.append("      Network Recv: "
                       f"{ps_to_ns(int(self.recv_stall_ps[t]))}")
            if self.detailed_stalls is not None:
                # `iocoom_core_model.cc:64-77` outputSummary
                ds = self.detailed_stalls
                out.append("    Detailed Stall Time Breakdown "
                           "(in nanoseconds): ")
                out.append(f"      Load Queue: "
                           f"{ps_to_ns(int(ds['load_queue'][t]))}")
                out.append(f"      Store Queue: "
                           f"{ps_to_ns(int(ds['store_queue'][t]))}")
                out.append(f"      L1-I Cache: "
                           f"{ps_to_ns(int(ds['l1icache'][t]))}")
                out.append(
                    "      L1-D Cache (Intra-Instruction): "
                    f"{ps_to_ns(int(ds['intra_ins_l1dcache'][t]))}")
                out.append(
                    "      L1-D Cache (Inter-Instruction): "
                    f"{ps_to_ns(int(ds['inter_ins_l1dcache'][t]))}")
                out.append(
                    "      Execution Unit (Intra-Instruction): "
                    f"{ps_to_ns(int(ds['intra_ins_execution_unit'][t]))}")
                out.append(
                    "      Execution Unit (Inter-Instruction): "
                    f"{ps_to_ns(int(ds['inter_ins_execution_unit'][t]))}")
            bp_total = int(self.bp_correct[t] + self.bp_incorrect[t])
            if bp_total:
                out.append("    Branch Predictor:")
                out.append(f"      Num Correct: {int(self.bp_correct[t])}")
                out.append(f"      Num Incorrect: {int(self.bp_incorrect[t])}")
            if self.mem_counters is not None:
                mc = self.mem_counters
                out.append("  Cache Summary:")
                out.append(f"    L1-I Misses: {int(mc['l1i_misses'][t])}")
                out.append(
                    "    L1-D Misses: "
                    f"{int(mc['l1d_read_misses'][t] + mc['l1d_write_misses'][t])}")
                out.append(f"    L2 Misses: {int(mc['l2_misses'][t])}")
                # miss-type breakdown (`cache.cc outputSummary`, populated
                # under `[l2_cache/<type>] track_miss_types`)
                if int(mc["l2_cold_misses"][t] + mc["l2_capacity_misses"][t]
                       + mc["l2_sharing_misses"][t]):
                    out.append(
                        f"      Cold Misses: {int(mc['l2_cold_misses'][t])}")
                    out.append("      Capacity Misses: "
                               f"{int(mc['l2_capacity_misses'][t])}")
                    out.append("      Sharing Misses: "
                               f"{int(mc['l2_sharing_misses'][t])}")
                # cache-line utilization (cache_line_utilization.h; under
                # `[l2_cache/<type>] track_cache_line_utilization`)
                if ("line_util_hist" in mc
                        and int(np.asarray(mc["line_util_hist"][t]).sum())):
                    hist = np.asarray(mc["line_util_hist"][t])
                    out.append("    Cache Line Utilization (L2):")
                    out.append("      Total Reads: "
                               f"{int(mc['line_util_reads'][t])}")
                    out.append("      Total Writes: "
                               f"{int(mc['line_util_writes'][t])}")
                    labels = ("0", "1", "2-3", "4-7", "8-15", "16-31",
                              "32-63", ">=64")
                    for lb, n in zip(labels, hist):
                        out.append(f"      Accesses {lb}: {int(n)}")
            out.append("  Network Summary (USER):")
            out.append(f"    Packets Sent: {int(self.packets_sent[t])}")
            out.append(f"    Packets Received: {int(self.packets_received[t])}")
            if self.packets_received[t]:
                avg = self.total_packet_latency_ps[t] / self.packets_received[t] / 1000
                out.append(f"    Average Packet Latency (in nanoseconds): {avg:.3f}")
        return "\n".join(out)


def _mem_state_bytes(mp) -> int:
    """Rough HBM footprint of the protocol state: directory (dominant),
    cache meta words, and the [T, T] mailbox matrices."""
    T = mp.n_tiles
    dir_entry = mp.sharer_words * 4 + 8  # sharers words + packed word
    dir_bytes = T * mp.dir_sets * mp.dir_ways * dir_entry
    cache_bytes = 8 * T * (
        mp.l1i.num_sets * mp.l1i.num_ways
        + mp.l1d.num_sets * mp.l1d.num_ways
        + 2 * mp.l2.num_sets * mp.l2.num_ways)
    mail_bytes = 4 * T * T * 13
    return dir_bytes + cache_bytes + mail_bytes


def auto_mailbox_depth(batch: "TraceBatch") -> int:
    """Upper-bound the per-(dst, src) mailbox ring occupancy from the
    recorded trace, so no caller has to guess `mailbox_depth` (VERDICT
    round-3 ask: overflow unreachable for recorded traces).

    The bound is barrier-phase aware: records are bucketed by the count
    of completed blocking barrier waits before them on their lane (the
    only cross-lane ordering a trace guarantees).  In any execution,
    messages in flight for a pair during epoch e cannot exceed the
    pair's sends through epoch e minus its receives completed in epochs
    strictly before e (later sends have not happened; earlier receives
    have).  ANY_SENDER receives cannot be credited to a pair, but they
    do bound the total into their destination, so each pair also takes
    the destination-wide bound.  Epochs only order lanes when every
    lane passes the same sequence of GLOBAL barriers, so barrier credit
    applies only when one barrier id is waited on, its declared
    participant count covers all tiles, and every lane waits equally
    often; anything else (including no barriers) collapses to one epoch
    — the exact worst case, every send of the pair outstanding at once.
    The engine's fail-stop `MailboxOverflowError` remains the backstop.
    """
    from graphite_tpu.trace.schema import Op

    op = np.asarray(batch.op)
    aux0 = np.asarray(batch.aux0)
    aux1 = np.asarray(batch.aux1)
    T, L = op.shape
    send_mask = op == int(Op.SEND)
    if L == 0 or not send_mask.any():
        return 2
    recv_mask = op == int(Op.NET_RECV)

    is_bar = (op == int(Op.BARRIER_WAIT)) | (op == int(Op.BARRIER_SYNC))
    bar_global = False
    if is_bar.any():
        bar_ids = np.unique(aux0[is_bar])
        per_lane = is_bar.sum(axis=1)
        init_mask = op == int(Op.BARRIER_INIT)
        counts = np.unique(aux1[init_mask & np.isin(aux0, bar_ids)])
        bar_global = (
            len(bar_ids) == 1
            and (per_lane == per_lane[0]).all() and per_lane[0] > 0
            and len(counts) > 0 and (counts >= T).all())
    if bar_global:
        epoch = np.cumsum(is_bar, axis=1) - is_bar   # exclusive prefix
        E = int(epoch.max()) + 1
    else:
        epoch = np.zeros((T, L), np.int64)
        E = 1
    lanes = np.broadcast_to(np.arange(T)[:, None], (T, L))

    s_src = lanes[send_mask]
    s_dst = np.clip(aux0[send_mask], 0, T - 1)
    s_e = epoch[send_mask]
    r_dst = lanes[recv_mask]
    r_src = aux0[recv_mask]                          # -1 = ANY_SENDER
    r_e = epoch[recv_mask]

    # per-destination bound (all sources vs all receives at d)
    dst_sends = np.zeros((T, E), np.int64)
    np.add.at(dst_sends, (s_dst, s_e), 1)
    dst_recvs = np.zeros((T, E), np.int64)
    np.add.at(dst_recvs, (r_dst, r_e), 1)
    dst_s_cum = np.cumsum(dst_sends, axis=1)
    dst_r_cum_prev = np.concatenate(
        [np.zeros((T, 1), np.int64), np.cumsum(dst_recvs, axis=1)[:, :-1]],
        axis=1)
    dst_bound = (dst_s_cum - dst_r_cum_prev).max(axis=1)   # [T]

    # per-pair bound over the pairs that actually send
    pair_ids = s_src.astype(np.int64) * T + s_dst
    pairs, pair_idx = np.unique(pair_ids, return_inverse=True)
    P = len(pairs)
    pair_sends = np.zeros((P, E), np.int64)
    np.add.at(pair_sends, (pair_idx, s_e), 1)
    pair_recvs = np.zeros((P, E), np.int64)
    specific = r_src >= 0
    rp_ids = r_src[specific].astype(np.int64) * T + r_dst[specific]
    rp_pos = np.searchsorted(pairs, rp_ids)
    in_range = rp_pos < P
    rp_match = np.zeros_like(rp_ids, bool)
    rp_match[in_range] = pairs[rp_pos[in_range]] == rp_ids[in_range]
    np.add.at(pair_recvs, (rp_pos[rp_match], r_e[specific][rp_match]), 1)
    pair_s_cum = np.cumsum(pair_sends, axis=1)
    pair_r_cum_prev = np.concatenate(
        [np.zeros((P, 1), np.int64), np.cumsum(pair_recvs, axis=1)[:, :-1]],
        axis=1)
    pair_bound = (pair_s_cum - pair_r_cum_prev).max(axis=1)
    bound = np.minimum(pair_bound, dst_bound[pairs % T]).max()
    # Unphased send streams (no barriers between rounds) degenerate to
    # the total-sends-per-pair worst case; a [T, T, total] ring would
    # dwarf the real occupancy (recv interlock keeps it small), so cap
    # the automatic size — the engine's overflow fail-stop still guards
    # the cap, and the explicit knob remains for genuinely deep traffic.
    return int(np.clip(bound, 2, 64))


def mem_phase_names(params: EngineParams) -> tuple:
    """The memory engine's protocol-phase names, in the skip-vector's
    order (one source of truth for skip-counter labeling — Simulator's
    last_phase_skips and the sweep runner's per-sim demux)."""
    if params.mem.protocol.startswith("pr_l1_sh_l2"):
        from graphite_tpu.memory.engine_shl2 import SHL2_PHASE_NAMES
        return SHL2_PHASE_NAMES
    from graphite_tpu.memory.engine import PHASE_NAMES
    return PHASE_NAMES


# run_streamed's default [T, W] window length — also the window bound
# residency_breakdown prices for a streaming sim, so the two stay one
# number.
STREAM_WINDOW_RECORDS = 4096

_STREAM_RUNNERS: dict = {}
# Each cached wrapper pins a compiled executable (tens of MB of device
# program + host tracing caches); long-lived processes sweeping many
# configs would otherwise grow without bound.
_STREAM_RUNNERS_MAX = 8


def _streamed_runner(params: EngineParams, quantum_ps, max_quanta: int,
                     mesh=None, spmd=None, state_ex=None, window_ex=None):
    """One jitted streamed-run wrapper per (params, quantum, max_quanta,
    mesh program): identical configs share a wrapper, so a warmup run on
    one Simulator instance warms the executable every other instance
    uses.  LRU-bounded at _STREAM_RUNNERS_MAX entries."""
    key = (params, quantum_ps, int(max_quanta), mesh, spmd)
    fn = _STREAM_RUNNERS.get(key)
    if fn is not None:
        # LRU refresh (dicts preserve insertion order)
        del _STREAM_RUNNERS[key]
        _STREAM_RUNNERS[key] = fn
    if fn is None:
        if spmd == "shard_map":
            from graphite_tpu.parallel.mesh import make_shard_map_runner

            fn = make_shard_map_runner(
                params, quantum_ps, max_quanta, mesh, state_ex, window_ex,
                streamed=True)
        else:
            from graphite_tpu.engine.step import run_simulation

            fn = jax.jit(
                lambda st, tr, base: run_simulation(
                    params, tr, st, quantum_ps, max_quanta, trace_base=base))
        while len(_STREAM_RUNNERS) >= _STREAM_RUNNERS_MAX:
            _STREAM_RUNNERS.pop(next(iter(_STREAM_RUNNERS)))
        _STREAM_RUNNERS[key] = fn
    return fn


class Simulator:
    """Builds engine parameters from a SimConfig and runs a trace batch."""

    def __init__(
        self,
        config: SimConfig | ConfigFile | str,
        trace: TraceBatch,
        *,
        mailbox_depth: int | None = None,
        inner_block: int = 32,
        bp_size: int | None = None,
        n_barriers: int = 64,
        n_mutexes: int = 64,
        n_conds: int = 64,
        mesh=None,
        stream: bool = False,
        spmd: str | None = None,
        donate: bool = False,
        dir_stage: bool | None = None,
        barrier_host: bool | None = None,
        phase_gate: bool | None = None,
        mem_gate_bytes: int | None = None,
        barrier_batch: int | None = None,
        telemetry=None,
        profile=None,
        base_consolidate: bool | None = None,
        dvfs=None,
        hist=None,
    ):
        """`dir_stage`: force the directory write-staging path on/off
        (None = auto: on for single-device private-L2 runs whose sharers
        store is >= 64 MB — the regime where XLA's dense scatter lowering
        dominates; see MemParams.dir_stage_cap).

        `spmd` (mesh runs only): "shard_map" — the packed-exchange
        multi-chip program (parallel/px.py; the default for every
        protocol) — or "gspmd" — whole-program partitioning via
        sharding specs (the legacy path).

        `phase_gate`: per-phase activity gating of the memory engines —
        each protocol phase under its own scalar-predicate lax.cond
        carrying only small per-phase state, so quiet phases cost ~zero
        at EVERY scale including the >= 1 GB directories where the
        whole-engine mem_gate must stay off (MemParams.phase_gate).
        None = on whenever the memory subsystem is built; False is the
        escape hatch back to the straight-line engine.  Config key:
        `[general] phase_gate`.

        `mem_gate_bytes`: the whole-engine mem_gate's state-size ceiling
        (the gate's lax.cond double-buffers the carried memory state, so
        it auto-disables above this; formerly a hard-coded 1 << 30).
        Config key: `[general] mem_gate_bytes`.

        `barrier_batch`: quanta per host dispatch under `barrier_host`
        (a bounded device-side while_loop that early-exits on
        host-visible work — done/overflow/deadlock — amortizing the
        ~100 ms tunnel dispatch ~K x; `engine/step.barrier_host_batch`).
        1 restores the per-quantum dispatch.  Config key:
        `[general] barrier_batch` (default 8).

        `telemetry`: an `obs.TelemetrySpec` to record a device-resident
        metric timeline inside the compiled loop (sampled on
        `sample_interval_ps` simulated-time boundaries, zero host sync;
        read back post-run via `Simulator.telemetry` /
        `SimResults.telemetry`).  None — the default — lowers a
        bit-identical program (the knobs=None contract).

        `profile`: an `obs.ProfileSpec` to record the device-resident
        PER-TILE profile ring ([S, T, m], sampled on the same
        simulated-time boundaries as telemetry; read back via
        `Simulator.profile` / `SimResults.profile`).  Same None
        bit-identity contract, enforced by the `profile-off` lint.

        `dvfs`: a `dvfs.DvfsSpec` attaching the runtime DVFS manager —
        the chip-global per-domain operating point rides the carry
        (`SimState.dvfs_rt`), in-trace DVFS_SET events and the optional
        governor retune it, and the memory/network timing conversions
        read the carried frequencies.  Same None bit-identity contract,
        enforced by the `dvfs-off` lint.

        `donate=True` gives the input state's device buffers to XLA each
        run (halves big-state HBM residency — required for the 1024-tile
        full-directory coherence runs, PERF.md); the pre-run state object
        becomes unusable, so warmup()/state-restoring repeat patterns
        must keep the default."""
        if isinstance(config, str):
            config = ConfigFile.from_file(config)
        if isinstance(config, ConfigFile):
            config = SimConfig(config)
        self.config = config
        cfg = config.cfg
        self.trace_batch = trace
        n_tiles = trace.n_tiles
        if n_tiles != config.application_tiles:
            raise ValueError(
                f"trace has {n_tiles} tiles but config expects "
                f"{config.application_tiles} application tiles"
            )
        if mailbox_depth is None:
            # size the [T, T, D] rings from the trace itself (barrier-
            # phase-aware in-flight bound); overflow stays a fail-stop
            mailbox_depth = auto_mailbox_depth(trace)
        costs = tuple(
            cfg.get_int(f"core/static_instruction_costs/{k}", 0)
            for k in STATIC_COST_KEYS
        )
        bp_type = cfg.get_string("branch_predictor/type", "one_bit")

        # Memory subsystem: built when shared memory is enabled AND the
        # trace actually touches memory (`general/enable_shared_mem`,
        # `carbon_sim.cfg:40-44`; protocol factory `memory_manager.cc:31-48`).
        from graphite_tpu.trace.schema import FLAG_MEM0_VALID, FLAG_MEM1_VALID

        has_mem = bool(
            np.any(trace.flags & (FLAG_MEM0_VALID | FLAG_MEM1_VALID))
        ) or cfg.get_bool("general/enable_icache_modeling", False)
        # dynamic records (op 15-19) commit without waiting on memory
        # completion, so memory flags on them would leave slot machinery
        # dangling into the next record (and diverge from the golden
        # oracle, which gives dynamic ops no memory slots) — reject the
        # combination outright; no builder emits it
        dyn_mem = np.any(
            (trace.op >= 15) & (trace.op < 20)
            & ((trace.flags & (FLAG_MEM0_VALID | FLAG_MEM1_VALID)) != 0))
        if bool(dyn_mem):
            raise ValueError(
                "dynamic trace records (ops 15-19) must not carry "
                "FLAG_MEM*_VALID memory operands")
        if dir_stage and not (config.enable_shared_mem and has_mem):
            raise ValueError(
                "dir_stage=True needs the memory subsystem (shared mem "
                "enabled and a memory-carrying trace)")
        mem_params = None
        if config.enable_shared_mem and has_mem:
            from graphite_tpu.memory import MemParams

            mem_params = MemParams.from_config(config)
            supported = ("pr_l1_pr_l2_dram_directory_msi",
                         "pr_l1_pr_l2_dram_directory_mosi",
                         "pr_l1_sh_l2_msi", "pr_l1_sh_l2_mesi")
            if mem_params.protocol not in supported:
                raise NotImplementedError(
                    f"caching protocol {mem_params.protocol!r} pending "
                    f"(available: {', '.join(supported)})"
                )
            # Directory write-staging (MemParams.dir_stage_cap): lifts
            # the coherence-storm floor — XLA lowers per-lane scatters on
            # the big sharers store as full-array dense passes, so big
            # directories stage writes and flush once per inner block
            # (PERF.md round-5).  Private-L2 protocols only.  Auto-on
            # stays conservative: single-device programs whose sharers
            # store alone is >= 64 MB.  Meshed runs stage on EXPLICIT
            # dir_stage=True (round 12: the per-lane rows shard with the
            # directory, but only under the consolidated base — the
            # check below enforces that; auto-enabling under a mesh
            # would surprise base_consolidate=False configurations).
            private_l2 = mem_params.protocol.startswith("pr_l1_pr_l2")
            sharers_bytes = (4 * n_tiles * mem_params.dir_sets
                             * mem_params.dir_ways
                             * mem_params.sharer_words)
            if dir_stage is None:
                dir_stage = (private_l2 and mesh is None
                             and sharers_bytes >= 64 << 20)
            # Round-12 base consolidation (one packed directory gather +
            # one merged scatter per iteration; MemParams.base_consolidate).
            # None = config `[general] base_consolidate` (default on);
            # False restores the round-11 per-phase layout — the regress
            # equivalence oracle.
            if base_consolidate is not None:
                mem_params = dataclasses.replace(
                    mem_params, base_consolidate=bool(base_consolidate))
            if dir_stage:
                if not private_l2:
                    # Not "pending work": the shared-L2 engines don't
                    # NEED staging.  Their embedded directory (round-5
                    # packed words + set-row-major sharer rows) is
                    # written as ONE add-a-delta row scatter per phase,
                    # not the private engine's three per-lane
                    # entry-granular passes that staging amortizes — so
                    # there is no dense-scatter storm to lift.
                    raise ValueError(
                        "dir_stage applies to the private-L2 directory "
                        "protocols only: the shared-L2 engines' embedded "
                        "directory already writes one row-form scatter "
                        "per phase (no per-entry dense-pass storm to "
                        "stage away), so staging would add table scans "
                        "for nothing")
                if mesh is not None and not mem_params.base_consolidate:
                    # the per-lane staging rows shard with the directory
                    # (round 12), but only the consolidated working-set
                    # gather overlays them block-locally before the
                    # exchange — the legacy per-phase view never did
                    raise ValueError(
                        "dir_stage under a mesh needs the round-12 "
                        "consolidated base (base_consolidate=True): the "
                        "legacy per-phase directory view does not "
                        "overlay the staging rows before the shard_map "
                        "exchange.  Drop base_consolidate=False (the "
                        "consolidated default shards the per-home-lane "
                        "staging rows with the directory), or run the "
                        "sim as a campaign under SweepRunner's 2D "
                        "batch x tile layout (layout='tile'/'2d'), "
                        "which composes the consolidated exchange with "
                        "batching")
                wpi = (5 if mem_params.dir_type == "limited_no_broadcast"
                       else 3)
                # per-LANE capacity (round-12 layout): each home stages
                # at most writes_per_iter entries per iteration
                mem_params = dataclasses.replace(
                    mem_params,
                    dir_stage_cap=wpi * inner_block)
            # Per-phase activity gating (round 6): on by default for
            # every memory-engine program — the per-phase conds carry
            # only small state (see MemParams.phase_gate), so unlike the
            # whole-engine mem_gate there is no size ceiling; predicates
            # are replicated-deterministic, so sharded programs gate
            # identically on every device.
            if phase_gate is None:
                phase_gate = cfg.get_bool("general/phase_gate", True)
            if phase_gate:
                mem_params = dataclasses.replace(mem_params,
                                                 phase_gate=True)
        # Full hop-by-hop USER NoC with per-port contention
        user_hbh = None
        user_atac = None
        if config.network_types[0] == "emesh_hop_by_hop":
            from graphite_tpu.models.network_hop_by_hop import HopByHopParams

            user_hbh = HopByHopParams.from_config(config, "user")
        elif config.network_types[0] == "atac":
            from graphite_tpu.models.network_atac import AtacParams

            user_atac = AtacParams.from_config(config, "user")
        iocoom_params = None
        # Per-tile core models (`[tile] model_list` heterogeneity,
        # `config.cc:365-472`): iocoom tiles run the pipeline algebra, the
        # rest the simple 1-IPC path, mixed freely within one mesh
        core_types = [config.tile_spec(t).core_type for t in range(n_tiles)]
        unknown = {t for t in core_types
                   if t not in ("iocoom", "simple", "default", "magic")}
        if unknown:
            raise NotImplementedError(f"core model(s) {sorted(unknown)!r}")
        iocoom_tiles = None
        if "iocoom" in core_types:
            from graphite_tpu.models.iocoom import IocoomParams

            iocoom_params = IocoomParams.from_config(cfg)
            if any(t != "iocoom" for t in core_types):
                iocoom_tiles = tuple(t == "iocoom" for t in core_types)
        from graphite_tpu.models.dvfs import DvfsParams

        dvfs_params = DvfsParams.from_config(cfg)
        self.params = EngineParams(
            n_tiles=n_tiles,
            static_cost_cycles=costs,
            net=UserNetworkParams.from_config(config, "user"),
            bp_enabled=(bp_type != "none"),
            bp_size=bp_size or cfg.get_int("branch_predictor/size", 1024),
            bp_mispredict_penalty=cfg.get_int(
                "branch_predictor/mispredict_penalty", 14
            ),
            mailbox_depth=mailbox_depth,
            inner_block=inner_block,
            n_conds=n_conds,
            # SYSTEM network is always magic (`config.cc:484`) and outside
            # the DVFS domain map (only NETWORK_USER/NETWORK_MEMORY are
            # tunable modules): 1 cycle each way to the MCP at 1 GHz
            syscall_rt_ps=int(cycles_to_ps(2, 1000)),
            iocoom=iocoom_params,
            iocoom_tiles=iocoom_tiles,
            dvfs=dvfs_params,
            mem=mem_params,
            user_hbh=user_hbh,
            user_atac=user_atac,
            # the engine gate's lax.cond double-buffers the memory state in
            # HBM; keep it only while the duplicate comfortably fits (the
            # directory sharer maps grow as tiles^2 x dir entries).  Above
            # the (config-driven) ceiling the per-phase gating inside the
            # engine takes over — its conds carry only small state, so it
            # has no such ceiling (MemParams.phase_gate).
            mem_gate=(mem_params is None
                      or _mem_state_bytes(mem_params)
                      < self._resolve_mem_gate_bytes(cfg, mem_gate_bytes)),
            # runtime BBLOCK compression for per-instruction streams
            # (simple-core memoryless runs; bit-exact by construction —
            # engine/step.py plain-run batching)
            # 16 measured best on the 1024-tile per-instruction streamed
            # ring (8: 1.06M, 16: 1.76M, 32: 0.79M instr/s — PERF.md);
            # configs above the measured-safe ceiling are clamped + warned
            plain_unroll=self._resolve_plain_unroll(
                cfg, mem_params, iocoom_params),
        )
        # Clock-skew scheme (`carbon_sim.cfg:85-108`): lax_barrier uses the
        # config quantum; lax runs one unbounded quantum; lax_p2p runs
        # unbounded quanta with per-iteration random pairwise clamping
        # (`lax_p2p_sync_client.h:13-83`) applied inside the step.
        scheme = cfg.get_string("clock_skew_management/scheme", "lax_barrier")
        self.p2p_slack_ps = None
        if scheme == "lax_barrier":
            self.quantum_ps = ns_to_ps(
                cfg.get_int("clock_skew_management/lax_barrier/quantum", 1000)
            )
        elif scheme == "lax_p2p":
            self.quantum_ps = None
            self.p2p_slack_ps = ns_to_ps(
                cfg.get_int("clock_skew_management/lax_p2p/slack", 1000)
            )
        else:
            self.quantum_ps = None  # lax: unbounded
        # Host-driven lax_barrier quanta: at 1024 tiles with the memory
        # engine, SEND-carrying traces crash the TPU worker under the
        # single-region lax_barrier program (round-5 retest: canneal —
        # no CAPI sends — compiles AND runs single-region now; the FFT
        # skeleton still kills the worker), while the per-quantum region
        # (no outer while_loop, qend as an argument) runs — so the
        # Simulator drives the barrier loop host-side exactly there,
        # with identical quantum semantics
        # (`lax_barrier_sync_server.h:12-36`).  Override via barrier_host.
        if barrier_host is None:
            from graphite_tpu.trace.schema import Op as _Op

            barrier_host = (self.quantum_ps is not None
                            and mem_params is not None
                            and n_tiles >= 1024
                            and bool(np.any(trace.op == int(_Op.SEND)))
                            and mesh is None and not stream)
        if barrier_host and self.quantum_ps is None:
            raise ValueError(
                "barrier_host=True needs the lax_barrier clock scheme "
                "(there are no quanta to drive host-side otherwise)")
        self.barrier_host = bool(barrier_host)
        if self.barrier_host and (mesh is not None or stream):
            raise ValueError(
                "host-driven lax_barrier quanta support single-device "
                "resident runs only")
        # quanta per host dispatch under barrier_host (the batched
        # device-side loop; 1 = the legacy per-quantum dispatch)
        if barrier_batch is None:
            barrier_batch = cfg.get_int("general/barrier_batch", 8)
        if barrier_batch < 1:
            raise ValueError("barrier_batch must be >= 1")
        self.barrier_batch = int(barrier_batch)
        if self.p2p_slack_ps is not None:
            self.params = dataclasses.replace(
                self.params, p2p_slack_ps=self.p2p_slack_ps)

        models_on = not cfg.get_bool(
            "general/trigger_models_within_application", False
        )
        core_freq = module_freq_mhz(cfg, "CORE")
        self.state: SimState = init_state(
            n_tiles,
            core_freq_mhz=core_freq,
            bp_size=self.params.bp_size,
            mailbox_depth=mailbox_depth,
            n_barriers=n_barriers,
            n_mutexes=n_mutexes,
            n_conds=n_conds,
            models_enabled=models_on,
        )
        if mem_params is not None:
            from graphite_tpu.memory import init_mem_state

            if mem_params.protocol.startswith("pr_l1_sh_l2"):
                from graphite_tpu.memory.engine_shl2 import init_shl2_state

                self.state = self.state.replace(
                    mem=init_shl2_state(mem_params))
            else:
                self.state = self.state.replace(
                    mem=init_mem_state(mem_params))
            if mem_params.net_hbh is not None:
                # per-port queue state of the MEMORY NoC (`[network]
                # memory = emesh_hop_by_hop`) — coherence messages route
                # through it with per-hop contention (mem_net_send)
                from graphite_tpu.models.network_hop_by_hop import (
                    init_noc_state,
                )

                self.state = self.state.replace(
                    mem=self.state.mem.replace(
                        noc=init_noc_state(mem_params.net_hbh)))
            elif mem_params.net_atac is not None:
                # ATAC hub-queue state of the MEMORY NoC (`[network]
                # memory = atac`) — coherence messages route over the
                # clusters/hubs/waveguide with hub contention
                from graphite_tpu.models.network_atac import (
                    init_atac_state,
                )

                self.state = self.state.replace(
                    mem=self.state.mem.replace(
                        noc=init_atac_state(mem_params.net_atac)))
        if user_hbh is not None:
            from graphite_tpu.models.network_hop_by_hop import init_noc_state

            self.state = self.state.replace(noc_user=init_noc_state(user_hbh))
        if user_atac is not None:
            from graphite_tpu.models.network_atac import init_atac_state

            self.state = self.state.replace(
                noc_user=init_atac_state(user_atac))
        if iocoom_params is not None:
            from graphite_tpu.models.iocoom import init_iocoom_state

            self.state = self.state.replace(
                ioc=init_iocoom_state(n_tiles, iocoom_params))
        from graphite_tpu.engine.state import DvfsState

        nd = dvfs_params.n_domains
        init_freqs = jnp.broadcast_to(
            jnp.asarray(dvfs_params.domain_freq_mhz, jnp.int32)[None, :],
            (n_tiles, nd)).copy()
        init_volts = jnp.asarray(
            [dvfs_params.min_voltage_mv(f)
             for f in dvfs_params.domain_freq_mhz], jnp.int32)
        self.state = self.state.replace(dvfs=DvfsState(
            freq_mhz=init_freqs,
            voltage_mv=jnp.broadcast_to(
                init_volts[None, :], (n_tiles, nd)).copy(),
            errors=jnp.zeros(n_tiles, jnp.int64),
        ))
        # streaming mode keeps the trace host-side; run_streamed() uploads
        # [T, W] windows on demand (bounded HBM regardless of trace size)
        self.stream = bool(stream)
        self.mesh = mesh
        # Multi-chip program selection: the packed shard_map exchange is
        # the default for EVERY protocol (one collective per engine
        # phase; PERF.md) — the reference's process striping serves
        # every protocol equally.  spmd='gspmd' keeps the legacy
        # whole-program-partitioning path.
        if spmd not in (None, "shard_map", "gspmd"):
            raise ValueError(f"unknown spmd program {spmd!r} "
                             "(expected 'shard_map' or 'gspmd')")
        if mesh is not None and spmd is None:
            spmd = "shard_map"
        self.spmd = spmd if mesh is not None else None
        self.device_trace = None if stream else DeviceTrace.from_batch(trace)
        if mesh is not None:
            # Shard the tile axis over the device mesh (SURVEY §2.10): the
            # TPU-native form of Graphite's process striping.  Streamed
            # runs shard the state here and each [T, W] window at upload
            # (run_streamed) — the two scale mechanisms compose: bounded-
            # HBM traces on a multi-chip mesh.
            if self.spmd == "shard_map":
                from graphite_tpu.parallel.mesh import place_shard_map

                if stream:
                    self.state = place_shard_map(self.state, mesh)
                else:
                    self.state, self.device_trace = place_shard_map(
                        self.state, mesh, self.device_trace)
            else:
                from graphite_tpu.parallel.mesh import shard_sim, shard_state

                if stream:
                    self.state = shard_state(self.state, mesh)
                else:
                    self.state, self.device_trace = shard_sim(
                        self.state, self.device_trace, mesh
                    )
        self.donate = bool(donate)
        # subquantum iterations executed by the last run (device loop
        # observability: wall / iterations = the engine's per-iteration
        # cost, the number PERF.md's floor analysis tracks)
        self.last_n_iterations = 0
        self._runner = None
        self._runner_max_quanta = None
        self._hb_runner = None
        # lower-once plumbing (round 11): audit, cost and fingerprint
        # all consume one lowering per (program, max_quanta) instead of
        # re-tracing per consumer; `lower_count` is the trace-count
        # probe the identity tests pin.  `lower_gen` counts program-
        # identity mutations (attach_telemetry) so wrappers holding
        # their own lowering caches (SweepRunner) can invalidate too.
        self._lowered = {}
        self.lower_count = 0
        self.lower_gen = 0
        # device-resident telemetry timeline (graphite_tpu/obs): resolve
        # the spec against this program's series set and seed the ring
        # into the state carry; None records nothing and lowers the
        # historical program bit-identically
        self.telemetry_spec = None
        # device-resident per-tile profile ring (graphite_tpu/obs/
        # profile.py): same attach/resolve/None-contract as telemetry
        self.profile_spec = None
        # runtime DVFS manager (graphite_tpu/dvfs): same attach/resolve/
        # None-contract — None carries no DvfsRtState leaves
        self.dvfs_spec = None
        # device-resident latency histograms (graphite_tpu/obs/hist.py):
        # same attach/resolve/None-contract as telemetry/profile
        self.hist_spec = None
        if telemetry is not None:
            self.attach_telemetry(telemetry)
        if profile is not None:
            self.attach_profile(profile)
        if dvfs is not None:
            self.attach_dvfs(dvfs)
        if hist is not None:
            self.attach_hist(hist)

    def attach_telemetry(self, spec) -> None:
        """Attach (or replace) a telemetry spec on a not-yet-run
        instance: resolves the series selection against this program,
        seeds the ring buffer into the state carry, and invalidates any
        compiled runner (the spec is baked into the lowering).  Used by
        `StatisticsManager`'s device backend to upgrade a plain sim."""
        from graphite_tpu.obs.telemetry import TelemetrySpec, init_telemetry

        if not isinstance(spec, TelemetrySpec):
            raise TypeError("telemetry must be an obs.TelemetrySpec")
        spec = spec.resolve(self.params)
        if self.mesh is not None or self.stream:
            # the ONE residency-refusal exception type (analysis/cost.py):
            # the message carries the analyzer's per-consumer breakdown so
            # the caller sees exactly what the refused layout would cost
            from graphite_tpu.analysis.cost import (
                ResidencyBudgetError, format_breakdown,
            )

            raise ResidencyBudgetError(
                "telemetry timelines support single-device resident runs "
                "and batched sweeps only (the ring is not threaded "
                "through the Simulator's own multi-chip exchange or the "
                "streaming window loop).  For a multi-device run, serve "
                "the sim as a campaign under SweepRunner's 2D "
                "batch x tile layout (layout='tile'/'2d'), which records "
                "the ring replicated per batch cell and splits the "
                "residency bill into per-device tile blocks — or use "
                "the chunked StatisticsManager backend.  Refused "
                "residency: "
                + format_breakdown(self.residency_breakdown(spec)))
        self.telemetry_spec = spec
        self.state = self.state.replace(telemetry=init_telemetry(spec))
        self._runner = None
        self._runner_max_quanta = None
        self._hb_runner = None
        self._lowered = {}   # the spec is baked into the lowering too
        self.lower_gen += 1

    def attach_profile(self, spec) -> None:
        """Attach (or replace) a per-tile profile spec on a not-yet-run
        instance: resolves the series selection against this program,
        seeds the [S, T, m] ring into the state carry, and invalidates
        any compiled runner (the spec is baked into the lowering) —
        the spatial-profiler twin of `attach_telemetry`."""
        from graphite_tpu.obs.profile import ProfileSpec, init_profile

        if not isinstance(spec, ProfileSpec):
            raise TypeError("profile must be an obs.ProfileSpec")
        spec = spec.resolve(self.params)
        if self.mesh is not None or self.stream:
            from graphite_tpu.analysis.cost import (
                ResidencyBudgetError, format_breakdown,
            )

            raise ResidencyBudgetError(
                "per-tile profile rings support single-device resident "
                "runs and batched sweeps only (the ring is not threaded "
                "through the Simulator's own multi-chip exchange or the "
                "streaming window loop).  For a multi-device run, serve "
                "the sim as a campaign under SweepRunner's 2D "
                "batch x tile layout (layout='tile'/'2d'): the "
                "[S, T, m] ring's tile axis shards with the directory "
                "and reassembles on fetch, so each device holds only "
                "its tile block of the ring.  Refused residency: "
                + format_breakdown(
                    self.residency_breakdown(profile_spec=spec)))
        self.profile_spec = spec
        self.state = self.state.replace(profile=init_profile(spec))
        self._runner = None
        self._runner_max_quanta = None
        self._hb_runner = None
        self._lowered = {}   # the spec is baked into the lowering too
        self.lower_gen += 1

    def attach_hist(self, spec) -> None:
        """Attach (or replace) a latency-histogram spec on a
        not-yet-run instance: resolves the source selection against
        this program, seeds the bucket-count ring into the state carry,
        and invalidates any compiled runner (the spec is baked into the
        lowering) — the distribution twin of `attach_profile`."""
        from graphite_tpu.obs.hist import HistSpec, init_hist

        if not isinstance(spec, HistSpec):
            raise TypeError("hist must be an obs.HistSpec")
        spec = spec.resolve(self.params)
        if self.mesh is not None or self.stream:
            from graphite_tpu.analysis.cost import (
                ResidencyBudgetError, format_breakdown,
            )

            raise ResidencyBudgetError(
                "latency histograms support single-device resident "
                "runs and batched sweeps only (the ring is not threaded "
                "through the Simulator's own multi-chip exchange or the "
                "streaming window loop).  For a multi-device run, serve "
                "the sim as a campaign under SweepRunner's 2D "
                "batch x tile layout (layout='tile'/'2d'): a per-tile "
                "ring's tile axis shards with the directory and "
                "reassembles on fetch.  Refused residency: "
                + format_breakdown(
                    self.residency_breakdown(hist_spec=spec)))
        self.hist_spec = spec
        self.state = self.state.replace(hist=init_hist(spec))
        self._runner = None
        self._runner_max_quanta = None
        self._hb_runner = None
        self._lowered = {}   # the spec is baked into the lowering too
        self.lower_gen += 1

    def attach_dvfs(self, spec, domain_mhz=None) -> None:
        """Attach (or replace) a runtime-DVFS spec on a not-yet-run
        instance: validates it against this program's [dvfs] tables,
        seeds the per-domain carry (`SimState.dvfs_rt`) from the
        config's initial domain frequencies — or `domain_mhz`, an
        int32[n_domains] override — and invalidates any compiled runner
        (the spec is baked into the lowering).  The CORE domain's seed
        broadcasts into `CoreState.freq_mhz` (chip-global semantics)."""
        from graphite_tpu.dvfs.runtime import (
            DvfsSpec, core_freq_tiles, init_dvfs_rt,
        )

        if not isinstance(spec, DvfsSpec):
            raise TypeError("dvfs must be a dvfs.DvfsSpec")
        spec = spec.resolve(self.params)
        if self.mesh is not None or self.stream:
            raise ValueError(
                "the runtime DVFS manager supports single-device "
                "resident runs and batched sweeps only (the carry is "
                "not threaded through the Simulator's own multi-chip "
                "exchange or the streaming window loop); serve the sim "
                "as a batched campaign under SweepRunner instead")
        rt = init_dvfs_rt(self.params.dvfs, spec, domain_mhz)
        self.dvfs_spec = spec
        self.state = self.state.replace(
            dvfs_rt=rt,
            core=self.state.core.replace(freq_mhz=core_freq_tiles(
                self.params.dvfs, rt, self.state.core.freq_mhz)))
        self._runner = None
        self._runner_max_quanta = None
        self._hb_runner = None
        self._lowered = {}   # the spec is baked into the lowering too
        self.lower_gen += 1

    def residency_breakdown(self, telemetry_spec=None,
                            profile_spec=None, hist_spec=None) -> dict:
        """Per-consumer HBM residency estimate of THIS sim's layout
        (analysis/cost.residency_breakdown): state pytree, resident
        device trace (or one streaming window bound), telemetry ring,
        per-tile profile ring, histogram ring.  `telemetry_spec`/
        `profile_spec`/`hist_spec` override the attached specs — the
        attach_* refusal paths price the spec they are refusing before
        it is attached."""
        from graphite_tpu.analysis.cost import residency_breakdown

        spec = telemetry_spec if telemetry_spec is not None \
            else self.telemetry_spec
        if spec is not None and not spec.resolved:
            spec = spec.resolve(self.params)
        pspec = profile_spec if profile_spec is not None \
            else self.profile_spec
        if pspec is not None and not pspec.resolved:
            pspec = pspec.resolve(self.params)
        hspec = hist_spec if hist_spec is not None else self.hist_spec
        if hspec is not None and not hspec.resolved:
            hspec = hspec.resolve(self.params)
        # the rings are itemized as their own consumers — strip them
        # from the state pytree so an attached spec is not counted twice
        state = self.state
        if state.telemetry is not None:
            state = state.replace(telemetry=None)
        if state.profile is not None:
            state = state.replace(profile=None)
        if state.hist is not None:
            state = state.replace(hist=None)
        stream_bytes = None
        if self.stream:
            # run_streamed's default [T, W] window, double-buffered by
            # the prefetch staging — pure arithmetic, never materialized
            # (this runs inside refusal paths on memory-constrained
            # devices, so it must not allocate what it is pricing)
            from graphite_tpu.analysis.cost import trace_record_bytes

            stream_bytes = (2 * self.params.n_tiles
                            * STREAM_WINDOW_RECORDS
                            * trace_record_bytes(self.trace_batch))
        return residency_breakdown(
            state=state, trace=self.device_trace,
            telemetry_spec=spec, profile_spec=pspec, hist_spec=hspec,
            stream_window_bytes=stream_bytes)

    @property
    def profile(self):
        """The recorded per-tile profile (obs.TileProfile) of
        everything run so far, or None when the sim records none."""
        if self.profile_spec is None:
            return None
        from graphite_tpu.obs.profile import profile_from_state

        return profile_from_state(self.profile_spec, self.state.profile)

    @property
    def hist(self):
        """The recorded latency histograms (obs.Hist) of everything
        run so far, or None when the sim records none."""
        if self.hist_spec is None:
            return None
        from graphite_tpu.obs.hist import hist_from_state

        return hist_from_state(self.hist_spec, self.state.hist)

    @property
    def telemetry(self):
        """The recorded timeline (obs.Timeline) of everything run so
        far, or None when the sim records no telemetry."""
        if self.telemetry_spec is None:
            return None
        from graphite_tpu.obs.telemetry import timeline_from_state

        return timeline_from_state(self.telemetry_spec,
                                   self.state.telemetry)

    @staticmethod
    def _resolve_mem_gate_bytes(cfg, mem_gate_bytes) -> int:
        """The whole-engine mem_gate's state-size ceiling: kwarg, else
        `[general] mem_gate_bytes`, else the historical 1 GB default —
        an escape hatch now, not a hard-code (per-phase gating covers
        the regime above it)."""
        if mem_gate_bytes is not None:
            return int(mem_gate_bytes)
        return cfg.get_int("general/mem_gate_bytes", 1 << 30)

    @staticmethod
    def _resolve_plain_unroll(cfg, mem_params, iocoom_params) -> int:
        from graphite_tpu.engine.step import PLAIN_UNROLL_MAX

        pu = cfg.get_int(
            "general/plain_unroll",
            16 if (mem_params is None and iocoom_params is None) else 1)
        if pu > PLAIN_UNROLL_MAX:
            import warnings

            warnings.warn(
                f"[general] plain_unroll = {pu} exceeds the measured-safe "
                f"ceiling {PLAIN_UNROLL_MAX} (the [T, K] follow-on gather "
                f"regresses superlinearly past it — PERF.md unroll sweep); "
                f"clamping to {PLAIN_UNROLL_MAX}",
                stacklevel=3)
            pu = PLAIN_UNROLL_MAX
        return pu

    @property
    def last_phase_skips(self):
        """Per-phase lax.cond skip counts of the memory engine across
        everything run so far (gate observability: skip rate = skips /
        `last_n_iterations`).  Dict phase-name -> count in the engine's
        own phase order, or None when the run has no memory subsystem.
        Counts every skip source: the per-phase conds AND whole-engine
        mem_gate skips (which count as a skip of every phase)."""
        if self.state.mem is None:
            return None
        skips = np.asarray(jax.device_get(self.state.mem.phase_skips))
        names = mem_phase_names(self.params)
        return {n: int(v) for n, v in zip(names, skips.tolist())}

    def _get_runner(self, max_quanta: int):
        if self._runner is None or self._runner_max_quanta != max_quanta:
            if self.spmd == "shard_map":
                from graphite_tpu.parallel.mesh import make_shard_map_runner

                sm = make_shard_map_runner(
                    self.params, self.quantum_ps, max_quanta, self.mesh,
                    self.state, self.device_trace)
                trace = self.device_trace
                self._runner = lambda st: sm(st, trace)
            else:
                from graphite_tpu.engine.step import make_simulation_runner

                self._runner = make_simulation_runner(
                    self.params, self.device_trace, self.quantum_ps,
                    max_quanta, donate=self.donate,
                    telemetry=self.telemetry_spec,
                    profile=self.profile_spec,
                    dvfs=self.dvfs_spec,
                    hist=self.hist_spec)
            self._runner_max_quanta = max_quanta
        return self._runner

    def lower(self, max_quanta: int = 4096):
        """The compiled program as a ClosedJaxpr, plus its flat invar
        paths — the program auditor's input (analysis/audit.py).

        Lowers the program run() actually compiles: the single-region
        device-driven loop, or — for barrier_host sims — the bounded
        batched host-dispatch region (`engine/step.barrier_host_batch`,
        with its dynamic prev_qend/budget operands), so audit verdicts
        certify the executed artifact.  `jax.make_jaxpr` only: pure
        tracing, no compile, so auditing works on CPU-only CI.  Path i
        of the returned list names closed.jaxpr.invars[i] (state leaves
        first, then trace leaves).

        Lower-once: the (closed, paths) pair is cached per max_quanta —
        the auditor, the cost model and the identity fingerprint all
        describe ONE tracing instead of re-lowering per consumer
        (`lower_count` counts actual traces; the identity tests pin it
        at 1 across the whole audit+cost+fingerprint pipeline)."""
        from graphite_tpu.analysis.walk import invar_path_strings

        hit = self._lowered.get(max_quanta)
        if hit is None:
            fn, args = self._auditable_fn(max_quanta)
            closed = jax.make_jaxpr(fn)(*args)
            self.lower_count += 1
            hit = (closed, invar_path_strings(args))
            self._lowered[max_quanta] = hit
        return hit

    def _auditable_fn(self, max_quanta: int = 4096):
        """(fn, args) of the program run() actually executes — lower()
        traces it with make_jaxpr; the cost model's backend cross-check
        (analysis/cost.backend_memory_comparison) jits and compiles the
        SAME pair, so the static estimate and memory_analysis() always
        describe one artifact."""
        if self.mesh is not None or self.stream:
            raise ValueError(
                "lower() supports single-device resident programs only "
                "(the auditable artifact is the one-region jaxpr)")
        params = self.params
        tel = self.telemetry_spec
        prof = self.profile_spec
        dv = self.dvfs_spec
        hs = self.hist_spec
        if self.barrier_host:
            from graphite_tpu.engine.step import barrier_host_batch

            qps = int(self.quantum_ps)

            def fn(st, tr, prev_qend, budget):
                return barrier_host_batch(params, tr, st, prev_qend,
                                          qps, budget, telemetry=tel,
                                          profile=prof, dvfs=dv, hist=hs)

            args = (self.state, self.device_trace,
                    jnp.asarray(0, jnp.int64),
                    jnp.asarray(self.barrier_batch, jnp.int32))
        else:
            from graphite_tpu.engine.step import run_simulation

            qps = self.quantum_ps

            def fn(st, tr):
                return run_simulation(params, tr, st, qps, max_quanta,
                                      telemetry=tel, profile=prof,
                                      dvfs=dv, hist=hs)

            args = (self.state, self.device_trace)
        return fn, args

    def run_chunk(self, n_quanta: int):
        """Run at most `n_quanta` quanta (for sampled/checkpointed runs).

        Returns (done, quanta_executed).  Unlike run(), hitting the bound
        is not an error — the caller samples/checkpoints and continues.
        """
        if self.barrier_host:
            nq, all_done = self._host_barrier_loop(n_quanta)
            return all_done, nq
        state, n_quanta_dev, deadlock_dev, n_iters = self._get_runner(
            n_quanta)(self.state)
        nq, deadlock, overflow, done, self.last_n_iterations = (
            jax.device_get((n_quanta_dev, deadlock_dev, state.net.overflow,
                            state.done, n_iters)))
        if bool(overflow):
            raise MailboxOverflowError(
                "a (dst,src) mailbox ring overflowed; re-run with a "
                "larger mailbox_depth")
        if bool(deadlock):
            blocked = np.flatnonzero(~done).tolist()
            raise DeadlockError(
                f"no progress across a quantum; blocked tiles: "
                f"{blocked[:16]}{'...' if len(blocked) > 16 else ''}")
        self.state = state
        return bool(done.all()), int(nq)

    def _run_host_barrier(self, max_quanta: int) -> SimResults:
        """lax_barrier quanta driven host-side (see run()): one compiled
        BOUNDED multi-quantum region per dispatch (`barrier_host_batch` —
        a device-side while_loop over up to `barrier_batch` quanta, no
        unbounded outer loop) — the variant that compiles where the
        1024-tile + memory-engine single-region lax_barrier program
        crashes the remote-compile helper.  Semantics mirror
        `run_simulation`'s device loop exactly: next boundary above the
        laggard tile, empty quanta skipped, zero-progress with a tile
        beyond the boundary jumps the window, else deadlock.  The batch
        loop early-exits to the host on host-visible work (all done,
        mailbox overflow, deadlock), so each ~100 ms tunneled dispatch is
        amortized over up to K quanta instead of one."""
        n, all_done = self._host_barrier_loop(max_quanta)
        if not all_done:
            raise RuntimeError(f"exceeded max_quanta={max_quanta}")
        return self._results_from_state(n)

    def _hb_get_runner(self):
        if self._hb_runner is None:
            from graphite_tpu.engine.step import barrier_host_batch

            params, trace = self.params, self.device_trace
            qps = int(self.quantum_ps)
            tel = self.telemetry_spec
            prof = self.profile_spec
            dv = self.dvfs_spec
            hs = self.hist_spec

            def qrun(st, prev_qend, budget):
                return barrier_host_batch(params, trace, st, prev_qend,
                                          qps, budget, telemetry=tel,
                                          profile=prof, dvfs=dv, hist=hs)

            self._hb_runner = jax.jit(
                qrun, donate_argnums=(0,) if self.donate else ())
        return self._hb_runner

    def _host_barrier_loop(self, max_quanta: int):
        """Run up to max_quanta host-driven barrier quanta in batches of
        `barrier_batch` per dispatch; returns (quanta_executed,
        all_done).  Mutates self.state.  The budget rides as a DYNAMIC
        operand, so run_chunk-style partial budgets never recompile and
        never overshoot."""
        import jax.numpy as jnp

        runner = self._hb_get_runner()
        state = self.state
        prev_qend = jnp.asarray(0, jnp.int64)
        n = 0
        total_iters = 0
        done = jax.device_get(state.done)
        while n < max_quanta and not done.all():
            budget = min(self.barrier_batch, max_quanta - n)
            state, prev_qend, nq_d, deadlock_d, iters_d = runner(
                state, prev_qend, jnp.asarray(budget, jnp.int32))
            nq, deadlock, iters, done, overflow = jax.device_get(
                (nq_d, deadlock_d, iters_d, state.done,
                 state.net.overflow))
            n += int(nq)
            total_iters += int(iters)
            if bool(overflow):
                raise MailboxOverflowError(
                    "a (dst,src) mailbox ring overflowed; re-run with a "
                    "larger mailbox_depth")
            if bool(deadlock):
                blocked = np.flatnonzero(~done).tolist()
                raise DeadlockError(
                    f"no progress across a quantum; blocked tiles: "
                    f"{blocked[:16]}{'...' if len(blocked) > 16 else ''}")
            if int(nq) == 0 and not done.all():
                # the device loop ran zero quanta without raising a flag:
                # its entry condition should make this unreachable
                raise DeadlockError(
                    "host-barrier batch made no progress and raised no "
                    "flag")
        self.state = state
        self.last_n_iterations = total_iters
        return n, bool(done.all())

    @staticmethod
    def _result_parts(state: SimState):
        """Device-side pytrees for the summary counters (shared by run()
        and _results_from_state — keep in one place)."""
        mem_part = (
            (state.mem.counters, state.mem.func_errors)
            if state.mem is not None else None
        )
        ioc_part = (
            {
                "load_queue": state.ioc.load_queue_stall_ps,
                "store_queue": state.ioc.store_queue_stall_ps,
                "l1icache": state.ioc.l1icache_stall_ps,
                "intra_ins_l1dcache": state.ioc.intra_ins_l1dcache_stall_ps,
                "inter_ins_l1dcache": state.ioc.inter_ins_l1dcache_stall_ps,
                "intra_ins_execution_unit":
                    state.ioc.intra_ins_execution_unit_stall_ps,
                "inter_ins_execution_unit":
                    state.ioc.inter_ins_execution_unit_stall_ps,
            }
            if state.ioc is not None else None
        )
        net_part = (state.net.packets_sent, state.net.packets_received,
                    state.net.total_latency_ps)
        tel_part = (
            (state.telemetry.buf, state.telemetry.count)
            if state.telemetry is not None else None
        )
        prof_part = (
            (state.profile.buf, state.profile.times, state.profile.count)
            if state.profile is not None else None
        )
        hist_part = (
            (state.hist.buf, state.hist.boundaries)
            if state.hist is not None else None
        )
        return (net_part, mem_part, ioc_part, tel_part, prof_part,
                hist_part)

    def _timeline_host(self, tel_h):
        """Demux an already-fetched (buf, count) pair into a Timeline —
        keeps the ring inside run()'s ONE batched device→host fetch
        (a separate read over a tunneled chip costs ~100 ms)."""
        if tel_h is None or self.telemetry_spec is None:
            return None
        from graphite_tpu.obs.telemetry import Timeline

        buf, count = tel_h
        return Timeline.from_host_state(self.telemetry_spec,
                                        np.asarray(buf), int(count))

    def _profile_host(self, prof_h):
        """Demux an already-fetched (buf, times, count) triple into a
        TileProfile — rides run()'s ONE batched device→host fetch like
        the telemetry ring."""
        if prof_h is None or self.profile_spec is None:
            return None
        from graphite_tpu.obs.profile import TileProfile

        buf, times, count = prof_h
        return TileProfile.from_host_state(
            self.profile_spec, np.asarray(buf), np.asarray(times),
            int(count))

    def _hist_host(self, hist_h):
        """Demux an already-fetched (buf, boundaries) pair into a Hist —
        rides run()'s ONE batched device→host fetch like the other
        rings."""
        if hist_h is None or self.hist_spec is None:
            return None
        from graphite_tpu.obs.hist import Hist

        buf, boundaries = hist_h
        return Hist(sources=tuple(self.hist_spec.sources),
                    edges=self.hist_spec.bucket_edges(),
                    counts=np.asarray(buf), boundaries=int(boundaries))

    def _results_from_state(self, n_quanta: int) -> SimResults:
        """SimResults from the CURRENT state (after run_chunk loops)."""
        state = self.state
        (net_part, mem_part, ioc_part, tel_part, prof_part,
         hist_part) = self._result_parts(state)
        core_h, net_h, mem_h, ioc_h, tel_h, prof_h, hist_h = \
            jax.device_get((
                state.core, net_part, mem_part, ioc_part, tel_part,
                prof_part, hist_part,
            ))
        return self._results_host(core_h, net_h, mem_h, n_quanta, ioc_h,
                                  telemetry=self._timeline_host(tel_h),
                                  profile=self._profile_host(prof_h),
                                  hist=self._hist_host(hist_h))

    def write_output(self, results: SimResults,
                     output_dir: str = "results") -> str:
        """Write the `sim.out` summary + a config snapshot, mirroring the
        reference's per-run results directory (`carbon_sim.cfg:11-30`,
        `simulator.cc:152-170`)."""
        import os

        os.makedirs(output_dir, exist_ok=True)
        out_path = os.path.join(output_dir, "sim.out")
        with open(out_path, "w") as f:
            f.write(results.summary() + "\n")
        with open(os.path.join(output_dir, "carbon_sim.cfg"), "w") as f:
            for key, value in sorted(self.config.cfg.as_dict().items()):
                f.write(f"{key} = {value}\n")
        return out_path

    def run_streamed(self, window_records: int = STREAM_WINDOW_RECORDS,
                     max_quanta: int = 1_000_000,
                     max_windows: int = 1_000_000) -> SimResults:
        """Like run(), but the trace streams host->HBM in [T, W] windows
        (the schema's promised streaming mode — `trace/schema.py`; the
        reference analog is Pin's continuous instruction pipe,
        `pin/instruction_modeling.cc:13-21`).  Device memory for trace
        data is bounded by one window regardless of trace length.

        Windows have PER-TILE base records (each lane's window follows
        its own stream position), so lanes may skew arbitrarily — a
        leader pausing at its window edge never starves a laggard.  The
        device loop runs until every lane is done, deadlocked, or paused
        at its window's end; the host then re-bases every lane's window
        at its current record and re-enters.  A guessed next window
        (every lane one full window ahead — the lockstep case) is staged
        with an async upload while the device crunches, overlapping
        transfer with compute.
        """
        W = int(window_records)
        batch = self.trace_batch

        # mesh runs shard each [T, W] window on upload (row t of every
        # window lives with tile t's shard) — streaming and multi-chip
        # striping compose.  Under shard_map the per-tile base vector is
        # replicated control state (the engine lo()s it for local reads).
        if self.mesh is not None and self.spmd == "shard_map":
            from graphite_tpu.parallel.mesh import place_shard_map_window

            def place(win, b):
                return place_shard_map_window(win, self.mesh, b)
        elif self.mesh is not None:
            from graphite_tpu.parallel.mesh import shard_window

            def place(win, b):
                return shard_window(win, self.mesh, b)
        else:
            def place(win, b):
                return win, jnp.asarray(b)

        # module-level runner cache: a fresh jit(lambda) per call (or per
        # Simulator — benchmark warmups use a throwaway instance) would
        # register a new wrapper whose traces don't share the previous
        # executables, silently putting re-compilation inside timed runs
        first_window = None
        if self.spmd == "shard_map":
            bases0 = np.zeros(batch.n_tiles, np.int32)
            first_window = place(DeviceTrace.window(batch, bases0, W),
                                 bases0)
            runner = _streamed_runner(
                self.params, self.quantum_ps, max_quanta, self.mesh,
                self.spmd, self.state, first_window[0])
        else:
            runner = _streamed_runner(self.params, self.quantum_ps,
                                      max_quanta)

        bases = np.zeros(batch.n_tiles, np.int32)
        state = self.state
        window, dev_bases = (
            first_window if first_window is not None
            else place(DeviceTrace.window(batch, bases, W), bases))
        prefetch_bases = None
        prefetch = None
        prefetch_on = True  # lockstep so far; first miss turns it off
        n_quanta = 0
        for _ in range(max_windows):
            out = runner(state, window, dev_bases)
            # overlap: stage the lockstep-guess window during the run —
            # only while every slide so far matched the guess (a skewed
            # run would rebuild + re-upload a discarded window each slide)
            guess = bases + W
            if prefetch_on and (guess < batch.length).any():
                prefetch_bases = guess
                prefetch = place(DeviceTrace.window(batch, guess, W), guess)
            else:
                prefetch_bases = None
            state, nq_dev, deadlock_dev, n_iters_dev = out
            done, idx, deadlock, overflow = jax.device_get(
                (state.done, state.core.idx, deadlock_dev,
                 state.net.overflow))
            n_quanta += int(nq_dev)
            if bool(overflow):
                raise MailboxOverflowError(
                    "a (dst,src) mailbox ring overflowed; re-run with a "
                    "larger mailbox_depth")
            if done.all():
                break
            if bool(deadlock):
                blocked = np.flatnonzero(~done).tolist()
                raise DeadlockError(
                    f"no progress across a quantum; blocked tiles: "
                    f"{blocked[:16]}{'...' if len(blocked) > 16 else ''}")
            new_bases = np.where(done, bases, idx.astype(np.int32))
            if (new_bases == bases).all():
                # every lane held position across a full window run —
                # cannot happen unless the device loop bailed for a
                # reason the flags above should have caught
                raise DeadlockError(
                    "streaming made no progress across a window slide")
            bases = new_bases
            hit = (prefetch_bases is not None
                   and np.array_equal(prefetch_bases, bases))
            if not hit:
                prefetch_on = False
            window, dev_bases = (
                prefetch if hit
                else place(DeviceTrace.window(batch, bases, W), bases))
        else:
            raise RuntimeError(f"exceeded max_windows={max_windows}")
        self.state = state
        return self._results_from_state(n_quanta)

    def warmup(self, max_quanta: int = 1_000_000) -> None:
        """Compile (and execute once, discarding results) the full runner —
        for benchmarking so timed runs exclude compilation."""
        if self.donate:
            # the donated run would delete self.state's buffers and the
            # discarded output is the only live copy — a later run() would
            # fail with an opaque "array has been deleted"
            raise RuntimeError(
                "warmup() is incompatible with donate=True (the warmup "
                "run would consume self.state); warm a separate "
                "non-donating instance and adopt_runner() from it")
        if self.barrier_host:
            # compile + execute one single-quantum batch (the unbounded
            # single-region program is the one that crashes at this
            # scale); the output is discarded, self.state stays untouched
            import jax.numpy as jnp

            out = self._hb_get_runner()(
                self.state, jnp.asarray(0, jnp.int64),
                jnp.asarray(1, jnp.int32))
            jax.block_until_ready(out)
            return
        out = self._get_runner(max_quanta)(self.state)
        jax.block_until_ready(out)

    def adopt_runner(self, other: "Simulator") -> None:
        """Reuse another instance's compiled runner.

        For timed repeat runs with donate=True (which consumes the ran
        instance's state): build a fresh instance over the SAME config and
        trace batch, adopt the first instance's runner, and the timed run
        excludes retrace/recompile.  The runner closes over the other
        instance's device trace, so both instances must be built from the
        SAME trace batch object and identical config/donation."""
        if other._runner is None and other._hb_runner is None:
            raise ValueError(
                "adopt_runner: the donor has no compiled runner (run it "
                "first) — adopting nothing would silently time a "
                "retrace+recompile")
        if (other.params != self.params or other.spmd != self.spmd
                or other.quantum_ps != self.quantum_ps
                or other.mesh != self.mesh
                or other.donate != self.donate
                or other.barrier_host != self.barrier_host
                or other.barrier_batch != self.barrier_batch
                # the recording specs are baked into the lowering: an
                # adopted runner with different specs would silently
                # record nothing (or retrace) instead of refusing
                or other.telemetry_spec != self.telemetry_spec
                or other.profile_spec != self.profile_spec
                or other.dvfs_spec != self.dvfs_spec
                or other.hist_spec != self.hist_spec
                or other.trace_batch is not self.trace_batch):
            raise ValueError(
                "adopt_runner needs the same trace batch and identical "
                "config/program/quantum/mesh/donation/recording specs")
        # the adopted runner closes over the donor's device trace — drop
        # this instance's duplicate upload (matters at 1024-tile scale)
        self.device_trace = other.device_trace
        self._runner = other._runner
        self._runner_max_quanta = other._runner_max_quanta
        self._hb_runner = other._hb_runner

    def run(self, max_quanta: int = 1_000_000) -> SimResults:
        """Drive quanta until every tile's trace is exhausted.

        The whole quantum loop runs on device as one compiled region
        (`run_simulation`): loop control (next boundary above the laggard
        tile, zero-progress/deadlock detection, overflow) is device-side,
        so the run costs a single host↔device round trip — each control
        read over a tunneled chip costs ~100 ms, which made the previous
        per-quantum host loop 5x slower than the simulation itself.
        Empty quanta are skipped by jumping qend to the next boundary above
        the laggard tile's clock (the reference's barrier only collects
        *running* threads, so idle quanta never happen there either —
        `lax_barrier_sync_server.h:12-36`).  A quantum with zero progress
        while some tile was eligible to run is a genuine deadlock.

        Under `barrier_host` (the 1024-tile + memory-engine lax_barrier
        combination) the barrier loop runs host-side instead — identical
        quantum semantics, one bounded compiled region per `barrier_batch`
        quanta (early-exiting on host-visible work).
        """
        if self.barrier_host:
            return self._run_host_barrier(max_quanta)
        state, n_quanta_dev, deadlock_dev, n_iters = self._get_runner(
            max_quanta)(self.state)
        # ONE batched device→host fetch for control flags + all summary
        # counters + the telemetry ring (each separate read over a
        # tunneled chip costs ~100 ms).
        (net_part, mem_part, ioc_part, tel_part, prof_part,
         hist_part) = self._result_parts(state)
        host = jax.device_get((
            n_quanta_dev, deadlock_dev, state.net.overflow, state.done,
            state.core, net_part, mem_part, ioc_part, tel_part,
            prof_part, hist_part, n_iters,
        ))
        (n_quanta, deadlock, overflow, done, core_h, net_h, mem_h,
         ioc_h, tel_h, prof_h, hist_h, self.last_n_iterations) = host
        if bool(overflow):
            raise MailboxOverflowError(
                "a (dst,src) mailbox ring overflowed; re-run with a "
                "larger mailbox_depth"
            )
        if bool(deadlock):
            blocked = np.flatnonzero(~done).tolist()
            raise DeadlockError(
                f"no progress across a quantum; blocked tiles: "
                f"{blocked[:16]}{'...' if len(blocked) > 16 else ''}"
            )
        if not bool(done.all()):
            raise RuntimeError(f"exceeded max_quanta={max_quanta}")
        self.state = state
        return self._results_host(core_h, net_h, mem_h, int(n_quanta), ioc_h,
                                  telemetry=self._timeline_host(tel_h),
                                  profile=self._profile_host(prof_h),
                                  hist=self._hist_host(hist_h))

    def _results_host(self, core, net_h, mem_h, n_quanta: int,
                      ioc_h=None, telemetry=None,
                      profile=None, hist=None) -> SimResults:
        """Assemble SimResults from already-fetched host arrays."""
        clock = np.asarray(core.clock_ps)
        mem_counters = None
        func_errors = 0
        if mem_h is not None:
            import dataclasses as _dc

            counters_h, func_errors_h = mem_h
            mem_counters = {
                f.name: np.asarray(getattr(counters_h, f.name))
                for f in _dc.fields(counters_h)
            }
            func_errors = int(func_errors_h)
        packets_sent, packets_received, total_latency_ps = net_h
        return SimResults(
            n_tiles=self.params.n_tiles,
            completion_time_ps=int(clock.max()),
            instruction_count=np.asarray(core.instruction_count),
            clock_ps=clock,
            memory_stall_ps=np.asarray(core.memory_stall_ps),
            execution_stall_ps=np.asarray(core.execution_stall_ps),
            recv_instructions=np.asarray(core.recv_instructions),
            recv_stall_ps=np.asarray(core.recv_stall_ps),
            sync_instructions=np.asarray(core.sync_instructions),
            sync_stall_ps=np.asarray(core.sync_stall_ps),
            bp_correct=np.asarray(core.bp_correct),
            bp_incorrect=np.asarray(core.bp_incorrect),
            packets_sent=np.asarray(packets_sent),
            packets_received=np.asarray(packets_received),
            total_packet_latency_ps=np.asarray(total_latency_ps),
            n_quanta=n_quanta,
            mem_counters=mem_counters,
            func_errors=func_errors,
            detailed_stalls=(
                {k: np.asarray(v) for k, v in ioc_h.items()}
                if ioc_h is not None else None),
            telemetry=telemetry,
            profile=profile,
            hist=hist,
        )

