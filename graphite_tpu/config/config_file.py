"""carbon_sim.cfg-compatible hierarchical INI configuration.

Reference behavior being matched (not translated):
 - hierarchical sections `[a/b/c]` (`common/config/config.hpp`,
   grammar `common/config/config_file_grammar.hpp:7-11`);
 - values are quoted strings, integers, floats, or true/false
   (`carbon_sim.cfg:7-8`);
 - `#` starts a comment, including trailing comments after values
   (`carbon_sim.cfg` throughout, e.g. `:143`);
 - typed getters `getInt/getBool/getString/getFloat` keyed by full path
   `"section/sub/key"` (`common/config/config_file.hpp:20-42`);
 - CLI overrides `--section/sub/key=value` and `-c <file>` merged on top
   (`common/misc/handle_args.cc:45-58`).

This is a fresh pure-Python implementation (the reference uses boost-spirit);
only the observable config surface is reproduced.
"""

from __future__ import annotations

import re
from typing import Any, Iterable


class ConfigError(KeyError):
    pass


_SECTION_RE = re.compile(r"^\[([A-Za-z0-9_/\-]+)\]\s*$")
_KEY_RE = re.compile(r"^([A-Za-z0-9_\-]+)\s*=\s*(.*)$")


def _strip_comment(line: str) -> str:
    """Remove a trailing # comment, respecting double-quoted strings."""
    out = []
    in_quote = False
    for ch in line:
        if ch == '"':
            in_quote = not in_quote
        elif ch == "#" and not in_quote:
            break
        out.append(ch)
    return "".join(out)


class ConfigFile:
    """Flat map of "section/sub/key" -> raw string value, with typed getters."""

    def __init__(self) -> None:
        self._values: dict[str, str] = {}

    # --- loading ---------------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "ConfigFile":
        cfg = cls()
        with open(path, "r") as f:
            cfg.load_string(f.read())
        return cfg

    @classmethod
    def from_string(cls, text: str) -> "ConfigFile":
        cfg = cls()
        cfg.load_string(text)
        return cfg

    def load_string(self, text: str) -> None:
        section = ""
        for lineno, raw in enumerate(text.splitlines(), start=1):
            line = _strip_comment(raw).strip()
            if not line:
                continue
            m = _SECTION_RE.match(line)
            if m:
                section = m.group(1).strip("/")
                # register the section even if empty (e.g. [core] at
                # carbon_sim.cfg:178 has no keys of its own)
                continue
            m = _KEY_RE.match(line)
            if m is None:
                raise ConfigError(f"config parse error at line {lineno}: {raw!r}")
            key, value = m.group(1), m.group(2).strip()
            full = f"{section}/{key}" if section else key
            self._values[full] = value

    def merge(self, other: "ConfigFile") -> None:
        """Later files / overrides win (handle_args.cc merge-on-top)."""
        self._values.update(other._values)

    def set(self, path: str, value: Any) -> None:
        if isinstance(value, bool):
            value = "true" if value else "false"
        self._values[path.strip("/")] = str(value)

    # --- typed getters ---------------------------------------------------

    _MISSING = object()

    def _raw(self, path: str, default: Any = _MISSING) -> str:
        path = path.strip("/")
        if path in self._values:
            return self._values[path]
        if default is not ConfigFile._MISSING:
            return default
        raise ConfigError(f"missing config key: {path}")

    def has(self, path: str) -> bool:
        return path.strip("/") in self._values

    def get_string(self, path: str, default: Any = _MISSING) -> str:
        v = self._raw(path, default)
        if not isinstance(v, str):
            return v
        v = v.strip()
        if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
            v = v[1:-1]
        return v

    def get_int(self, path: str, default: Any = _MISSING) -> int:
        v = self._raw(path, default)
        if not isinstance(v, str):
            return v
        try:
            return int(v, 0)
        except ValueError:
            # the reference tolerates float-formatted ints in int contexts
            try:
                f = float(v)
            except ValueError:
                raise ConfigError(f"config key {path} = {v!r} is not an int")
            if f != int(f):
                raise ConfigError(f"config key {path} = {v!r} is not an int")
            return int(f)

    def get_float(self, path: str, default: Any = _MISSING) -> float:
        v = self._raw(path, default)
        if not isinstance(v, str):
            return v
        return float(v)

    def get_bool(self, path: str, default: Any = _MISSING) -> bool:
        v = self._raw(path, default)
        if not isinstance(v, str):
            return v
        lv = v.strip().lower()
        if lv in ("true", "1"):
            return True
        if lv in ("false", "0"):
            return False
        raise ConfigError(f"config key {path} = {v!r} is not a bool")

    # --- introspection ---------------------------------------------------

    def keys(self) -> Iterable[str]:
        return self._values.keys()

    def section(self, prefix: str) -> dict[str, str]:
        """All keys directly under `prefix` (used for [process_map])."""
        prefix = prefix.strip("/") + "/"
        out = {}
        for k, v in self._values.items():
            if k.startswith(prefix) and "/" not in k[len(prefix):]:
                out[k[len(prefix):]] = v
        return out

    def as_dict(self) -> dict[str, str]:
        return dict(self._values)


def parse_override_args(argv: list[str]) -> tuple[list[str], ConfigFile, str | None]:
    """Parse `-c <file>` and `--section/key=value` overrides.

    Mirrors `common/misc/handle_args.cc:45-58`: returns (remaining argv,
    override ConfigFile, config file path or None).
    """
    overrides = ConfigFile()
    cfg_path: str | None = None
    rest: list[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "-c":
            if i + 1 >= len(argv):
                raise ConfigError("-c requires a file argument")
            cfg_path = argv[i + 1]
            i += 2
            continue
        if arg.startswith("-c="):
            cfg_path = arg[len("-c="):]
            i += 1
            continue
        if arg.startswith("--") and "=" in arg:
            path, _, value = arg[2:].partition("=")
            overrides.set(path, value)
            i += 1
            continue
        rest.append(arg)
        i += 1
    return rest, overrides, cfg_path


def load_config(path: str | None, argv: list[str] | None = None) -> ConfigFile:
    """Load a config file then apply CLI overrides on top."""
    argv = argv or []
    rest, overrides, cli_path = parse_override_args(argv)
    cfg_path = cli_path or path
    if cfg_path is None:
        raise ConfigError("no config file given")
    cfg = ConfigFile.from_file(cfg_path)
    cfg.merge(overrides)
    return cfg
