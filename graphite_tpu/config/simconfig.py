"""Target-topology configuration: tiles, processes, MCP/thread-spawner math.

Reference: `common/misc/config.{h,cc}`.
 - total_tiles = application_tiles + 1 (MCP) [+ num_processes thread-spawner
   tiles in FULL mode] (`config.cc:59-96`).
 - MCP lives on the last tile, owned by process 0 (`config.cc:191-193`,
   `config.h:88-89`).
 - Thread-spawner for process p is tile total_tiles-(1+num_processes-p),
   i.e. tiles application_tiles..total_tiles-2 (`config.cc:123-133,180-189`).
 - Default process→tile mapping is round-robin striping of application tiles
   (`config.cc:220-227`); mesh-aware models may override it
   (`config.cc:198-218`, `network_model.h:95`).
 - Per-tile heterogeneous core/cache types come from the `[tile] model_list`
   tuples `<num,core,l1i,l1d,l2>` with `default` placeholders
   (`config.cc:365-472`, `carbon_sim.cfg:158-176`).

In the TPU build "process" maps to *device shard*: the tile axis of the
state tensor is sharded over the ICI mesh, and the process→tile mapping
becomes the sharding layout.  The MCP/thread-spawner bookkeeping is kept for
config parity (tile counts, summary layout, trace addressing).
"""

from __future__ import annotations

import dataclasses
import enum
import re

from graphite_tpu.config.config_file import ConfigFile
from graphite_tpu.time_types import ghz_to_mhz

INVALID_TILE_ID = -1

# The four static networks (`common/network/packet_type.h:40-56`).
STATIC_NETWORK_USER = 0
STATIC_NETWORK_MEMORY = 1
STATIC_NETWORK_SYSTEM = 2
STATIC_NETWORK_DVFS = 3
NUM_STATIC_NETWORKS = 4
STATIC_NETWORK_NAMES = ("user", "memory", "system", "dvfs")


class SimulationMode(enum.Enum):
    FULL = "full"
    LITE = "lite"


@dataclasses.dataclass(frozen=True)
class TileSpec:
    """Per-tile model selection (`config.cc:447`, TileParameters)."""

    core_type: str = "simple"
    l1_icache_type: str = "T1"
    l1_dcache_type: str = "T1"
    l2_cache_type: str = "T1"


def _parse_list(text: str, delims: str) -> list[str]:
    """Split a `"<a,b>, <c,d>"`-style list on the given bracket delimiters.

    Mirrors the reference's parseList utility usage in `config.cc:392,405`.
    """
    if delims == "<>":
        return [m.group(1).strip() for m in re.finditer(r"<([^<>]*)>", text)]
    return [s.strip() for s in text.split(delims) if s.strip()]


class SimConfig:
    """The resolved target topology (reference `Config` singleton analog)."""

    def __init__(self, cfg: ConfigFile):
        self.cfg = cfg
        self.application_tiles: int = cfg.get_int("general/total_cores")
        self.num_processes: int = cfg.get_int("general/num_processes", 1)
        self.mode = SimulationMode(cfg.get_string("general/mode", "lite"))
        self.enable_core_modeling = cfg.get_bool("general/enable_core_modeling", True)
        self.enable_power_modeling = cfg.get_bool("general/enable_power_modeling", False)
        self.enable_area_modeling = cfg.get_bool("general/enable_area_modeling", False)
        self.enable_shared_mem = cfg.get_bool("general/enable_shared_mem", True)
        self.output_file = cfg.get_string("general/output_file", "sim.out")
        self.max_frequency_mhz = ghz_to_mhz(cfg.get_float("general/max_frequency", 1.0))
        self.technology_node = cfg.get_int("general/technology_node", 45)
        self.temperature = cfg.get_int("general/temperature", 300)
        self.tile_width_mm = cfg.get_float("general/tile_width", 1.0)

        if self.application_tiles <= 0:
            raise ValueError("general/total_cores must be > 0")
        if self.num_processes <= 0:
            raise ValueError("general/num_processes must be > 0")
        if self.mode == SimulationMode.LITE and self.num_processes > 1:
            raise ValueError("Use only 1 process in lite mode")  # config.cc:66-70

        # Tile-count bookkeeping (`config.cc:77-82`).
        self.total_tiles = self.application_tiles + 1  # + MCP
        if self.mode == SimulationMode.FULL:
            self.total_tiles += self.num_processes  # + thread spawners

        # Static network model types (`config.cc:474-497`).
        self.network_types: list[str] = [
            cfg.get_string("network/user", "magic"),
            cfg.get_string("network/memory", "magic"),
            "magic",  # SYSTEM is always magic (config.cc:484)
            "magic",  # DVFS is always magic (config.cc:485)
        ]

        self.tile_specs = self._parse_tile_parameters()
        self.process_to_tiles, self.tile_to_process = self._compute_tile_map()

    # --- derived ids (`config.cc:108-147`, `config.h:88-89`) --------------

    @property
    def mcp_tile_id(self) -> int:
        return self.total_tiles - 1

    def is_application_tile(self, tile_id: int) -> bool:
        return 0 <= tile_id < self.application_tiles

    def thread_spawner_tile_id(self, proc_num: int) -> int:
        if self.mode != SimulationMode.FULL:
            return INVALID_TILE_ID
        return self.total_tiles - (1 + self.num_processes - proc_num)

    def is_thread_spawner_tile(self, tile_id: int) -> bool:
        return (
            self.mode == SimulationMode.FULL
            and self.application_tiles <= tile_id < self.total_tiles - 1
        )

    # --- model_list parsing (`config.cc:365-472`) -------------------------

    def _parse_tile_parameters(self) -> list[TileSpec]:
        default = TileSpec()
        model_list = self.cfg.get_string("tile/model_list", "<default>")
        specs: list[TileSpec] = []
        for tup in _parse_list(model_list, "<>"):
            fields = [f.strip() for f in tup.split(",")]
            num = self.application_tiles
            vals = [default.core_type, default.l1_icache_type,
                    default.l1_dcache_type, default.l2_cache_type]
            for i, f in enumerate(fields):
                if f == "default" or f == "":
                    continue
                if i == 0:
                    num = int(f)
                elif i <= 4:
                    vals[i - 1] = f
                else:
                    raise ValueError(f"tile tuple has too many fields: {tup!r}")
            specs.extend(TileSpec(*vals) for _ in range(num))
            if len(specs) > self.application_tiles:
                raise ValueError(
                    f"model_list initializes {len(specs)} tiles, "
                    f"but there are only {self.application_tiles} application tiles"
                )
        if len(specs) != self.application_tiles:
            raise ValueError(
                f"model_list initializes {len(specs)} of "
                f"{self.application_tiles} application tiles"
            )
        # MCP + thread-spawner tiles get default models (`config.cc:466-471`).
        specs.extend(TileSpec() for _ in range(self.total_tiles - self.application_tiles))
        return specs

    # --- process ↔ tile mapping (`config.cc:154-228`) ---------------------

    def _compute_tile_map(self) -> tuple[list[list[int]], list[int]]:
        mapping = self._network_process_mapping()
        if mapping is None:
            # Default: round-robin striping (`config.cc:220-227`).
            mapping = [[] for _ in range(self.num_processes)]
            for t in range(self.application_tiles):
                mapping[t % self.num_processes].append(t)

        proc_to_tiles = [list(tl) for tl in mapping]
        tile_to_proc = [0] * self.total_tiles
        for p, tiles in enumerate(proc_to_tiles):
            for t in tiles:
                tile_to_proc[t] = p
        if self.mode == SimulationMode.FULL:
            # Thread-spawner tiles: one per process (`config.cc:177-189`).
            for p in range(self.num_processes):
                t = self.application_tiles + p
                tile_to_proc[t] = p
                proc_to_tiles[p].append(t)
        # MCP on the last tile, process 0 (`config.cc:191-193`).
        proc_to_tiles[0].append(self.total_tiles - 1)
        tile_to_proc[self.total_tiles - 1] = 0
        return proc_to_tiles, tile_to_proc

    def _network_process_mapping(self) -> list[list[int]] | None:
        """Mesh-aware process→tile mapping override (`config.cc:198-218`).

        emesh_hop_by_hop/atac stripe *contiguous mesh blocks* per process so
        cross-process traffic rides neighboring links; in the TPU build the
        same layout keeps neighbor `ppermute` exchanges on adjacent ICI
        devices.  Implemented in network models; queried here lazily to avoid
        an import cycle.
        """
        from graphite_tpu.models.network_emesh import (
            emesh_process_to_tile_mapping,
            is_tile_count_permissible,
        )

        for net_type in self.network_types:
            if net_type in ("emesh_hop_counter", "emesh_hop_by_hop", "atac"):
                # Mesh models require an exact w*h factorization; the
                # reference aborts at `config.cc:87-90`.
                if not is_tile_count_permissible(self.application_tiles):
                    raise ValueError(
                        f"tile count {self.application_tiles} does not factor "
                        f"into a full 2D mesh (network model {net_type!r})"
                    )
        for net_type in self.network_types:
            if net_type in ("emesh_hop_by_hop", "atac"):
                return emesh_process_to_tile_mapping(
                    self.application_tiles, self.num_processes
                )
        return None

    # --- misc -------------------------------------------------------------

    def tile_spec(self, tile_id: int) -> TileSpec:
        return self.tile_specs[tile_id]

    def process_map_hosts(self) -> list[str]:
        """[process_map] hostnames (`carbon_sim.cfg:119-139`)."""
        sec = self.cfg.section("process_map")
        hosts = []
        for p in range(self.num_processes):
            raw = sec.get(f"process{p}", '"127.0.0.1"').strip().strip('"')
            hosts.append(raw)
        return hosts
