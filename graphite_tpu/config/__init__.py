"""Config subsystem: carbon_sim.cfg-compatible parsing + target topology.

Reference: `common/config/` (INI parser, boost-spirit grammar),
`common/misc/handle_args.cc` (CLI overrides), `common/misc/config.{h,cc}`
(target-topology Config object).
"""

from graphite_tpu.config.config_file import ConfigFile, parse_override_args
from graphite_tpu.config.simconfig import SimConfig, SimulationMode, TileSpec

__all__ = [
    "ConfigFile",
    "parse_override_args",
    "SimConfig",
    "SimulationMode",
    "TileSpec",
]
