"""Compile-time memory-subsystem parameters resolved from the config.

Mirrors the constructor plumbing in
`pr_l1_pr_l2_dram_directory_msi/memory_manager.cc:50-170`: cache geometries
from `[l1_icache/<type>]`/`[l1_dcache/<type>]`/`[l2_cache/<type>]`, the
directory from `[dram_directory]` (auto-sizing per
`cache/directory_cache.cc:244-330`), DRAM from `[dram]`, memory-controller
placement per `memory_manager.cc:214-278`, and the home lookup
(`address_home_lookup.cc`, ahl_param = log2(cache_line_size)).

Everything here is hashable (tuples only) so it can ride inside the jitted
step's static EngineParams.
"""

from __future__ import annotations

import dataclasses
import math

from graphite_tpu.config.simconfig import SimConfig

# ShmemMsg modeled lengths (`memory_subsystem/shmem_msg.h:8`,
# `pr_l1_pr_l2_dram_directory_msi/shmem_msg.h:81`, `shmem_msg.cc:100-125`).
NUM_MSG_TYPE_BITS = 4
NUM_PHYSICAL_ADDRESS_BITS = 48
# DRAM timing is computed in cycles at a fixed 1 GHz (DRAM_FREQUENCY,
# `dram_perf_model.cc:80-115`), i.e. 1 cycle = 1 ns.
DRAM_FREQ_MHZ = 1000


@dataclasses.dataclass(frozen=True)
class CacheLevelParams:
    """One cache level (`carbon_sim.cfg:207-230` [l1_icache/T1] etc.)."""

    num_sets: int             # MAX across tiles (array allocation size)
    num_ways: int             # MAX across tiles
    data_access_cycles: int
    tags_access_cycles: int
    sequential: bool          # perf_model_type (parallel|sequential)
    track_miss_types: bool = False
    # per-line read/write access counters, histogram-classified when the
    # line leaves the cache (`cache/cache_line_utilization.h`; the MOSI
    # L2 controller's eviction/invalidation hook points,
    # `pr_l1_pr_l2_dram_directory_mosi/l2_cache_cntlr.cc:120`)
    track_line_utilization: bool = False
    # `replacement_policy` (`carbon_sim.cfg:213`): lru | round_robin
    # (factory `CacheReplacementPolicy::create`)
    replacement: str = "lru"
    # `num_banks` (`carbon_sim.cfg:212,223,234`): in the reference this
    # knob has NO timing effect — its only consumer is the McPAT cache
    # config (`mcpat_cache_interface.cc:226`); parsed and fed to the
    # energy model accordingly
    num_banks: int = 1
    # heterogeneous per-tile geometries (`misc/config.h:92-100` model_list
    # cache types): None = homogeneous; else int tuples of length T.  The
    # dense arrays are padded to the MAX geometry; per-tile set moduli and
    # way counts mask the engine's indexing/victim picks.
    tile_sets: "tuple | None" = None
    tile_ways: "tuple | None" = None
    tile_data_cycles: "tuple | None" = None
    tile_tags_cycles: "tuple | None" = None

    @property
    def sets_mod(self):
        """Per-tile set modulus: int (homogeneous) or np int32[T]."""
        if self.tile_sets is None:
            return self.num_sets
        import numpy as np

        return np.asarray(self.tile_sets, np.int32)

    @property
    def ways_limit(self):
        """Per-tile way count for victim masking: None or np int32[T]."""
        if self.tile_ways is None:
            return None
        import numpy as np

        return np.asarray(self.tile_ways, np.int32)

    @classmethod
    def merge(cls, per_tile: "list[CacheLevelParams]") -> "CacheLevelParams":
        """One padded level over heterogeneous per-tile configurations."""
        first = per_tile[0]
        if all(p == first for p in per_tile):
            return first
        if any(p.replacement != first.replacement for p in per_tile):
            raise NotImplementedError(
                "mixed replacement policies across tiles of one cache "
                "level are not supported (policy is compile-time)")
        if any(p.sequential != first.sequential for p in per_tile):
            raise NotImplementedError(
                "mixed perf_model_type across tiles is not supported")

        def per(vals, homog_ok=True):
            return None if homog_ok and len(set(vals)) == 1 else tuple(vals)

        sets = [p.num_sets for p in per_tile]
        ways = [p.num_ways for p in per_tile]
        data = [p.data_access_cycles for p in per_tile]
        tags = [p.tags_access_cycles for p in per_tile]
        return cls(
            num_sets=max(sets), num_ways=max(ways),
            data_access_cycles=first.data_access_cycles,
            tags_access_cycles=first.tags_access_cycles,
            sequential=first.sequential,
            track_miss_types=any(p.track_miss_types for p in per_tile),
            track_line_utilization=any(
                p.track_line_utilization for p in per_tile),
            replacement=first.replacement,
            tile_sets=per(sets), tile_ways=per(ways),
            tile_data_cycles=per(data), tile_tags_cycles=per(tags),
        )

    # CachePerfModel::getLatency (`cache_perf_model_{parallel,sequential}.h`)
    # — int when homogeneous, np int64[T] when per-tile (either broadcasts
    # through the engine's jnp cost math)
    @property
    def tags_cycles(self):
        if self.tile_tags_cycles is None:
            return self.tags_access_cycles
        import numpy as np

        return np.asarray(self.tile_tags_cycles, np.int64)

    @property
    def data_and_tags_cycles(self):
        if not self.sequential:
            # parallel tag/data: tags don't add — per-tile only when the
            # data cycles themselves vary (a 0-d array here would crash
            # the golden model's per-tile indexing)
            if self.tile_data_cycles is None:
                return self.data_access_cycles
            import numpy as np

            return np.asarray(self.tile_data_cycles, np.int64)
        if self.tile_data_cycles is None and self.tile_tags_cycles is None:
            return self.data_access_cycles + self.tags_access_cycles
        import numpy as np

        data = np.asarray(
            self.tile_data_cycles
            if self.tile_data_cycles is not None
            else self.data_access_cycles, np.int64)
        tags = np.asarray(
            self.tile_tags_cycles
            if self.tile_tags_cycles is not None
            else self.tags_access_cycles, np.int64)
        return data + tags

    # Defaults per level = the T1 configuration (`carbon_sim.cfg:207-230`)
    _DEFAULTS = {
        "l1_icache": dict(size_kb=16, assoc=4, data=1, tags=1),
        "l1_dcache": dict(size_kb=32, assoc=4, data=1, tags=1),
        "l2_cache": dict(size_kb=512, assoc=8, data=8, tags=3),
    }

    @classmethod
    def from_config(cls, cfg, section: str, line_size: int) -> "CacheLevelParams":
        level = section.split("/")[0]
        d = cls._DEFAULTS.get(level, cls._DEFAULTS["l1_dcache"])
        size_kb = cfg.get_int(f"{section}/cache_size", d["size_kb"])
        assoc = cfg.get_int(f"{section}/associativity", d["assoc"])
        num_lines = size_kb * 1024 // line_size
        num_sets = max(1, num_lines // assoc)
        if num_sets * assoc != num_lines:
            raise ValueError(
                f"[{section}] cache_size/associativity does not tile: "
                f"{num_lines} lines / {assoc} ways"
            )
        return cls(
            num_sets=num_sets,
            num_ways=assoc,
            data_access_cycles=cfg.get_int(f"{section}/data_access_time",
                                           d["data"]),
            tags_access_cycles=cfg.get_int(f"{section}/tags_access_time",
                                           d["tags"]),
            sequential=cfg.get_string(f"{section}/perf_model_type", "parallel")
            == "sequential",
            track_miss_types=cfg.get_bool(f"{section}/track_miss_types", False),
            track_line_utilization=cfg.get_bool(
                f"{section}/track_cache_line_utilization", False),
            replacement=cfg.get_string(f"{section}/replacement_policy",
                                       "lru").strip(),
            num_banks=cfg.get_int(f"{section}/num_banks", 1),
        )


def _auto_directory_access_cycles(directory_size_bytes: int) -> int:
    """`directory_cache.cc:293-330` size→cycles staircase."""
    kb = math.ceil(directory_size_bytes / 1024)
    for limit, cycles in ((16, 1), (32, 2), (64, 4), (128, 6), (256, 8),
                          (512, 10), (1024, 13), (2048, 16)):
        if kb <= limit:
            return cycles
    return 20


@dataclasses.dataclass(frozen=True)
class MemParams:
    n_tiles: int
    line_size: int
    line_bits: int            # log2(line_size)
    protocol: str             # caching_protocol/type
    l1i: CacheLevelParams
    l1d: CacheLevelParams
    l2: CacheLevelParams
    # directory slice per home tile (`[dram_directory]`)
    dir_sets: int
    dir_ways: int
    dir_access_cycles: int
    dir_type: str             # full_map | ackwise | limited_* | limitless
    max_hw_sharers: int
    limitless_trap_cycles: int
    # dram (`[dram]`)
    dram_latency_ns: int
    dram_processing_ns: int   # line_size / bandwidth + 1 (`dram_perf_model.cc:91`)
    dram_queue_type: str      # "disabled" | basic | history_list | ...
    mc_tiles: tuple           # tiles with memory controllers (home slices)
    # memory-network zero-load model (hop-counter math; contention separate)
    net_kind: str             # magic | emesh_hop_counter
    net_freq_mhz: int
    mesh_width: int
    hop_latency_cycles: int
    flit_width_bits: int
    dir_freq_mhz: int         # DIRECTORY domain frequency
    # DVFS domain ids per module for synchronization delay
    # (CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY, NETWORK_MEMORY)
    module_domains: tuple
    sync_delay_cycles: int    # [dvfs] synchronization_delay
    # engine knobs
    icache_modeling: bool
    func_mem_words: int       # functional memory size (0 = disabled)
    # full per-hop MEMORY NoC with per-port contention
    # (`[network] memory = emesh_hop_by_hop`, `carbon_sim.cfg:281-282`):
    # every coherence message — request, eviction, INV/FLUSH/WB forward,
    # ack, reply — routes through the dense hop-by-hop engine instead of
    # the zero-load hop-counter math (HopByHopParams | None)
    net_hbh: "object" = None
    # MEMORY network ATAC optical model (`[network] memory = atac`):
    # coherence messages route over clusters/hubs/waveguide with hub
    # contention on the memory NoC's own state (AtacParams | None)
    net_atac: "object" = None
    # how many requester slot-starts run per engine iteration: >1 lets a
    # record whose slots HIT the L1 complete several slots per iteration.
    # Measured A/B: a win only for hit-dominated multi-slot records —
    # miss-heavy storms (canneal) pay the repeat for nothing (~1.4x
    # slower at 64 tiles), so the default stays 1; opt in per study via
    # `[general] requester_unroll`.  PRIVATE-L2 engines only: the
    # shared-L2 engine's requester phase does not read it (its L1-only
    # hit path is already a single cheap lookup per iteration)
    requester_unroll: int = 1
    # Directory write-staging capacity PER HOME LANE (0 = disabled).
    # XLA TPU lowers a per-lane scatter on the big [T, DS, DW*SW]
    # sharers store as a FULL-ARRAY dense pass (~8 ms each at 1024
    # tiles, three per engine iteration — the coherence-storm floor,
    # PERF.md round-4 findings).  When enabled, sharers writes append
    # into per-lane [T, cap, SW] staging rows (reads overlay the latest
    # match) and flush to the big store ONCE per inner_block iterations
    # — one amortized dense pass instead of 3*inner_block.  The
    # Simulator sizes cap = writes_per_iter * inner_block (overflow-
    # impossible) and auto-enables on big directories.  Lane-local by
    # construction, so the rows shard with the directory under
    # shard_map (round 12; the old global-table form was single-device
    # only).
    dir_stage_cap: int = 0
    # Round-12 base consolidation: the three home phases read the
    # directory through ONE packed per-iteration set-row gather (entry +
    # sharers, one collective under shard_map) with pending-delta
    # forwarding between phases, and their delta plans land in ONE
    # merged scatter per store at the end of the iteration.  False
    # restores the round-11 per-phase gather/apply layout (bit-identical
    # by construction — `tools/regress.py --smoke` pins it), kept as the
    # equivalence oracle.
    base_consolidate: bool = True
    # Per-phase activity gating (round 6): each protocol phase runs under
    # its OWN scalar-predicate lax.cond whose carried operands are only
    # the small per-phase state — the big directory/sharers stores are
    # read through the existing views and written outside the conds
    # (home phases return compact per-lane delta plans; see
    # engine._cond_dir), so the conds never double-buffer them and
    # gating survives at the >= 1 GB scale where the whole-engine
    # mem_gate must stay off.  Predicates are pure functions of
    # replicated control state (mailboxes, txn, requester phase), so the
    # sharded program takes identical branches on every device with no
    # new collectives.  Simulator enables this by default; kept off here
    # so direct engine-level users see the historical ungated program.
    phase_gate: bool = False

    @property
    def req_bits(self) -> int:
        return NUM_MSG_TYPE_BITS + NUM_PHYSICAL_ADDRESS_BITS

    @property
    def rep_bits(self) -> int:
        return self.req_bits + self.line_size * 8

    @property
    def sharer_words(self) -> int:
        return (self.n_tiles + 31) // 32

    @property
    def is_mosi(self) -> bool:
        """O-state protocol (`pr_l1_pr_l2_dram_directory_mosi/`): owner
        retains dirty data on read-sharing; reads are served cache-to-cache
        from a sharer instead of DRAM."""
        return self.protocol == "pr_l1_pr_l2_dram_directory_mosi"

    @classmethod
    def from_config(cls, sc: SimConfig) -> "MemParams":
        cfg = sc.cfg
        T = sc.application_tiles
        if T > 8190 and cfg.get_string(
                "caching_protocol/type",
                "pr_l1_pr_l2_dram_directory_msi").startswith("pr_l1_pr_l2"):
            # packed directory-entry words carry owner/nsharers in
            # 13-bit fields (memory/state.py DIR_ID_BITS); the shared-L2
            # engines keep plain int32 arrays and have no such limit
            raise NotImplementedError(
                "private-L2 directory protocols support at most 8190 "
                "tiles")
        spec = sc.tile_spec(0)
        l1d_sec = f"l1_dcache/{spec.l1_dcache_type}"
        line = cfg.get_int(f"{l1d_sec}/cache_line_size", 64)
        line_bits = line.bit_length() - 1
        if 1 << line_bits != line:
            raise ValueError(f"cache_line_size {line} is not a power of 2")
        # heterogeneous per-tile cache types (`misc/config.h:92-100`,
        # `[tile] model_list`): build each tile's level config, then merge
        # into ONE padded level with per-tile set/way/timing vectors
        per_level: dict[str, list] = {"l1_icache": [], "l1_dcache": [],
                                      "l2_cache": []}
        for t in range(T):
            s = sc.tile_spec(t)
            for level, typ in (("l1_icache", s.l1_icache_type),
                               ("l1_dcache", s.l1_dcache_type),
                               ("l2_cache", s.l2_cache_type)):
                other_line = cfg.get_int(f"{level}/{typ}/cache_line_size",
                                         line)
                if other_line != line:
                    raise NotImplementedError(
                        "mixed cache_line_size across tiles is not "
                        "supported (the line is the coherence unit)")
                per_level[level].append(
                    CacheLevelParams.from_config(cfg, f"{level}/{typ}",
                                                 line))
        l1i = CacheLevelParams.merge(per_level["l1_icache"])
        l1d = CacheLevelParams.merge(per_level["l1_dcache"])
        l2 = CacheLevelParams.merge(per_level["l2_cache"])

        # --- memory controllers (`memory_manager.cc:214-278`) -------------
        num_mc_str = cfg.get_string("dram/num_controllers", "ALL")
        positions = cfg.get_string("dram/controller_positions", "").strip()
        if num_mc_str == "ALL":
            mc_tiles = tuple(range(T))
        else:
            num_mc = int(num_mc_str)
            if positions:
                mc_tiles = tuple(
                    int(x) for x in positions.replace('"', "").split(",") if x.strip()
                )
                if len(mc_tiles) != num_mc:
                    raise ValueError(
                        "dram/controller_positions length != num_controllers"
                    )
            else:
                # Even striping (NetworkModel::computeMemoryControllerPositions
                # default: evenly spaced over the tile array).
                stride = T // num_mc
                mc_tiles = tuple((i * stride) for i in range(num_mc))

        # --- directory slice sizing (`directory_cache.cc:244-264`) --------
        dir_ways = cfg.get_int("dram_directory/associativity", 16)
        entries_str = cfg.get_string("dram_directory/total_entries", "auto")
        n_slices = len(mc_tiles)
        # auto-size from the largest ACTUAL per-tile L2 (max sets x max
        # ways could pair maxima from different tiles and oversize it)
        l2_size_kb = max(
            p.num_sets * p.num_ways for p in per_level["l2_cache"]
        ) * line // 1024
        if entries_str == "auto":
            num_sets = math.ceil(
                2.0 * l2_size_kb * 1024 * T / (line * dir_ways * n_slices)
            )
            num_sets = 1 << max(0, (num_sets - 1).bit_length())  # ceil pow2
            total_entries = num_sets * dir_ways
        else:
            total_entries = int(entries_str)
        dir_sets = max(1, total_entries // dir_ways)

        dir_type = cfg.get_string("dram_directory/directory_type", "full_map")
        # Directory entry size for the access-time staircase: reference uses
        # max_hw_sharers-dependent sizes (`directory_cache.cc:50`); full_map
        # entry ~ T bits + owner + state.
        entry_bytes = max(8, sc.application_tiles // 8)
        access_str = cfg.get_string("dram_directory/access_time", "auto")
        if access_str == "auto":
            dir_access = _auto_directory_access_cycles(total_entries * entry_bytes)
        else:
            dir_access = int(access_str)

        # --- dram timing (`dram_perf_model.cc:80-115`) ---------------------
        dram_latency_ns = int(cfg.get_float("dram/latency", 100))
        bw = cfg.get_float("dram/per_controller_bandwidth", 5.0)  # GB/s == B/ns
        dram_processing_ns = int(line / bw) + 1
        dram_queue_enabled = cfg.get_bool("dram/queue_model/enabled", True)
        dram_queue_type = (
            cfg.get_string("dram/queue_model/type", "history_tree")
            if dram_queue_enabled
            else "disabled"
        )

        # --- memory network params -----------------------------------------
        from graphite_tpu.models.network_user import UserNetworkParams

        mem_kind = sc.network_types[1]
        netp = UserNetworkParams.from_config(sc, "memory")
        net_hbh = None
        net_atac = None
        if mem_kind == "emesh_hop_by_hop":
            from graphite_tpu.models.network_hop_by_hop import HopByHopParams

            net_hbh = HopByHopParams.from_config(sc, "memory")
        elif mem_kind == "atac":
            # any network model serves the MEMORY net in the reference
            # (`network.cc:21-40` model-per-net factory,
            # `carbon_sim.cfg:281-282`): coherence messages route over
            # the ATAC clusters/hubs/waveguide with hub contention on the
            # memory NoC's own state (engine mem_net_send)
            from graphite_tpu.models.network_atac import AtacParams

            net_atac = AtacParams.from_config(sc, "memory")

        # --- DVFS domains for synchronization delay ------------------------
        from graphite_tpu.models.dvfs import module_domain_index, module_freq_mhz

        modules = ("CORE", "L1_ICACHE", "L1_DCACHE", "L2_CACHE", "DIRECTORY",
                   "NETWORK_MEMORY")
        module_domains = tuple(module_domain_index(cfg, m) for m in modules)
        dir_freq_mhz = module_freq_mhz(cfg, "DIRECTORY")

        protocol = cfg.get_string(
            "caching_protocol/type", "pr_l1_pr_l2_dram_directory_msi")
        requester_unroll = cfg.get_int("general/requester_unroll", 1)
        if requester_unroll > 1 and protocol.startswith("pr_l1_sh_l2"):
            raise NotImplementedError(
                "[general] requester_unroll > 1 applies to the private-L2 "
                "engines only (the shared-L2 requester phase does not "
                "read it)")
        return cls(
            dir_freq_mhz=dir_freq_mhz,
            n_tiles=T,
            line_size=line,
            line_bits=line_bits,
            protocol=protocol,
            l1i=l1i,
            l1d=l1d,
            l2=l2,
            dir_sets=dir_sets,
            dir_ways=dir_ways,
            dir_access_cycles=dir_access,
            dir_type=dir_type,
            max_hw_sharers=cfg.get_int("dram_directory/max_hw_sharers", 64),
            limitless_trap_cycles=cfg.get_int(
                "limitless/software_trap_penalty", 200
            ),
            dram_latency_ns=dram_latency_ns,
            dram_processing_ns=dram_processing_ns,
            dram_queue_type=dram_queue_type,
            mc_tiles=mc_tiles,
            net_kind=netp.kind,
            net_freq_mhz=netp.freq_mhz,
            mesh_width=netp.mesh_width,
            hop_latency_cycles=netp.hop_latency_cycles,
            flit_width_bits=netp.flit_width_bits,
            net_hbh=net_hbh,
            net_atac=net_atac,
            module_domains=module_domains,
            sync_delay_cycles=cfg.get_int("dvfs/synchronization_delay", 2),
            icache_modeling=cfg.get_bool("general/enable_icache_modeling", False),
            func_mem_words=cfg.get_int("general/functional_memory_kb", 256) * 256,
            requester_unroll=requester_unroll,
            base_consolidate=cfg.get_bool("general/base_consolidate",
                                          True),
        )

    def sync_cycles(self, module_a: int, module_b: int) -> int:
        """`Cache::getSynchronizationDelay` (`cache.cc:559-567`): the [dvfs]
        synchronization_delay when the two modules sit in different DVFS
        domains, else 0.  Module indices follow `module_domains` order."""
        if self.module_domains[module_a] == self.module_domains[module_b]:
            return 0
        return self.sync_delay_cycles
