"""The vectorized MSI dram-directory protocol engine.

One `memory_engine_step` advances every tile's memory machinery by one
subquantum iteration.  It is the TPU-native fusion of three reference code
paths that each ran on their own host thread:

 - the app thread's `L1CacheCntlr::processMemOpFromCore` →
   `L2CacheCntlr::processShmemRequestFromL1Cache` miss path
   (`l1_cache_cntlr.cc:90-180`, `l2_cache_cntlr.cc:181-292`);
 - the home tile's sim thread running the directory FSM
   (`dram_directory_cntlr.cc:44-559`);
 - every other tile's sim thread serving INV/FLUSH/WB requests
   (`l2_cache_cntlr.cc:295-503`).

Concurrency discipline (replaces locks + semaphores + TCP):
 - each tile lane owns its own row of every cache tensor and at most one
   mailbox cell per matrix per iteration, so scatters never collide;
 - a home tile's fan-out (invalidation multicast) is a dense outer-product
   write into the FWD matrix, of which the home owns a full column (it has
   one active transaction at a time — the vectorized form of the
   per-address request queue serialization in `dram_directory_cntlr.cc`);
 - sharers and homes consume one incoming message per iteration (earliest
   timestamp first), which makes the engine deterministic — the reference's
   arrival-order FIFO is host-timing dependent.

Timing follows the reference exactly where stated (cache access cycles,
synchronization delays at DVFS-domain crossings, directory access cycles,
DRAM latency + bandwidth serialization, network zero-load + serialization);
simulated time rides in the messages, never in a global clock.

Known divergences (documented for the parity harness):
 - a home services one transaction at a time even across different
   addresses; sim-time is message-carried so this only serializes *wall*
   progress, plus a same-address completion floor mirrors the reference's
   per-address queue (`processNextReqFromL2Cache`);
 - directory NULLIFY picks the min-sharer victim of the set without the
   "not in request queue" exclusion (our serialization makes it moot);
 - DRAM queue-model contention is layered on separately (queue_models).

Directory schemes (`directory_schemes/directory_entry_*.cc`): all five are
supported — full_map, limited_no_broadcast (capacity-displacement INV of one
tracked sharer), ackwise / limited_broadcast (broadcast INV sweeps on
overflowed entries; acks awaited only from true holders), limitless
(software-trap penalty on overflowed entries).  The sharers bitvector stays
exact ground truth in all schemes — the schemes differ in *which messages
travel* and *what they cost*, which is what the timing model observes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.intmath import nn_div, nn_mod

from graphite_tpu.memory import cache_array as ca
from graphite_tpu.memory.cache_array import (
    INVALID, MODIFIED, OWNED, SHARED, state_readable, state_writable,
)
from graphite_tpu.memory.params import MemParams
from graphite_tpu.memory.state import (
    DIR_ID_BITS, DIR_MODIFIED, DIR_NSH_SHIFT, DIR_OWNED, DIR_OWNER_SHIFT,
    DIR_SHARED, DIR_STATE_SHIFT, DIR_TAG_BITS, DIR_UNCACHED,
    MOD_CORE, MOD_DIR, MOD_L1D, MOD_L1I, MOD_L2, MOD_NET_MEM,
    MSG_EX_REP, MSG_EX_REQ, MSG_FLUSH_REP, MSG_FLUSH_REQ, MSG_INV_REP,
    MSG_INV_REQ, MSG_NONE, MSG_NULLIFY, MSG_SH_REP, MSG_SH_REQ, MSG_WB_REP,
    MSG_WB_REQ,
    MT_EVICTED, MT_FETCHED, MT_INVALIDATED,
    PHASE_IDLE, PHASE_WAIT_REPLY,
    MemState,
)
from graphite_tpu.parallel.px import IDENT, ParallelCtx
from graphite_tpu.time_types import cycles_to_ps
from graphite_tpu.trace.schema import (
    FLAG_CHECK, FLAG_MEM0_VALID, FLAG_MEM0_WRITE, FLAG_MEM1_VALID,
    FLAG_MEM1_WRITE, Op,
)

I64 = jnp.int64
U32 = jnp.uint32
FAR = 2**62  # python int: folds to an inline literal, never a device-constant buffer


# --------------------------------------------------------------------------
# small helpers


def _bit_word(idx):
    # idx is a tile id (>= 0 at every call site): truncating div/rem
    return (nn_div(idx, 32).astype(jnp.int32),
            nn_mod(idx, 32).astype(jnp.uint32))


def set_bit(words: jax.Array, idx: jax.Array, mask: jax.Array) -> jax.Array:
    """words[t, idx[t]//64] |= 1 << idx%64 where mask; words is [T, SW]."""
    T = words.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    w, b = _bit_word(idx)
    cur = words[tiles, w]
    new = cur | (jnp.uint32(1) << b)
    return words.at[tiles, w].set(jnp.where(mask, new, cur))


def clear_bit(words: jax.Array, idx: jax.Array, mask: jax.Array) -> jax.Array:
    T = words.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    w, b = _bit_word(idx)
    cur = words[tiles, w]
    new = cur & ~(jnp.uint32(1) << b)
    return words.at[tiles, w].set(jnp.where(mask, new, cur))


def test_bit(words: jax.Array, idx: jax.Array) -> jax.Array:
    T = words.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    w, b = _bit_word(idx)
    return ((words[tiles, w] >> b) & jnp.uint32(1)) != 0


def popcount(words: jax.Array) -> jax.Array:
    """[T, SW] → int32[T]."""
    return jax.lax.population_count(words).sum(axis=1).astype(jnp.int32)


def lowest_sharer(words: jax.Array) -> jax.Array:
    """Lowest set bit index per row ([T, SW] → int32[T], -1 when empty).

    The deterministic form of `DirectoryEntry::getOneSharer` (the reference
    returns an arbitrary list member)."""
    nonzero = words != 0
    w_idx = jnp.argmax(nonzero, axis=1).astype(jnp.int32)
    any_bit = nonzero.any(axis=1)
    tiles = np.arange(words.shape[0], dtype=np.int32)
    w = words[tiles, w_idx]
    low = w & (~w + jnp.uint32(1))
    bit = jax.lax.population_count(low - jnp.uint32(1)).astype(jnp.int32)
    return jnp.where(any_bit, w_idx * 32 + bit, -1)


# packed directory-entry word accessors (layout: memory/state.py).  All
# pure bit math on int64 — unpacking is free ALU inside fusions.
_TAG_MASK = (1 << DIR_TAG_BITS) - 1
_ID_MASK = (1 << DIR_ID_BITS) - 1


def dir_tag(word):
    return (word & _TAG_MASK).astype(jnp.int32) - 1


def dir_state(word):
    return ((word >> DIR_STATE_SHIFT) & 7).astype(jnp.uint8)


def dir_owner(word):
    return ((word >> DIR_OWNER_SHIFT) & _ID_MASK).astype(jnp.int32) - 1


def dir_nsh(word):
    return ((word >> DIR_NSH_SHIFT) & _ID_MASK).astype(jnp.int32)


def _dir_set_field(word, val, shift, mask):
    return (word & ~(mask << shift)) | ((val.astype(I64) & mask) << shift)


def unpack_sharers(words: jax.Array, n: int) -> jax.Array:
    """[T, SW] uint32 → bool[T, n] (bit s of row t)."""
    s = np.arange(n)
    w = (s // 32).astype(np.int32)
    b = (s % 32).astype(np.uint32)
    return ((words[:, w] >> b[None, :]) & jnp.uint32(1)) != 0


def _row_earliest(cell_type: jax.Array, cell_time: jax.Array):
    """Earliest nonzero cell per row: (col int32[T], found bool[T]).

    Deterministic total order on (time, column) — the reference's
    arrival-order processing is host-timing dependent; this is not.
    """
    C = cell_type.shape[1]
    key = jnp.where(
        cell_type != MSG_NONE,
        cell_time * C + np.arange(C, dtype=np.int64)[None, :],
        FAR,
    )
    col = jnp.argmin(key, axis=1).astype(jnp.int32)
    found = jnp.take_along_axis(key, col[:, None].astype(jnp.int64), axis=1)[:, 0] < FAR
    return col, found


def _req_earliest(mail):
    """Earliest pending request per HOME over the per-requester lanes:
    (requester int32[T], found bool[T]).

    The compact form of the old [T, T] row scan: key = time * T +
    requester, segment-min'd into home buckets — the SAME deterministic
    total order `_row_earliest` used on the matrix, so the pop order is
    bit-identical to the round-11 layout."""
    T = mail.req_type.shape[0]
    r = np.arange(T, dtype=np.int64)
    live = mail.req_type != MSG_NONE
    key = jnp.where(live, mail.req_time * T + r, FAR)
    best = (
        jnp.full((T + 1,), FAR, I64)
        .at[jnp.where(live, mail.req_home, T)]
        .min(key)
    )[:T]
    found = best < FAR
    col = jnp.where(found, nn_mod(best, T), 0).astype(jnp.int32)
    return col, found


def _req_consume(mail, use_pop, r_col):
    """Clear the popped requester lanes (each home pops at most one)."""
    T = mail.req_type.shape[0]
    r = np.arange(T, dtype=np.int32)
    live = mail.req_type != MSG_NONE
    popped = live & use_pop[mail.req_home] & (r_col[mail.req_home] == r)
    return mail.replace(req_type=jnp.where(popped, MSG_NONE,
                                           mail.req_type))


def mem_net_latency_ps(mp: MemParams, src, dst, bits: int, enabled):
    """MEMORY-network zero-load latency (`network_model_emesh_hop_counter.cc`
    + receive serialization `network_model.cc:119-149`; ATAC zero-load
    path costs under `memory = atac`)."""
    src = jnp.asarray(src)
    dst = jnp.asarray(dst)
    if mp.net_kind == "magic":
        cycles = jnp.where(enabled, jnp.ones_like(src, I64), 0)
        return cycles_to_ps(cycles, mp.net_freq_mhz)
    if mp.net_atac is not None:
        from graphite_tpu.models.network_atac import atac_zeroload_ps

        return atac_zeroload_ps(mp.net_atac, src, dst, bits, enabled)
    w = mp.mesh_width
    hops = (jnp.abs(nn_mod(src, w) - nn_mod(dst, w))
            + jnp.abs(nn_div(src, w) - nn_div(dst, w)))
    flits = (bits + mp.flit_width_bits - 1) // mp.flit_width_bits
    cycles = hops.astype(I64) * mp.hop_latency_cycles + jnp.where(
        src == dst, 0, flits
    )
    cycles = jnp.where(enabled, cycles, 0)
    return cycles_to_ps(cycles, mp.net_freq_mhz)


def mem_net_send(mp: MemParams, noc, src, dst, bits, t0_ps, mask, enabled):
    """Unicast a coherence message through the MEMORY network.

    Returns (noc, arrival_ps[T]).  With `[network] memory =
    emesh_hop_by_hop` (mp.net_hbh) the packet routes through the dense
    per-hop contention engine on the memory NoC's own port-queue state
    (`MemState.noc`); with `memory = atac` (mp.net_atac) it routes over
    the ATAC clusters/hubs/waveguide with hub contention on the memory
    NoC's own AtacState — the analog of the reference routing every
    ShmemMsg through the configured memory network model (any-model-per-
    net factory `network.cc:21-40`, `carbon_sim.cfg:281-282`).
    Otherwise zero-load hop-counter/magic math (state untouched)."""
    if mp.net_atac is not None:
        from graphite_tpu.models.network_atac import route_atac

        bits = jnp.broadcast_to(jnp.asarray(bits, I64), jnp.shape(src))
        noc, arrival_ps, _ = route_atac(
            mp.net_atac, noc, src, dst, bits, t0_ps, mask, enabled)
        return noc, arrival_ps
    if mp.net_hbh is None:
        return noc, t0_ps + mem_net_latency_ps(mp, src, dst, bits, enabled)
    from graphite_tpu.models.network_hop_by_hop import route_hop_by_hop

    bits = jnp.broadcast_to(jnp.asarray(bits, I64), jnp.shape(src))
    noc, arrival_ps, _, _ = route_hop_by_hop(
        mp.net_hbh, noc, src, dst, bits, t0_ps, mask, enabled)
    return noc, arrival_ps


def mem_net_fanout(mp: MemParams, noc, send_hs, bits: int, t0_ps, enabled):
    """A home's INV/FLUSH/WB multicast through the MEMORY network.

    send_hs: bool[T(home), T(target)]; t0_ps: int64[T(home)].  Returns
    (noc, arrival_ps[T, T]).

    The reference (broadcast tree disabled, the default
    `carbon_sim.cfg:304`) sends one unicast per target through the
    memory model.  Dense per-pair routing would cost [T^2, h, w] grids,
    so under hop_by_hop the fan-out charges the dominant contention
    exactly and approximates the rest:
     - the home's INJECT port serializes the k copies: each copy pays
       the inject queue delay plus its rank among the targets (by tile
       id, deterministic) times its flit count, and the port commits
       k * flits of occupancy;
     - each copy then pays the hop-by-hop ZERO-LOAD path cost (router +
       per-hop router+link + receive serialization); intermediate-hop
       queue contention for fan-out copies is NOT charged (documented
       approximation — under the serialized oracle contract those queues
       are empty, so serialized workloads remain exact).
    """
    T = mp.n_tiles
    src = np.arange(T, dtype=np.int32)[:, None]
    dst = np.arange(T, dtype=np.int32)[None, :]
    if mp.net_atac is not None:
        # ATAC multicast (`network_model_atac.cc:372-500` broadcast over
        # the waveguide): the home's SEND HUB serializes its ONet copies
        # (one queue charge of k_onet * flits, delay applied to ONet
        # copies), every copy pays its rank (by tile id) times flits at
        # the source, then its zero-load path — the same
        # dominant-contention-exact / intermediate-hops-approximate
        # contract as the hop-by-hop fan-out below, mirrored by the
        # oracle (`_AtacNet.fanout`)
        from graphite_tpu.models import queue_models as qm
        from graphite_tpu.models.network_atac import (
            _cluster_of, atac_use_onet, atac_zeroload_ps,
        )
        from graphite_tpu.time_types import ps_to_cycles

        p = mp.net_atac
        zl = atac_zeroload_ps(p, src, dst, bits, enabled)       # [T, T]
        flits = max(1, (bits + p.flit_width_bits - 1) // p.flit_width_bits)
        onet_pair = atac_use_onet(p, src, dst)                  # [T, T]
        k_onet = (send_hs & onet_pair).sum(axis=1, dtype=I64)
        fan = send_hs.any(axis=1)
        t0_cyc = ps_to_cycles(t0_ps, p.freq_mhz)
        if p.contention_enabled:
            go = fan & (k_onet > 0) & jnp.asarray(enabled, bool)
            home = np.arange(T, dtype=np.int32)
            qid = jnp.where(go, _cluster_of(p, home),
                            2 * p.n_clusters).astype(jnp.int32)
            queues, hub_delay = qm.scatter_queue_delay(
                p.queue, noc.hub_queues, qid, t0_cyc, k_onet * flits, go)
            noc = noc.replace(hub_queues=queues)
        else:
            hub_delay = jnp.zeros(T, I64)
        rank = jnp.cumsum(send_hs.astype(I64), axis=1) - 1
        extra_cyc = rank * flits + jnp.where(onet_pair, hub_delay[:, None],
                                             0)
        extra_cyc = jnp.where(jnp.asarray(enabled, bool), extra_cyc, 0)
        arrival = t0_ps[:, None] + zl + cycles_to_ps(extra_cyc, p.freq_mhz)
        return noc, arrival
    if mp.net_hbh is None:
        lat = mem_net_latency_ps(mp, src, dst, bits, enabled)
        return noc, t0_ps[:, None] + lat
    from graphite_tpu.models import queue_models as qm
    from graphite_tpu.models.network_hop_by_hop import (
        NUM_PORTS, PORT_INJECT,
    )
    from graphite_tpu.time_types import ps_to_cycles

    p = mp.net_hbh
    w = p.mesh_width
    flits = max(1, (bits + p.flit_width_bits - 1) // p.flit_width_bits)
    hops = (jnp.abs(nn_mod(src, w) - nn_mod(dst, w))
            + jnp.abs(nn_div(src, w) - nn_div(dst, w))).astype(I64)
    step = p.router_delay + p.link_delay
    zl = p.router_delay + (hops + 1) * step + jnp.where(
        src == dst, 0, flits)
    fan = send_hs.any(axis=1)
    k = send_hs.sum(axis=1, dtype=I64)
    t0_cyc = ps_to_cycles(t0_ps, p.freq_mhz)
    if p.contention_enabled:
        qid = (np.arange(T, dtype=np.int32) * NUM_PORTS + PORT_INJECT)
        queues, inj_delay = qm.scatter_queue_delay(
            p.queue, noc.queues, qid, t0_cyc, k * flits,
            fan & jnp.asarray(enabled, bool))
        noc = noc.replace(queues=queues)
    else:
        inj_delay = jnp.zeros(T, I64)
    rank = (jnp.cumsum(send_hs.astype(I64), axis=1) - 1)
    cyc = zl + inj_delay[:, None] + rank * flits
    cyc = jnp.where(jnp.asarray(enabled, bool), cyc, 0)
    arrival = t0_ps[:, None] + cycles_to_ps(cyc, p.freq_mhz)
    return noc, arrival


# --------------------------------------------------------------------------
# L2 cache-line utilization (`cache/cache_line_utilization.h`: per-line
# read/write access counters; harvested at the MOSI L2 controller's
# eviction/invalidation hook points, `mosi/l2_cache_cntlr.cc:120`).
# Packed uint32 per line: low 16 bits = reads, high 16 = writes
# (saturating).  Classified into a log2 histogram (0, 1, 2-3, ..., >=64)
# when the line leaves the L2.


def _util_inc(cur, is_write, mask):
    """Saturating read/write increment of packed util counters [T]."""
    inc = jnp.where(is_write, jnp.uint32(1) << 16, jnp.uint32(1))
    fld = jnp.where(is_write, cur >> 16, cur & jnp.uint32(0xFFFF))
    return jnp.where(mask & (fld < 0xFFFF), cur + inc, cur)


def _util_classify(counters, util_val, mask, enabled):
    """Histogram a departing line's packed util counter."""
    rd = (util_val & jnp.uint32(0xFFFF)).astype(I64)
    wr = (util_val >> 16).astype(I64)
    total = (rd + wr).astype(jnp.int32)
    bucket = jnp.minimum(7, 32 - jax.lax.clz(total)).astype(jnp.int32)
    m = mask & jnp.asarray(enabled, bool)
    tiles = np.arange(util_val.shape[0], dtype=np.int32)
    return counters.replace(
        line_util_hist=counters.line_util_hist.at[tiles, bucket].add(
            m.astype(I64), unique_indices=True),
        line_util_reads=counters.line_util_reads + jnp.where(m, rd, 0),
        line_util_writes=counters.line_util_writes + jnp.where(m, wr, 0))


def _util_row_local(l2_util, line_l, sets_mod_l):
    """This device's [Tl, W2] util row at each local lane's L2 set (the
    cross-device exchange happens via _rows_exchange at the call sites)."""
    Tl = l2_util.shape[0]
    lt = np.arange(Tl, dtype=np.int32)
    sets_l = nn_mod(line_l, jnp.asarray(sets_mod_l)).astype(jnp.int32)
    return l2_util[lt, sets_l]


def _util_scatter(px: ParallelCtx, l2_util, line, sets_mod, way, cur, new):
    """Apply per-lane packed-counter updates block-locally (add-a-delta,
    unique rows)."""
    sets = nn_mod(line, jnp.asarray(sets_mod)).astype(jnp.int32)
    sets_l, way_l, cur_l, new_l = px.lo((sets, way, cur, new))
    Tl = l2_util.shape[0]
    lt = np.arange(Tl, dtype=np.int32)
    return l2_util.at[lt, sets_l, way_l].add(
        new_l - cur_l, unique_indices=True, indices_are_sorted=True)


def _mt_bit(line):
    """Hash bucket of a line in the miss-type bitmaps (MT_BITS buckets)."""
    from graphite_tpu.memory.state import MT_BITS

    h = (line.astype(jnp.uint32) & jnp.uint32(MT_BITS - 1))
    return (h // 32).astype(jnp.int32), (h % 32).astype(jnp.uint32)


def _mt_test(mt, row: int, line):
    T = mt.shape[0]
    tiles = jnp.arange(T, dtype=jnp.int32)
    w, b = _mt_bit(line)
    return ((mt[tiles, row, w] >> b) & jnp.uint32(1)) != 0


def _mt_update(mt, row: int, line, mask, set_bit_val: bool):
    """Set or clear the line's bucket bit in bitmap `row` where mask
    (delta-add scatter: per-lane rows are unique).  Operates on whatever
    block of tile rows `mt` holds — sharded callers pass block-local
    line/mask."""
    T = mt.shape[0]
    tiles = jnp.arange(T, dtype=jnp.int32)
    w, b = _mt_bit(line)
    cur = mt[tiles, row, w]
    new = (cur | (jnp.uint32(1) << b)) if set_bit_val else (
        cur & ~(jnp.uint32(1) << b))
    return mt.at[tiles, row, w].add(
        jnp.where(mask, new - cur, jnp.uint32(0)),
        unique_indices=True, indices_are_sorted=True)


def _mt_same_bucket(a, b):
    """Do two lines hash to the same miss-type bucket?  (Pure math — lets
    the sharded path fold a just-applied local bitmap write into an
    already-exchanged pre-write test bit.)"""
    from graphite_tpu.memory.state import MT_BITS

    m = jnp.uint32(MT_BITS - 1)
    return (a.astype(jnp.uint32) & m) == (b.astype(jnp.uint32) & m)


# --------------------------------------------------------------------------
# shard_map phase-exchange helpers: block-local row gathers packed into one
# all-gather per engine phase (identity under the single-device px) — see
# parallel/px.py for the exchange design.


def _row_pack(row: "ca.CacheRow"):
    """The compact exchanged form of a gathered cache row."""
    return row.meta0, row.sets


def _rows_exchange(px: ParallelCtx, local_rows, extra=()):
    """Exchange locally gathered CacheRows (+ any extra per-lane fields)
    to full tile width in ONE packed collective (identity single-device)."""
    if not px.sharded:
        return tuple(local_rows), tuple(extra)
    packed = tuple(_row_pack(r) for r in local_rows)
    out = px.ag((packed, tuple(extra)))
    rows = tuple(ca.row_from_meta(m, s) for (m, s) in out[0])
    return rows, out[1]


class _DirSetView:
    """Each home lane's directory SET at `line`, behind one interface for
    both programs:

     - single-device (IDENT px): ONE lazy [T, DW] packed-word row gather
       serves the lookup, the allocation rows, and every entry() field
       (unpacked with free ALU bit math inside the consuming fusions),
       plus the lazy sharers-row gather;
     - sharded px: the whole set's rows are gathered block-locally and
       exchanged in ONE collective up front; lookup/entry() are then
       replicated take_along_axis selections (a second exchange for the
       way-dependent entry read would double the phase's collectives).
    """

    def __init__(self, px: ParallelCtx, d: "DirectoryArrays", line, mp):
        self.sets = nn_mod(line, mp.dir_sets).astype(jnp.int32)
        self._line = line
        self._sharded = px.sharded
        self._dw = d.entry.shape[2]
        if px.sharded:
            line_l = px.lo(line)
            Tl = d.entry.shape[0]
            lt = np.arange(Tl, dtype=np.int32)
            sets_l = nn_mod(line_l, mp.dir_sets).astype(jnp.int32)
            self._word_r, self._sharers_r = px.ag((
                d.entry[lt, sets_l], d.sharers[lt, sets_l]))
        else:
            self._d = d
            T = d.entry.shape[0]
            self._tiles = np.arange(T, dtype=np.int32)
            self._word_r = None
            self._sharers_r = None

    def _word_row(self):
        """The set's packed entry words, [T, DW]."""
        if self._word_r is None:
            self._word_r = self._d.entry[self._tiles, self.sets]
        return self._word_r

    def rows(self):
        """(tag_row, nsharers_row) — the [T, DW] set rows the allocation
        decisions (free way / min-sharer victim) need."""
        row = self._word_row()
        return dir_tag(row), dir_nsh(row)

    def lookup(self):
        """(found, way) of `line` within the set."""
        tag_row = dir_tag(self._word_row())
        way_hits = tag_row == self._line[:, None]
        found = way_hits.any(axis=1)
        way = jnp.argmax(way_hits, axis=1).astype(jnp.int32)
        return found, way

    def _sharers_row(self):
        """The set's sharer words, [T, DW*SW] (stored set-row-major)."""
        if self._sharers_r is None:
            self._sharers_r = self._d.sharers[self._tiles, self.sets]
        return self._sharers_r

    def entry(self, way):
        """(tags, dstate, owner, sharers, nsh) at `way`."""
        row = self._sharers_row()
        row3 = row.reshape(row.shape[0], self._dw, -1)
        sharers = jnp.take_along_axis(row3, way[:, None, None], axis=1)[:, 0]
        word = jnp.take_along_axis(self._word_row(), way[:, None],
                                   axis=1)[:, 0]
        if not self._sharded and self._d.skey is not None:
            # staged writes since the last flush supersede the big store
            sharers = _stage_overlay(self._d, self.sets, way, sharers)
        return (dir_tag(word), dir_state(word), dir_owner(word),
                sharers, dir_nsh(word))


@dataclasses.dataclass(frozen=True)
class RecView:
    """Current trace record fields needed by the memory engine (all [T])."""

    op: jax.Array
    flags: jax.Array
    pc: jax.Array
    addr0: jax.Array
    addr1: jax.Array
    aux0: jax.Array
    aux1: jax.Array


@struct.dataclass
class MemStepOut:
    ms: MemState
    mem_complete: jax.Array  # bool[T] all slots of current record done
    acc_ps: jax.Array        # int64[T] memory latency of the record so far
    slot_lat_ps: jax.Array   # int64[T, 3] per-slot latency [icache, m0, m1]
    progress: jax.Array      # int32[] events this iteration
    # miss-service completions THIS call (fills consumed by phase 6).
    # A whole miss transaction can start and fill within one engine call
    # (message timestamps model the latency, not iteration count), so
    # callers observing only the entry/exit requester phase undercount;
    # these carry the per-call events for the round-21 latency
    # histograms.  fill_lat_ps is the filled slot's end-to-end latency
    # (lookup + protocol round trip — the same value the slot_lat_ps
    # algebra records).  Over a drained run, total fills == total miss
    # starts (l2_misses for `msi`, the three L1 miss counters for
    # `pr_l1_sh_l2*`) — the conservation pairing obs/hist checks.
    # Opt-in via `fill_events=True`: None (the default) contributes no
    # pytree leaves and no equations, so hist-off programs keep lowering
    # the historical trace byte-identically (the `hist-off` audit lint
    # and the pre-existing PROGRAMS.lock fingerprints).
    fill_now: "jax.Array | None" = None      # bool[T] miss completed this call
    fill_lat_ps: "jax.Array | None" = None   # int64[T] its slot latency


def slots_present(mp: MemParams, rec: "RecView", enabled) -> jax.Array:
    """bool[T, 3]: which of [icache, mem0, mem1] this record carries.

    icache fetches for static/branch records (op < DYNAMIC_MISC) and
    compressed BBLOCK runs (one fetch for the block's first line — a
    documented approximation); dynamic ops (15-19) commit without waiting
    on mem_ok, so they get no fetch slot."""
    is_instr = (rec.op < 15) | (rec.op == int(Op.BBLOCK))
    icache_present = (
        jnp.asarray(mp.icache_modeling) & jnp.asarray(enabled) & is_instr
    )
    mem0 = (rec.flags & FLAG_MEM0_VALID) != 0
    mem1 = (rec.flags & FLAG_MEM1_VALID) != 0
    return jnp.stack([icache_present, mem0, mem1], axis=1)


def next_present_slot(present: jax.Array, slot: jax.Array) -> jax.Array:
    """First present slot index >= slot, else 3."""
    k = np.arange(3)[None, :]
    cand = jnp.where(present & (k >= slot[:, None]), k, 3)
    return cand.min(axis=1).astype(jnp.int32)


def protocol_live(ms, *extra) -> jax.Array:
    """Any protocol state outstanding (messages, transactions, waiting
    requesters)?  Shared by both engines so the mem_gate's wake-up
    condition cannot drift between them; engine-specific terms (e.g. the
    shared-L2 engine's in-flight DRAM fetches) come in via *extra."""
    mail = ms.mail
    live = (
        (mail.req_type != MSG_NONE).any()
        | (mail.evict_type != MSG_NONE).any()
        | (mail.fwd_type != MSG_NONE).any()
        | (mail.ack_type != MSG_NONE).any()
        | (mail.rep_type != MSG_NONE).any()
        | ms.txn.active.any()
        | ms.txn.saved_valid.any()
        | (ms.req.phase != PHASE_IDLE).any()
    )
    for term in extra:
        live = live | term
    return live


# phase order of the private-L2 engine's skip vector (MemState.phase_skips)
PHASE_NAMES = ("requester", "home_evict", "home_start", "sharer",
               "home_finish", "requester_fill")


def dir_store_avals(ms) -> tuple:
    """(shape, dtype) signatures of the big directory stores — the
    [T, DS, DW] packed entry words and [T, DS, DW*SW] sharers bitvector
    — that a gated home phase must NEVER return as lax.cond outputs
    (they'd be double-buffered; the `_DirAcc` delta plan exists so the
    cond carries compact per-lane deltas instead).  The program
    auditor's cond-payload rule (analysis/rules.py) enforces this for
    every cond in the lowered program."""
    d = ms.directory
    return (
        (tuple(d.entry.shape), str(d.entry.dtype)),
        (tuple(d.sharers.shape), str(d.sharers.dtype)),
    )


def mem_idle_out(mp: MemParams, ms, rec: "RecView", enabled,
                 fill_events: bool = False) -> MemStepOut:
    """The engine step's result when there is provably nothing to do —
    no lane's record carries memory slots and no protocol state is live
    (`ms.live`).  Lets the caller skip the whole engine under a lax.cond
    on compute-only iterations (the engine costs ~600 us/iteration in
    small kernels; see PERF.md).  A whole-engine skip counts as a skip of
    every phase in the gate-observability vector."""
    present = slots_present(mp, rec, enabled)
    final_slot = next_present_slot(present, ms.req.slot)
    mem_complete = (ms.req.phase == PHASE_IDLE) & (final_slot >= 3)
    if ms.phase_skips is not None:
        ms = ms.replace(phase_skips=ms.phase_skips + 1)
    T = ms.req.phase.shape[0]
    return MemStepOut(
        ms=ms, mem_complete=mem_complete, acc_ps=ms.req.acc_ps,
        slot_lat_ps=ms.req.slot_lat_ps,
        progress=jnp.zeros((), jnp.int32),
        fill_now=jnp.zeros((T,), jnp.bool_) if fill_events else None,
        fill_lat_ps=jnp.zeros((T,), I64) if fill_events else None)


# --------------------------------------------------------------------------
# directory-entry helpers (structured [T, DS, DW(, SW)] arrays — a flat
# entry-major repack was built and measured 1.6x slower; see PERF.md
# round-3 findings and the DirectoryArrays docstring).
#
# Sharers write-staging (dir_stage_cap > 0): XLA TPU lowers every
# per-lane scatter on the big [T, DS, DW*SW] sharers store as a
# FULL-ARRAY dense pass (measured ~8 ms each at 1024 tiles, three per
# iteration — the coherence-storm floor, PERF.md round-4 findings; the
# same writes on the small [T, DS, DW] entry arrays cost little and stay
# direct).  Staged mode: writes land in the small per-LANE (skey, sval)
# rows (`_stage_put`); the engine's sharers reads overlay them
# (`_stage_overlay_rows`); `dir_stage_flush` applies the rows to the big
# store once per inner_block iterations (engine/step._quantum_loop), one
# amortized dense pass instead of 3*inner_block.
#
# Round-12 layout: the table is [T, c] per home lane (c = writes_per_
# iter * inner_block), not one global [C = wpi * T * inner_block] list.
# Every directory write is home-lane-local, so a put is a single
# append-at-cursor scatter — the old layout's [T, C] unique-key dedup
# scan (trip product T * wpi * T * inner_block at 1024 tiles) is gone,
# and every staging operation's cost now scales with the per-lane
# staged-entry count.  Keys may repeat within a lane row; reads take
# the LATEST slot and the flush applies only each key's last slot, so
# the big-store values are bit-identical to the unique-key layout.
# Lane-locality also makes the table block-local under shard_map (each
# device stages its own home rows), which is what lets big sharded
# directories stage at all — the standing "dir_stage is single-device"
# restriction fell with it.  Reference hot path this lifts:
# `dram_directory_cntlr.cc:44-559` per-message directory updates.


def _stage_key(d, sets, way, dw=None):
    """Within-lane staging key of a (set, way) entry.  `dw` overrides
    the way count when the entry store is detached from the caller's
    cond (the consolidated home phases)."""
    DW = d.entry.shape[2] if dw is None else dw
    return sets * DW + way


def _stage_put(d, sets, way, mask, new_sh, dw=None):
    """Append a masked per-lane sharers write at each lane's cursor.

    ONE out-of-bounds-dropping scatter per table array — no dedup scan,
    no cond.  Masked-off lanes target slot c (dropped); capacity
    c = writes_per_iter * inner_block makes mid-block overflow
    impossible, so in-bounds appends never collide."""
    C = d.skey.shape[1]
    T = d.skey.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    key = _stage_key(d, sets, way, dw)
    pos = jnp.where(mask, d.sn, C)
    return d.replace(
        skey=d.skey.at[tiles, pos].set(key, mode="drop",
                                       unique_indices=True),
        sval=d.sval.at[tiles, pos].set(new_sh, mode="drop",
                                       unique_indices=True),
        sn=d.sn + mask.astype(jnp.int32))


def _stage_overlay(d, sets, way, sharers):
    """The latest staged value of each lane's (set, way) entry, if any,
    else the given big-store value ([*, SW]).  Scans only the lane's own
    [c] staging row."""
    C = d.skey.shape[1]
    key = _stage_key(d, sets, way)
    m = (d.skey >= 0) & (d.skey == key[:, None])   # [T, c]
    rank = np.arange(1, C + 1, dtype=np.int32)
    best = jnp.max(jnp.where(m, rank, 0), axis=1)  # latest slot + 1
    found = best > 0
    c = jnp.where(found, best - 1, 0)
    T = d.skey.shape[0]
    return jnp.where(found[:, None],
                     d.sval[np.arange(T, dtype=np.int32), c], sharers)


def _stage_overlay_rows(d, sets, rows):
    """Overlay each lane's staged writes onto gathered sharers SET rows.

    `sets` int32[T, K] (the gathered rows' set indices), `rows`
    uint32[T, K, DW*SW].  For every way of every gathered row the
    LATEST staged slot matching (lane, set, way) wins — append order is
    program order, so this reproduces the old unique-key overwrite
    semantics exactly.  Cost scales with the per-lane capacity c."""
    if d.skey is None:
        return rows
    T, C = d.skey.shape
    SW = d.sval.shape[2]
    K = sets.shape[1]
    DW = rows.shape[2] // SW
    valid = d.skey >= 0                                       # [T, c]
    key = jnp.where(valid, d.skey, 0)
    s_of = nn_div(key, DW)
    w_of = nn_mod(key, DW)
    m = valid[:, None, :] & (s_of[:, None, :] == sets[:, :, None])
    mw = m[:, :, None, :] & (
        w_of[:, None, None, :]
        == np.arange(DW, dtype=np.int32)[None, None, :, None])
    rank = np.arange(1, C + 1, dtype=np.int32)
    best = jnp.max(jnp.where(mw, rank, 0), axis=3)            # [T, K, DW]
    has = best > 0
    idx = jnp.where(has, best - 1, 0)
    vals = d.sval[np.arange(T, dtype=np.int32)[:, None, None], idx]
    rows3 = rows.reshape(T, K, DW, SW)
    out = jnp.where(has[..., None], vals, rows3)
    return out.reshape(T, K, DW * SW)


def dir_stage_flush(d):
    """Apply the staging rows to the big sharers store and reset them.

    ROW-form add-a-delta: gather each staged slot's whole [DW*SW] set
    row (structured [t, s] row indexing — the fast TPU gather path; the
    3D element-index form measured 90 ms/flush, PERF.md round-5), expand
    the slot's delta into its way's column, and scatter-add rows back.
    Only each key's LAST slot within its lane row applies (later slots
    overwrite earlier ones, the append-order analog of the old layout's
    in-place overwrite); two applied slots in the same set touch
    disjoint way columns, so duplicate (t, s) row adds stay exact; empty
    and superseded slots add zero out of bounds (dropped).  The add
    aliases the loop-carried buffer in place."""
    if d.skey is None:
        return d
    T, DS, DW = d.entry.shape
    SW = d.sval.shape[2]
    C = d.skey.shape[1]
    tiles = np.arange(T, dtype=np.int32)[:, None]
    valid = d.skey >= 0                                       # [T, c]
    key = jnp.where(valid, d.skey, 0)
    w = nn_mod(key, DW)
    s = nn_div(key, DW)
    # a slot applies iff no LATER slot in its lane row stages the same key
    later = (valid[:, :, None] & valid[:, None, :]
             & (key[:, :, None] == key[:, None, :])
             & (np.arange(C)[None, None, :] > np.arange(C)[None, :, None]))
    is_last = valid & ~later.any(axis=2)
    row = d.sharers[tiles, s]                                 # [T, c, DW*SW]
    row3 = row.reshape(T, C, DW, SW)
    cur = jnp.take_along_axis(row3, w[:, :, None, None], axis=2)[:, :, 0]
    delta = jnp.where(is_last[..., None], d.sval - cur, jnp.uint32(0))
    onehot = (np.arange(DW, dtype=np.int32)[None, None, :, None]
              == w[:, :, None, None])
    row_delta = jnp.where(onehot, delta[:, :, None, :],
                          jnp.uint32(0)).reshape(T, C, DW * SW)
    s_oob = jnp.where(is_last, s, DS)              # dropped when superseded
    return d.replace(
        sharers=d.sharers.at[tiles, s_oob].add(row_delta, mode="drop"),
        skey=jnp.full_like(d.skey, -1),
        sn=jnp.zeros_like(d.sn))


class _DirAcc:
    """Deferred directory writes of one gated home phase.

    Under per-phase gating (MemParams.phase_gate) the home phases run
    inside a lax.cond that must not carry the big [T, DS, DW] entry /
    [T, DS, DW*SW] sharers stores (a cond's branch outputs are
    double-buffered — the round-2 pathology that disabled the
    whole-engine gate above 1 GB).  `_dir_update` therefore accumulates
    its writes here as compact block-local per-lane deltas — one int64
    entry-word delta and (unstaged mode only) one [Tl, DW*SW] sharers
    set-row delta — which the cond returns and `_dir_apply` scatters
    outside it.  Staged sharers writes keep going through the small
    (skey, sval) table inside the cond.

    Invariants (hold by construction in the three home phases):
     - every `_dir_update` call of one phase targets the SAME per-lane
       (sets, way) pair (checked by object identity on the pre-px.lo
       operands at trace time);
     - the calls' masks are pairwise disjoint per lane, so summing
       new-minus-cur deltas read against the unmodified pre-phase store
       is exact.
    """

    def __init__(self, consolidated: bool = False):
        # consolidated (round 12): deltas stay replicated full-width and
        # the sharers row delta is recorded in EVERY mode (staged too —
        # later phases' views forward it); `pack_c` is the plan shape
        # and `_dir_apply_merged` lands all three phases' plans in one
        # scatter per store at the end of the iteration.
        self.consolidated = consolidated
        self._ref = None
        self.sets = None
        self.way = None
        self.entry_delta = None
        self.sharers_delta = None

    def _bind(self, ref, sets_l, way_l):
        # ref is the (sets, way) operand pair itself — holding the
        # objects pins their identity for the check's lifetime (a bare
        # id() tuple could be recycled after gc)
        if self._ref is None:
            self._ref, self.sets, self.way = ref, sets_l, way_l
        elif not (self._ref[0] is ref[0] and self._ref[1] is ref[1]):
            raise AssertionError(
                "_DirAcc: a gated home phase issued _dir_update calls "
                "with different (sets, way) operands — the deferred "
                "delta plan assumes one target entry per lane per phase")

    def add_entry(self, ref, sets_l, way_l, delta):
        self._bind(ref, sets_l, way_l)
        self.entry_delta = (delta if self.entry_delta is None
                            else self.entry_delta + delta)

    def add_sharers(self, ref, sets_l, way_l, row_delta):
        self._bind(ref, sets_l, way_l)
        self.sharers_delta = (row_delta if self.sharers_delta is None
                              else self.sharers_delta + row_delta)

    def pack(self, d):
        """The cond-carried plan: (sets, way, entry_delta[, sharers_row
        _delta]) — all block-local [Tl(, DW*SW)] arrays, zeros when the
        phase made no writes of that kind."""
        Tl = d.entry.shape[0]
        sets = (self.sets if self.sets is not None
                else jnp.zeros(Tl, jnp.int32))
        way = (self.way if self.way is not None
               else jnp.zeros(Tl, jnp.int32))
        ed = (self.entry_delta if self.entry_delta is not None
              else jnp.zeros(Tl, I64))
        if d.skey is not None:
            return (sets, way, ed)
        row_shape = (d.sharers.shape[0], d.sharers.shape[2])
        shd = (self.sharers_delta if self.sharers_delta is not None
               else jnp.zeros(row_shape, U32))
        return (sets, way, ed, shd)

    @staticmethod
    def zero_pack(d):
        Tl = d.entry.shape[0]
        base = (jnp.zeros(Tl, jnp.int32), jnp.zeros(Tl, jnp.int32),
                jnp.zeros(Tl, I64))
        if d.skey is not None:
            return base
        return base + (jnp.zeros((Tl, d.sharers.shape[2]), U32),)

    def pack_c(self, d, n_tiles: int):
        """The consolidated plan: (sets, way, entry_delta, sharers_row
        _delta) — replicated full-width [T(, DW*SW)], zeros when the
        phase made no writes of that kind."""
        sets = (self.sets if self.sets is not None
                else jnp.zeros(n_tiles, jnp.int32))
        way = (self.way if self.way is not None
               else jnp.zeros(n_tiles, jnp.int32))
        ed = (self.entry_delta if self.entry_delta is not None
              else jnp.zeros(n_tiles, I64))
        shd = (self.sharers_delta if self.sharers_delta is not None
               else jnp.zeros((n_tiles, d.sharers.shape[2]), U32))
        return (sets, way, ed, shd)

    @staticmethod
    def zero_pack_c(d, n_tiles: int):
        return (jnp.zeros(n_tiles, jnp.int32),
                jnp.zeros(n_tiles, jnp.int32),
                jnp.zeros(n_tiles, I64),
                jnp.zeros((n_tiles, d.sharers.shape[2]), U32))


def _dir_apply(d, pack):
    """Scatter a gated home phase's deferred delta plan into the big
    directory stores — OUTSIDE the phase's lax.cond, so the stores are
    never cond outputs.  Zero deltas (masked-off lanes, skipped phases)
    add nothing; indices are per-lane rows, so the adds alias in
    place."""
    sets, way, entry_delta = pack[:3]
    T = d.entry.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    d = d.replace(entry=d.entry.at[tiles, sets, way].add(
        entry_delta, unique_indices=True, indices_are_sorted=True))
    if len(pack) > 3:
        d = d.replace(sharers=d.sharers.at[tiles, sets].add(
            pack[3], unique_indices=True, indices_are_sorted=True))
    return d


class _DirRowView:
    """A `_DirSetView`-compatible view over ONE pre-gathered (and
    delta-forwarded) directory set row per home lane — what the round-12
    consolidated home phases read instead of re-gathering the big
    stores.  Staged writes were already overlaid at gather time
    (`_stage_overlay_rows`), and earlier phases' pending deltas were
    forwarded in (`_DirWorkingSet.view`), so `entry()` is pure register
    math."""

    def __init__(self, line, sets, entry_row, sharers_row, dw):
        self.sets = sets
        self._line = line
        self._word = entry_row      # int64[T, DW]
        self._sh = sharers_row      # uint32[T, DW*SW]
        self._dw = dw

    def rows(self):
        return dir_tag(self._word), dir_nsh(self._word)

    def lookup(self):
        tag_row = dir_tag(self._word)
        way_hits = tag_row == self._line[:, None]
        found = way_hits.any(axis=1)
        way = jnp.argmax(way_hits, axis=1).astype(jnp.int32)
        return found, way

    def word_at(self, way):
        return jnp.take_along_axis(self._word, way[:, None], axis=1)[:, 0]

    def sharers_row3(self):
        return self._sh.reshape(self._sh.shape[0], self._dw, -1)

    def entry(self, way):
        sharers = jnp.take_along_axis(
            self.sharers_row3(), way[:, None, None], axis=1)[:, 0]
        word = self.word_at(way)
        return (dir_tag(word), dir_state(word), dir_owner(word),
                sharers, dir_nsh(word))


class _DirWorkingSet:
    """The iteration's packed directory working set (round 12).

    After the requester phase, every set the three home phases can
    touch is known: the earliest EVICT cell's line, the earliest
    REQUEST lane's line (or the saved post-NULLIFY original), and the
    transaction line.  A transaction STARTED this iteration carries the
    effective request line, whose set equals the request row's set
    (directory tags are congruent to their set mod DS by construction),
    so THREE set rows cover phase 5 too — `view_finish` selects by set
    equality, where any ambiguity is harmless because equal sets mean
    identical row content.

    ONE packed [T, 3, DW] entry-row + [T, 3, DW*SW] sharers-row gather
    (one collective under shard_map, with the per-lane staging rows
    overlaid block-locally first) serves all three phases; each phase's
    view forwards the pending delta plans of the phases before it, and
    `_dir_apply_merged` lands every plan in ONE scatter per store at
    the end of the iteration.  This is the packed CacheRow exchange
    form promoted to the iteration's working set: the six phases
    operate on rows-in-registers, and the big stores see exactly one
    gather and one scatter per iteration."""

    def __init__(self, px: ParallelCtx, d: "DirectoryArrays", mp, lines):
        self._dw = d.entry.shape[2]
        self._dir_sets = mp.dir_sets
        self.sets3 = jnp.stack(
            [nn_mod(ln, mp.dir_sets).astype(jnp.int32) for ln in lines],
            axis=1)                                           # [T, 3]
        if px.sharded:
            sets_l = px.lo(self.sets3)
            Tl = d.entry.shape[0]
            lt = np.arange(Tl, dtype=np.int32)[:, None]
            ew = d.entry[lt, sets_l]                          # [Tl, 3, DW]
            sh = d.sharers[lt, sets_l]                        # [Tl, 3, DW*SW]
            if d.skey is not None:
                sh = _stage_overlay_rows(d, sets_l, sh)
            self.entry_rows, self.sharer_rows = px.ag((ew, sh))
        else:
            T = d.entry.shape[0]
            tl = np.arange(T, dtype=np.int32)[:, None]
            self.entry_rows = d.entry[tl, self.sets3]
            sh = d.sharers[tl, self.sets3]
            if d.skey is not None:
                sh = _stage_overlay_rows(d, self.sets3, sh)
            self.sharer_rows = sh

    def _forward(self, sets, ew, sh, packs):
        """Add earlier phases' pending deltas where their target set is
        this view's set (all directory writes are home-lane-local, so a
        per-lane set compare decides).  Deltas were computed against the
        then-current forwarded view, so the adds chain exactly."""
        DW = self._dw
        for (psets, pway, ped, pshd) in packs:
            m = psets == sets
            onehot = (np.arange(DW, dtype=np.int32)[None, :]
                      == pway[:, None])
            ew = ew + jnp.where(m[:, None] & onehot, ped[:, None],
                                jnp.zeros_like(ew))
            sh = sh + jnp.where(m[:, None], pshd, jnp.zeros_like(sh))
        return ew, sh

    def view(self, k: int, line, packs) -> _DirRowView:
        ew, sh = self._forward(self.sets3[:, k], self.entry_rows[:, k],
                               self.sharer_rows[:, k], packs)
        return _DirRowView(line, self.sets3[:, k], ew, sh, self._dw)

    def view_finish(self, line, packs) -> _DirRowView:
        sets = nn_mod(line, self._dir_sets).astype(jnp.int32)
        use1 = sets == self.sets3[:, 1]
        ew = jnp.where(use1[:, None], self.entry_rows[:, 1],
                       self.entry_rows[:, 2])
        sh = jnp.where(use1[:, None], self.sharer_rows[:, 1],
                       self.sharer_rows[:, 2])
        ew, sh = self._forward(sets, ew, sh, packs)
        return _DirRowView(line, sets, ew, sh, self._dw)


def _dir_apply_merged(d, px: ParallelCtx, packs):
    """ONE merged scatter per big directory store per iteration: the
    home phases' consolidated delta plans land together at the end of
    the engine step.  Duplicate targets (two phases updating the same
    per-lane entry) are folded into the earliest plan and the duplicate
    slot redirected out of bounds, so the scatters keep unique indices
    (in-place friendly) and the summed deltas stay exact — each phase's
    delta was computed against the forwarded view, so the fold telescopes
    to final-minus-initial.  Sharers deltas apply only in unstaged mode
    (staged writes ride the per-lane table and flush per block)."""
    packs = [tuple(px.lo(p)) for p in packs]
    Tl = d.entry.shape[0]
    t = np.arange(Tl, dtype=np.int32)
    sets = [p[0] for p in packs]
    way = [p[1] for p in packs]
    ed = [p[2] for p in packs]
    shd = [p[3] for p in packs]
    n = len(packs)
    drop_e = [jnp.zeros(Tl, jnp.bool_) for _ in range(n)]
    drop_s = [jnp.zeros(Tl, jnp.bool_) for _ in range(n)]
    for j in range(1, n):
        for i in range(j):
            eq_e = ((sets[i] == sets[j]) & (way[i] == way[j])
                    & ~drop_e[i] & ~drop_e[j])
            ed[i] = ed[i] + jnp.where(eq_e, ed[j], 0)
            drop_e[j] = drop_e[j] | eq_e
            eq_s = (sets[i] == sets[j]) & ~drop_s[i] & ~drop_s[j]
            shd[i] = shd[i] + jnp.where(eq_s[:, None], shd[j],
                                        jnp.zeros_like(shd[j]))
            drop_s[j] = drop_s[j] | eq_s
    t_e = jnp.concatenate([jnp.where(dr, Tl, t) for dr in drop_e])
    s_all = jnp.concatenate(sets)
    w_all = jnp.concatenate(way)
    ed_all = jnp.concatenate(ed)
    out = d.replace(entry=d.entry.at[t_e, s_all, w_all].add(
        ed_all, mode="drop", unique_indices=True))
    if d.skey is None:
        t_s = jnp.concatenate([jnp.where(dr, Tl, t) for dr in drop_s])
        shd_all = jnp.concatenate(shd)
        out = out.replace(sharers=out.sharers.at[t_s, s_all].add(
            shd_all, mode="drop", unique_indices=True))
    return out


def _cond_dir_c(pred, fn, ms, n_tiles: int):
    """Round-12 form of `_cond_dir`: the phase reads the directory only
    through its pre-gathered `_DirRowView` (closed over by `fn` — cond
    inputs), so BOTH big stores detach from the cond entirely; the cond
    returns the phase's consolidated delta plan for forwarding and the
    end-of-iteration merged scatter.  The per-lane staging rows (small,
    lane-local) stay carried — staged puts happen inside."""
    d0 = ms.directory

    def detach(m):
        return m.replace(directory=m.directory.replace(
            entry=None, sharers=None))

    def run(m):
        # the phase runs with BOTH big stores detached — its only
        # directory reads are the view rows, its only writes the plan
        acc = _DirAcc(consolidated=True)
        m2, prog = fn(m, acc)
        return m2, prog, acc.pack_c(d0, n_tiles)

    def skip(m):
        return m, jnp.zeros((), jnp.int32), _DirAcc.zero_pack_c(
            d0, n_tiles)

    ms2, prog, pack = jax.lax.cond(pred, run, skip, detach(ms))
    d = ms2.directory.replace(entry=d0.entry, sharers=d0.sharers)
    return ms2.replace(directory=d), prog, pack


def _cond_nodir(pred, fn, ms):
    """Run a directory-free engine phase (requester start, sharer serve,
    requester fill) under a scalar-predicate lax.cond.  The directory is
    detached from the carried operands entirely — these phases neither
    read nor write it — so the cond cannot double-buffer the big
    stores."""
    d0 = ms.directory

    def run(m):
        return fn(m)

    def skip(m):
        return m, jnp.zeros((), jnp.int32)

    ms2, prog = jax.lax.cond(pred, run, skip, ms.replace(directory=None))
    return ms2.replace(directory=d0), prog


def _cond_dir(pred, fn, ms):
    """Run a home-side phase (evictions / starts / acks+finish) under a
    scalar-predicate lax.cond.  The phase reads the big directory stores
    (cond inputs — no double-buffering) but writes them only through a
    `_DirAcc` delta plan the cond returns; `_dir_apply` lands the plan
    outside.  Staged sharers writes ride the small (skey, sval) table,
    which IS carried.  `fn(ms, acc) -> (ms, progress)` must leave
    ms.directory.entry/.sharers untouched (it defers via acc)."""
    d0 = ms.directory

    def detach(m):
        return m.replace(directory=m.directory.replace(
            entry=None, sharers=None))

    def run(m):
        acc = _DirAcc()
        m2, prog = fn(m.replace(directory=d0), acc)
        return detach(m2), prog, acc.pack(d0)

    def skip(m):
        return m, jnp.zeros((), jnp.int32), _DirAcc.zero_pack(d0)

    ms2, prog, pack = jax.lax.cond(pred, run, skip, detach(ms))
    d = ms2.directory.replace(entry=d0.entry, sharers=d0.sharers)
    return ms2.replace(directory=_dir_apply(d, pack)), prog


def _dir_update(d, sets, way, mask, *, px: ParallelCtx = IDENT, tags=None,
                dstate=None, owner=None, sharers=None, nsharers=None,
                acc: "_DirAcc | None" = None,
                view: "_DirRowView | None" = None):
    """Masked per-lane write of one directory entry.

    Add-a-delta scatters (new = cur + (new - cur) under mask): per-lane
    indices are unique (row = lane), so the add is exact and the scatter
    can update the loop-carried buffers in place.  The operands arrive
    replicated full-width; a sharded px applies only this device's home
    rows.  With `acc` set (per-phase gating) the entry-word and unstaged
    sharers deltas are accumulated instead of scattered — the caller's
    lax.cond returns them and `_dir_apply` lands them outside it.

    With `view` set (round-12 consolidation) the current values are
    read from the phase's forwarded working-set row instead of the big
    stores (which may be detached from the cond entirely), deltas stay
    replicated full-width in the acc — `_dir_apply_merged` lands every
    phase's plan in one scatter per store at the end of the iteration —
    and the sharers row delta is recorded in staged mode too so later
    phases' views can forward it."""
    if view is not None:
        ref = (sets, way)
        out = d
        cur = view.word_at(way)
        new = cur
        if tags is not None:
            new = _dir_set_field(new, tags.astype(I64) + 1, 0, _TAG_MASK)
        if dstate is not None:
            new = _dir_set_field(new, jnp.asarray(dstate, jnp.uint8),
                                 DIR_STATE_SHIFT, 7)
        if owner is not None:
            new = _dir_set_field(new, owner.astype(I64) + 1,
                                 DIR_OWNER_SHIFT, _ID_MASK)
        if nsharers is not None:
            new = _dir_set_field(new, nsharers, DIR_NSH_SHIFT, _ID_MASK)
        if new is not cur:
            delta = jnp.where(mask, new - cur, jnp.zeros_like(cur))
            acc.add_entry(ref, sets, way, delta)
        if sharers is not None:
            DW = view._dw
            row3 = view.sharers_row3()
            onehot = (np.arange(DW, dtype=np.int32)[None, :, None]
                      == way[:, None, None]) & mask[:, None, None]
            new3 = jnp.where(onehot, sharers[:, None, :], row3)
            row_delta = (new3 - row3).reshape(row3.shape[0], -1)
            acc.add_sharers(ref, sets, way, row_delta)
            if out.skey is not None:
                out = _stage_put(out, *px.lo((sets, way, mask, sharers)),
                                 dw=DW)
        return out

    ref = (sets, way)
    sets, way, mask = px.lo((sets, way, mask))
    T = d.entry.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    out = d

    # ONE packed RMW scatter updates every written word field together
    # (four separate arrays cost four dense-lowered scatters plus their
    # layout-conversion copies each phase)
    cur = out.entry[tiles, sets, way]
    new = cur
    if tags is not None:
        new = _dir_set_field(new, px.lo(tags).astype(I64) + 1, 0, _TAG_MASK)
    if dstate is not None:
        new = _dir_set_field(new, px.lo(jnp.asarray(dstate, jnp.uint8)),
                             DIR_STATE_SHIFT, 7)
    if owner is not None:
        new = _dir_set_field(new, px.lo(owner).astype(I64) + 1,
                             DIR_OWNER_SHIFT, _ID_MASK)
    if nsharers is not None:
        new = _dir_set_field(new, px.lo(nsharers), DIR_NSH_SHIFT, _ID_MASK)
    if new is not cur:
        delta = jnp.where(mask, new - cur, jnp.zeros_like(cur))
        if acc is not None:
            acc.add_entry(ref, sets, way, delta)
        else:
            out = out.replace(entry=out.entry.at[tiles, sets, way].add(
                delta, unique_indices=True, indices_are_sorted=True))
    if sharers is not None:
        new_sh = px.lo(sharers)                       # [Tl, SW]
        if out.skey is not None:
            # staged mode (legacy view: single-device programs only —
            # the Simulator forbids staging under a mesh without the
            # consolidated base)
            assert not px.sharded
            out = _stage_put(out, sets, way, mask, new_sh)
        else:
            # sharers store set-row-major [T, DS, DW*SW]: RMW the lane's
            # set row, placing the entry's [SW] words at its way's slot
            # (per-lane rows unique, so the 2D-indexed add aliases in
            # place)
            DW = out.entry.shape[2]
            row = out.sharers[tiles, sets]            # [Tl, DW*SW]
            row3 = row.reshape(row.shape[0], DW, -1)
            onehot = (np.arange(DW, dtype=np.int32)[None, :, None]
                      == way[:, None, None]) & mask[:, None, None]
            new3 = jnp.where(onehot, new_sh[:, None, :], row3)
            row_delta = (new3 - row3).reshape(row.shape)
            if acc is not None:
                acc.add_sharers(ref, sets, way, row_delta)
            else:
                out = out.replace(sharers=out.sharers.at[tiles, sets].add(
                    row_delta,
                    unique_indices=True, indices_are_sorted=True))
    return out


# --------------------------------------------------------------------------
# the engine step


def memory_engine_step(
    mp: MemParams,
    ms: MemState,
    rec: RecView,
    clock_ps: jax.Array,      # int64[T] core clocks (base of slot accesses)
    freq_mhz: jax.Array,      # int32[T] per-tile core/cache frequency
    active: jax.Array,        # bool[T] lane may start new work this iter
    enabled,                  # bool[] models enabled
    px: ParallelCtx = IDENT,  # shard_map exchange context (parallel/px.py)
    fill_events: bool = False,  # emit per-call MemStepOut.fill_now/_lat_ps
) -> MemStepOut:
    T = mp.n_tiles
    tiles = np.arange(T, dtype=np.int32)
    progress = jnp.zeros((), jnp.int32)
    fmhz = freq_mhz.astype(I64)

    mc = jnp.asarray(mp.mc_tiles, jnp.int32)

    def home_of(line):
        return mc[nn_mod(line, len(mp.mc_tiles)).astype(jnp.int32)]

    def ccycles(n, f=None):
        """cycles→ps at per-tile cache frequency (or given), model-gated."""
        n = jnp.asarray(n, I64)
        ps = cycles_to_ps(n, fmhz if f is None else f)
        return jnp.where(enabled, ps, 0)

    dram_lat_ps = jnp.where(
        enabled, (mp.dram_latency_ns + mp.dram_processing_ns) * 1000, 0
    ).astype(I64)
    dir_access_ps = jnp.where(
        enabled, cycles_to_ps(jnp.asarray(mp.dir_access_cycles, I64),
                              mp.dir_freq_mhz), 0
    ).astype(I64)

    sync_core_l1d = ccycles(mp.sync_cycles(MOD_CORE, MOD_L1D))
    sync_core_l1i = ccycles(mp.sync_cycles(MOD_CORE, MOD_L1I))
    sync_l1d_l2 = ccycles(mp.sync_cycles(MOD_L1D, MOD_L2))
    sync_l1i_l2 = ccycles(mp.sync_cycles(MOD_L1I, MOD_L2))
    sync_l2_net = ccycles(mp.sync_cycles(MOD_L2, MOD_NET_MEM))
    sync_dir_l2 = jnp.where(
        enabled,
        cycles_to_ps(jnp.asarray(mp.sync_cycles(MOD_DIR, MOD_L2), I64),
                     mp.dir_freq_mhz), 0).astype(I64)
    sync_dir_net = jnp.where(
        enabled,
        cycles_to_ps(jnp.asarray(mp.sync_cycles(MOD_DIR, MOD_NET_MEM), I64),
                     mp.dir_freq_mhz), 0).astype(I64)

    # ---- slot decomposition of the current record -------------------------
    flags = rec.flags
    present = slots_present(mp, rec, enabled)

    def next_present(slot):
        return next_present_slot(present, slot)

    # ======================================================================
    # (1) requester slot starts (app-thread L1/L2 path) — unrolled
    # mp.requester_unroll times per engine iteration: records whose
    # next slots HIT the L1 complete several slots per iteration (the
    # repeat is ~15 cheap L1/L2-row kernels vs a whole extra engine
    # iteration per slot).  A lane that misses sets PHASE_WAIT_REPLY
    # and later repeats are no-ops for it; within-iteration repeats
    # see no intervening protocol messages — the serialization the
    # golden oracle itself uses (whole records at once).
    # ======================================================================
    def _requester_once(ms, progress):
        # ======================================================================
        # (1) requester slot starts (app-thread L1/L2 path)
        # ======================================================================
        slot = next_present(ms.req.slot)
        has_slot = slot < 3
        idle = ms.req.phase == PHASE_IDLE
        starting = active & idle & has_slot

        # slot attributes
        s_is_icache = slot == 0
        s_addr = jnp.where(
            s_is_icache, rec.pc.astype(jnp.int32),
            jnp.where(slot == 1, rec.addr0.astype(jnp.int32),
                      rec.addr1.astype(jnp.int32)))
        s_line = (s_addr.astype(jnp.uint32) >> mp.line_bits).astype(jnp.int32)
        s_write = jnp.where(
            s_is_icache, False,
            jnp.where(slot == 1, (flags & FLAG_MEM0_WRITE) != 0,
                      (flags & FLAG_MEM1_WRITE) != 0))
        s_comp_l1i = s_is_icache

        # instruction-buffer fast path (`core.cc:205-220`): hit = 1 cycle
        ibuf_hit = starting & s_is_icache & (s_line == ms.req.instr_buf)
        new_instr_buf = jnp.where(starting & s_is_icache, s_line, ms.req.instr_buf)

        # L1 lookups (both caches, masked by component) — each lane's set rows
        # are gathered ONCE per cache level here and scattered back once below
        # (the engine is op-count-bound; see cache_array.py).  Under a
        # sharded px the gathers read this device's block and ONE packed
        # all-gather replicates the rows (plus the pre-update miss-type
        # test bits, which must be read before this phase's own writes).
        s_line_l = px.lo(s_line)
        rows_l = (
            ca.gather_row(ms.l1i, s_line_l, px.lo_const(mp.l1i.sets_mod),
                          nonneg=True),
            ca.gather_row(ms.l1d, s_line_l, px.lo_const(mp.l1d.sets_mod),
                          nonneg=True),
            ca.gather_row(ms.l2, s_line_l, px.lo_const(mp.l2.sets_mod),
                          nonneg=True),
        )
        if mp.l2.track_miss_types:
            mt_bits_l = (_mt_test(ms.mt, MT_EVICTED, s_line_l),
                         _mt_test(ms.mt, MT_INVALIDATED, s_line_l),
                         _mt_test(ms.mt, MT_FETCHED, s_line_l))
        else:
            mt_bits_l = ()
        if mp.l2.track_line_utilization:
            mt_bits_l = mt_bits_l + (_util_row_local(
                ms.l2_util, s_line_l, px.lo_const(mp.l2.sets_mod)),)
        (l1i_row, l1d_row, l2_row), mt_bits = _rows_exchange(
            px, rows_l, mt_bits_l)
        if mp.l2.track_line_utilization:
            lu_row, mt_bits = mt_bits[-1], mt_bits[:-1]
        l1i_hit, l1i_way, l1i_state = ca.row_lookup(l1i_row, s_line)
        l1d_hit, l1d_way, l1d_state = ca.row_lookup(l1d_row, s_line)
        l1_state = jnp.where(s_comp_l1i, l1i_state, l1d_state)
        l1_permit = jnp.where(s_write, state_writable(l1_state),
                              state_readable(l1_state))
        do_l1 = starting & ~ibuf_hit

        sync_core = jnp.where(s_comp_l1i, sync_core_l1i, sync_core_l1d)
        l1_dat = jnp.where(
            s_comp_l1i, ccycles(mp.l1i.data_and_tags_cycles),
            ccycles(mp.l1d.data_and_tags_cycles))
        l1_tag = jnp.where(
            s_comp_l1i, ccycles(mp.l1i.tags_cycles), ccycles(mp.l1d.tags_cycles))
        sync_l1_l2 = jnp.where(s_comp_l1i, sync_l1i_l2, sync_l1d_l2)

        l1_hit_now = do_l1 & l1_permit
        l1_miss = do_l1 & ~l1_permit

        # L2 lookup for L1 misses
        l2_hit, l2_way, l2_state = ca.row_lookup(l2_row, s_line)
        l2_permit = jnp.where(s_write, state_writable(l2_state),
                              state_readable(l2_state))
        l2_hit_now = l1_miss & l2_permit
        l2_miss = l1_miss & ~l2_permit

        # upgrade (write to a readable-but-not-writable L2 line): invalidate L2
        # + eviction message to home, then a full EX_REQ refetch
        # (`l2_cache_cntlr.cc:261-282 processExReqFromL1Cache`; documented
        # simplification: the reference's UPGRADE_REP without data is modeled
        # as a refetch, same message count, slightly larger data serialization).
        # MOSI: an OWNED line is dirty, so its upgrade eviction must FLUSH.
        upgrade = l2_miss & s_write & (
            (l2_state == SHARED) | (l2_state == OWNED))
        upgrade_dirty = upgrade & (l2_state == OWNED)
        s_home = home_of(s_line)
        evict_cell_busy = ms.mail.evict_type[s_home, tiles] != MSG_NONE
        stall_start = upgrade & evict_cell_busy
        l2_miss_go = l2_miss & ~stall_start

        # --- apply the L1-hit path -------------------------------------------
        sclock = clock_ps + sync_core           # processMemOpFromCore entry
        l1_hit_done_ps = sclock + l1_dat

        # hits refresh recency under LRU; round_robin's update is a no-op
        if mp.l1i.replacement != "round_robin":
            l1i_row = ca.row_touch(l1i_row, l1i_way, l1_hit_now & s_comp_l1i)
        if mp.l1d.replacement != "round_robin":
            l1d_row = ca.row_touch(l1d_row, l1d_way, l1_hit_now & ~s_comp_l1i)

        # L1 line invalidated on miss before L2 is consulted
        # (`l1_cache_cntlr.cc:137`) — must precede the L2-hit fill below, so
        # the fill lands in the just-freed way and survives
        l1i_row = ca.row_invalidate(l1i_row, s_line, l1_miss & s_comp_l1i)
        l1d_row = ca.row_invalidate(l1d_row, s_line, l1_miss & ~s_comp_l1i)

        # --- apply the L2-hit path (fill L1 from L2) -------------------------
        # timing: L1 tags (miss) + L2 sync + L2 data+tags + L1 data+tags
        l2_hit_done_ps = sclock + l1_tag + sync_l1_l2 + ccycles(
            mp.l2.data_and_tags_cycles) + l1_dat
        # L1 fill state = L2 state (`insertCacheLineInL1`)
        fill_l1i = l2_hit_now & s_comp_l1i
        fill_l1d = l2_hit_now & ~s_comp_l1i

        def l1_fill(row, mask, st, policy, ways):
            way, v_valid, v_line, _ = ca.row_pick_victim(row, policy, ways)
            out = ca.row_insert(row, s_line, way, st, mask)
            return out, way, v_valid & mask, v_line

        l1i_row, _, l1i_ev, l1i_ev_line = l1_fill(
            l1i_row, fill_l1i, l2_state, mp.l1i.replacement,
            mp.l1i.ways_limit)
        l1d_row, _, l1d_ev, l1d_ev_line = l1_fill(
            l1d_row, fill_l1d, l2_state, mp.l1d.replacement,
            mp.l1d.ways_limit)
        # L1 victims: clear their cached-loc in L2 (line stays valid in L2).
        # The whole read-modify-write chain is block-local: its only
        # consumer is the local cloc scatter, so nothing travels.
        l1_ev = l1i_ev | l1d_ev
        l1_ev_line = jnp.where(l1i_ev, l1i_ev_line, l1d_ev_line)
        ev_line_l = px.lo(l1_ev_line)
        l2_mod_l = px.lo_const(mp.l2.sets_mod)
        ev_hit_l, ev_way_l, _ = ca.lookup(ms.l2, ev_line_l, l2_mod_l)
        ev_sets_l = (ev_line_l % jnp.asarray(l2_mod_l)).astype(jnp.int32)
        l2_cloc = px.entry_set(ms.l2_cloc, ev_sets_l, ev_way_l,
                               px.lo(l1_ev) & ev_hit_l, 0)
        # record new cached-loc for the filled line
        f_sets = nn_mod(s_line, jnp.asarray(mp.l2.sets_mod)).astype(jnp.int32)
        new_cloc = jnp.where(s_comp_l1i, MOD_L1I, MOD_L1D).astype(jnp.uint8)
        l2_cloc = px.entry_set(
            l2_cloc, *px.lo((f_sets, l2_way, l2_hit_now, new_cloc)))
        if mp.l2.replacement != "round_robin":
            l2_row = ca.row_touch(l2_row, l2_way, l2_hit_now)

        # --- apply the L2-miss path (send request) ---------------------------
        # `processExReqFromL1Cache`/`processShReqFromL1Cache`: request time =
        # entry sync + L1 tags + L2 tags
        req_send_ps = sclock + l1_tag + ccycles(mp.l2.tags_cycles)
        # upgrade: invalidate L2 + eviction message (INV_REP clean, FLUSH_REP
        # for a dirty OWNED line)
        up_go = upgrade & ~stall_start
        l2_row = ca.row_invalidate(l2_row, s_line, up_go)
        if mp.l2.track_line_utilization:
            # L2 hit: count the access; upgrade invalidate: the line
            # leaves the L2 — classify its counters and zero them
            en = jnp.asarray(enabled, bool)
            lu_cur = jnp.take_along_axis(lu_row, l2_way[:, None],
                                         axis=1)[:, 0]
            lu_new = _util_inc(lu_cur, s_write, l2_hit_now & en)
            lu_new = jnp.where(up_go & en, jnp.uint32(0), lu_new)
            ms = ms.replace(l2_util=_util_scatter(
                px, ms.l2_util, s_line, mp.l2.sets_mod, l2_way,
                lu_cur, lu_new))
            ms = ms.replace(counters=_util_classify(
                ms.counters, lu_cur, up_go, enabled))
        # scatter the three set rows back — ONE scatter per cache level,
        # each device taking its own lanes' rows
        l1i_upd = ca.scatter_row(ms.l1i, px.lo(l1i_row))
        l1d_upd = ca.scatter_row(ms.l1d, px.lo(l1d_row))
        l2_upd = ca.scatter_row(ms.l2, px.lo(l2_row))
        mail = ms.mail
        noc = ms.noc
        up_msg = jnp.where(upgrade_dirty, MSG_FLUSH_REP,
                           MSG_INV_REP).astype(jnp.uint8)
        w_home = jnp.where(up_go, s_home, 0)
        noc, up_arrival = mem_net_send(
            mp, noc, tiles, s_home, mp.req_bits, req_send_ps, up_go, enabled)
        mail = mail.replace(
            evict_type=mail.evict_type.at[w_home, tiles].set(
                jnp.where(up_go, up_msg, mail.evict_type[w_home, tiles])),
            evict_line=mail.evict_line.at[w_home, tiles].set(
                jnp.where(up_go, s_line, mail.evict_line[w_home, tiles])),
            evict_time=mail.evict_time.at[w_home, tiles].set(
                jnp.where(up_go, up_arrival,
                          mail.evict_time[w_home, tiles])),
        )
        rq_type = jnp.where(s_write, MSG_EX_REQ, MSG_SH_REQ).astype(jnp.uint8)
        noc, rq_arrival = mem_net_send(
            mp, noc, tiles, s_home, mp.req_bits, req_send_ps, l2_miss_go,
            enabled)
        # per-requester lane (one outstanding miss per tile): plain
        # masked selects, no matrix scatter
        mail = mail.replace(
            req_type=jnp.where(l2_miss_go, rq_type, mail.req_type),
            req_home=jnp.where(l2_miss_go, s_home, mail.req_home),
            req_line=jnp.where(l2_miss_go, s_line, mail.req_line),
            req_time=jnp.where(l2_miss_go, rq_arrival, mail.req_time),
        )

        # --- requester bookkeeping for this iteration's starts ----------------
        slot_done_now = ibuf_hit | l1_hit_now | l2_hit_now
        slot_done_ps = jnp.where(
            ibuf_hit, clock_ps + ccycles(1),
            jnp.where(l1_hit_now, l1_hit_done_ps, l2_hit_done_ps))

        req_state = ms.req.replace(
            phase=jnp.where(l2_miss_go, PHASE_WAIT_REPLY, ms.req.phase),
            line=jnp.where(l2_miss_go, s_line, ms.req.line),
            is_write=jnp.where(l2_miss_go, s_write, ms.req.is_write),
            component=jnp.where(
                l2_miss_go, jnp.where(s_comp_l1i, MOD_L1I, MOD_L1D),
                ms.req.component).astype(jnp.uint8),
            clock_ps=jnp.where(l2_miss_go, req_send_ps, ms.req.clock_ps),
            acc_ps=ms.req.acc_ps
            + jnp.where(slot_done_now, slot_done_ps - clock_ps, 0),
            # per-slot latency for the iocoom operand algebra
            slot_lat_ps=jnp.where(
                (slot_done_now[:, None]
                 & (np.arange(3)[None, :] == slot[:, None])),
                (slot_done_ps - clock_ps)[:, None], ms.req.slot_lat_ps),
            instr_buf=new_instr_buf,
            # slot advances on completion; on miss it stays (the reply path
            # advances it); skipped-over absent slots jump to the live one
            slot=jnp.where(slot_done_now, slot + 1,
                           jnp.where(starting, slot, ms.req.slot)),
        )

        # count misses only when the miss actually proceeds: a lane stalled on
        # a busy evict cell (stall_start) retries `starting` every iteration
        # and must not re-count
        miss_go = l1_miss & ~stall_start
        # L2 miss-type classification (`cache.cc getMissType` priority:
        # evicted -> CAPACITY, else invalidated/fetched -> SHARING, else
        # COLD), read BEFORE this access's own set updates
        if mp.l2.track_miss_types:
            cls = l2_miss_go & jnp.asarray(enabled, bool)
            in_e, in_i, in_f = mt_bits  # pre-update reads (exchanged above)
            mt_cap = cls & in_e
            mt_sha = cls & ~in_e & (in_i | in_f)
            mt_cold = cls & ~in_e & ~in_i & ~in_f
            # the upgrade's local L2 invalidate feeds the invalidated set
            # (`setCacheLineInfo` INVALID transition)
            new_mt = _mt_update(ms.mt, MT_INVALIDATED, s_line_l,
                                px.lo(up_go), True)
            ms = ms.replace(mt=new_mt)
        else:
            mt_cap = mt_sha = mt_cold = jnp.zeros((T,), jnp.bool_)
        counters = ms.counters.replace(
            l1i_hits=ms.counters.l1i_hits
            + ((l1_hit_now | ibuf_hit) & s_comp_l1i & enabled).astype(I64),
            l1i_misses=ms.counters.l1i_misses
            + (miss_go & s_comp_l1i & enabled).astype(I64),
            l1d_read_hits=ms.counters.l1d_read_hits
            + (l1_hit_now & ~s_comp_l1i & ~s_write & enabled).astype(I64),
            l1d_read_misses=ms.counters.l1d_read_misses
            + (miss_go & ~s_comp_l1i & ~s_write & enabled).astype(I64),
            l1d_write_hits=ms.counters.l1d_write_hits
            + (l1_hit_now & ~s_comp_l1i & s_write & enabled).astype(I64),
            l1d_write_misses=ms.counters.l1d_write_misses
            + (miss_go & ~s_comp_l1i & s_write & enabled).astype(I64),
            l2_hits=ms.counters.l2_hits + (l2_hit_now & enabled).astype(I64),
            l2_misses=ms.counters.l2_misses + (l2_miss_go & enabled).astype(I64),
            l2_cold_misses=ms.counters.l2_cold_misses + mt_cold.astype(I64),
            l2_capacity_misses=ms.counters.l2_capacity_misses
            + mt_cap.astype(I64),
            l2_sharing_misses=ms.counters.l2_sharing_misses
            + mt_sha.astype(I64),
        )
        progress = progress + jnp.sum(slot_done_now | l2_miss_go, dtype=jnp.int32)

        ms = ms.replace(
            l1i=l1i_upd, l1d=l1d_upd, l2=l2_upd, l2_cloc=l2_cloc,
            mail=mail, req=req_state, counters=counters, noc=noc,
        )

        # functional effect of slots completed via L1/L2 (loads/stores)
        ms = _apply_functional(mp, ms, rec, slot, s_addr, s_write,
                               slot_done_now & ~s_is_icache)
        return ms, progress

    # The phase ORDER is chosen so a miss resolves in ONE engine iteration
    # when no queued transaction is ahead of it: the request written by
    # phase (1) is popped by (3), whose INV/FLUSH/WB fan-out is
    # served by (4), whose acks finish the transaction in (5), whose reply
    # fills the requester in (6) — all mailbox hand-offs are visible
    # same-iteration because each phase reads the matrices its predecessor
    # just wrote.  Simulated time rides IN the messages, so this ordering
    # only compresses wall-clock iterations (the old order needed 2 per
    # fan-out miss); the timing algebra is unchanged.
    #
    # Per-phase activity gating (mp.phase_gate): each phase runs under its
    # OWN scalar-predicate lax.cond, computed from replicated control
    # state (mailboxes, txn, requester phase) at that point in the
    # sequence — so a phase a predecessor just fed still fires
    # same-iteration, and under shard_map every device takes the same
    # branch with no new collectives.  A phase with its predicate false is
    # a provable no-op (every write is masked by the very condition the
    # predicate disjoins over), so gating is bit-exact; the conds carry
    # only small per-phase state — see _cond_nodir/_cond_dir.

    gate = bool(getattr(mp, "phase_gate", False))
    consolidate = bool(getattr(mp, "base_consolidate", True))

    def _phase_requester(ms):
        prog = jnp.zeros((), jnp.int32)
        for _ in range(max(int(mp.requester_unroll), 1)):
            ms, prog = _requester_once(ms, prog)
        return ms, prog

    # ======================================================================
    # (1) requester slot starts (app-thread L1/L2 path)
    # ======================================================================
    # a lane that cannot start at block entry cannot start mid-unroll
    # either (only phase 6 returns a lane to PHASE_IDLE), so one
    # predicate covers the whole unrolled block
    pred1 = jnp.any(active & (ms.req.phase == PHASE_IDLE)
                    & (next_present(ms.req.slot) < 3))
    if gate:
        ms, p = _cond_nodir(pred1, _phase_requester, ms)
    else:
        ms, p = _phase_requester(ms)
    progress = progress + p

    # ======================================================================
    # (2) homes consume one EVICT per iteration
    # ======================================================================
    # Round-12 consolidated base: after the requester phase every set
    # the home phases can touch is known, so ONE packed working-set
    # gather (entry + sharers rows, staging overlaid) serves phases
    # 2/3/5, each phase's cond returns its delta plan for forwarding,
    # and the plans land in ONE merged scatter per store after phase 5.
    ws = None
    packs = []
    if consolidate:
        mail0 = ms.mail
        src_e0, _ = _row_earliest(mail0.evict_type, mail0.evict_time)
        eline0 = mail0.evict_line[tiles, src_e0]
        use_saved0 = ~ms.txn.active & ms.txn.saved_valid
        r_col0, _ = _req_earliest(mail0)
        rline0 = jnp.where(use_saved0, ms.txn.saved_line,
                           mail0.req_line[r_col0])
        ws = _DirWorkingSet(px, ms.directory, mp,
                            (eline0, rline0, ms.txn.line))

    def _run_dir_phase(pred, fn):
        """One home phase in the selected regime; consolidated runs
        collect the phase's delta plan into `packs`."""
        nonlocal ms, packs
        if consolidate:
            if gate:
                ms, p, pk = _cond_dir_c(pred, fn, ms, T)
            else:
                a = _DirAcc(consolidated=True)
                d0 = ms.directory
                ms, p = fn(ms, a)
                pk = a.pack_c(d0, T)
            packs.append(pk)
            return p
        if gate:
            ms, p = _cond_dir(pred, fn, ms)
            return p
        ms, p = fn(ms, None)
        return p

    pred2 = (ms.mail.evict_type != MSG_NONE).any()
    view2 = ws.view(0, eline0, packs) if consolidate else None
    p = _run_dir_phase(
        pred2,
        lambda m, a: _home_evictions(
            mp, m, dir_access_ps, enabled, jnp.zeros((), jnp.int32),
            px, acc=a, dsv=view2))
    progress = progress + p

    # ======================================================================
    # (3) homes start transactions (pop request / resume saved)
    # ======================================================================
    pred3 = ((ms.mail.req_type != MSG_NONE).any()
             | (ms.txn.saved_valid & ~ms.txn.active).any())
    view3 = ws.view(1, rline0, list(packs)) if consolidate else None
    p = _run_dir_phase(
        pred3,
        lambda m, a: _home_starts(
            mp, m, dram_lat_ps, dir_access_ps, sync_dir_l2,
            sync_dir_net, enabled, jnp.zeros((), jnp.int32), px,
            acc=a, dsv=view3))
    progress = progress + p

    # ======================================================================
    # (4) sharers consume one FWD per iteration
    # ======================================================================
    pred4 = (ms.mail.fwd_type != MSG_NONE).any()
    if gate:
        ms, p = _cond_nodir(
            pred4,
            lambda m: _sharer_step(mp, m, fmhz, enabled,
                                   jnp.zeros((), jnp.int32),
                                   sync_l2_net, sync_l1d_l2, px),
            ms)
    else:
        ms, p = _sharer_step(mp, ms, fmhz, enabled,
                             jnp.zeros((), jnp.int32),
                             sync_l2_net, sync_l1d_l2, px)
    progress = progress + p

    # ======================================================================
    # (5) homes consume ACKs, finish transactions
    # ======================================================================
    pred5 = (ms.mail.ack_type != MSG_NONE).any() | ms.txn.active.any()
    view5 = (ws.view_finish(ms.txn.line, list(packs))
             if consolidate else None)
    p = _run_dir_phase(
        pred5,
        lambda m, a: _home_acks_and_finish(
            mp, m, dram_lat_ps, dir_access_ps, enabled,
            jnp.zeros((), jnp.int32), px, acc=a, dsv=view5))
    progress = progress + p
    if consolidate:
        # the ONE merged scatter per big store for this iteration
        ms = ms.replace(directory=_dir_apply_merged(
            ms.directory, px, packs))

    # ======================================================================
    # (6) requesters consume replies (fill L2+L1, complete slot)
    # ======================================================================
    pred6 = ((ms.req.phase == PHASE_WAIT_REPLY)
             & (ms.mail.rep_type != MSG_NONE)).any()
    # fill observability: only phase 6's fill advances req.slot / adds to
    # req.acc_ps, so the pre/post delta IS the per-call fill event — exact
    # even when the whole miss started in phase 1 of this same call
    slot_pre6 = ms.req.slot
    acc_pre6 = ms.req.acc_ps
    if gate:
        ms, p = _cond_nodir(
            pred6,
            lambda m: _requester_fill(mp, m, rec, clock_ps, fmhz, enabled,
                                      jnp.zeros((), jnp.int32),
                                      sync_l2_net, px),
            ms)
    else:
        ms, p = _requester_fill(mp, ms, rec, clock_ps, fmhz, enabled,
                                jnp.zeros((), jnp.int32), sync_l2_net, px)
    progress = progress + p

    # ---- completion signal ----------------------------------------------
    final_slot = next_present(ms.req.slot)
    mem_complete = (ms.req.phase == PHASE_IDLE) & (final_slot >= 3)
    # protocol-liveness flag: lets the caller skip the whole engine on
    # iterations with no memory work (see mem_idle_out)
    ms = ms.replace(live=protocol_live(ms))
    if gate:
        skipped = 1 - jnp.stack(
            [pred1, pred2, pred3, pred4, pred5, pred6]).astype(I64)
        ms = ms.replace(phase_skips=ms.phase_skips + skipped)
    return MemStepOut(
        ms=ms, mem_complete=mem_complete, acc_ps=ms.req.acc_ps,
        slot_lat_ps=ms.req.slot_lat_ps,
        progress=progress,
        fill_now=(ms.req.slot != slot_pre6) if fill_events else None,
        fill_lat_ps=(ms.req.acc_ps - acc_pre6) if fill_events else None,
    )


# --------------------------------------------------------------------------
# functional memory


def _apply_functional(mp, ms: MemState, rec: RecView, slot, s_addr, s_write,
                      mask):
    if mp.func_mem_words <= 0:
        return ms
    word = ((s_addr.astype(jnp.uint32) >> 2) % mp.func_mem_words).astype(
        jnp.int32)
    value = jnp.where(slot == 1, rec.aux0, rec.aux1).astype(jnp.uint32)
    wr = mask & s_write
    # masked-off lanes write a dedicated scratch slot (the last word) so a
    # dummy write can never clobber a live one
    tgt = jnp.where(wr, word, mp.func_mem_words)
    fm = ms.func_mem.at[tgt].set(jnp.where(wr, value, 0))
    check = mask & ~s_write & (slot == 1) & ((rec.flags & FLAG_CHECK) != 0)
    loaded = fm[word]
    errs = jnp.sum(check & (loaded != rec.aux0.astype(jnp.uint32)),
                   dtype=I64)
    return ms.replace(func_mem=fm, func_errors=ms.func_errors + errs)


# --------------------------------------------------------------------------
# sharer-side FWD service (`l2_cache_cntlr.cc:295-503`)


def _sharer_step(mp, ms: MemState, fmhz, enabled, progress,
                 sync_l2_net, sync_l1d_l2, px: ParallelCtx = IDENT):
    T = mp.n_tiles
    tiles = np.arange(T, dtype=np.int32)
    mail = ms.mail

    def ccyc(n):
        ps = cycles_to_ps(jnp.asarray(n, I64), fmhz)
        return jnp.where(enabled, ps, 0)

    h, found = _row_earliest(mail.fwd_type, mail.fwd_time)
    ftype = mail.fwd_type[tiles, h]
    fline = mail.fwd_line[tiles, h]
    ftime = mail.fwd_time[tiles, h]

    # block-local row gathers at the served line (+ the cached-loc SET row
    # — way selection happens replicated after the exchange; single-device
    # keeps the direct element read)
    fline_l = px.lo(fline)
    l2_mod_l = px.lo_const(mp.l2.sets_mod)
    sets_l = nn_mod(fline_l, jnp.asarray(l2_mod_l)).astype(jnp.int32)
    lt = np.arange(ms.l2.meta.shape[0], dtype=np.int32)
    rows_l = (ca.gather_row(ms.l2, fline_l, l2_mod_l, nonneg=True),
              ca.gather_row(ms.l1i, fline_l, px.lo_const(mp.l1i.sets_mod),
                            nonneg=True),
              ca.gather_row(ms.l1d, fline_l, px.lo_const(mp.l1d.sets_mod),
                            nonneg=True))
    util_row_l = (_util_row_local(ms.l2_util, fline_l, l2_mod_l)
                  if mp.l2.track_line_utilization else None)
    if px.sharded:
        extras = (ms.l2_cloc[lt, sets_l],)
        if util_row_l is not None:
            extras = extras + (util_row_l,)
        (l2_r, l1i_r, l1d_r), extras = _rows_exchange(px, rows_l, extras)
        cloc_row = extras[0]
        lu_row = extras[1] if util_row_l is not None else None
    else:
        l2_r, l1i_r, l1d_r = rows_l
        cloc_row = None
        lu_row = util_row_l
    l2_hit, l2_way, l2_state = ca.row_lookup(l2_r, fline)
    serve = found & l2_hit & (l2_state != INVALID)
    silent = found & ~serve  # already evicted; eviction msg satisfies home

    # time: network sync + L2 access + L1 tag access + domain syncs
    # (`processInvReqFromDramDirectory` / Flush / Wb)
    is_inv = ftype == MSG_INV_REQ
    l2_cost = jnp.where(is_inv, ccyc(mp.l2.tags_cycles),
                        ccyc(mp.l2.data_and_tags_cycles))
    l1_cost = ccyc(mp.l1d.tags_cycles)
    done_ps = ftime + sync_l2_net + l2_cost + l1_cost + 2 * sync_l1d_l2

    # invalidate / downgrade L1 (whichever L1 holds it, by cached-loc)
    sets = nn_mod(fline, jnp.asarray(mp.l2.sets_mod)).astype(jnp.int32)
    if cloc_row is not None:
        cloc = jnp.take_along_axis(cloc_row, l2_way[:, None], axis=1)[:, 0]
    else:
        cloc = ms.l2_cloc[tiles, sets, l2_way]
    inv_l1 = serve & (ftype != MSG_WB_REQ)
    wb_l1 = serve & (ftype == MSG_WB_REQ)
    l1i_r = ca.row_invalidate(l1i_r, fline, inv_l1 & (cloc == MOD_L1I))
    l1d_r = ca.row_invalidate(l1d_r, fline, inv_l1 & (cloc == MOD_L1D))
    l1i_hit, l1i_way, _ = ca.row_lookup(l1i_r, fline)
    l1d_hit, l1d_way, _ = ca.row_lookup(l1d_r, fline)
    # WB downgrade: MSI M→SHARED; MOSI M→OWNED, O→O, S→S (the owner keeps
    # the dirty line — mosi `l2_cache_cntlr.cc:538-566`)
    if mp.is_mosi:
        wb_state = jnp.where(l2_state == MODIFIED, OWNED,
                             l2_state).astype(jnp.uint8)
    else:
        wb_state = jnp.full_like(l2_state, SHARED)
    l1i_r = ca.row_set_state(l1i_r, l1i_way, wb_state,
                             wb_l1 & (cloc == MOD_L1I) & l1i_hit)
    l1d_r = ca.row_set_state(l1d_r, l1d_way, wb_state,
                             wb_l1 & (cloc == MOD_L1D) & l1d_hit)
    l1i = ca.scatter_row(ms.l1i, px.lo(l1i_r))
    l1d = ca.scatter_row(ms.l1d, px.lo(l1d_r))

    # L2: invalidate (INV/FLUSH) or downgrade (WB)
    l2_r = ca.row_invalidate(l2_r, fline, inv_l1)
    l2_r = ca.row_set_state(l2_r, l2_way, wb_state, wb_l1)
    l2 = ca.scatter_row(ms.l2, px.lo(l2_r))
    if mp.l2.track_line_utilization:
        # the INV/FLUSH'd line leaves the L2: classify + zero its counters
        en = jnp.asarray(enabled, bool)
        lu_cur = jnp.take_along_axis(lu_row, l2_way[:, None], axis=1)[:, 0]
        ms = ms.replace(
            l2_util=_util_scatter(
                px, ms.l2_util, fline, mp.l2.sets_mod, l2_way, lu_cur,
                jnp.where(inv_l1 & en, jnp.uint32(0), lu_cur)),
            counters=_util_classify(ms.counters, lu_cur, inv_l1, enabled))
    if mp.l2.track_miss_types:
        ms = ms.replace(mt=_mt_update(ms.mt, MT_INVALIDATED, fline_l,
                                      px.lo(inv_l1), True))
    l2_cloc = px.entry_set(ms.l2_cloc, sets_l, px.lo(l2_way),
                           px.lo(inv_l1), 0)

    # ack message back to the home
    ack = jnp.where(
        ftype == MSG_INV_REQ, MSG_INV_REP,
        jnp.where(ftype == MSG_FLUSH_REQ, MSG_FLUSH_REP, MSG_WB_REP),
    ).astype(jnp.uint8)
    # serialization differs per type (INV acks are header-only, FLUSH/WB
    # carry the line)
    ack_bits = jnp.where(is_inv, mp.req_bits, mp.rep_bits)
    noc, ack_arrival = mem_net_send(
        mp, ms.noc, tiles, h, ack_bits, done_ps, serve, enabled)
    wh = jnp.where(serve, h, 0)
    mail = mail.replace(
        ack_type=mail.ack_type.at[wh, tiles].set(
            jnp.where(serve, ack, mail.ack_type[wh, tiles])),
        ack_line=mail.ack_line.at[wh, tiles].set(
            jnp.where(serve, fline, mail.ack_line[wh, tiles])),
        ack_time=mail.ack_time.at[wh, tiles].set(
            jnp.where(serve, ack_arrival, mail.ack_time[wh, tiles])),
    )
    # consume the fwd cell
    ch = jnp.where(found, h, 0)
    mail = mail.replace(
        fwd_type=mail.fwd_type.at[tiles, ch].set(
            jnp.where(found, MSG_NONE, mail.fwd_type[tiles, ch])),
    )
    counters = ms.counters.replace(
        invalidations=ms.counters.invalidations
        + (serve & is_inv & enabled).astype(I64),
    )
    progress = progress + jnp.sum(found, dtype=jnp.int32)
    return ms.replace(l1i=l1i, l1d=l1d, l2=l2, l2_cloc=l2_cloc, mail=mail,
                      counters=counters, noc=noc), progress


# --------------------------------------------------------------------------
# home-side: evictions (`processInvRepFromL2Cache` / `processFlushRep...`
# "just an eviction" branches)


def _home_evictions(mp, ms: MemState, dir_access_ps, enabled, progress,
                    px: ParallelCtx = IDENT, acc: "_DirAcc | None" = None,
                    dsv=None):
    T = mp.n_tiles
    tiles = np.arange(T, dtype=np.int32)
    mail = ms.mail

    src, found = _row_earliest(mail.evict_type, mail.evict_time)
    etype = mail.evict_type[tiles, src]
    eline = mail.evict_line[tiles, src]
    etime = mail.evict_time[tiles, src]

    d = ms.directory
    if dsv is None:
        dsv = _DirSetView(px, d, eline, mp)
    vw = dsv if isinstance(dsv, _DirRowView) else None
    sets = dsv.sets
    dfound, way = dsv.lookup()
    apply = found & dfound
    _, dstate, owner, sharers, nsh = dsv.entry(way)

    was_sharer = test_bit(sharers, src)
    new_sharers = clear_bit(sharers, src, apply)
    new_nsh = nsh - (apply & was_sharer).astype(jnp.int32)
    is_flush = etype == MSG_FLUSH_REP
    new_owner = jnp.where(apply & is_flush, -1, owner)
    # empty entry → UNCACHED; a dirty (owner) departure with sharers left
    # behind → SHARED (the MOSI O→S downgrade; MSI flushes always empty the
    # entry so the same formula holds)
    new_dstate = jnp.where(
        apply,
        jnp.where(new_nsh == 0, DIR_UNCACHED,
                  jnp.where(is_flush, DIR_SHARED, dstate)),
        dstate,
    ).astype(jnp.uint8)
    d = _dir_update(d, sets, way, apply, px=px, dstate=new_dstate,
                    owner=new_owner, sharers=new_sharers, nsharers=new_nsh,
                    acc=acc, view=vw)

    # active same-line transaction: treat the eviction as the ack
    txn = ms.txn
    txn_match = txn.active & found & (txn.line == eline)
    txn = txn.replace(
        pending=clear_bit(txn.pending, src, txn_match),
        time_ps=jnp.where(txn_match,
                          jnp.maximum(txn.time_ps, etime + dir_access_ps),
                          txn.time_ps),
        data_cached=txn.data_cached | (txn_match & is_flush),
        # park flushed data in the home's one-entry buffer
        # (`_cached_data_list`): a later request for the line skips DRAM
        cdata_line=jnp.where(found & is_flush, eline, txn.cdata_line),
        cdata_valid=txn.cdata_valid | (found & is_flush),
    )

    csrc = jnp.where(found, src, 0)
    mail = mail.replace(
        evict_type=mail.evict_type.at[tiles, csrc].set(
            jnp.where(found, MSG_NONE, mail.evict_type[tiles, csrc])),
    )
    counters = ms.counters.replace(
        evictions=ms.counters.evictions + (found & enabled).astype(I64),
        dram_writes=ms.counters.dram_writes
        + (found & is_flush & enabled).astype(I64),
    )
    progress = progress + jnp.sum(found, dtype=jnp.int32)
    return ms.replace(directory=d, txn=txn, mail=mail,
                      counters=counters), progress


# --------------------------------------------------------------------------
# home-side: ack consumption + transaction finish


def _home_acks_and_finish(mp, ms: MemState, dram_lat_ps, dir_access_ps,
                          enabled, progress, px: ParallelCtx = IDENT,
                          acc: "_DirAcc | None" = None, dsv=None):
    T = mp.n_tiles
    tiles = np.arange(T, dtype=np.int32)
    mail = ms.mail
    txn = ms.txn

    # consume ALL matching acks per home row at once (row-wise reduction;
    # each ack clears a distinct pending bit, times are max-reduced)
    match = (mail.ack_type != MSG_NONE) & txn.active[:, None] & (
        mail.ack_line == txn.line[:, None])
    any_match = match.any(axis=1)
    max_ack = jnp.where(match, mail.ack_time, 0).max(axis=1)
    got_data = (match & ((mail.ack_type == MSG_FLUSH_REP)
                         | (mail.ack_type == MSG_WB_REP))).any(axis=1)
    wb_any = (match & (mail.ack_type == MSG_WB_REP)).any(axis=1)

    # clear pending bits for acked sharers: pack match row back to words
    SW = mp.sharer_words
    pad = SW * 32 - T
    mpad = jnp.pad(match, ((0, 0), (0, pad)))
    acked_words = (
        mpad.reshape(T, SW, 32).astype(U32)
        << jnp.arange(32, dtype=U32)[None, None, :]
    ).sum(axis=2, dtype=U32)
    txn = txn.replace(
        pending=txn.pending & ~acked_words,
        time_ps=jnp.where(any_match,
                          jnp.maximum(txn.time_ps, max_ack + dir_access_ps),
                          txn.time_ps),
        data_cached=txn.data_cached | got_data,
    )
    # drop every ack cell (matched = consumed; stale = dropped)
    mail = mail.replace(ack_type=jnp.where(
        mail.ack_type != MSG_NONE, MSG_NONE, mail.ack_type))

    # ---- finish transactions whose pending set is empty ------------------
    no_pending = (txn.pending == 0).all(axis=1)
    finish = txn.active & no_pending
    is_ex = txn.mtype == MSG_EX_REQ
    is_sh = txn.mtype == MSG_SH_REQ
    is_nullify = txn.mtype == MSG_NULLIFY

    d = ms.directory
    if dsv is None:
        dsv = _DirSetView(px, d, txn.line, mp)
    vw = dsv if isinstance(dsv, _DirRowView) else None
    sets = dsv.sets
    dfound, way = dsv.lookup()
    r = txn.requester
    rbit_words = jnp.zeros((T, mp.sharer_words), U32)
    rbit_words = set_bit(rbit_words, r, finish)

    # EX finish: M, owner=r, sharers={r} (`processExReqFromL2Cache` UNCACHED
    # branch after invalidations).  SH finish: add r as sharer.  MSI: entry
    # becomes SHARED ownerless (`processWbRepFromL2Cache`).  MOSI: a dirty
    # source keeps the line — M/O entries become/stay OWNED with the owner
    # retained (mosi `processWbRepFromL2Cache` M→OWNED, `restartShmemReq`).
    # The two cases are disjoint masks on the SAME entry, merged into ONE
    # _dir_update: every scatter on the directory arrays that XLA fails to
    # alias costs a whole-array copy per iteration (the [T, DS, DW, SW]
    # sharers tensor is 2 GB at 1024 tiles — see PERF.md).
    exf = finish & is_ex & dfound
    _, cur_dstate, cur_owner, cur_sharers, cur_nsh = dsv.entry(way)
    shf = finish & is_sh & dfound
    had = test_bit(cur_sharers, r)
    if mp.is_mosi:
        from_dirty = (cur_dstate == DIR_MODIFIED) | (cur_dstate == DIR_OWNED)
        sh_dstate = jnp.where(from_dirty, DIR_OWNED,
                              DIR_SHARED).astype(jnp.uint8)
        sh_owner = jnp.where(from_dirty, cur_owner, -1)
    else:
        sh_dstate = jnp.full(T, DIR_SHARED, jnp.uint8)
        sh_owner = jnp.full(T, -1, jnp.int32)
    fin_upd = exf | shf
    d = _dir_update(
        d, sets, way, fin_upd, px=px,
        dstate=jnp.where(exf, DIR_MODIFIED, sh_dstate).astype(jnp.uint8),
        owner=jnp.where(exf, r, sh_owner),
        sharers=jnp.where(exf[:, None], rbit_words,
                          set_bit(cur_sharers, r, shf)),
        nsharers=jnp.where(exf, 1, cur_nsh + (~had).astype(jnp.int32)),
        acc=acc, view=vw)
    # NULLIFY finish: the entry was already replaced at allocation; nothing
    # directory-side remains (`processNullifyReq` UNCACHED branch)

    # reply to requester (dram read only if the data did not come back
    # cached via FLUSH/WB or sit in the home's flushed-data buffer —
    # `retrieveDataAndSendToL2Cache` checks `_cached_data_list` first)
    cdata_hit = txn.cdata_valid & (txn.cdata_line == txn.line)
    data_avail = txn.data_cached | cdata_hit
    need_dram = finish & ~data_avail & ~is_nullify
    rep_ready_ps = txn.time_ps + jnp.where(need_dram, dram_lat_ps, 0)
    rep_msg = jnp.where(is_ex, MSG_EX_REP, MSG_SH_REP).astype(jnp.uint8)
    rep_go = finish & ~is_nullify
    noc, rep_arrival = mem_net_send(
        mp, ms.noc, tiles, r, mp.rep_bits, rep_ready_ps, rep_go, enabled)
    # add-delta scatter: target cells are zero (the requester resets both
    # fields on consumption), so masked-off dummy writes to cell 0 add 0
    # and can never clobber a live reply
    wr = jnp.where(rep_go, r, 0)
    mail = mail.replace(
        rep_type=mail.rep_type.at[wr].add(
            jnp.where(rep_go, rep_msg, 0).astype(jnp.uint8)),
        rep_time=mail.rep_time.at[wr].add(
            jnp.where(rep_go, rep_arrival, 0)),
    )
    # clear our FWD column so stale multicasts cannot leak into the next
    # transaction (see module docstring)
    mail = mail.replace(
        fwd_type=jnp.where(finish[None, :], MSG_NONE, mail.fwd_type))

    txn = txn.replace(
        active=txn.active & ~finish,
        last_line=jnp.where(finish, txn.line, txn.last_line),
        last_done_ps=jnp.where(finish, rep_ready_ps, txn.last_done_ps),
        cdata_valid=txn.cdata_valid & ~(finish & cdata_hit),  # consumed
    )
    # MSI writes WB data through to DRAM (the entry turns SHARED clean);
    # MOSI keeps it dirty at the owner (entry turns OWNED) — DRAM is only
    # written when dirty lines are evicted/flushed
    wb_writes_dram = (jnp.zeros_like(wb_any) if mp.is_mosi else wb_any)
    counters = ms.counters.replace(
        dram_reads=ms.counters.dram_reads + (need_dram & enabled).astype(I64),
        dram_writes=ms.counters.dram_writes
        + (wb_writes_dram & enabled).astype(I64),
        dram_total_lat_ps=ms.counters.dram_total_lat_ps
        + jnp.where(need_dram & enabled, dram_lat_ps, 0),
    )
    progress = progress + jnp.sum(finish, dtype=jnp.int32) + jnp.sum(
        any_match, dtype=jnp.int32)
    return ms.replace(directory=d, txn=txn, mail=mail,
                      counters=counters, noc=noc), progress


# --------------------------------------------------------------------------
# home-side: transaction start (pop request or resume saved original)


def _home_starts(mp, ms: MemState, dram_lat_ps, dir_access_ps,
                 sync_dir_l2, sync_dir_net, enabled, progress,
                 px: ParallelCtx = IDENT, acc: "_DirAcc | None" = None,
                 dsv=None):
    T = mp.n_tiles
    tiles = np.arange(T, dtype=np.int32)
    mail = ms.mail
    txn = ms.txn

    can_start = ~txn.active
    # source 1: saved original request (after a NULLIFY completed)
    use_saved = can_start & txn.saved_valid
    # source 2: earliest pending request lane targeting this home
    r_col, r_found = _req_earliest(mail)
    use_pop = can_start & ~use_saved & r_found

    starting = use_saved | use_pop
    rtype = jnp.where(use_saved, txn.saved_type,
                      mail.req_type[r_col]).astype(jnp.uint8)
    rline = jnp.where(use_saved, txn.saved_line, mail.req_line[r_col])
    rreq = jnp.where(use_saved, txn.saved_requester, r_col)
    rtime = jnp.where(use_saved, txn.saved_time_ps,
                      mail.req_time[r_col])
    # message sync at the directory (`handleMsgFromL2Cache` entry) —
    # charged once per message: saved_time_ps already includes it, so
    # resumed requests (post-NULLIFY) must not pay it again
    rtime = rtime + jnp.where(
        use_saved, 0, jnp.where(rreq == tiles, sync_dir_l2, sync_dir_net)
    )
    # same-address serialization floor (`processNextReqFromL2Cache` time
    # update for queued same-address requests)
    rtime = jnp.where(starting & (rline == txn.last_line),
                      jnp.maximum(rtime, txn.last_done_ps), rtime)

    # consume the popped lane
    mail = _req_consume(mail, use_pop, r_col)
    txn = txn.replace(saved_valid=txn.saved_valid & ~use_saved)

    # ---- directory entry lookup / allocation -----------------------------
    d = ms.directory
    if dsv is None:
        dsv = _DirSetView(px, d, rline, mp)
    vw = dsv if isinstance(dsv, _DirRowView) else None
    sets = dsv.sets
    dfound, way = dsv.lookup()
    tag_row, nsh_row = dsv.rows()
    # free way if no match (tags == -1)
    free_ways = tag_row == -1
    any_free = free_ways.any(axis=1)
    free_way = jnp.argmax(free_ways, axis=1).astype(jnp.int32)
    # victim: min sharers (`processDirectoryEntryAllocationReq`)
    victim_way = jnp.argmin(nsh_row, axis=1).astype(jnp.int32)
    alloc_way = jnp.where(dfound, way, jnp.where(any_free, free_way,
                                                 victim_way)).astype(jnp.int32)
    need_nullify = starting & ~dfound & ~any_free

    # victim entry contents (for the NULLIFY transaction)
    v_line, v_dstate, v_owner, v_sharers, v_nsh = dsv.entry(alloc_way)

    # the new entry's install (the reference's `replaceDirectoryEntry`
    # immediate swap) is merged into the immediate-finish update below —
    # one scatter on the directory arrays instead of two (each unaliased
    # scatter costs a whole-array copy; see _dir_update)
    is_new = starting & ~dfound

    # ---- NULLIFY path ----------------------------------------------------
    # save the original request; run the nullify on the victim line
    nullify_live = need_nullify & (v_dstate != DIR_UNCACHED)
    txn = txn.replace(
        saved_valid=jnp.where(nullify_live, True, txn.saved_valid),
        saved_type=jnp.where(nullify_live, rtype, txn.saved_type),
        saved_line=jnp.where(nullify_live, rline, txn.saved_line),
        saved_requester=jnp.where(nullify_live, rreq, txn.saved_requester),
        saved_time_ps=jnp.where(nullify_live, rtime, txn.saved_time_ps),
    )

    # ---- state branch for the (non-nullify) request ----------------------
    run_req = starting & ~nullify_live
    dstate = jnp.where(dfound, v_dstate, DIR_UNCACHED).astype(jnp.uint8)
    # entry state for nullify runs is the *victim's*
    eff_line = jnp.where(nullify_live, v_line, rline)
    eff_type = jnp.where(nullify_live, MSG_NULLIFY, rtype).astype(jnp.uint8)
    eff_dstate = jnp.where(nullify_live, v_dstate, dstate).astype(jnp.uint8)
    eff_time = rtime + dir_access_ps

    is_ex = eff_type == MSG_EX_REQ
    is_sh = eff_type == MSG_SH_REQ

    uncached = eff_dstate == DIR_UNCACHED
    shared = eff_dstate == DIR_SHARED
    modified = eff_dstate == DIR_MODIFIED
    owned = eff_dstate == DIR_OWNED

    # ---- directory-scheme variants (`directory_schemes/directory_entry_*.cc`,
    # `directory_type.h:3`).  full_map tracks every sharer exactly; the
    # other schemes cap the hardware sharer list at k = max_hw_sharers:
    #  - limited_no_broadcast: a (k+1)-th sharer cannot be tracked — the
    #    home invalidates one tracked sharer first (addSharer failure →
    #    getSharerToInvalidate → INV, buffered request then proceeds);
    #  - ackwise / limited_broadcast: beyond k the precise list degrades
    #    (AckWise keeps the exact *count*); invalidation sweeps become a
    #    broadcast to every tile, but the home still awaits acks only from
    #    true holders (non-holders drop the INV silently);
    #  - limitless: overflow handled in software — full_map behavior plus a
    #    software-trap penalty on accesses to overflowed entries
    #    (`[limitless] software_trap_penalty`, `carbon_sim.cfg:260-263`).
    k = mp.max_hw_sharers
    already = test_bit(v_sharers, rreq)
    if mp.dir_type == "limited_no_broadcast":
        sh_over = run_req & is_sh & (shared | owned) & (v_nsh >= k) & ~already
        # MODIFIED entry already at capacity (k=1): the owner cannot stay a
        # tracked sharer alongside the requester — its WB becomes a FLUSH
        # (data + invalidation) and the entry empties before the SH finish
        # adds the requester (addSharer failure on the M→S transition)
        sh_over_m = run_req & is_sh & modified & (v_nsh >= k) & ~already
    else:
        sh_over = jnp.zeros((T,), jnp.bool_)
        sh_over_m = jnp.zeros((T,), jnp.bool_)
    if mp.dir_type == "limitless":
        sw_mode = (v_nsh > k) | (is_sh & ~already & (v_nsh >= k)
                                 & (shared | owned))
        trap_ps = jnp.where(
            enabled & starting & dfound & sw_mode,
            cycles_to_ps(jnp.asarray(mp.limitless_trap_cycles, I64),
                         mp.dir_freq_mhz),
            0,
        )
        eff_time = eff_time + trap_ps

    # (a) immediate finishes: UNCACHED requests; MSI also serves SHARED+SH
    # straight from DRAM, while MOSI fetches cache-to-cache (below)
    imm_ex = run_req & is_ex & uncached
    if mp.is_mosi:
        imm_sh = run_req & is_sh & uncached
    else:
        imm_sh = run_req & is_sh & (uncached | shared) & ~sh_over
    imm = imm_ex | imm_sh
    rbit = set_bit(jnp.zeros((T, mp.sharer_words), U32), rreq, imm)
    cur_sh = jnp.where(imm_sh[:, None] & shared[:, None], v_sharers,
                       jnp.zeros_like(v_sharers))
    had = test_bit(cur_sh, rreq)
    # ONE merged scatter: new-entry install (UNCACHED empty, including the
    # entry swapped in under a pending NULLIFY) + immediate finishes; the
    # two overlap on is_new & imm lanes where the finish value wins.  For
    # imm-on-found lanes tags rewrite their current value (v_line == rline
    # when dfound).
    upd = is_new | imm
    d = _dir_update(
        d, sets, alloc_way, upd, px=px, acc=acc, view=vw,
        tags=jnp.where(is_new, rline, v_line),
        dstate=jnp.where(
            imm, jnp.where(imm_ex, DIR_MODIFIED, DIR_SHARED),
            DIR_UNCACHED).astype(jnp.uint8),
        owner=jnp.where(imm_ex, rreq, -1),
        sharers=jnp.where(imm[:, None], cur_sh | rbit,
                          jnp.zeros((T, mp.sharer_words), U32)),
        nsharers=jnp.where(
            imm_ex, 1,
            jnp.where(imm, popcount(cur_sh) + (~had).astype(jnp.int32), 0)))
    # UNCACHED/SHARED reads hit DRAM unless the home's flushed-data buffer
    # holds the line (`retrieveDataAndSendToL2Cache` cached-data lookup)
    cdata_imm = txn.cdata_valid & (txn.cdata_line == eff_line) & imm
    rep_ready = eff_time + jnp.where(cdata_imm, 0, dram_lat_ps)
    txn = txn.replace(cdata_valid=txn.cdata_valid & ~cdata_imm)
    noc = ms.noc
    noc, imm_arrival = mem_net_send(
        mp, noc, tiles, rreq, mp.rep_bits, rep_ready, imm, enabled)
    # add-delta scatter (cells zero before a live write; see finish path)
    wr = jnp.where(imm, rreq, 0)
    mail = mail.replace(
        rep_type=mail.rep_type.at[wr].add(
            jnp.where(imm, jnp.where(imm_ex, MSG_EX_REP, MSG_SH_REP), 0
                      ).astype(jnp.uint8)),
        rep_time=mail.rep_time.at[wr].add(
            jnp.where(imm, imm_arrival, 0)),
    )
    txn = txn.replace(
        last_line=jnp.where(imm, eff_line, txn.last_line),
        last_done_ps=jnp.where(imm, rep_ready, txn.last_done_ps),
    )

    # (b) fan-out transactions: EX/NULLIFY on SHARED (INV multicast; in
    #     MOSI also on OWNED, where the owner gets FLUSH and the rest INV),
    #     anything on MODIFIED (FLUSH/WB to owner), and — MOSI only — SH on
    #     SHARED/OWNED fetching the data cache-to-cache from one sharer
    #     (mosi `dram_directory_cntlr.cc:430-520`)
    if mp.is_mosi:
        fan_inv = ((run_req & is_ex) | nullify_live) & (shared | owned)
        sh_fetch = run_req & is_sh & (shared | owned) & ~sh_over
    else:
        fan_inv = (run_req & is_ex & shared) | (nullify_live & shared)
        sh_fetch = jnp.zeros((T,), jnp.bool_)
    fan_owner = ((run_req | nullify_live) & modified)
    fan = fan_inv | fan_owner | sh_fetch | sh_over
    owner_bits = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                         jnp.clip(v_owner, 0, T - 1), fan_owner)
    # cache-to-cache source: the owner when the entry is OWNED (it has the
    # dirty line), else the lowest-id sharer (deterministic getOneSharer)
    fetch_src = jnp.where(owned & (v_owner >= 0), v_owner,
                          lowest_sharer(v_sharers))
    fetch_bits = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                         jnp.clip(fetch_src, 0, T - 1),
                         sh_fetch & (fetch_src >= 0))
    pending = jnp.where(
        fan_inv[:, None], v_sharers,
        jnp.where(sh_fetch[:, None], fetch_bits, owner_bits))
    fwd_msg = jnp.where(
        fan_inv, MSG_INV_REQ,
        jnp.where(is_sh, MSG_WB_REQ, MSG_FLUSH_REQ)).astype(jnp.uint8)

    if mp.dir_type == "limited_no_broadcast":
        # victim sharer to evict so the requester fits in the hw list:
        # lowest non-owner sharer (the owner holds dirty data); when the
        # owner is the only sharer, it is flushed instead (data + invalidate)
        owner_word = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                             jnp.clip(v_owner, 0, T - 1),
                             owned & (v_owner >= 0))
        victim0 = lowest_sharer(v_sharers & ~owner_word)
        victim_is_owner = sh_over & (victim0 < 0)
        victim = jnp.where(victim0 >= 0, victim0,
                           jnp.clip(v_owner, 0, T - 1)).astype(jnp.int32)
        victim_bits = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                              victim, sh_over)
        # drop the victim from the entry now — its INV/FLUSH ack is consumed
        # by this transaction, not the eviction path (one txn per home)
        d = _dir_update(
            d, sets, alloc_way, sh_over, px=px, acc=acc, view=vw,
            sharers=v_sharers & ~victim_bits,
            nsharers=v_nsh - 1,
            owner=jnp.where(victim_is_owner, -1, v_owner),
            dstate=jnp.where(victim_is_owner, DIR_SHARED,
                             eff_dstate).astype(jnp.uint8))
        # acks awaited: the victim, plus the data-supplying owner (MOSI
        # OWNED entries fetch cache-to-cache alongside the invalidation)
        ow_pend = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                          jnp.clip(v_owner, 0, T - 1),
                          sh_over & owned & ~victim_is_owner & (v_owner >= 0))
        pending = jnp.where(sh_over[:, None], victim_bits | ow_pend, pending)
        fwd_msg = jnp.where(sh_over, MSG_INV_REQ, fwd_msg).astype(jnp.uint8)
        # M→S at capacity: FLUSH the owner instead of WB and empty the
        # entry now (the SH finish then installs {requester} alone)
        fwd_msg = jnp.where(sh_over_m, MSG_FLUSH_REQ, fwd_msg).astype(
            jnp.uint8)
        d = _dir_update(
            d, sets, alloc_way, sh_over_m, px=px, acc=acc, view=vw,
            sharers=jnp.zeros((T, mp.sharer_words), U32),
            nsharers=jnp.zeros(T, jnp.int32),
            owner=jnp.full(T, -1, jnp.int32),
            dstate=jnp.full(T, DIR_UNCACHED, jnp.uint8))

    txn = txn.replace(
        active=txn.active | fan,
        mtype=jnp.where(fan, eff_type, txn.mtype).astype(jnp.uint8),
        line=jnp.where(fan, eff_line, txn.line),
        requester=jnp.where(fan, rreq, txn.requester),
        time_ps=jnp.where(fan, eff_time, txn.time_ps),
        pending=jnp.where(fan[:, None], pending, txn.pending),
        data_cached=jnp.where(fan, False, txn.data_cached),
    )

    # dense multicast into the FWD matrix: [sharer, home]
    targets = unpack_sharers(pending, T)          # [home, sharer]
    send = fan[:, None] & targets                 # [home, sharer]
    send_t = send.T                               # [sharer, home]
    msg_hs = jnp.broadcast_to(fwd_msg[:, None], (T, T))  # [home, sharer]
    if mp.is_mosi:
        # one target of an invalidation sweep supplies the data by FLUSH
        # (`INV_FLUSH_COMBINED_REQ`, mosi `dram_directory_cntlr.cc:385-395`):
        # the owner when the entry is OWNED (dirty), else one sharer for an
        # EX on SHARED — the EX then completes cache-to-cache with no DRAM
        # read.  NULLIFY on SHARED keeps plain INVs (data is clean in DRAM).
        flush_pick = jnp.where(owned & (v_owner >= 0), v_owner,
                               lowest_sharer(v_sharers))
        pick_col = tiles[None, :] == flush_pick[:, None]  # [home, sharer]
        pick_rows = (fan_inv & (owned | (run_req & is_ex & shared)))
        msg_hs = jnp.where(
            pick_rows[:, None] & pick_col,
            jnp.uint8(MSG_FLUSH_REQ), msg_hs)
    if mp.dir_type == "limited_no_broadcast" and mp.is_mosi:
        # data supplier for the displaced SH: the victim FLUSHes when it
        # must both leave and supply (clean c2c pick, or the owner-is-victim
        # corner); otherwise the owner WBs alongside the victim's INV
        victim_col = tiles[None, :] == victim[:, None]
        owner_col = tiles[None, :] == jnp.clip(v_owner, 0, T - 1)[:, None]
        msg_hs = jnp.where(
            (sh_over & (shared | victim_is_owner))[:, None] & victim_col,
            jnp.uint8(MSG_FLUSH_REQ), msg_hs)
        msg_hs = jnp.where(
            (sh_over & owned & ~victim_is_owner)[:, None] & owner_col,
            jnp.uint8(MSG_WB_REQ), msg_hs)
    if mp.dir_type in ("ackwise", "limited_broadcast"):
        # overflowed entries lose sharer precision: the INV sweep goes to
        # every tile (`directory_entry_ackwise.cc` / `..._limited_broadcast`);
        # `pending` (acks awaited) stays the true holder set — non-holders
        # drop the INV silently, exactly the sharer-side `silent` path
        over_bc = fan_inv & (v_nsh > k)
        send = send | over_bc[:, None]
        send_t = send.T
    noc, arrive = mem_net_fanout(
        mp, noc, send, mp.req_bits, eff_time, enabled)  # [home, sharer]
    mail = mail.replace(
        fwd_type=jnp.where(send_t, msg_hs.T, mail.fwd_type),
        fwd_line=jnp.where(send_t, eff_line[None, :], mail.fwd_line),
        fwd_time=jnp.where(send_t, arrive.T, mail.fwd_time),
    )

    counters = ms.counters.replace(
        dir_accesses=ms.counters.dir_accesses
        + (starting & enabled).astype(I64),
        dram_reads=ms.counters.dram_reads
        + (imm & ~cdata_imm & enabled).astype(I64),
        dram_total_lat_ps=ms.counters.dram_total_lat_ps
        + jnp.where(imm & ~cdata_imm & enabled, dram_lat_ps, 0),
    )
    if mp.dir_type in ("ackwise", "limited_broadcast"):
        counters = counters.replace(
            dir_broadcasts=counters.dir_broadcasts
            + (over_bc & enabled).astype(I64))
    progress = progress + jnp.sum(starting, dtype=jnp.int32)
    return ms.replace(directory=d, txn=txn, mail=mail,
                      counters=counters, noc=noc), progress


# --------------------------------------------------------------------------
# requester-side reply fill (`handleMsgFromDramDirectory` EX_REP/SH_REP +
# `insertCacheLineInHierarchy`)


def _requester_fill(mp, ms: MemState, rec: RecView, clock_ps, fmhz, enabled,
                    progress, sync_l2_net, px: ParallelCtx = IDENT):
    T = mp.n_tiles
    tiles = np.arange(T, dtype=np.int32)
    mail = ms.mail

    def ccyc(n):
        ps = cycles_to_ps(jnp.asarray(n, I64), fmhz)
        return jnp.where(enabled, ps, 0)

    have_rep = (ms.req.phase == PHASE_WAIT_REPLY) & (mail.rep_type != MSG_NONE)
    line = ms.req.line
    comp_l1i = ms.req.component == MOD_L1I

    # block-local row gathers at the filled line (+ the pre-update
    # miss-type test bits — the victim's own bitmap write is folded back
    # in below via the bucket-collision correction)
    line_l = px.lo(line)
    rows_l = (ca.gather_row(ms.l2, line_l, px.lo_const(mp.l2.sets_mod),
                            nonneg=True),
              ca.gather_row(ms.l1i, line_l, px.lo_const(mp.l1i.sets_mod),
                            nonneg=True),
              ca.gather_row(ms.l1d, line_l, px.lo_const(mp.l1d.sets_mod),
                            nonneg=True))
    if mp.l2.track_miss_types:
        mt_bits_l = (_mt_test(ms.mt, MT_EVICTED, line_l),
                     _mt_test(ms.mt, MT_INVALIDATED, line_l))
    else:
        mt_bits_l = ()
    if mp.l2.track_line_utilization:
        mt_bits_l = mt_bits_l + (_util_row_local(
            ms.l2_util, line_l, px.lo_const(mp.l2.sets_mod)),)
    (l2_r, l1i_r, l1d_r), mt_bits = _rows_exchange(px, rows_l, mt_bits_l)
    if mp.l2.track_line_utilization:
        lu_row, mt_bits = mt_bits[-1], mt_bits[:-1]

    # L2 victim for the fill; a valid victim emits an eviction message that
    # needs its (home, us) EVICT cell free — else stall this iteration
    way, v_valid, v_line, v_state = ca.row_pick_victim(
        l2_r, mp.l2.replacement, mp.l2.ways_limit)
    v_home_all = jnp.asarray(mp.mc_tiles, jnp.int32)[
        (v_line % len(mp.mc_tiles)).astype(jnp.int32)]
    need_evict = have_rep & v_valid
    evict_busy = mail.evict_type[v_home_all, tiles] != MSG_NONE
    fill = have_rep & ~(need_evict & evict_busy)
    evict_go = need_evict & fill

    new_state = jnp.where(mail.rep_type == MSG_EX_REP, MODIFIED, SHARED)
    l2 = ca.scatter_row(ms.l2, px.lo(ca.row_insert(l2_r, line, way,
                                                   new_state, fill)))
    if mp.l2.track_line_utilization:
        # the victim leaves the L2 (classify); the filled line's counter
        # restarts with the miss access itself as its first use
        en = jnp.asarray(enabled, bool)
        lu_cur = jnp.take_along_axis(lu_row, way[:, None], axis=1)[:, 0]
        init = jnp.where(ms.req.is_write, jnp.uint32(1) << 16,
                         jnp.uint32(1))
        ms = ms.replace(
            l2_util=_util_scatter(
                px, ms.l2_util, line, mp.l2.sets_mod, way, lu_cur,
                jnp.where(fill & en, init, lu_cur)),
            counters=_util_classify(ms.counters, lu_cur, evict_go,
                                    enabled))
    sets = nn_mod(line, jnp.asarray(mp.l2.sets_mod)).astype(jnp.int32)
    l2_cloc = px.entry_set(
        ms.l2_cloc, *px.lo((
            sets, way, fill,
            jnp.where(comp_l1i, MOD_L1I, MOD_L1D).astype(jnp.uint8))))

    # eviction message (FLUSH_REP if dirty — MODIFIED, or OWNED in MOSI —
    # else INV_REP; `insertCacheLine`, `l2_cache_cntlr.cc:75-116`, mosi
    # `l2_cache_cntlr.cc:116-138`)
    v_dirty = (v_state == MODIFIED) | (v_state == OWNED)
    e_msg = jnp.where(v_dirty, MSG_FLUSH_REP,
                      MSG_INV_REP).astype(jnp.uint8)
    e_bits = jnp.where(v_dirty, mp.rep_bits, mp.req_bits)
    # fill timing: reply arrival + net sync + L2 insert (data+tags), then
    # second L1 pass: L2 sync + L1 data+tags (`processMemOpFromCore` loop)
    fill_l2_ps = mail.rep_time + sync_l2_net + ccyc(mp.l2.data_and_tags_cycles)
    l1_dat = jnp.where(comp_l1i, ccyc(mp.l1i.data_and_tags_cycles),
                       ccyc(mp.l1d.data_and_tags_cycles))
    done_ps = fill_l2_ps + l1_dat

    noc, e_arrival = mem_net_send(
        mp, ms.noc, tiles, v_home_all, e_bits, fill_l2_ps, evict_go,
        enabled)
    wh = jnp.where(evict_go, v_home_all, 0)
    mail = mail.replace(
        evict_type=mail.evict_type.at[wh, tiles].set(
            jnp.where(evict_go, e_msg, mail.evict_type[wh, tiles])),
        evict_line=mail.evict_line.at[wh, tiles].set(
            jnp.where(evict_go, v_line, mail.evict_line[wh, tiles])),
        evict_time=mail.evict_time.at[wh, tiles].set(
            jnp.where(evict_go, e_arrival,
                      mail.evict_time[wh, tiles])),
        # reset BOTH fields so home-side add-delta reply writes stay exact
        rep_type=jnp.where(fill, MSG_NONE, mail.rep_type),
        rep_time=jnp.where(fill, 0, mail.rep_time),
    )

    # L1 fill (the rows were gathered in the phase exchange above)
    l1_state = new_state  # L1 gets the L2 state (`insertCacheLineInL1`)
    l1i_way, l1i_vv, l1i_vline, _ = ca.row_pick_victim(
        l1i_r, mp.l1i.replacement, mp.l1i.ways_limit)
    l1d_way, l1d_vv, l1d_vline, _ = ca.row_pick_victim(
        l1d_r, mp.l1d.replacement, mp.l1d.ways_limit)
    l1i = ca.scatter_row(
        ms.l1i, px.lo(ca.row_insert(l1i_r, line, l1i_way, l1_state,
                                    fill & comp_l1i)))
    l1d = ca.scatter_row(
        ms.l1d, px.lo(ca.row_insert(l1d_r, line, l1d_way, l1_state,
                                    fill & ~comp_l1i)))
    # clear cached-loc of L1 victims in L2 (block-local RMW chain)
    l1_ev = (fill & comp_l1i & l1i_vv) | (fill & ~comp_l1i & l1d_vv)
    l1_ev_line = jnp.where(comp_l1i, l1i_vline, l1d_vline)
    ev_line_l = px.lo(l1_ev_line)
    l2_mod_l = px.lo_const(mp.l2.sets_mod)
    ev_hit_l, ev_way_l, _ = ca.lookup(l2, ev_line_l, l2_mod_l)
    ev_sets_l = (ev_line_l % jnp.asarray(l2_mod_l)).astype(jnp.int32)
    l2_cloc = px.entry_set(l2_cloc, ev_sets_l, ev_way_l,
                           px.lo(l1_ev) & ev_hit_l, 0)

    if mp.l2.track_miss_types:
        mt = ms.mt
        # victim -> evicted set (`insertCacheLine` eviction branch)
        mt = _mt_update(mt, MT_EVICTED, px.lo(v_line), px.lo(evict_go), True)
        # inserted line: clearMissTypeTrackingSets erases from exactly
        # ONE set (evicted elif invalidated elif fetched), then the
        # fetched set gains the line.  The tests must see the victim's
        # just-applied EVICTED bit; the exchanged pre-write bit is
        # corrected for a same-bucket victim write instead of re-reading.
        e_in = mt_bits[0] | (evict_go & _mt_same_bucket(v_line, line))
        i_in = mt_bits[1]
        mt = _mt_update(mt, MT_EVICTED, line_l, px.lo(fill & e_in), False)
        mt = _mt_update(mt, MT_INVALIDATED, line_l,
                        px.lo(fill & ~e_in & i_in), False)
        mt = _mt_update(mt, MT_FETCHED, line_l, px.lo(fill), True)
        ms = ms.replace(mt=mt)

    req = ms.req.replace(
        phase=jnp.where(fill, PHASE_IDLE, ms.req.phase),
        slot=jnp.where(fill, ms.req.slot + 1, ms.req.slot),
        acc_ps=ms.req.acc_ps + jnp.where(fill, done_ps - clock_ps, 0),
        slot_lat_ps=jnp.where(
            (fill[:, None]
             & (np.arange(3)[None, :] == ms.req.slot[:, None])),
            (done_ps - clock_ps)[:, None], ms.req.slot_lat_ps),
    )
    ms = ms.replace(l1i=l1i, l1d=l1d, l2=l2, l2_cloc=l2_cloc, mail=mail,
                    req=req, noc=noc)
    # functional effect of the completed slot
    s_addr = jnp.where(ms.req.slot - 1 == 1, rec.addr0.astype(jnp.int32),
                       rec.addr1.astype(jnp.int32))
    ms = _apply_functional(mp, ms, rec, ms.req.slot - 1, s_addr,
                           ms.req.is_write, fill)
    counters = ms.counters.replace(
        evictions=ms.counters.evictions + (evict_go & enabled).astype(I64))
    progress = progress + jnp.sum(fill, dtype=jnp.int32)
    return ms.replace(counters=counters), progress


# ---------------------------------------------------------------------------
# Host-side census (analysis/protocol.py differential mode)
# ---------------------------------------------------------------------------


def line_census(ms: MemState, mp: MemParams, lines) -> dict:
    """Abstract per-line coherence view of a (fetched) MemState.

    Pure host-side numpy over the packed arrays — the model checker
    compares this against the golden interpreter's abstract state after
    replaying the same access sequence.  Returns, per line:
    ``{"l1d": (state per tile), "l2": (state per tile),
       "dir": (dstate, owner, frozenset(sharers)) | None,
       "cdata": bool}`` (states are cache_array constants, 0 = absent).
    """
    l1d_tag = np.asarray(ms.l1d.tags)
    l1d_st = np.asarray(ms.l1d.state)
    l2_tag = np.asarray(ms.l2.tags)
    l2_st = np.asarray(ms.l2.state)
    entry = np.asarray(ms.directory.entry)
    sharers = np.asarray(ms.directory.sharers)
    cdata_line = np.asarray(ms.txn.cdata_line)
    cdata_valid = np.asarray(ms.txn.cdata_valid)
    T = mp.n_tiles
    sw = mp.sharer_words

    def cache_state(tag, st, line):
        out = []
        for t in range(T):
            s = line % tag.shape[1]
            hit = tag[t, s, :] == line
            out.append(int(st[t, s, hit.argmax()]) if hit.any() else 0)
        return tuple(out)

    out = {}
    for line in lines:
        home = mp.mc_tiles[line % len(mp.mc_tiles)]
        dset = line % mp.dir_sets
        dent = None
        for w in range(mp.dir_ways):
            word = int(entry[home, dset, w])
            if (word & ((1 << DIR_TAG_BITS) - 1)) - 1 != line:
                continue
            dstate = (word >> DIR_STATE_SHIFT) & 7
            owner = ((word >> DIR_OWNER_SHIFT) & ((1 << DIR_ID_BITS) - 1)) - 1
            bits = sharers[home, dset, w * sw:(w + 1) * sw]
            shset = frozenset(
                i * 32 + b for i in range(sw) for b in range(32)
                if (int(bits[i]) >> b) & 1)
            dent = (int(dstate), int(owner), shset)
            break
        out[line] = {
            "l1d": cache_state(l1d_tag, l1d_st, line),
            "l2": cache_state(l2_tag, l2_st, line),
            "dir": dent,
            "cdata": bool(
                cdata_valid[home] and int(cdata_line[home]) == line),
        }
    return out
