"""Vectorized shared-L2 protocol engine (pr_l1_sh_l2_msi / _mesi).

Reference: `common/tile/memory_subsystem/pr_l1_sh_l2_{msi,mesi}/` — private
L1s with a DISTRIBUTED shared L2: the L2 slice at a line's home tile holds
both the data and an embedded directory entry over the L1 copies
(`l2_cache_cntlr.h:27-67`, `l2_directory_cfg.cc`).  An L1 miss sends
EX/SH_REQ to the home (`l1_cache_cntlr.cc:81-160`); the home's L2 either
serves it (running the directory FSM over the L1 sharers,
`l2_cache_cntlr.cc:443-700`) or allocates the line in state DATA_INVALID
and fetches it from DRAM (`:541-560,900-915`).  MESI grants EXCLUSIVE on a
read of an uncached line (`pr_l1_sh_l2_mesi/l2_cache_cntlr.cc:660-680`).

Vectorized form mirrors engine.py's discipline: one lane per tile, dense
mailboxes, one active transaction per home, simulated time carried in
messages.  Like engine.py, the engine takes the packed shard_map
exchange context (`parallel/px.py`): every phase gathers its lanes' L1 /
L2-slice / embedded-directory rows block-locally, exchanges them in ONE
packed all-gather, computes full-width on replicated control state, and
scatters row deltas back to this device's block — so shared-L2 meshes
ride the same one-collective-per-phase program as the private-L2 engines
(the reference's process striping serves every protocol equally,
`config.cc` computeProcessToTileMapping + `socktransport.cc`).

The embedded directory is stored packed like the private engine's
(state/owner/nsharers/cloc in ONE int64 word per L2 line, all-zero =
UNCACHED; sharer bitvectors set-row-major [T, S2, W2*SW] so the minor
dim stays un-padded on TPU — PERF.md "array padding").

Documented simplifications (same class as engine.py's):
 - upgrade replies are modeled as EX_REP (same message count, the data
   serialization is slightly larger than the reference's UPGRADE_REP);
 - one transaction per home serializes same-home requests (the reference
   queues per address);
 - the DRAM fetch is a timing-only round trip to the line's DRAM home
   (`dram_home_lookup`), not a separate controller state machine.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.memory import cache_array as ca
from graphite_tpu.memory.cache_array import (
    EXCLUSIVE, INVALID, MODIFIED, SHARED,
    state_readable, state_writable,
)
from graphite_tpu.memory.engine import (
    MemStepOut, RecView, _dir_set_field, _ID_MASK, _req_consume,
    _req_earliest, _row_earliest,
    _rows_exchange, clear_bit, lowest_sharer, mem_net_fanout,
    mem_net_latency_ps, mem_net_send, set_bit, test_bit, unpack_sharers,
)
from graphite_tpu.memory.params import MemParams
from graphite_tpu.memory.state import (
    DIR_MODIFIED, DIR_SHARED, DIR_UNCACHED,
    MOD_CORE, MOD_L1D, MOD_L1I, MOD_L2, MOD_NET_MEM,
    MSG_EX_REP, MSG_EX_REQ, MSG_EXCL_REP, MSG_FLUSH_REP, MSG_FLUSH_REQ,
    MSG_INV_REP, MSG_INV_REQ, MSG_NONE, MSG_NULLIFY, MSG_SH_REP, MSG_SH_REQ,
    MSG_WB_REP, MSG_WB_REQ,
    PHASE_IDLE, PHASE_WAIT_REPLY,
    MemCounters, MemMailboxes, RequesterState, init_mem_common,
)
from graphite_tpu.parallel.px import IDENT, ParallelCtx
from graphite_tpu.time_types import cycles_to_ps
from graphite_tpu.trace.schema import (
    FLAG_CHECK, FLAG_MEM0_VALID, FLAG_MEM0_WRITE, FLAG_MEM1_VALID,
    FLAG_MEM1_WRITE,
)

I64 = jnp.int64
U32 = jnp.uint32
FAR = 2**62

# phase order of the shared-L2 engine's skip vector (ShL2State.phase_skips)
SHL2_PHASE_NAMES = ("requester", "sharer", "home_evict", "home_finish",
                    "home_start", "requester_fill")


def dir_store_avals(ms) -> tuple:
    """(shape, dtype) signatures of the embedded directory's big stores
    — the [T, S2, W2] packed words and [T, S2, W2*SW] sharer rows —
    that a gated shl2 home phase must NEVER return as lax.cond outputs
    (the `_RowAcc` row-delta plan carries them instead; see `_cond_dir`).
    Enforced program-wide by the auditor's cond-payload rule
    (analysis/rules.py)."""
    d = ms.dir
    return (
        (tuple(d.word.shape), str(d.word.dtype)),
        (tuple(d.sharers.shape), str(d.sharers.dtype)),
    )

# L2 slice data state (`cache_line_info.h` ShL2CacheLineInfo): the line is
# allocated (directory live) but its data is still in flight from DRAM
DATA_INVALID = 5

# MESI directory state for an exclusive clean L1 copy
DIR_EXCLUSIVE = 4

# packed embedded-directory word layout (int64[T, S2, W2]; all-zero word =
# UNCACHED, owner -1, 0 sharers, cloc 0):
SHL2_STATE_SHIFT = 0    # bits 0..2: directory state
SHL2_OWNER_SHIFT = 3    # bits 3..15: owner tile + 1
SHL2_NSH_SHIFT = 16     # bits 16..28: sharer count
SHL2_CLOC_SHIFT = 29    # bits 29..30: caching component (MOD_L1I/L1D)


@struct.dataclass
class ShL2Dir:
    """Per-L2-line embedded directory, packed (layout above)."""

    word: jax.Array      # int64[T(home), S2, W2]
    sharers: jax.Array   # uint32[T(home), S2, W2*SW] set-row-major


def _d_state(w):
    return (w & 7).astype(jnp.uint8)


def _d_owner(w):
    return ((w >> SHL2_OWNER_SHIFT) & _ID_MASK).astype(jnp.int32) - 1


def _d_nsh(w):
    return ((w >> SHL2_NSH_SHIFT) & _ID_MASK).astype(jnp.int32)


def _d_cloc(w):
    return ((w >> SHL2_CLOC_SHIFT) & 3).astype(jnp.uint8)


def _dir_rows_local(d: ShL2Dir, sets_l):
    """This device's [Tl, W2] word row + [Tl, W2*SW] sharers row at each
    local lane's set (exchanged via _rows_exchange at the call sites)."""
    Tl = d.word.shape[0]
    lt = jnp.arange(Tl, dtype=jnp.int32)
    return d.word[lt, sets_l], d.sharers[lt, sets_l]


def _entry_at(dw, dsh, way):
    """(dstate, owner, sharers, nsh, cloc) at `way` from full-width rows."""
    word = jnp.take_along_axis(dw, way[:, None], axis=1)[:, 0]
    W2 = dw.shape[1]
    sh3 = dsh.reshape(dsh.shape[0], W2, -1)
    sharers = jnp.take_along_axis(sh3, way[:, None, None], axis=1)[:, 0]
    return (_d_state(word), _d_owner(word), sharers, _d_nsh(word),
            _d_cloc(word))


def _row_update(dw, way, mask, *, dstate=None, owner=None, nsharers=None,
                cloc=None):
    """Masked per-lane field update of the entry at `way` in the [T, W2]
    word row (pure bit math; the phase's single scatter applies it)."""
    word = jnp.take_along_axis(dw, way[:, None], axis=1)[:, 0]
    new = word
    if dstate is not None:
        new = _dir_set_field(new, jnp.asarray(dstate, jnp.uint8),
                             SHL2_STATE_SHIFT, 7)
    if owner is not None:
        new = _dir_set_field(new, owner.astype(I64) + 1,
                             SHL2_OWNER_SHIFT, _ID_MASK)
    if nsharers is not None:
        new = _dir_set_field(new, nsharers, SHL2_NSH_SHIFT, _ID_MASK)
    if cloc is not None:
        new = _dir_set_field(new, cloc, SHL2_CLOC_SHIFT, 3)
    onehot = (jnp.arange(dw.shape[1], dtype=jnp.int32)[None, :]
              == way[:, None]) & mask[:, None]
    return jnp.where(onehot, new[:, None], dw)


def _rowsh_update(dsh, way, mask, new_sh):
    """Masked per-lane sharers write at `way` in the [T, W2*SW] row."""
    W2SW = dsh.shape[1]
    SW = new_sh.shape[1]
    W2 = W2SW // SW
    sh3 = dsh.reshape(dsh.shape[0], W2, SW)
    onehot = (jnp.arange(W2, dtype=jnp.int32)[None, :, None]
              == way[:, None, None]) & mask[:, None, None]
    return jnp.where(onehot, new_sh[:, None, :], sh3).reshape(
        dsh.shape[0], W2SW)


def _dir_apply_rows(d: ShL2Dir, px: ParallelCtx, sets, dwd, dshd):
    """Scatter full-width embedded-directory ROW deltas block-locally:
    ONE add-a-delta scatter per array (per-lane rows unique, aliases in
    place).  Zero deltas — masked-off lanes, gated-off phases — add
    nothing."""
    sets_l, dwd_l, dshd_l = px.lo((sets, dwd, dshd))
    Tl = d.word.shape[0]
    lt = jnp.arange(Tl, dtype=jnp.int32)
    return d.replace(
        word=d.word.at[lt, sets_l].add(
            dwd_l, unique_indices=True, indices_are_sorted=True),
        sharers=d.sharers.at[lt, sets_l].add(
            dshd_l, unique_indices=True, indices_are_sorted=True))


def _dir_scatter(d: ShL2Dir, px: ParallelCtx, sets, dw0, dw, dsh0, dsh,
                 acc: "_RowAcc | None" = None):
    """Apply the phase's accumulated full-width row updates — directly
    (ungated path) or deferred into `acc` so a gated phase's lax.cond
    returns the compact [T, W2(*SW)] row deltas instead of carrying the
    big stores (see shl2_engine_step's per-phase gating)."""
    if acc is not None:
        acc.add(sets, dw - dw0, dsh - dsh0)
        return d
    return _dir_apply_rows(d, px, sets, dw - dw0, dsh - dsh0)


class _RowAcc:
    """Deferred embedded-directory row deltas of one gated home phase
    (the shared-L2 analog of engine._DirAcc — the shl2 phases already
    compute row-form deltas, so the plan is just (sets, Δword rows,
    Δsharers rows), full-width replicated like the rows themselves)."""

    def __init__(self):
        self.plan = None

    def add(self, sets, dwd, dshd):
        if self.plan is not None:
            raise AssertionError(
                "_RowAcc: one _dir_scatter per gated shl2 phase")
        self.plan = (sets, dwd, dshd)

    def pack(self, d, n_tiles):
        if self.plan is not None:
            return self.plan
        return _RowAcc.zero_pack(d, n_tiles)

    @staticmethod
    def zero_pack(d, n_tiles):
        return (jnp.zeros(n_tiles, jnp.int32),
                jnp.zeros((n_tiles, d.word.shape[2]), I64),
                jnp.zeros((n_tiles, d.sharers.shape[2]), U32))


def _cond_nodir(pred, fn, ms):
    """Run a directory-free shl2 phase under a scalar-predicate lax.cond
    with the embedded directory detached from the carried operands."""
    d0 = ms.dir

    def run(m):
        return fn(m)

    def skip(m):
        return m, jnp.zeros((), jnp.int32)

    ms2, prog = jax.lax.cond(pred, run, skip, ms.replace(dir=None))
    return ms2.replace(dir=d0), prog


def _cond_dir(pred, fn, ms, n_tiles, px):
    """Run a home-side shl2 phase under a scalar-predicate lax.cond: the
    embedded directory is read inside (cond input, no double-buffering)
    but written only through the `_RowAcc` delta plan the cond returns;
    `_dir_apply_rows` lands the plan outside.  `fn(ms, acc) ->
    (ms, progress)` must leave ms.dir untouched."""
    d0 = ms.dir

    def run(m):
        acc = _RowAcc()
        m2, prog = fn(m.replace(dir=d0), acc)
        return m2.replace(dir=None), prog, acc.pack(d0, n_tiles)

    def skip(m):
        return (m, jnp.zeros((), jnp.int32), _RowAcc.zero_pack(d0, n_tiles))

    ms2, prog, plan = jax.lax.cond(pred, run, skip, ms.replace(dir=None))
    return ms2.replace(dir=_dir_apply_rows(d0, px, *plan)), prog


@struct.dataclass
class ShL2Txn:
    active: jax.Array      # bool[T]
    mtype: jax.Array       # uint8[T]
    line: jax.Array        # int32[T]
    requester: jax.Array   # int32[T]
    req_comp: jax.Array    # uint8[T] MOD_L1I / MOD_L1D
    time_ps: jax.Array     # int64[T]
    pending: jax.Array     # uint32[T, SW]
    dram_ready_ps: jax.Array  # int64[T] (FAR = no fetch in flight)
    got_flush: jax.Array   # bool[T] — dirty data arrived (L2 turns M)
    saved_valid: jax.Array
    saved_type: jax.Array
    saved_line: jax.Array
    saved_requester: jax.Array
    saved_comp: jax.Array
    saved_time_ps: jax.Array
    last_line: jax.Array
    last_done_ps: jax.Array


@struct.dataclass
class ShL2State:
    l1i: ca.CacheArrays
    l1d: ca.CacheArrays
    l2: ca.CacheArrays          # the local SLICE (home-indexed lines)
    dir: ShL2Dir
    mail: MemMailboxes
    txn: ShL2Txn
    req: RequesterState
    counters: MemCounters
    func_mem: jax.Array
    func_errors: jax.Array
    # bool[] — any protocol state outstanding; False lets the step skip
    # the engine entirely (see engine.mem_idle_out)
    live: jax.Array
    # int64[6] — per-phase lax.cond skip counts under phase gating
    # (SHL2_PHASE_NAMES order; see MemState.phase_skips)
    phase_skips: jax.Array = None
    # MEMORY-NoC port-queue state when memory = emesh_hop_by_hop (see
    # engine.mem_net_send); None otherwise
    noc: "object" = None


def init_shl2_state(mp: MemParams) -> ShL2State:
    """Build from the shared pieces (L1/L2 arrays, mailboxes, requester)."""
    base = init_mem_common(mp)
    T = mp.n_tiles
    S2, W2 = mp.l2.num_sets, mp.l2.num_ways
    SW = mp.sharer_words
    zdir = ShL2Dir(
        word=jnp.zeros((T, S2, W2), I64),
        sharers=jnp.zeros((T, S2, W2 * SW), U32),
    )
    txn = ShL2Txn(
        active=jnp.zeros(T, jnp.bool_),
        mtype=jnp.zeros(T, jnp.uint8),
        line=jnp.zeros(T, jnp.int32),
        requester=jnp.zeros(T, jnp.int32),
        req_comp=jnp.zeros(T, jnp.uint8),
        time_ps=jnp.zeros(T, I64),
        pending=jnp.zeros((T, SW), U32),
        dram_ready_ps=jnp.full(T, FAR, I64),
        got_flush=jnp.zeros(T, jnp.bool_),
        saved_valid=jnp.zeros(T, jnp.bool_),
        saved_type=jnp.zeros(T, jnp.uint8),
        saved_line=jnp.zeros(T, jnp.int32),
        saved_requester=jnp.zeros(T, jnp.int32),
        saved_comp=jnp.zeros(T, jnp.uint8),
        saved_time_ps=jnp.zeros(T, I64),
        last_line=jnp.full(T, -1, jnp.int32),
        last_done_ps=jnp.zeros(T, I64),
    )
    return ShL2State(dir=zdir, txn=txn, live=jnp.zeros((), jnp.bool_),
                     **base)


def _l2_home(mp: MemParams, line):
    """The L2 slice holding `line`: interleaved over ALL tiles
    (`l2_cache_hash_fn.cc` home lookup)."""
    return (line % mp.n_tiles).astype(jnp.int32)


def _dram_lat_ps(mp: MemParams, home, enabled):
    """DRAM fetch round trip from the home's L2 slice: network to the DRAM
    home + access + return (`DRAM_FETCH_REQ`/`REP`)."""
    mc = jnp.asarray(mp.mc_tiles, jnp.int32)
    dram_home = mc[(home % len(mp.mc_tiles)).astype(jnp.int32)]
    net = mem_net_latency_ps(mp, home, dram_home, mp.rep_bits, enabled)
    acc = jnp.where(enabled,
                    (mp.dram_latency_ns + mp.dram_processing_ns) * 1000, 0)
    return 2 * net + acc


def shl2_engine_step(
    mp: MemParams,
    ms: ShL2State,
    rec: RecView,
    clock_ps: jax.Array,
    freq_mhz: jax.Array,
    active: jax.Array,
    enabled,
    px: ParallelCtx = IDENT,
    fill_events: bool = False,
) -> MemStepOut:
    T = mp.n_tiles
    tiles = jnp.arange(T, dtype=jnp.int32)
    fmhz = freq_mhz.astype(I64)
    progress = jnp.zeros((), jnp.int32)
    mesi = mp.protocol.endswith("mesi")

    def ccyc(n, f=None):
        ps = cycles_to_ps(jnp.asarray(n, I64), fmhz if f is None else f)
        return jnp.where(enabled, ps, 0)

    sync_core_l1 = ccyc(mp.sync_cycles(MOD_CORE, MOD_L1D))
    sync_l1_net = ccyc(mp.sync_cycles(MOD_L1D, MOD_NET_MEM))
    sync_l2_net = ccyc(mp.sync_cycles(MOD_L2, MOD_NET_MEM))
    l2_access = ccyc(mp.l2.data_and_tags_cycles)

    # ======================================================================
    # (1) requester slot starts: L1-only lookup; misses go to the L2 home
    # ======================================================================
    flags = rec.flags
    # shared with engine.py + the mem_gate's skip decision — MUST stay the
    # same definition or the gate could idle-skip live slots
    from graphite_tpu.memory.engine import next_present_slot, slots_present

    present = slots_present(mp, rec, enabled)

    def next_present(slot):
        return next_present_slot(present, slot)

    def _phase_requester(ms):
        slot = next_present(ms.req.slot)
        has_slot = slot < 3
        idle = ms.req.phase == PHASE_IDLE
        starting = active & idle & has_slot

        s_is_icache = slot == 0
        s_addr = jnp.where(
            s_is_icache, rec.pc.astype(jnp.int32),
            jnp.where(slot == 1, rec.addr0.astype(jnp.int32),
                      rec.addr1.astype(jnp.int32)))
        s_line = (s_addr.astype(jnp.uint32) >> mp.line_bits).astype(jnp.int32)
        s_write = jnp.where(
            s_is_icache, False,
            jnp.where(slot == 1, (flags & FLAG_MEM0_WRITE) != 0,
                      (flags & FLAG_MEM1_WRITE) != 0))

        ibuf_hit = starting & s_is_icache & (s_line == ms.req.instr_buf)
        new_instr_buf = jnp.where(starting & s_is_icache, s_line,
                                  ms.req.instr_buf)

        # L1 rows: block-local gathers, ONE exchange, full-width row ops
        s_line_l = px.lo(s_line)
        rows_l = (
            ca.gather_row(ms.l1i, s_line_l, px.lo_const(mp.l1i.sets_mod)),
            ca.gather_row(ms.l1d, s_line_l, px.lo_const(mp.l1d.sets_mod)),
        )
        (l1i_row, l1d_row), _ = _rows_exchange(px, rows_l)
        l1i_hit, l1i_way, l1i_state = ca.row_lookup(l1i_row, s_line)
        l1d_hit, l1d_way, l1d_state = ca.row_lookup(l1d_row, s_line)
        l1_state = jnp.where(s_is_icache, l1i_state, l1d_state)
        l1_permit = jnp.where(s_write, state_writable(l1_state),
                              state_readable(l1_state))
        do_l1 = starting & ~ibuf_hit
        l1_hit_now = do_l1 & l1_permit
        l1_miss = do_l1 & ~l1_permit

        l1_dat = jnp.where(s_is_icache, ccyc(mp.l1i.data_and_tags_cycles),
                           ccyc(mp.l1d.data_and_tags_cycles))
        l1_tag = jnp.where(s_is_icache, ccyc(mp.l1i.tags_cycles),
                           ccyc(mp.l1d.tags_cycles))
        sclock = clock_ps + sync_core_l1
        l1_hit_done_ps = sclock + l1_dat

        # MESI silent upgrade: a write to an EXCLUSIVE L1 line promotes to M
        # with no messages (the write-hit path: E is writable)
        promote = l1_hit_now & s_write & (l1_state == EXCLUSIVE)
        l1d_row = ca.row_set_state(l1d_row, l1d_way, MODIFIED,
                                   promote & ~s_is_icache)
        # hits refresh recency under LRU; round_robin's update is a no-op
        if mp.l1i.replacement != "round_robin":
            l1i_row = ca.row_touch(l1i_row, l1i_way, l1_hit_now & s_is_icache)
        if mp.l1d.replacement != "round_robin":
            l1d_row = ca.row_touch(l1d_row, l1d_way, l1_hit_now & ~s_is_icache)
        l1i_upd = ca.scatter_row(ms.l1i, px.lo(l1i_row))
        l1d_upd = ca.scatter_row(ms.l1d, px.lo(l1d_row))

        # L1 miss: an upgrade (write to readable-but-unwritable line) keeps the
        # line until the reply; a plain miss sends the request right away.  In
        # both cases the L1 stays untouched here — the FILL path replaces it.
        s_home = _l2_home(mp, s_line)
        rq_type = jnp.where(s_write, MSG_EX_REQ, MSG_SH_REQ).astype(jnp.uint8)
        req_send_ps = sclock + l1_tag + sync_l1_net
        noc, rq_arrival = mem_net_send(
            mp, ms.noc, tiles, s_home, mp.req_bits, req_send_ps, l1_miss,
            enabled)
        mail = ms.mail
        # per-requester lane (one outstanding miss per tile): plain
        # masked selects, no matrix scatter
        mail = mail.replace(
            req_type=jnp.where(l1_miss, rq_type, mail.req_type),
            req_home=jnp.where(l1_miss, s_home, mail.req_home),
            req_line=jnp.where(l1_miss, s_line, mail.req_line),
            req_time=jnp.where(l1_miss, rq_arrival, mail.req_time),
        )

        slot_done_now = ibuf_hit | l1_hit_now
        slot_done_ps = jnp.where(ibuf_hit, clock_ps + ccyc(1), l1_hit_done_ps)
        req_state = ms.req.replace(
            phase=jnp.where(l1_miss, PHASE_WAIT_REPLY, ms.req.phase),
            line=jnp.where(l1_miss, s_line, ms.req.line),
            is_write=jnp.where(l1_miss, s_write, ms.req.is_write),
            component=jnp.where(
                l1_miss, jnp.where(s_is_icache, MOD_L1I, MOD_L1D),
                ms.req.component).astype(jnp.uint8),
            clock_ps=jnp.where(l1_miss, req_send_ps, ms.req.clock_ps),
            acc_ps=ms.req.acc_ps
            + jnp.where(slot_done_now, slot_done_ps - clock_ps, 0),
            slot_lat_ps=jnp.where(
                (slot_done_now[:, None]
                 & (jnp.arange(3)[None, :] == slot[:, None])),
                (slot_done_ps - clock_ps)[:, None], ms.req.slot_lat_ps),
            instr_buf=new_instr_buf,
            slot=jnp.where(slot_done_now, slot + 1,
                           jnp.where(starting, slot, ms.req.slot)),
        )
        counters = ms.counters.replace(
            l1i_hits=ms.counters.l1i_hits
            + ((l1_hit_now | ibuf_hit) & s_is_icache & enabled).astype(I64),
            l1i_misses=ms.counters.l1i_misses
            + (l1_miss & s_is_icache & enabled).astype(I64),
            l1d_read_hits=ms.counters.l1d_read_hits
            + (l1_hit_now & ~s_is_icache & ~s_write & enabled).astype(I64),
            l1d_read_misses=ms.counters.l1d_read_misses
            + (l1_miss & ~s_is_icache & ~s_write & enabled).astype(I64),
            l1d_write_hits=ms.counters.l1d_write_hits
            + (l1_hit_now & ~s_is_icache & s_write & enabled).astype(I64),
            l1d_write_misses=ms.counters.l1d_write_misses
            + (l1_miss & ~s_is_icache & s_write & enabled).astype(I64),
        )
        prog = jnp.sum(slot_done_now | l1_miss, dtype=jnp.int32)
        ms = ms.replace(l1i=l1i_upd, l1d=l1d_upd, mail=mail, req=req_state,
                        counters=counters, noc=noc)
        ms = _apply_functional(mp, ms, rec, slot, s_addr, s_write, slot_done_now)
        return ms, prog

    gate = bool(getattr(mp, "phase_gate", False))
    # a lane that cannot start now cannot start later this iteration
    # (only the fill phase returns a lane to PHASE_IDLE)
    pred1 = jnp.any(active & (ms.req.phase == PHASE_IDLE)
                    & (next_present(ms.req.slot) < 3))
    if gate:
        ms, p = _cond_nodir(pred1, _phase_requester, ms)
    else:
        ms, p = _phase_requester(ms)
    progress = progress + p

    # ======================================================================
    # (2) L1 sharers serve INV/FLUSH/WB from homes
    # ======================================================================
    pred2 = (ms.mail.fwd_type != MSG_NONE).any()
    if gate:
        ms, p = _cond_nodir(
            pred2,
            lambda m: _sharer_step(mp, m, fmhz, enabled,
                                   jnp.zeros((), jnp.int32),
                                   sync_l1_net, px),
            ms)
    else:
        ms, p = _sharer_step(mp, ms, fmhz, enabled,
                             jnp.zeros((), jnp.int32), sync_l1_net, px)
    progress = progress + p

    # ======================================================================
    # (3) homes consume L1 evictions (directory + L2 dirty fill)
    # ======================================================================
    pred3 = (ms.mail.evict_type != MSG_NONE).any()
    if gate:
        ms, p = _cond_dir(
            pred3,
            lambda m, a: _home_evictions(mp, m, l2_access, enabled,
                                         jnp.zeros((), jnp.int32), px,
                                         acc=a),
            ms, T, px)
    else:
        ms, p = _home_evictions(mp, ms, l2_access, enabled,
                                jnp.zeros((), jnp.int32), px)
    progress = progress + p

    # ======================================================================
    # (4) homes consume acks / dram arrivals, finish transactions
    # ======================================================================
    pred4 = (ms.mail.ack_type != MSG_NONE).any() | ms.txn.active.any()
    if gate:
        ms, p = _cond_dir(
            pred4,
            lambda m, a: _home_finish(mp, m, l2_access, sync_l2_net,
                                      enabled, jnp.zeros((), jnp.int32),
                                      mesi, px, acc=a),
            ms, T, px)
    else:
        ms, p = _home_finish(mp, ms, l2_access, sync_l2_net, enabled,
                             jnp.zeros((), jnp.int32), mesi, px)
    progress = progress + p

    # ======================================================================
    # (5) homes start transactions
    # ======================================================================
    pred5 = ((ms.mail.req_type != MSG_NONE).any()
             | (ms.txn.saved_valid & ~ms.txn.active).any())
    if gate:
        ms, p = _cond_dir(
            pred5,
            lambda m, a: _home_starts(mp, m, l2_access, sync_l2_net,
                                      enabled, jnp.zeros((), jnp.int32),
                                      mesi, px, acc=a),
            ms, T, px)
    else:
        ms, p = _home_starts(mp, ms, l2_access, sync_l2_net, enabled,
                             jnp.zeros((), jnp.int32), mesi, px)
    progress = progress + p

    # ======================================================================
    # (6) requesters consume replies (fill L1)
    # ======================================================================
    pred6 = ((ms.req.phase == PHASE_WAIT_REPLY)
             & (ms.mail.rep_type != MSG_NONE)).any()
    # fill observability for the round-21 latency histograms: phase 6's
    # fill is the only writer of req.slot / req.acc_ps in this block, so
    # the pre/post delta is the exact per-call miss completion (see
    # engine.MemStepOut.fill_now)
    slot_pre6 = ms.req.slot
    acc_pre6 = ms.req.acc_ps
    if gate:
        ms, p = _cond_nodir(
            pred6,
            lambda m: _requester_fill(mp, m, rec, clock_ps, fmhz, enabled,
                                      jnp.zeros((), jnp.int32),
                                      sync_l1_net, px),
            ms)
    else:
        ms, p = _requester_fill(mp, ms, rec, clock_ps, fmhz, enabled,
                                jnp.zeros((), jnp.int32), sync_l1_net, px)
    progress = progress + p

    final_slot = next_present(ms.req.slot)
    mem_complete = (ms.req.phase == PHASE_IDLE) & (final_slot >= 3)
    # protocol-liveness flag (see engine.mem_idle_out): includes in-flight
    # home-side DRAM fetches, which this engine tracks outside txn.active
    from graphite_tpu.memory.engine import protocol_live

    ms = ms.replace(live=protocol_live(
        ms, (ms.txn.dram_ready_ps < FAR).any()))
    if gate:
        skipped = 1 - jnp.stack(
            [pred1, pred2, pred3, pred4, pred5, pred6]).astype(I64)
        ms = ms.replace(phase_skips=ms.phase_skips + skipped)
    return MemStepOut(
        ms=ms, mem_complete=mem_complete, acc_ps=ms.req.acc_ps,
        slot_lat_ps=ms.req.slot_lat_ps, progress=progress,
        fill_now=(ms.req.slot != slot_pre6) if fill_events else None,
        fill_lat_ps=(ms.req.acc_ps - acc_pre6) if fill_events else None,
    )


def _apply_functional(mp, ms: ShL2State, rec: RecView, slot, s_addr,
                      s_write, mask):
    if mp.func_mem_words <= 0:
        return ms
    word = ((s_addr.astype(jnp.uint32) >> 2) % mp.func_mem_words).astype(
        jnp.int32)
    value = jnp.where(slot == 1, rec.aux0, rec.aux1).astype(jnp.uint32)
    wr = mask & s_write
    tgt = jnp.where(wr, word, mp.func_mem_words)
    fm = ms.func_mem.at[tgt].set(jnp.where(wr, value, 0))
    check = mask & ~s_write & (slot == 1) & ((rec.flags & FLAG_CHECK) != 0)
    loaded = fm[word]
    errs = jnp.sum(check & (loaded != rec.aux0.astype(jnp.uint32)),
                   dtype=I64)
    return ms.replace(func_mem=fm, func_errors=ms.func_errors + errs)


def _sharer_step(mp, ms: ShL2State, fmhz, enabled, progress, sync_l1_net,
                 px: ParallelCtx = IDENT):
    """L1-side service of INV/FLUSH/WB (`l1_cache_cntlr.cc` handlers)."""
    T = mp.n_tiles
    tiles = jnp.arange(T, dtype=jnp.int32)
    mail = ms.mail

    def ccyc(n):
        ps = cycles_to_ps(jnp.asarray(n, I64), fmhz)
        return jnp.where(enabled, ps, 0)

    h, found = _row_earliest(mail.fwd_type, mail.fwd_time)
    ftype = mail.fwd_type[tiles, h]
    fline = mail.fwd_line[tiles, h]
    ftime = mail.fwd_time[tiles, h]

    fline_l = px.lo(fline)
    rows_l = (
        ca.gather_row(ms.l1i, fline_l, px.lo_const(mp.l1i.sets_mod)),
        ca.gather_row(ms.l1d, fline_l, px.lo_const(mp.l1d.sets_mod)),
    )
    (l1i_row, l1d_row), _ = _rows_exchange(px, rows_l)
    l1i_hit, l1i_way, l1i_state = ca.row_lookup(l1i_row, fline)
    l1d_hit, l1d_way, l1d_state = ca.row_lookup(l1d_row, fline)
    have = l1i_hit | l1d_hit
    serve = found & have
    was_dirty = ((l1d_hit & ((l1d_state == MODIFIED)))
                 | (l1i_hit & (l1i_state == MODIFIED)))

    is_inv = ftype == MSG_INV_REQ
    is_wb = ftype == MSG_WB_REQ
    done_ps = ftime + sync_l1_net + ccyc(mp.l1d.data_and_tags_cycles)

    inv_do = serve & ~is_wb
    l1i_row = ca.row_invalidate(l1i_row, fline, inv_do & l1i_hit)
    l1d_row = ca.row_invalidate(l1d_row, fline, inv_do & l1d_hit)
    # WB downgrades M/E -> SHARED, data written back
    l1i_row = ca.row_set_state(l1i_row, l1i_way, SHARED,
                               serve & is_wb & l1i_hit)
    l1d_row = ca.row_set_state(l1d_row, l1d_way, SHARED,
                               serve & is_wb & l1d_hit)
    l1i = ca.scatter_row(ms.l1i, px.lo(l1i_row))
    l1d = ca.scatter_row(ms.l1d, px.lo(l1d_row))

    # ack: FLUSH_REP when dirty data travels (flush of M, or WB of M),
    # else INV_REP / WB_REP
    ack = jnp.where(
        is_inv, MSG_INV_REP,
        jnp.where(is_wb,
                  jnp.where(was_dirty, MSG_FLUSH_REP, MSG_WB_REP),
                  MSG_FLUSH_REP)).astype(jnp.uint8)
    # a FLUSH of a clean (S/E) line carries no data: INV_REP
    ack = jnp.where((ftype == MSG_FLUSH_REQ) & ~was_dirty, MSG_INV_REP, ack)
    ack_bits = jnp.where(ack == MSG_INV_REP, mp.req_bits, mp.rep_bits)
    noc, ack_arrival = mem_net_send(
        mp, ms.noc, tiles, h, ack_bits, done_ps, serve, enabled)
    wh = jnp.where(serve, h, 0)
    mail = mail.replace(
        ack_type=mail.ack_type.at[wh, tiles].set(
            jnp.where(serve, ack, mail.ack_type[wh, tiles])),
        ack_line=mail.ack_line.at[wh, tiles].set(
            jnp.where(serve, fline, mail.ack_line[wh, tiles])),
        ack_time=mail.ack_time.at[wh, tiles].set(
            jnp.where(serve, ack_arrival, mail.ack_time[wh, tiles])),
    )
    ch = jnp.where(found, h, 0)
    mail = mail.replace(
        fwd_type=mail.fwd_type.at[tiles, ch].set(
            jnp.where(found, MSG_NONE, mail.fwd_type[tiles, ch])),
    )
    counters = ms.counters.replace(
        invalidations=ms.counters.invalidations
        + (serve & is_inv & enabled).astype(I64))
    progress = progress + jnp.sum(found, dtype=jnp.int32)
    return ms.replace(l1i=l1i, l1d=l1d, mail=mail, counters=counters,
                      noc=noc), progress


def _home_evictions(mp, ms: ShL2State, l2_access, enabled, progress,
                    px: ParallelCtx = IDENT, acc: "_RowAcc | None" = None):
    """L1 eviction notices update the embedded directory; dirty flushes
    land in the L2 slice (its line turns MODIFIED wrt DRAM)."""
    T = mp.n_tiles
    tiles = jnp.arange(T, dtype=jnp.int32)
    mail = ms.mail

    src, found = _row_earliest(mail.evict_type, mail.evict_time)
    etype = mail.evict_type[tiles, src]
    eline = mail.evict_line[tiles, src]
    etime = mail.evict_time[tiles, src]

    eline_l = px.lo(eline)
    mod_l = px.lo_const(mp.l2.sets_mod)
    l2row_l = ca.gather_row(ms.l2, eline_l, mod_l)
    sets_l = (eline_l % jnp.asarray(mod_l)).astype(jnp.int32)
    dw_l, dsh_l = _dir_rows_local(ms.dir, sets_l)
    (l2row,), (dw, dsh) = _rows_exchange(px, (l2row_l,), (dw_l, dsh_l))
    dw0, dsh0 = dw, dsh
    l2_hit, l2_way, l2_state = ca.row_lookup(l2row, eline)
    sets = (eline % jnp.asarray(mp.l2.sets_mod)).astype(jnp.int32)
    apply = found & l2_hit
    dstate, owner, sharers, nsh, cloc = _entry_at(dw, dsh, l2_way)

    was_sharer = test_bit(sharers, src)
    new_sharers = clear_bit(sharers, src, apply)
    new_nsh = nsh - (apply & was_sharer).astype(jnp.int32)
    is_flush = etype == MSG_FLUSH_REP
    from_owner = src == owner
    new_owner = jnp.where(apply & from_owner, -1, owner)
    new_dstate = jnp.where(
        apply,
        jnp.where(new_nsh == 0, DIR_UNCACHED, DIR_SHARED),
        dstate).astype(jnp.uint8)
    dw = _row_update(dw, l2_way, apply, dstate=new_dstate, owner=new_owner,
                     nsharers=new_nsh)
    dsh = _rowsh_update(dsh, l2_way, apply, new_sharers)
    d = _dir_scatter(ms.dir, px, sets, dw0, dw, dsh0, dsh, acc=acc)
    # dirty flush data lands in the slice
    l2row = ca.row_set_state(l2row, l2_way, MODIFIED, apply & is_flush)
    l2 = ca.scatter_row(ms.l2, px.lo(l2row))

    txn = ms.txn
    txn_match = txn.active & found & (txn.line == eline)
    txn = txn.replace(
        pending=clear_bit(txn.pending, src, txn_match),
        time_ps=jnp.where(txn_match,
                          jnp.maximum(txn.time_ps, etime + l2_access),
                          txn.time_ps),
        got_flush=txn.got_flush | (txn_match & is_flush),
    )
    csrc = jnp.where(found, src, 0)
    mail = mail.replace(
        evict_type=mail.evict_type.at[tiles, csrc].set(
            jnp.where(found, MSG_NONE, mail.evict_type[tiles, csrc])),
    )
    counters = ms.counters.replace(
        evictions=ms.counters.evictions + (found & enabled).astype(I64))
    progress = progress + jnp.sum(found, dtype=jnp.int32)
    return ms.replace(dir=d, l2=l2, mail=mail, txn=txn,
                      counters=counters), progress


def _home_finish(mp, ms: ShL2State, l2_access, sync_l2_net, enabled,
                 progress, mesi, px: ParallelCtx = IDENT,
                 acc: "_RowAcc | None" = None):
    """Consume acks + DRAM arrivals; finish when nothing is pending."""
    T = mp.n_tiles
    tiles = jnp.arange(T, dtype=jnp.int32)
    mail = ms.mail
    txn = ms.txn

    match = (mail.ack_type != MSG_NONE) & txn.active[:, None] & (
        mail.ack_line == txn.line[:, None])
    any_match = match.any(axis=1)
    max_ack = jnp.where(match, mail.ack_time, 0).max(axis=1)
    got_flush = (match & (mail.ack_type == MSG_FLUSH_REP)).any(axis=1)

    SW = mp.sharer_words
    pad = SW * 32 - T
    mpad = jnp.pad(match, ((0, 0), (0, pad)))
    acked_words = (
        mpad.reshape(T, SW, 32).astype(U32)
        << jnp.arange(32, dtype=U32)[None, None, :]
    ).sum(axis=2, dtype=U32)
    txn = txn.replace(
        pending=txn.pending & ~acked_words,
        time_ps=jnp.where(any_match,
                          jnp.maximum(txn.time_ps, max_ack + l2_access),
                          txn.time_ps),
        got_flush=txn.got_flush | got_flush,
    )
    mail = mail.replace(ack_type=jnp.where(
        mail.ack_type != MSG_NONE, MSG_NONE, mail.ack_type))

    # the phase's L2 + directory rows for each home's transaction line
    tl_l = px.lo(txn.line)
    mod_l = px.lo_const(mp.l2.sets_mod)
    l2row_l = ca.gather_row(ms.l2, tl_l, mod_l)
    sets_l = (tl_l % jnp.asarray(mod_l)).astype(jnp.int32)
    dw_l, dsh_l = _dir_rows_local(ms.dir, sets_l)
    (l2row,), (dw, dsh) = _rows_exchange(px, (l2row_l,), (dw_l, dsh_l))
    dw0, dsh0 = dw, dsh
    sets = (txn.line % jnp.asarray(mp.l2.sets_mod)).astype(jnp.int32)

    # DRAM arrival: the fetched line fills the slice in SHARED
    dram_in = txn.active & (txn.dram_ready_ps < FAR) & (
        txn.pending == 0).all(axis=1)
    l2_hit, l2_way, _ = ca.row_lookup(l2row, txn.line)
    l2row = ca.row_set_state(l2row, l2_way, SHARED, dram_in & l2_hit)
    txn = txn.replace(
        time_ps=jnp.where(dram_in,
                          jnp.maximum(txn.time_ps, txn.dram_ready_ps),
                          txn.time_ps),
        dram_ready_ps=jnp.where(dram_in, FAR, txn.dram_ready_ps),
    )

    # finish: no pending acks, no pending dram
    no_pending = (txn.pending == 0).all(axis=1) & (txn.dram_ready_ps >= FAR)
    finish = txn.active & no_pending
    is_ex = txn.mtype == MSG_EX_REQ
    is_sh = txn.mtype == MSG_SH_REQ
    is_nullify = txn.mtype == MSG_NULLIFY

    _, l2_way, l2_state = ca.row_lookup(l2row, txn.line)
    r = txn.requester
    rbit = set_bit(jnp.zeros((T, mp.sharer_words), U32), r, finish)
    dstate, owner, sharers, nsh, cloc = _entry_at(dw, dsh, l2_way)

    # dirty acks flushed data into the slice
    l2row = ca.row_set_state(l2row, l2_way, MODIFIED,
                             finish & txn.got_flush & ~is_nullify)

    # EX finish: directory MODIFIED owner=r
    exf = finish & is_ex
    dw = _row_update(dw, l2_way, exf,
                     dstate=jnp.full(T, DIR_MODIFIED, jnp.uint8), owner=r,
                     nsharers=jnp.ones(T, jnp.int32), cloc=txn.req_comp)
    dsh = _rowsh_update(dsh, l2_way, exf, rbit)
    # SH finish: add r as a sharer; MESI grants EXCLUSIVE when alone
    shf = finish & is_sh
    had = test_bit(sharers, r)
    alone = (nsh - had.astype(jnp.int32)) == 0
    excl = shf & alone & mesi
    sh_dstate = jnp.where(excl, DIR_EXCLUSIVE, DIR_SHARED).astype(jnp.uint8)
    dw = _row_update(dw, l2_way, shf, dstate=sh_dstate,
                     owner=jnp.where(excl, r, -1),
                     nsharers=nsh + (~had).astype(jnp.int32),
                     cloc=txn.req_comp)
    dsh = _rowsh_update(dsh, l2_way, shf, sharers | rbit)
    # NULLIFY finish: entry dies; dirty data (slice M or flushed) → DRAM
    nlf = finish & is_nullify
    wb_dram = nlf & ((l2_state == MODIFIED) | txn.got_flush)
    l2row = ca.row_invalidate(l2row, txn.line, nlf)
    dw = _row_update(dw, l2_way, nlf,
                     dstate=jnp.full(T, DIR_UNCACHED, jnp.uint8),
                     owner=jnp.full(T, -1, jnp.int32),
                     nsharers=jnp.zeros(T, jnp.int32))
    dsh = _rowsh_update(dsh, l2_way, nlf,
                        jnp.zeros((T, mp.sharer_words), U32))
    l2 = ca.scatter_row(ms.l2, px.lo(l2row))
    d = _dir_scatter(ms.dir, px, sets, dw0, dw, dsh0, dsh, acc=acc)

    # reply to the requester (the slice access was charged at txn start)
    rep_ready = txn.time_ps + sync_l2_net
    rep_msg = jnp.where(
        finish & is_ex, MSG_EX_REP,
        jnp.where(excl, MSG_EXCL_REP, MSG_SH_REP)).astype(jnp.uint8)
    rep_go = finish & ~is_nullify
    noc, rep_arrival = mem_net_send(
        mp, ms.noc, tiles, r, mp.rep_bits, rep_ready, rep_go, enabled)
    wr = jnp.where(rep_go, r, 0)
    mail = mail.replace(
        rep_type=mail.rep_type.at[wr].add(
            jnp.where(rep_go, rep_msg, 0).astype(jnp.uint8)),
        rep_time=mail.rep_time.at[wr].add(
            jnp.where(rep_go, rep_arrival, 0)),
    )
    mail = mail.replace(
        fwd_type=jnp.where(finish[None, :], MSG_NONE, mail.fwd_type))
    txn = txn.replace(
        active=txn.active & ~finish,
        got_flush=txn.got_flush & ~finish,
        last_line=jnp.where(finish, txn.line, txn.last_line),
        last_done_ps=jnp.where(finish, rep_ready, txn.last_done_ps),
    )
    counters = ms.counters.replace(
        dram_writes=ms.counters.dram_writes + (wb_dram & enabled).astype(I64),
    )
    progress = progress + jnp.sum(finish, dtype=jnp.int32) + jnp.sum(
        any_match | dram_in, dtype=jnp.int32)
    return ms.replace(l2=l2, dir=d, mail=mail, txn=txn,
                      counters=counters, noc=noc), progress


def _home_starts(mp, ms: ShL2State, l2_access, sync_l2_net, enabled,
                 progress, mesi, px: ParallelCtx = IDENT,
                 acc: "_RowAcc | None" = None):
    T = mp.n_tiles
    tiles = jnp.arange(T, dtype=jnp.int32)
    mail = ms.mail
    txn = ms.txn

    can_start = ~txn.active
    use_saved = can_start & txn.saved_valid
    r_col, r_found = _req_earliest(mail)
    use_pop = can_start & ~use_saved & r_found
    starting = use_saved | use_pop
    rtype = jnp.where(use_saved, txn.saved_type,
                      mail.req_type[r_col]).astype(jnp.uint8)
    rline = jnp.where(use_saved, txn.saved_line, mail.req_line[r_col])
    rreq = jnp.where(use_saved, txn.saved_requester, r_col)
    rcomp = jnp.where(use_saved, txn.saved_comp, MOD_L1D).astype(jnp.uint8)
    rtime = jnp.where(use_saved, txn.saved_time_ps,
                      mail.req_time[r_col])
    rtime = rtime + jnp.where(use_saved, 0, sync_l2_net)
    rtime = jnp.where(starting & (rline == txn.last_line),
                      jnp.maximum(rtime, txn.last_done_ps), rtime)
    mail = _req_consume(mail, use_pop, r_col)
    txn = txn.replace(saved_valid=txn.saved_valid & ~use_saved)

    # ---- L2 slice lookup / allocation (all on rline's SET: the victim
    # and the effective line share it, so ONE row exchange serves the
    # whole phase) ---------------------------------------------------------
    rline_l = px.lo(rline)
    mod_l = px.lo_const(mp.l2.sets_mod)
    l2row_l = ca.gather_row(ms.l2, rline_l, mod_l)
    sets_l = (rline_l % jnp.asarray(mod_l)).astype(jnp.int32)
    dw_l, dsh_l = _dir_rows_local(ms.dir, sets_l)
    (l2row,), (dw, dsh) = _rows_exchange(px, (l2row_l,), (dw_l, dsh_l))
    dw0, dsh0 = dw, dsh
    sets = (rline % jnp.asarray(mp.l2.sets_mod)).astype(jnp.int32)

    l2_hit, way, l2_state = ca.row_lookup(l2row, rline)
    # allocate on miss; a valid victim with L1 copies runs NULLIFY first
    v_way, v_valid, v_line, v_state = ca.row_pick_victim(
        l2row, mp.l2.replacement, mp.l2.ways_limit)
    v_dstate, v_owner, v_sharers, v_nsh, v_cloc = _entry_at(dw, dsh, v_way)
    need_alloc = starting & ~l2_hit
    nullify_live = need_alloc & v_valid & (v_dstate != DIR_UNCACHED)
    # clean victim with no L1 copies: drop now (dirty → DRAM write)
    silent_kill = need_alloc & v_valid & (v_dstate == DIR_UNCACHED)
    l2row = ca.row_invalidate(l2row, v_line, silent_kill)
    dram_wb = silent_kill & (v_state == MODIFIED)

    txn = txn.replace(
        saved_valid=jnp.where(nullify_live, True, txn.saved_valid),
        saved_type=jnp.where(nullify_live, rtype, txn.saved_type),
        saved_line=jnp.where(nullify_live, rline, txn.saved_line),
        saved_requester=jnp.where(nullify_live, rreq, txn.saved_requester),
        saved_comp=jnp.where(nullify_live, rcomp, txn.saved_comp),
        saved_time_ps=jnp.where(nullify_live, rtime, txn.saved_time_ps),
    )
    # install the new line (DATA_INVALID until DRAM returns)
    do_install = need_alloc & ~nullify_live
    alloc_way = v_way  # pick_victim returns invalid-way-first
    l2row = ca.row_insert(l2row, rline, alloc_way, DATA_INVALID, do_install)
    dw = _row_update(dw, alloc_way, do_install,
                     dstate=jnp.full(T, DIR_UNCACHED, jnp.uint8),
                     owner=jnp.full(T, -1, jnp.int32),
                     nsharers=jnp.zeros(T, jnp.int32))
    dsh = _rowsh_update(dsh, alloc_way, do_install,
                        jnp.zeros((T, mp.sharer_words), U32))

    eff_line = jnp.where(nullify_live, v_line, rline)
    eff_type = jnp.where(nullify_live, MSG_NULLIFY, rtype).astype(jnp.uint8)
    eff_time = rtime + l2_access
    run_req = starting & ~nullify_live

    # re-read the directory for the effective line (post-install rows)
    _, eff_way, eff_l2_state = ca.row_lookup(l2row, eff_line)
    dstate, owner, sharers, nsh, cloc = _entry_at(dw, dsh, eff_way)

    is_ex = eff_type == MSG_EX_REQ
    is_sh = eff_type == MSG_SH_REQ
    data_missing = run_req & (eff_l2_state == DATA_INVALID)

    # (a) data present, dstate FSM
    served = run_req & ~data_missing
    uncached = dstate == DIR_UNCACHED
    shared = dstate == DIR_SHARED
    owned_like = (dstate == DIR_MODIFIED) | (dstate == DIR_EXCLUSIVE)

    # immediate finishes: SH on UNCACHED/SHARED, EX on UNCACHED → resolved
    # by the finish pass next iteration (pending stays empty).  Fan-outs:
    # EX on SHARED → INV sharers; anything on M/E → FLUSH/WB the owner;
    # NULLIFY → INV/FLUSH everyone.
    is_nullify = eff_type == MSG_NULLIFY
    fan_inv = (served & is_ex & shared) | (nullify_live & shared)
    fan_owner = ((served | nullify_live) & owned_like)
    owner_bits = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                         jnp.clip(owner, 0, T - 1), fan_owner)
    pending = jnp.where(fan_inv[:, None], sharers, owner_bits)
    fan = fan_inv | fan_owner
    fwd_msg = jnp.where(
        fan_inv, MSG_INV_REQ,
        jnp.where(is_sh, MSG_WB_REQ, MSG_FLUSH_REQ)).astype(jnp.uint8)
    # EX on SHARED where the requester itself is a sharer: don't ask the
    # requester to invalidate its own line (upgrade) — clear its bit.
    # ONLY for the upgrade case: a NULLIFY sweep must invalidate the saved
    # requester's copy of the VICTIM line too, or it would keep a stale L1
    # copy after the directory entry dies.
    upgrade_clear = served & is_ex & shared
    pending = clear_bit(pending, jnp.clip(rreq, 0, T - 1),
                        upgrade_clear & test_bit(pending, rreq))

    # ---- directory-scheme variants on the embedded L1-sharer directory
    # (`l2_directory_cfg.cc` analog; same semantics as the private-L2
    # engine's schemes — see memory/engine.py)
    k = mp.max_hw_sharers
    already = test_bit(sharers, rreq)
    sh_over = jnp.zeros((T,), jnp.bool_)
    over_bc = jnp.zeros((T,), jnp.bool_)
    if mp.dir_type == "limited_no_broadcast":
        # SH on SHARED at capacity: displace the lowest tracked sharer
        sh_over = served & is_sh & shared & (nsh >= k) & ~already
        victim = lowest_sharer(sharers)
        victim_bits = set_bit(jnp.zeros((T, mp.sharer_words), U32),
                              jnp.clip(victim, 0, T - 1),
                              sh_over & (victim >= 0))
        dw = _row_update(dw, eff_way, sh_over, nsharers=nsh - 1)
        dsh = _rowsh_update(dsh, eff_way, sh_over, sharers & ~victim_bits)
        pending = jnp.where(sh_over[:, None], victim_bits, pending)
        fwd_msg = jnp.where(sh_over, MSG_INV_REQ, fwd_msg).astype(jnp.uint8)
        fan = fan | sh_over
        # M/E at capacity (k=1): the owner's WB becomes a FLUSH and the
        # entry empties (addSharer failure on the downgrade); the finish
        # then installs {requester} alone (MESI re-grants EXCLUSIVE)
        sh_over_m = served & is_sh & owned_like & (nsh >= k) & ~already
        fwd_msg = jnp.where(sh_over_m, MSG_FLUSH_REQ,
                            fwd_msg).astype(jnp.uint8)
        dw = _row_update(dw, eff_way, sh_over_m,
                         dstate=jnp.full(T, DIR_UNCACHED, jnp.uint8),
                         owner=jnp.full(T, -1, jnp.int32),
                         nsharers=jnp.zeros(T, jnp.int32))
        dsh = _rowsh_update(dsh, eff_way, sh_over_m,
                            jnp.zeros((T, mp.sharer_words), U32))
    if mp.dir_type == "limitless":
        sw_mode = (nsh > k) | (is_sh & ~already & (nsh >= k)
                               & (shared | owned_like))
        eff_time = eff_time + jnp.where(
            enabled & starting & sw_mode,
            cycles_to_ps(jnp.asarray(mp.limitless_trap_cycles, I64),
                         mp.dir_freq_mhz),
            0)
    l2 = ca.scatter_row(ms.l2, px.lo(l2row))
    d = _dir_scatter(ms.dir, px, sets, dw0, dw, dsh0, dsh, acc=acc)

    activate = fan | data_missing | served | nullify_live
    txn = txn.replace(
        active=txn.active | (starting & activate),
        mtype=jnp.where(starting, eff_type, txn.mtype).astype(jnp.uint8),
        line=jnp.where(starting, eff_line, txn.line),
        requester=jnp.where(starting, rreq, txn.requester),
        req_comp=jnp.where(starting, rcomp, txn.req_comp).astype(jnp.uint8),
        time_ps=jnp.where(starting, eff_time, txn.time_ps),
        pending=jnp.where(starting[:, None], pending, txn.pending),
        got_flush=jnp.where(starting, False, txn.got_flush),
        dram_ready_ps=jnp.where(
            data_missing,
            eff_time + _dram_lat_ps(mp, tiles, enabled),
            jnp.where(starting, FAR, txn.dram_ready_ps)),
    )

    # multicast forwards
    targets = unpack_sharers(pending, T)
    send = fan[:, None] & targets
    if mp.dir_type in ("ackwise", "limited_broadcast"):
        # overflowed entries lose sharer precision: INV sweeps broadcast to
        # every tile except the requester (its upgrade copy must survive);
        # acks still awaited only from true holders (non-holders silent)
        over_bc = fan_inv & (nsh > k)
        send = send | (over_bc[:, None]
                       & (tiles[None, :] != jnp.clip(rreq, 0, T - 1)[:, None]))
    send_t = send.T
    noc, arrive = mem_net_fanout(
        mp, ms.noc, send, mp.req_bits, eff_time, enabled)
    mail = mail.replace(
        fwd_type=jnp.where(send_t, fwd_msg[None, :], mail.fwd_type),
        fwd_line=jnp.where(send_t, eff_line[None, :], mail.fwd_line),
        fwd_time=jnp.where(send_t, arrive.T, mail.fwd_time),
    )
    counters = ms.counters.replace(
        dir_accesses=ms.counters.dir_accesses
        + (starting & enabled).astype(I64),
        dir_broadcasts=ms.counters.dir_broadcasts
        + (over_bc & enabled).astype(I64),
        l2_hits=ms.counters.l2_hits
        + (run_req & ~data_missing & enabled).astype(I64),
        l2_misses=ms.counters.l2_misses
        + (data_missing & enabled).astype(I64),
        dram_reads=ms.counters.dram_reads
        + (data_missing & enabled).astype(I64),
        dram_writes=ms.counters.dram_writes + (dram_wb & enabled).astype(I64),
        dram_total_lat_ps=ms.counters.dram_total_lat_ps
        + jnp.where(data_missing & enabled,
                    (mp.dram_latency_ns + mp.dram_processing_ns) * 1000, 0),
    )
    progress = progress + jnp.sum(starting, dtype=jnp.int32)
    return ms.replace(l2=l2, dir=d, mail=mail, txn=txn,
                      counters=counters, noc=noc), progress


def _requester_fill(mp, ms: ShL2State, rec: RecView, clock_ps, fmhz,
                    enabled, progress, sync_l1_net,
                    px: ParallelCtx = IDENT):
    """Reply fills the L1 (`handleMsgFromL2Cache` → insertCacheLine)."""
    T = mp.n_tiles
    tiles = jnp.arange(T, dtype=jnp.int32)
    mail = ms.mail

    def ccyc(n):
        ps = cycles_to_ps(jnp.asarray(n, I64), fmhz)
        return jnp.where(enabled, ps, 0)

    have_rep = (ms.req.phase == PHASE_WAIT_REPLY) & (mail.rep_type != MSG_NONE)
    line = ms.req.line
    comp_l1i = ms.req.component == MOD_L1I
    new_state = jnp.where(
        mail.rep_type == MSG_EX_REP, MODIFIED,
        jnp.where(mail.rep_type == MSG_EXCL_REP, EXCLUSIVE,
                  SHARED)).astype(jnp.uint8)

    # Upgrade replies land in the line's EXISTING way (the S copy stays
    # put during an EX upgrade); only true misses pick a victim.
    line_l = px.lo(line)
    rows_l = (
        ca.gather_row(ms.l1i, line_l, px.lo_const(mp.l1i.sets_mod)),
        ca.gather_row(ms.l1d, line_l, px.lo_const(mp.l1d.sets_mod)),
    )
    (l1i_row, l1d_row), _ = _rows_exchange(px, rows_l)
    l1i_hit, l1i_hway, _ = ca.row_lookup(l1i_row, line)
    l1d_hit, l1d_hway, _ = ca.row_lookup(l1d_row, line)
    l1i_vway, l1i_vv, l1i_vline, l1i_vstate = ca.row_pick_victim(
        l1i_row, mp.l1i.replacement, mp.l1i.ways_limit)
    l1d_vway, l1d_vv, l1d_vline, l1d_vstate = ca.row_pick_victim(
        l1d_row, mp.l1d.replacement, mp.l1d.ways_limit)
    l1i_way = jnp.where(l1i_hit, l1i_hway, l1i_vway)
    l1d_way = jnp.where(l1d_hit, l1d_hway, l1d_vway)
    already = jnp.where(comp_l1i, l1i_hit, l1d_hit)
    v_valid = jnp.where(comp_l1i, l1i_vv, l1d_vv) & ~already
    v_line = jnp.where(comp_l1i, l1i_vline, l1d_vline)
    v_state = jnp.where(comp_l1i, l1i_vstate, l1d_vstate)
    v_home = _l2_home(mp, v_line)
    need_evict = have_rep & v_valid
    evict_busy = mail.evict_type[v_home, tiles] != MSG_NONE
    fill = have_rep & ~(need_evict & evict_busy)
    evict_go = need_evict & fill

    l1i_row = ca.row_insert(l1i_row, line, l1i_way, new_state,
                            fill & comp_l1i)
    l1d_row = ca.row_insert(l1d_row, line, l1d_way, new_state,
                            fill & ~comp_l1i)
    l1i = ca.scatter_row(ms.l1i, px.lo(l1i_row))
    l1d = ca.scatter_row(ms.l1d, px.lo(l1d_row))

    e_msg = jnp.where(v_state == MODIFIED, MSG_FLUSH_REP,
                      MSG_INV_REP).astype(jnp.uint8)
    fill_ps = mail.rep_time + sync_l1_net + ccyc(
        mp.l1d.data_and_tags_cycles)
    e_bits = jnp.where(v_state == MODIFIED, mp.rep_bits, mp.req_bits)
    noc, e_arrival = mem_net_send(
        mp, ms.noc, tiles, v_home, e_bits, fill_ps, evict_go, enabled)
    wh = jnp.where(evict_go, v_home, 0)
    mail = mail.replace(
        evict_type=mail.evict_type.at[wh, tiles].set(
            jnp.where(evict_go, e_msg, mail.evict_type[wh, tiles])),
        evict_line=mail.evict_line.at[wh, tiles].set(
            jnp.where(evict_go, v_line, mail.evict_line[wh, tiles])),
        evict_time=mail.evict_time.at[wh, tiles].set(
            jnp.where(evict_go, e_arrival,
                      mail.evict_time[wh, tiles])),
        rep_type=jnp.where(fill, MSG_NONE, mail.rep_type),
        rep_time=jnp.where(fill, 0, mail.rep_time),
    )
    req = ms.req.replace(
        phase=jnp.where(fill, PHASE_IDLE, ms.req.phase),
        slot=jnp.where(fill, ms.req.slot + 1, ms.req.slot),
        acc_ps=ms.req.acc_ps + jnp.where(fill, fill_ps - clock_ps, 0),
        slot_lat_ps=jnp.where(
            (fill[:, None]
             & (jnp.arange(3)[None, :] == ms.req.slot[:, None])),
            (fill_ps - clock_ps)[:, None], ms.req.slot_lat_ps),
    )
    ms = ms.replace(l1i=l1i, l1d=l1d, mail=mail, req=req, noc=noc)
    s_addr = jnp.where(ms.req.slot - 1 == 1, rec.addr0.astype(jnp.int32),
                       rec.addr1.astype(jnp.int32))
    ms = _apply_functional(mp, ms, rec, ms.req.slot - 1, s_addr,
                           ms.req.is_write, fill)
    counters = ms.counters.replace(
        evictions=ms.counters.evictions + (evict_go & enabled).astype(I64))
    progress = progress + jnp.sum(fill, dtype=jnp.int32)
    return ms.replace(counters=counters), progress


# ---------------------------------------------------------------------------
# Host-side census (analysis/protocol.py differential mode)
# ---------------------------------------------------------------------------


def shl2_line_census(ms: ShL2State, mp: MemParams, lines) -> dict:
    """Abstract per-line coherence view of a (fetched) ShL2State.

    Shared-L2 counterpart of `engine.line_census`: per line, the per-tile
    L1I/L1D states, the home slice's L2 data state, and the embedded
    directory entry at the slice way holding the line.  Pure host-side
    numpy; see `analysis/protocol.py`.
    """
    l1i_tag = np.asarray(ms.l1i.tags)
    l1i_st = np.asarray(ms.l1i.state)
    l1d_tag = np.asarray(ms.l1d.tags)
    l1d_st = np.asarray(ms.l1d.state)
    l2_tag = np.asarray(ms.l2.tags)
    l2_st = np.asarray(ms.l2.state)
    word = np.asarray(ms.dir.word)
    sharers = np.asarray(ms.dir.sharers)
    T = mp.n_tiles
    sw = mp.sharer_words

    def cache_state(tag, st, t, line):
        s = line % tag.shape[1]
        hit = tag[t, s, :] == line
        return int(st[t, s, hit.argmax()]) if hit.any() else 0

    out = {}
    for line in lines:
        home = line % T
        sset = line % l2_tag.shape[1]
        slice_st = 0
        dent = None
        hit = l2_tag[home, sset, :] == line
        if hit.any():
            way = int(hit.argmax())
            slice_st = int(l2_st[home, sset, way])
            w = int(word[home, sset, way])
            dstate = (w >> SHL2_STATE_SHIFT) & 7
            owner = ((w >> SHL2_OWNER_SHIFT) & _ID_MASK) - 1
            bits = sharers[home, sset, way * sw:(way + 1) * sw]
            shset = frozenset(
                i * 32 + b for i in range(sw) for b in range(32)
                if (int(bits[i]) >> b) & 1)
            dent = (int(dstate), int(owner), shset)
        out[line] = {
            "l1i": tuple(cache_state(l1i_tag, l1i_st, t, line)
                         for t in range(T)),
            "l1d": tuple(cache_state(l1d_tag, l1d_st, t, line)
                         for t in range(T)),
            "slice": slice_st,
            "dir": dent,
        }
    return out
