"""Vectorized set-associative cache arrays (packed representation).

The reference `Cache` (`common/tile/memory_subsystem/cache/cache.h:26-135`)
is a per-tile C++ object: tag store + state + replacement policy, accessed
one address at a time under a lock.  Here a cache *level* across all tiles
is ONE dense tensor

    meta int64[T, S, W] = line(32 bits, signed; -1 = free) << 16
                        | state(8) << 8 | lru(8)

and every operation is a masked gather/scatter over the tile axis.  The
three logical fields live in one word so a lookup is a single gather and
an insert a single scatter — the memory engine is op-count-bound on TPU
(hundreds of small kernels per subquantum iteration), so each saved
gather/scatter kernel is wall-clock (see PERF.md "Engine cost model").

Two API levels:
 - element ops (`lookup`/`touch_lru`/`insert_at`/...) — one gather or
   scatter each, used by the shared-L2 engine and tests;
 - row ops (`gather_row`/`scatter_row` + `row_*`) — fetch each lane's set
   row ONCE per engine phase, do every lookup/victim/insert decision as
   [T, W] elementwise math, write the row back once.  The private-L2
   engine phases use these.

Set index = line % num_sets, matching the reference `CacheHashFn` modulo
mapping (`cache/cache_hash_fn.cc`).  Replacement is LRU with
invalid-way-first victim selection (`cache/lru_replacement_policy.cc`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.intmath import nn_mod

# CacheState (`common/tile/memory_subsystem/cache_state.h`).
INVALID = 0
SHARED = 1
MODIFIED = 2
EXCLUSIVE = 3   # MESI protocols
OWNED = 4       # MOSI protocols

# readable: S/E/M/O; writable: E/M (`cache_state.h` readable()/writable()).
_READABLE = (1 << SHARED) | (1 << MODIFIED) | (1 << EXCLUSIVE) | (1 << OWNED)
_WRITABLE = (1 << MODIFIED) | (1 << EXCLUSIVE)

I64 = jnp.int64


def state_readable(state: jax.Array) -> jax.Array:
    return ((_READABLE >> state.astype(jnp.int32)) & 1).astype(jnp.bool_)


def state_writable(state: jax.Array) -> jax.Array:
    return ((_WRITABLE >> state.astype(jnp.int32)) & 1).astype(jnp.bool_)


def _pack(line, state, lru):
    return ((jnp.asarray(line).astype(I64) << 16)
            | (jnp.asarray(state).astype(I64) << 8)
            | jnp.asarray(lru).astype(I64))


def _unpack(meta):
    # arithmetic >> keeps line == -1 working (sign-extends through int32)
    return (
        (meta >> 16).astype(jnp.int32),
        ((meta >> 8) & 0xFF).astype(jnp.uint8),
        (meta & 0xFF).astype(jnp.int32),
    )


@struct.dataclass
class CacheArrays:
    meta: jax.Array   # int64[T, S, W]

    @property
    def num_sets(self) -> int:
        return self.meta.shape[1]

    @property
    def num_ways(self) -> int:
        return self.meta.shape[2]

    # host-side convenience views (statistics sampling, tests)
    @property
    def tags(self) -> jax.Array:
        return (self.meta >> 16).astype(jnp.int32)

    @property
    def state(self) -> jax.Array:
        return ((self.meta >> 8) & 0xFF).astype(jnp.uint8)

    @property
    def lru(self) -> jax.Array:
        return (self.meta & 0xFF).astype(jnp.uint8)


def make_cache(n_tiles: int, num_sets: int, num_ways: int) -> CacheArrays:
    shape = (n_tiles, num_sets, num_ways)
    # lru ranks start as a strict permutation 0..W-1 per set; touches
    # preserve the permutation (bump-below-rank + zero the way)
    lru0 = jnp.broadcast_to(jnp.arange(num_ways, dtype=I64), shape)
    return CacheArrays(meta=(jnp.asarray(-1, I64) << 16) | lru0)


# ---------------------------------------------------------------------------
# row-level API: one gather per phase, [T, W] elementwise math, one scatter


@struct.dataclass
class CacheRow:
    """One set row per lane: each lane's (line % S) row of a cache level."""

    tag: jax.Array   # int32[T, W]
    st: jax.Array    # int32[T, W]  (int32 for arithmetic convenience)
    lru: jax.Array   # int32[T, W]
    sets: jax.Array  # int32[T]
    meta0: jax.Array  # int64[T, W] packed words as gathered (delta base)


def gather_row(cache: CacheArrays, line: jax.Array,
               sets_mod=None, *, nonneg: bool = False) -> CacheRow:
    """`sets_mod`: per-tile set count (int or int32[T]) for heterogeneous
    geometries; defaults to the array's (max) set dimension.

    `nonneg=True`: the caller guarantees `line >= 0` (record-derived and
    mailbox-carried lines), so the set index uses the one-equation
    `intmath.nn_mod` instead of the floor-mod fixup chain — bit-identical
    there.  Victim lines read off an invalid way can be -1 and must keep
    the default."""
    T = cache.meta.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    mod = cache.num_sets if sets_mod is None else jnp.asarray(sets_mod)
    sets = (nn_mod(line, mod) if nonneg else line % mod).astype(jnp.int32)
    meta = cache.meta[tiles, sets]                 # [T, W] — ONE gather
    tag, st, lru = _unpack(meta)
    return CacheRow(tag=tag, st=st.astype(jnp.int32), lru=lru, sets=sets,
                    meta0=meta)


def row_from_meta(meta: jax.Array, sets: jax.Array) -> CacheRow:
    """Rebuild a CacheRow from its packed (meta, sets) pair — the compact
    form a row travels in through the shard_map phase exchange (pack ∘
    unpack is the identity, so the rebuilt row is bit-equal to the
    gather_row original)."""
    tag, st, lru = _unpack(meta)
    return CacheRow(tag=tag, st=st.astype(jnp.int32), lru=lru, sets=sets,
                    meta0=meta)


def scatter_row(cache: CacheArrays, row: CacheRow) -> CacheArrays:
    """Write each lane's row back — ONE scatter, no masking: the row_*
    ops are themselves masked per lane, so an untouched lane's row packs
    back to exactly the live value.  Written add-a-delta against the
    gathered words (per-lane rows are distinct, so the add is exact):
    the scatter is then the meta array's only remaining use and XLA
    updates the loop-carried buffer in place instead of copying it."""
    T = cache.meta.shape[0]
    tiles = np.arange(T, dtype=np.int32)
    new_meta = _pack(row.tag, row.st, row.lru)
    return cache.replace(meta=cache.meta.at[tiles, row.sets].add(
        new_meta - row.meta0, unique_indices=True, indices_are_sorted=True))


def row_lookup(row: CacheRow, line: jax.Array):
    """(hit bool[T], way int32[T], state uint8[T]) within the row."""
    way_hits = (row.tag == line[:, None]) & (row.st != INVALID)
    hit = way_hits.any(axis=1)
    way = jnp.argmax(way_hits, axis=1).astype(jnp.int32)
    st = jnp.where(
        hit, jnp.take_along_axis(row.st, way[:, None], axis=1)[:, 0], INVALID
    ).astype(jnp.uint8)
    return hit, way, st


def row_touch(row: CacheRow, way: jax.Array, mask: jax.Array) -> CacheRow:
    """Make `way` the MRU of its row where mask (ranks below it shift up)."""
    rank = jnp.take_along_axis(row.lru, way[:, None], axis=1)
    bumped = row.lru + (row.lru < rank).astype(jnp.int32)
    onehot = np.arange(row.lru.shape[1])[None, :] == way[:, None]
    new_lru = jnp.where(onehot, 0, bumped)
    return row.replace(lru=jnp.where(mask[:, None], new_lru, row.lru))


def row_set_state(row: CacheRow, way: jax.Array, new_state,
                  mask: jax.Array) -> CacheRow:
    onehot = np.arange(row.st.shape[1])[None, :] == way[:, None]
    sel = onehot & mask[:, None]
    return row.replace(st=jnp.where(
        sel, jnp.broadcast_to(jnp.asarray(new_state, jnp.int32)[..., None],
                              row.st.shape), row.st))


def row_invalidate(row: CacheRow, line: jax.Array,
                   mask: jax.Array) -> CacheRow:
    hit, way, _ = row_lookup(row, line)
    return row_set_state(row, way, INVALID, mask & hit)


def row_pick_victim(row: CacheRow, policy: str = "lru", ways=None):
    """(way, victim_valid, victim_line, victim_state).

    lru (`lru_replacement_policy.cc`): first invalid way, else the
    max-rank way.  round_robin (`round_robin_replacement_policy.cc`): the
    set's rotating index regardless of validity — the rank permutation
    doubles as the rotation state (ranks only move on insertion, so the
    max-rank way IS the current index and inserting rotates it), and
    victim_valid reflects whether the chosen way held a live line.

    `ways` (int32[T] or None): per-tile way count for heterogeneous
    geometries — padded ways beyond it are never picked (their initial
    ranks sit above every usable rank and are masked here; touches never
    move them)."""
    usable = None
    if ways is not None:
        usable = (np.arange(row.lru.shape[1], dtype=np.int32)[None, :]
                  < jnp.asarray(ways)[:, None])
    lru_eff = row.lru if usable is None else jnp.where(usable, row.lru, -1)
    lru_way = jnp.argmax(lru_eff, axis=1)
    if policy == "round_robin":
        way = lru_way.astype(jnp.int32)
        victim_state = jnp.take_along_axis(
            row.st, way[:, None], axis=1)[:, 0].astype(jnp.uint8)
        victim_valid = victim_state != INVALID
    else:
        inv = row.st == INVALID
        if usable is not None:
            inv = inv & usable
        any_inv = inv.any(axis=1)
        inv_way = jnp.argmax(inv, axis=1)
        way = jnp.where(any_inv, inv_way, lru_way).astype(jnp.int32)
        victim_state = jnp.take_along_axis(
            row.st, way[:, None], axis=1)[:, 0].astype(jnp.uint8)
        victim_valid = ~any_inv
    victim_line = jnp.take_along_axis(row.tag, way[:, None], axis=1)[:, 0]
    return way, victim_valid, victim_line, victim_state


def row_insert(row: CacheRow, line: jax.Array, way: jax.Array, new_state,
               mask: jax.Array) -> CacheRow:
    """Install `line` at `way` with `new_state` where mask, making it MRU."""
    onehot = np.arange(row.tag.shape[1])[None, :] == way[:, None]
    sel = onehot & mask[:, None]
    out = row.replace(
        tag=jnp.where(sel, line[:, None], row.tag),
        st=jnp.where(
            sel,
            jnp.broadcast_to(jnp.asarray(new_state, jnp.int32)[..., None],
                             row.st.shape),
            row.st),
    )
    return row_touch(out, way, mask)


# ---------------------------------------------------------------------------
# element-level API (one gather/scatter per call) — shared-L2 engine, tests


def lookup(cache: CacheArrays, line: jax.Array, sets_mod=None):
    """Per-lane lookup: (hit bool[T], way int32[T], state uint8[T]).

    `Cache::getCacheLineInfo` (`cache.h:92`) vectorized: way is valid only
    where hit; state is INVALID where miss.
    """
    row = gather_row(cache, line, sets_mod)
    return row_lookup(row, line)


def touch_lru(cache: CacheArrays, line: jax.Array, way: jax.Array,
              mask: jax.Array, sets_mod=None) -> CacheArrays:
    """Make `way` the MRU of its set where mask (LRU ranks shift up)."""
    row = gather_row(cache, line, sets_mod)
    return scatter_row(cache, row_touch(row, way, mask))


def set_state(cache: CacheArrays, line: jax.Array, way: jax.Array,
              new_state: jax.Array, mask: jax.Array,
              sets_mod=None) -> CacheArrays:
    """Set the state of (line, way) where mask (`Cache::setCacheLineInfo`)."""
    row = gather_row(cache, line, sets_mod)
    return scatter_row(cache, row_set_state(row, way, new_state, mask))


def invalidate(cache: CacheArrays, line: jax.Array,
               mask: jax.Array, sets_mod=None) -> CacheArrays:
    """Invalidate `line` where mask & present (`Cache::invalidateCacheLine`)."""
    row = gather_row(cache, line, sets_mod)
    hit, way, _ = row_lookup(row, line)
    m = mask & hit
    return scatter_row(cache, row_set_state(row, way, INVALID, m))


def pick_victim(cache: CacheArrays, line: jax.Array, policy: str = "lru",
                sets_mod=None, ways=None):
    """Victim way per lane (see row_pick_victim for policy semantics).

    Returns (way int32[T], victim_valid bool[T], victim_line int32[T],
    victim_state uint8[T]).
    """
    row = gather_row(cache, line, sets_mod)
    return row_pick_victim(row, policy, ways)


def insert_at(cache: CacheArrays, line: jax.Array, way: jax.Array,
              new_state: jax.Array, mask: jax.Array,
              sets_mod=None) -> CacheArrays:
    """Install `line` in `way` with `new_state` where mask, making it MRU.

    `Cache::insertCacheLine` (`cache.h:90`) minus the eviction message
    (the caller handles the victim it got from pick_victim).
    """
    row = gather_row(cache, line, sets_mod)
    return scatter_row(cache, row_insert(row, line, way, new_state, mask))
