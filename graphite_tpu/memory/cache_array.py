"""Vectorized set-associative cache arrays.

The reference `Cache` (`common/tile/memory_subsystem/cache/cache.h:26-135`)
is a per-tile C++ object: tag store + state + replacement policy, accessed
one address at a time under a lock.  Here a cache *level* across all tiles
is three dense tensors

    tags  int32[T, S, W]   cache-line address (full line number, no split
                           tag/index — avoids reconstruction)
    state uint8[T, S, W]   CacheState (INVALID/SHARED/MODIFIED/... below)
    lru   uint8[T, S, W]   LRU rank, 0 = most recently used

and every operation is a masked gather/scatter over the tile axis: one XLA
op looks up (or updates) one line in *every* tile's cache simultaneously.
Each lane touches only its own tile's row, so scatters never collide;
masked-off lanes write back unchanged values.

Set index = line % num_sets, matching the reference `CacheHashFn` modulo
mapping (`cache/cache_hash_fn.cc`).  Replacement is LRU with
invalid-way-first victim selection (`cache/lru_replacement_policy.cc`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

# CacheState (`common/tile/memory_subsystem/cache_state.h`).
INVALID = 0
SHARED = 1
MODIFIED = 2
EXCLUSIVE = 3   # MESI protocols
OWNED = 4       # MOSI protocols

# readable: S/E/M/O; writable: E/M (`cache_state.h` readable()/writable()).
_READABLE = (1 << SHARED) | (1 << MODIFIED) | (1 << EXCLUSIVE) | (1 << OWNED)
_WRITABLE = (1 << MODIFIED) | (1 << EXCLUSIVE)


def state_readable(state: jax.Array) -> jax.Array:
    return ((_READABLE >> state.astype(jnp.int32)) & 1).astype(jnp.bool_)


def state_writable(state: jax.Array) -> jax.Array:
    return ((_WRITABLE >> state.astype(jnp.int32)) & 1).astype(jnp.bool_)


@struct.dataclass
class CacheArrays:
    tags: jax.Array   # int32[T, S, W]
    state: jax.Array  # uint8[T, S, W]
    lru: jax.Array    # uint8[T, S, W]

    @property
    def num_sets(self) -> int:
        return self.tags.shape[1]

    @property
    def num_ways(self) -> int:
        return self.tags.shape[2]


def make_cache(n_tiles: int, num_sets: int, num_ways: int) -> CacheArrays:
    shape = (n_tiles, num_sets, num_ways)
    return CacheArrays(
        tags=jnp.full(shape, -1, jnp.int32),
        state=jnp.zeros(shape, jnp.uint8),
        # ranks start as a strict permutation 0..W-1 per set; touch_lru
        # preserves the permutation (bump-below-rank + zero the way)
        lru=jnp.broadcast_to(
            jnp.arange(num_ways, dtype=jnp.uint8), shape
        ).copy(),
    )


def _rows(cache: CacheArrays, line: jax.Array):
    """Gather each lane's set row: ([T,W] tags, [T,W] state, [T,W] lru, set)."""
    T = cache.tags.shape[0]
    tiles = jnp.arange(T, dtype=jnp.int32)
    sets = (line % cache.num_sets).astype(jnp.int32)
    return (
        cache.tags[tiles, sets],
        cache.state[tiles, sets],
        cache.lru[tiles, sets],
        tiles,
        sets,
    )


def lookup(cache: CacheArrays, line: jax.Array):
    """Per-lane lookup: (hit bool[T], way int32[T], state uint8[T]).

    `Cache::getCacheLineInfo` (`cache.h:92`) vectorized: way is valid only
    where hit; state is INVALID where miss.
    """
    tag_row, st_row, _, _, _ = _rows(cache, line)
    way_hits = (tag_row == line[:, None]) & (st_row != INVALID)
    hit = way_hits.any(axis=1)
    way = jnp.argmax(way_hits, axis=1).astype(jnp.int32)
    st = jnp.where(
        hit, jnp.take_along_axis(st_row, way[:, None], axis=1)[:, 0], INVALID
    ).astype(jnp.uint8)
    return hit, way, st


def touch_lru(cache: CacheArrays, line: jax.Array, way: jax.Array,
              mask: jax.Array) -> CacheArrays:
    """Make `way` the MRU of its set where mask (LRU ranks shift up)."""
    _, _, lru_row, tiles, sets = _rows(cache, line)
    rank = jnp.take_along_axis(lru_row, way[:, None], axis=1)  # [T,1]
    bumped = lru_row + (lru_row < rank).astype(jnp.uint8)
    onehot = jnp.arange(cache.num_ways)[None, :] == way[:, None]
    new_row = jnp.where(onehot, 0, bumped).astype(jnp.uint8)
    new_row = jnp.where(mask[:, None], new_row, lru_row)
    return cache.replace(lru=cache.lru.at[tiles, sets].set(new_row))


def set_state(cache: CacheArrays, line: jax.Array, way: jax.Array,
              new_state: jax.Array, mask: jax.Array) -> CacheArrays:
    """Set the state of (line, way) where mask (`Cache::setCacheLineInfo`)."""
    tiles = jnp.arange(cache.tags.shape[0], dtype=jnp.int32)
    sets = (line % cache.num_sets).astype(jnp.int32)
    cur = cache.state[tiles, sets, way]
    val = jnp.where(mask, jnp.asarray(new_state, jnp.uint8), cur)
    return cache.replace(state=cache.state.at[tiles, sets, way].set(val))


def invalidate(cache: CacheArrays, line: jax.Array,
               mask: jax.Array) -> CacheArrays:
    """Invalidate `line` where mask & present (`Cache::invalidateCacheLine`)."""
    hit, way, _ = lookup(cache, line)
    return set_state(cache, line, way, INVALID, mask & hit)


def pick_victim(cache: CacheArrays, line: jax.Array):
    """Victim way per lane: first invalid way, else the LRU (max-rank) way.

    Returns (way int32[T], victim_valid bool[T], victim_line int32[T],
    victim_state uint8[T]).
    """
    tag_row, st_row, lru_row, _, _ = _rows(cache, line)
    inv = st_row == INVALID
    any_inv = inv.any(axis=1)
    inv_way = jnp.argmax(inv, axis=1)
    lru_way = jnp.argmax(lru_row, axis=1)
    way = jnp.where(any_inv, inv_way, lru_way).astype(jnp.int32)
    victim_valid = ~any_inv
    victim_line = jnp.take_along_axis(tag_row, way[:, None], axis=1)[:, 0]
    victim_state = jnp.take_along_axis(st_row, way[:, None], axis=1)[:, 0]
    return way, victim_valid, victim_line, victim_state


def insert_at(cache: CacheArrays, line: jax.Array, way: jax.Array,
              new_state: jax.Array, mask: jax.Array) -> CacheArrays:
    """Install `line` in `way` with `new_state` where mask, making it MRU.

    `Cache::insertCacheLine` (`cache.h:90`) minus the eviction message
    (the caller handles the victim it got from pick_victim).
    """
    tiles = jnp.arange(cache.tags.shape[0], dtype=jnp.int32)
    sets = (line % cache.num_sets).astype(jnp.int32)
    tags = cache.tags.at[tiles, sets, way].set(
        jnp.where(mask, line, cache.tags[tiles, sets, way])
    )
    state = cache.state.at[tiles, sets, way].set(
        jnp.where(mask, jnp.asarray(new_state, jnp.uint8),
                  cache.state[tiles, sets, way])
    )
    out = cache.replace(tags=tags, state=state)
    return touch_lru(out, line, way, mask)
