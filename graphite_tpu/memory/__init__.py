"""Memory subsystem: vectorized caches + directory coherence protocols.

TPU-native re-design of `common/tile/memory_subsystem/` (SURVEY §2.5):
per-tile C++ cache/directory objects exchanging heap-allocated messages
become struct-of-arrays tensors over the tile axis advanced by masked
vectorized FSM steps; the MEMORY network's per-tile queues become dense
[tile, tile] single-slot matrices (each tile has at most one outstanding
memory transaction, `l2_cache_cntlr.h` _outstanding_shmem_msg).
"""

from graphite_tpu.memory.params import MemParams
from graphite_tpu.memory.state import MemState, init_mem_state

__all__ = ["MemParams", "MemState", "init_mem_state"]
