"""Memory-subsystem state: caches, directory, protocol mailboxes, DRAM.

Layout notes (all leading axis = tile):
 - REQUEST cells live per REQUESTER lane ([T] + a target-home vector)
   because each tile has exactly one outstanding L2 miss
   (`l2_cache_cntlr.h` _outstanding_shmem_msg) — the compact analog of
   the per-address request queue in `dram_directory_cntlr.cc:59-96`;
   homes pop the earliest (time, requester) via a segment-min over the
   lanes targeting them.
 - FWD cells [sharer, home] carry INV/FLUSH/WB requests from a home's
   active transaction; a home owns its column (one transaction at a time)
   and clears it when the transaction ends, so stale messages cannot leak
   into a later transaction.
 - ACK cells [home, sharer] carry INV/FLUSH/WB replies; a sharer owns its
   cell.
 - EVICT cells [home, src] carry unsolicited evictions (INV_REP/FLUSH_REP
   from `l2_cache_cntlr.cc:75-116 insertCacheLine`); the L2 fill that would
   emit a second eviction to the same home blocks until the cell frees
   (back-pressure; homes drain one eviction per subquantum iteration).
 - The functional store is a single word-addressed array: the coherence
   protocol serializes conflicting accesses, so applying values at access
   completion preserves the observable semantics of the reference's
   in-cache data + DRAM map (`dram_cntlr.h:37`) without moving bytes
   through the mailboxes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from graphite_tpu.memory.cache_array import CacheArrays, make_cache
from graphite_tpu.memory.params import MemParams

I64 = jnp.int64

# message types (subset of `shmem_msg.h:12-30`)
MSG_NONE = 0
MSG_SH_REQ = 1
MSG_EX_REQ = 2
MSG_INV_REQ = 3
MSG_FLUSH_REQ = 4
MSG_WB_REQ = 5
MSG_INV_REP = 6
MSG_FLUSH_REP = 7
MSG_WB_REP = 8
MSG_SH_REP = 9
MSG_EX_REP = 10
MSG_NULLIFY = 11
MSG_EXCL_REP = 12   # MESI exclusive grant (`pr_l1_sh_l2_mesi`)

# directory states (`directory_state.h`)
DIR_UNCACHED = 0
DIR_SHARED = 1
DIR_MODIFIED = 2
DIR_OWNED = 3    # MOSI

# requester phases
PHASE_IDLE = 0
PHASE_WAIT_REPLY = 1

# memory components (indices into MemParams.module_domains)
MOD_CORE = 0
MOD_L1I = 1
MOD_L1D = 2
MOD_L2 = 3
MOD_DIR = 4
MOD_NET_MEM = 5


# Packed directory-entry word layout (int64[T, DS, DW]): one scatter
# per engine phase updates (tag, dstate, owner, nsharers) together —
# four separate arrays cost four dense-lowered scatters plus their
# layout-conversion copies each phase (PERF.md round-5).  The all-zero
# word IS the free entry (tag+1 = 0, owner+1 = 0 -> -1, UNCACHED, 0
# sharers), so init is plain zeros.
DIR_TAG_BITS = 34        # bits 0..33: line + 1 (0 = free)
DIR_STATE_SHIFT = 34     # bits 34..36: directory state
DIR_OWNER_SHIFT = 37     # bits 37..49: owner tile + 1
DIR_NSH_SHIFT = 50       # bits 50..62: sharer count
DIR_ID_BITS = 13         # owner/nsharers field width (tiles <= 8190)


@struct.dataclass
class DirectoryArrays:
    """Per-home-slice directory cache (`cache/directory_cache.h:20-68`).

    Kept as structured [T, DS, DW(, SW)] arrays: a flat 2-D repack
    (entry-major, large minor dim) was built and measured 1.6x SLOWER —
    the computed-column gathers lower worse than structured indexing,
    and the whole-array copies it targeted barely moved (PERF.md
    round-3 findings)."""

    # packed (tag, dstate, owner, nsharers) word per entry — layout above
    entry: jax.Array     # int64[T, DS, DW]
    # full-map bitvector, stored set-row-major [T, DS, DW*SW] (way w's
    # words at [.., w*SW:(w+1)*SW]): a [T, DS, DW, SW] layout pads SW up
    # to the 128-lane tile on TPU (4x physical at 1024 tiles — PERF.md
    # "array padding"), and the set-row form matches how every phase
    # reads it anyway
    sharers: jax.Array   # uint32[T, DS, DW*SW]
    # sharers write-staging rows, PER HOME LANE (MemParams.dir_stage_cap
    # > 0; see engine._stage_put / dir_stage_flush).  Append-only: a put
    # lands at the lane's cursor `sn`; keys may repeat within a row —
    # reads take the latest match and the flush applies only each key's
    # last slot (round 12; every directory write is home-lane-local, so
    # the rows are block-local under shard_map).  None when staging is
    # disabled.
    skey: "object" = None  # int32[T, c] set*DW + way, -1 = empty
    sval: "object" = None  # uint32[T, c, SW] staged sharer words
    sn: "object" = None    # int32[T] slots appended since last flush


@struct.dataclass
class TxnState:
    """One active directory transaction per home tile.

    The dense form of the front-of-queue request being serviced
    (`dram_directory_cntlr.cc:44-130`); `saved_*` holds the original
    request while a NULLIFY (directory-entry replacement,
    `processDirectoryEntryAllocationReq`) runs first.
    """

    active: jax.Array        # bool[T]
    mtype: jax.Array         # uint8[T] MSG_SH_REQ/MSG_EX_REQ/MSG_NULLIFY
    line: jax.Array          # int32[T]
    requester: jax.Array     # int32[T]
    time_ps: jax.Array       # int64[T] running ShmemPerfModel clock
    pending: jax.Array       # uint32[T, SW] outstanding INV/FLUSH/WB acks
    data_cached: jax.Array   # bool[T] reply data arrived via FLUSH/WB_REP
    saved_valid: jax.Array   # bool[T]
    saved_type: jax.Array    # uint8[T]
    saved_line: jax.Array    # int32[T]
    saved_requester: jax.Array  # int32[T]
    saved_time_ps: jax.Array    # int64[T]
    last_line: jax.Array     # int32[T]  same-address serialization floor
    last_done_ps: jax.Array  # int64[T]
    # one-entry flushed-data buffer per home (`_cached_data_list` analog):
    # a FLUSH_REP eviction parks its line here; a later request for the
    # same line is served without a DRAM read
    cdata_line: jax.Array    # int32[T]
    cdata_valid: jax.Array   # bool[T]


@struct.dataclass
class MemMailboxes:
    # The request "matrix" is stored per REQUESTER lane: each tile has
    # exactly one outstanding L2 (shared-L2: L1) miss (`l2_cache_cntlr.h`
    # _outstanding_shmem_msg — the requester sits in PHASE_WAIT_REPLY
    # until its reply fills), so the writer set of the old [T, T] form's
    # column was provably one tile and the [T, T] matrix carried T-1
    # dead cells per lane.  Round 12 compacts it to [T] lanes +
    # `req_home`; the home-side pop is a segment-min over requesters
    # with the SAME (time, requester) key order as the old row scan
    # (engine._req_earliest), so the compaction is bit-exact.
    req_type: jax.Array    # uint8[T(requester)]
    req_home: jax.Array    # int32[T] target home of the live request
    req_line: jax.Array    # int32[T]
    req_time: jax.Array    # int64[T]
    evict_type: jax.Array  # uint8[T(home), T(src)]
    evict_line: jax.Array  # int32[T, T]
    evict_time: jax.Array  # int64[T, T]
    fwd_type: jax.Array    # uint8[T(sharer), T(home)]
    fwd_line: jax.Array    # int32[T, T]
    fwd_time: jax.Array    # int64[T, T]
    ack_type: jax.Array    # uint8[T(home), T(sharer)]
    ack_line: jax.Array    # int32[T, T]
    ack_time: jax.Array    # int64[T, T]
    rep_type: jax.Array    # uint8[T(requester)]
    rep_time: jax.Array    # int64[T]


@struct.dataclass
class RequesterState:
    phase: jax.Array       # int32[T] PHASE_*
    slot: jax.Array        # int32[T] current memory slot of the record
    acc_ps: jax.Array      # int64[T] accumulated memory latency this record
    clock_ps: jax.Array    # int64[T] running shmem clock of current slot
    line: jax.Array        # int32[T] line being fetched
    is_write: jax.Array    # bool[T]
    component: jax.Array   # uint8[T] MOD_L1I or MOD_L1D
    instr_buf: jax.Array   # int32[T] instruction-buffer line (`core.cc:207-219`)
    # per-slot latency of the current record [icache, mem0, mem1] — the
    # iocoom model needs per-operand latencies (`DynamicMemoryInfo::_latency`)
    slot_lat_ps: jax.Array  # int64[T, 3]


@struct.dataclass
class MemCounters:
    l1i_hits: jax.Array        # int64[T]
    l1i_misses: jax.Array
    l1d_read_hits: jax.Array
    l1d_read_misses: jax.Array
    l1d_write_hits: jax.Array
    l1d_write_misses: jax.Array
    l2_hits: jax.Array
    l2_misses: jax.Array
    evictions: jax.Array
    invalidations: jax.Array   # INV_REQs served with a valid line
    dir_accesses: jax.Array
    dir_broadcasts: jax.Array  # ackwise/limited_broadcast INV sweeps sent to all tiles
    dram_reads: jax.Array
    dram_writes: jax.Array
    dram_total_lat_ps: jax.Array
    # L2 miss-type classification (`cache.h:45-49` COLD/CAPACITY/SHARING;
    # populated when `[l2_cache/<type>] track_miss_types` — private-L2
    # engines only)
    l2_cold_misses: jax.Array
    l2_capacity_misses: jax.Array
    l2_sharing_misses: jax.Array
    # L2 cache-line utilization (`cache/cache_line_utilization.h`; MOSI
    # l2_cache_cntlr eviction/invalidation hooks) — populated when
    # `[l2_cache/<type>] track_cache_line_utilization`:
    # histogram of per-line TOTAL accesses classified when the line
    # leaves the L2 (buckets: 0, 1, 2-3, 4-7, ..., >=64), plus the
    # classified lines' accumulated read/write access counts
    line_util_hist: jax.Array    # int64[T, 8]
    line_util_reads: jax.Array   # int64[T]
    line_util_writes: jax.Array  # int64[T]


@struct.dataclass
class MemState:
    l1i: CacheArrays
    l1d: CacheArrays
    l2: CacheArrays
    l2_cloc: jax.Array       # uint8[T, S2, W2] which L1 holds it (0/MOD_L1I/MOD_L1D)
    # per-L2-line utilization counters when track_cache_line_utilization:
    # uint32[T, S2, W2], low 16 bits = read accesses, high 16 = writes
    # (saturating); None when tracking is off
    l2_util: "object"
    directory: DirectoryArrays
    txn: TxnState
    mail: MemMailboxes
    req: RequesterState
    counters: MemCounters
    func_mem: jax.Array      # uint32[mem_words] functional word store
    func_errors: jax.Array   # int64[] failed FLAG_CHECK loads
    # bool[] — any protocol state outstanding (messages, transactions,
    # waiting requesters); False lets the step skip the engine entirely
    live: jax.Array
    # int64[6] — per-phase lax.cond skip counts under phase gating
    # (MemParams.phase_gate; engine.PHASE_NAMES order).  A whole-engine
    # mem_gate skip counts every phase.  Replicated control state under
    # shard_map (deterministic from replicated predicates).
    phase_skips: jax.Array = None
    # per-port queue state of the MEMORY NoC when `[network] memory =
    # emesh_hop_by_hop` (models/network_hop_by_hop.NocState), else None
    noc: "object" = None
    # L2 miss-type tracking bitmaps, uint32[T, 3, MT_WORDS] (rows:
    # fetched / evicted / invalidated — the reference's three address
    # sets, `cache.cc getMissType`, hashed to MT_BITS buckets per tile;
    # bucket collisions are a documented approximation shared with the
    # oracle).  None when track_miss_types is off.
    mt: "object" = None


# the engines' protocol phase count (engine.PHASE_NAMES /
# engine_shl2.SHL2_PHASE_NAMES index the skip vector)
N_PHASES = 6


def init_mem_common(mp: MemParams) -> dict:
    """The protocol-independent state pieces (L1/L2 arrays, mailboxes,
    requester machinery, counters, functional memory) — shared between the
    private-L2 and shared-L2 engines."""
    T = mp.n_tiles

    def zi64():
        return jnp.zeros(T, I64)

    mail = MemMailboxes(
        req_type=jnp.zeros(T, jnp.uint8),
        req_home=jnp.zeros(T, jnp.int32),
        req_line=jnp.zeros(T, jnp.int32),
        req_time=jnp.zeros(T, I64),
        evict_type=jnp.zeros((T, T), jnp.uint8),
        evict_line=jnp.zeros((T, T), jnp.int32),
        evict_time=jnp.zeros((T, T), I64),
        fwd_type=jnp.zeros((T, T), jnp.uint8),
        fwd_line=jnp.zeros((T, T), jnp.int32),
        fwd_time=jnp.zeros((T, T), I64),
        ack_type=jnp.zeros((T, T), jnp.uint8),
        ack_line=jnp.zeros((T, T), jnp.int32),
        ack_time=jnp.zeros((T, T), I64),
        rep_type=jnp.zeros(T, jnp.uint8),
        rep_time=zi64(),
    )
    req = RequesterState(
        phase=jnp.zeros(T, jnp.int32),
        slot=jnp.zeros(T, jnp.int32),
        acc_ps=zi64(),
        clock_ps=zi64(),
        line=jnp.zeros(T, jnp.int32),
        is_write=jnp.zeros(T, jnp.bool_),
        component=jnp.zeros(T, jnp.uint8),
        instr_buf=jnp.full(T, -1, jnp.int32),
        slot_lat_ps=jnp.zeros((T, 3), jnp.int64),
    )
    counters = MemCounters(
        l1i_hits=zi64(), l1i_misses=zi64(),
        l1d_read_hits=zi64(), l1d_read_misses=zi64(),
        l1d_write_hits=zi64(), l1d_write_misses=zi64(),
        l2_hits=zi64(), l2_misses=zi64(),
        evictions=zi64(), invalidations=zi64(),
        dir_accesses=zi64(), dir_broadcasts=zi64(),
        dram_reads=zi64(), dram_writes=zi64(),
        dram_total_lat_ps=zi64(),
        l2_cold_misses=zi64(), l2_capacity_misses=zi64(),
        l2_sharing_misses=zi64(),
        line_util_hist=jnp.zeros((T, 8), I64),
        line_util_reads=zi64(), line_util_writes=zi64(),
    )
    return dict(
        l1i=make_cache(T, mp.l1i.num_sets, mp.l1i.num_ways),
        l1d=make_cache(T, mp.l1d.num_sets, mp.l1d.num_ways),
        l2=make_cache(T, mp.l2.num_sets, mp.l2.num_ways),
        mail=mail,
        req=req,
        counters=counters,
        # +1 scratch word absorbing masked-off dummy writes
        func_mem=jnp.zeros(max(mp.func_mem_words, 1) + 1, jnp.uint32),
        func_errors=jnp.zeros((), I64),
        phase_skips=jnp.zeros(N_PHASES, I64),
    )


# miss-type tracking hash space: 2^16 buckets = 2048 uint32 words/set
MT_BITS = 1 << 16
MT_WORDS = MT_BITS // 32
MT_FETCHED, MT_EVICTED, MT_INVALIDATED = 0, 1, 2


def init_mem_state(mp: MemParams) -> MemState:
    T = mp.n_tiles
    SW = mp.sharer_words
    DS, DW = mp.dir_sets, mp.dir_ways

    def zi64():
        return jnp.zeros(T, I64)

    directory = DirectoryArrays(
        entry=jnp.zeros((T, DS, DW), I64),
        sharers=jnp.zeros((T, DS, DW * SW), jnp.uint32),
        skey=(jnp.full((T, mp.dir_stage_cap), -1, jnp.int32)
              if mp.dir_stage_cap else None),
        sval=(jnp.zeros((T, mp.dir_stage_cap, SW), jnp.uint32)
              if mp.dir_stage_cap else None),
        sn=(jnp.zeros(T, jnp.int32) if mp.dir_stage_cap else None),
    )
    txn = TxnState(
        active=jnp.zeros(T, jnp.bool_),
        mtype=jnp.zeros(T, jnp.uint8),
        line=jnp.zeros(T, jnp.int32),
        requester=jnp.zeros(T, jnp.int32),
        time_ps=zi64(),
        pending=jnp.zeros((T, SW), jnp.uint32),
        data_cached=jnp.zeros(T, jnp.bool_),
        saved_valid=jnp.zeros(T, jnp.bool_),
        saved_type=jnp.zeros(T, jnp.uint8),
        saved_line=jnp.zeros(T, jnp.int32),
        saved_requester=jnp.zeros(T, jnp.int32),
        saved_time_ps=zi64(),
        last_line=jnp.full(T, -1, jnp.int32),
        last_done_ps=zi64(),
        cdata_line=jnp.full(T, -1, jnp.int32),
        cdata_valid=jnp.zeros(T, jnp.bool_),
    )
    mt = (jnp.zeros((T, 3, MT_WORDS), jnp.uint32)
          if mp.l2.track_miss_types else None)
    return MemState(
        l2_cloc=jnp.zeros((T, mp.l2.num_sets, mp.l2.num_ways), jnp.uint8),
        l2_util=(jnp.zeros((T, mp.l2.num_sets, mp.l2.num_ways), jnp.uint32)
                 if mp.l2.track_line_utilization else None),
        directory=directory,
        txn=txn,
        live=jnp.zeros((), jnp.bool_),
        mt=mt,
        **init_mem_common(mp),
    )
