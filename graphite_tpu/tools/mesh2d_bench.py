"""BENCH_MESH2D companion: solo vs 1D vs 2D campaign layouts.

Measures, at one fixed geometry, the warm per-iteration wall cost and
the PER-DEVICE residency bill of the three campaign layouts (solo vmap,
1D batch-axis shard_map, the round-18 2D batch x tile mesh), plus the
admission outcome for a sim whose per-sim bill exceeds one device's
budget: a 1-device admission controller rejects it, a multi-device one
admits it as a 2D class.  Emits ONE JSON line (the bench.py contract);
bench.py merges the fields into the round artifact, running this module
in-process when >= 4 devices are visible and as a forced-4-device CPU
subprocess otherwise.

Usage: python -m graphite_tpu.tools.mesh2d_bench
Needs >= 4 devices (force on CPU with
XLA_FLAGS=--xla_force_host_platform_device_count=4).
"""

from __future__ import annotations

import json
import os
import sys
import time


def measure_mesh2d() -> dict:
    import jax

    import graphite_tpu  # noqa: F401  (x64)
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.analysis.cost import ResidencyBudgetError
    from graphite_tpu.serve.admission import (
        AdmissionController, measure_job,
    )
    from graphite_tpu.serve.job import Job
    from graphite_tpu.sweep import SweepRunner
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace import synthetic

    n_dev = len(jax.devices())
    if n_dev < 4:
        return {"mesh2d_error": f"needs >= 4 devices, have {n_dev}"}
    tiles = int(os.environ.get("BENCH_MESH2D_TILES", "16"))
    # B = the device count so every layout uses the whole platform
    # (solo runs them all on one device — that contrast IS the point)
    B = n_dev
    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier")))
    traces = [
        synthetic.memory_stress_trace(
            tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=s)
        for s in range(1, B + 1)
    ]
    # gating forced off uniformly: the three layouts then lower the
    # same per-sim engine shape and the wall contrast is the layout's
    gate_kw = dict(phase_gate=False, mem_gate_bytes=0)

    def timed(layout):
        r = SweepRunner(sc, traces, layout=layout, **gate_kw)
        r.run(max_quanta=200_000)            # compile + first run
        t0 = time.perf_counter()
        out = r.run(max_quanta=200_000)      # warm steady state
        wall = time.perf_counter() - t0
        iters = max(int(out.n_iterations.sum()), 1)
        return (round(1000 * wall / iters, 4),
                int(r.device_breakdown()["total"]), out.layout)

    ms_solo, dev_solo, _ = timed("solo")
    ms_1d, dev_1d, name_1d = timed("batch")
    ms_2d, dev_2d, name_2d = timed((B // 2, 2))

    # admission outcome: a sim too big for ONE device's budget
    job = Job("mesh2d-big", sc, traces[0], seed=1)
    m = measure_job(job, mailbox_depth=8, pad_length=64)
    budget = (m.per_sim_total + m.device_block(2)["total"]) // 2
    try:
        AdmissionController(hbm_budget_bytes=budget, batch_size=4,
                            n_devices=1).admit(job)
        adm_1dev = "accepted"  # should not happen — the bench flags it
    except ResidencyBudgetError:
        adm_1dev = "rejected"
    cls, _ = AdmissionController(
        hbm_budget_bytes=budget, batch_size=4,
        n_devices=n_dev).admit(job)
    adm_nd = (f"accepted-2d(b={cls.batch_shards},t={cls.tile_shards})"
              if cls.tile_shards > 1 else "accepted-1d")
    return {
        "mesh2d_devices": n_dev,
        "mesh2d_tiles": tiles,
        "mesh2d_batch": B,
        "mesh2d_ms_per_iter_solo": ms_solo,
        "mesh2d_ms_per_iter_1d": ms_1d,
        "mesh2d_ms_per_iter_2d": ms_2d,
        "mesh2d_bytes_per_device_solo": dev_solo,
        "mesh2d_bytes_per_device_1d": dev_1d,
        "mesh2d_bytes_per_device_2d": dev_2d,
        "mesh2d_layout_1d": name_1d,
        "mesh2d_layout_2d": name_2d,
        "mesh2d_admission_budget": int(budget),
        "mesh2d_big_sim_bytes": int(m.per_sim_total),
        "mesh2d_admission_1dev": adm_1dev,
        "mesh2d_admission": adm_nd,
    }


def main() -> int:
    out = measure_mesh2d()
    print(json.dumps(out))
    return 1 if "mesh2d_error" in out else 0


if __name__ == "__main__":
    sys.exit(main())
