"""Capture REAL program executions as traces; calibrate the skeletons.

The reusable harness behind `capture_fft.py` (VERDICT round-3/4 ask:
generalize the one-off FFT capture), plus real SPLASH-2-shaped
implementations of RADIX and LU recorded the same way.  These are not
synthetic generators: each app EXECUTES its algorithm — real data, true
addresses — under the live-recording Carbon API (the reference analog is
capturing a real binary under Pin, `pin/instruction_modeling.cc`).
Every arithmetic op is recorded as an instruction record and every
element access goes through `carbon_load`/`carbon_store`, so a replay
drives the full cache/coherence stack with the program's actual sharing
pattern, and `measured_mix` reports the real instruction mix — the
calibration source for the `trace/benchmarks.py` skeletons.

Validation (both apps, like the FFT capture):
 - functionally on replay: barrier-separated single-writer reads carry
   FLAG_CHECK — the coherence engine must reproduce every loaded value
   (func_errors == 0);
 - numerically at capture: radix output must equal numpy's sort; the
   LU factors must reconstruct the input matrix within fixed-point
   tolerance.

Usage:  python -m graphite_tpu.tools.capture {radix|lu} [out.npz]
"""

from __future__ import annotations

import numpy as np

FX = 16  # 16.16 fixed point (LU)


def _w32(v: int) -> int:
    return ((v & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000


# --------------------------------------------------------------------------
# shared harness


def measured_mix(batch) -> dict:
    """Instruction/memory mix of a captured trace, by record type."""
    from graphite_tpu.trace.schema import (
        FLAG_MEM0_VALID, FLAG_MEM0_WRITE, Op,
    )

    op = batch.op
    flags = batch.flags
    mem = (flags & FLAG_MEM0_VALID) != 0
    return {
        "records": int((op != int(Op.NOP)).sum()),
        "fmul": int((op == int(Op.FMUL)).sum()),
        "falu": int((op == int(Op.FALU)).sum()),
        "fdiv": int((op == int(Op.FDIV)).sum()),
        "ialu": int((op == int(Op.IALU)).sum()),
        "loads": int((mem & ((flags & FLAG_MEM0_WRITE) == 0)).sum()),
        "stores": int((mem & ((flags & FLAG_MEM0_WRITE) != 0)).sum()),
    }


def replay_report(batch, n_tiles: int, out_path: str | None = None) -> dict:
    """Save (optionally), reload, and replay a captured batch through the
    full memory engine; report counters + the measured mix.  FLAG_CHECK
    loads make the replay a functional test of the coherence stack."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace.io import load_trace_npz, save_trace_npz

    if out_path:
        save_trace_npz(out_path, batch)
        batch = load_trace_npz(out_path)
    sc = SimConfig(ConfigFile.from_string(config_text(
        n_tiles, shared_mem=True, clock_scheme="lax")))
    res = Simulator(sc, batch).run()
    return {
        "npz": out_path,
        "func_errors": res.func_errors,
        "completion_ns": res.completion_time_ps // 1000,
        "instructions": res.total_instructions,
        "l2_misses": int(np.asarray(res.mem_counters["l2_misses"]).sum()),
        "mix": measured_mix(batch),
    }


def make_app(n_tiles: int):
    """A CarbonApp over the standard capture config."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.frontend import carbon_api as capi
    from graphite_tpu.tools._template import config_text

    sc = SimConfig(ConfigFile.from_string(config_text(
        n_tiles, shared_mem=True, clock_scheme="lax")))
    return capi.CarbonApp(sc)


def run_threads(app, worker, n_tiles: int, *args):
    """main_fn boilerplate: spawn `worker(tile, barrier, *args)` on every
    tile, join.  Returns the recorded TraceBatch."""
    from graphite_tpu.frontend import carbon_api as capi

    def main_fn():
        bar = capi.CarbonBarrier(n_tiles)
        tids = [capi.carbon_spawn_thread(worker, t, bar, *args)
                for t in range(1, n_tiles)]
        worker(0, bar, *args)
        for tid in tids:
            capi.carbon_join_thread(tid)

    return app.start(main_fn)


# --------------------------------------------------------------------------
# RADIX: real parallel LSD radix sort (SPLASH-2 `kernels/radix/radix.C`:
# per digit pass — local histogram, global rank bases, permutation).


def run_radix_app(n_tiles: int = 4, keys_per_tile: int = 256,
                  radix: int = 16, n_digits: int = 2, seed: int = 17):
    """Execute a parallel radix sort under the recording API.

    Returns (TraceBatch, input_keys, output_keys).  Keys are drawn
    < radix**n_digits so n_digits passes sort completely; the sort is
    the textbook stable counting-sort-per-digit of the SPLASH-2 kernel
    (local histogram -> cross-tile rank bases -> permutation), with the
    rank arrays and the key buffers truly shared (rank reads and the
    permutation's scattered writes cross tile-partition boundaries)."""
    from graphite_tpu.frontend import carbon_api as capi

    T = n_tiles
    N = T * keys_per_tile
    bits = radix.bit_length() - 1
    assert 1 << bits == radix
    # region layout bounds (aliasing window documented below): keys must
    # fit the 64 KB per-array slots, histograms/ranks their 32/16 KB
    assert 4 * N <= 0x10000, "key arrays overrun the region layout"
    # RANK has the narrowest slot (16 KB, 0x128000..0x12C000)
    assert 4 * T * radix <= 0x4000, "hist/rank overrun the region layout"
    # all regions inside one 256 KB window: the replay's functional
    # memory maps addr>>2 modulo general/functional_memory_kb*256 words
    # (memory/params.py:440), so wider spacing would alias
    A, B = 0x100000, 0x110000          # double-buffered key arrays
    HIST = 0x120000                    # hist[t][d] per-tile histograms
    RANK = 0x128000                    # rank[t][d] global write bases
    TOT = 0x12C000                     # digit totals + prefix

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, radix ** n_digits, size=N).astype(np.int64)

    def worker(tile, bar):
        lo, hi = tile * keys_per_tile, (tile + 1) * keys_per_tile
        # setup: each tile stores its own slice of the input
        for i in range(lo, hi):
            capi.carbon_store(A + 4 * i, int(keys[i]))
        bar.wait()
        for p in range(n_digits):
            src = A if p % 2 == 0 else B
            dst = B if p % 2 == 0 else A
            shift = p * bits
            # ---- phase 1: local histogram (private accumulation, one
            # shared store per digit — radix.C keeps density private)
            hist = [0] * radix
            for i in range(lo, hi):
                k = capi.carbon_load(src + 4 * i, check=True)
                capi.carbon_instr()          # digit extract (shift+mask)
                hist[(k >> shift) & (radix - 1)] += 1
            for d in range(radix):
                capi.carbon_instr()          # store index arithmetic
                capi.carbon_store(HIST + 4 * (tile * radix + d), hist[d])
            bar.wait()
            # ---- phase 2: rank bases.  Digits distributed round-robin:
            # each owner sums its digits across ALL tiles' histograms
            # (true read-sharing) and writes per-(tile, digit) bases.
            for d in range(tile, radix, T):
                run = 0
                for t2 in range(T):
                    capi.carbon_instr()      # index arithmetic
                    h = capi.carbon_load(
                        HIST + 4 * (t2 * radix + d), check=True)
                    capi.carbon_store(RANK + 4 * (t2 * radix + d), run)
                    run += h
                capi.carbon_store(TOT + 4 * d, run)
            bar.wait()
            # digit-total exclusive prefix (tile 0 — small serial tail;
            # radix.C uses a prefix tree, same O(radix) work overall)
            if tile == 0:
                run = 0
                for d in range(radix):
                    tot = capi.carbon_load(TOT + 4 * d, check=True)
                    capi.carbon_instr()      # accumulate
                    capi.carbon_store(TOT + 4 * (radix + d), run)
                    run += tot
            bar.wait()
            # ---- phase 3: permutation — stable scatter of own keys to
            # their globally ranked positions (all-to-all true writes)
            base = {}
            for i in range(lo, hi):
                k = capi.carbon_load(src + 4 * i, check=True)
                capi.carbon_instr()          # digit extract
                d = (k >> shift) & (radix - 1)
                if d not in base:
                    pre = capi.carbon_load(TOT + 4 * (radix + d),
                                           check=True)
                    rb = capi.carbon_load(RANK + 4 * (tile * radix + d),
                                          check=True)
                    base[d] = pre + rb
                capi.carbon_instr()          # dest address arithmetic
                capi.carbon_store(dst + 4 * base[d], k)
                base[d] += 1
            bar.wait()

    app = make_app(T)
    batch = run_threads(app, worker, T)
    out_base = B if n_digits % 2 == 1 else A
    out = np.array([_w32(app._memory.get(out_base + 4 * i, 0))
                    for i in range(N)], np.int64)
    return batch, keys, out


# --------------------------------------------------------------------------
# LU: real blocked dense LU factorization, no pivoting (SPLASH-2
# `kernels/lu/lu.C`: per step — diagonal factor, perimeter solves,
# interior update; block-cyclic ownership) in 16.16 fixed point.


def run_lu_app(n_tiles: int = 4, n: int = 32, block: int = 8,
               seed: int = 23):
    """Execute a blocked LU factorization under the recording API.

    Returns (TraceBatch, input_matrix_float, lu_in_place_float).
    Diagonally dominant integer input keeps the no-pivoting
    factorization exact-friendly in fixed point."""
    from graphite_tpu.frontend import carbon_api as capi

    T = n_tiles
    NB = n // block
    assert NB * block == n
    ABASE = 0x400000
    # single region: must fit the 256 KB functional-memory window
    assert 4 * n * n <= 0x40000, "matrix overruns the functional window"

    def addr(i, j):
        return ABASE + 4 * (i * n + j)

    rng = np.random.default_rng(seed)
    a0 = rng.integers(-8, 9, size=(n, n)).astype(np.int64)
    np.fill_diagonal(a0, a0.diagonal() + 16 * n)   # dominance: |L| < 1
    afx = a0 << FX

    # 2-D block-cyclic ownership over a ~sqrt(T) grid (lu.C's
    # proc-grid scatter) — keeps rows AND columns spread across tiles
    pr = max(1, int(np.sqrt(T)))
    pc = max(1, T // pr)

    def owner(bi, bj):
        return (bi % pr) * pc + (bj % pc)

    def load_block(bi, bj, check):
        """Load a block's elements (true addresses) into a local dict."""
        blk = {}
        r0, c0 = bi * block, bj * block
        for r in range(block):
            for c in range(block):
                blk[(r, c)] = _w32(capi.carbon_load(
                    addr(r0 + r, c0 + c), check=check))
        return blk

    def store_block(bi, bj, blk):
        r0, c0 = bi * block, bj * block
        for r in range(block):
            for c in range(block):
                capi.carbon_store(addr(r0 + r, c0 + c),
                                  _w32(blk[(r, c)]))

    def fxmul(a, b):
        capi.carbon_instr(capi.Op.FMUL)
        return (a * b) >> FX

    def fxdiv_recip(d):
        capi.carbon_instr(capi.Op.FDIV)
        return ((1 << (2 * FX)) + (d // 2)) // d if d else 0

    def worker(tile, bar):
        # setup: block owners store their blocks of the input
        for bi in range(NB):
            for bj in range(NB):
                if owner(bi, bj) == tile:
                    blk = {(r, c): int(afx[bi * block + r, bj * block + c])
                           for r in range(block) for c in range(block)}
                    store_block(bi, bj, blk)
        bar.wait()
        for k in range(NB):
            # ---- diagonal factor (lu.C lu0): in-place LU of block (k,k)
            if owner(k, k) == tile:
                dk = load_block(k, k, check=True)
                for j in range(block):
                    recip = fxdiv_recip(dk[(j, j)])
                    for i in range(j + 1, block):
                        dk[(i, j)] = fxmul(dk[(i, j)], recip)
                        for m in range(j + 1, block):
                            capi.carbon_instr(capi.Op.FALU)
                            dk[(i, m)] -= fxmul(dk[(i, j)], dk[(j, m)])
                store_block(k, k, dk)
            bar.wait()
            # ---- perimeter (lu.C bdiv/bmodd): row blocks (k, j) get
            # L(k,k)^-1 applied; column blocks (i, k) get U(k,k)^-1.
            # Every perimeter owner RE-LOADS the diagonal block — the
            # read-sharing the shared-memory original exhibits.
            prow = [j for j in range(k + 1, NB) if owner(k, j) == tile]
            pcol = [i for i in range(k + 1, NB) if owner(i, k) == tile]
            if prow or pcol:
                dk = load_block(k, k, check=True)
            for j in prow:
                blk = load_block(k, j, check=True)
                for c in range(block):
                    for r in range(block):
                        for q in range(r):
                            capi.carbon_instr(capi.Op.FALU)
                            blk[(r, c)] -= fxmul(dk[(r, q)], blk[(q, c)])
                store_block(k, j, blk)
            for i in pcol:
                blk = load_block(i, k, check=True)
                recips = [fxdiv_recip(dk[(q, q)]) for q in range(block)]
                for r in range(block):
                    for c in range(block):
                        for q in range(c):
                            capi.carbon_instr(capi.Op.FALU)
                            blk[(r, c)] -= fxmul(blk[(r, q)], dk[(q, c)])
                        blk[(r, c)] = fxmul(blk[(r, c)], recips[c])
                store_block(i, k, blk)
            bar.wait()
            # ---- interior (lu.C bmod): A(i,j) -= A(i,k) @ A(k,j)
            mine = [(i, j) for i in range(k + 1, NB)
                    for j in range(k + 1, NB) if owner(i, j) == tile]
            for (i, j) in mine:
                li = load_block(i, k, check=True)
                uj = load_block(k, j, check=True)
                blk = load_block(i, j, check=True)
                for r in range(block):
                    for c in range(block):
                        for q in range(block):
                            capi.carbon_instr(capi.Op.FALU)
                            blk[(r, c)] -= fxmul(li[(r, q)], uj[(q, c)])
                store_block(i, j, blk)
            bar.wait()

    app = make_app(T)
    batch = run_threads(app, worker, T)
    lu = np.empty((n, n), np.float64)
    for i in range(n):
        for j in range(n):
            lu[i, j] = _w32(app._memory.get(addr(i, j), 0)) / (1 << FX)
    return batch, a0.astype(np.float64), lu


def verify_lu(a0: np.ndarray, lu: np.ndarray) -> float:
    """Max relative reconstruction error |L@U - A| / |A|."""
    n = a0.shape[0]
    L = np.tril(lu, -1) + np.eye(n)
    U = np.triu(lu)
    scale = max(1.0, float(np.abs(a0).max()))
    return float(np.abs(L @ U - a0).max() / scale)


# --------------------------------------------------------------------------
# CLI


def main(which: str, out_path: str | None = None) -> dict:
    if which == "radix":
        batch, keys, out = run_radix_app()
        sorted_ok = bool((np.sort(keys) == out).all())
        report = replay_report(batch, 4, out_path)
        n_keys = len(keys)
        report.update(
            sorted_ok=sorted_ok,
            records_per_key_per_pass=report["mix"]["records"] / n_keys / 2,
            loads_per_key_per_pass=report["mix"]["loads"] / n_keys / 2,
        )
        assert sorted_ok, "captured radix sort produced a wrong order"
    elif which == "lu":
        batch, a0, lu = run_lu_app()
        err = verify_lu(a0, lu)
        report = replay_report(batch, 4, out_path)
        b3 = 8 ** 3
        report.update(numeric_max_rel_err=err,
                      fp_per_b3=(report["mix"]["fmul"]
                                 + report["mix"]["falu"]
                                 + report["mix"]["fdiv"]) / b3)
        assert err < 5e-2, f"captured LU reconstruction error {err}"
    else:
        raise SystemExit(f"unknown app {which!r} (radix|lu)")
    assert report["func_errors"] == 0, "replay FLAG_CHECK mismatches"
    return report


if __name__ == "__main__":
    import json
    import sys

    which = sys.argv[1] if len(sys.argv) > 1 else "radix"
    out = sys.argv[2] if len(sys.argv) > 2 else None
    print(json.dumps(main(which, out), indent=1))
