"""The north-star-shaped coherence measurement: 1024-tile SPLASH-2 FFT
with the FULL memory engine (MSI, per-line true addresses) — the honest
companion VERDICT round 3 asked for (`BENCH_r{N}.json` field
`coherence_1024_instr_per_s`).

Run as a subprocess (bench.py does) because the largest configs can kill
the TPU worker; bench.py walks a fidelity ladder — full directory +
hop-by-hop memory NoC, then full directory + hop-counter, then a reduced
directory — and records the first rung that completes, tagged with its
fidelity, so the recorded number is always real.

Round-5 status: the round-4 "deterministic TPU kernel fault" on
1024-tile x full-directory x SEND-carrying traces no longer reproduces
under the staged+packed directory program — the FFT rung completes at
FULL directory with the hop-counter NoC.  The remaining failing
combination is hbh NoC + full directory + SEND traces (worker crash;
memstress+hbh+full and fft+hbh+quarter both run, so it is the combined
footprint, not the hbh code) — hence the ladder's second rung is the
one that records today.

Usage: python -m graphite_tpu.tools.coherence1024 [--net hbh|hopctr]
       [--dir full|small] [--workload fft|memstress] [--points N]
Prints ONE JSON line: {"config": ..., "instr": N, "wall_s": S, "rate": R}.
"""

from __future__ import annotations

import argparse
import json
import time


def run_one(net: str, dir_size: str, points: int,
            workload: str = "fft") -> dict:
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace.benchmarks import fft_trace

    # the reference's default lax_barrier scheme: at this scale the
    # Simulator auto-selects the host-driven barrier loop (barrier_host)
    # since the single-region lax_barrier program crashes the tunnel's
    # remote-compile helper (PERF.md)
    text = config_text(
        1024, shared_mem=True, clock_scheme="lax_barrier",
        network="emesh_hop_by_hop" if net == "hbh" else "emesh_hop_counter")
    if dir_size == "small":
        # quarter-size directory: 0.73 GB of sharer state instead of the
        # auto-sized 2.4 GB — the rung that fits alongside XLA's
        # scatter-staging copies today
        text += "\n[dram_directory]\ntotal_entries = 4096\n" \
                "associativity = 16\n"
    sc = SimConfig(ConfigFile.from_string(text))
    if workload == "memstress":
        from graphite_tpu.trace import synthetic

        batch = synthetic.memory_stress_trace(
            1024, n_accesses=4 * points, working_set_bytes=1 << 15,
            write_fraction=0.4, shared_fraction=0.5, seed=7)
    else:
        batch = fft_trace(1024, points_per_tile=points, use_memory=True)
    # donate the input state: halves the big-state HBM residency
    sim = Simulator(sc, batch, donate=True)
    t0 = time.perf_counter()
    res = sim.run()
    wall = time.perf_counter() - t0
    # warm second instance for the honest steady rate: adopt the first
    # instance's compiled runner so the timed region excludes
    # retrace/recompile (a fresh jit wrapper would re-trace)
    sim2 = Simulator(sc, batch, donate=True)
    sim2.adopt_runner(sim)
    # free the donor's post-run state before the timed run — at 1024
    # tiles it holds the full directory alongside sim2's donated state
    sim.state = None
    t1 = time.perf_counter()
    res = sim2.run()
    wall = time.perf_counter() - t1
    return {
        "config": f"1024t_{workload}_msi_{net}_{dir_size}dir",
        "instr": res.total_instructions,
        "wall_s": round(wall, 2),
        "rate": round(res.total_instructions / wall),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--net", choices=("hbh", "hopctr"), default="hbh")
    ap.add_argument("--dir", dest="dir_size", choices=("full", "small"),
                    default="full")
    ap.add_argument("--points", type=int, default=16)
    ap.add_argument("--workload", choices=("fft", "memstress"),
                    default="fft")
    args = ap.parse_args()
    out = run_one(args.net, args.dir_size, args.points, args.workload)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
