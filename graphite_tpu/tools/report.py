"""Telemetry timeline reporter: render recorded timelines for CI/bench.

Input: one or more `.npz` files saved via `obs.Timeline.save` — a solo
run's timeline, or several (a campaign's demuxed `SweepOutcome.timelines`
saved one file per sim).  Output (stdout):

  --format json   one JSON line per sample (keys: sim, sample, time_ns,
                  then one key per recorded series), then one summary
                  line per timeline — the shape bench.py and the CI
                  artifacts consume;
  --format text   an aligned-text table per timeline (one row per
                  sample) followed by its summary;
  --summary       summaries only (either format).

Usage:
  python -m graphite_tpu.tools.report run.npz [sim0.npz sim1.npz ...]
                                      [--format json|text] [--summary]
"""

from __future__ import annotations

import argparse
import json
import sys


def _text_table(tl) -> "list[str]":
    """Aligned rows: sample index + time_ns + every non-time series."""
    cols = ["sample", "time_ns"] + [s for s in tl.series
                                    if s != "time_ps"]
    rows = [[str(r["sample"]), str(r["time_ns"])]
            + [str(r[s]) for s in cols[2:]] for r in tl.json_rows()]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.rjust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render device-recorded telemetry timelines")
    ap.add_argument("files", nargs="+",
                    help=".npz timeline file(s) (obs.Timeline.save); "
                    "several files render as one campaign, sim-indexed "
                    "in argument order")
    ap.add_argument("--format", choices=("json", "text"), default="json")
    ap.add_argument("--summary", action="store_true",
                    help="emit per-timeline summaries only (peak "
                    "injection rate, clock spread, stall quanta, ...)")
    args = ap.parse_args(argv)

    # pure host-side post-processing — never touch a chip
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from graphite_tpu.obs import Timeline

    for b, path in enumerate(args.files):
        tl = Timeline.load(path)
        summary = {"sim": b, "file": path,
                   "sample_interval_ps": tl.sample_interval_ps,
                   **tl.summary()}
        if args.format == "json":
            if not args.summary:
                for row in tl.json_rows():
                    print(json.dumps({"sim": b, **row}))
            print(json.dumps(summary))
        else:
            print(f"== sim {b}: {path} "
                  f"(interval {tl.sample_interval_ps} ps, "
                  f"{len(tl)} of {tl.n_total} samples"
                  + (", ring WRAPPED" if tl.wrapped else "") + ")")
            if not args.summary:
                for line in _text_table(tl):
                    print(line)
            for k, v in summary.items():
                if k not in ("sim", "file"):
                    print(f"  {k:28} {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
