"""Observability reporter: timelines, span traces, metrics dumps.

Three input kinds, one renderer:

  positional .npz   device telemetry timelines (`obs.Timeline.save`) —
                    a solo run, or several files as one campaign;
  --spans FILE      job/batch lifecycle spans saved as JSON-lines by
                    `tools/serve.py --trace-out` — renders one aligned
                    latency-breakdown row per job (submit, queue dwell,
                    execute, ... in microseconds) plus the batch
                    execution table (class, occupancy, cache hit,
                    compile time);
  --metrics FILE    a Prometheus text exposition written by
                    `tools/serve.py --metrics-out` — renders counters/
                    gauges and histogram summaries (count, sum,
                    p50/p90/p99 from the cumulative buckets).

Output (stdout):

  --format json   machine rows (one JSON line per sample / job / metric)
                  — the shape bench.py and the CI artifacts consume;
  --format text   aligned-text tables;
  --summary       summaries only (timeline mode).

Usage:
  python -m graphite_tpu.tools.report run.npz [sim0.npz sim1.npz ...]
                                      [--format json|text] [--summary]
  python -m graphite_tpu.tools.report --spans spans.jsonl --format text
  python -m graphite_tpu.tools.report --metrics metrics.prom
"""

from __future__ import annotations

import argparse
import json
import math
import sys


def _align(cols: "list[str]", rows: "list[list[str]]") -> "list[str]":
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.rjust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
    return lines


def _text_table(tl) -> "list[str]":
    """Aligned rows: sample index + time_ns + every non-time series."""
    cols = ["sample", "time_ns"] + [s for s in tl.series
                                    if s != "time_ps"]
    rows = [[str(r["sample"]), str(r["time_ns"])]
            + [str(r[s]) for s in cols[2:]] for r in tl.json_rows()]
    return _align(cols, rows)


def render_spans(path: str, fmt: str) -> "list[str]":
    """Span JSON-lines -> per-job latency breakdown + batch table."""
    from graphite_tpu.obs.trace import (
        BATCH_TRACE_PREFIX, JOB_SPANS, job_breakdown, load_jsonl,
    )

    rows = load_jsonl(path)
    jobs = sorted(job_breakdown(rows), key=lambda r: r["job"])
    if fmt == "json":
        out = [json.dumps(r) for r in jobs]
        for r in rows:
            if r["trace"].startswith(BATCH_TRACE_PREFIX) \
                    and r["span"] == "batch":
                out.append(json.dumps(r))
        return out
    # aligned per-job table: lifecycle spans in canonical order, then
    # any extra recorded spans (split/retry/...), then status/total
    span_cols = [s + "_us" for s in JOB_SPANS]
    extra = sorted({k for r in jobs for k in r
                    if k.endswith("_us") and k != "total_us"
                    and k not in span_cols})
    span_cols = [c for c in span_cols if any(c in r for r in jobs)]
    span_cols += [c for c in extra if c not in span_cols]
    cols = ["job"] + span_cols + ["total_us", "status"]
    body = [[str(r.get(c, "-")) for c in cols] for r in jobs]
    lines = _align(cols, body)
    batches = [r for r in rows
               if r["trace"].startswith(BATCH_TRACE_PREFIX)
               and r["span"] == "batch"]
    if batches:
        bcols = ["batch", "class", "n_jobs", "capacity", "occupancy",
                 "cache_hit", "compile_s", "dur_us", "ok"]
        brows = [[str(r["trace"]), str(r.get("class", "-")),
                  str(r.get("n_jobs", "-")), str(r.get("capacity", "-")),
                  str(r.get("occupancy", "-")),
                  str(r.get("cache_hit", "-")),
                  str(r.get("compile_s", "-")), str(r["dur_us"]),
                  str(r.get("ok", "-"))] for r in batches]
        lines.append("")
        lines.extend(_align(bcols, brows))
    return lines


def _hist_quantile(buckets: "dict[str, int]", count: int,
                   q: float) -> str:
    """Quantile from cumulative `le -> count` buckets (the same
    first-bucket-reaching-rank rule obs.metrics.Histogram uses; the
    +Inf tail renders as '>LAST' since the text format cannot carry
    the true max)."""
    if count == 0:
        return "0"
    rank = math.ceil(q * count)
    finite = [(le, c) for le, c in buckets.items() if le != "+Inf"]
    for le, cum in finite:
        if cum >= rank:
            return le
    return f">{finite[-1][0]}" if finite else "inf"


def render_metrics(path: str, fmt: str) -> "list[str]":
    """Prometheus text dump -> aligned metric summaries."""
    from graphite_tpu.obs.metrics import parse_exposition

    with open(path) as fh:
        parsed = parse_exposition(fh.read())
    if fmt == "json":
        return [json.dumps({"metric": name, **m})
                for name, m in parsed.items()]
    cols = ["metric", "type", "value", "count", "sum", "p50", "p90",
            "p99"]
    rows = []
    for name, m in parsed.items():
        if m["type"] == "histogram":
            n = m["count"]
            rows.append([name, "histogram", "-", str(n),
                         str(round(m["sum"], 6))]
                        + [_hist_quantile(m["buckets"], n, q)
                           for q in (0.5, 0.9, 0.99)])
        else:
            v = m.get("value", 0)
            v = int(v) if float(v).is_integer() else round(v, 6)
            rows.append([name, m["type"], str(v), "-", "-", "-", "-",
                         "-"])
    return _align(cols, rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render telemetry timelines, span traces, and "
        "metrics dumps")
    ap.add_argument("files", nargs="*",
                    help=".npz timeline file(s) (obs.Timeline.save); "
                    "several files render as one campaign, sim-indexed "
                    "in argument order")
    ap.add_argument("--spans", metavar="FILE",
                    help="render a span JSON-lines file "
                    "(tools/serve.py --trace-out) as a per-job latency "
                    "breakdown + batch table")
    ap.add_argument("--metrics", metavar="FILE",
                    help="render a Prometheus text exposition "
                    "(tools/serve.py --metrics-out) as metric "
                    "summaries")
    ap.add_argument("--format", choices=("json", "text"), default="json")
    ap.add_argument("--summary", action="store_true",
                    help="emit per-timeline summaries only (peak "
                    "injection rate, clock spread, stall quanta, ...)")
    args = ap.parse_args(argv)

    modes = sum((bool(args.files), bool(args.spans), bool(args.metrics)))
    if modes != 1:
        ap.error("give exactly one input: timeline .npz file(s), "
                 "--spans FILE, or --metrics FILE")

    # pure host-side post-processing — never touch a chip
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.spans:
        for line in render_spans(args.spans, args.format):
            print(line)
        return 0
    if args.metrics:
        for line in render_metrics(args.metrics, args.format):
            print(line)
        return 0

    from graphite_tpu.obs import Timeline

    for b, path in enumerate(args.files):
        tl = Timeline.load(path)
        summary = {"sim": b, "file": path,
                   "sample_interval_ps": tl.sample_interval_ps,
                   **tl.summary()}
        if args.format == "json":
            if not args.summary:
                for row in tl.json_rows():
                    print(json.dumps({"sim": b, **row}))
            print(json.dumps(summary))
        else:
            print(f"== sim {b}: {path} "
                  f"(interval {tl.sample_interval_ps} ps, "
                  f"{len(tl)} of {tl.n_total} samples"
                  + (", ring WRAPPED" if tl.wrapped else "") + ")")
            if not args.summary:
                for line in _text_table(tl):
                    print(line)
            for k, v in summary.items():
                if k not in ("sim", "file"):
                    print(f"  {k:28} {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
