"""Observability reporter: timelines, profiles, span traces, metrics.

Input kinds, one renderer:

  positional .npz   device telemetry timelines (`obs.Timeline.save`) —
                    a solo run, or several files as one campaign;
  --heatmap         positional .npz files are per-tile PROFILES
                    (`obs.TileProfile.save`): renders each selected
                    series as a tile-grid heatmap (aligned ASCII shade
                    digits for the terminal; JSON rows carrying the
                    full [T] vector) plus the straggler/imbalance
                    summary (max/mean skew, leader/straggler tile,
                    traffic Gini).  `--slice total|last|<idx>` picks
                    the time slice; `--series a,b` restricts series;
  --spans FILE      job/batch lifecycle spans saved as JSON-lines by
                    `tools/serve.py --trace-out` — renders one aligned
                    latency-breakdown row per job (submit, queue dwell,
                    execute, ... in microseconds) plus the batch
                    execution table (class, occupancy, cache hit,
                    compile time);
  --trade-curve FILE
                    the same span JSON-lines rendered as the latency/
                    occupancy trade curve: one scatter row per job
                    (queue_dwell_us vs its batch's occupancy) plus
                    occupancy-bucketed dwell aggregates — the
                    measurement half of latency-aware batching.  When
                    the file holds per-job RESULT rows that carry
                    `energy_pj` + `completion_time_ns` (a DVFS
                    race-to-idle campaign), the same flag renders the
                    energy-vs-wall trade instead: one scatter row per
                    operating point (wall, energy, EDP) plus the
                    Pareto frontier;
  --metrics FILE    a Prometheus text exposition written by
                    `tools/serve.py --metrics-out` — renders counters/
                    gauges and histogram summaries (count, sum,
                    p50/p90/p99 from the cumulative buckets);
  --perfetto OUT.json
                    unified Chrome-trace export (round 21): merges the
                    span JSONL (`--spans`, host-time track), telemetry
                    timelines (positional .npz), per-tile profiles
                    (`--profile-npz`) and latency histograms
                    (`--hist`, `obs.Hist.save` / `tools/serve.py
                    --hist-out`) into ONE trace with separate
                    host-time and sim-time clock tracks — open in the
                    Perfetto UI or chrome://tracing.

Output (stdout):

  --format json   machine rows (one JSON line per sample / job / metric)
                  — the shape bench.py and the CI artifacts consume;
  --format text   aligned-text tables;
  --summary       summaries only (timeline/heatmap modes).  Timeline
                  summaries carry per-series `peaks` (max + argmax
                  sample/time), so stragglers and spikes are nameable
                  from scalar timelines too.

Usage:
  python -m graphite_tpu.tools.report run.npz [sim0.npz sim1.npz ...]
                                      [--format json|text] [--summary]
  python -m graphite_tpu.tools.report --heatmap prof.npz --slice total
  python -m graphite_tpu.tools.report --spans spans.jsonl --format text
  python -m graphite_tpu.tools.report --trade-curve spans.jsonl
  python -m graphite_tpu.tools.report --metrics metrics.prom
  python -m graphite_tpu.tools.report --perfetto trace.json \
      --spans spans.jsonl --hist hists/*.npz run.npz
"""

from __future__ import annotations

import argparse
import json
import math
import sys


def _align(cols: "list[str]", rows: "list[list[str]]") -> "list[str]":
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.rjust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(c.rjust(w) for c, w in zip(r, widths)))
    return lines


def _text_table(tl) -> "list[str]":
    """Aligned rows: sample index + time_ns + every non-time series."""
    cols = ["sample", "time_ns"] + [s for s in tl.series
                                    if s != "time_ps"]
    rows = [[str(r["sample"]), str(r["time_ns"])]
            + [str(r[s]) for s in cols[2:]] for r in tl.json_rows()]
    return _align(cols, rows)


def render_spans(path: str, fmt: str) -> "list[str]":
    """Span JSON-lines -> per-job latency breakdown + batch table."""
    from graphite_tpu.obs.trace import (
        BATCH_TRACE_PREFIX, JOB_SPANS, job_breakdown, load_jsonl,
    )

    rows = load_jsonl(path)
    jobs = sorted(job_breakdown(rows), key=lambda r: r["job"])
    if fmt == "json":
        out = [json.dumps(r) for r in jobs]
        for r in rows:
            if r["trace"].startswith(BATCH_TRACE_PREFIX) \
                    and r["span"] == "batch":
                out.append(json.dumps(r))
        return out
    # aligned per-job table: lifecycle spans in canonical order, then
    # any extra recorded spans (split/retry/...), then status/total
    span_cols = [s + "_us" for s in JOB_SPANS]
    extra = sorted({k for r in jobs for k in r
                    if k.endswith("_us") and k != "total_us"
                    and k not in span_cols})
    span_cols = [c for c in span_cols if any(c in r for r in jobs)]
    span_cols += [c for c in extra if c not in span_cols]
    cols = ["job"] + span_cols + ["total_us", "status"]
    body = [[str(r.get(c, "-")) for c in cols] for r in jobs]
    lines = _align(cols, body)
    batches = [r for r in rows
               if r["trace"].startswith(BATCH_TRACE_PREFIX)
               and r["span"] == "batch"]
    if batches:
        bcols = ["batch", "class", "n_jobs", "capacity", "occupancy",
                 "cache_hit", "compile_s", "dur_us", "ok"]
        brows = [[str(r["trace"]), str(r.get("class", "-")),
                  str(r.get("n_jobs", "-")), str(r.get("capacity", "-")),
                  str(r.get("occupancy", "-")),
                  str(r.get("cache_hit", "-")),
                  str(r.get("compile_s", "-")), str(r["dur_us"]),
                  str(r.get("ok", "-"))] for r in batches]
        lines.append("")
        lines.extend(_align(bcols, brows))
    return lines


_SHADES = "0123456789"


def heatmap_lines(prof, *, series=None,
                  sample: "int | str" = "total") -> "list[str]":
    """ASCII tile-grid heatmaps of one TileProfile: per selected
    series, the near-square emesh grid with each tile's value scaled
    to a shade digit 0-9 (0 = the slice minimum, 9 = the maximum; a
    flat slice renders all zeros), plus the min/max legend.  Aligned,
    deterministic — the golden-render shape the tests pin."""
    from graphite_tpu.obs.profile import grid_shape

    names = tuple(series) if series else prof.series
    rows_n, cols_n = grid_shape(prof.n_tiles)
    out = []
    for s in names:
        vec = prof.tile_slice(s, sample)
        lo, hi = int(vec.min()), int(vec.max())
        span = hi - lo
        out.append(f"-- {s} [slice {sample}] min {lo} max {hi} "
                   f"(0='{_SHADES[0]}' .. 9='{_SHADES[-1]}')")
        for r in range(rows_n):
            cells = []
            for c in range(cols_n):
                t = r * cols_n + c
                if t >= prof.n_tiles:
                    cells.append(" ")
                    continue
                v = int(vec[t])
                shade = 0 if span == 0 else (9 * (v - lo)) // span
                cells.append(_SHADES[shade])
            out.append(" ".join(cells).rstrip())
    return out


def render_heatmap(paths, fmt: str, *, series=None,
                   sample: "int | str" = "total",
                   summary_only: bool = False) -> "list[str]":
    """Per-tile profile .npz file(s) -> heatmaps + straggler summary."""
    from graphite_tpu.obs.profile import TileProfile

    lines = []
    for b, path in enumerate(paths):
        prof = TileProfile.load(path)
        names = tuple(series) if series else prof.series
        unknown = [s for s in names if s not in prof.series]
        if unknown:
            raise SystemExit(
                f"{path}: unknown series {unknown} "
                f"(recorded: {', '.join(prof.series)})")
        if len(prof) == 0:
            raise SystemExit(f"{path}: profile holds no recorded "
                             "samples — nothing to render")
        if isinstance(sample, int) \
                and not -len(prof) <= sample < len(prof):
            raise SystemExit(
                f"{path}: --slice {sample} out of range (profile "
                f"holds {len(prof)} recorded sample(s))")
        summary = {"sim": b, "file": path,
                   "sample_interval_ps": prof.sample_interval_ps,
                   **prof.summary()}
        if fmt == "json":
            if not summary_only:
                lines.extend(json.dumps({"sim": b, **row})
                             for row in prof.json_rows(
                                 series=names, sample=sample))
            lines.append(json.dumps(summary))
            continue
        lines.append(
            f"== sim {b}: {path} ({prof.n_tiles} tiles, "
            f"{len(prof)} of {prof.n_total} samples"
            + (", ring WRAPPED" if prof.wrapped else "") + ")")
        if not summary_only:
            lines.extend(heatmap_lines(prof, series=names,
                                       sample=sample))
        for k, v in summary.items():
            if k not in ("sim", "file"):
                lines.append(f"  {k:22} {v}")
    return lines


def trade_curve_rows(rows: "list[dict]") -> "tuple[list, list]":
    """Span rows -> (per-job scatter rows, occupancy-bucket aggregate
    rows) of the latency/occupancy trade: each job's queue dwell
    against the occupancy of the batch that ran it — the measurement
    the round-14 `queue_dwell_seconds` histogram and `batch_occupancy`
    series exist to feed (the scale-out item's dwell-knob evidence)."""
    from graphite_tpu.obs.trace import BATCH_TRACE_PREFIX

    occ_by_batch = {}
    for r in rows:
        if r["trace"].startswith(BATCH_TRACE_PREFIX) \
                and r["span"] == "batch" and "occupancy" in r:
            occ_by_batch[r["trace"]] = r
    scatter = []
    for r in rows:
        if r["span"] != "queue" or "batch" not in r:
            continue
        b = occ_by_batch.get(f"batch-{r['batch']}")
        if b is None:
            continue
        scatter.append({
            "job": r["trace"], "batch": int(r["batch"]),
            "queue_dwell_us": int(r["dur_us"]),
            "occupancy": float(b["occupancy"]),
            "n_jobs": b.get("n_jobs"),
            "capacity": b.get("capacity"),
            "execute_us": int(b["dur_us"]),
        })
    buckets: "dict[float, list]" = {}
    for s in scatter:
        # bucket occupancy to one decimal: the curve's x grid
        buckets.setdefault(round(s["occupancy"], 1), []).append(s)
    curve = []
    for occ in sorted(buckets):
        group = buckets[occ]
        dwells = sorted(g["queue_dwell_us"] for g in group)
        curve.append({
            "curve": True, "occupancy_bucket": occ,
            "jobs": len(group),
            "mean_dwell_us": int(sum(dwells) / len(dwells)),
            "max_dwell_us": int(dwells[-1]),
            "mean_execute_us": int(sum(g["execute_us"] for g in group)
                                   / len(group)),
        })
    return scatter, curve


def energy_trade_rows(rows: "list[dict]") -> "tuple[list, list]":
    """Per-job result rows (tools/serve.py output lines, or any JSON
    lines carrying `energy_pj` + `completion_time_ns`) -> (per-config
    scatter rows, Pareto frontier rows) of the energy-vs-wall trade —
    the race-to-idle campaign's headline curve.  Each scatter row
    carries the operating point (the `dvfs_domain_mhz` knob when
    present), the simulated wall, the priced energy, and their product
    (EDP, pJ·ns).  A point is on the frontier when no other point is
    at least as good on BOTH axes and better on one."""
    scatter = []
    for r in rows:
        if "energy_pj" not in r or "completion_time_ns" not in r:
            continue
        s = {"job": r.get("job"),
             "wall_ns": int(r["completion_time_ns"]),
             "energy_pj": int(r["energy_pj"])}
        if "dvfs_domain_mhz" in r:
            s["dvfs_domain_mhz"] = tuple(
                int(x) for x in r["dvfs_domain_mhz"]) \
                if isinstance(r["dvfs_domain_mhz"], (tuple, list)) \
                else int(r["dvfs_domain_mhz"])
        s["edp_pj_ns"] = s["wall_ns"] * s["energy_pj"]
        scatter.append(s)
    scatter.sort(key=lambda s: (s["wall_ns"], s["energy_pj"]))
    frontier = []
    for s in scatter:
        dominated = any(
            o is not s
            and o["wall_ns"] <= s["wall_ns"]
            and o["energy_pj"] <= s["energy_pj"]
            and (o["wall_ns"] < s["wall_ns"]
                 or o["energy_pj"] < s["energy_pj"])
            for o in scatter)
        if not dominated:
            frontier.append({**s, "pareto": True})
    return scatter, frontier


def render_trade_curve(path: str, fmt: str) -> "list[str]":
    from graphite_tpu.obs.trace import load_jsonl

    rows = load_jsonl(path)
    if any("energy_pj" in r and "completion_time_ns" in r for r in rows):
        # energy-vs-wall mode: per-job result rows from a DVFS campaign
        scatter, frontier = energy_trade_rows(rows)
        if fmt == "json":
            return [json.dumps(r) for r in scatter + frontier]
        cols = ["job", "dvfs_domain_mhz", "wall_ns", "energy_pj",
                "edp_pj_ns"]
        frontier_keys = {(f["wall_ns"], f["energy_pj"], f["job"])
                         for f in frontier}
        body = [[str(r.get(c, "-")) for c in cols]
                + ["*" if (r["wall_ns"], r["energy_pj"],
                           r["job"]) in frontier_keys else ""]
                for r in scatter]
        return _align(cols + ["pareto"], body)
    scatter, curve = trade_curve_rows(rows)
    if fmt == "json":
        return [json.dumps(r) for r in scatter + curve]
    cols = ["job", "batch", "queue_dwell_us", "occupancy", "n_jobs",
            "capacity", "execute_us"]
    lines = _align(cols, [[str(r.get(c, "-")) for c in cols]
                          for r in scatter])
    if curve:
        ccols = ["occupancy_bucket", "jobs", "mean_dwell_us",
                 "max_dwell_us", "mean_execute_us"]
        lines.append("")
        lines.extend(_align(ccols, [[str(r[c]) for c in ccols]
                                    for r in curve]))
    return lines


def _hist_quantile(buckets: "dict[str, int]", count: int,
                   q: float) -> str:
    """Quantile from cumulative `le -> count` buckets (the same
    first-bucket-reaching-rank rule obs.metrics.Histogram uses; the
    +Inf tail renders as '>LAST' since the text format cannot carry
    the true max)."""
    if count == 0:
        return "0"
    rank = math.ceil(q * count)
    finite = [(le, c) for le, c in buckets.items() if le != "+Inf"]
    for le, cum in finite:
        if cum >= rank:
            return le
    return f">{finite[-1][0]}" if finite else "inf"


HOST_PID = 1   # serve lifecycle spans (tracer clock, ts in us)
SIM_PID = 2    # device rings: telemetry/profile counters + histograms
               # (simulated time, ts in ns)


def perfetto_events(*, spans: "str | None" = None, timelines=(),
                    profiles=(), hists=()) -> "list[dict]":
    """One Chrome-trace event list from every observability artifact.

    Two clock tracks, kept as separate trace processes because their
    clocks never align: HOST_PID carries the serve span JSONL
    (`tools/serve.py --trace-out`, ts = tracer microseconds) as 'X'
    complete events, SIM_PID carries the device rings in SIMULATED
    time — telemetry and per-tile profile samples as 'C' counter
    tracks (ts = sim ns), and each latency histogram as one instant
    event whose args hold the deterministic count/p50/p95/p99 summary
    (`obs.Hist.summary` — the shared bucket_quantile definition).
    Events are sorted (pid, ts), so every track's stamps are monotone
    — the invariant tools/regress.py's perfetto rung asserts."""
    events = [
        {"ph": "M", "pid": HOST_PID, "tid": 0, "ts": 0,
         "name": "process_name",
         "args": {"name": "host-time (serve spans, us)"}},
        {"ph": "M", "pid": SIM_PID, "tid": 0, "ts": 0,
         "name": "process_name",
         "args": {"name": "sim-time (device rings, ns)"}},
    ]
    if spans:
        from graphite_tpu.obs.trace import load_jsonl

        for r in load_jsonl(spans):
            ev = {"name": r["span"], "cat": "serve", "ph": "X",
                  "pid": HOST_PID, "tid": r["trace"],
                  "ts": int(r["start_us"]),
                  "dur": int(r["dur_us"])}
            extra = {k: v for k, v in r.items()
                     if k not in ("trace", "span", "start_us",
                                  "dur_us")}
            if extra:
                ev["args"] = extra
            events.append(ev)
    if timelines:
        from graphite_tpu.obs import Timeline

        for b, path in enumerate(timelines):
            tl = Timeline.load(path)
            for row in tl.json_rows():
                for s in tl.series:
                    if s == "time_ps":
                        continue
                    events.append({
                        "name": f"tl{b}.{s}", "cat": "telemetry",
                        "ph": "C", "pid": SIM_PID, "tid": 0,
                        "ts": int(row["time_ns"]),
                        "args": {"value": int(row[s])}})
    if profiles:
        from graphite_tpu.obs.profile import TileProfile

        for b, path in enumerate(profiles):
            prof = TileProfile.load(path)
            times = prof.time_ns
            for s in prof.series:
                col = prof.col(s)       # [S, T]
                for i in range(len(prof)):
                    # one stacked counter track per series: every
                    # tile's value rides the same event's args
                    events.append({
                        "name": f"prof{b}.{s}", "cat": "profile",
                        "ph": "C", "pid": SIM_PID, "tid": 0,
                        "ts": int(times[i]),
                        "args": {f"t{t}": int(col[i, t])
                                 for t in range(prof.n_tiles)}})
    if hists:
        from graphite_tpu.obs.hist import Hist

        for b, path in enumerate(hists):
            h = Hist.load(path)
            for s in h.sources:
                events.append({
                    "name": f"hist{b}.{s}", "cat": "hist", "ph": "i",
                    "pid": SIM_PID, "tid": 0, "ts": 0, "s": "g",
                    "args": {"count": h.total(s),
                             "p50": h.quantile(s, 0.5),
                             "p95": h.quantile(s, 0.95),
                             "p99": h.quantile(s, 0.99),
                             "file": path}})
    # metadata first, then every track's stamps monotone within its pid
    events.sort(key=lambda e: (e["ph"] != "M", e["pid"], e["ts"]))
    return events


def write_perfetto(out_path: str, **kw) -> int:
    """Write the unified Chrome trace (load in Perfetto UI /
    chrome://tracing); returns the event count."""
    events = perfetto_events(**kw)
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ns"},
                  fh)
    return len(events)


def render_metrics(path: str, fmt: str) -> "list[str]":
    """Prometheus text dump -> aligned metric summaries."""
    from graphite_tpu.obs.metrics import parse_exposition

    with open(path) as fh:
        parsed = parse_exposition(fh.read())
    if fmt == "json":
        return [json.dumps({"metric": name, **m})
                for name, m in parsed.items()]
    cols = ["metric", "type", "value", "count", "sum", "p50", "p90",
            "p99"]
    rows = []
    for name, m in parsed.items():
        if m["type"] == "histogram":
            n = m["count"]
            rows.append([name, "histogram", "-", str(n),
                         str(round(m["sum"], 6))]
                        + [_hist_quantile(m["buckets"], n, q)
                           for q in (0.5, 0.9, 0.99)])
        else:
            v = m.get("value", 0)
            v = int(v) if float(v).is_integer() else round(v, 6)
            rows.append([name, m["type"], str(v), "-", "-", "-", "-",
                         "-"])
    return _align(cols, rows)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render telemetry timelines, span traces, and "
        "metrics dumps")
    ap.add_argument("files", nargs="*",
                    help=".npz timeline file(s) (obs.Timeline.save) — "
                    "or, with --heatmap, per-tile profile file(s) "
                    "(obs.TileProfile.save); several files render as "
                    "one campaign, sim-indexed in argument order")
    ap.add_argument("--heatmap", action="store_true",
                    help="treat the positional .npz files as per-tile "
                    "profiles and render tile-grid heatmaps + the "
                    "straggler/imbalance summary")
    ap.add_argument("--slice", default=None, metavar="WHICH",
                    help="heatmap time slice: 'total' (the default; "
                    "delta series sum over samples, levels take the "
                    "last), 'last', or a sample index (negative from "
                    "the end)")
    ap.add_argument("--series", metavar="A,B,...",
                    help="restrict heatmaps to these series")
    ap.add_argument("--spans", metavar="FILE",
                    help="render a span JSON-lines file "
                    "(tools/serve.py --trace-out) as a per-job latency "
                    "breakdown + batch table")
    ap.add_argument("--trade-curve", metavar="FILE",
                    help="render a span JSON-lines file as the "
                    "latency/occupancy trade curve (per-job queue "
                    "dwell vs batch occupancy + bucketed aggregates); "
                    "per-job result rows with energy_pj render as the "
                    "energy-vs-wall trade + Pareto frontier instead")
    ap.add_argument("--metrics", metavar="FILE",
                    help="render a Prometheus text exposition "
                    "(tools/serve.py --metrics-out) as metric "
                    "summaries")
    ap.add_argument("--perfetto", metavar="OUT.json",
                    help="write one unified Chrome-trace JSON merging "
                    "every given artifact: --spans JSONL (host-time "
                    "track), positional telemetry .npz + --profile-npz "
                    "+ --hist .npz files (sim-time track); open in "
                    "the Perfetto UI or chrome://tracing")
    ap.add_argument("--hist", metavar="FILE", nargs="+", default=(),
                    help="latency-histogram .npz file(s) "
                    "(obs.Hist.save / tools/serve.py --hist-out) to "
                    "fold into the --perfetto export")
    ap.add_argument("--profile-npz", metavar="FILE", nargs="+",
                    default=(),
                    help="per-tile profile .npz file(s) to fold into "
                    "the --perfetto export as stacked counter tracks")
    ap.add_argument("--format", choices=("json", "text"), default="json")
    ap.add_argument("--summary", action="store_true",
                    help="emit per-timeline/profile summaries only "
                    "(peak injection rate, clock spread + per-series "
                    "peaks, skew/Gini stragglers, ...)")
    args = ap.parse_args(argv)

    if args.perfetto:
        if args.metrics or args.trade_curve or args.heatmap:
            ap.error("--perfetto combines positional timeline .npz, "
                     "--spans, --profile-npz and --hist only")
        if not (args.files or args.spans or args.hist
                or args.profile_npz):
            ap.error("--perfetto needs at least one input artifact "
                     "(timeline .npz, --spans, --profile-npz, --hist)")
    elif args.hist or args.profile_npz:
        ap.error("--hist/--profile-npz apply to --perfetto mode only")
    else:
        modes = sum((bool(args.files), bool(args.spans),
                     bool(args.metrics), bool(args.trade_curve)))
        if modes != 1:
            ap.error("give exactly one input: timeline/profile .npz "
                     "file(s), --spans FILE, --trade-curve FILE, or "
                     "--metrics FILE")
    if args.heatmap and not args.files:
        ap.error("--heatmap needs positional profile .npz file(s)")
    if not args.heatmap and (args.slice is not None or args.series):
        ap.error("--slice/--series apply to --heatmap mode only")

    # pure host-side post-processing — never touch a chip
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.perfetto:
        n = write_perfetto(args.perfetto, spans=args.spans,
                           timelines=args.files,
                           profiles=args.profile_npz, hists=args.hist)
        print(json.dumps({"perfetto": args.perfetto, "events": n}))
        return 0

    if args.spans:
        for line in render_spans(args.spans, args.format):
            print(line)
        return 0
    if args.trade_curve:
        for line in render_trade_curve(args.trade_curve, args.format):
            print(line)
        return 0
    if args.metrics:
        for line in render_metrics(args.metrics, args.format):
            print(line)
        return 0
    if args.heatmap:
        sl = args.slice if args.slice is not None else "total"
        if sl not in ("total", "last"):
            try:
                sl = int(sl)
            except ValueError:
                ap.error("--slice must be 'total', 'last', or an "
                         "integer sample index")
        names = (tuple(s.strip() for s in args.series.split(",")
                       if s.strip()) if args.series else None)
        for line in render_heatmap(args.files, args.format,
                                   series=names, sample=sl,
                                   summary_only=args.summary):
            print(line)
        return 0

    from graphite_tpu.obs import Timeline

    for b, path in enumerate(args.files):
        tl = Timeline.load(path)
        summary = {"sim": b, "file": path,
                   "sample_interval_ps": tl.sample_interval_ps,
                   **tl.summary()}
        if args.format == "json":
            if not args.summary:
                for row in tl.json_rows():
                    print(json.dumps({"sim": b, **row}))
            print(json.dumps(summary))
        else:
            print(f"== sim {b}: {path} "
                  f"(interval {tl.sample_interval_ps} ps, "
                  f"{len(tl)} of {tl.n_total} samples"
                  + (", ring WRAPPED" if tl.wrapped else "") + ")")
            if not args.summary:
                for line in _text_table(tl):
                    print(line)
            for k, v in summary.items():
                if k in ("sim", "file"):
                    continue
                if k == "peaks":
                    # per-series argmax rows: spikes are nameable by
                    # sample/time, not only sized
                    for s, p in v.items():
                        print(f"  peak {s:22} {p['max']} at sample "
                              f"{p['sample']} (t={p['time_ns']} ns)")
                    continue
                print(f"  {k:28} {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
