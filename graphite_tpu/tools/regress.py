"""Configuration-sweep regression driver — the analog of the reference's
`tools/regress/run_tests.py` + `aggregate_results.py` (compile & schedule
SPLASH-2 x machines x modes with config overrides, aggregate results).

Sweeps the model matrix on small traces: caching protocol x directory
scheme x NoC model x core model, replaying a benchmark trace through each,
and prints one result row per config (completion time, instructions,
func_errors).  Exit code is nonzero if any config fails.

Usage:
  python -m graphite_tpu.tools.regress [--tiles 8] [--quick]
  python -m graphite_tpu.tools.regress --smoke   # tier-1 companion, CPU

`--smoke` is the fast gating/dispatch attestation (runs in well under a
minute on CPU with a warm XLA cache): the 16-tile per-phase-gated vs
ungated engine pair must be bit-identical, the batched host-barrier
dispatch (barrier_batch > 1) must reproduce the per-quantum dispatch
exactly, the B=4 sweep must match sequential runs, telemetry recording
must leave SimResults bit-identical (solo, gated + ungated) and the
B=4 campaign's demuxed timelines must equal sequential telemetry runs
(the per-tile profile ring repeats all three checks in rung 10, plus
the cross-ring per-tile-sums-equal-scalar-series invariant),
the program auditor's jaxpr invariant lints (graphite_tpu/analysis)
must pass on the lowered default programs, every default program's
static cost report must sit within the checked-in BUDGETS.json
ceilings (the round-10 budget gate — kernel proxy, bytes/iter, peak
residency; tools/audit.py --budget-update refreshes after an
intentional change), every default program's canonical fingerprint
must match its registered identity in PROGRAMS.lock (the round-11
identity gate — tools/audit.py --lock-update re-registers), and the
round-18 2D batch x tile campaign must be bit-identical — results,
timelines, per-tile profile rings — to the 1D batch layout and to
sequential solo runs on forced host devices, with the admission
controller bin-packing a too-big-for-one-device sim across devices
(rung 12; standalone via --smoke-mesh2d), and the round-19 runtime
DVFS manager must be invisible at the config's own frequencies
(carried-frequency engines and the B=4 campaign bit-identical to the
constant-folded ones), match the hand-stepped golden interpreter on
in-trace DVFS_SET retunes, and govern deterministically (rung 13),
and the round-20 bounded model checker must exhaust the 2-tile/1-line
MSI and MOSI state spaces with zero invariant violations, replay every
explored transition bit-equal through the vectorized engines, and
catch the seeded 'mosi-owner-skips-wb' mutant with a named data-value
counterexample (rung 14), and the round-21 device-resident latency
histograms must be pure observability (hist on/off SimResults
bit-identical, gated + ungated), conserve events exactly (every
histogram total bit-equals its paired cumulative counter), demux the
B=4 campaign identically to sequential recordings, and export a valid
monotone-stamped Chrome trace via tools/report.py --perfetto
(rung 15), and the round-22 collective/ICI analyzer must pass its
comms audit over the registered mesh programs under the forced-4-
device re-exec (every collective a whitelisted px packed exchange,
every declared-replicated output provably uniform) while the known-bad
legacy unpacked-exchange fixture trips the gspmd-insertion lint with
exit 1 (rung 16).
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time


from graphite_tpu.tools._template import config_text

PROTOCOLS = (
    "pr_l1_pr_l2_dram_directory_msi",
    "pr_l1_pr_l2_dram_directory_mosi",
    "pr_l1_sh_l2_msi",
    "pr_l1_sh_l2_mesi",
)
SCHEMES = ("full_map", "limited_no_broadcast", "ackwise", "limitless")
NETWORKS = ("magic", "emesh_hop_counter", "emesh_hop_by_hop")
CORES = ("simple", "iocoom")


def run_one(tiles, protocol, scheme, network, core, workload):
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.trace.benchmarks import BENCHMARKS

    shared = workload == "canneal"
    cfg = ConfigFile.from_string(config_text(
        tiles, protocol=protocol, scheme=scheme, network=network,
        core=core, shared_mem=shared))
    if workload == "canneal":
        batch = BENCHMARKS[workload](tiles, footprint_lines=256,
                                     swaps_per_tile=6)
    elif workload == "fft":
        batch = BENCHMARKS[workload](tiles, points_per_tile=32)
    else:
        batch = BENCHMARKS[workload](tiles)
    sim = Simulator(SimConfig(cfg), batch)
    res = sim.run()
    return res


def _compare(name, ra, rb):
    """Bit-equality of two SimResults (clocks + memory counters)."""
    import numpy as np

    ok = (np.asarray(ra.clock_ps) == np.asarray(rb.clock_ps)).all()
    if ra.mem_counters is not None:
        for k in ra.mem_counters:
            ok = ok and (np.asarray(ra.mem_counters[k])
                         == np.asarray(rb.mem_counters[k])).all()
    ok = ok and ra.n_quanta == rb.n_quanta
    print(f"{name:44} {'PASS' if ok else 'FAIL'}")
    return 0 if ok else 1


def smoke(tiles: int = 16) -> int:
    """The tier-1 companion fast path: gated/ungated bit-exactness and
    batched-barrier equivalence at 16 tiles on CPU."""
    import time as _t

    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.trace import synthetic

    t0 = _t.perf_counter()
    failures = 0

    # 1) per-phase gating is mechanism, not policy: gated vs ungated
    #    engines must be bit-identical on coherence-heavy traffic
    #    (mem_gate_bytes=0 forces the whole-engine gate OFF so the
    #    per-phase conds are the only gating in the gated program)
    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax")))
    batch = synthetic.memory_stress_trace(
        tiles, n_accesses=40, working_set_bytes=1 << 13,
        write_fraction=0.4, shared_fraction=0.5, seed=7)
    r_gate = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0).run()
    r_flat = Simulator(sc, batch, phase_gate=False, mem_gate_bytes=0).run()
    failures += _compare("phase-gated vs ungated (MSI, 16t)", r_gate,
                         r_flat)

    # 1b) base consolidation is layout, not policy (round 12): the
    #     packed one-gather/one-merged-scatter directory working set
    #     must be bit-identical to the round-11 per-phase layout
    #     (base_consolidate=False) on gated AND ungated MSI, and on the
    #     B=4 campaign — the same pattern as the round-6 gating rung
    for gate, label in ((True, "gated"), (False, "ungated")):
        r_new = Simulator(sc, batch, phase_gate=gate,
                          mem_gate_bytes=0).run()
        r_old = Simulator(sc, batch, phase_gate=gate, mem_gate_bytes=0,
                          base_consolidate=False).run()
        failures += _compare(
            f"base-consolidated vs round-11 ({label})", r_new, r_old)

    # 2) batched host-barrier dispatch == per-quantum dispatch
    sc_b = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier")))
    r_b1 = Simulator(sc_b, batch, barrier_host=True, barrier_batch=1).run()
    r_b8 = Simulator(sc_b, batch, barrier_host=True, barrier_batch=8).run()
    failures += _compare("barrier_batch=8 vs per-quantum dispatch", r_b1,
                         r_b8)

    # 3) batched campaign == sequential runs (round 7, sweep/): B=4 sims
    #    vmapped through ONE compiled program with per-sim traced knobs
    #    must be bit-identical to 4 independent Simulator runs
    from graphite_tpu.sweep import SweepRunner

    seeds = (1, 2, 3, 4)
    sweep_traces = [
        synthetic.memory_stress_trace(
            tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=s)
        for s in seeds
    ]
    sweep = SweepRunner(sc, sweep_traces)
    out = sweep.run()
    for b, s in enumerate(seeds):
        r_seq = Simulator(sc, sweep_traces[b],
                          mailbox_depth=sweep.mailbox_depth).run()
        failures += _compare(f"sweep B=4 sim {b} (seed {s}) vs sequential",
                             out.results[b], r_seq)
    # 3b) the B=4 campaign under the round-11 layout must demux the
    #     same per-sim results as the consolidated default (round 12)
    out_old = SweepRunner(sc, sweep_traces, base_consolidate=False).run()
    for b, s in enumerate(seeds):
        failures += _compare(
            f"sweep B=4 sim {b} consolidated vs round-11",
            out.results[b], out_old.results[b])

    # 4) telemetry is pure observability (round 9): recording a dense
    #    device timeline must leave every SimResults field bit-identical
    #    (gated + ungated), and the B=4 campaign's demuxed [B, S, n]
    #    timelines must equal 4 sequential telemetry runs' rows exactly
    import numpy as np

    from graphite_tpu.obs import TelemetrySpec

    tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=64)
    for gate, label in ((True, "gated"), (False, "ungated")):
        r_tel = Simulator(sc_b, batch, phase_gate=gate, mem_gate_bytes=0,
                          telemetry=tel).run()
        r_off = Simulator(sc_b, batch, phase_gate=gate,
                          mem_gate_bytes=0).run()
        failures += _compare(f"telemetry on vs off ({label} MSI, 16t)",
                             r_tel, r_off)
    sweep_tel = SweepRunner(sc_b, sweep_traces, telemetry=tel)
    out_tel = sweep_tel.run()
    for b, s in enumerate(seeds):
        solo = Simulator(sc_b, sweep_traces[b],
                         mailbox_depth=sweep_tel.mailbox_depth,
                         phase_gate=False, mem_gate_bytes=0,
                         telemetry=tel).run().telemetry
        tl = out_tel.timelines[b]
        ok = (tl.n_total == solo.n_total
              and np.array_equal(tl.data, solo.data))
        print(f"{f'sweep B=4 sim {b} timeline vs sequential':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1

    # 5) program auditor (round 8): the jaxpr invariant lints must pass
    #    on the lowered default programs — both memory engines (gated,
    #    ungated, shl2), the B=4 sweep campaign, and the telemetry
    #    programs.  Static analysis only: make_jaxpr, no compile.
    from graphite_tpu.analysis import audit, default_programs

    specs = default_programs(8)
    report = audit(specs)
    for row in report.summary_rows():
        name = f"audit {row['program']}"
        ok = row["ok"]
        print(f"{name:44} {'PASS' if ok else 'FAIL'}"
              + ("" if ok else f"  ({row['errors']} error(s))"))
        failures += 0 if ok else 1
    for f in report.findings:
        print(f"    {f}")

    # 6) budget gate (round 10): every default program's static cost
    #    report (analysis/cost.py) must sit within the checked-in
    #    BUDGETS.json ceilings — kernel proxy, bytes/iter, peak
    #    residency.  The same lowered specs as rung 5; no compile.
    from graphite_tpu.analysis import cost as _cost
    from graphite_tpu.analysis import registry as _registry

    try:
        budgets = _cost.load_budgets()
    except FileNotFoundError:
        print(f"{'budget BUDGETS.json':44} FAIL  (missing — run "
              f"tools/audit.py --budget-update)")
        failures += 1
    else:
        # round 11: budgets resolve THROUGH the program registry, so a
        # ceiling measured at a different fingerprint errors loudly
        try:
            reg = _registry.load_lock()
        except FileNotFoundError:
            reg = None
        for spec in specs:
            rep = _cost.cost_report(spec)
            trips = _cost.check_budget(
                rep, budgets,
                record=(reg or {}).get(rep.program))
            name = f"budget {rep.program}"
            print(f"{name:44} {'PASS' if not trips else 'FAIL'}")
            for f in trips:
                print(f"    {f}")
            failures += 1 if trips else 0

    # 7) identity lock (round 11): every default program's canonical
    #    fingerprint (analysis/identity.py) must match its registered
    #    entry in PROGRAMS.lock — geometry and knob signature included.
    #    Same lowered specs as rungs 5-6; tools/audit.py --lock-update
    #    re-registers after an intentional change.
    try:
        lock = _registry.load_lock()
    except FileNotFoundError:
        print(f"{'lock PROGRAMS.lock':44} FAIL  (missing — run "
              f"tools/audit.py --lock-update)")
        failures += 1
    else:
        trips = _registry.check_lock(specs, lock, expect_complete=True)
        by_prog = {}
        for f in trips:
            by_prog.setdefault(f.program, []).append(f)
        for spec in specs:
            name = f"lock {spec.name}"
            fs = by_prog.pop(spec.name, [])
            print(f"{name:44} {'PASS' if not fs else 'FAIL'}")
            for f in fs:
                print(f"    {f}")
            failures += 1 if fs else 0
        for prog, fs in sorted(by_prog.items()):
            print(f"{f'lock {prog}':44} FAIL")
            for f in fs:
                print(f"    {f}")
            failures += 1

    # 8) campaign service (round 13, serve/): a MIXED-GEOMETRY job set
    #    through the admission-controlled service — batched, padded,
    #    cache-served with hit verification on (every hit re-proves the
    #    program fingerprint) — must be bit-identical (results + demuxed
    #    telemetry) to sequential Simulator runs, and each program class
    #    must pay exactly ONE compile.
    from graphite_tpu.serve import CampaignService, Job

    tel_sv = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=32)
    sc4 = SimConfig(ConfigFile.from_string(config_text(
        4, shared_mem=True, clock_scheme="lax")))
    sc8 = SimConfig(ConfigFile.from_string(config_text(
        8, shared_mem=True, clock_scheme="lax")))

    def _mkt(tiles, seed):
        return synthetic.memory_stress_trace(
            tiles, n_accesses=12, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.5, seed=seed)

    svc = CampaignService(batch_size=2, max_quanta=200_000,
                          verify_hits=True)
    serve_jobs = []
    for i, s in enumerate((1, 2, 3)):
        serve_jobs.append(Job(f"t4-{i}", sc4, _mkt(4, s), seed=s))
        serve_jobs.append(Job(f"t8-{i}", sc8, _mkt(8, s), seed=s,
                              telemetry=tel_sv))
    for job in serve_jobs:
        svc.submit(job)
    served = {r.job_id: r for r in svc.drain()}
    for job in serve_jobs:
        sc_j = sc4 if job.n_tiles == 4 else sc8
        if job.telemetry is not None:
            # the vmapped campaign runs gates-off (SweepRunner default),
            # so the telemetry oracle's skip_* series must too
            seq = Simulator(sc_j, job.trace, phase_gate=False,
                            mem_gate_bytes=0, telemetry=tel_sv).run()
        else:
            seq = Simulator(sc_j, job.trace).run()
        got = served[job.job_id]
        failures += _compare(f"serve {job.job_id} vs sequential",
                             got.results, seq)
        if job.telemetry is not None:
            ok = (got.telemetry.n_total == seq.telemetry.n_total
                  and np.array_equal(got.telemetry.data,
                                     seq.telemetry.data))
            print(f"{f'serve {job.job_id} timeline vs sequential':44} "
                  f"{'PASS' if ok else 'FAIL'}")
            failures += 0 if ok else 1
    c = svc.counters
    ok = (c["compile_count"] == 2 and c["cache_hits"] == 2
          and c["failed"] == 0
          and len({b.n_tiles for b in svc.batch_log}) == 2)
    print(f"{'serve 2 classes, 1 compile each':44} "
          f"{'PASS' if ok else 'FAIL'}"
          + ("" if ok else f"  (compiles={c['compile_count']} "
             f"hits={c['cache_hits']} failed={c['failed']})"))
    failures += 0 if ok else 1

    # 9) observability (round 14): the SAME mixed-geometry job set with
    #    span tracing + host metrics ON and the energy_pj telemetry
    #    series priced onto the t8 jobs — SimResults bit-equal to the
    #    rung-8 untraced run (tracing/metrics are host-side, energy is
    #    pure observability on device), every submitted job's span
    #    chain terminal-complete, the energy column equal to the
    #    hand-priced sum of the run's own counters, and both exporters'
    #    output parsing back.
    import io as _io

    from graphite_tpu.obs import EnergyPrices, parse_exposition
    from graphite_tpu.obs.trace import job_breakdown, load_jsonl

    prices = EnergyPrices(
        instruction_pj=3, l1d_access_pj=2, l2_access_pj=9,
        l2_miss_pj=120, invalidation_pj=15, eviction_pj=20,
        dram_access_pj=500, packet_pj=7)
    tel_e = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=32,
                          energy_prices=prices)
    svc9 = CampaignService(batch_size=2, max_quanta=200_000,
                           tracing=True)
    jobs9 = []
    for i, s in enumerate((1, 2, 3)):
        jobs9.append(Job(f"t4-{i}", sc4, _mkt(4, s), seed=s))
        jobs9.append(Job(f"t8-{i}", sc8, _mkt(8, s), seed=s,
                         telemetry=tel_e))
    for job in jobs9:
        svc9.submit(job)
    served9 = {r.job_id: r for r in svc9.drain()}
    for job in jobs9:
        failures += _compare(f"traced serve {job.job_id} vs untraced",
                             served9[job.job_id].results,
                             served[job.job_id].results)
    for i in range(3):
        r9 = served9[f"t8-{i}"]
        res = r9.results
        mc = res.mem_counters
        exp = (3 * int(res.total_instructions)
               + 7 * int(np.sum(res.packets_sent))
               + 2 * int(sum(mc[k].sum() for k in (
                   "l1d_read_hits", "l1d_read_misses",
                   "l1d_write_hits", "l1d_write_misses")))
               + 9 * int(mc["l2_hits"].sum() + mc["l2_misses"].sum())
               + 120 * int(mc["l2_misses"].sum())
               + 15 * int(mc["invalidations"].sum())
               + 20 * int(mc["evictions"].sum())
               + 500 * int(mc["dram_reads"].sum()
                           + mc["dram_writes"].sum()))
        got = int(r9.telemetry.col("energy_pj").sum())
        ok = got == exp
        print(f"{f'serve t8-{i} energy_pj vs hand-priced sum':44} "
              f"{'PASS' if ok else 'FAIL'}"
              + ("" if ok else f"  (got {got}, expected {exp})"))
        failures += 0 if ok else 1
    missing = svc9.tracer.missing_terminal([j.job_id for j in jobs9])
    print(f"{'serve span set terminal-complete':44} "
          f"{'PASS' if not missing else 'FAIL'}"
          + ("" if not missing else f"  (missing: {missing})"))
    failures += 1 if missing else 0
    buf = _io.StringIO()
    n_spans = svc9.export_spans(buf)
    buf.seek(0)
    rows = load_jsonl(buf)
    bd = {r["job"] for r in job_breakdown(rows)}
    ok = (len(rows) == n_spans and n_spans > 0
          and bd == {j.job_id for j in jobs9})
    print(f"{'serve span JSON-lines export round-trip':44} "
          f"{'PASS' if ok else 'FAIL'}")
    failures += 0 if ok else 1
    snap = parse_exposition(svc9.metrics.exposition())
    ok = (snap["queue_dwell_seconds"]["type"] == "histogram"
          and snap["queue_dwell_seconds"]["count"] == len(jobs9)
          and snap["jobs_completed_total"]["value"] == len(jobs9)
          and snap["compiles_total"]["value"] == 2)
    print(f"{'serve metrics exposition parses':44} "
          f"{'PASS' if ok else 'FAIL'}"
          + ("" if ok else f"  ({snap.get('queue_dwell_seconds')})"))
    failures += 0 if ok else 1

    # 10) spatial profiler (round 16, obs/profile.py): recording the
    #     per-tile [S, T, m] ring must leave SimResults bit-identical
    #     (gated + ungated), the B=4 campaign must demux per-sim
    #     per-tile rows equal to sequential profile runs, and — the
    #     free cross-ring invariant — a run carrying BOTH rings on one
    #     sampling cursor must have every shared delta series sum over
    #     T to exactly the round-9 scalar column, with
    #     max(clock_skew) + clock_min == clock_max sample for sample.
    from graphite_tpu.obs import ProfileSpec

    prof = ProfileSpec(sample_interval_ps=1_000_000, n_samples=64)
    for gate, label in ((True, "gated"), (False, "ungated")):
        r_prof = Simulator(sc_b, batch, phase_gate=gate,
                           mem_gate_bytes=0, profile=prof).run()
        r_off = Simulator(sc_b, batch, phase_gate=gate,
                          mem_gate_bytes=0).run()
        failures += _compare(f"profile on vs off ({label} MSI, 16t)",
                             r_prof, r_off)
    sweep_prof = SweepRunner(sc_b, sweep_traces, profile=prof)
    out_prof = sweep_prof.run()
    for b, s in enumerate(seeds):
        solo = Simulator(sc_b, sweep_traces[b],
                         mailbox_depth=sweep_prof.mailbox_depth,
                         phase_gate=False, mem_gate_bytes=0,
                         profile=prof).run().profile
        pf = out_prof.profiles[b]
        ok = (pf.n_total == solo.n_total
              and np.array_equal(pf.data, solo.data)
              and np.array_equal(pf.times_ps, solo.times_ps))
        print(f"{f'sweep B=4 sim {b} profile vs sequential':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1
    # both rings on one cursor, energy priced on BOTH (one shared
    # ladder — obs/telemetry.tile_energy_pj — so energy_pj is part of
    # the cross-ring sum invariant, not just the unit test)
    tel_x = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=64,
                          energy_prices=prices)
    prof_x = ProfileSpec(sample_interval_ps=1_000_000, n_samples=64,
                         energy_prices=prices)
    r_both = Simulator(sc_b, batch, phase_gate=False, mem_gate_bytes=0,
                       telemetry=tel_x, profile=prof_x).run()
    pf, tl = r_both.profile, r_both.telemetry
    ok = pf.n_total == tl.n_total \
        and np.array_equal(pf.times_ps, tl.col("time_ps"))
    for s in ("instructions", "packets_sent", "sync_stall_ps",
              "l2_misses", "invalidations", "evictions", "energy_pj"):
        ok = ok and np.array_equal(pf.col(s).sum(axis=1), tl.col(s))
    ok = ok and np.array_equal(
        pf.col("clock_skew_ps").max(axis=1) + tl.col("clock_min_ps"),
        tl.col("clock_max_ps"))
    print(f"{'cross-ring: per-tile sums == scalar series':44} "
          f"{'PASS' if ok else 'FAIL'}")
    failures += 0 if ok else 1

    # 11) persistent AOT program store (round 17, store/): the rung-8
    #     mixed-geometry job set served through a store-backed service
    #     must be bit-identical to the in-memory serve, a SECOND
    #     service over the same store must warm-start with ZERO
    #     compiles (fleet-once compilation), and `tools/store.py
    #     verify` must exit 0 on the populated store and 1 after
    #     deliberate corruption.
    import shutil as _sh
    import tempfile as _tf

    from graphite_tpu.store import ProgramStore
    from graphite_tpu.tools.store import main as store_main

    store_dir = _tf.mkdtemp(prefix="graphite-regress-store-")
    try:
        def _mkjobs():
            out = []
            for i, s in enumerate((1, 2, 3)):
                out.append(Job(f"t4-{i}", sc4, _mkt(4, s), seed=s))
                out.append(Job(f"t8-{i}", sc8, _mkt(8, s), seed=s,
                               telemetry=tel_sv))
            return out

        svc_st = CampaignService(batch_size=2, max_quanta=200_000,
                                 store=store_dir)
        for job in _mkjobs():
            svc_st.submit(job)
        served_st = {r.job_id: r for r in svc_st.drain()}
        for jid, ref in served.items():
            got = served_st[jid]
            failures += _compare(f"store serve {jid} vs in-memory",
                                 got.results, ref.results)
            if ref.telemetry is not None:
                ok = (got.telemetry.n_total == ref.telemetry.n_total
                      and np.array_equal(got.telemetry.data,
                                         ref.telemetry.data))
                print(f"{f'store serve {jid} timeline':44} "
                      f"{'PASS' if ok else 'FAIL'}")
                failures += 0 if ok else 1
        c_st = svc_st.counters
        ok = (c_st["compile_count"] == 2 and c_st["store_fills"] == 2
              and c_st["store_hits"] == 0
              and c_st["store_integrity"] == 0)
        print(f"{'store cold start: 2 compiles, 2 fills':44} "
              f"{'PASS' if ok else 'FAIL'}"
              + ("" if ok else f"  (compiles={c_st['compile_count']} "
                 f"fills={c_st['store_fills']} "
                 f"hits={c_st['store_hits']} "
                 f"integ={c_st['store_integrity']})"))
        failures += 0 if ok else 1

        svc_w = CampaignService(batch_size=2, max_quanta=200_000,
                                store=store_dir)
        n_warm = svc_w.warm_start()
        for job in _mkjobs():
            svc_w.submit(job)
        served_w = {r.job_id: r for r in svc_w.drain()}
        for jid, ref in served.items():
            failures += _compare(f"warm-start serve {jid} vs in-memory",
                                 served_w[jid].results, ref.results)
        c_w = svc_w.counters
        ok = (n_warm == 2 and c_w["compile_count"] == 0
              and c_w["store_hits"] == 2 and c_w["store_misses"] == 0
              and c_w["store_integrity"] == 0)
        print(f"{'store warm start: 0 compiles, 2 hits':44} "
              f"{'PASS' if ok else 'FAIL'}"
              + ("" if ok else f"  (warm={n_warm} "
                 f"compiles={c_w['compile_count']} "
                 f"hits={c_w['store_hits']} "
                 f"integ={c_w['store_integrity']})"))
        failures += 0 if ok else 1

        rc_clean = store_main(["--store", store_dir, "verify"])
        print(f"{'tools/store.py verify (sound store) == 0':44} "
              f"{'PASS' if rc_clean == 0 else 'FAIL'}")
        failures += 0 if rc_clean == 0 else 1
        import os as _os
        row = ProgramStore(store_dir).entries()[0]
        pbin = _os.path.join(store_dir, "entries", row["entry_id"],
                             "program.bin")
        with open(pbin, "rb") as fh:
            pb = fh.read()
        with open(pbin, "wb") as fh:
            fh.write(pb[:64] + bytes([pb[64] ^ 0xFF]) + pb[65:])
        rc_bad = store_main(["--store", store_dir, "verify"])
        print(f"{'tools/store.py verify (corrupted) == 1':44} "
              f"{'PASS' if rc_bad == 1 else 'FAIL'}")
        failures += 0 if rc_bad == 1 else 1
    finally:
        _sh.rmtree(store_dir, ignore_errors=True)

    # 12) 2D batch x tile campaigns (round 18): the Mesh(('batch',
    #     'tile')) program on forced host devices must be bit-identical
    #     — results, demuxed timelines AND per-tile profile rings — to
    #     the 1D batch-axis layout and to sequential solo runs on the
    #     same job set, and the admission controller must bin-pack a
    #     sim too big for one device's budget ACROSS devices (admitted
    #     as 2D, per-device block <= budget) where a 1-device service
    #     rejects it.  Needs >= 4 devices: run in-process when the
    #     platform has them, else re-exec this rung under
    #     XLA_FLAGS=--xla_force_host_platform_device_count=4.
    import jax as _jax

    if len(_jax.devices()) >= 4:
        failures += smoke_mesh2d(tiles)
    else:
        import os as _os
        import subprocess as _sp

        env = dict(_os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()
        rc = _sp.call([sys.executable, "-m",
                       "graphite_tpu.tools.regress", "--smoke-mesh2d",
                       "--tiles", str(tiles)], env=env)
        print(f"{'mesh2d rung (forced 4-device subprocess)':44} "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        failures += 0 if rc == 0 else 1

    # 13) runtime DVFS manager (round 19, dvfs/): (a) attaching a
    #     DvfsSpec at the config's own domain frequencies must be
    #     bit-identical to the constant-folded engines — gated +
    #     ungated MSI and the B=4 campaign (carried frequency is
    #     mechanism, not policy); (b) an in-trace DVFS_SET retune must
    #     match the hand-stepped golden interpreter exactly — clocks,
    #     instruction counts, rejected-set counters — across an
    #     up-retune, a down-retune, a rejected request and per-tile
    #     divergence; (c) the reactive governor is deterministic: two
    #     fresh engines agree bit-for-bit on results AND on the final
    #     per-domain V/f state.
    from graphite_tpu.dvfs import DvfsSpec, GovernorSpec

    dv0 = DvfsSpec()
    for gate, label in ((True, "gated"), (False, "ungated")):
        r_dv = Simulator(sc, batch, phase_gate=gate, mem_gate_bytes=0,
                         dvfs=dv0).run()
        r_ref = Simulator(sc, batch, phase_gate=gate,
                          mem_gate_bytes=0).run()
        failures += _compare(f"dvfs at config freq vs folded ({label})",
                             r_dv, r_ref)
    out_dv = SweepRunner(sc, sweep_traces, dvfs=dv0).run()
    for b, s in enumerate(seeds):
        failures += _compare(f"dvfs-off sweep B=4 sim {b} vs plain",
                             out_dv.results[b], out.results[b])

    from graphite_tpu.golden.interpreter import run_golden
    from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder

    sc_dv = SimConfig(ConfigFile.from_string("""
[general]
total_cores = 2
mode = lite
max_frequency = 2.0
technology_node = 22
[dvfs]
synchronization_delay = 2
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE>, \
<1.0, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>"
[network]
user = magic
memory = magic
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax
"""))

    def _dv_builders():
        b0 = TraceBuilder()
        for _ in range(4):
            b0.instr(Op.IALU)
        b0.dvfs_set(0, 2000)            # AUTO up-retune
        for _ in range(4):
            b0.instr(Op.IALU)
        b1 = TraceBuilder()
        b1.dvfs_set(0, 500)             # AUTO down-retune
        b1.dvfs_set(0, 5000)            # above table max: rejected
        for _ in range(3):
            b1.instr(Op.IALU)
        return [b0, b1]

    batch_dv = TraceBatch.from_builders(_dv_builders())
    sim_dv = Simulator(sc_dv, batch_dv)
    r_eng = sim_dv.run()
    g = run_golden(sc_dv, batch_dv)
    ok = (np.array_equal(np.asarray(r_eng.clock_ps), g.clock_ps)
          and np.array_equal(np.asarray(r_eng.instruction_count),
                             g.instruction_count)
          and np.array_equal(np.asarray(sim_dv.state.dvfs.errors),
                             g.dvfs_errors))
    print(f"{'in-trace DVFS_SET vs golden oracle':44} "
          f"{'PASS' if ok else 'FAIL'}")
    failures += 0 if ok else 1

    gv = DvfsSpec(governor=GovernorSpec(interval_ps=2000, domains=(0,)))
    gov_runs = []
    for _ in range(2):
        sim_g = Simulator(sc_dv, TraceBatch.from_builders(_dv_builders()),
                          dvfs=gv)
        r_g = sim_g.run()
        gov_runs.append((r_g, np.asarray(sim_g.state.dvfs_rt.domain_mhz),
                         np.asarray(sim_g.state.dvfs_rt.domain_mv)))
    failures += _compare("governor determinism (results)",
                         gov_runs[0][0], gov_runs[1][0])
    ok = (np.array_equal(gov_runs[0][1], gov_runs[1][1])
          and np.array_equal(gov_runs[0][2], gov_runs[1][2]))
    print(f"{'governor determinism (final V/f state)':44} "
          f"{'PASS' if ok else 'FAIL'}")
    failures += 0 if ok else 1

    # 14) bounded model checking (round 20, analysis/protocol.py): the
    #     2-tile/1-line MSI and MOSI explorations must exhaust with
    #     ZERO invariant violations, every explored transition must
    #     replay bit-equal through the vectorized engine
    #     (differential mode — the checker attests the SHIPPED
    #     kernels), and the seeded 'mosi-owner-skips-wb' mutant must
    #     be caught with a named data-value counterexample (the
    #     checker's own self-test: a mutant that explores clean means
    #     the rung lost its teeth).
    from graphite_tpu.analysis import protocol as _P

    for proto in ("msi", "mosi"):
        res = _P.explore(proto, 2, 1)
        ok = res.ok and res.states_explored > 0
        print(f"{f'model check {proto} 2t/1l exhaustive':44} "
              f"{'PASS' if ok else 'FAIL'}"
              + ("" if ok else
                 f"  ({[v.invariant for v in res.violations]})"))
        failures += 0 if ok else 1
        if ok:
            d = _P.differential(res)
            ok = d.ok and d.n_ok == res.transitions
            print(f"{f'differential replay {proto} ({d.n_ok} trans)':44} "
                  f"{'PASS' if ok else 'FAIL'}")
            failures += 0 if ok else 1

    mres = _P.explore("mosi", 2, 1, mutant="mosi-owner-skips-wb")
    ok = (not mres.ok
          and any(v.invariant == "data-value" for v in mres.violations))
    print(f"{'mutant self-test names data-value':44} "
          f"{'PASS' if ok else 'FAIL'}")
    failures += 0 if ok else 1

    # 15) latency histograms (round 21, obs/hist.py): a dense device-
    #     resident histogram recording must leave every SimResults
    #     field bit-identical (gated + ungated — the hist=None
    #     off-identity's runtime twin), every histogram total must
    #     bit-equal its paired cumulative counter (the conservation
    #     invariant, on every config this rung runs), the B=4
    #     campaign's demuxed hists must equal sequential solo
    #     recordings bucket-for-bucket, and the unified --perfetto
    #     export must load back as valid JSON with monotone per-track
    #     stamps.
    import json as _json
    import os as _os
    import tempfile as _tf2

    from graphite_tpu.obs import HistSpec, conservation_totals
    from graphite_tpu.tools import report as _report

    hspec = HistSpec()
    hist_ref = None
    for gate, label in ((True, "gated"), (False, "ungated")):
        sim_h = Simulator(sc_b, batch, phase_gate=gate, mem_gate_bytes=0,
                          hist=hspec)
        r_h = sim_h.run()
        r_off = Simulator(sc_b, batch, phase_gate=gate,
                          mem_gate_bytes=0).run()
        failures += _compare(f"hist on vs off ({label} MSI, 16t)",
                             r_h, r_off)
        cons = conservation_totals(
            r_h.hist, r_h, protocol=sim_h.params.mem.protocol)
        ok = (all(a == b for a, b in cons.values())
              and any(a > 0 for a, _ in cons.values()))
        print(f"{f'hist conservation ({label}, {len(cons)} src)':44} "
              f"{'PASS' if ok else 'FAIL'}"
              + ("" if ok else f"  ({cons})"))
        failures += 0 if ok else 1
        hist_ref = r_h.hist
    sweep_h = SweepRunner(sc_b, sweep_traces, hist=hspec)
    out_h = sweep_h.run()
    proto_h = sweep_h.sim.params.mem.protocol
    for b, s in enumerate(seeds):
        solo = Simulator(sc_b, sweep_traces[b],
                         mailbox_depth=sweep_h.mailbox_depth,
                         phase_gate=False, mem_gate_bytes=0,
                         hist=hspec).run()
        hb = out_h.hists[b]
        cons = conservation_totals(hb, out_h.results[b],
                                   protocol=proto_h)
        ok = (np.array_equal(hb.counts, solo.hist.counts)
              and hb.boundaries == solo.hist.boundaries
              and all(a == c for a, c in cons.values()))
        print(f"{f'sweep B=4 sim {b} hist vs sequential':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1
    with _tf2.TemporaryDirectory() as td:
        hp = _os.path.join(td, "hist.npz")
        hist_ref.save(hp)
        outp = _os.path.join(td, "trace.json")
        n_ev = _report.write_perfetto(outp, hists=[hp])
        with open(outp) as fh:
            doc = _json.load(fh)
        evs = doc.get("traceEvents", [])
        ok = n_ev == len(evs) and n_ev > 2
        last = {}
        for e in evs:
            if e["ph"] == "M":
                continue
            ok = ok and e["ts"] >= last.get(e["pid"], 0)
            last[e["pid"]] = e["ts"]
        print(f"{'perfetto export valid JSON + monotone':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1

    # 16) collective/ICI traffic analyzer (round 22, analysis/comms.py):
    #     the comms audit must exit 0 over the registered mesh programs
    #     — every collective a whitelisted px packed exchange, every
    #     declared-replicated shard_map output provably uniform, the
    #     per-phase collective tables emitted — and the known-bad
    #     legacy unpacked-exchange fixture must trip the
    #     gspmd-insertion lint (exit 1, the stray's phase named).  Both
    #     run under the same forced-4-host-device re-exec recipe as
    #     rung 12 so the audit sees a real multi-device platform.
    import os as _os16
    import subprocess as _sp16

    env16 = dict(_os16.environ)
    env16["JAX_PLATFORMS"] = "cpu"
    flags16 = env16.get("XLA_FLAGS", "")
    env16["XLA_FLAGS"] = (
        flags16 + " --xla_force_host_platform_device_count=4").strip()
    rc = _sp16.call(
        [sys.executable, "-m", "graphite_tpu.tools.audit",
         "--programs", "sweep-b4-2d,gated-msi-2d", "--comms"],
        env=env16, stdout=_sp16.DEVNULL)
    print(f"{'comms audit (mesh programs, forced 4-dev)':44} "
          f"{'PASS' if rc == 0 else 'FAIL'}")
    failures += 0 if rc == 0 else 1
    rc = _sp16.call(
        [sys.executable, "-m", "graphite_tpu.tools.audit",
         "--comms-fixture"], env=env16, stdout=_sp16.DEVNULL)
    print(f"{'gspmd-insertion fixture exits 1':44} "
          f"{'PASS' if rc == 1 else 'FAIL'}")
    failures += 0 if rc == 1 else 1

    print(f"{failures} failure(s)  ({_t.perf_counter() - t0:.0f}s)")
    return 1 if failures else 0


def smoke_mesh2d(tiles: int = 16) -> int:
    """Regress rung 12 (round 18): 2D batch x tile campaign equality +
    across-device admission, on >= 4 (forced host) devices."""
    import time as _t

    import jax
    import numpy as np

    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.obs import ProfileSpec, TelemetrySpec
    from graphite_tpu.serve import CampaignService, Job
    from graphite_tpu.sweep import SweepRunner
    from graphite_tpu.trace import synthetic

    t0 = _t.perf_counter()
    failures = 0
    n_dev = len(jax.devices())
    if n_dev < 4:
        print(f"{'mesh2d rung':44} FAIL  (needs >= 4 devices, have "
              f"{n_dev})")
        return 1
    # every tile count this rung uses must split 2 ways
    tiles = tiles if tiles % 2 == 0 else 16
    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier")))
    seeds = (1, 2, 3, 4)
    traces = [
        synthetic.memory_stress_trace(
            tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=s)
        for s in seeds
    ]
    tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=64)
    prof = ProfileSpec(sample_interval_ps=1_000_000, n_samples=64)
    # gating forced OFF uniformly so the 2D (vmapped cells), 1D-batch
    # (one gated sim per device) and solo programs record identical
    # skip_* telemetry columns — gating is mechanism, results are
    # bit-identical either way (rung 1)
    gate_kw = dict(phase_gate=False, mem_gate_bytes=0)

    r2d = SweepRunner(sc, traces, layout=(2, 2), telemetry=tel,
                      profile=prof, **gate_kw)
    out2d = r2d.run(max_quanta=200_000)
    r1d = SweepRunner(sc, traces, layout="batch", telemetry=tel,
                      profile=prof, **gate_kw)
    out1d = r1d.run(max_quanta=200_000)
    print(f"{'mesh2d layouts':44} 2d={out2d.layout} 1d={out1d.layout}")
    for b, s in enumerate(seeds):
        solo = Simulator(sc, traces[b], mailbox_depth=r2d.mailbox_depth,
                         telemetry=tel, profile=prof, **gate_kw).run()
        failures += _compare(
            f"2D campaign sim {b} (seed {s}) vs solo",
            out2d.results[b], solo)
        failures += _compare(
            f"2D campaign sim {b} vs 1D-batch",
            out2d.results[b], out1d.results[b])
        tl, pf = out2d.timelines[b], out2d.profiles[b]
        ok = (tl.n_total == solo.telemetry.n_total
              and np.array_equal(tl.data, solo.telemetry.data))
        print(f"{f'2D sim {b} timeline demux vs solo':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1
        ok = (pf.n_total == solo.profile.n_total
              and np.array_equal(pf.data, solo.profile.data)
              and np.array_equal(pf.times_ps, solo.profile.times_ps))
        print(f"{f'2D sim {b} profile ring demux vs solo':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1
        ok = (out1d.timelines[b].n_total == tl.n_total
              and np.array_equal(out1d.timelines[b].data, tl.data)
              and np.array_equal(out1d.profiles[b].data, pf.data))
        print(f"{f'2D sim {b} rings vs 1D-batch':44} "
              f"{'PASS' if ok else 'FAIL'}")
        failures += 0 if ok else 1

    # across-device admission: a sim whose per-sim bill exceeds one
    # device's budget is REJECTED by a 1-device service and ADMITTED
    # as a 2D class (per-device block proven <= budget) by one that
    # may bin-pack across devices — results still bit-equal to solo
    from graphite_tpu.analysis.cost import ResidencyBudgetError
    from graphite_tpu.serve.admission import measure_job

    sc_big = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax")))
    big_jobs = [Job(f"big-{i}", sc_big, traces[i], seed=seeds[i])
                for i in range(2)]
    m = measure_job(big_jobs[0], mailbox_depth=8, pad_length=64)
    budget = (m.per_sim_total + m.device_block(2)["total"]) // 2
    try:
        CampaignService(batch_size=2, max_quanta=200_000,
                        hbm_budget_bytes=budget).submit(big_jobs[0])
        print(f"{'1-device service rejects the big sim':44} FAIL")
        failures += 1
    except ResidencyBudgetError:
        print(f"{'1-device service rejects the big sim':44} PASS")
    svc = CampaignService(batch_size=2, max_quanta=200_000,
                          hbm_budget_bytes=budget, n_devices="auto")
    for j in big_jobs:
        svc.submit(j)
    served = {r.job_id: r for r in svc.drain()}
    cls = next(iter(svc.admission.classes.values()))
    ok = (cls.tile_shards > 1
          and cls.device_breakdown()["total"] <= budget
          and all(served[j.job_id].status == "ok" for j in big_jobs))
    print(f"{'big sim admitted as 2D, per-device <= budget':44} "
          f"{'PASS' if ok else 'FAIL'}"
          + ("" if ok else f"  (tile_shards={cls.tile_shards} "
             f"per_dev={cls.device_breakdown()['total']} "
             f"budget={budget})"))
    failures += 0 if ok else 1
    for j in big_jobs:
        seq = Simulator(sc_big, j.trace, **gate_kw).run()
        failures += _compare(f"2D-served {j.job_id} vs sequential",
                             served[j.job_id].results, seq)

    print(f"mesh2d: {failures} failure(s)  "
          f"({_t.perf_counter() - t0:.0f}s)")
    return failures


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiles", type=int, default=8)
    ap.add_argument("--quick", action="store_true",
                    help="one representative config per axis instead of "
                    "the cross product")
    ap.add_argument("--smoke", action="store_true",
                    help="fast tier-1 companion: 16-tile gated/ungated "
                    "pair + batched-barrier equivalence on CPU")
    ap.add_argument("--smoke-mesh2d", action="store_true",
                    help="rung 12 alone: 2D batch x tile campaign "
                    "equality + across-device admission (needs >= 4 "
                    "devices; --smoke re-execs this under a forced "
                    "4-device host platform when needed)")
    args = ap.parse_args()

    if args.smoke_mesh2d:
        return 1 if smoke_mesh2d(args.tiles if args.tiles != 8
                                 else 16) else 0

    if args.smoke:
        return smoke(args.tiles if args.tiles != 8 else 16)

    if args.quick:
        matrix = [
            ("pr_l1_pr_l2_dram_directory_msi", "full_map", "magic",
             "simple", "canneal"),
            ("pr_l1_pr_l2_dram_directory_mosi", "ackwise",
             "emesh_hop_counter", "iocoom", "canneal"),
            ("pr_l1_sh_l2_mesi", "limited_no_broadcast",
             "emesh_hop_by_hop", "simple", "canneal"),
            ("pr_l1_pr_l2_dram_directory_msi", "full_map",
             "emesh_hop_counter", "iocoom", "fft"),
        ]
    else:
        # memory sweep: protocol x scheme (network/core fixed), then
        # network x core (protocol fixed) on the fft kernel, then the
        # full 13-kernel SPLASH-2/PARSEC roster under the default config
        # (the reference's regress runs every SPLASH-2 app —
        # `tools/regress/run_tests.py:44-58`)
        from graphite_tpu.trace.benchmarks import BENCHMARKS

        matrix = [(p, s, "magic", "simple", "canneal")
                  for p, s in itertools.product(PROTOCOLS, SCHEMES)]
        matrix += [("pr_l1_pr_l2_dram_directory_msi", "full_map", n, c,
                    "fft")
                   for n, c in itertools.product(NETWORKS, CORES)]
        matrix += [("pr_l1_pr_l2_dram_directory_msi", "full_map",
                    "emesh_hop_counter", "simple", w)
                   for w in sorted(BENCHMARKS)
                   if w not in ("canneal", "fft")]

    failures = 0
    print(f"{'protocol':38} {'scheme':22} {'network':18} {'core':7} "
          f"{'workload':8} {'ns':>10} {'instrs':>10} ok")
    for protocol, scheme, network, core, workload in matrix:
        t0 = time.perf_counter()
        try:
            res = run_one(args.tiles, protocol, scheme, network, core,
                          workload)
            ok = res.func_errors == 0
            failures += 0 if ok else 1
            print(f"{protocol:38} {scheme:22} {network:18} {core:7} "
                  f"{workload:8} {res.completion_time_ps // 1000:>10} "
                  f"{res.total_instructions:>10} "
                  f"{'PASS' if ok else 'FAIL'}  ({time.perf_counter()-t0:.0f}s)")
        except Exception as e:  # noqa: BLE001 — a sweep reports, not raises
            failures += 1
            print(f"{protocol:38} {scheme:22} {network:18} {core:7} "
                  f"{workload:8} {'-':>10} {'-':>10} FAIL  {type(e).__name__}: "
                  f"{str(e)[:80]}")
    print(f"{failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
