"""Configuration-sweep regression driver — the analog of the reference's
`tools/regress/run_tests.py` + `aggregate_results.py` (compile & schedule
SPLASH-2 x machines x modes with config overrides, aggregate results).

Sweeps the model matrix on small traces: caching protocol x directory
scheme x NoC model x core model, replaying a benchmark trace through each,
and prints one result row per config (completion time, instructions,
func_errors).  Exit code is nonzero if any config fails.

Usage:
  python -m graphite_tpu.tools.regress [--tiles 8] [--quick]
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time


from graphite_tpu.tools._template import config_text

PROTOCOLS = (
    "pr_l1_pr_l2_dram_directory_msi",
    "pr_l1_pr_l2_dram_directory_mosi",
    "pr_l1_sh_l2_msi",
    "pr_l1_sh_l2_mesi",
)
SCHEMES = ("full_map", "limited_no_broadcast", "ackwise", "limitless")
NETWORKS = ("magic", "emesh_hop_counter", "emesh_hop_by_hop")
CORES = ("simple", "iocoom")


def run_one(tiles, protocol, scheme, network, core, workload):
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.trace.benchmarks import BENCHMARKS

    shared = workload == "canneal"
    cfg = ConfigFile.from_string(config_text(
        tiles, protocol=protocol, scheme=scheme, network=network,
        core=core, shared_mem=shared))
    if workload == "canneal":
        batch = BENCHMARKS[workload](tiles, footprint_lines=256,
                                     swaps_per_tile=6)
    elif workload == "fft":
        batch = BENCHMARKS[workload](tiles, points_per_tile=32)
    else:
        batch = BENCHMARKS[workload](tiles)
    sim = Simulator(SimConfig(cfg), batch)
    res = sim.run()
    return res


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiles", type=int, default=8)
    ap.add_argument("--quick", action="store_true",
                    help="one representative config per axis instead of "
                    "the cross product")
    args = ap.parse_args()

    if args.quick:
        matrix = [
            ("pr_l1_pr_l2_dram_directory_msi", "full_map", "magic",
             "simple", "canneal"),
            ("pr_l1_pr_l2_dram_directory_mosi", "ackwise",
             "emesh_hop_counter", "iocoom", "canneal"),
            ("pr_l1_sh_l2_mesi", "limited_no_broadcast",
             "emesh_hop_by_hop", "simple", "canneal"),
            ("pr_l1_pr_l2_dram_directory_msi", "full_map",
             "emesh_hop_counter", "iocoom", "fft"),
        ]
    else:
        # memory sweep: protocol x scheme (network/core fixed), then
        # network x core (protocol fixed) on the fft kernel, then the
        # full 13-kernel SPLASH-2/PARSEC roster under the default config
        # (the reference's regress runs every SPLASH-2 app —
        # `tools/regress/run_tests.py:44-58`)
        from graphite_tpu.trace.benchmarks import BENCHMARKS

        matrix = [(p, s, "magic", "simple", "canneal")
                  for p, s in itertools.product(PROTOCOLS, SCHEMES)]
        matrix += [("pr_l1_pr_l2_dram_directory_msi", "full_map", n, c,
                    "fft")
                   for n, c in itertools.product(NETWORKS, CORES)]
        matrix += [("pr_l1_pr_l2_dram_directory_msi", "full_map",
                    "emesh_hop_counter", "simple", w)
                   for w in sorted(BENCHMARKS)
                   if w not in ("canneal", "fft")]

    failures = 0
    print(f"{'protocol':38} {'scheme':22} {'network':18} {'core':7} "
          f"{'workload':8} {'ns':>10} {'instrs':>10} ok")
    for protocol, scheme, network, core, workload in matrix:
        t0 = time.perf_counter()
        try:
            res = run_one(args.tiles, protocol, scheme, network, core,
                          workload)
            ok = res.func_errors == 0
            failures += 0 if ok else 1
            print(f"{protocol:38} {scheme:22} {network:18} {core:7} "
                  f"{workload:8} {res.completion_time_ps // 1000:>10} "
                  f"{res.total_instructions:>10} "
                  f"{'PASS' if ok else 'FAIL'}  ({time.perf_counter()-t0:.0f}s)")
        except Exception as e:  # noqa: BLE001 — a sweep reports, not raises
            failures += 1
            print(f"{protocol:38} {scheme:22} {network:18} {core:7} "
                  f"{workload:8} {'-':>10} {'-':>10} FAIL  {type(e).__name__}: "
                  f"{str(e)[:80]}")
    print(f"{failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
