"""Campaign-service CLI: JSON-lines jobs in -> JSON-lines results out.

The front-end wrapper over `serve.CampaignService`: each input line is
one job spec, each output line one result envelope (emitted as its
batch completes — the stream a long-running caller tails), plus one
trailing summary line with the service counters (queue depth, batch
occupancy, cache hit rate, compile count, jobs/s).

Job-spec line schema (all fields except `id` optional):

  {"id": "j0",                     // job id echoed into the result
   "workload": "memstress",        // memstress | a trace/benchmarks name
   "tiles": 16, "seed": 7,
   "accesses": 24,                 // memstress accesses per tile
   "protocol": "pr_l1_pr_l2_dram_directory_msi",
   "network": "emesh_hop_counter",
   "knobs": {"dram_latency_ns": 120, ...},   // traced sweep knobs
   "clock_scheme": "lax_barrier",  // lax_barrier | lax | lax_p2p
   "telemetry": {"sample_interval_ps": 1000000, "n_samples": 64,
                 // optional energy_pj series: explicit pJ prices, or
                 // {"node_nm": 45} to price via the native power model
                 "energy": {"instruction_pj": 2, "l2_miss_pj": 120}},
   "profile": {"sample_interval_ps": 1000000, "n_samples": 64,
               // optional "series": [...], "energy": {...} — the
               // per-tile spatial profiler ring (obs.ProfileSpec);
               // render results with tools/report.py --heatmap
               "series": ["clock_skew_ps", "l2_misses"]},
   "hist": {"log2_buckets": 32,    // device-resident latency histograms
            // optional "sources": [...], explicit "edges": [...],
            // "per_tile": true, "energy": {...} (obs.HistSpec);
            // persist counts with --hist-out DIR
            "sources": ["miss_lat_ps", "net_lat_ps"]}}

Usage:
  python -m graphite_tpu.tools.serve --jobs jobs.jsonl --budget-bytes 2e9
  cat jobs.jsonl | python -m graphite_tpu.tools.serve --batch-size 8
  python -m graphite_tpu.tools.serve --dryrun    # tiny CPU smoke, no input
  python -m graphite_tpu.tools.serve --jobs jobs.jsonl --store /shared/aot
      # fleet mode (round 17): executables deserialize from / serialize
      # into the shared store, warm-starting from compatible entries —
      # each program class compiles once per FLEET; the summary line's
      # store_hits / store_fills / compile_count report the split
      # (maintain the store with tools/store.py ls|verify|gc|evict)

`--dryrun` pins JAX to CPU and serves a built-in mixed-geometry,
mixed-knob demo job set — the smoke shape `tools/regress.py --smoke`'s
serve rung also exercises.

Observability (round 14): `--trace-out spans.jsonl` records every
job's lifecycle spans (submit → validate → admit → queue dwell →
execute → emit) plus per-batch execution spans and writes them as
JSON-lines (`tools/report.py --spans` renders the per-job latency
breakdown); `--metrics-out metrics.prom` dumps the service's metrics
registry in Prometheus text format (`tools/report.py --metrics`
renders it); the trailing summary line always embeds the JSON metrics
snapshot under "metrics" alongside the round-13 counter keys.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


DRYRUN_JOBS = [
    {"id": "d0", "tiles": 4, "seed": 1, "accesses": 10},
    {"id": "d1", "tiles": 4, "seed": 2, "accesses": 10,
     "knobs": {"dram_latency_ns": 150}},
    {"id": "d2", "tiles": 4, "seed": 3, "accesses": 10},
    {"id": "d3", "tiles": 8, "seed": 4, "accesses": 10},
    {"id": "d4", "tiles": 4, "seed": 5, "accesses": 10,
     "knobs": {"hop_latency_cycles": 3}},
    {"id": "d5", "tiles": 4, "seed": 6, "accesses": 10,
     "telemetry": {"sample_interval_ps": 1_000_000, "n_samples": 16,
                   "energy": {"instruction_pj": 2, "l2_miss_pj": 120,
                              "dram_access_pj": 500}}},
    {"id": "d6", "tiles": 4, "seed": 7, "accesses": 10,
     "profile": {"sample_interval_ps": 1_000_000, "n_samples": 16}},
    {"id": "d7", "tiles": 4, "seed": 8, "accesses": 10,
     "hist": {"log2_buckets": 24}},
]


def build_job(spec: dict, config_cache: dict):
    """One input line -> a serve.Job (config objects cached per
    geometry/protocol/network so same-shaped jobs share a digest-equal
    config and co-batch)."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.obs import TelemetrySpec
    from graphite_tpu.serve import Job
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace import synthetic

    if "id" not in spec:
        raise ValueError("job spec needs an \"id\" field")
    tiles = int(spec.get("tiles", 16))
    workload = spec.get("workload", "memstress")
    seed = int(spec.get("seed", 7))
    protocol = spec.get("protocol", "pr_l1_pr_l2_dram_directory_msi")
    network = spec.get("network", "emesh_hop_counter")
    shared = workload == "memstress"
    ckey = (tiles, protocol, network, shared)
    sc = config_cache.get(ckey)
    if sc is None:
        sc = SimConfig(ConfigFile.from_string(config_text(
            tiles, shared_mem=shared, protocol=protocol,
            network=network, clock_scheme="lax_barrier")))
        config_cache[ckey] = sc
    if workload == "memstress":
        trace = synthetic.memory_stress_trace(
            tiles, n_accesses=int(spec.get("accesses", 24)),
            working_set_bytes=1 << 13, write_fraction=0.4,
            shared_fraction=0.5, seed=seed)
    else:
        from graphite_tpu.trace.benchmarks import BENCHMARKS

        if workload not in BENCHMARKS:
            raise ValueError(
                f"unknown workload {workload!r} (memstress or: "
                f"{', '.join(sorted(BENCHMARKS))})")
        trace = BENCHMARKS[workload](tiles)
    def _prices(t, what):
        if not t.get("energy"):
            return None
        from graphite_tpu.obs import EnergyPrices

        e = t["energy"]
        if not isinstance(e, dict):
            raise ValueError(
                f"{what}.energy must be a dict of pJ prices or "
                '{"node_nm": N} for the native power model')
        if "node_nm" in e:
            return EnergyPrices.from_power_model(
                int(e["node_nm"]), voltage=float(e.get("voltage", 1.0)))
        return EnergyPrices(**e)

    telemetry = None
    if spec.get("telemetry"):
        t = spec["telemetry"]
        telemetry = TelemetrySpec(
            sample_interval_ps=int(t["sample_interval_ps"]),
            n_samples=int(t.get("n_samples", 256)),
            series=tuple(t["series"]) if t.get("series") else None,
            energy_prices=_prices(t, "telemetry"))
    profile = None
    if spec.get("profile"):
        from graphite_tpu.obs import ProfileSpec

        p = spec["profile"]
        profile = ProfileSpec(
            sample_interval_ps=int(p["sample_interval_ps"]),
            n_samples=int(p.get("n_samples", 256)),
            series=tuple(p["series"]) if p.get("series") else None,
            energy_prices=_prices(p, "profile"))
    hist = None
    if spec.get("hist"):
        from graphite_tpu.obs import HistSpec

        h = spec["hist"]
        hist = HistSpec(
            sources=tuple(h["sources"]) if h.get("sources") else None,
            edges=tuple(h["edges"]) if h.get("edges") else None,
            log2_buckets=int(h.get("log2_buckets", 32)),
            per_tile=bool(h.get("per_tile", False)),
            energy_prices=_prices(h, "hist"))
    return Job(job_id=str(spec["id"]), config=sc, trace=trace,
               knobs=dict(spec.get("knobs", {})), telemetry=telemetry,
               profile=profile, hist=hist, seed=seed,
               clock_scheme=spec.get("clock_scheme"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="campaign service: JSON-lines jobs in, JSON-lines "
        "results out")
    ap.add_argument("--jobs", help="job-spec JSON-lines file (default: "
                    "stdin)")
    ap.add_argument("--budget-bytes", type=float, default=0,
                    help="per-device hbm_budget_bytes admission budget "
                    "(0 = off)")
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--cache-bytes", type=float, default=0,
                    help="program-cache eviction budget (0 = unbounded)")
    ap.add_argument("--max-pending", type=int, default=1024)
    ap.add_argument("--max-quanta", type=int, default=1_000_000)
    ap.add_argument("--n-devices", default="1",
                    help="devices admission may bin-pack a too-big-"
                    "for-one-device sim across (the 2D batch x tile "
                    "layout); an integer or 'auto' (visible device "
                    "count).  Default 1 = round-13 single-device "
                    "admission")
    ap.add_argument("--verify-hits", action="store_true",
                    help="re-lower every cache hit and re-prove "
                    "fingerprint equality (retrace, never recompile)")
    ap.add_argument("--store", metavar="DIR",
                    help="persistent AOT program store directory "
                    "(created if absent, shared across a fleet of "
                    "serve processes): compiled executables are "
                    "deserialized from / serialized into it, and the "
                    "service warm-starts from compatible entries "
                    "(maintain with tools/store.py)")
    ap.add_argument("--warm-limit", type=int, default=None,
                    metavar="N",
                    help="stage at most N most-recently-used store "
                    "entries at startup (default: every compatible "
                    "entry; unstaged classes still store-hit lazily)")
    ap.add_argument("--max-dwell-s", type=float, default=0.0,
                    help="let an under-full batch wait up to this long "
                    "for its class to fill before forming (latency/"
                    "occupancy trade; 0 = run immediately)")
    ap.add_argument("--trace-out", metavar="FILE",
                    help="enable span tracing and write job/batch "
                    "lifecycle spans as JSON-lines on exit "
                    "(render: tools/report.py --spans FILE)")
    ap.add_argument("--profile-out", metavar="DIR",
                    help="save each job's per-tile profile as "
                    "DIR/<job_id>.npz (obs.TileProfile.save; the "
                    "result line gains \"profile_file\"; render: "
                    "tools/report.py --heatmap DIR/*.npz)")
    ap.add_argument("--hist-out", metavar="DIR",
                    help="save each job's latency histograms as "
                    "DIR/<job_id>.npz (obs.Hist.save; the result line "
                    "gains \"hist_file\"; merge into a Chrome trace: "
                    "tools/report.py --perfetto out.json --hist "
                    "DIR/*.npz)")
    ap.add_argument("--metrics-out", metavar="FILE",
                    help="write the metrics registry as Prometheus "
                    "text exposition on exit "
                    "(render: tools/report.py --metrics FILE)")
    ap.add_argument("--dryrun", action="store_true",
                    help="CPU smoke: force JAX_PLATFORMS=cpu and serve "
                    "a built-in mixed demo job set")
    args = ap.parse_args(argv)

    if args.dryrun:
        # must land before jax initializes its backends
        os.environ["JAX_PLATFORMS"] = "cpu"

    if args.store is not None:
        # a clean refusal beats a traceback deep inside the store: a
        # path that EXISTS but is not a directory can never hold the
        # entries/ layout (a missing path is first boot — create it)
        if os.path.exists(args.store) and not os.path.isdir(args.store):
            print(f"error: --store {args.store!r} exists and is not a "
                  "directory", file=sys.stderr)
            return 2
        os.makedirs(args.store, exist_ok=True)

    import graphite_tpu  # noqa: F401  (x64)

    from graphite_tpu.analysis.cost import ResidencyBudgetError
    from graphite_tpu.serve import CampaignService, QueueFullError
    from graphite_tpu.trace.validate import TraceValidationError

    failures = 0
    if args.dryrun:
        specs = list(DRYRUN_JOBS)
    else:
        fh = open(args.jobs) if args.jobs else sys.stdin
        specs = []
        for lineno, line in enumerate(fh, 1):
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            try:
                specs.append(json.loads(line))
            except ValueError as e:
                # one bad line rejects that line, never the stream
                failures += 1
                print(json.dumps({"line": lineno, "status": "rejected",
                                  "error": f"bad JSON: {e}"}))
        if args.jobs:
            fh.close()

    if args.n_devices != "auto":
        try:
            args.n_devices = int(args.n_devices)
        except ValueError:
            raise SystemExit(
                f"--n-devices must be an integer or 'auto' "
                f"(got {args.n_devices!r})")
    service = CampaignService(
        hbm_budget_bytes=int(args.budget_bytes),
        batch_size=args.batch_size,
        cache_bytes=int(args.cache_bytes),
        max_pending=args.max_pending,
        max_quanta=args.max_quanta,
        verify_hits=args.verify_hits,
        n_devices=args.n_devices,
        tracing=bool(args.trace_out),
        store=args.store,
        max_dwell_s=args.max_dwell_s)
    n_warm = service.warm_start(limit=args.warm_limit)
    if n_warm:
        print(json.dumps({"warm_start": n_warm,
                          "store": args.store}), flush=True)

    config_cache: dict = {}
    t0 = time.perf_counter()

    def emit(res):
        """One result line; --profile-out persists the per-tile ring
        (the envelope only carries a sample count) and names the file
        in the line so the heatmap render is one copy-paste away."""
        row = res.to_json()
        if args.profile_out and res.profile is not None:
            os.makedirs(args.profile_out, exist_ok=True)
            path = os.path.join(args.profile_out, f"{res.job_id}.npz")
            res.profile.save(path)
            row["profile_file"] = path
        if args.hist_out and res.hist is not None:
            os.makedirs(args.hist_out, exist_ok=True)
            path = os.path.join(args.hist_out, f"{res.job_id}.npz")
            res.hist.save(path)
            row["hist_file"] = path
        print(json.dumps(row), flush=True)

    # submit with per-job drain on backpressure: a full queue runs a
    # batch (streaming its results) instead of dropping the job
    for spec in specs:
        try:
            job = build_job(spec, config_cache)
        except (ValueError, KeyError) as e:
            failures += 1
            print(json.dumps({"job": spec.get("id"), "status": "rejected",
                              "error": f"bad spec: {e}"}))
            continue
        while True:
            try:
                service.submit(job)
                break
            except QueueFullError:
                # drain through the dwell policy first (it runs a
                # FULL class while an under-full head ages), forcing
                # only when every class is under-full and held — the
                # queue must shrink for the submit to retry
                ran = False
                for res in service.step():
                    ran = True
                    emit(res)
                if not ran:
                    for res in service.step(force=True):
                        emit(res)
            except (ResidencyBudgetError, TraceValidationError,
                    ValueError) as e:
                failures += 1
                print(json.dumps({"job": job.job_id,
                                  "status": "rejected",
                                  "error": str(e)}))
                break
        if args.max_dwell_s > 0:
            # streaming dwell: run whatever the policy considers
            # ready NOW (a full class, or a head past its window),
            # holding under-full batches for later arrivals — the
            # latency/occupancy dial acting mid-stream, not only at
            # backpressure; with the default 0 the round-13
            # submit-everything-then-drain flow is untouched
            for res in service.step():
                emit(res)
    # input is exhausted: no job can ever fill an under-full batch, so
    # force past any dwell hold instead of sleeping out the window
    for res in service.drain(force=True):
        emit(res)
    counters = service.counters
    failures += counters["failed"]
    if args.trace_out:
        n_spans = service.export_spans(args.trace_out)
        print(json.dumps({"trace_out": args.trace_out,
                          "spans": n_spans}), flush=True)
    if args.metrics_out:
        with open(args.metrics_out, "w") as fh:
            fh.write(service.metrics.exposition())

    def _round(v):
        if isinstance(v, float):
            return round(v, 6)
        if isinstance(v, dict):
            return {k: _round(x) for k, x in v.items()}
        return v

    print(json.dumps({
        "summary": True,
        "wall_s": round(time.perf_counter() - t0, 3),
        **{k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in counters.items()},
        # the registry's JSON snapshot rides the summary line — one
        # artifact holds both the compatibility counters and the
        # histogram summaries (count/sum/p50/p90/p99)
        "metrics": _round(service.metrics.snapshot()),
        "dryrun": bool(args.dryrun),
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
