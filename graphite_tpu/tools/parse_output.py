"""Parse a `sim.out` summary into a nested dict — the analog of the
reference's `tools/parse_output.py` (consumed by the regress aggregation,
`tools/regress/aggregate_results.py`).

Usage: python -m graphite_tpu.tools.parse_output results/sim.out
"""

from __future__ import annotations

import json
import re
import sys


def parse_sim_out(text: str) -> dict:
    """Returns {"target_completion_time_ns", "total_instructions",
    "tiles": {tile_id: {flat summary keys}}}."""
    out: dict = {"tiles": {}}
    tile = None
    section: list[str] = []
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line.strip():
            continue
        m = re.match(r"Target Completion Time \(in nanoseconds\): (\d+)", line)
        if m:
            out["target_completion_time_ns"] = int(m.group(1))
            continue
        m = re.match(r"Total Instructions: (\d+)", line)
        if m and tile is None:
            out["total_instructions"] = int(m.group(1))
            continue
        m = re.match(r"Tile (\d+) Summary:", line)
        if m:
            tile = int(m.group(1))
            out["tiles"][tile] = {}
            section = []
            continue
        if tile is None:
            continue
        indent = len(line) - len(line.lstrip())
        depth = max(0, indent // 2 - 1)
        key_part = line.strip()
        m = re.match(r"(.+?):\s*(-?\d+(?:\.\d+)?)$", key_part)
        if m:
            key, raw_value = m.group(1), m.group(2)
            value = float(raw_value) if "." in raw_value else int(raw_value)
            full = " / ".join(section[:depth] + [key])
            out["tiles"][tile][full] = value
        else:
            header = key_part.rstrip(": ")
            section = section[:depth] + [header]
    return out


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "results/sim.out"
    with open(path) as f:
        parsed = parse_sim_out(f.read())
    json.dump(parsed, sys.stdout, indent=1)
    print()


if __name__ == "__main__":
    main()
