"""Campaign CLI: run a knob-grid x seed sweep as ONE compiled program.

The batched-campaign frontend (sweep/runner.py): a grid spec over timing
knobs crossed with trace seeds becomes a [B]-batched vmapped run — one
XLA compile for the whole campaign, one JSON line per simulation on
stdout, one trailing summary line with campaign throughput (sims/s and
amortized per-sim ms/iteration).

Usage:
  python -m graphite_tpu.tools.sweep --tiles 16 \\
      --knob dram_latency_ns=50,100,200 --knob hop_latency_cycles=1,2
  python -m graphite_tpu.tools.sweep --seeds 1,2,3,4   # trace sweep
  python -m graphite_tpu.tools.sweep --dryrun          # tiny CPU smoke

Knob axes cross-multiply (grid_points); seeds replicate the grid per
trace.  `--dryrun` pins JAX to CPU and shrinks the workload — the
smoke-test shape regress.py --smoke also exercises.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def parse_knob_axes(specs: "list[str]") -> dict:
    """--knob name=v1,v2,... (repeatable) -> {name: [int, ...]}."""
    axes = {}
    for spec in specs:
        if "=" not in spec:
            raise SystemExit(f"--knob {spec!r}: expected name=v1,v2,...")
        name, _, vals = spec.partition("=")
        try:
            axes[name.strip()] = [int(v) for v in vals.split(",") if v.strip()]
        except ValueError:
            raise SystemExit(f"--knob {spec!r}: values must be integers")
        if not axes[name.strip()]:
            raise SystemExit(f"--knob {spec!r}: no values")
    return axes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="batched simulation campaign (one compile, B sims)")
    ap.add_argument("--tiles", type=int, default=16)
    ap.add_argument("--workload", default="memstress",
                    help="memstress (seedable) or a trace/benchmarks name")
    ap.add_argument("--knob", action="append", default=[],
                    metavar="NAME=V1,V2,...",
                    help="knob axis (repeatable; axes cross-multiply)")
    ap.add_argument("--seeds", default="7",
                    help="comma-separated memstress trace seeds")
    ap.add_argument("--accesses", type=int, default=40,
                    help="memstress accesses per tile")
    ap.add_argument("--clock", default="lax_barrier",
                    choices=("lax", "lax_barrier"))
    ap.add_argument("--protocol", default="pr_l1_pr_l2_dram_directory_msi")
    ap.add_argument("--network", default="emesh_hop_counter")
    ap.add_argument("--max-quanta", type=int, default=1_000_000)
    ap.add_argument("--layout", default=None,
                    help="device layout: solo | batch | tile | 2d | "
                    "DBxDT (e.g. 2x2 — batch_shards x tile_shards; "
                    "default: auto from residency + device count)")
    ap.add_argument("--dryrun", action="store_true",
                    help="CPU smoke: force JAX_PLATFORMS=cpu, shrink the "
                    "workload, cap the grid at 4 points")
    args = ap.parse_args(argv)

    if args.dryrun:
        # must land before jax initializes its backends
        os.environ["JAX_PLATFORMS"] = "cpu"
        args.tiles = min(args.tiles, 8)
        args.accesses = min(args.accesses, 16)

    import graphite_tpu  # noqa: F401  (x64)

    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.sweep import SweepRunner, grid_points
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace import synthetic

    axes = parse_knob_axes(args.knob)
    try:
        grid = grid_points(**axes) if axes else [{}]
    except ValueError as e:
        raise SystemExit(f"--knob: {e}")
    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    if args.dryrun:
        grid = grid[:4] if axes else [
            {"dram_latency_ns": 60}, {"dram_latency_ns": 180}]
        seeds = seeds[:2]

    shared = args.workload == "memstress"
    sc = SimConfig(ConfigFile.from_string(config_text(
        args.tiles, shared_mem=shared, protocol=args.protocol,
        network=args.network, clock_scheme=args.clock)))

    def make_trace(seed):
        if args.workload == "memstress":
            return synthetic.memory_stress_trace(
                args.tiles, n_accesses=args.accesses,
                working_set_bytes=1 << 13, write_fraction=0.4,
                shared_fraction=0.5, seed=seed)
        from graphite_tpu.trace.benchmarks import BENCHMARKS

        if args.workload not in BENCHMARKS:
            raise SystemExit(
                f"unknown workload {args.workload!r} (memstress or: "
                f"{', '.join(sorted(BENCHMARKS))})")
        return BENCHMARKS[args.workload](args.tiles)

    # seeds x grid: each seed's trace replicated across the knob grid
    if args.workload != "memstress" and len(seeds) > 1:
        raise SystemExit("--seeds applies to the memstress workload only")
    from graphite_tpu.sweep import pack_traces

    traces, points, meta = [], [], []
    for s in seeds:
        tr = make_trace(s)
        for p in grid:
            traces.append(tr)
            points.append(p)
            meta.append(s)

    layout = args.layout
    if layout and "x" in layout:
        try:
            db, dt = (int(v) for v in layout.split("x"))
        except ValueError:
            raise SystemExit(
                f"bad --layout {layout!r}: DBxDT needs two integers")
        layout = (db, dt)
    runner = SweepRunner(sc, pack_traces(traces, seeds=meta), points,
                         layout=layout)
    t0 = time.perf_counter()
    out = runner.run(max_quanta=args.max_quanta)
    elapsed = time.perf_counter() - t0
    for row in out.json_rows():
        print(json.dumps(row))
    total_iters = int(out.n_iterations.sum())
    print(json.dumps({
        "summary": True,
        "sweep_batch": runner.n_sims,
        "layout": out.layout,
        "wall_s": round(elapsed, 3),
        "sims_per_s": round(runner.n_sims / elapsed, 3),
        # amortized per-sim cost of one engine iteration: campaign wall
        # over the total useful iterations served across the batch
        "ms_per_iter_amortized": round(1000 * elapsed / max(total_iters, 1),
                                       4),
        "dryrun": bool(args.dryrun),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
