"""Measure multi-device step wall-clock vs single-device (virtual mesh).

VERDICT/PERF follow-up: `parallel/mesh.py` replicates the sync tables and
`func_mem` and relies on whole-program GSPMD — the concern is that mailbox
scatters and replicated-buffer updates lower to cross-device collectives
that make the 8-device step *slower* than one device.  Real ICI speedups
cannot be measured on one chip; what a virtual CPU mesh CAN measure is
pathology: if the 8-device program is catastrophically slower than the
single-device program on identical hardware resources, the sharded lowering
is broken.  Run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m graphite_tpu.tools.shard_bench

Output is JSON lines in bench.py's field convention — one row per
workload with {"metric", "value", "unit", "vs_baseline"} plus
companions: the single-device and GSPMD wall-clocks, and the STATIC
collective counts of the packed-exchange lowering (analysis/comms.py
over a SweepRunner tile-axis lowering of the same config —
`collectives_per_iter` / `ici_bytes_per_iter` / stray count), so every
measured number sits next to the collective budget that explains it.
`vs_baseline` is the shard_map/single wall ratio: ~1 means the sharded
lowering costs what the math costs; GSPMD's ~10x is the pathology the
packed exchange exists to avoid.

With fewer than 2 visible devices the bench emits a single
{"skipped": true, "reason": ...} row and exits 0 — the measured
comparison needs a mesh, and a silent half-run would look like data.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def _timed(sc, batch, mesh, repeats=3, spmd=None):
    """Best-of-N wall-clock of the compiled run, compile excluded: warm up
    and time the SAME Simulator instance (each instance owns its own jitted
    runner), restoring the initial state between repeats."""
    from graphite_tpu.engine.simulator import Simulator

    sim = Simulator(sc, batch, mesh=mesh, spmd=spmd)
    init_state = sim.state
    sim.warmup()
    best = float("inf")
    res = None
    for _ in range(repeats):
        sim.state = init_state
        t0 = time.perf_counter()
        res = sim.run()
        best = min(best, time.perf_counter() - t0)
    return best, res


def _static_comms(sc, batch, n_dev: int) -> dict:
    """The static collective budget of the same config sharded over the
    tile axis: lower a (1, n_dev) batch x tile campaign of `batch` over
    a device-less AbstractMesh (no devices consumed — pure tracing) and
    run the comms extractor over its main loop.  These are the numbers
    BUDGETS.json ratchets for the registered mesh programs, computed
    here for the BENCHED shape so the measured ratio sits next to the
    collective count that explains it."""
    from graphite_tpu.analysis import comms
    from graphite_tpu.analysis.audit import spec_from_sweep
    from graphite_tpu.sweep import SweepRunner

    runner = SweepRunner(sc, [batch], layout=(1, n_dev))
    spec = spec_from_sweep("shard-bench", runner, 4096)
    rep = comms.comms_report(spec)
    return {
        "static_collectives_per_iter": int(rep.collectives_per_iter),
        "static_ici_bytes_per_iter": int(rep.ici_bytes_per_iter),
        "static_stray_collectives": len(rep.strays()),
    }


def main() -> int:
    # the ambient TPU-tunnel sitecustomize can override JAX_PLATFORMS at
    # interpreter startup; flip it back (same recipe as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())
    if n_dev < 2:
        print(json.dumps({
            "skipped": True,
            "reason": f"needs a multi-device platform (found {n_dev} "
            f"device); run with JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8",
            "metric": "multi-device step wall-clock"}))
        return 0

    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.parallel.mesh import make_tile_mesh
    from graphite_tpu.tools._template import (
        coherence_stress_workload, config_text,
    )
    from graphite_tpu.trace import synthetic

    mesh = make_tile_mesh(n_dev)
    rows = []

    # workload 1: full-MSI coherence stress (the [T, T] mailbox path)
    sc, batch = coherence_stress_workload(64, n_accesses=200)
    t1, r1 = _timed(sc, batch, None)
    tsm, rsm = _timed(sc, batch, mesh)  # shard_map (default)
    np.testing.assert_array_equal(r1.clock_ps, rsm.clock_ps)
    tg, rg = _timed(sc, batch, mesh, spmd="gspmd")
    np.testing.assert_array_equal(r1.clock_ps, rg.clock_ps)
    rows.append(("msi_stress_64t", sc, batch, t1, tsm, tg))

    # workload 2: memoryless message ring (the USER-net mailbox path)
    sc2 = SimConfig(ConfigFile.from_string(config_text(64)))
    batch2 = synthetic.message_ring_batch(64, n_rounds=64,
                                          compute_per_round=8)
    t1b, _ = _timed(sc2, batch2, None)
    tsmb, _ = _timed(sc2, batch2, mesh)
    tgb, _ = _timed(sc2, batch2, mesh, spmd="gspmd")
    rows.append(("ring_64t", sc2, batch2, t1b, tsmb, tgb))

    # workload 3: shared-L2 coherence stress — round 5 put the shL2
    # engines on the packed exchange; its multi-device overhead should
    # sit near the MSI program's, not GSPMD's ~10x
    sc3, batch3 = coherence_stress_workload(
        64, n_accesses=200, protocol="pr_l1_sh_l2_msi")
    t1c, r1c = _timed(sc3, batch3, None)
    tsmc, rsmc = _timed(sc3, batch3, mesh)
    np.testing.assert_array_equal(r1c.clock_ps, rsmc.clock_ps)
    tgc, rgc = _timed(sc3, batch3, mesh, spmd="gspmd")
    np.testing.assert_array_equal(r1c.clock_ps, rgc.clock_ps)
    rows.append(("shl2_stress_64t", sc3, batch3, t1c, tsmc, tgc))

    for name, wsc, wbatch, single, sharded, gspmd in rows:
        print(json.dumps({
            "metric": f"multi-device step wall-clock ({name}, "
            f"{n_dev} dev shard_map)",
            "value": round(sharded * 1e3, 1),
            "unit": "ms",
            "vs_baseline": round(sharded / single, 4),
            "single_ms": round(single * 1e3, 1),
            "gspmd_ms": round(gspmd * 1e3, 1),
            "gspmd_vs_single": round(gspmd / single, 4),
            "devices": n_dev,
            **_static_comms(wsc, wbatch, n_dev),
        }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
