"""Measure multi-device step wall-clock vs single-device (virtual mesh).

VERDICT/PERF follow-up: `parallel/mesh.py` replicates the sync tables and
`func_mem` and relies on whole-program GSPMD — the concern is that mailbox
scatters and replicated-buffer updates lower to cross-device collectives
that make the 8-device step *slower* than one device.  Real ICI speedups
cannot be measured on one chip; what a virtual CPU mesh CAN measure is
pathology: if the 8-device program is catastrophically slower than the
single-device program on identical hardware resources, the sharded lowering
is broken.  Run:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m graphite_tpu.tools.shard_bench

Prints one line per (workload, devices) with wall-clock and the
sharded/single ratio.
"""

from __future__ import annotations

import time

import jax
import numpy as np


def _timed(sc, batch, mesh, repeats=3, spmd=None):
    """Best-of-N wall-clock of the compiled run, compile excluded: warm up
    and time the SAME Simulator instance (each instance owns its own jitted
    runner), restoring the initial state between repeats."""
    from graphite_tpu.engine.simulator import Simulator

    sim = Simulator(sc, batch, mesh=mesh, spmd=spmd)
    init_state = sim.state
    sim.warmup()
    best = float("inf")
    res = None
    for _ in range(repeats):
        sim.state = init_state
        t0 = time.perf_counter()
        res = sim.run()
        best = min(best, time.perf_counter() - t0)
    return best, res


def main():
    # the ambient TPU-tunnel sitecustomize can override JAX_PLATFORMS at
    # interpreter startup; flip it back (same recipe as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 2, (
        "needs a multi-device platform: run with JAX_PLATFORMS=cpu "
        "XLA_FLAGS=--xla_force_host_platform_device_count=8")

    from graphite_tpu.parallel.mesh import make_tile_mesh
    from graphite_tpu.tools._template import coherence_stress_workload, config_text
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.trace import synthetic

    n_dev = len(jax.devices())
    mesh = make_tile_mesh(n_dev)
    results = []

    # workload 1: full-MSI coherence stress (the [T, T] mailbox path)
    sc, batch = coherence_stress_workload(64, n_accesses=200)
    t1, r1 = _timed(sc, batch, None)
    tsm, rsm = _timed(sc, batch, mesh)  # shard_map (default)
    np.testing.assert_array_equal(r1.clock_ps, rsm.clock_ps)
    tg, rg = _timed(sc, batch, mesh, spmd="gspmd")
    np.testing.assert_array_equal(r1.clock_ps, rg.clock_ps)
    results.append(("msi_stress_64t", t1, tsm, tg))

    # workload 2: memoryless message ring (the USER-net mailbox path)
    sc2 = SimConfig(ConfigFile.from_string(config_text(64)))
    batch2 = synthetic.message_ring_batch(64, n_rounds=64,
                                          compute_per_round=8)
    t1b, _ = _timed(sc2, batch2, None)
    tsmb, _ = _timed(sc2, batch2, mesh)
    tgb, _ = _timed(sc2, batch2, mesh, spmd="gspmd")
    results.append(("ring_64t", t1b, tsmb, tgb))

    # workload 3: shared-L2 coherence stress — round 5 put the shL2
    # engines on the packed exchange; its multi-device overhead should
    # sit near the MSI program's, not GSPMD's ~10x
    sc3, batch3 = coherence_stress_workload(
        64, n_accesses=200, protocol="pr_l1_sh_l2_msi")
    t1c, r1c = _timed(sc3, batch3, None)
    tsmc, rsmc = _timed(sc3, batch3, mesh)
    np.testing.assert_array_equal(r1c.clock_ps, rsmc.clock_ps)
    tgc, rgc = _timed(sc3, batch3, mesh, spmd="gspmd")
    np.testing.assert_array_equal(r1c.clock_ps, rgc.clock_ps)
    results.append(("shl2_stress_64t", t1c, tsmc, tgc))

    for name, a, b, c in results:
        print(f"{name}: single={a*1e3:.0f} ms  "
              f"{n_dev}dev shard_map={b*1e3:.0f} ms ({b/a:.2f}x)  "
              f"{n_dev}dev gspmd={c*1e3:.0f} ms ({c/a:.2f}x)")
    return results


if __name__ == "__main__":
    main()
