"""Shared config-INI template for the tools' drivers (regress, graduated).

One source of truth for the sweep/benchmark configuration surface so knob
changes land in every driver at once.
"""

from __future__ import annotations


def config_text(tiles: int, *, core: str = "simple",
                network: str = "emesh_hop_counter",
                shared_mem: bool = False,
                protocol: str = "pr_l1_pr_l2_dram_directory_msi",
                scheme: str = "full_map", max_hw_sharers: int = 2,
                clock_scheme: str = "lax_barrier",
                dvfs: bool = False) -> str:
    dvfs_section = """
[dvfs]
technology_node = 22
max_frequency = 1.0
synchronization_delay = 2
[dvfs/domains]
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>"
""" if dvfs else ""
    return f"""
[general]
total_cores = {tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = {"true" if shared_mem else "false"}
[tile]
model_list = <{tiles}, {core}>
[caching_protocol]
type = {protocol}
[dram_directory]
directory_type = {scheme}
max_hw_sharers = {max_hw_sharers}
[network]
user = {network}
memory = {network}
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[network/emesh_hop_by_hop]
flit_width = 64
[network/emesh_hop_by_hop/router]
delay = 1
num_flits_per_port_buffer = 4
[network/emesh_hop_by_hop/link]
delay = 1
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
falu = 3
fmul = 5
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = {clock_scheme}
[clock_skew_management/lax_barrier]
quantum = 1000
{dvfs_section}
"""


def coherence_stress_workload(n_tiles: int, *, n_accesses: int = 40,
                              protocol: str =
                              "pr_l1_pr_l2_dram_directory_msi"):
    """The shared cross-shard coherence attestation workload: one config +
    trace used by BOTH the sharding test matrix (tests/test_sharding.py)
    and the driver's multichip dryrun (__graft_entry__.py), so the two
    cannot drift apart.  shared_fraction drives cross-tile (and, sharded,
    cross-device) protocol traffic: line homes stripe over ALL tiles
    (`dram/num_controllers` ALL), so requests/replies/invalidations cross
    every shard cut.  Returns (SimConfig, TraceBatch)."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.trace import synthetic

    sc = SimConfig(ConfigFile.from_string(config_text(
        n_tiles, shared_mem=True, protocol=protocol, clock_scheme="lax")))
    batch = synthetic.memory_stress_trace(
        n_tiles, n_accesses=n_accesses, working_set_bytes=1 << 13,
        write_fraction=0.4, shared_fraction=0.5, seed=7)
    return sc, batch
