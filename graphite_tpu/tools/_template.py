"""Shared config-INI template for the tools' drivers (regress, graduated).

One source of truth for the sweep/benchmark configuration surface so knob
changes land in every driver at once.
"""

from __future__ import annotations


def config_text(tiles: int, *, core: str = "simple",
                network: str = "emesh_hop_counter",
                shared_mem: bool = False,
                protocol: str = "pr_l1_pr_l2_dram_directory_msi",
                scheme: str = "full_map", max_hw_sharers: int = 2,
                clock_scheme: str = "lax_barrier",
                dvfs: bool = False) -> str:
    dvfs_section = """
[dvfs]
technology_node = 22
max_frequency = 1.0
synchronization_delay = 2
[dvfs/domains]
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>"
""" if dvfs else ""
    return f"""
[general]
total_cores = {tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = {"true" if shared_mem else "false"}
[tile]
model_list = <{tiles}, {core}>
[caching_protocol]
type = {protocol}
[dram_directory]
directory_type = {scheme}
max_hw_sharers = {max_hw_sharers}
[network]
user = {network}
memory = {network}
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[network/emesh_hop_by_hop]
flit_width = 64
[network/emesh_hop_by_hop/router]
delay = 1
num_flits_per_port_buffer = 4
[network/emesh_hop_by_hop/link]
delay = 1
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
falu = 3
fmul = 5
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = {clock_scheme}
[clock_skew_management/lax_barrier]
quantum = 1000
{dvfs_section}
"""
