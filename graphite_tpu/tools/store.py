"""Program-store maintenance CLI: ls / verify / gc / evict.

The operator's view of a fleet's shared AOT program store
(`graphite_tpu/store/`): list what is cached (and how stale), audit
integrity without quarantining, reclaim bytes, and drop entries by
hand.

Usage:
  python -m graphite_tpu.tools.store --store DIR ls [--json]
  python -m graphite_tpu.tools.store --store DIR verify [--json]
  python -m graphite_tpu.tools.store --store DIR gc --max-bytes 2e9 \
      [--purge-corrupt] [--json]
  python -m graphite_tpu.tools.store --store DIR evict ENTRY_ID

Exit codes: `verify` exits 1 when ANY entry fails its audit (including
previously quarantined `.corrupt-*` dirs — a store that has seen
corruption audits loudly until the wreckage is gc'd with
`--purge-corrupt`); `evict` exits 1 when the entry does not exist;
everything else exits 0 on success.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _age(now: float, then: float) -> str:
    d = max(0.0, now - then)
    for unit, width in (("d", 86400.0), ("h", 3600.0), ("m", 60.0)):
        if d >= width:
            return f"{d / width:.1f}{unit}"
    return f"{d:.0f}s"


def cmd_ls(store, args) -> int:
    rows = store.entries(include_corrupt=True)
    if args.json:
        for r in rows:
            man = r["manifest"] or {}
            print(json.dumps({
                "entry_id": r["entry_id"], "corrupt": r["corrupt"],
                "bytes": r["bytes"], "last_used": r["last_used"],
                "name": man.get("name"), "batch": man.get("batch"),
                "max_quanta": man.get("max_quanta"),
                "fingerprint": man.get("fingerprint"),
                "env": man.get("env"),
                "compile_s": man.get("compile_s"),
            }))
        return 0
    now = time.time()
    print(f"{'entry':42} {'name':34} {'B':>3} {'bytes':>12} "
          f"{'used':>8} fingerprint")
    for r in rows:
        man = r["manifest"] or {}
        tag = r["entry_id"]    # quarantined rows carry .corrupt-<n>
        fp = (man.get("fingerprint") or "?")[:22]
        name = (man.get("name") or
                ("(corrupt)" if r["corrupt"] else "?"))[:34]
        used = "-" if r["corrupt"] else _age(now, r["last_used"])
        print(f"{tag:42} {name:34} {man.get('batch', '-'):>3} "
              f"{r['bytes']:>12} {used:>8} {fp}")
    s = store.stats()
    print(f"{s['entries']} entr{'y' if s['entries'] == 1 else 'ies'}, "
          f"{s['bytes']} bytes, {s['corrupt']} quarantined")
    return 0


def cmd_verify(store, args) -> int:
    findings = store.verify()
    bad = 0
    for f in findings:
        if args.json:
            print(json.dumps(f))
        else:
            status = "PASS" if f["ok"] else f"FAIL ({f['reason']})"
            print(f"{f['entry_id']:60} {status}")
            if not f["ok"] and f["message"]:
                print(f"    {f['message']}")
        bad += 0 if f["ok"] else 1
    if not args.json:
        print(f"{len(findings)} entr{'y' if len(findings) == 1 else 'ies'}"
              f", {bad} failure(s)")
    return 1 if bad else 0


def cmd_gc(store, args) -> int:
    budget = int(args.max_bytes) if args.max_bytes is not None else None
    if budget is not None and budget <= 0:
        # the store layer reads 0 as "unbounded" (the constructor's
        # no-budget convention) — an operator typing 0 means "empty
        # it", which gc never does (the MRU entry always survives):
        # refuse loudly instead of silently evicting nothing
        print("error: --max-bytes must be positive (gc always keeps "
              "the most-recently-used entry; --max-bytes 1 evicts "
              "down to it, `evict ENTRY_ID` deletes by hand)",
              file=sys.stderr)
        return 2
    evicted = store.gc(budget, include_corrupt=args.purge_corrupt)
    out = {"evicted": evicted, "entries": store.stats()["entries"],
           "bytes": store.total_bytes}
    print(json.dumps(out) if args.json else
          f"evicted {len(evicted)} entr"
          f"{'y' if len(evicted) == 1 else 'ies'}; "
          f"{out['entries']} remain ({out['bytes']} bytes)")
    return 0


def cmd_evict(store, args) -> int:
    ok = store.evict(args.entry_id)
    print(json.dumps({"evicted": args.entry_id, "ok": ok}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="AOT program-store maintenance (ls / verify / gc / "
        "evict)")
    ap.add_argument("--store", required=True, metavar="DIR",
                    help="the store directory (as passed to "
                    "tools/serve.py --store)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON lines instead of the "
                    "aligned table")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("ls", help="list entries (incl. quarantined)")
    sub.add_parser("verify", help="audit every entry; exit 1 on any "
                   "failure (non-quarantining)")
    gc = sub.add_parser("gc", help="evict LRU entries to a byte budget")
    gc.add_argument("--max-bytes", type=float, default=None,
                    help="positive byte budget to evict down to "
                    "(default: keep everything valid; the most-"
                    "recently-used entry always survives)")
    gc.add_argument("--purge-corrupt", action="store_true",
                    help="also delete quarantined .corrupt-* dirs")
    ev = sub.add_parser("evict", help="delete one entry by id")
    ev.add_argument("entry_id")
    args = ap.parse_args(argv)

    import os

    from graphite_tpu.store import ProgramStore

    if not os.path.isdir(args.store):
        print(f"error: --store {args.store!r} is not a directory",
              file=sys.stderr)
        return 2
    store = ProgramStore(args.store)
    return {"ls": cmd_ls, "verify": cmd_verify, "gc": cmd_gc,
            "evict": cmd_evict}[args.cmd](store, args)


if __name__ == "__main__":
    sys.exit(main())
