"""Bounded model checker CLI: exhaust the coherence protocols.

Extracts the transition relation from the golden interpreters
(`analysis/protocol.py`) and enumerates EVERY reachable
(directory-entry, per-tile L1/L2 line-state, data-freshness)
configuration of a small geometry — 2-4 tiles, 1-2 lines — for the
MSI, MOSI, and shared-L2 MESI protocols, checking the classic
invariants at every quiescent state and inside every transition:

  single-writer-multiple-reader   one M/E holder, no concurrent S
  data-value                      a read returns the last write
  directory-cache-agreement       dir entry == the caches' truth
  bounded-in-flight               request fan-out stays bounded
  progress                        every access quiesces in bounded
                                  events; no deadlock/livelock

A violation prints a named counterexample: the access path from reset
plus the violating transition's event sequence, rendered through the
round-6 phase names (home_start/sharer/home_finish/...), then exits
nonzero.

Differential mode (on by default) closes the loop with the SHIPPED
kernels: every explored transition is replayed one access at a time
through the vectorized engines (`memory/engine.py`,
`memory/engine_shl2.py`) at the same geometry, asserting the golden
clock, every memory counter, and the full per-line cache/directory
census are bit-equal — the checker verifies the compiled engines, not
just the oracle.

`--mutant` is the CI self-test (mirroring audit's
`--regression-fixture`/`--lock-fixture`): it checks a deliberately
broken transition relation — by default `mosi-owner-skips-wb`, the
MOSI owner acking a writeback-fwd without supplying data — and MUST
exit nonzero naming the violated invariant.  A mutant that explores
clean means the checker lost its teeth.

Output is JSON lines: one `mc` line per (protocol, geometry), one
`violation` line per counterexample (with the rendered trace), one
`diff` line per differential replay, then one trailing overall line.
Exit code 0 iff every exploration and replay is clean (so `--mutant`
exits 1 on success-of-the-self-test).

Usage:
  python -m graphite_tpu.tools.mc [--protocols msi,mosi,shl2_mesi]
                                  [--tiles N] [--lines N]
                                  [--no-differential] [--max-quanta N]
                                  [--max-states N] [--mutant [NAME]]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="exhaustive coherence model checking over the "
        "golden transition relation + differential replay through the "
        "vectorized engines")
    ap.add_argument("--protocols", default=None,
                    help="comma-separated subset of msi,mosi,shl2_mesi "
                    "(default: all three)")
    ap.add_argument("--tiles", type=int, default=2,
                    help="tile count of the checked geometry (2-4; "
                    "state count grows fast)")
    ap.add_argument("--lines", type=int, default=1,
                    help="number of distinct cache lines (1-2; all "
                    "map to the same set so they contend)")
    ap.add_argument("--max-states", type=int, default=50000,
                    help="exploration bound — exceeding it is a "
                    "progress violation, not silent truncation")
    ap.add_argument("--no-differential", action="store_true",
                    help="skip the vectorized-engine replay (pure "
                    "golden-model exploration; much faster)")
    ap.add_argument("--max-quanta", type=int, default=4096,
                    help="quantum bound for each replayed trace")
    ap.add_argument("--mutant", nargs="?", const="mosi-owner-skips-wb",
                    default=None, metavar="NAME",
                    help="CI self-test: explore the named broken "
                    "transition relation (default "
                    "'mosi-owner-skips-wb') — MUST find a violation "
                    "and exit nonzero naming the invariant")
    args = ap.parse_args(argv)

    # model checking is host-side; the differential replay jits tiny
    # 2-4 tile programs — never touch a real chip
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import graphite_tpu  # noqa: F401  (x64)

    from graphite_tpu.analysis import protocol as P

    names = list(P.PROTOCOLS)
    if args.protocols:
        names = [s.strip() for s in args.protocols.split(",")
                 if s.strip()]
        unknown = [n for n in names if n not in P.PROTOCOLS]
        if unknown:
            ap.error(f"unknown protocol(s) {unknown} "
                     f"(choose from {', '.join(P.PROTOCOLS)})")
    if args.mutant is not None:
        if args.mutant not in P.MUTANT_NAMES:
            ap.error(f"unknown mutant {args.mutant!r} "
                     f"(choose from {', '.join(P.MUTANT_NAMES)})")
        # every registered mutant breaks a private-L2 protocol; the
        # self-test pins the protocol the mutation is meaningful for
        names = ["mosi"]

    t0 = time.perf_counter()
    ok = True
    n_violations = 0
    for name in names:
        res = P.explore(name, args.tiles, args.lines,
                        mutant=args.mutant,
                        max_states=args.max_states)
        print(json.dumps({
            "mc": True, "protocol": name, "mutant": args.mutant,
            "tiles": args.tiles, "lines": list(res.lines),
            "states_explored": res.states_explored,
            "transitions": res.transitions,
            "histogram": res.histogram,
            "fan_in": res.fan_in,
            "max_in_flight": res.max_in_flight,
            "violations": len(res.violations),
            "ok": res.ok}))
        for v in res.violations:
            n_violations += 1
            print(json.dumps({
                "violation": True, "protocol": name,
                "mutant": args.mutant, "invariant": v.invariant,
                "message": v.message,
                "path": [str(a) for a in v.path],
                "events": list(v.events),
                "counterexample": v.render()}))
            print(f"counterexample ({name}"
                  + (f", mutant {args.mutant}" if args.mutant else "")
                  + f"):\n{v.render()}", file=sys.stderr)
        ok = ok and res.ok
        if res.ok and not args.no_differential \
                and args.mutant is None:
            d = P.differential(res, max_quanta=args.max_quanta)
            print(json.dumps({
                "diff": True, "protocol": name,
                "n_transitions": d.n_transitions, "n_ok": d.n_ok,
                "mismatches": d.mismatches[:8], "ok": d.ok}))
            ok = ok and d.ok

    print(json.dumps({
        "overall": True, "ok": ok, "mutant": args.mutant,
        "protocols": names, "violations": n_violations,
        "wall_s": round(time.perf_counter() - t0, 1)}))
    if args.mutant is not None and ok:
        # the self-test's failure mode: the broken relation explored
        # clean, so the checker would not catch a real regression
        print(f"mutant {args.mutant!r} explored CLEAN — the checker "
              f"failed to detect the seeded bug", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
