"""Capture a REAL program execution as a trace and calibrate the skeletons.

This is not a synthetic generator: `run_fft_app` EXECUTES a parallel
radix-2 decimation-in-time FFT — real butterflies over real data in
16.16 fixed point, partitioned across Carbon threads with a barrier per
stage — under the live-recording Carbon API (the reference analog is
capturing a real binary under Pin, `pin/instruction_modeling.cc`).
Every arithmetic operation is recorded as an instruction record and
every element access goes through `carbon_load`/`carbon_store` with its
true address, so the replay drives the full cache/coherence stack with
the program's actual sharing pattern (adjacent elements share cache
lines across tile-partition boundaries).

The captured run is validated two ways:
 - functionally on replay: stage reads are barrier-separated
   single-writer, so they carry FLAG_CHECK — the coherence engine must
   reproduce every loaded value (func_errors == 0);
 - numerically at capture: the fixed-point result must match numpy.fft
   within fixed-point tolerance.

`measured_mix` then reports the real per-butterfly instruction mix, the
calibration source for the `fft_trace` skeleton (see PERF.md
"Trace-capture calibration").

Usage:  python -m graphite_tpu.tools.capture_fft [out.npz]
"""

from __future__ import annotations

import math

import numpy as np

FX = 16  # 16.16 fixed point


def _fx(x: float) -> int:
    return int(round(x * (1 << FX)))


def _fxmul(a: int, b: int) -> int:
    return (a * b) >> FX


def _w32(v: int) -> int:
    return ((v & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000


def run_fft_app(n_tiles: int = 4, n_points: int = 128, seed: int = 9):
    """Execute the parallel FFT under the recording API.

    Returns (TraceBatch, input_complex, output_complex) — the recorded
    trace plus the program's actual numeric input/output for the
    numerical check."""
    from graphite_tpu.frontend import carbon_api as capi
    from graphite_tpu.tools.capture import make_app, run_threads

    N = n_points
    stages = int(math.log2(N))
    assert 1 << stages == N, "n_points must be a power of 2"
    BASE = 0x100000

    def re_addr(i):
        return BASE + 8 * i

    def im_addr(i):
        return BASE + 8 * i + 4

    rng = np.random.default_rng(seed)
    # small integer inputs (exact in fixed point): butterfly magnitudes
    # grow up to 2^stages-fold, and intermediate values must stay inside
    # int32 after the 16-bit scale — |x| < 16 keeps N <= 2048 safe
    x = (rng.integers(-15, 16, size=N).astype(np.int64) << FX)
    x_c = x.astype(np.float64) / (1 << FX)

    # twiddles in fixed point (the app's own constant table — computed
    # once, like the reference FFT's twiddle array)
    wre = [_fx(math.cos(-2 * math.pi * k / N)) for k in range(N // 2)]
    wim = [_fx(math.sin(-2 * math.pi * k / N)) for k in range(N // 2)]

    def worker(tile, bar):
        # stage -1: bit-reverse permuted input, tile-partitioned writes
        bits = stages
        for i in range(tile, N, n_tiles):
            r = int(f"{i:0{bits}b}"[::-1], 2)
            capi.carbon_instr()  # index arithmetic (bit reverse)
            capi.carbon_store(re_addr(i), _w32(int(x[r])))
            capi.carbon_store(im_addr(i), 0)
        bar.wait()
        # butterfly stages: tile t owns butterflies t, t+T, t+2T, ...
        for s in range(stages):
            half = 1 << s
            step = N // (2 * half)
            bidx = 0
            for g in range(0, N, 2 * half):
                for j in range(half):
                    if bidx % n_tiles == tile:
                        a, b = g + j, g + j + half
                        tw_r, tw_i = wre[j * step], wim[j * step]
                        capi.carbon_instr()   # a index
                        capi.carbon_instr()   # b index / twiddle index
                        ar = capi.carbon_load(re_addr(a), check=True)
                        ai = capi.carbon_load(im_addr(a), check=True)
                        br = capi.carbon_load(re_addr(b), check=True)
                        bi = capi.carbon_load(im_addr(b), check=True)
                        ar, ai, br, bi = (_w32(v) for v in
                                          (ar, ai, br, bi))
                        # complex mul t = w * b: 4 FMUL + 2 FALU
                        for _ in range(4):
                            capi.carbon_instr(capi.Op.FMUL)
                        tr = _fxmul(tw_r, br) - _fxmul(tw_i, bi)
                        ti = _fxmul(tw_r, bi) + _fxmul(tw_i, br)
                        for _ in range(2):
                            capi.carbon_instr(capi.Op.FALU)
                        # butterfly add/sub: 4 FALU
                        for _ in range(4):
                            capi.carbon_instr(capi.Op.FALU)
                        capi.carbon_store(re_addr(a), _w32(ar + tr))
                        capi.carbon_store(im_addr(a), _w32(ai + ti))
                        capi.carbon_store(re_addr(b), _w32(ar - tr))
                        capi.carbon_store(im_addr(b), _w32(ai - ti))
                    bidx += 1
            bar.wait()

    app = make_app(n_tiles)
    batch = run_threads(app, worker, n_tiles)

    # the program's actual output, from the functional store
    out = np.empty(N, np.complex128)
    for i in range(N):
        r = _w32(app._memory.get(re_addr(i), 0))
        im = _w32(app._memory.get(im_addr(i), 0))
        out[i] = complex(r, im) / (1 << FX)
    return batch, x_c, out


def verify_numerics(x_c, out, n_points) -> float:
    """Max relative error of the captured run vs numpy.fft."""
    ref = np.fft.fft(x_c)
    scale = max(1.0, float(np.abs(ref).max()))
    return float(np.abs(out - ref).max() / scale)


# shared with the generalized harness (tools/capture.py) — re-exported
# so existing callers keep working
from graphite_tpu.tools.capture import measured_mix  # noqa: E402


def main(out_path: str = "fft_captured.npz",
         n_tiles: int = 4, n_points: int = 128) -> dict:
    from graphite_tpu.tools.capture import replay_report

    batch, x_c, out = run_fft_app(n_tiles, n_points)
    err = verify_numerics(x_c, out, n_points)
    report = replay_report(batch, n_tiles, out_path)
    mix = report["mix"]
    stages = int(math.log2(n_points))
    butterflies = (n_points // 2) * stages
    report.update(
        numeric_max_rel_err=err,
        fp_per_butterfly=(mix["fmul"] + mix["falu"]) / butterflies,
        mem_refs_per_butterfly=(mix["loads"] + mix["stores"])
        / butterflies,
    )
    return report


if __name__ == "__main__":
    import json
    import sys

    path = sys.argv[1] if len(sys.argv) > 1 else "fft_captured.npz"
    print(json.dumps(main(path), indent=1))
