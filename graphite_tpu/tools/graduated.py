"""Run the BASELINE.json graduated configs end to end and report each.

The five configs scale the stack up exactly as BASELINE.json lists them:
 1. 16-tile default (simple core, emesh_hop_counter), ping_pong
 2. 64-tile iocoom + pr_l1_pr_l2_dram_directory_msi, SPLASH-2 FFT
 3. 256-tile emesh_hop_by_hop (finite-buffer contention), SPLASH-2 RADIX
 4. 1024-tile mesh sharded over the device mesh, PARSEC blackscholes
 5. 1024-tile + DVFS + power modeling, PARSEC canneal

Usage: python -m graphite_tpu.tools.graduated [--only N] [--small]
  --small scales tile counts down 4x for quick CPU validation.

Prints one line per config: completion time, instructions, wall seconds,
aggregate simulated instr/s.
"""

from __future__ import annotations

import argparse
import sys
import time


from graphite_tpu.tools._template import config_text


def _cfg(tiles, core="simple", network="emesh_hop_counter",
         shared_mem=False, protocol="pr_l1_pr_l2_dram_directory_msi",
         dvfs=False):
    return config_text(tiles, core=core, network=network,
                       shared_mem=shared_mem, protocol=protocol,
                       scheme="full_map", dvfs=dvfs)


def run_config(n: int, small: bool):
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.trace import synthetic
    from graphite_tpu.trace.benchmarks import (
        blackscholes_trace, canneal_trace, fft_trace, radix_trace,
    )

    scale = 4 if small else 1
    if n == 1:
        tiles = 16 // scale if small else 16
        sc = SimConfig(ConfigFile.from_string(_cfg(tiles)))
        batch = synthetic.ping_pong_trace(tiles)
        label = f"{tiles}-tile simple/hop-counter ping_pong"
    elif n == 2:
        tiles = 64 // scale
        sc = SimConfig(ConfigFile.from_string(
            _cfg(tiles, core="iocoom", shared_mem=True)))
        batch = fft_trace(tiles, points_per_tile=64 if small else 256,
                          use_memory=True)
        label = f"{tiles}-tile iocoom+MSI FFT"
    elif n == 3:
        tiles = 256 // scale
        sc = SimConfig(ConfigFile.from_string(
            _cfg(tiles, network="emesh_hop_by_hop")))
        batch = radix_trace(tiles, keys_per_tile=256 if small else 1024)
        label = f"{tiles}-tile hop-by-hop RADIX"
    elif n == 4:
        tiles = 1024 // scale
        sc = SimConfig(ConfigFile.from_string(_cfg(tiles)))
        batch = blackscholes_trace(
            tiles, options_per_tile=128 if small else 2048)
        # shard the tile axis over every available device (ICI mesh); on
        # one chip this is the degenerate 1-device mesh, and the driver's
        # dryrun_multichip validates the multi-device path on a CPU mesh
        from graphite_tpu.parallel.mesh import make_tile_mesh

        mesh = make_tile_mesh()
        label = (f"{tiles}-tile sharded blackscholes "
                 f"({mesh.devices.size}-device mesh)")
        return label, Simulator(sc, batch, mesh=mesh)
    elif n == 5:
        tiles = 1024 // scale
        text = _cfg(tiles, shared_mem=True, dvfs=True)
        # canneal carries no CAPI sends, so the single-region
        # lax_barrier program compiles and runs device-driven at 1024
        # tiles (round-5 retest); SEND-carrying traces at this scale
        # auto-select the host-driven barrier loop instead
        # (Simulator.barrier_host).  Either way: the reference's default
        # scheme, no substitution.
        sc = SimConfig(ConfigFile.from_string(text))
        batch = canneal_trace(tiles, footprint_lines=4096,
                              swaps_per_tile=8 if small else 16)
        label = f"{tiles}-tile +DVFS+power canneal"
        return label, Simulator(sc, batch)
    else:
        raise SystemExit(f"no config {n}")
    return label, Simulator(sc, batch)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", type=int, default=0)
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--in-process", action="store_true",
                    help="run all configs in this process instead of one "
                    "subprocess each (subprocesses isolate TPU-client "
                    "faults: the tunnel can return UNAVAILABLE to a client "
                    "starting immediately after another exits)")
    args = ap.parse_args()

    if not args.only and not args.in_process:
        import subprocess
        import time as _t

        failures = 0
        for n in (1, 2, 3, 4, 5):
            for attempt in (1, 2):
                p = subprocess.run(
                    [sys.executable, "-m", "graphite_tpu.tools.graduated",
                     "--only", str(n)] + (["--small"] if args.small else []),
                    capture_output=True, text=True)
                out = p.stdout.strip().splitlines()
                transient = "UNAVAILABLE" in (p.stderr or "")
                if p.returncode == 0 or not transient or attempt == 2:
                    break
                _t.sleep(10)  # let the tunnel release the device, retry
            for line in out:
                # forward result lines AND the per-config JSON line
                # (phase-skip observability) to the captured output
                if line.startswith(("config", "  ", "{")):
                    print(line)
            if p.returncode != 0:
                failures += 1
                err = (p.stderr or "").strip().splitlines()
                print(f"config {n}: FAIL "
                      f"({err[-1][:120] if err else 'no stderr'})")
        print(f"{failures} failure(s)")
        return 1 if failures else 0

    import graphite_tpu  # noqa: F401

    failures = 0
    for n in ([args.only] if args.only else [1, 2, 3, 4, 5]):
        label, sim = run_config(n, args.small)
        sim.warmup()
        t0 = time.perf_counter()
        res = sim.run()
        dt = time.perf_counter() - t0
        ok = res.func_errors == 0
        failures += 0 if ok else 1
        print(f"config {n}: {label}: {res.completion_time_ps // 1000} ns, "
              f"{res.total_instructions} instrs, {dt:.2f}s wall, "
              f"{res.total_instructions / dt / 1e6:.2f}M instr/s "
              f"{'PASS' if ok else 'FAIL'}")
        # one machine-readable line per config so BENCH_r{N}-style
        # captures track gate skip rates alongside throughput
        import json

        print(json.dumps({
            "config": n,
            "instr_per_s": round(res.total_instructions / dt),
            "engine_iters": int(sim.last_n_iterations),
            "phase_skips": sim.last_phase_skips,
        }))
        if n == 5:
            # power modeling pass over the final counters (config 5)
            try:
                from graphite_tpu.power.interface import TileEnergyMonitor

                mon = TileEnergyMonitor(sim, res)
                e0 = mon.tile_energy_j(0)
                print(f"  tile 0 energy breakdown keys: "
                      f"{sorted(e0)[:6]} ...")
            except Exception as e:  # noqa: BLE001 — report, don't abort
                print(f"  power pass failed: {type(e).__name__}: {e}")
                failures += 1
    print(f"{failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
