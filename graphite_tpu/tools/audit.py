"""Program auditor CLI: lint the lowered default programs, JSON lines.

Lowers the default config set — the per-phase-GATED private-L2 engine,
the UNGATED one, the shared-L2 engine, the B=4 vmapped sweep campaign,
and the telemetry-recording gated engine — and runs every jaxpr
invariant lint (analysis/rules.py) over each: cond-payload (with the
telemetry ring's aval in the forbidden set for telemetry-on programs),
knob-fold, time-dtype, vmap-gate, host-sync, telemetry-off.  Pure
static analysis over `jax.make_jaxpr` output: no compile, no
execution, runs on CPU-only CI in well under a minute.

Output is JSON lines: one line per finding, then one summary line per
program, then one trailing overall line.  Exit code 0 iff no
error-severity finding fired (`--strict` also fails on warnings).

Usage:
  python -m graphite_tpu.tools.audit [--tiles 8] [--max-cond-bytes N]
                                     [--strict] [--programs a,b,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="jaxpr invariant lints over the default programs")
    ap.add_argument("--tiles", type=int, default=8,
                    help="tile count for the audited geometries (the "
                    "lints are structural; 8 carries the same program "
                    "shape as 1024)")
    ap.add_argument("--max-cond-bytes", type=int, default=None,
                    help="generic cond-payload ceiling in bytes "
                    "(default 64 MiB; directory stores are additionally "
                    "matched by signature at any size)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too (e.g. vmap-gate)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset of program names "
                    "(default: all five)")
    args = ap.parse_args(argv)

    # auditing is host-side static analysis — never touch a real chip
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import graphite_tpu  # noqa: F401  (x64)

    from graphite_tpu.analysis.audit import (
        DEFAULT_MAX_COND_BYTES, audit, default_programs,
    )

    t0 = time.perf_counter()
    names = None
    if args.programs:
        names = [s.strip() for s in args.programs.split(",") if s.strip()]
    try:
        specs = default_programs(args.tiles, names=names)
    except ValueError as e:
        raise SystemExit(str(e))
    report = audit(specs, max_cond_bytes=(
        args.max_cond_bytes if args.max_cond_bytes is not None
        else DEFAULT_MAX_COND_BYTES))

    for f in report.findings:
        print(json.dumps(f.to_json()))
    for row in report.summary_rows():
        print(json.dumps(row))
    ok = report.ok and not (args.strict and report.findings)
    print(json.dumps({
        "overall": True,
        "ok": ok,
        "programs": len(specs),
        "errors": len(report.errors),
        "warnings": len(report.findings) - len(report.errors),
        "wall_s": round(time.perf_counter() - t0, 1),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
