"""Program auditor CLI: lint + cost the lowered default programs.

Lowers the default config set — the per-phase-GATED private-L2 engine,
the UNGATED one, the shared-L2 engine, the B=4 vmapped sweep campaign,
the telemetry-recording gated engine, and the combined sweep+telemetry
campaign — and runs every jaxpr invariant lint (analysis/rules.py) over
each: cond-payload (with the telemetry ring's aval in the forbidden set
for telemetry-on programs), knob-fold, time-dtype, vmap-gate, host-sync,
telemetry-off.  Each program's STATIC COST report (analysis/cost.py —
per-iteration kernel proxy with per-phase attribution, bytes moved,
peak-live residency) is emitted as a JSON line alongside the lint rows.
Pure static analysis over `jax.make_jaxpr` output: no compile, no
execution, runs on CPU-only CI in well under a minute.

`--budget` additionally gates every cost report against the checked-in
BUDGETS.json ceilings (exit nonzero on any excess, the offending
equation named); `--budget-update` refreshes the baselines after an
intentional change.  `--regression-fixture` swaps in the known-bad
inflated-carry program — the gate must trip on it (the CI self-test).

Output is JSON lines: one line per lint finding, one cost line and one
summary line per program, then one trailing overall line.  Exit code 0
iff no error-severity finding fired (`--strict` also fails on warnings).

Usage:
  python -m graphite_tpu.tools.audit [--tiles 8] [--max-cond-bytes N]
                                     [--strict] [--programs a,b,...]
                                     [--budget | --budget-update]
                                     [--budgets-file PATH]
                                     [--regression-fixture]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="jaxpr invariant lints + static cost/budget gates "
        "over the default programs")
    ap.add_argument("--tiles", type=int, default=8,
                    help="tile count for the audited geometries (the "
                    "lints are structural; 8 carries the same program "
                    "shape as 1024)")
    ap.add_argument("--max-cond-bytes", type=int, default=None,
                    help="generic cond-payload ceiling in bytes "
                    "(default 64 MiB; directory stores are additionally "
                    "matched by signature at any size)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too (e.g. vmap-gate)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset of program names "
                    "(default: all six)")
    ap.add_argument("--budget", action="store_true",
                    help="gate each cost report against BUDGETS.json "
                    "ceilings (exit nonzero on any excess)")
    ap.add_argument("--budget-update", action="store_true",
                    help="refresh BUDGETS.json baselines+ceilings from "
                    "this run's measurements (after an INTENTIONAL "
                    "change; merges, so --programs subsets are safe)")
    ap.add_argument("--budgets-file", default=None,
                    help="override the BUDGETS.json path (default: "
                    "repo root)")
    ap.add_argument("--regression-fixture", action="store_true",
                    help="audit the known-bad inflated-carry fixture "
                    "instead of the real gated-msi program — the budget "
                    "gate MUST exit nonzero (CI self-test)")
    args = ap.parse_args(argv)
    if args.budget and args.budget_update:
        ap.error("--budget and --budget-update are mutually exclusive "
                 "(gate against the ceilings OR refresh them, not both)")
    if args.regression_fixture and args.budget_update:
        # the fixture deliberately reuses the real program's name so the
        # gate runs against its checked-in ceilings — writing its
        # inflated measurements back would corrupt the real baseline and
        # turn the CI self-test green on a broken gate
        ap.error("--regression-fixture is a read-only self-test; it "
                 "cannot be combined with --budget-update")
    # the fixture exists only to prove the gate trips — without the gate
    # its lints all pass and the self-test would be vacuously green
    if args.regression_fixture:
        args.budget = True

    # auditing is host-side static analysis — never touch a real chip
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import graphite_tpu  # noqa: F401  (x64)

    from graphite_tpu.analysis import cost
    from graphite_tpu.analysis.audit import (
        DEFAULT_MAX_COND_BYTES, audit, default_programs,
    )

    t0 = time.perf_counter()
    names = None
    if args.programs:
        names = [s.strip() for s in args.programs.split(",") if s.strip()]
    try:
        if args.regression_fixture:
            specs = [cost.budget_regression_fixture(args.tiles)]
        else:
            specs = default_programs(args.tiles, names=names)
    except ValueError as e:
        raise SystemExit(str(e))
    report = audit(specs, max_cond_bytes=(
        args.max_cond_bytes if args.max_cond_bytes is not None
        else DEFAULT_MAX_COND_BYTES))

    # static cost reports ride alongside the lint rows unconditionally
    # (walking a lowered jaxpr is cheap; the budget GATE is opt-in)
    cost_reports = [cost.cost_report(s) for s in specs]
    budget_findings = []
    if args.budget or args.budget_update:
        if args.budget_update:
            path = cost.save_budgets(cost_reports, args.budgets_file)
            print(json.dumps({"budgets_updated": True, "path": path,
                              "programs": [r.program
                                           for r in cost_reports]}))
        else:
            try:
                budgets = cost.load_budgets(args.budgets_file)
            except FileNotFoundError as e:
                raise SystemExit(
                    f"no budgets file ({e}); create one with "
                    f"--budget-update")
            budget_findings = cost.check_budgets(cost_reports, budgets)

    for f in report.findings:
        print(json.dumps(f.to_json()))
    for rep in cost_reports:
        print(json.dumps(rep.to_json()))
    for f in budget_findings:
        print(json.dumps(f.to_json()))
    for row in report.summary_rows():
        print(json.dumps(row))
    n_budget_err = len(budget_findings)
    ok = (report.ok and not n_budget_err
          and not (args.strict and report.findings))
    print(json.dumps({
        "overall": True,
        "ok": ok,
        "programs": len(specs),
        "errors": len(report.errors) + n_budget_err,
        "warnings": len(report.findings) - len(report.errors),
        "budget_errors": n_budget_err,
        "wall_s": round(time.perf_counter() - t0, 1),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
