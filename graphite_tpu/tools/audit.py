"""Program auditor CLI: lint + cost the lowered default programs.

Lowers the default config set — the per-phase-GATED private-L2 engine,
the UNGATED one, the shared-L2 engine, the B=4 vmapped sweep campaign,
the telemetry-recording gated engine, the combined sweep+telemetry
campaign, and the 2D batch x tile campaign (round 18, lowered over a
device-less AbstractMesh) — and runs every jaxpr invariant lint
(analysis/rules.py) over
each: cond-payload (with the telemetry/profile ring avals in the
forbidden set for recording programs), knob-fold, time-dtype,
vmap-gate, host-sync, scatter-determinism, write-race (the round-20
[T, k]-compaction gate — no ordered-multi-writer scatter into a req
lane or mailbox matrix; `--lanes` emits the full classification
table), telemetry-off, profile-off, and (round 22, mesh programs only)
gspmd-insertion + replication-drift — every collective must match the
px packed-exchange whitelist and every declared-replicated shard_map
output must be provably uniform across the tile axis.  `--comms` emits
each mesh program's per-phase collective/ICI table (analysis/comms.py:
collectives_per_iter / ici_bytes_per_iter, phase-attributed and priced
by the ring model); `--comms-fixture` swaps in the known-bad legacy
unpacked-exchange lowering — the gspmd-insertion lint MUST exit
nonzero naming the stray collectives' phase (the CI self-test for the
mesh.py GSPMD-cliff gate).  Each program's STATIC COST report (analysis/cost.py —
per-iteration kernel proxy with per-phase attribution, bytes moved,
peak-live residency) is emitted as a JSON line alongside the lint rows.
Pure static analysis over `jax.make_jaxpr` output: no compile, no
execution, runs on CPU-only CI in well under a minute.

`--budget` additionally gates every cost report against the checked-in
BUDGETS.json ceilings (exit nonzero on any excess, the offending
equation named); `--budget-update` refreshes the baselines after an
intentional change.  `--regression-fixture` swaps in the known-bad
inflated-carry program — the gate must trip on it (the CI self-test).

`--lock` gates program IDENTITY the same way (round 11): every default
program's canonical fingerprint (analysis/identity.py) must match its
registered entry in the checked-in PROGRAMS.lock (analysis/registry.py)
— tile geometry and sweep-knob signature included — so no program
drifts unnoticed and no renamed/retraced program silently inherits
stale budget ceilings (budget entries record the fingerprint they were
measured at and are resolved THROUGH the registry).  `--lock-update`
re-registers after an INTENTIONAL program change; `--lock-fixture`
swaps in the intentionally perturbed gated-MSI lowering — the lock
gate must trip on it AND the emitted structural diff must name the
first divergent equation with its protocol phase (the CI self-test
that drift reports are attributed, not just "hash changed").

Output is JSON lines: one line per lint finding, one cost line and one
summary line per program, then one trailing overall line.  Exit code 0
iff no error-severity finding fired (`--strict` also fails on warnings).

Usage:
  python -m graphite_tpu.tools.audit [--tiles 8] [--max-cond-bytes N]
                                     [--strict] [--programs a,b,...]
                                     [--budget | --budget-update]
                                     [--budgets-file PATH]
                                     [--regression-fixture]
                                     [--lock | --lock-update]
                                     [--lock-file PATH]
                                     [--lock-fixture]
                                     [--comms] [--comms-fixture]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="jaxpr invariant lints + static cost/budget gates "
        "over the default programs")
    ap.add_argument("--tiles", type=int, default=8,
                    help="tile count for the audited geometries (the "
                    "lints are structural; 8 carries the same program "
                    "shape as 1024)")
    ap.add_argument("--max-cond-bytes", type=int, default=None,
                    help="generic cond-payload ceiling in bytes "
                    "(default 64 MiB; directory stores are additionally "
                    "matched by signature at any size)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too (e.g. vmap-gate)")
    ap.add_argument("--lanes", action="store_true",
                    help="emit each program's write-race lane-"
                    "classification table (req-lane / mailbox-matrix / "
                    "engine-state scatters broken down by single-writer "
                    "/ commutative / ordered — the [T, k] compaction "
                    "input; reachable fan-in bounds come from "
                    "tools/mc.py)")
    ap.add_argument("--comms", action="store_true",
                    help="emit each mesh program's per-phase "
                    "collective/ICI table (collectives_per_iter / "
                    "ici_bytes_per_iter, phase-attributed and priced "
                    "by the ring model; non-mesh programs emit a "
                    "mesh:false row)")
    ap.add_argument("--comms-fixture", action="store_true",
                    help="audit the known-bad legacy unpacked-exchange "
                    "lowering instead of the real programs — the "
                    "gspmd-insertion lint MUST exit nonzero naming the "
                    "stray collectives' protocol phase (CI self-test)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset of program names "
                    "(default: all seven)")
    ap.add_argument("--budget", action="store_true",
                    help="gate each cost report against BUDGETS.json "
                    "ceilings (exit nonzero on any excess)")
    ap.add_argument("--budget-update", action="store_true",
                    help="refresh BUDGETS.json baselines+ceilings from "
                    "this run's measurements (after an INTENTIONAL "
                    "change; merges, so --programs subsets are safe)")
    ap.add_argument("--ratchet", action="store_true",
                    help="with --budget-update: only LOWER ceilings — "
                    "refuse (exit nonzero, nothing written) if any "
                    "metric's new ceiling would exceed the checked-in "
                    "one, unless named via --allow-increase")
    ap.add_argument("--allow-increase", action="append", default=[],
                    metavar="METRIC",
                    help="with --budget-update --ratchet: permit this "
                    "metric's ceiling to rise (repeatable; an explicit, "
                    "reviewed exception to the ratchet)")
    ap.add_argument("--budgets-file", default=None,
                    help="override the BUDGETS.json path (default: "
                    "repo root)")
    ap.add_argument("--regression-fixture", action="store_true",
                    help="audit the known-bad inflated-carry fixture "
                    "instead of the real gated-msi program — the budget "
                    "gate MUST exit nonzero (CI self-test)")
    ap.add_argument("--lock", action="store_true",
                    help="gate each program's canonical fingerprint "
                    "against the checked-in PROGRAMS.lock registry "
                    "(exit nonzero on any identity drift)")
    ap.add_argument("--lock-update", action="store_true",
                    help="re-register this run's program identities "
                    "into PROGRAMS.lock (after an INTENTIONAL change; "
                    "merges, so --programs subsets are safe)")
    ap.add_argument("--lock-file", default=None,
                    help="override the PROGRAMS.lock path (default: "
                    "repo root)")
    ap.add_argument("--lock-fixture", action="store_true",
                    help="audit the intentionally perturbed gated-msi "
                    "lowering instead of the real one — the lock gate "
                    "MUST exit nonzero with a structural diff naming "
                    "the divergent equation and its protocol phase "
                    "(CI self-test)")
    args = ap.parse_args(argv)
    if args.budget and args.budget_update:
        ap.error("--budget and --budget-update are mutually exclusive "
                 "(gate against the ceilings OR refresh them, not both)")
    if args.lock and args.lock_update:
        ap.error("--lock and --lock-update are mutually exclusive "
                 "(gate against the registry OR refresh it, not both)")
    if args.ratchet and not args.budget_update:
        ap.error("--ratchet modifies the --budget-update refresh; it "
                 "does nothing without it")
    if args.allow_increase and not args.ratchet:
        ap.error("--allow-increase is a ratchet exception; it needs "
                 "--budget-update --ratchet")
    n_fixtures = sum((args.regression_fixture, args.lock_fixture,
                      args.comms_fixture))
    if n_fixtures > 1:
        ap.error("--regression-fixture, --lock-fixture and "
                 "--comms-fixture each swap in their own known-bad "
                 "program; run the self-tests separately")
    # each fixture self-tests ONE gate; arming the OTHER gate alongside
    # lets its finding (the budget fixture's perturbed identity always
    # trips the lock) carry the nonzero exit even when the gate under
    # test is broken — a vacuously green CI self-test
    if args.regression_fixture and args.lock:
        ap.error("--regression-fixture self-tests the budget gate; "
                 "--lock would trip on the fixture's identity and mask "
                 "a broken budget gate (run the lock gate separately)")
    if args.lock_fixture and args.budget:
        ap.error("--lock-fixture self-tests the lock gate; combine it "
                 "with --budget and the exit code no longer isolates "
                 "the gate under test (run the budget gate separately)")
    if args.comms_fixture and (args.budget or args.lock):
        # same isolation discipline as the other fixtures: the
        # gspmd-insertion lint always runs on mesh programs, so the
        # fixture needs no gate armed — but an unregistered fixture
        # also trips the budget/lock gates, and either would carry the
        # nonzero exit even with the lint under test broken
        ap.error("--comms-fixture self-tests the gspmd-insertion lint; "
                 "--budget/--lock would trip on the unregistered "
                 "fixture and mask a broken lint (run those gates "
                 "separately)")
    if (args.regression_fixture or args.lock_fixture
            or args.comms_fixture) \
            and (args.budget_update or args.lock_update):
        # both fixtures deliberately reuse the real program's name so
        # their gates run against the checked-in baselines — writing a
        # fixture's measurements or identity back would corrupt the
        # real entries and turn the CI self-tests green on broken gates
        ap.error("the fixtures are read-only self-tests; they cannot "
                 "be combined with --budget-update or --lock-update")
    # a fixture exists only to prove its gate trips — without the gate
    # its lints all pass and the self-test would be vacuously green
    if args.regression_fixture:
        args.budget = True
    if args.lock_fixture:
        args.lock = True

    # auditing is host-side static analysis — never touch a real chip
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import graphite_tpu  # noqa: F401  (x64)

    from graphite_tpu.analysis import cost, identity, registry
    from graphite_tpu.analysis.audit import (
        DEFAULT_MAX_COND_BYTES, audit, default_programs,
    )

    budgetable = cost.BUDGET_METRICS + cost.COMMS_METRICS
    unknown_metrics = [m for m in args.allow_increase
                       if m not in budgetable]
    if unknown_metrics:
        ap.error(f"--allow-increase: unknown metric(s) "
                 f"{unknown_metrics} (choose from "
                 f"{', '.join(budgetable)})")

    t0 = time.perf_counter()
    names = None
    if args.programs:
        names = [s.strip() for s in args.programs.split(",") if s.strip()]
    try:
        if args.regression_fixture:
            specs = [cost.budget_regression_fixture(args.tiles)]
        elif args.lock_fixture:
            specs = [registry.lock_regression_fixture(args.tiles)]
        elif args.comms_fixture:
            from graphite_tpu.analysis import comms
            specs = [comms.gspmd_insertion_fixture(args.tiles)]
        else:
            specs = default_programs(args.tiles, names=names)
    except ValueError as e:
        raise SystemExit(str(e))
    report = audit(specs, max_cond_bytes=(
        args.max_cond_bytes if args.max_cond_bytes is not None
        else DEFAULT_MAX_COND_BYTES))

    # the lock registry doubles as the budget gate's resolver: budget
    # entries are looked up under the registered budget_key and refuse
    # ceilings measured at a different fingerprint
    lock = None
    if args.lock or args.budget or args.budget_update:
        try:
            lock = registry.load_lock(args.lock_file)
        except FileNotFoundError as e:
            if args.lock:
                raise SystemExit(
                    f"no PROGRAMS.lock ({e}); create one with "
                    f"--lock-update")
            # --budget without a lock file: ceilings resolve by name
            # only, as before round 11

    lock_findings = []
    if args.lock_update:
        path = registry.save_lock(
            [registry.record_from_spec(s) for s in specs],
            args.lock_file)
        print(json.dumps({"lock_updated": True, "path": path,
                          "programs": [s.name for s in specs]}))
        # a combined --budget/--budget-update run must resolve through
        # the registry JUST written (merged entries, preserved budget
        # keys) — the pre-update records' fingerprints would certify
        # ceilings against the artifact the refresh just replaced
        lock = registry.load_lock(args.lock_file)
    elif args.lock:
        # a full-set run also flags stale registered names nothing
        # audits anymore; subset/fixture runs only check what they
        # lowered
        lock_findings = registry.check_lock(
            specs, lock,
            expect_complete=(names is None and not args.lock_fixture
                             and not args.regression_fixture))
        if args.lock_fixture and lock_findings:
            # the self-test must prove drift is ATTRIBUTED: diff the
            # perturbed lowering against the reference program and
            # name the first divergent equation + its protocol phase
            ref = default_programs(args.tiles,
                                   names=("gated-msi",))[0]
            d = identity.diff_or_none(
                ref.closed, specs[0].closed, n_tiles=ref.n_tiles,
                phase_names=ref.phase_names)
            if d is not None:
                for f in lock_findings:
                    f.message += f"; {d}"
                    f.data["diff"] = d.to_json()
                print(json.dumps({"lock_diff": True,
                                  "program": specs[0].name,
                                  **d.to_json()}))

    # static cost reports ride alongside the lint rows unconditionally
    # (walking a lowered jaxpr is cheap; the budget GATE is opt-in)
    cost_reports = [cost.cost_report(s) for s in specs]
    budget_findings = []
    if args.budget or args.budget_update:
        if args.budget_update:
            try:
                path = cost.save_budgets(
                    cost_reports, args.budgets_file,
                    fingerprints={s.name: identity.fingerprint(s.closed)
                                  for s in specs},
                    registry=lock,
                    ratchet=args.ratchet,
                    allow_increase=tuple(args.allow_increase))
            except cost.BudgetRatchetError as e:
                print(json.dumps({"budget_ratchet_refused": True,
                                  "error": str(e)}))
                return 1
            print(json.dumps({"budgets_updated": True, "path": path,
                              "ratchet": bool(args.ratchet),
                              "programs": [r.program
                                           for r in cost_reports]}))
        else:
            try:
                budgets = cost.load_budgets(args.budgets_file)
            except FileNotFoundError as e:
                raise SystemExit(
                    f"no budgets file ({e}); create one with "
                    f"--budget-update")
            budget_findings = cost.check_budgets(cost_reports, budgets,
                                                 registry=lock)

    if args.lanes:
        from graphite_tpu.analysis import rules
        for s in specs:
            writes = rules.lane_writes(s.closed, s.n_tiles)
            print(json.dumps({
                "lanes": True, "program": s.name,
                "n_scatters": len(writes),
                "table": rules.lane_summary(writes)}))

    if args.comms:
        from graphite_tpu.analysis import comms
        for s in specs:
            if not comms.has_mesh_region(s.closed):
                print(json.dumps({"comms": True, "program": s.name,
                                  "mesh": False}))
                continue
            print(json.dumps(comms.comms_report(s).to_json()))

    for f in report.findings:
        print(json.dumps(f.to_json()))
    for rep in cost_reports:
        print(json.dumps(rep.to_json()))
    for f in budget_findings + lock_findings:
        print(json.dumps(f.to_json()))
    for row in report.summary_rows():
        print(json.dumps(row))
    n_budget_err = len(budget_findings)
    n_lock_err = len(lock_findings)
    ok = (report.ok and not n_budget_err and not n_lock_err
          and not (args.strict and report.findings))
    print(json.dumps({
        "overall": True,
        "ok": ok,
        "programs": len(specs),
        "errors": len(report.errors) + n_budget_err + n_lock_err,
        "warnings": len(report.findings) - len(report.errors),
        "budget_errors": n_budget_err,
        "lock_errors": n_lock_err,
        "wall_s": round(time.perf_counter() - t0, 1),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
