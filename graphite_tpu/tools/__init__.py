"""Host-side tools: output parsing and regression driving — the analog of
the reference's `tools/` directory (`tools/parse_output.py`,
`tools/regress/run_tests.py`).  Multi-machine spawn helpers
(`tools/spawn*.py`, `schedule.py`) have no TPU analog: distribution is
`shard_map` over the device mesh, not process spawning (SURVEY §2.10)."""
