"""Time-sampled statistics traces (`common/system/statistics_manager.cc`).

Reference behavior: a statistics thread wakes at every barrier quantum that
crosses the sampling interval and appends cache-line-replication and
network-utilization records to trace files (`statistics_thread.h:8-28`,
knobs `carbon_sim.cfg:394-411`).  Device-driven equivalent: the simulation
runs in bounded-quantum chunks sized to the sampling interval; between
chunks the sampler reads the state it needs in one batched device fetch and
appends records.  (Each sample costs one host↔device round trip — only
stats-enabled runs pay it, like the reference only pays when
[statistics_trace] enabled.)

Cache-line replication: from the L2 tag tensors directly — the number of
tiles caching each distinct line, as a histogram (the reference walks every
cache; here it is one np.unique over the tag arrays).
Network utilization: per-interval injection rate on the USER network
(exact, from packet counters) and the MEMORY network (message count
approximated from the protocol event counters).
Progress trace (`pin/progress_trace.cc`): per-tile clock/record progress
per sample.

Two backends (round 9):
 - `device`: the simulation runs as ONE compiled region recording a
   device-resident telemetry timeline (graphite_tpu/obs — zero host sync,
   the dispatch-tail fix), converted to the same `.trace` files in one
   post-run pass.  Covers the counter-derived statistics (network
   utilization); sample times are the quanta whose laggard clock crosses
   the sampling interval — the reference's statistics-thread wakeups.
 - `chunked`: the legacy host-driven sampling loop (one host<->device
   round trip PER SAMPLE).  Stays as the fallback for live-STATE
   snapshots the telemetry carry cannot afford: replication histograms
   over the full L2 tags, per-tile progress rows, energy sampling.
`backend="auto"` (the default) picks `device` exactly when every enabled
statistic is counter-derived.
"""

from __future__ import annotations

import os

import numpy as np

import jax


def chunk_quanta(sampling_interval_ns: int, quantum_ps: int) -> int:
    """Quanta per chunked-backend sample: the sampling interval floor-
    divided by the barrier quantum, never below one quantum (the
    reference's statistics thread wakes at barrier quanta only, so a
    sub-quantum interval degrades to per-quantum sampling).  Pinned by
    tests before the round-9 backend split."""
    return max(1, (int(sampling_interval_ns) * 1000) // int(quantum_ps))


class _StateEnergyView:
    """Live-state snapshot with the SimResults attributes
    `TileEnergyMonitor.tile_energy_j` consumes — lets the energy model
    run mid-simulation for periodic power sampling."""

    def __init__(self, sim):
        import dataclasses as _dc

        state = sim.state
        core = jax.device_get(state.core)
        self.clock_ps = np.asarray(core.clock_ps)
        self.instruction_count = np.asarray(core.instruction_count)
        self.bp_correct = np.asarray(core.bp_correct)
        self.bp_incorrect = np.asarray(core.bp_incorrect)
        self.packets_sent = np.asarray(
            jax.device_get(state.net.packets_sent))
        self.n_tiles = self.clock_ps.shape[0]
        if state.mem is not None:
            counters = jax.device_get(state.mem.counters)
            self.mem_counters = {
                f.name: np.asarray(getattr(counters, f.name))
                for f in _dc.fields(counters)}
        else:
            self.mem_counters = None


class StatisticsManager:
    """Drives a Simulator in sampling-interval chunks, writing traces."""

    def __init__(self, sim, output_dir: str = "stats",
                 backend: str = "auto"):
        cfg = sim.config.cfg
        self.sim = sim
        self.enabled = cfg.get_bool("statistics_trace/enabled", False)
        stats = cfg.get_string(
            "statistics_trace/statistics",
            "cache_line_replication, network_utilization")
        self.types = {s.strip() for s in stats.split(",") if s.strip()}
        self.sampling_interval_ns = cfg.get_int(
            "statistics_trace/sampling_interval", 10000)
        self.progress_enabled = cfg.get_bool("progress_trace/enabled", False)
        # periodic energy/power sampling (`[runtime_energy_modeling]`,
        # `carbon_sim.cfg:141-145`; `tile_energy_monitor.h:29`): rides the
        # same sampling loop; writes power.trace when power_trace/enabled
        self.power_enabled = cfg.get_bool(
            "runtime_energy_modeling/power_trace/enabled", False)
        if backend not in ("auto", "device", "chunked"):
            raise ValueError(f"unknown statistics backend {backend!r} "
                             "(expected 'auto', 'device' or 'chunked')")
        if backend == "device" and not self.device_supported():
            raise ValueError(
                "the device-timeline backend covers counter-derived "
                "statistics only (network_utilization under "
                "[statistics_trace]); replication/utilization histograms, "
                "per-tile progress rows and power sampling need live-state "
                "snapshots the telemetry carry cannot afford — use "
                "backend='chunked' (or 'auto') for those")
        self.backend = backend
        self.out_dir = output_dir
        self._files: dict = {}
        self._prev_user_packets = 0.0
        self._prev_mem_msgs = 0.0
        self._prev_sample_ns = 0
        self._energy_monitor = None
        self._prev_energy_j = None

    def device_supported(self) -> bool:
        """True when every ENABLED statistic is counter-derived, i.e.
        recordable from the carry by the device timeline: network
        utilization yes; replication/utilization histograms (full L2
        tag scans), per-tile progress rows and energy sampling no.
        Meshed and streamed sims always fall back to the chunked loop
        (the telemetry ring is not threaded through the multi-chip
        exchange or the streaming window loop)."""
        if self.sim.mesh is not None or self.sim.stream:
            return False
        if self.progress_enabled or self.power_enabled:
            return False
        if not self.enabled:
            # nothing to record at all — the chunked loop degenerates
            # to a plain run anyway, but there is no timeline to write
            return False
        unsupported = self.types - {"network_utilization"}
        return not unsupported and "network_utilization" in self.types

    # -- trace files (`openTraceFiles`) ---------------------------------
    def _file(self, name: str):
        if name not in self._files:
            os.makedirs(self.out_dir, exist_ok=True)
            self._files[name] = open(
                os.path.join(self.out_dir, f"{name}.trace"), "w")
        return self._files[name]

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()

    # -- samplers --------------------------------------------------------
    def replication_histogram(self) -> np.ndarray:
        """hist[k] = number of distinct lines cached by exactly k tiles
        (k = 1..n_tiles), from the L2 tag/state tensors."""
        ms = self.sim.state.mem
        if ms is None:
            return np.zeros(self.sim.params.n_tiles, np.int64)
        tags, state = jax.device_get((ms.l2.tags, ms.l2.state))
        valid = state != 0  # INVALID == 0
        lines = tags[valid]
        if lines.size == 0:
            return np.zeros(self.sim.params.n_tiles, np.int64)
        _, counts = np.unique(lines, return_counts=True)
        hist = np.bincount(counts, minlength=self.sim.params.n_tiles + 1)
        return hist[1:]

    def _memory_message_count(self, mem_counters) -> float:
        """Protocol messages ≈ 2x misses (req+rep) + 2x invalidations +
        evictions (approximation: the reference counts per-packet)."""
        if mem_counters is None:
            return 0.0
        return float(
            2 * mem_counters["l2_misses"].sum()
            + 2 * mem_counters["invalidations"].sum()
            + mem_counters["evictions"].sum())

    def _sim_time_ns(self) -> int:
        """Current simulated time: the laggard non-done tile's clock (the
        barrier boundary the quantum loop just crossed), or the max clock
        when all tiles are done."""
        done, clocks = jax.device_get(
            (self.sim.state.done, self.sim.state.core.clock_ps))
        pending = clocks[~done]
        t = pending.min() if pending.size else clocks.max()
        return int(t) // 1000

    def sample(self, time_ns: int) -> None:
        state = self.sim.state
        if not self.enabled:
            # [statistics_trace] enabled=false: only the independently
            # gated progress + power traces may write
            if self.power_enabled:
                self._sample_power(time_ns)
            if self.progress_enabled:
                clocks, idx = jax.device_get(
                    (state.core.clock_ps, state.core.idx))
                row = " ".join(
                    f"{c // 1000}/{i}" for c, i in zip(clocks, idx))
                self._file("progress").write(f"{time_ns} {row}\n")
            return
        if "cache_line_replication" in self.types and state.mem is not None:
            hist = self.replication_histogram()
            nz = np.flatnonzero(hist)
            row = " ".join(f"{k + 1}:{hist[k]}" for k in nz)
            self._file("cache_line_replication").write(
                f"{time_ns} {row}\n")
        if ("cache_line_utilization" in self.types and state.mem is not None
                and getattr(state.mem, "l2_util", None) is not None):
            # cumulative histogram of classified (departed) L2 lines by
            # total accesses, aggregated over tiles
            # (cache_line_utilization.h harvested at eviction/invalidation)
            hist = np.asarray(jax.device_get(
                state.mem.counters.line_util_hist)).sum(axis=0)
            row = " ".join(f"{k}:{int(v)}" for k, v in enumerate(hist))
            self._file("cache_line_utilization").write(
                f"{time_ns} {row}\n")
        if "network_utilization" in self.types:
            interval_ns = max(time_ns - self._prev_sample_ns, 1)
            sent, = jax.device_get((state.net.packets_sent,))
            total = float(sent.sum())
            delta = total - self._prev_user_packets
            self._prev_user_packets = total
            rate = delta / interval_ns / max(self.sim.params.n_tiles, 1)
            self._file("network_utilization_user").write(
                f"{time_ns} {rate:.6f}\n")
            if state.mem is not None:
                import dataclasses as _dc

                counters_h = jax.device_get(state.mem.counters)
                mc = {f.name: np.asarray(getattr(counters_h, f.name))
                      for f in _dc.fields(counters_h)}
                msgs = self._memory_message_count(mc)
                mdelta = msgs - self._prev_mem_msgs
                self._prev_mem_msgs = msgs
                mrate = mdelta / interval_ns / max(
                    self.sim.params.n_tiles, 1)
                f = self._file("network_utilization_memory")
                if f.tell() == 0:
                    # labeled as approximated (VERDICT weak #7): derived
                    # from protocol counters (~2x misses + 2x INVs +
                    # evictions), not per-interval packet counts
                    f.write("# approximated from protocol counters "
                            "(see _memory_message_count)\n")
                f.write(
                    f"{time_ns} {mrate:.6f}\n")
        self._prev_sample_ns = time_ns
        if self.power_enabled:
            self._sample_power(time_ns)
        if self.progress_enabled:
            clocks, idx = jax.device_get(
                (state.core.clock_ps, state.core.idx))
            row = " ".join(f"{c // 1000}/{i}" for c, i in zip(clocks, idx))
            self._file("progress").write(f"{time_ns} {row}\n")

    def _sample_power(self, time_ns: int) -> None:
        """Periodic per-tile energy/power from the live counters
        (`TileEnergyMonitor::periodicallyCollectEnergy`): total energy so
        far per tile, and average power over the elapsed interval; one
        `time_ns  e0:p0 e1:p1 ...` row per sample in power.trace."""
        from graphite_tpu.power.interface import TileEnergyMonitor

        snap = _StateEnergyView(self.sim)
        if self._energy_monitor is None:
            self._energy_monitor = TileEnergyMonitor(self.sim, snap)
        else:
            self._energy_monitor.results = snap
        T = self.sim.params.n_tiles
        energies = np.asarray(
            [self._energy_monitor.tile_energy_j(t)["total"]
             for t in range(T)])
        if self._prev_energy_j is None:
            self._prev_energy_j = np.zeros(T)
            prev_t = 0
        else:
            prev_t = self._power_prev_t
        dt_s = max(time_ns - prev_t, 1) * 1e-9
        power_w = (energies - self._prev_energy_j) / dt_s
        self._prev_energy_j = energies
        self._power_prev_t = time_ns
        row = " ".join(f"{e:.4e}:{p:.4e}"
                       for e, p in zip(energies, power_w))
        self._file("power").write(f"{time_ns} {row}\n")

    # -- sampled run (`statistics_thread` + barrier wakeups) -------------
    def run(self, max_samples: int = 100000):
        """Run the simulation to completion, sampling every interval.

        Requires lax_barrier (the reference demands the same:
        `carbon_sim.cfg:397`).  Backend dispatch: `device` records the
        timeline inside ONE compiled run (zero host sync) and converts
        it post-run; `chunked` drives the legacy host loop — chunk size
        is sampling_interval / barrier quantum (`chunk_quanta`), so
        samples land on quantum boundaries exactly as the reference's
        statistics thread does.  `auto` picks `device` when every
        enabled statistic is counter-derived.
        """
        sim = self.sim
        if sim.quantum_ps is None:
            raise ValueError(
                "statistics sampling needs clock_skew_management/scheme = "
                "lax_barrier (reference requirement)")
        if self.backend == "device" or (self.backend == "auto"
                                        and self.device_supported()):
            return self._run_device(max_samples)
        quanta_per_sample = chunk_quanta(self.sampling_interval_ns,
                                         sim.quantum_ps)
        total_quanta = 0
        done = False
        for s in range(max_samples):
            done, nq = sim.run_chunk(int(quanta_per_sample))
            total_quanta += nq
            # timestamp from the device clocks: the loop skips empty
            # quanta, so iteration count is NOT simulated time
            self.sample(time_ns=self._sim_time_ns())
            if done:
                break
        self.close()
        if not done:
            raise RuntimeError(
                f"statistics run truncated: {max_samples} samples "
                f"({total_quanta} quanta) without completing")
        return sim._results_from_state(total_quanta)

    # -- device-timeline backend (round 9, graphite_tpu/obs) -------------
    def _run_device(self, max_samples: int):
        """One compiled telemetry-recording run, then a post-run pass
        converting the timeline into the same `.trace` files the chunked
        sampler writes — no per-sample host round trips."""
        from graphite_tpu.obs import TelemetrySpec

        sim = self.sim
        series = ["time_ps", "packets_sent"]
        if sim.state.mem is not None:
            series += ["l2_misses", "invalidations", "evictions"]
        sim.attach_telemetry(TelemetrySpec(
            sample_interval_ps=self.sampling_interval_ns * 1000,
            n_samples=max_samples, series=series))
        results = sim.run()
        self.write_timeline(results.telemetry)
        self.close()
        return results

    def write_timeline(self, tl) -> None:
        """Convert a recorded `obs.Timeline` into the chunked sampler's
        `.trace` file formats (same rows, same normalization: per-ns
        per-tile rates against the previous sample's timestamp)."""
        if tl.wrapped:
            raise ValueError(
                "telemetry ring wrapped: the first "
                f"{tl.n_total - len(tl)} sample(s) were overwritten — "
                "raise max_samples (the ring depth) to cover the run")
        T = max(self.sim.params.n_tiles, 1)
        have_mem = all(s in tl.series
                       for s in ("l2_misses", "invalidations", "evictions"))
        prev_ns = 0
        for i in range(len(tl)):
            t_ns = int(tl.time_ns[i])
            interval_ns = max(t_ns - prev_ns, 1)
            prev_ns = t_ns
            if "network_utilization" not in self.types or not self.enabled:
                continue
            rate = float(tl.col("packets_sent")[i]) / interval_ns / T
            self._file("network_utilization_user").write(
                f"{t_ns} {rate:.6f}\n")
            if have_mem:
                # the chunked backend's approximation applied to the
                # recorded DELTAS (the formula is linear, so
                # delta-of-approx == approx-of-delta)
                mdelta = self._memory_message_count(
                    {k: tl.col(k)[i:i + 1]
                     for k in ("l2_misses", "invalidations", "evictions")})
                f = self._file("network_utilization_memory")
                if f.tell() == 0:
                    f.write("# approximated from protocol counters "
                            "(see _memory_message_count)\n")
                f.write(f"{t_ns} {mdelta / interval_ns / T:.6f}\n")
