"""Time-sampled statistics traces (`common/system/statistics_manager.cc`).

Reference behavior: a statistics thread wakes at every barrier quantum that
crosses the sampling interval and appends cache-line-replication and
network-utilization records to trace files (`statistics_thread.h:8-28`,
knobs `carbon_sim.cfg:394-411`).  Device-driven equivalent: the simulation
runs in bounded-quantum chunks sized to the sampling interval; between
chunks the sampler reads the state it needs in one batched device fetch and
appends records.  (Each sample costs one host↔device round trip — only
stats-enabled runs pay it, like the reference only pays when
[statistics_trace] enabled.)

Cache-line replication: from the L2 tag tensors directly — the number of
tiles caching each distinct line, as a histogram (the reference walks every
cache; here it is one np.unique over the tag arrays).
Network utilization: per-interval injection rate on the USER network
(exact, from packet counters) and the MEMORY network (message count
approximated from the protocol event counters).
Progress trace (`pin/progress_trace.cc`): per-tile clock/record progress
per sample.
"""

from __future__ import annotations

import os

import numpy as np

import jax


class _StateEnergyView:
    """Live-state snapshot with the SimResults attributes
    `TileEnergyMonitor.tile_energy_j` consumes — lets the energy model
    run mid-simulation for periodic power sampling."""

    def __init__(self, sim):
        import dataclasses as _dc

        state = sim.state
        core = jax.device_get(state.core)
        self.clock_ps = np.asarray(core.clock_ps)
        self.instruction_count = np.asarray(core.instruction_count)
        self.bp_correct = np.asarray(core.bp_correct)
        self.bp_incorrect = np.asarray(core.bp_incorrect)
        self.packets_sent = np.asarray(
            jax.device_get(state.net.packets_sent))
        self.n_tiles = self.clock_ps.shape[0]
        if state.mem is not None:
            counters = jax.device_get(state.mem.counters)
            self.mem_counters = {
                f.name: np.asarray(getattr(counters, f.name))
                for f in _dc.fields(counters)}
        else:
            self.mem_counters = None


class StatisticsManager:
    """Drives a Simulator in sampling-interval chunks, writing traces."""

    def __init__(self, sim, output_dir: str = "stats"):
        cfg = sim.config.cfg
        self.sim = sim
        self.enabled = cfg.get_bool("statistics_trace/enabled", False)
        stats = cfg.get_string(
            "statistics_trace/statistics",
            "cache_line_replication, network_utilization")
        self.types = {s.strip() for s in stats.split(",") if s.strip()}
        self.sampling_interval_ns = cfg.get_int(
            "statistics_trace/sampling_interval", 10000)
        self.progress_enabled = cfg.get_bool("progress_trace/enabled", False)
        # periodic energy/power sampling (`[runtime_energy_modeling]`,
        # `carbon_sim.cfg:141-145`; `tile_energy_monitor.h:29`): rides the
        # same sampling loop; writes power.trace when power_trace/enabled
        self.power_enabled = cfg.get_bool(
            "runtime_energy_modeling/power_trace/enabled", False)
        self.out_dir = output_dir
        self._files: dict = {}
        self._prev_user_packets = 0.0
        self._prev_mem_msgs = 0.0
        self._prev_sample_ns = 0
        self._energy_monitor = None
        self._prev_energy_j = None

    # -- trace files (`openTraceFiles`) ---------------------------------
    def _file(self, name: str):
        if name not in self._files:
            os.makedirs(self.out_dir, exist_ok=True)
            self._files[name] = open(
                os.path.join(self.out_dir, f"{name}.trace"), "w")
        return self._files[name]

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()

    # -- samplers --------------------------------------------------------
    def replication_histogram(self) -> np.ndarray:
        """hist[k] = number of distinct lines cached by exactly k tiles
        (k = 1..n_tiles), from the L2 tag/state tensors."""
        ms = self.sim.state.mem
        if ms is None:
            return np.zeros(self.sim.params.n_tiles, np.int64)
        tags, state = jax.device_get((ms.l2.tags, ms.l2.state))
        valid = state != 0  # INVALID == 0
        lines = tags[valid]
        if lines.size == 0:
            return np.zeros(self.sim.params.n_tiles, np.int64)
        _, counts = np.unique(lines, return_counts=True)
        hist = np.bincount(counts, minlength=self.sim.params.n_tiles + 1)
        return hist[1:]

    def _memory_message_count(self, mem_counters) -> float:
        """Protocol messages ≈ 2x misses (req+rep) + 2x invalidations +
        evictions (approximation: the reference counts per-packet)."""
        if mem_counters is None:
            return 0.0
        return float(
            2 * mem_counters["l2_misses"].sum()
            + 2 * mem_counters["invalidations"].sum()
            + mem_counters["evictions"].sum())

    def _sim_time_ns(self) -> int:
        """Current simulated time: the laggard non-done tile's clock (the
        barrier boundary the quantum loop just crossed), or the max clock
        when all tiles are done."""
        done, clocks = jax.device_get(
            (self.sim.state.done, self.sim.state.core.clock_ps))
        pending = clocks[~done]
        t = pending.min() if pending.size else clocks.max()
        return int(t) // 1000

    def sample(self, time_ns: int) -> None:
        state = self.sim.state
        if not self.enabled:
            # [statistics_trace] enabled=false: only the independently
            # gated progress + power traces may write
            if self.power_enabled:
                self._sample_power(time_ns)
            if self.progress_enabled:
                clocks, idx = jax.device_get(
                    (state.core.clock_ps, state.core.idx))
                row = " ".join(
                    f"{c // 1000}/{i}" for c, i in zip(clocks, idx))
                self._file("progress").write(f"{time_ns} {row}\n")
            return
        if "cache_line_replication" in self.types and state.mem is not None:
            hist = self.replication_histogram()
            nz = np.flatnonzero(hist)
            row = " ".join(f"{k + 1}:{hist[k]}" for k in nz)
            self._file("cache_line_replication").write(
                f"{time_ns} {row}\n")
        if ("cache_line_utilization" in self.types and state.mem is not None
                and getattr(state.mem, "l2_util", None) is not None):
            # cumulative histogram of classified (departed) L2 lines by
            # total accesses, aggregated over tiles
            # (cache_line_utilization.h harvested at eviction/invalidation)
            hist = np.asarray(jax.device_get(
                state.mem.counters.line_util_hist)).sum(axis=0)
            row = " ".join(f"{k}:{int(v)}" for k, v in enumerate(hist))
            self._file("cache_line_utilization").write(
                f"{time_ns} {row}\n")
        if "network_utilization" in self.types:
            interval_ns = max(time_ns - self._prev_sample_ns, 1)
            sent, = jax.device_get((state.net.packets_sent,))
            total = float(sent.sum())
            delta = total - self._prev_user_packets
            self._prev_user_packets = total
            rate = delta / interval_ns / max(self.sim.params.n_tiles, 1)
            self._file("network_utilization_user").write(
                f"{time_ns} {rate:.6f}\n")
            if state.mem is not None:
                import dataclasses as _dc

                counters_h = jax.device_get(state.mem.counters)
                mc = {f.name: np.asarray(getattr(counters_h, f.name))
                      for f in _dc.fields(counters_h)}
                msgs = self._memory_message_count(mc)
                mdelta = msgs - self._prev_mem_msgs
                self._prev_mem_msgs = msgs
                mrate = mdelta / interval_ns / max(
                    self.sim.params.n_tiles, 1)
                f = self._file("network_utilization_memory")
                if f.tell() == 0:
                    # labeled as approximated (VERDICT weak #7): derived
                    # from protocol counters (~2x misses + 2x INVs +
                    # evictions), not per-interval packet counts
                    f.write("# approximated from protocol counters "
                            "(see _memory_message_count)\n")
                f.write(
                    f"{time_ns} {mrate:.6f}\n")
        self._prev_sample_ns = time_ns
        if self.power_enabled:
            self._sample_power(time_ns)
        if self.progress_enabled:
            clocks, idx = jax.device_get(
                (state.core.clock_ps, state.core.idx))
            row = " ".join(f"{c // 1000}/{i}" for c, i in zip(clocks, idx))
            self._file("progress").write(f"{time_ns} {row}\n")

    def _sample_power(self, time_ns: int) -> None:
        """Periodic per-tile energy/power from the live counters
        (`TileEnergyMonitor::periodicallyCollectEnergy`): total energy so
        far per tile, and average power over the elapsed interval; one
        `time_ns  e0:p0 e1:p1 ...` row per sample in power.trace."""
        from graphite_tpu.power.interface import TileEnergyMonitor

        snap = _StateEnergyView(self.sim)
        if self._energy_monitor is None:
            self._energy_monitor = TileEnergyMonitor(self.sim, snap)
        else:
            self._energy_monitor.results = snap
        T = self.sim.params.n_tiles
        energies = np.asarray(
            [self._energy_monitor.tile_energy_j(t)["total"]
             for t in range(T)])
        if self._prev_energy_j is None:
            self._prev_energy_j = np.zeros(T)
            prev_t = 0
        else:
            prev_t = self._power_prev_t
        dt_s = max(time_ns - prev_t, 1) * 1e-9
        power_w = (energies - self._prev_energy_j) / dt_s
        self._prev_energy_j = energies
        self._power_prev_t = time_ns
        row = " ".join(f"{e:.4e}:{p:.4e}"
                       for e, p in zip(energies, power_w))
        self._file("power").write(f"{time_ns} {row}\n")

    # -- sampled run (`statistics_thread` + barrier wakeups) -------------
    def run(self, max_samples: int = 100000):
        """Run the simulation to completion, sampling every interval.

        Requires lax_barrier (the reference demands the same:
        `carbon_sim.cfg:397`); the chunk size is
        sampling_interval / barrier quantum, so samples land on quantum
        boundaries exactly as the reference's statistics thread does.
        """
        sim = self.sim
        if sim.quantum_ps is None:
            raise ValueError(
                "statistics sampling needs clock_skew_management/scheme = "
                "lax_barrier (reference requirement)")
        interval_ps = self.sampling_interval_ns * 1000
        quanta_per_sample = max(1, interval_ps // sim.quantum_ps)
        total_quanta = 0
        done = False
        for s in range(max_samples):
            done, nq = sim.run_chunk(int(quanta_per_sample))
            total_quanta += nq
            # timestamp from the device clocks: the loop skips empty
            # quanta, so iteration count is NOT simulated time
            self.sample(time_ns=self._sim_time_ns())
            if done:
                break
        self.close()
        if not done:
            raise RuntimeError(
                f"statistics run truncated: {max_samples} samples "
                f"({total_quanta} quanta) without completing")
        return sim._results_from_state(total_quanta)
