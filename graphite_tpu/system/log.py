"""Per-tile logging with module filters (`common/misc/log.{h,cc}`).

The reference writes one file per tile plus per-simthread files, with
module-level enable/disable filters and simulated timestamps
(`log.h:34-47,63-67`; knobs `carbon_sim.cfg:73-79`).  Here the engine is
compiled XLA — per-instruction logging does not exist by construction — so
the Log serves the host orchestration layer: lifecycle events, quantum
boundaries, stats samples, and model summaries, with the same filter knobs
and a per-tile file layout.  Disabled logging costs one predicate check
(the reference compiles it out under NDEBUG; `log.h:84-90`).
"""

from __future__ import annotations

import os
import time


class Log:
    """`Log::getSingleton()`-style logger driven by the `[log]` section."""

    def __init__(self, cfg, output_dir: str = "logs", n_tiles: int = 0):
        self.enabled = cfg.get_bool("log/enabled", False)
        disabled = cfg.get_string("log/disabled_modules", "")
        enabled_mods = cfg.get_string("log/enabled_modules", "")
        self._disabled = {m.strip() for m in disabled.split(",") if m.strip()}
        self._enabled_only = {
            m.strip() for m in enabled_mods.split(",") if m.strip()
        }
        self._dir = output_dir
        self._files: dict = {}
        self._t0 = time.time()
        self._n_tiles = n_tiles
        if self.enabled:
            os.makedirs(output_dir, exist_ok=True)

    def is_logging_enabled(self, module: str) -> bool:
        if not self.enabled:
            return False
        if self._enabled_only:
            return module in self._enabled_only
        return module not in self._disabled

    def _file(self, tile_id: int):
        if tile_id not in self._files:
            name = ("system.log" if tile_id < 0
                    else f"tile_{tile_id}.log")
            self._files[tile_id] = open(
                os.path.join(self._dir, name), "a")
        return self._files[tile_id]

    def log(self, module: str, message: str, tile_id: int = -1,
            sim_time_ns: int | None = None) -> None:
        """`LOG_PRINT` analog: [elapsed][tile][sim-time][module] message."""
        if not self.is_logging_enabled(module):
            return
        f = self._file(tile_id)
        elapsed_ms = int((time.time() - self._t0) * 1000)
        st = "" if sim_time_ns is None else f"[{sim_time_ns}ns]"
        f.write(f"[{elapsed_ms}ms][{tile_id}]{st}[{module}] {message}\n")
        f.flush()

    def assert_error(self, condition: bool, module: str, message: str,
                     tile_id: int = -1) -> None:
        """`LOG_ASSERT_ERROR`: log + raise when the condition fails."""
        if not condition:
            self.log(module, f"ASSERT FAILED: {message}", tile_id)
            raise AssertionError(f"[{module}] {message}")

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
