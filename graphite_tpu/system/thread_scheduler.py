"""Thread scheduling: placement, run queues, yield, migrate, affinity.

Reference: `common/system/thread_scheduler.{h,cc}` +
`round_robin_thread_scheduler.cc` — `masterScheduleThread` places a spawned
thread on a core and enqueues it (running head + waiters), `yieldThread`
requeues the head to the tail, `masterMigrateThread` moves a thread between
cores, `masterSchedSetAffinity` restricts placement and migrates if the
current core leaves the mask.  The shipped reference hardcodes the
cooperative scheme (`thread_scheduler.cc:22,71-72`: scheme "none", the
`thread_scheduling/*` config reads commented out); preemptive quantum
rotation exists only as the round_robin requeue primitive, which we expose
the same way.

Here scheduling is a host-side (MCP-analog) concern: decisions order the
per-tile trace segments the frontend records; the engine replays each
tile's stream in that order (SURVEY §2.10 — centralized services run
host-side between quanta).
"""

from __future__ import annotations

import collections
import dataclasses


@dataclasses.dataclass
class ThreadInfo:
    tid: int
    tile: int | None = None          # current tile (None until scheduled)
    affinity: frozenset | None = None  # allowed tiles (None = all)
    state: str = "new"               # new | queued | running | done


class RoundRobinThreadScheduler:
    """Round-robin placement over tiles + per-tile FIFO run queues.

    Queue head = the running thread (`m_waiter_queue` in the reference);
    `yield_thread` rotates head→tail (`round_robin_thread_scheduler.cc:21`).
    """

    def __init__(self, n_tiles: int):
        self.n_tiles = n_tiles
        self.queues = [collections.deque() for _ in range(n_tiles)]
        self.threads: dict[int, ThreadInfo] = {}
        self._next_tile = 0  # masterScheduleThread round-robin pointer

    # ---- placement (`masterScheduleThread`) -----------------------------

    def _allowed(self, info: ThreadInfo) -> list:
        if info.affinity is None:
            return list(range(self.n_tiles))
        return sorted(info.affinity)

    def schedule(self, tid: int, affinity=None,
                 requested_tile: int | None = None) -> int:
        """Place a new thread; returns its tile.  Prefers an idle allowed
        tile scanning round-robin from the placement pointer; otherwise
        enqueues on the least-loaded allowed tile."""
        info = self.threads.setdefault(tid, ThreadInfo(tid))
        if affinity is not None:
            info.affinity = frozenset(affinity)
        allowed = self._allowed(info)
        if not allowed:
            raise ValueError(f"thread {tid}: empty affinity mask")
        if requested_tile is not None:
            if not (0 <= requested_tile < self.n_tiles):
                raise ValueError(
                    f"thread {tid}: requested tile {requested_tile} out of "
                    f"range [0, {self.n_tiles})")
            if requested_tile not in allowed:
                raise ValueError(
                    f"thread {tid}: requested tile {requested_tile} not in "
                    "affinity mask")
            tile = requested_tile
        else:
            tile = None
            for i in range(self.n_tiles):
                cand = (self._next_tile + i) % self.n_tiles
                if cand in allowed and not self.queues[cand]:
                    tile = cand
                    break
            if tile is None:
                tile = min(allowed, key=lambda t: len(self.queues[t]))
            self._next_tile = (tile + 1) % self.n_tiles
        info.tile = tile
        info.state = "running" if not self.queues[tile] else "queued"
        self.queues[tile].append(tid)
        return tile

    def running_on(self, tile: int) -> int | None:
        q = self.queues[tile]
        return q[0] if q else None

    # ---- lifecycle (`masterOnThreadExit` → `masterStartThread`) ---------

    def thread_exit(self, tid: int) -> int | None:
        """Remove an exiting thread; returns the next thread to run on its
        tile (the new queue head), if any."""
        info = self.threads[tid]
        q = self.queues[info.tile]
        q.remove(tid)
        info.state = "done"
        if q:
            self.threads[q[0]].state = "running"
            return q[0]
        return None

    # ---- stall/resume (`ThreadManager::stallThread/resumeThread`) -------

    def block_thread(self, tid: int) -> int | None:
        """Take a blocking thread off its tile's run queue (join/stall) so
        queued threads can run; returns the tile's new running thread."""
        info = self.threads[tid]
        q = self.queues[info.tile]
        was_head = q and q[0] == tid
        q.remove(tid)
        info.state = "blocked"
        if was_head and q:
            self.threads[q[0]].state = "running"
            return q[0]
        return None

    def unblock_thread(self, tid: int) -> None:
        """Re-enqueue a previously blocked thread on its tile."""
        info = self.threads[tid]
        q = self.queues[info.tile]
        info.state = "running" if not q else "queued"
        q.append(tid)

    # ---- yield (`masterYieldThread` + round-robin requeue) --------------

    def yield_thread(self, tid: int) -> int:
        """Requeue the running head to the tail; returns the thread now at
        the head (may be the yielder itself if alone)."""
        info = self.threads[tid]
        q = self.queues[info.tile]
        assert q and q[0] == tid, "only the running thread may yield"
        if len(q) > 1:
            q.rotate(-1)
            info.state = "queued"
            self.threads[q[0]].state = "running"
        return q[0]

    # ---- migration (`masterMigrateThread`) ------------------------------

    def migrate(self, tid: int, dst_tile: int) -> int | None:
        """Move a thread to another tile's queue; returns the thread that
        now runs on the source tile (if the migrant was running there)."""
        info = self.threads[tid]
        if info.affinity is not None and dst_tile not in info.affinity:
            raise ValueError(
                f"thread {tid}: tile {dst_tile} not in affinity mask")
        src_q = self.queues[info.tile]
        was_head = src_q and src_q[0] == tid
        src_q.remove(tid)
        next_tid = None
        if was_head and src_q:
            next_tid = src_q[0]
            self.threads[next_tid].state = "running"
        info.tile = dst_tile
        dst_q = self.queues[dst_tile]
        info.state = "running" if not dst_q else "queued"
        dst_q.append(tid)
        return next_tid

    # ---- affinity (`masterSchedSetAffinity/GetAffinity`) ----------------

    def set_affinity(self, tid: int, tiles) -> int | None:
        """Restrict a thread to `tiles`; migrates it (round-robin pick from
        the mask) when its current tile falls outside — the reference's
        masterSchedSetAffinity behavior.  Returns the source tile's new
        running thread when a migration displaced the head."""
        info = self.threads[tid]
        info.affinity = frozenset(tiles)
        if info.tile is not None and info.tile not in info.affinity:
            allowed = self._allowed(info)
            idle = [t for t in allowed if not self.queues[t]]
            dst = idle[0] if idle else min(
                allowed, key=lambda t: len(self.queues[t]))
            return self.migrate(tid, dst)
        return None

    def get_affinity(self, tid: int) -> frozenset | None:
        return self.threads[tid].affinity
