"""Centralized syscall execution + simulated address-space layout.

Reference: the SyscallServer runs on the MCP tile and executes application
syscalls centrally so every process in a distributed simulation sees one
OS view (`common/system/syscall_server.cc`, 1,174 LoC: open/read/write/
close/lseek/access/mmap/brk/futex...); the client side marshals arguments
over the SYSTEM network (`common/tile/core/syscall_model.cc:132-244`).
VMManager lays out the simulated address space (`common/system/
vm_manager.cc`: segments, brk, mmap regions).

TPU-native form: functional execution is host-side (this module) against an
in-memory file system — the simulated-OS view — while the trace carries one
SYSCALL record per call (`Op.SYSCALL`) whose replay cost is the SYSTEM-net
round trip to the MCP (engine/step.py).  Futex never reaches here: the
frontend's mutex/cond/barrier map to the engine's sync machinery, the same
way the reference special-cases futex into the SyncServer path.
"""

from __future__ import annotations

import threading

# fcntl-style flags (subset the reference marshals)
O_RDONLY = 0x0
O_WRONLY = 0x1
O_RDWR = 0x2
O_CREAT = 0x40
O_TRUNC = 0x200
O_APPEND = 0x400

SEEK_SET = 0
SEEK_CUR = 1
SEEK_END = 2


class SimFile:
    """One regular file in the simulated FS (central byte store)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes = b""):
        self.data = bytearray(data)


class SyscallServer:
    """The MCP-side syscall executor over an in-memory simulated FS.

    Thread-safe: every operation takes the server lock, mirroring the MCP
    thread serializing all syscalls (`mcp.cc:59-146` dispatch).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._files: dict[str, SimFile] = {}
        # fd -> [SimFile, pos, flags]: the fd holds the file object itself,
        # so an unlinked file stays readable/writable until close (POSIX)
        self._fds: dict[int, list] = {}
        self._next_fd = 3  # 0/1/2 reserved (stdio pass-through)
        self._cwd = "/"
        self.counts: dict[str, int] = {}

    def _count(self, name: str) -> None:
        self.counts[name] = self.counts.get(name, 0) + 1

    # ---- files ----------------------------------------------------------

    def open(self, path: str, flags: int = O_RDONLY) -> int:
        with self._lock:
            self._count("open")
            f = self._files.get(path)
            if f is None:
                if not (flags & O_CREAT):
                    return -2  # -ENOENT
                f = self._files[path] = SimFile()
            if flags & O_TRUNC:
                del f.data[:]  # in place: open fds share the object
            pos = len(f.data) if (flags & O_APPEND) else 0
            fd = self._next_fd
            self._next_fd += 1
            self._fds[fd] = [f, pos, flags]
            return fd

    def close(self, fd: int) -> int:
        with self._lock:
            self._count("close")
            return 0 if self._fds.pop(fd, None) is not None else -9  # -EBADF

    def read(self, fd: int, nbytes: int) -> bytes | int:
        with self._lock:
            self._count("read")
            ent = self._fds.get(fd)
            if ent is None:
                return -9
            f, pos, flags = ent
            if (flags & 0x3) == O_WRONLY:
                return -9  # -EBADF: not open for reading
            data = bytes(f.data[pos:pos + nbytes])
            ent[1] = pos + len(data)
            return data

    def write(self, fd: int, data: bytes) -> int:
        with self._lock:
            self._count("write")
            ent = self._fds.get(fd)
            if ent is None:
                return -9
            f, pos, flags = ent
            if (flags & 0x3) == O_RDONLY:
                return -9  # -EBADF: not open for writing
            buf = f.data
            if len(buf) < pos + len(data):
                buf.extend(b"\x00" * (pos + len(data) - len(buf)))
            buf[pos:pos + len(data)] = data
            ent[1] = pos + len(data)
            return len(data)

    def lseek(self, fd: int, offset: int, whence: int = SEEK_SET) -> int:
        with self._lock:
            self._count("lseek")
            ent = self._fds.get(fd)
            if ent is None:
                return -9
            f, pos, _flags = ent
            size = len(f.data)
            new = {SEEK_SET: offset, SEEK_CUR: pos + offset,
                   SEEK_END: size + offset}.get(whence, -1)
            if new < 0:
                return -22  # -EINVAL
            ent[1] = new
            return new

    def access(self, path: str) -> int:
        with self._lock:
            self._count("access")
            return 0 if path in self._files else -2

    def unlink(self, path: str) -> int:
        with self._lock:
            self._count("unlink")
            return 0 if self._files.pop(path, None) is not None else -2

    def stat_size(self, path: str) -> int:
        with self._lock:
            self._count("stat")
            f = self._files.get(path)
            return len(f.data) if f is not None else -2

    # ---- the remaining marshalled surface (`syscall_model.cc:132-244`):
    # fstat/lstat, pipe, writev/readahead, getcwd/rmdir, ioctl,
    # clock_gettime.  futex/affinity land in the sync/thread machinery
    # (engine sync tables + ThreadScheduler), getpid is tile-local.

    def fstat_size(self, fd: int) -> int:
        with self._lock:
            self._count("fstat")
            ent = self._fds.get(fd)
            return len(ent[0].data) if ent is not None else -9

    def lstat_size(self, path: str) -> int:
        # the in-memory FS has no symlinks: lstat == stat
        with self._lock:
            self._count("lstat")
            f = self._files.get(path)
            return len(f.data) if f is not None else -2

    def pipe(self) -> tuple[int, int]:
        """fd pair over one shared byte store (read end consumes)."""
        with self._lock:
            self._count("pipe")
            f = SimFile()
            rd, wr = self._next_fd, self._next_fd + 1
            self._next_fd += 2
            self._fds[rd] = [f, 0, O_RDONLY]
            self._fds[wr] = [f, 0, O_WRONLY | O_APPEND]
            return rd, wr

    def writev(self, fd: int, chunks: list[bytes]) -> int:
        """Vectored write — ATOMIC like POSIX writev (one lock hold, so
        concurrent writev chunks can never interleave)."""
        with self._lock:
            self._count("writev")
            ent = self._fds.get(fd)
            if ent is None:
                return -9
            f, pos, flags = ent
            if (flags & 0x3) == O_RDONLY:
                return -9
            data = b"".join(bytes(c) for c in chunks)
            buf = f.data
            if len(buf) < pos + len(data):
                buf.extend(b"\x00" * (pos + len(data) - len(buf)))
            buf[pos:pos + len(data)] = data
            ent[1] = pos + len(data)
            return len(data)

    def readahead(self, fd: int, nbytes: int) -> int:
        with self._lock:
            self._count("readahead")
            return 0 if fd in self._fds else -9  # hint only: no data moves

    def getcwd(self) -> str:
        with self._lock:
            self._count("getcwd")
            return self._cwd

    def rmdir(self, path: str) -> int:
        """The flat FS models directories as path prefixes: rmdir fails
        -ENOTEMPTY while any file lives under the prefix, else succeeds."""
        with self._lock:
            self._count("rmdir")
            prefix = path.rstrip("/") + "/"
            if any(p.startswith(prefix) for p in self._files):
                return -39  # -ENOTEMPTY
            return 0

    def ioctl(self, fd: int, request: int) -> int:
        with self._lock:
            self._count("ioctl")
            if fd not in self._fds and fd > 2:
                return -9
            return -25  # -ENOTTY: no terminal devices in the sim FS

    def clock_gettime(self, sim_time_ns: int) -> tuple[int, int]:
        """CLOCK_* read returns SIMULATED time (the MCP answers with the
        simulation clock, keeping target time deterministic)."""
        with self._lock:
            self._count("clock_gettime")
            return sim_time_ns // 1_000_000_000, sim_time_ns % 1_000_000_000


class VMManager:
    """Simulated address-space layout (`vm_manager.cc`): a data segment
    grown by brk and a stack-down mmap region; munmap only unmaps whole
    trailing regions (the reference's simplification)."""

    def __init__(self, data_base: int = 0x1000_0000,
                 mmap_top: int = 0x7000_0000, page: int = 4096):
        self._lock = threading.Lock()
        self.page = page
        self.data_base = data_base
        self.brk_ptr = data_base
        self.mmap_top = mmap_top
        self.mmap_ptr = mmap_top
        self._regions: dict[int, int] = {}  # base -> length

    def brk(self, addr: int) -> int:
        with self._lock:
            if addr == 0:
                return self.brk_ptr
            if addr < self.data_base or addr >= self.mmap_ptr:
                return self.brk_ptr  # refused: return current (linux brk)
            self.brk_ptr = addr
            return self.brk_ptr

    def mmap(self, length: int) -> int:
        with self._lock:
            length = -(-length // self.page) * self.page
            self.mmap_ptr -= length
            if self.mmap_ptr <= self.brk_ptr:
                self.mmap_ptr += length
                return -12  # -ENOMEM
            self._regions[self.mmap_ptr] = length
            return self.mmap_ptr

    def munmap(self, base: int) -> int:
        with self._lock:
            length = self._regions.pop(base, None)
            if length is None:
                return -22
            if base == self.mmap_ptr:
                self.mmap_ptr += length
            return 0
