"""Checkpoint/resume of simulation state.

The reference has NO checkpointing (SURVEY §5: a host death hangs the
simulation and all progress is lost).  Here the entire simulation state is
one pytree of dense arrays, so a checkpoint is a flat npz of its leaves
plus the quantum counter; resume rebuilds the Simulator from the same
config+trace and restores the leaves.  Bitwise-exact: a resumed run
produces the same final state as an uninterrupted one (tested).
"""

from __future__ import annotations

import numpy as np

import jax

_SEP = "||"


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = _SEP.join(
            getattr(p, "name", None) or str(getattr(p, "idx", p))
            for p in path
        )
        out[key] = np.asarray(leaf)
    return out, treedef


def save_checkpoint(sim, path: str, n_quanta: int = 0) -> None:
    """Write the Simulator's current state (+ progress marker) to `path`."""
    leaves, _ = _flatten_with_paths(sim.state)
    leaves["__n_quanta__"] = np.asarray(n_quanta)
    np.savez_compressed(path, **leaves)


def load_checkpoint(sim, path: str) -> int:
    """Restore state saved by save_checkpoint into a Simulator built from
    the SAME config and trace.  Returns the saved quantum counter."""
    with np.load(path) as data:
        flat, treedef = jax.tree_util.tree_flatten_with_path(sim.state)
        restored = []
        for p, leaf in flat:
            key = _SEP.join(
                getattr(q, "name", None) or str(getattr(q, "idx", q))
                for q in p
            )
            if key not in data:
                raise ValueError(
                    f"checkpoint missing leaf {key!r} — was it saved from "
                    "a different config/topology?")
            arr = data[key]
            if arr.shape != leaf.shape:
                raise ValueError(
                    f"checkpoint leaf {key!r} shape {arr.shape} != "
                    f"state shape {leaf.shape}")
            restored.append(jax.numpy.asarray(arr, leaf.dtype))
        sim.state = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(sim.state), restored)
        return int(data["__n_quanta__"])
