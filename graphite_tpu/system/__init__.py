"""System-level services around the engine (the MCP/LCP service layer).

Reference: `common/system/` — StatisticsManager periodic sampling
(`statistics_manager.h:7-29`), the per-tile `Log` (`misc/log.h:13-110`),
progress trace (`pin/progress_trace.cc`), and the `sim.out` summary writer
(`simulator.cc:135-203`).  Checkpoint/resume is ABSENT in the reference
(SURVEY §5) — here the state pytree *is* the checkpoint, so it comes free.
"""

from graphite_tpu.system.checkpoint import load_checkpoint, save_checkpoint
from graphite_tpu.system.log import Log
from graphite_tpu.system.statistics import StatisticsManager

__all__ = [
    "Log",
    "StatisticsManager",
    "load_checkpoint",
    "save_checkpoint",
]
