"""Per-phase activity gating (round 6) + batched host-barrier dispatch.

The memory engines' six protocol phases each run under their OWN
scalar-predicate lax.cond (MemParams.phase_gate) whose carried operands
exclude the big directory stores — home phases return compact per-lane
delta plans applied outside the cond (engine._DirAcc / engine_shl2.
_RowAcc).  Gating is pure mechanism: these tests pin bit-exactness vs
the golden oracles and vs the ungated program, assert the program
STRUCTURE at a 1024-tile shape (one cond per phase, no cond output
carrying the directory stores — the round-2 double-buffering pathology),
and pin the batched `barrier_host` dispatch against the per-quantum one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.golden import run_golden
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder

MSI = "pr_l1_pr_l2_dram_directory_msi"
MOSI = "pr_l1_pr_l2_dram_directory_mosi"
SHL2_MSI = "pr_l1_sh_l2_msi"
SHL2_MESI = "pr_l1_sh_l2_mesi"


def make_config(n_tiles, proto=MSI, extra=""):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = magic
[caching_protocol]
type = {proto}
[core/static_instruction_costs]
mov = 1
ialu = 1
{extra}
"""
    return SimConfig(ConfigFile.from_string(text))


def mutex_rmw(n, rounds, base=0x900000, lines=2):
    """Mutex-serialized RMWs of shared lines (engine iteration order and
    oracle clock order coincide — the bit-exact contract)."""
    bs = [TraceBuilder() for _ in range(n)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, n)
    for b in bs:
        b.barrier_wait(9)
    for r in range(n * rounds):
        t = r % n
        addr = base + (r % lines) * 64
        bs[t].mutex_lock(0)
        bs[t].load(addr, 8)
        bs[t].store(addr, 8)
        bs[t].mutex_unlock(0)
    return TraceBatch.from_builders(bs)


def assert_exact_gated(sc, batch, **kw):
    """Gated run (phase conds the ONLY gating: whole-engine mem_gate
    forced off) must be bit-exact vs the golden oracle."""
    res = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0,
                    **kw).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps,
                                  err_msg="clock")
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)
    return res


# ---- bit-exactness vs the golden oracles ----------------------------------


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_gated_serialized_exact(proto):
    assert_exact_gated(make_config(4, proto), mutex_rmw(4, 5))


@pytest.mark.parametrize("proto", [SHL2_MSI, SHL2_MESI])
def test_gated_shl2_serialized_exact(proto):
    assert_exact_gated(make_config(4, proto), mutex_rmw(4, 5))


def test_gated_staged_exact():
    """Gating composes with directory write-staging: staged sharers ride
    the small table INSIDE the home-phase conds, flushes stay per-block
    outside; inner_block=4 crosses many flush boundaries."""
    assert_exact_gated(make_config(4, MSI), mutex_rmw(4, 4, lines=3),
                       dir_stage=True, inner_block=4)


def test_gated_limited_scheme_exact():
    """limited_no_broadcast issues THREE deferred _dir_update calls per
    home-start — the delta plan must sum them exactly."""
    extra = ("[dram_directory]\ndirectory_type = limited_no_broadcast\n"
             "max_hw_sharers = 2\n")
    assert_exact_gated(make_config(4, MSI, extra=extra), mutex_rmw(4, 4))


def test_gated_matches_ungated_racy():
    """On free-running racy traffic the engine may diverge from the
    oracle (documented envelope) but gated and ungated programs must be
    BIT-IDENTICAL to each other: gating is mechanism, not policy."""
    batch = synthetic.memory_stress_trace(
        8, n_accesses=80, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.6, seed=11)
    sc = make_config(8)
    r0 = Simulator(sc, batch, phase_gate=False, mem_gate_bytes=0).run()
    r1 = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0).run()
    np.testing.assert_array_equal(np.asarray(r0.clock_ps),
                                  np.asarray(r1.clock_ps))
    for k in r0.mem_counters:
        np.testing.assert_array_equal(np.asarray(r0.mem_counters[k]),
                                      np.asarray(r1.mem_counters[k]),
                                      err_msg=k)


def test_phase_gate_default_on():
    sim = Simulator(make_config(2), mutex_rmw(2, 1))
    assert sim.params.mem.phase_gate


# ---- gate observability ---------------------------------------------------


def test_phase_skip_counts():
    """Serialized traffic leaves most phases idle most iterations: the
    skip counters must be populated and bounded by the iteration count
    (the denominator for skip rates)."""
    sc = make_config(4, MSI)
    sim = Simulator(sc, mutex_rmw(4, 3), phase_gate=True, mem_gate_bytes=0)
    sim.run()
    skips = sim.last_phase_skips
    from graphite_tpu.memory.engine import PHASE_NAMES

    assert set(skips) == set(PHASE_NAMES)
    iters = int(sim.last_n_iterations)
    assert iters > 0
    assert all(0 <= v <= iters for v in skips.values()), (skips, iters)
    # a mutex-serialized workload cannot keep every phase busy every
    # iteration — some skips must have been recorded
    assert sum(skips.values()) > 0


def test_phase_skips_none_without_memory():
    cfg = """
[general]
total_cores = 2
mode = lite
[core/static_instruction_costs]
ialu = 1
"""
    bs = [TraceBuilder() for _ in range(2)]
    for b in bs:
        b.instr(Op.IALU)
    sim = Simulator(SimConfig(ConfigFile.from_string(cfg)),
                    TraceBatch.from_builders(bs))
    sim.run()
    assert sim.last_phase_skips is None


# ---- program structure at the 1024-tile shape -----------------------------


def test_phase_cond_structure_1024_shape():
    """The acceptance shape: a 1024-tile program (CPU-scaled caches /
    directory) TRACES with per-phase conds — one cond per protocol phase
    — and NO cond output carries the directory entry or sharers stores
    (cond branch outputs are double-buffered by XLA; keeping the big
    stores out of them is what lets gating survive where the >= 1 GB
    whole-engine gate disable used to apply).  Structural jaxpr
    assertion, no TPU wall-clock needed.

    Traversal and the cond-payload assertion are served by the SHARED
    program-auditor pass (graphite_tpu/analysis) — the same walker and
    rule `python -m graphite_tpu.tools.audit` runs on every config, so
    there is one source of truth for jaxpr traversal."""
    T = 1024
    # geometries chosen so the directory entry/sharers avals are UNIQUE
    # in the program (l1i (32,2), l1d (32,4), l2 (64,8) meta vs entry
    # (16,4) / sharers (16,128)) — the aval check below must not false-
    # positive on a cache meta array of coincidentally equal shape
    extra = """
[l1_icache/T1]
cache_size = 4
associativity = 2
[l1_dcache/T1]
cache_size = 8
associativity = 4
[l2_cache/T1]
cache_size = 32
associativity = 8
[dram_directory]
total_entries = 64
associativity = 4
"""
    sc = make_config(T, MSI, extra=extra)
    bs = []
    for t in range(T):
        b = TraceBuilder()
        b.load(0x100000 + t * 64, 8)
        b.store(0x100000 + (t % 7) * 64, 8)
        bs.append(b)
    batch = TraceBatch.from_builders(bs)
    # mem_gate_bytes=0: the big-state regime — whole-engine gate off,
    # per-phase conds are the only gating (exactly the config-5 shape)
    sim = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0)
    assert sim.params.mem_gate is False
    assert sim.params.mem.phase_gate is True

    from graphite_tpu.engine.step import subquantum_iteration

    qend = jnp.asarray(2**61, jnp.int64)
    closed = jax.make_jaxpr(
        lambda st: subquantum_iteration(sim.params, sim.device_trace,
                                        st, qend))(sim.state)

    from graphite_tpu.analysis import iter_eqns
    from graphite_tpu.analysis.rules import cond_payload, phase_conds
    from graphite_tpu.memory.engine import dir_store_avals

    conds = [e for e in iter_eqns(closed)
             if e.primitive.name == "cond"]
    assert conds, "gated program lost its lax.conds"

    # one cond per protocol phase: each phase cond writes at least one
    # uint8[T, T] mailbox type matrix, and nothing else in the program
    # does (jax prunes unmodified pass-through cond outputs, so only the
    # matrices a phase actually writes appear)
    n_phase_conds = len(phase_conds(closed, T))
    assert n_phase_conds == 6, (
        f"expected one cond per protocol phase (6), found "
        f"{n_phase_conds}")

    # no cond output may be (a copy of) the directory stores: the shared
    # cond-payload rule, fed the engine's own store signatures (the
    # geometry above keeps them unique in the program)
    findings = cond_payload(closed,
                            forbidden=dir_store_avals(sim.state.mem))
    assert not findings, (
        "a lax.cond output carries a directory store — the round-2 "
        "double-buffering pathology is back:\n"
        + "\n".join(str(f) for f in findings))


# ---- batched host-barrier dispatch ----------------------------------------


class TestBarrierBatch:
    def _workload(self):
        from graphite_tpu.tools._template import config_text

        sc = SimConfig(ConfigFile.from_string(config_text(
            8, shared_mem=True, clock_scheme="lax_barrier")))
        batch = synthetic.memory_stress_trace(
            8, n_accesses=40, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.6, seed=5)
        return sc, batch

    def test_batched_matches_per_quantum_and_device(self):
        sc, batch = self._workload()
        r_dev = Simulator(sc, batch).run()
        r_b1 = Simulator(sc, batch, barrier_host=True,
                         barrier_batch=1).run()
        r_b8 = Simulator(sc, batch, barrier_host=True,
                         barrier_batch=8).run()
        for name, r in (("batch=1", r_b1), ("batch=8", r_b8)):
            assert r_dev.clock_ps.tolist() == r.clock_ps.tolist(), name
            assert r_dev.n_quanta == r.n_quanta, name
            for k in r_dev.mem_counters:
                np.testing.assert_array_equal(
                    np.asarray(r_dev.mem_counters[k]),
                    np.asarray(r.mem_counters[k]), err_msg=f"{name}:{k}")

    def test_batched_deadlock_detected(self):
        from graphite_tpu.engine.simulator import DeadlockError
        from graphite_tpu.tools._template import config_text

        sc = SimConfig(ConfigFile.from_string(config_text(
            4, clock_scheme="lax_barrier")))
        b0 = TraceBuilder()
        b0.recv(1)
        bs = [b0] + [TraceBuilder() for _ in range(3)]
        for b in bs[1:]:
            b.instr(Op.IALU)
        with pytest.raises(DeadlockError):
            Simulator(sc, TraceBatch.from_builders(bs),
                      barrier_host=True, barrier_batch=8).run()


# ---- plain-unroll clamp ---------------------------------------------------


def test_plain_unroll_clamped_and_warns():
    from graphite_tpu.engine.step import PLAIN_UNROLL_MAX

    cfg = """
[general]
total_cores = 2
mode = lite
plain_unroll = 32
[core/static_instruction_costs]
ialu = 1
"""
    bs = [TraceBuilder() for _ in range(2)]
    for b in bs:
        for _ in range(8):
            b.instr(Op.IALU)
    batch = TraceBatch.from_builders(bs)
    with pytest.warns(UserWarning, match="plain_unroll"):
        sim = Simulator(SimConfig(ConfigFile.from_string(cfg)), batch)
    assert sim.params.plain_unroll == PLAIN_UNROLL_MAX
    # the clamped program still runs and matches an explicit-16 run
    r32 = sim.run()
    cfg16 = cfg.replace("plain_unroll = 32", "plain_unroll = 16")
    r16 = Simulator(SimConfig(ConfigFile.from_string(cfg16)), batch).run()
    assert r32.clock_ps.tolist() == r16.clock_ps.tolist()


# ---- dir_stage on shared-L2: the real constraint --------------------------


def test_dir_stage_shl2_states_real_constraint():
    """Round-6 satellite: the shared-L2 rejection must state the REAL
    constraint (the embedded directory writes one row-form scatter per
    phase — nothing to stage), not a stale 'pending support' message."""
    with pytest.raises(ValueError, match="row-form scatter"):
        Simulator(make_config(4, SHL2_MSI), mutex_rmw(4, 1),
                  dir_stage=True)
