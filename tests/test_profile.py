"""The spatial profiler (graphite_tpu/obs/profile.py, round 16).

The contract pins:
 - `profile=None` (the default) lowers the HISTORICAL program — jaxpr
   structurally identical to the legacy entry point, with zero profile
   invars (the telemetry=None / knobs=None contract, also enforced by
   the `profile-off` audit lint);
 - recording is pure observability: a profile-enabled run's SimResults
   are bit-equal to its profile=None twin;
 - the recorded per-tile rows match a hand-stepped chunked oracle
   (run_chunk(1) + host-side per-tile differencing) sample for sample;
 - cross-ring consistency: with telemetry + profile on one sampling
   cursor, every shared delta series sums over T to the scalar column
   and max(clock_skew) + clock_min == clock_max;
 - the ring wraps at S exhaustion keeping the LAST S samples;
 - vmapped campaigns demux [B, S, T, m] per-sim profiles equal to
   sequential profile runs (shard_map campaigns gather per-device
   buffers through the same demux);
 - serve jobs with differing profile specs never co-batch (distinct
   admission class keys) and envelopes carry the demuxed TileProfile;
 - the heatmap CLI renders a deterministic golden shape.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphite_tpu.analysis import rules
from graphite_tpu.analysis.audit import spec_from_simulator
from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.obs import (
    PROFILE_CORE_SERIES, PROFILE_LEVEL_SERIES, ProfileSpec, TileProfile,
    available_tile_series, gini, grid_shape,
)
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

TILES = 8
QUANTUM_PS = 1_000_000   # config_text default: 1000 ns lax_barrier


def _config(extra: str = ""):
    return SimConfig(ConfigFile.from_string(config_text(
        TILES, shared_mem=True, clock_scheme="lax_barrier") + extra))


def _trace(seed=7, n=24):
    return synthetic.memory_stress_trace(
        TILES, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _spec(interval=QUANTUM_PS, s=64, series=None):
    return ProfileSpec(sample_interval_ps=interval, n_samples=s,
                       series=series)


class TestSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="positive"):
            ProfileSpec(sample_interval_ps=0)
        with pytest.raises(ValueError, match="positive"):
            ProfileSpec(sample_interval_ps=1, n_samples=0)

    def test_resolve_selects_and_dedupes(self):
        sim = Simulator(_config(), _trace())
        spec = _spec(series=("l2_misses", "clock_skew_ps",
                             "l2_misses")).resolve(sim.params)
        assert spec.series == ("l2_misses", "clock_skew_ps")
        assert spec.n_series == 2
        assert spec.n_tiles == TILES
        assert spec.buffer_sig() == ((64, TILES, 2), "int64")

    def test_resolve_rejects_unknown_series(self):
        sim = Simulator(_config(), _trace())
        with pytest.raises(ValueError, match="unavailable profile"):
            _spec(series=("no_such_series",)).resolve(sim.params)

    def test_dense_series_set(self):
        sim = Simulator(_config(), _trace())
        avail = available_tile_series(sim.params)
        assert set(PROFILE_CORE_SERIES) <= set(avail)
        spec = _spec().resolve(sim.params)
        assert spec.series == avail

    def test_memoryless_program_offers_core_series_only(self):
        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, clock_scheme="lax_barrier")))
        batch = synthetic.message_ring_batch(TILES, n_rounds=4,
                                             compute_per_round=8)
        sim = Simulator(sc, batch)
        assert available_tile_series(sim.params) == PROFILE_CORE_SERIES
        with pytest.raises(ValueError, match="unavailable"):
            _spec(series=("l2_misses",)).resolve(sim.params)

    def test_energy_series_needs_prices(self):
        sim = Simulator(_config(), _trace())
        with pytest.raises(ValueError, match="energy_prices"):
            _spec(series=("energy_pj",)).resolve(sim.params)

    def test_ring_bytes_accounting(self):
        sim = Simulator(_config(), _trace())
        spec = _spec(s=32, series=("clock_skew_ps",
                                   "l2_misses")).resolve(sim.params)
        S, T, m = 32, TILES, 2
        assert spec.ring_bytes() == (S * T * m + T * m + S + 2) * 8

    def test_attach_rejects_stream_and_requires_spec(self):
        sim = Simulator(_config(), _trace(), stream=True)
        with pytest.raises(ValueError, match="single-device resident"):
            sim.attach_profile(_spec())
        sim2 = Simulator(_config(), _trace())
        with pytest.raises(TypeError, match="ProfileSpec"):
            sim2.attach_profile({"sample_interval_ps": 1})

    def test_grid_shape_and_gini(self):
        assert grid_shape(64) == (8, 8)
        assert grid_shape(8) == (3, 3)
        assert grid_shape(1) == (1, 1)
        assert gini([1, 1, 1, 1]) == 0.0
        assert gini([0, 0, 0, 0]) == 0.0
        # one tile carries everything: G -> 1 - 1/n
        assert gini([0, 0, 0, 8]) == pytest.approx(0.75)


class TestProgramIdentity:
    def test_profile_none_is_the_baseline_program(self):
        """profile=None must lower jaxpr-identically to the legacy
        entry point that never heard of the profiler, with zero
        profile invars."""
        from graphite_tpu.analysis.identity import same_program
        from graphite_tpu.engine.step import run_simulation

        sim = Simulator(_config(), _trace())
        closed_none, paths = sim.lower(max_quanta=512)
        params, qps = sim.params, sim.quantum_ps

        def legacy(st, tr):
            return run_simulation(params, tr, st, qps, 512)

        closed_legacy = jax.make_jaxpr(legacy)(sim.state,
                                               sim.device_trace)
        assert same_program(closed_none, closed_legacy)
        assert not any("profile" in p for p in paths)
        assert not rules.telemetry_off(closed_none, paths,
                                       state_key="profile",
                                       rule="profile-off")

    def test_profile_off_lint_fires_on_recording_program(self):
        simt = Simulator(_config(), _trace(), profile=_spec())
        closed, paths = simt.lower(max_quanta=512)
        fs = rules.telemetry_off(
            closed, paths, ring_sigs=(simt.profile_spec.buffer_sig(),),
            state_key="profile", rule="profile-off")
        assert fs
        assert all(f.rule == "profile-off" for f in fs)
        assert any("invar" in f.message for f in fs)

    def test_profile_off_lint_catches_internal_ring(self):
        S, T, m = 16, TILES, 4

        def bad(x):
            buf = jnp.zeros((S, T, m), jnp.int64)
            return buf.at[0, 0, 0].set(x)

        closed = jax.make_jaxpr(bad)(jnp.asarray(1, jnp.int64))
        fs = rules.telemetry_off(closed, ["x"],
                                 ring_sigs=(((S, T, m), "int64"),),
                                 state_key="profile",
                                 rule="profile-off")
        assert fs and fs[0].data["shape"] == [S, T, m]

    def test_ring_buffer_forbidden_in_conds(self):
        """Profile-on programs add the [S, T, m] aval to the
        cond-payload forbidden set; the real program passes, a toy cond
        carrying the ring fires."""
        simt = Simulator(_config(), _trace(), phase_gate=True,
                         mem_gate_bytes=0, profile=_spec())
        spec = spec_from_simulator("prof", simt, max_quanta=512)
        assert simt.profile_spec.buffer_sig() in \
            spec.forbidden_cond_avals
        assert spec.expect_profile
        assert not rules.cond_payload(
            spec.closed, forbidden=spec.forbidden_cond_avals)

        sig = simt.profile_spec.buffer_sig()

        def bad(p, buf):
            return jax.lax.cond(p, lambda b: b + 1, lambda b: b, buf)

        closed = jax.make_jaxpr(bad)(True, jnp.zeros(sig[0], jnp.int64))
        assert rules.cond_payload(closed, forbidden=(sig,))

    def test_off_specs_carry_profile_sigs_and_audit_passes(self):
        """Profile-OFF specs carry the canonical dense per-tile ring
        sig (plus the energy variant, one series wider), so the aval
        scan is live; a profile-ON program clears the full audit."""
        from graphite_tpu.analysis.audit import audit

        sim = Simulator(_config(), _trace())
        off = spec_from_simulator("off", sim, max_quanta=512)
        assert not off.expect_profile
        assert off.profile_sig is not None
        (S, T, m), dt = off.profile_sig
        assert T == TILES
        assert off.profile_extra_sigs[0] == ((S, T, m + 1), dt)

        simt = Simulator(_config(), _trace(), phase_gate=True,
                         mem_gate_bytes=0, profile=_spec())
        on = spec_from_simulator("prof-on", simt, max_quanta=512)
        report = audit([off, on])
        assert report.ok, [str(f) for f in report.errors]
        assert "profile-off" in {r.rule for r in report.results
                                 if r.program == "off"}
        assert "profile-off" not in {r.rule for r in report.results
                                     if r.program == "prof-on"}


class TestRecording:
    def test_results_bit_equal_and_profile_attached(self):
        batch = _trace()
        r_off = Simulator(_config(), batch).run()
        sim = Simulator(_config(), batch, profile=_spec())
        r_on = sim.run()
        np.testing.assert_array_equal(r_on.clock_ps, r_off.clock_ps)
        np.testing.assert_array_equal(r_on.instruction_count,
                                      r_off.instruction_count)
        for k in r_off.mem_counters:
            np.testing.assert_array_equal(
                r_on.mem_counters[k], r_off.mem_counters[k], err_msg=k)
        assert r_on.n_quanta == r_off.n_quanta
        assert r_off.profile is None
        pf = r_on.profile
        assert isinstance(pf, TileProfile)
        assert len(pf) > 0 and not pf.wrapped
        assert pf.data.shape[1:] == (TILES, sim.profile_spec.n_series)
        np.testing.assert_array_equal(sim.profile.data, pf.data)
        # the final row is the completion sample; per-tile delta series
        # sum (over samples AND tiles) to the run totals
        assert int(pf.times_ps[-1]) == r_on.completion_time_ps
        assert int(pf.col("instructions").sum()) == r_on.total_instructions
        np.testing.assert_array_equal(pf.col("packets_sent").sum(axis=0),
                                      r_on.packets_sent)
        np.testing.assert_array_equal(
            pf.col("l2_misses").sum(axis=0),
            r_on.mem_counters["l2_misses"])

    def test_rows_match_chunked_oracle(self):
        """Per-tile sample correctness: step the SAME sim quantum by
        quantum from the host (run_chunk(1)), difference the fetched
        per-tile counters by hand, and require the device rows to
        match exactly."""
        batch = _trace()
        series = ("clock_skew_ps", "instructions", "packets_sent",
                  "l2_misses")
        interval = 1_500_000   # 1.5 quanta — forces skipped boundaries
        simt = Simulator(_config(), batch,
                         profile=_spec(interval=interval, series=series))
        pf = simt.run().profile
        order = simt.profile_spec.series

        ref = Simulator(_config(), batch)
        prev = np.zeros((TILES, len(order)), np.int64)
        next_ps = interval
        rows = []
        times = []
        for _ in range(10_000):
            done, _ = ref.run_chunk(1)
            st = ref.state
            clocks, done_mask, instr, sent, mc = jax.device_get(
                (st.core.clock_ps, st.done, st.core.instruction_count,
                 st.net.packets_sent, st.mem.counters.l2_misses))
            pending = clocks[~done_mask]
            sim_time = int(pending.min() if pending.size
                           else clocks.max())
            cur_map = {
                "clock_skew_ps": clocks - clocks.min(),
                "instructions": instr,
                "packets_sent": sent,
                "l2_misses": mc,
            }
            cur = np.stack([np.asarray(cur_map[s], np.int64)
                            for s in order], axis=1)
            if sim_time >= next_ps or done:
                row = np.where(
                    np.array([s not in PROFILE_LEVEL_SERIES
                              for s in order])[None, :],
                    cur - prev, cur)
                rows.append(row)
                times.append(sim_time)
                prev = cur
                next_ps = (sim_time // interval + 1) * interval
            if done:
                break
        assert done
        np.testing.assert_array_equal(pf.data, np.array(rows))
        np.testing.assert_array_equal(pf.times_ps,
                                      np.array(times, np.int64))

    def test_cross_ring_sums_match_scalar_telemetry(self):
        """The free invariant: both rings on one sampling cursor —
        every shared delta series sums over T to the scalar column;
        the skew column reconstructs the clock spread."""
        from graphite_tpu.obs import TelemetrySpec

        batch = _trace()
        tel = TelemetrySpec(sample_interval_ps=QUANTUM_PS, n_samples=64)
        res = Simulator(_config(), batch, telemetry=tel,
                        profile=_spec()).run()
        pf, tl = res.profile, res.telemetry
        assert pf.n_total == tl.n_total
        np.testing.assert_array_equal(pf.times_ps, tl.col("time_ps"))
        for s in ("instructions", "packets_sent", "sync_stall_ps",
                  "l2_misses", "invalidations", "evictions"):
            np.testing.assert_array_equal(
                pf.col(s).sum(axis=1), tl.col(s), err_msg=s)
        np.testing.assert_array_equal(
            pf.col("clock_skew_ps").max(axis=1) + tl.col("clock_min_ps"),
            tl.col("clock_max_ps"))

    def test_per_tile_energy_sums_to_scalar_energy(self):
        from graphite_tpu.obs import EnergyPrices, TelemetrySpec

        prices = EnergyPrices(
            instruction_pj=3, l1d_access_pj=2, l2_access_pj=9,
            l2_miss_pj=120, invalidation_pj=15, eviction_pj=20,
            dram_access_pj=500, packet_pj=7)
        batch = _trace()
        tel = TelemetrySpec(sample_interval_ps=QUANTUM_PS, n_samples=64,
                            series=("energy_pj",),
                            energy_prices=prices)
        prof = ProfileSpec(sample_interval_ps=QUANTUM_PS, n_samples=64,
                           series=("energy_pj",), energy_prices=prices)
        res = Simulator(_config(), batch, telemetry=tel,
                        profile=prof).run()
        np.testing.assert_array_equal(
            res.profile.col("energy_pj").sum(axis=1),
            res.telemetry.col("energy_pj"))

    def test_ring_wraparound_keeps_last_samples(self):
        batch = _trace()
        big = Simulator(_config(), batch, profile=_spec(s=64))
        pf_big = big.run().profile
        assert pf_big.n_total > 2
        small = Simulator(_config(), batch, profile=_spec(s=2))
        pf = small.run().profile
        assert pf.wrapped and pf.n_total == pf_big.n_total
        assert len(pf) == 2
        np.testing.assert_array_equal(pf.data, pf_big.data[-2:])
        np.testing.assert_array_equal(pf.times_ps, pf_big.times_ps[-2:])

    def test_barrier_host_dispatch_records_identically(self):
        batch = _trace()
        pf_dev = Simulator(_config(), batch,
                           profile=_spec()).run().profile
        sim_hb = Simulator(_config(), batch, barrier_host=True,
                           barrier_batch=2, profile=_spec())
        pf_hb = sim_hb.run().profile
        assert pf_hb.n_total == pf_dev.n_total
        np.testing.assert_array_equal(pf_hb.data, pf_dev.data)
        np.testing.assert_array_equal(pf_hb.times_ps, pf_dev.times_ps)

    def test_save_load_roundtrip_and_heatmap_cli(self, tmp_path,
                                                 capsys):
        from graphite_tpu.tools.report import main as report_main

        pf = Simulator(_config(), _trace(),
                       profile=_spec()).run().profile
        path = str(tmp_path / "prof.npz")
        pf.save(path)
        back = TileProfile.load(path)
        assert back.series == pf.series
        assert back.n_total == pf.n_total
        np.testing.assert_array_equal(back.data, pf.data)
        np.testing.assert_array_equal(back.times_ps, pf.times_ps)

        # JSON rows: one per selected series, full [T] vector
        assert report_main([path, "--heatmap", "--format", "json",
                            "--series", "l2_misses"]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
        assert lines[0]["series"] == "l2_misses"
        assert lines[0]["tiles"] == [
            int(v) for v in pf.tile_slice("l2_misses", "total")]
        assert lines[-1]["straggler_tile"] == \
            pf.summary()["straggler_tile"]

        # golden text render: header + ceil(T/cols) grid rows of shade
        # digits per series, then the summary block
        assert report_main([path, "--heatmap", "--format", "text",
                            "--series", "clock_skew_ps",
                            "--slice", "last"]) == 0
        out = capsys.readouterr().out.splitlines()
        rows, cols = grid_shape(TILES)
        assert out[0].startswith("== sim 0:")
        assert out[1].startswith("-- clock_skew_ps [slice last] min ")
        grid = out[2:2 + rows]
        assert len(grid) == rows
        flat = "".join(grid).replace(" ", "")
        assert len(flat) == TILES
        assert set(flat) <= set("0123456789")
        assert "straggler_tile" in "".join(out)

    def test_timeline_summary_peaks_argmax(self, tmp_path, capsys):
        """The round-16 small fix: scalar timeline summaries name
        their per-series argmax sample/time."""
        from graphite_tpu.obs import TelemetrySpec
        from graphite_tpu.tools.report import main as report_main

        tl = Simulator(_config(), _trace(), telemetry=TelemetrySpec(
            sample_interval_ps=QUANTUM_PS,
            n_samples=64)).run().telemetry
        peaks = tl.summary()["peaks"]
        assert "l2_misses" in peaks and "clock_spread_ps" in peaks
        p = peaks["l2_misses"]
        col = tl.col("l2_misses")
        assert p["max"] == int(col.max())
        assert p["sample"] == int(np.argmax(col))
        assert p["time_ns"] == int(tl.time_ns[np.argmax(col)])
        path = str(tmp_path / "tl.npz")
        tl.save(path)
        assert report_main([path, "--format", "text",
                            "--summary"]) == 0
        assert "peak l2_misses" in capsys.readouterr().out


class TestSweepDemux:
    def test_vmap_campaign_demuxes_per_sim_profiles(self):
        from graphite_tpu.sweep import SweepRunner

        seeds = (1, 2, 3)
        traces = [_trace(seed=s) for s in seeds]
        sweep = SweepRunner(_config(), traces, shard_batch=False,
                            profile=_spec())
        out = sweep.run()
        assert out.profiles is not None and len(out.profiles) == 3
        n_series = sweep.sim.profile_spec.n_series
        for b in range(3):
            pf = out.profiles[b]
            assert pf.data.shape[1:] == (TILES, n_series)
            assert out.results[b].profile is pf
            solo = Simulator(_config(), traces[b],
                             mailbox_depth=sweep.mailbox_depth,
                             phase_gate=False, mem_gate_bytes=0,
                             profile=_spec()).run().profile
            assert pf.n_total == solo.n_total
            np.testing.assert_array_equal(pf.data, solo.data,
                                          err_msg=f"sim {b}")
            np.testing.assert_array_equal(pf.times_ps, solo.times_ps)

    def test_shard_map_campaign_gathers_device_buffers(self):
        from graphite_tpu.sweep import SweepRunner

        if len(jax.devices()) < 2:
            pytest.skip("needs the multi-device CPU platform")
        B = len(jax.devices())
        traces = [_trace(seed=s) for s in range(B)]
        sweep = SweepRunner(_config(), traces, shard_batch=True,
                            profile=_spec())
        out = sweep.run()
        assert len(out.profiles) == B
        for b in (0, B - 1):
            solo = Simulator(_config(), traces[b],
                             mailbox_depth=sweep.mailbox_depth,
                             profile=_spec()).run().profile
            assert out.profiles[b].n_total == solo.n_total
            np.testing.assert_array_equal(out.profiles[b].data,
                                          solo.data, err_msg=f"sim {b}")

    def test_campaign_residency_itemizes_profile_rings(self):
        from graphite_tpu.sweep import SweepRunner

        traces = [_trace(seed=s) for s in (1, 2)]
        sweep = SweepRunner(_config(), traces, shard_batch=False,
                            profile=_spec())
        bd = sweep.residency_breakdown()
        assert bd["profile"] == 2 * sweep.sim.profile_spec.ring_bytes()


class TestServe:
    def test_class_key_splits_on_profile_spec(self):
        from graphite_tpu.serve import CampaignService, Job

        svc = CampaignService(batch_size=4)
        batch = _trace()
        j_off = Job("off", _config(), batch)
        j_a = Job("a", _config(), batch, profile=_spec())
        j_b = Job("b", _config(), batch, profile=_spec(s=32))
        j_a2 = Job("a2", _config(), batch, profile=_spec())
        keys = [svc.admission.class_key(j)
                for j in (j_off, j_a, j_b, j_a2)]
        assert keys[1] != keys[0]
        assert keys[1] != keys[2]
        assert keys[1] == keys[3]

    def test_served_profile_matches_sequential(self):
        from graphite_tpu.serve import CampaignService, Job

        svc = CampaignService(batch_size=2, max_quanta=200_000,
                              verify_hits=True)
        jobs = [Job(f"p{i}", _config(), _trace(seed=i + 1),
                    profile=_spec()) for i in range(2)]
        for j in jobs:
            svc.submit(j)
        served = {r.job_id: r for r in svc.drain()}
        for j in jobs:
            got = served[j.job_id]
            assert got.ok and got.profile is not None
            assert got.to_json()["profile_samples"] == len(got.profile)
            seq = Simulator(_config(), j.trace,
                            mailbox_depth=svc.admission.classes[
                                svc.admission.class_key(j)].mailbox_depth,
                            phase_gate=False, mem_gate_bytes=0,
                            profile=_spec()).run().profile
            assert got.profile.n_total == seq.n_total
            np.testing.assert_array_equal(got.profile.data, seq.data)
        assert svc.counters["compile_count"] == 1

    def test_admission_bill_includes_profile_ring(self):
        from graphite_tpu.serve import CampaignService, Job

        svc = CampaignService(batch_size=2)
        job = Job("p", _config(), _trace(), profile=_spec())
        cls, _ = svc.admission.admit(job)
        assert cls.per_sim_bytes["profile"] == cls.profile.ring_bytes()
        assert "-prof" in svc._class_name(cls)

    def test_serve_cli_profile_out_writes_npz(self, tmp_path, capsys):
        from graphite_tpu.tools.serve import main as serve_main

        jobs = tmp_path / "jobs.jsonl"
        jobs.write_text(json.dumps({
            "id": "cli0", "tiles": 4, "seed": 1, "accesses": 8,
            "profile": {"sample_interval_ps": 1_000_000,
                        "n_samples": 16}}) + "\n")
        out_dir = tmp_path / "profiles"
        assert serve_main(["--jobs", str(jobs), "--batch-size", "1",
                           "--profile-out", str(out_dir)]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
        row = next(r for r in lines if r.get("job") == "cli0")
        path = row["profile_file"]
        assert path == str(out_dir / "cli0.npz")
        saved = TileProfile.load(path)
        assert saved.n_tiles == 4
        assert len(saved) == row["profile_samples"]


class TestTradeCurve:
    SPANS = [
        {"trace": "batch-0", "span": "batch", "start_us": 0,
         "dur_us": 900, "occupancy": 1.0, "n_jobs": 2, "capacity": 2},
        {"trace": "batch-1", "span": "batch", "start_us": 0,
         "dur_us": 700, "occupancy": 0.5, "n_jobs": 1, "capacity": 2},
        {"trace": "j0", "span": "queue", "start_us": 0, "dur_us": 100,
         "batch": 0},
        {"trace": "j1", "span": "queue", "start_us": 0, "dur_us": 300,
         "batch": 0},
        {"trace": "j2", "span": "queue", "start_us": 0, "dur_us": 40,
         "batch": 1},
        # no matching batch span: dropped from the scatter
        {"trace": "j3", "span": "queue", "start_us": 0, "dur_us": 5,
         "batch": 9},
        # not a queue span: ignored
        {"trace": "j0", "span": "execute", "start_us": 0, "dur_us": 1,
         "batch": 0},
    ]

    def test_rows_and_buckets(self):
        from graphite_tpu.tools.report import trade_curve_rows

        scatter, curve = trade_curve_rows(self.SPANS)
        assert [s["job"] for s in scatter] == ["j0", "j1", "j2"]
        assert scatter[0] == {"job": "j0", "batch": 0,
                              "queue_dwell_us": 100, "occupancy": 1.0,
                              "n_jobs": 2, "capacity": 2,
                              "execute_us": 900}
        assert [c["occupancy_bucket"] for c in curve] == [0.5, 1.0]
        assert curve[1]["jobs"] == 2
        assert curve[1]["mean_dwell_us"] == 200
        assert curve[1]["max_dwell_us"] == 300
        assert curve[0]["mean_execute_us"] == 700

    def test_cli_render(self, tmp_path, capsys):
        from graphite_tpu.tools.report import main as report_main

        path = tmp_path / "spans.jsonl"
        path.write_text("".join(json.dumps(r) + "\n"
                                for r in self.SPANS))
        assert report_main(["--trade-curve", str(path)]) == 0
        rows = [json.loads(ln) for ln in
                capsys.readouterr().out.strip().splitlines()]
        assert sum(1 for r in rows if r.get("curve")) == 2
        assert sum(1 for r in rows if "job" in r) == 3
        assert report_main(["--trade-curve", str(path), "--format",
                            "text"]) == 0
        out = capsys.readouterr().out
        assert "queue_dwell_us" in out and "occupancy_bucket" in out
