"""Campaign service (graphite_tpu/serve/): admission control, the
fingerprint-keyed compiled-program cache, and the scheduler loop.

The contract pins:
 - jobs served through the batched campaign path are BIT-IDENTICAL
   (results + telemetry) to sequential Simulator runs — the service is
   scheduling, never semantics;
 - N same-fingerprint jobs trigger exactly ONE compile (round-7
   compile-count probe on the cached jitted runner), and a
   registry-mismatched fingerprint at cache-insert time errors loudly;
 - no admitted batch's residency_breakdown total ever exceeds
   `hbm_budget_bytes`; a job that can never fit is rejected at submit
   with the itemized per-consumer breakdown;
 - mixed geometries never co-batch; padded-batch tail masks never leak
   into the result stream; batch-failure split/retry converges; FIFO
   fairness holds under backpressure.
"""

import dataclasses

import numpy as np
import pytest

from graphite_tpu.analysis.cost import ResidencyBudgetError
from graphite_tpu.analysis.registry import ProgramRecord
from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import DeadlockError, Simulator
from graphite_tpu.obs import TelemetrySpec
from graphite_tpu.serve import (
    AdmissionController, CacheEntry, CampaignService, Job, JobResult,
    ProgramCache, ProgramCacheError, QueueFullError, STATUS_OK,
)
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.validate import TraceValidationError

TILES = 4


def _config(clock="lax"):
    return SimConfig(ConfigFile.from_string(config_text(
        TILES, shared_mem=True, clock_scheme=clock)))


def _trace(seed, n=10, tiles=TILES):
    return synthetic.memory_stress_trace(
        tiles, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _assert_results_equal(ra, rb, msg=""):
    np.testing.assert_array_equal(ra.clock_ps, rb.clock_ps, err_msg=msg)
    np.testing.assert_array_equal(
        ra.instruction_count, rb.instruction_count, err_msg=msg)
    assert ra.n_quanta == rb.n_quanta, msg
    if ra.mem_counters is not None:
        for k in ra.mem_counters:
            np.testing.assert_array_equal(
                ra.mem_counters[k], rb.mem_counters[k],
                err_msg=f"{msg}: {k}")


# ---------------------------------------------------------------------------
# job validation
# ---------------------------------------------------------------------------


class TestJobValidation:
    def test_geometry_mismatch(self):
        job = Job("j", _config(), _trace(1, tiles=8))
        with pytest.raises(ValueError, match="tiles"):
            job.validate()

    def test_unknown_knob(self):
        job = Job("j", _config(), _trace(1), knobs={"nope": 3})
        with pytest.raises(ValueError, match="unknown knob"):
            job.validate()

    def test_quantum_knob_needs_lax_barrier(self):
        job = Job("j", _config("lax"), _trace(1),
                  knobs={"quantum_ps": 1000})
        with pytest.raises(ValueError, match="lax_barrier"):
            job.validate()
        # the clock_scheme override can LEGALIZE the knob
        Job("j", _config("lax"), _trace(1), knobs={"quantum_ps": 1000},
            clock_scheme="lax_barrier").validate()

    def test_bad_clock_scheme(self):
        job = Job("j", _config(), _trace(1), clock_scheme="strict")
        with pytest.raises(ValueError, match="clock_scheme"):
            job.validate()

    def test_malformed_trace_rejected(self):
        bad = _trace(1)
        bad = dataclasses.replace(
            bad, op=np.where(bad.op == bad.op[0, 0], np.uint8(250),
                             bad.op))
        with pytest.raises(TraceValidationError):
            Job("j", _config(), bad).validate()

    def test_telemetry_type_checked(self):
        job = Job("j", _config(), _trace(1), telemetry={"interval": 1})
        with pytest.raises(ValueError, match="TelemetrySpec"):
            job.validate()


# ---------------------------------------------------------------------------
# program cache (pure host-side)
# ---------------------------------------------------------------------------


def _entry(name, fp="gfp1:aa", nbytes=100, shape=(2, 4, 16)):
    return CacheEntry(name=name,
                      record=ProgramRecord(name=name, fingerprint=fp,
                                           tiles=4),
                      jitted=lambda *a: None, max_quanta=1000,
                      nbytes=nbytes, shape_sig=shape)


class TestProgramCache:
    def test_byte_accounted_lru_eviction(self):
        cache = ProgramCache(max_bytes=250)
        for k in ("a", "b"):
            cache.put(k, _entry(k), expect_fingerprint="gfp1:aa")
        assert cache.get("a", (2, 4, 16)) is not None  # a now most-recent
        cache.put("c", _entry("c"), expect_fingerprint="gfp1:aa")
        # b was least-recently-used: evicted to fit 250 bytes
        assert cache.keys() == ["a", "c"]
        assert cache.evictions == 1
        assert cache.total_bytes <= 250

    def test_newest_entry_survives_even_over_budget(self):
        cache = ProgramCache(max_bytes=50)
        cache.put("a", _entry("a", nbytes=100),
                  expect_fingerprint="gfp1:aa")
        assert cache.keys() == ["a"]

    def test_insert_fingerprint_mismatch_errors_loudly(self):
        cache = ProgramCache()
        with pytest.raises(ProgramCacheError, match="registered identity"):
            cache.put("a", _entry("a", fp="gfp1:bb"),
                      expect_fingerprint="gfp1:aa")
        assert len(cache) == 0

    def test_shape_sig_mismatch_errors_instead_of_recompiling(self):
        cache = ProgramCache()
        cache.put("a", _entry("a"), expect_fingerprint="gfp1:aa")
        with pytest.raises(ProgramCacheError, match="shape"):
            cache.get("a", (4, 4, 16))


# ---------------------------------------------------------------------------
# admission control (host arithmetic; probes are built, never run)
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_never_fits_rejected_with_itemized_breakdown(self):
        svc = CampaignService(hbm_budget_bytes=1000, batch_size=2)
        with pytest.raises(ResidencyBudgetError,
                           match="can never fit") as ei:
            svc.submit(Job("big", _config(), _trace(1)))
        bd = ei.value.breakdown
        assert set(bd) >= {"state", "trace", "total"}
        assert bd["total"] == bd["state"] + bd["trace"]
        assert "state" in str(ei.value) and "trace" in str(ei.value)
        assert svc.counters["rejected"] == 1

    def test_budget_caps_batch_capacity(self):
        probe = AdmissionController(batch_size=8)
        cls, _ = probe.admit(Job("p", _config(), _trace(1)))
        per_sim = cls.per_sim_total
        adm = AdmissionController(
            hbm_budget_bytes=int(2.5 * per_sim), batch_size=8)
        cls2, _ = adm.admit(Job("q", _config(), _trace(1)))
        assert cls2.batch_cap == 2
        assert cls2.breakdown(cls2.batch_cap)["total"] \
            <= int(2.5 * per_sim)
        # one more sim would not fit
        assert cls2.breakdown(cls2.batch_cap + 1)["total"] \
            > int(2.5 * per_sim)

    def test_backpressure_queue_full(self):
        svc = CampaignService(max_pending=2)
        svc.submit(Job("a", _config(), _trace(1)))
        svc.submit(Job("b", _config(), _trace(2)))
        with pytest.raises(QueueFullError, match="max_pending"):
            svc.submit(Job("c", _config(), _trace(3)))
        # backpressure is not a rejection: the job may resubmit later
        assert svc.counters["backpressure"] == 1
        assert svc.counters["rejected"] == 0
        assert svc.queue_depth == 2

    def test_class_keys_split_on_geometry_and_scheme(self):
        adm = AdmissionController()
        sc8 = SimConfig(ConfigFile.from_string(config_text(
            8, shared_mem=True, clock_scheme="lax")))
        k4 = adm.class_key(Job("a", _config(), _trace(1)))
        k8 = adm.class_key(Job("b", sc8, _trace(1, tiles=8)))
        k4lb = adm.class_key(Job("c", _config(), _trace(1),
                                 clock_scheme="lax_barrier"))
        k4tel = adm.class_key(Job("d", _config(), _trace(1),
                                  telemetry=TelemetrySpec(
                                      sample_interval_ps=1000)))
        assert len({k4, k8, k4lb, k4tel}) == 4
        # same shape + knob-only difference: SAME class (knobs are traced)
        k4b = adm.class_key(Job("e", _config(), _trace(2),
                                knobs={"dram_latency_ns": 99}))
        assert k4b == k4
        # a flags-memless trace keys separately — the exact per-sim
        # agreement SweepRunner enforces, so the runner's mixed-memness
        # refusal is unreachable from the service
        from graphite_tpu.trace.schema import Op
        t = _trace(1)
        memless = dataclasses.replace(
            t, flags=np.zeros_like(t.flags),
            op=np.where(t.op < 20, np.uint8(int(Op.IALU)), t.op))
        k4m = adm.class_key(Job("f", _config(), memless))
        assert k4m != k4

    def test_fifo_across_classes_serves_oldest_head(self):
        adm = AdmissionController(batch_size=2)
        sc8 = SimConfig(ConfigFile.from_string(config_text(
            8, shared_mem=True, clock_scheme="lax")))
        adm.admit(Job("a0", _config(), _trace(1)))
        adm.admit(Job("b0", sc8, _trace(1, tiles=8)))
        adm.admit(Job("a1", _config(), _trace(2)))
        adm.admit(Job("b1", sc8, _trace(2, tiles=8)))
        cls1, batch1 = adm.next_batch()
        assert [p.job.job_id for p in batch1] == ["a0", "a1"]
        cls2, batch2 = adm.next_batch()
        assert [p.job.job_id for p in batch2] == ["b0", "b1"]
        assert adm.next_batch() is None
        assert cls1 is not cls2


# ---------------------------------------------------------------------------
# scheduler policies (stubbed execution — no compiles)
# ---------------------------------------------------------------------------


def _stub_ok(svc):
    def execute(cls, pendings, batch_id):
        svc._last_residency = cls.breakdown(cls.batch_cap)["total"]
        return [JobResult(job_id=p.job.job_id, status=STATUS_OK,
                          batch_id=batch_id, attempts=p.attempts + 1)
                for p in pendings]
    return execute


class TestSchedulerPolicies:
    def test_mixed_geometries_never_cobatched(self, monkeypatch):
        svc = CampaignService(batch_size=4)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        sc8 = SimConfig(ConfigFile.from_string(config_text(
            8, shared_mem=True, clock_scheme="lax")))
        tiles_of = {}
        for i in range(3):
            svc.submit(Job(f"t4-{i}", _config(), _trace(i + 1)))
            tiles_of[f"t4-{i}"] = 4
            svc.submit(Job(f"t8-{i}", sc8, _trace(i + 1, tiles=8)))
            tiles_of[f"t8-{i}"] = 8
        done = svc.run_all()
        assert len(done) == 6
        for rep in svc.batch_log:
            sizes = {tiles_of[j] for j in rep.job_ids}
            assert len(sizes) == 1, f"batch {rep.batch_id} mixed {sizes}"
            assert rep.n_tiles == sizes.pop()
        assert len(svc.batch_log) == 2

    def test_split_retry_converges_to_singletons(self, monkeypatch):
        svc = CampaignService(batch_size=4, max_attempts=5)

        def flaky(cls, pendings, batch_id):
            if len(pendings) > 1:
                raise DeadlockError("multi-job batch poisoned")
            return _stub_ok(svc)(cls, pendings, batch_id)

        monkeypatch.setattr(svc, "_execute", flaky)
        ids = [f"j{i}" for i in range(4)]
        for i, jid in enumerate(ids):
            svc.submit(Job(jid, _config(), _trace(i + 1)))
        done = svc.run_all()
        assert sorted(r.job_id for r in done) == ids
        assert all(r.ok for r in done)
        # FIFO preserved through the splits
        assert [r.job_id for r in done] == ids
        c = svc.counters
        assert c["splits"] >= 2 and c["failed"] == 0
        assert c["completed"] == 4

    def test_always_failing_job_terminates_with_failed_envelope(
            self, monkeypatch):
        svc = CampaignService(batch_size=2, max_attempts=3)

        def always_fail(cls, pendings, batch_id):
            raise DeadlockError("always")

        monkeypatch.setattr(svc, "_execute", always_fail)
        svc.submit(Job("a", _config(), _trace(1)))
        svc.submit(Job("b", _config(), _trace(2)))
        for _ in range(64):   # hard bound: no infinite requeue
            if not svc.queue_depth:
                break
            svc.step()
        assert svc.queue_depth == 0
        done = svc.results
        assert sorted(r.job_id for r in done) == ["a", "b"]
        assert all(not r.ok and "DeadlockError" in r.error for r in done)
        assert all(r.attempts == 3 for r in done)
        assert svc.counters["failed"] == 2

    def test_fifo_order_under_backpressure(self, monkeypatch):
        svc = CampaignService(batch_size=2, max_pending=3)
        monkeypatch.setattr(svc, "_execute", _stub_ok(svc))
        order = []
        for i in range(8):
            job = Job(f"j{i}", _config(), _trace(i % 3 + 1))
            while True:
                try:
                    svc.submit(job)
                    break
                except QueueFullError:
                    order.extend(r.job_id for r in svc.step())
        order.extend(r.job_id for r in svc.drain())
        assert order == [f"j{i}" for i in range(8)]


# ---------------------------------------------------------------------------
# end-to-end: real compiles, bit-equality, the compile-count probe
# ---------------------------------------------------------------------------


SERVE_SEEDS = (1, 2, 3)
SERVE_KNOBS = ({}, {"dram_latency_ns": 140}, {"hop_latency_cycles": 3})


@pytest.fixture(scope="module")
def served_campaign():
    """One budgeted service run shared by the end-to-end pins: three
    same-class jobs, batch_size 2 -> a full batch + a PADDED batch
    through one cached program, with hit verification on."""
    probe = AdmissionController(batch_size=2)
    cls, _ = probe.admit(Job("probe", _config(), _trace(1)))
    budget = int(2.4 * cls.per_sim_total)
    svc = CampaignService(hbm_budget_bytes=budget, batch_size=2,
                          max_quanta=200_000, verify_hits=True)
    jobs = [Job(f"j{i}", _config(), _trace(s), knobs=dict(k), seed=s)
            for i, (s, k) in enumerate(zip(SERVE_SEEDS, SERVE_KNOBS))]
    for j in jobs:
        svc.submit(j)
    results = {r.job_id: r for r in svc.drain()}
    return svc, jobs, results, budget


class TestServiceEndToEnd:
    def test_bit_identical_to_sequential(self, served_campaign):
        svc, jobs, results, _ = served_campaign
        assert sorted(results) == [j.job_id for j in jobs]
        for job in jobs:
            sim = Simulator(_config(), job.trace)
            if job.knobs:
                sim.params = dataclasses.replace(
                    sim.params,
                    mem=dataclasses.replace(sim.params.mem, **job.knobs))
            ref = sim.run()
            got = results[job.job_id]
            assert got.ok
            _assert_results_equal(got.results, ref, msg=job.job_id)

    def test_one_compile_for_n_same_fingerprint_jobs(
            self, served_campaign):
        svc, jobs, _, _ = served_campaign
        c = svc.counters
        assert c["compile_count"] == 1
        assert c["cache_hits"] == 1          # batch 2 hit batch 1's entry
        assert c["cache_hit_rate"] == 0.5
        assert len(svc.cache) == 1
        [entry] = svc.cache._entries.values()
        # the round-7 probe: ONE compiled executable served every batch
        assert entry.jitted._cache_size() == 1
        # and the entry resolves through the registry
        assert svc.registry[entry.name].fingerprint \
            == entry.record.fingerprint

    def test_padded_tail_never_leaks(self, served_campaign):
        svc, jobs, results, _ = served_campaign
        assert len(results) == 3             # 2 batches of capacity 2
        full, padded = svc.batch_log
        assert (full.n_jobs, full.batch_cap) == (2, 2)
        assert (padded.n_jobs, padded.batch_cap) == (1, 2)
        assert padded.occupancy == 0.5
        assert svc.counters["mean_batch_occupancy"] == pytest.approx(0.75)

    def test_no_admitted_batch_exceeds_budget(self, served_campaign):
        svc, _, _, budget = served_campaign
        assert svc.batch_log
        for rep in svc.batch_log:
            assert rep.residency_total <= budget, rep

    def test_registry_mismatch_at_insert_errors_loudly(
            self, served_campaign):
        svc, jobs, _, _ = served_campaign
        [name] = list(svc.registry)
        original = svc.registry[name]
        # force the next batch to MISS, with a poisoned registered
        # identity: the re-lowered fingerprint cannot match, and the
        # insert must refuse loudly instead of serving the program
        svc.cache._entries.clear()
        svc.registry[name] = dataclasses.replace(
            original, fingerprint="gfp1:" + "0" * 64)
        try:
            svc.submit(Job("poisoned", _config(), _trace(1)))
            with pytest.raises(ProgramCacheError, match="registered"):
                svc.step()
        finally:
            svc.registry[name] = original
            # the poisoned pending was consumed by the failed step


class TestServeTelemetryAndSchemes:
    def test_telemetry_jobs_equal_sequential_timelines(self):
        tel = TelemetrySpec(sample_interval_ps=1_000_000, n_samples=32)
        svc = CampaignService(batch_size=2, max_quanta=200_000)
        for i, s in enumerate((1, 2)):
            svc.submit(Job(f"t{i}", _config(), _trace(s), telemetry=tel))
        out = {r.job_id: r for r in svc.drain()}
        for i, s in enumerate((1, 2)):
            # the vmapped campaign program runs gates-off (SweepRunner
            # default), so the skip_* series oracle must too
            solo = Simulator(_config(), _trace(s), phase_gate=False,
                             mem_gate_bytes=0, telemetry=tel).run()
            tl = out[f"t{i}"].telemetry
            assert tl is not None
            assert tl.n_total == solo.telemetry.n_total
            np.testing.assert_array_equal(tl.data, solo.telemetry.data)
            _assert_results_equal(out[f"t{i}"].results, solo, msg=f"t{i}")

    def test_clock_scheme_axis_batches_separately(self):
        svc = CampaignService(batch_size=2, max_quanta=200_000)
        svc.submit(Job("lb", _config(), _trace(5),
                       clock_scheme="lax_barrier"))
        svc.submit(Job("lx", _config(), _trace(5)))
        out = {r.job_id: r for r in svc.drain()}
        assert len({b.class_name for b in svc.batch_log}) == 2
        ref = Simulator(SimConfig(ConfigFile.from_string(config_text(
            TILES, shared_mem=True, clock_scheme="lax_barrier"))),
            _trace(5)).run()
        _assert_results_equal(out["lb"].results, ref, msg="lax_barrier")
        ref_lax = Simulator(_config(), _trace(5)).run()
        _assert_results_equal(out["lx"].results, ref_lax, msg="lax")
