"""tools/parse_output analog: sim.out round-trips through the parser."""

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.tools.parse_output import parse_sim_out
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def test_parse_sim_out_roundtrip(tmp_path):
    text = """
[general]
total_cores = 2
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[network]
user = magic
memory = magic
[core/static_instruction_costs]
ialu = 1
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    sc = SimConfig(ConfigFile.from_string(text))
    b0 = TraceBuilder()
    for _ in range(5):
        b0.instr(Op.IALU)
    sim = Simulator(sc, TraceBatch.from_builders([b0, TraceBuilder()]))
    res = sim.run()
    out_path = sim.write_output(res, output_dir=str(tmp_path))
    parsed = parse_sim_out(open(out_path).read())
    assert parsed["total_instructions"] == 5
    assert parsed["target_completion_time_ns"] == 5
    assert parsed["tiles"][0]["Core Summary / Total Instructions"] == 5
    assert parsed["tiles"][1]["Core Summary / Total Instructions"] == 0
