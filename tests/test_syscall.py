"""Syscall server + VMManager + SYSCALL replay timing.

Mirrors the reference's file-I/O unit test (`tests/unit/` file_io: threads
write their rank into a shared file through the central SyscallServer and
read it back) and the `vm_manager.cc` brk/mmap layout rules.
"""

import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.frontend import CarbonApp, CarbonBarrier, carbon_spawn_thread
from graphite_tpu.frontend.carbon_api import (
    carbon_access,
    carbon_brk,
    carbon_close,
    carbon_join_thread,
    carbon_lseek,
    carbon_mmap,
    carbon_munmap,
    carbon_open,
    carbon_read,
    carbon_unlink,
    carbon_work,
    carbon_write,
)
from graphite_tpu.system.syscall_server import (
    O_CREAT,
    O_RDWR,
    SEEK_SET,
    SyscallServer,
    VMManager,
)
from graphite_tpu.trace.schema import Op, TraceBuilder, TraceBatch, SYS_OPEN


def make_config(n_tiles):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


class TestSyscallServer:
    def test_open_write_read(self):
        s = SyscallServer()
        fd = s.open("/tmp/x", O_CREAT | O_RDWR)
        assert fd >= 3
        assert s.write(fd, b"hello") == 5
        assert s.lseek(fd, 0, SEEK_SET) == 0
        assert s.read(fd, 5) == b"hello"
        assert s.close(fd) == 0
        assert s.close(fd) == -9

    def test_enoent_and_unlink(self):
        s = SyscallServer()
        assert s.open("/nope") == -2
        assert s.access("/nope") == -2
        fd = s.open("/a", O_CREAT)
        s.close(fd)
        assert s.access("/a") == 0
        assert s.unlink("/a") == 0
        assert s.unlink("/a") == -2

    def test_unlinked_fd_stays_alive(self):
        """POSIX: an open fd keeps an unlinked file readable/writable
        until close."""
        s = SyscallServer()
        fd = s.open("/tmp/x", O_CREAT | O_RDWR)
        assert s.unlink("/tmp/x") == 0
        assert s.write(fd, b"hello") == 5
        assert s.lseek(fd, 0, SEEK_SET) == 0
        assert s.read(fd, 5) == b"hello"
        assert s.access("/tmp/x") == -2  # gone from the namespace
        assert s.close(fd) == 0

    def test_sparse_write_via_lseek(self):
        s = SyscallServer()
        fd = s.open("/f", O_CREAT | O_RDWR)
        s.lseek(fd, 8, SEEK_SET)
        s.write(fd, b"zz")
        assert s.stat_size("/f") == 10
        s.lseek(fd, 0, SEEK_SET)
        assert s.read(fd, 10) == b"\x00" * 8 + b"zz"


class TestVMManager:
    def test_brk_grow_and_query(self):
        vm = VMManager()
        base = vm.brk(0)
        assert vm.brk(base + 4096) == base + 4096
        assert vm.brk(0) == base + 4096
        # refused below the data segment
        assert vm.brk(1) == base + 4096

    def test_mmap_stack_down_and_munmap(self):
        vm = VMManager()
        a = vm.mmap(1000)            # rounded to one page
        b = vm.mmap(4096)
        assert b == a - 4096
        assert vm.munmap(b) == 0
        assert vm.munmap(b) == -22
        c = vm.mmap(4096)
        assert c == b                # trailing region reused


class TestFileIOApp:
    def test_ranks_file_io(self):
        """Each thread writes its rank at offset rank*4 through the central
        server; after the barrier every thread reads the whole file back."""
        T = 4
        app = CarbonApp(make_config(T))

        def worker(bar, me):
            fd = carbon_open("/ranks", O_CREAT | O_RDWR)
            carbon_lseek(fd, me * 4, SEEK_SET)
            carbon_write(fd, me.to_bytes(4, "little"))
            carbon_close(fd)
            bar.wait()
            fd = carbon_open("/ranks", O_RDWR)
            data = carbon_read(fd, 4 * T)
            carbon_close(fd)
            for r in range(T):
                assert int.from_bytes(data[r * 4:(r + 1) * 4], "little") == r

        def main():
            bar = CarbonBarrier(T)
            tids = [carbon_spawn_thread(worker, bar, i + 1)
                    for i in range(T - 1)]
            worker(bar, 0)
            for t in tids:
                carbon_join_thread(t)

        app.start(main)
        res = app.run()
        assert res.func_errors == 0
        assert app.syscalls.counts["open"] == 2 * T
        assert app.syscalls.counts["write"] == T

    def test_mmap_brk_from_app(self):
        app = CarbonApp(make_config(1))

        def main():
            b0 = carbon_brk(0)
            assert carbon_brk(b0 + 8192) == b0 + 8192
            m = carbon_mmap(4096)
            assert m > 0
            assert carbon_munmap(m) == 0

        app.start(main)
        res = app.run()
        assert res.func_errors == 0


class TestSyscallTiming:
    def test_round_trip_cost(self):
        """A syscall blocks for the SYSTEM-net round trip to the MCP
        (magic net: 1 cycle each way at 1 GHz = 2 ns)."""
        sc = make_config(1)
        b = TraceBuilder()
        b.instr(Op.IALU)     # 1 ns
        b.syscall(SYS_OPEN)  # 2 ns
        b.instr(Op.IALU)     # 1 ns
        from graphite_tpu.engine.simulator import Simulator

        res = Simulator(sc, TraceBatch.from_builders([b])).run()
        assert res.clock_ps[0] == 4_000
        # syscalls are not instructions
        assert res.instruction_count[0] == 2


class TestDvfsGetTiming:
    def test_dvfs_get_round_trip_cost(self):
        """DVFS_GET blocks for the DVFS-network round trip (magic net:
        2 cycles at 1 GHz = 2 ns), mirroring the syscall path."""
        sc = make_config(1)
        b = TraceBuilder()
        b.instr(Op.IALU)          # 1 ns
        b._append(Op.DVFS_GET, aux0=0)  # 2 ns
        b.instr(Op.IALU)          # 1 ns
        from graphite_tpu.engine.simulator import Simulator

        res = Simulator(sc, TraceBatch.from_builders([b])).run()
        assert res.clock_ps[0] == 4_000


class TestWideSurface:
    """The rest of the reference-marshalled surface
    (`syscall_model.cc:132-244`)."""

    def test_pipe_roundtrip(self):
        s = SyscallServer()
        rd, wr = s.pipe()
        assert s.write(wr, b"hello") == 5
        assert s.read(rd, 5) == b"hello"
        assert s.read(rd, 5) == b""      # drained
        assert s.close(rd) == 0 and s.close(wr) == 0

    def test_fstat_lstat(self):
        s = SyscallServer()
        fd = s.open("/a", O_CREAT | 0x1)
        s.write(fd, b"abc")
        assert s.fstat_size(fd) == 3
        assert s.lstat_size("/a") == 3
        assert s.fstat_size(99) == -9
        assert s.lstat_size("/nope") == -2

    def test_writev_readahead(self):
        s = SyscallServer()
        fd = s.open("/v", O_CREAT | 0x1)
        assert s.writev(fd, [b"ab", b"cd", b"e"]) == 5
        assert s.fstat_size(fd) == 5
        assert s.readahead(fd, 1024) == 0
        assert s.readahead(1234, 1) == -9

    def test_getcwd_rmdir_ioctl_clock(self):
        s = SyscallServer()
        assert s.getcwd() == "/"
        s.open("/dir/x", O_CREAT)
        assert s.rmdir("/dir") == -39    # not empty
        s.unlink("/dir/x")
        assert s.rmdir("/dir") == 0
        assert s.ioctl(0, 0x5401) == -25  # TCGETS on no-tty
        sec, ns = s.clock_gettime(2_500_000_123)
        assert (sec, ns) == (2, 500_000_123)
        # every call is counted like the reference's per-syscall stats
        for name in ("pipe", "getcwd", "rmdir", "ioctl", "clock_gettime"):
            assert s.counts.get(name, 0) >= 0
