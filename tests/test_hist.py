"""Device-resident latency histograms (graphite_tpu/obs/hist.py, round 21).

The contract pins:
 - `hist=None` (the default) lowers the HISTORICAL program — jaxpr
   structurally identical to the legacy entry point, with zero hist
   invars (the telemetry=None / profile=None contract, also enforced
   by the `hist-off` audit lint, which matches whole path segments so
   the pre-existing `line_util_hist` counter never trips it);
 - recording is pure observability: a hist-enabled run's SimResults
   are bit-equal to its hist=None twin;
 - CONSERVATION: every histogram total bit-equals the matching
   cumulative counter (`conservation_totals` documents each pairing) —
   the distribution analogue of round-16's cross-ring sum invariant;
 - boundary-source rows match a hand-stepped chunked oracle
   (run_chunk(1) + host-side searchsorted, one fleet skew observation
   per executed quantum);
 - quantiles use THE one shared definition (obs.metrics
   bucket_quantile), bit-equal to a host metrics Histogram over
   identical buckets;
 - vmapped campaigns demux [B, ...] bucket rings per sim equal to
   sequential runs (shard_map campaigns gather through the same
   demux);
 - serve jobs with differing hist specs never co-batch (distinct
   admission class keys) and the residency bill itemizes the ring;
 - the --perfetto export merges spans + timelines + histograms into
   one valid Chrome-trace JSON with per-pid monotone timestamps.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphite_tpu.analysis import rules
from graphite_tpu.analysis.audit import spec_from_simulator
from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.obs import (
    HIST_BOUNDARY_SOURCES, HIST_CORE_SOURCES, HIST_MEM_SOURCES, Hist,
    HistSpec, available_hist_sources, conservation_totals,
)
from graphite_tpu.obs.metrics import Histogram, bucket_quantile
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

TILES = 8
QUANTUM_PS = 1_000_000   # config_text default: 1000 ns lax_barrier


def _config(extra: str = ""):
    return SimConfig(ConfigFile.from_string(config_text(
        TILES, shared_mem=True, clock_scheme="lax_barrier") + extra))


def _trace(seed=7, n=24):
    return synthetic.memory_stress_trace(
        TILES, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _ring_batch():
    return synthetic.message_ring_batch(TILES, n_rounds=4,
                                        compute_per_round=8)


class TestSpec:
    def test_edge_validation_matrix(self):
        with pytest.raises(ValueError, match="non-empty"):
            HistSpec(edges=())
        with pytest.raises(ValueError, match="non-negative"):
            HistSpec(edges=(-1, 4))
        with pytest.raises(ValueError, match="strictly ascending"):
            HistSpec(edges=(1, 4, 4))
        with pytest.raises(ValueError, match="strictly ascending"):
            HistSpec(edges=(8, 4))
        with pytest.raises(ValueError, match="log2_buckets"):
            HistSpec(log2_buckets=1)
        # valid: explicit ladder wins over log2_buckets
        spec = HistSpec(edges=(10, 100, 1000))
        np.testing.assert_array_equal(spec.bucket_edges(),
                                      [10, 100, 1000])
        assert spec.n_buckets == 4

    def test_log2_ladder(self):
        spec = HistSpec(log2_buckets=6)
        np.testing.assert_array_equal(spec.bucket_edges(),
                                      [1, 2, 4, 8, 16])
        assert spec.n_buckets == 6

    def test_resolve_selects_and_dedupes(self):
        sim = Simulator(_config(), _trace())
        spec = HistSpec(sources=("miss_lat_ps", "clock_skew_ps",
                                 "miss_lat_ps")).resolve(sim.params)
        assert spec.sources == ("miss_lat_ps", "clock_skew_ps")
        assert spec.n_sources == 2
        assert spec.n_tiles == TILES
        assert spec.resolved

    def test_dense_source_set(self):
        sim = Simulator(_config(), _trace())
        avail = available_hist_sources(sim.params)
        assert avail == (HIST_CORE_SOURCES + HIST_MEM_SOURCES
                         + HIST_BOUNDARY_SOURCES)
        assert HistSpec().resolve(sim.params).sources == avail

    def test_memoryless_program_offers_no_mem_sources(self):
        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, clock_scheme="lax_barrier")))
        sim = Simulator(sc, _ring_batch())
        assert available_hist_sources(sim.params) == \
            HIST_CORE_SOURCES + HIST_BOUNDARY_SOURCES
        with pytest.raises(ValueError, match="unavailable"):
            HistSpec(sources=("miss_lat_ps",)).resolve(sim.params)

    def test_energy_source_needs_prices(self):
        sim = Simulator(_config(), _trace())
        with pytest.raises(ValueError, match="energy_prices"):
            HistSpec(sources=("energy_pj",)).resolve(sim.params)

    def test_buffer_sig_and_ring_bytes(self):
        sim = Simulator(_config(), _trace())
        spec = HistSpec(sources=("miss_lat_ps", "clock_skew_ps"),
                        log2_buckets=16).resolve(sim.params)
        assert spec.buffer_sig() == ((2, 16), "int64")
        assert spec.ring_bytes() == (2 * 16 + 1) * 8
        pt = HistSpec(sources=("clock_skew_ps",), log2_buckets=8,
                      per_tile=True).resolve(sim.params)
        assert pt.buffer_sig() == ((TILES, 1, 8), "int64")
        assert pt.ring_bytes() == (TILES * 8 + 1) * 8
        # tile-sharded per-device bill: the tile axis divides, the
        # boundaries cursor stays replicated
        assert pt.ring_bytes(tile_shards=2) == (TILES // 2 * 8 + 1) * 8
        with pytest.raises(ValueError, match="not divisible"):
            pt.ring_bytes(tile_shards=3)

    def test_attach_rejects_stream_and_requires_spec(self):
        sim = Simulator(_config(), _trace(), stream=True)
        with pytest.raises(ValueError, match="single-device resident"):
            sim.attach_hist(HistSpec())
        sim2 = Simulator(_config(), _trace())
        with pytest.raises(TypeError, match="HistSpec"):
            sim2.attach_hist({"log2_buckets": 16})


class TestProgramIdentity:
    def test_hist_none_is_the_baseline_program(self):
        """hist=None must lower jaxpr-identically to the legacy entry
        point that never heard of histograms, with zero hist invars —
        and the pre-existing `line_util_hist` counter (a path whose
        SUBSTRING contains 'hist') must not trip the segment-matching
        lint."""
        from graphite_tpu.analysis.identity import same_program
        from graphite_tpu.engine.step import run_simulation

        sim = Simulator(_config(), _trace())
        closed_none, paths = sim.lower(max_quanta=512)
        params, qps = sim.params, sim.quantum_ps

        def legacy(st, tr):
            return run_simulation(params, tr, st, qps, 512)

        closed_legacy = jax.make_jaxpr(legacy)(sim.state,
                                               sim.device_trace)
        assert same_program(closed_none, closed_legacy)
        assert any("line_util_hist" in p for p in paths)
        assert not any(
            "hist" in p.split(".")[-1] and "line_util" not in p
            for p in paths)
        assert not rules.telemetry_off(closed_none, paths,
                                       state_key="hist",
                                       rule="hist-off")

    def test_hist_off_lint_fires_on_recording_program(self):
        simt = Simulator(_config(), _trace(), hist=HistSpec())
        closed, paths = simt.lower(max_quanta=512)
        fs = rules.telemetry_off(
            closed, paths, ring_sigs=(simt.hist_spec.buffer_sig(),),
            state_key="hist", rule="hist-off")
        assert fs
        assert all(f.rule == "hist-off" for f in fs)
        assert any("invar" in f.message for f in fs)

    def test_hist_off_lint_catches_internal_ring(self):
        H, B = 4, 16

        def bad(x):
            buf = jnp.zeros((H, B), jnp.int64)
            return buf.at[0, 0].add(x)

        closed = jax.make_jaxpr(bad)(jnp.asarray(1, jnp.int64))
        fs = rules.telemetry_off(closed, ["x"],
                                 ring_sigs=(((H, B), "int64"),),
                                 state_key="hist", rule="hist-off")
        assert fs and fs[0].data["shape"] == [H, B]

    def test_lint_segment_matching_known_bads(self):
        """The path matcher flags real hist state leaves in any
        spelling — attribute, index, quoted key — but never a segment
        that merely CONTAINS 'hist'."""
        closed = jax.make_jaxpr(lambda x: x + 1)(
            jnp.asarray(1, jnp.int64))
        for bad in ("[0].hist.buf", "state.hist.boundaries",
                    "carry['hist'].buf"):
            assert rules.telemetry_off(closed, [bad],
                                       state_key="hist",
                                       rule="hist-off"), bad
        for ok in ("[0].mem.counters.line_util_hist",
                   "state.history_log", "tiles.hist0gram"):
            assert not rules.telemetry_off(closed, [ok],
                                           state_key="hist",
                                           rule="hist-off"), ok

    def test_ring_buffer_forbidden_in_conds(self):
        simt = Simulator(_config(), _trace(), phase_gate=True,
                         mem_gate_bytes=0, hist=HistSpec())
        spec = spec_from_simulator("hist", simt, max_quanta=512)
        assert simt.hist_spec.buffer_sig() in spec.forbidden_cond_avals
        assert spec.expect_hist
        assert not rules.cond_payload(
            spec.closed, forbidden=spec.forbidden_cond_avals)

        sig = simt.hist_spec.buffer_sig()

        def bad(p, buf):
            return jax.lax.cond(p, lambda b: b + 1, lambda b: b, buf)

        closed = jax.make_jaxpr(bad)(True, jnp.zeros(sig[0], jnp.int64))
        assert rules.cond_payload(closed, forbidden=(sig,))

    def test_off_specs_carry_hist_sigs_and_audit_passes(self):
        from graphite_tpu.analysis.audit import audit

        sim = Simulator(_config(), _trace())
        off = spec_from_simulator("off", sim, max_quanta=512)
        assert not off.expect_hist
        assert off.hist_sig is not None

        simt = Simulator(_config(), _trace(), phase_gate=True,
                         mem_gate_bytes=0, hist=HistSpec())
        on = spec_from_simulator("hist-on", simt, max_quanta=512)
        report = audit([off, on])
        assert report.ok, [str(f) for f in report.errors]
        assert "hist-off" in {r.rule for r in report.results
                              if r.program == "off"}
        assert "hist-off" not in {r.rule for r in report.results
                                  if r.program == "hist-on"}


class TestRecording:
    def test_results_bit_equal_and_conserved(self):
        batch = _trace()
        r_off = Simulator(_config(), batch).run()
        sim = Simulator(_config(), batch, hist=HistSpec())
        r_on = sim.run()
        np.testing.assert_array_equal(r_on.clock_ps, r_off.clock_ps)
        np.testing.assert_array_equal(r_on.instruction_count,
                                      r_off.instruction_count)
        for k in r_off.mem_counters:
            np.testing.assert_array_equal(
                r_on.mem_counters[k], r_off.mem_counters[k], err_msg=k)
        assert r_off.hist is None
        h = r_on.hist
        assert isinstance(h, Hist)
        assert not h.per_tile
        assert h.sources == sim.hist_spec.sources
        # THE invariant: every histogram total bit-equals its counter
        cons = conservation_totals(h, r_on,
                                   protocol=sim.params.mem.protocol)
        assert set(cons) == set(h.sources)
        for s, (got, want) in cons.items():
            assert got == want, (s, got, want)
        assert cons["l1d_lat_ps"][0] > 0
        assert cons["miss_lat_ps"][0] > 0
        assert cons["clock_skew_ps"][0] == h.boundaries * TILES
        assert h.boundaries > 0

    def test_core_sources_conserved_on_memoryless_ring(self):
        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, clock_scheme="lax_barrier")))
        batch = _ring_batch()
        sim = Simulator(sc, batch, hist=HistSpec())
        res = sim.run()
        cons = conservation_totals(res.hist, res)
        for s, (got, want) in cons.items():
            assert got == want, (s, got, want)
        assert cons["net_lat_ps"][0] > 0
        assert cons["recv_stall_ps"][0] > 0

    def test_per_tile_ring_sums_to_aggregate(self):
        batch = _trace()
        agg = Simulator(_config(), batch,
                        hist=HistSpec(log2_buckets=24)).run().hist
        pt = Simulator(
            _config(), batch,
            hist=HistSpec(log2_buckets=24, per_tile=True)).run().hist
        assert pt.per_tile and pt.counts.shape[0] == TILES
        np.testing.assert_array_equal(pt.counts.sum(axis=0),
                                      agg.counts)
        assert pt.boundaries == agg.boundaries
        # counts_for: fleet sum by default, one plane with tile=
        for s in agg.sources:
            np.testing.assert_array_equal(pt.counts_for(s),
                                          agg.counts_for(s))
            assert pt.total(s) == agg.total(s)
        np.testing.assert_array_equal(
            pt.counts_for("clock_skew_ps", tile=3),
            pt.counts[3, pt.sources.index("clock_skew_ps")])
        with pytest.raises(ValueError, match="per_tile"):
            agg.counts_for("clock_skew_ps", tile=0)

    def test_boundary_rows_match_chunked_oracle(self):
        """Hand-stepped oracle: run_chunk(1) executes one quantum per
        call; each call is one whole-fleet skew observation.  The
        host-side searchsorted accumulation must bit-equal the device
        ring."""
        batch = _trace()
        edges = (1_000, 10_000, 100_000, 1_000_000)
        simt = Simulator(_config(), batch,
                         hist=HistSpec(sources=("clock_skew_ps",),
                                       edges=edges))
        h = simt.run().hist

        ref = Simulator(_config(), batch)
        counts = np.zeros(len(edges) + 1, np.int64)
        n = 0
        for _ in range(10_000):
            done, _ = ref.run_chunk(1)
            clocks = np.asarray(
                jax.device_get(ref.state.core.clock_ps), np.int64)
            skew = clocks - clocks.min()
            np.add.at(counts,
                      np.searchsorted(edges, skew, side="right"), 1)
            n += 1
            if done:
                break
        assert done
        assert h.boundaries == n
        np.testing.assert_array_equal(h.counts_for("clock_skew_ps"),
                                      counts)

    def test_barrier_host_dispatch_records_identically(self):
        batch = _trace()
        h_dev = Simulator(_config(), batch,
                          hist=HistSpec()).run().hist
        h_hb = Simulator(_config(), batch, barrier_host=True,
                         barrier_batch=2, hist=HistSpec()).run().hist
        assert h_hb.boundaries == h_dev.boundaries
        np.testing.assert_array_equal(h_hb.counts, h_dev.counts)

    def test_save_load_roundtrip(self, tmp_path):
        h = Simulator(_config(), _trace(),
                      hist=HistSpec(log2_buckets=20)).run().hist
        path = str(tmp_path / "hist.npz")
        h.save(path)
        back = Hist.load(path)
        assert back.sources == h.sources
        assert back.boundaries == h.boundaries
        np.testing.assert_array_equal(back.edges, h.edges)
        np.testing.assert_array_equal(back.counts, h.counts)
        assert back.summary() == h.summary()


class TestQuantiles:
    EDGES = (10, 100, 1_000, 10_000)

    def _hand_hist(self, counts):
        return Hist(sources=("lat",),
                    edges=np.asarray(self.EDGES, np.int64),
                    counts=np.asarray([counts], np.int64),
                    boundaries=0)

    def test_matches_shared_bucket_quantile(self):
        counts = [3, 7, 5, 0, 2]
        h = self._hand_hist(counts)
        for q in (0.01, 0.5, 0.9, 0.95, 0.99, 1.0):
            assert h.quantile("lat", q) == bucket_quantile(
                counts, list(self.EDGES), q, overflow=self.EDGES[-1])
        # cumulative: 3, 10, 15, 15, 17 -> ceil(.5*17)=9 in bucket 1
        assert h.quantile("lat", 0.5) == 100
        # overflow observations saturate at the last edge
        assert h.quantile("lat", 1.0) == 10_000

    def test_matches_host_metrics_histogram(self):
        """Identical buckets, identical counts: the device Hist and the
        host metrics Histogram answer every quantile identically (the
        ONE shared bucket_quantile definition)."""
        counts = [4, 0, 9, 2, 0]   # nothing in the +Inf/overflow tail
        h = self._hand_hist(counts)
        m = Histogram("lat", buckets=self.EDGES)
        m.counts = list(counts)
        m.count = sum(counts)
        for q in (0.25, 0.5, 0.75, 0.99, 1.0):
            assert h.quantile("lat", q) == m.quantile(q)

    def test_device_run_quantiles_consistent(self):
        sim = Simulator(_config(), _trace(), hist=HistSpec())
        h = sim.run().hist
        for s in h.sources:
            p50 = h.quantile(s, 0.5)
            p99 = h.quantile(s, 0.99)
            assert p50 <= p99
            assert p99 == bucket_quantile(
                [int(c) for c in h.counts_for(s)],
                [int(e) for e in h.edges], 0.99,
                overflow=int(h.edges[-1]))
        summ = h.summary()
        assert summ["miss_lat_ps_p99"] == h.quantile("miss_lat_ps",
                                                     0.99)
        assert summ["miss_lat_ps_count"] == h.total("miss_lat_ps")


class TestSweepDemux:
    def test_vmap_campaign_demuxes_per_sim_hists(self):
        from graphite_tpu.sweep import SweepRunner

        seeds = (1, 2, 3)
        traces = [_trace(seed=s) for s in seeds]
        sweep = SweepRunner(_config(), traces, shard_batch=False,
                            hist=HistSpec())
        out = sweep.run()
        assert out.hists is not None and len(out.hists) == 3
        proto = sweep.sim.params.mem.protocol
        for b in range(3):
            hb = out.hists[b]
            assert out.results[b].hist is hb
            solo = Simulator(_config(), traces[b],
                             mailbox_depth=sweep.mailbox_depth,
                             phase_gate=False, mem_gate_bytes=0,
                             hist=HistSpec()).run().hist
            assert hb.boundaries == solo.boundaries
            np.testing.assert_array_equal(hb.counts, solo.counts,
                                          err_msg=f"sim {b}")
            cons = conservation_totals(hb, out.results[b],
                                       protocol=proto)
            assert all(a == c for a, c in cons.values())

    def test_shard_map_campaign_gathers_device_buffers(self):
        from graphite_tpu.sweep import SweepRunner

        if len(jax.devices()) < 2:
            pytest.skip("needs the multi-device CPU platform")
        B = len(jax.devices())
        traces = [_trace(seed=s) for s in range(B)]
        sweep = SweepRunner(_config(), traces, shard_batch=True,
                            hist=HistSpec())
        out = sweep.run()
        assert len(out.hists) == B
        for b in (0, B - 1):
            solo = Simulator(_config(), traces[b],
                             mailbox_depth=sweep.mailbox_depth,
                             hist=HistSpec()).run().hist
            assert out.hists[b].boundaries == solo.boundaries
            np.testing.assert_array_equal(out.hists[b].counts,
                                          solo.counts,
                                          err_msg=f"sim {b}")

    def test_campaign_residency_itemizes_hist_rings(self):
        from graphite_tpu.sweep import SweepRunner

        traces = [_trace(seed=s) for s in (1, 2)]
        sweep = SweepRunner(_config(), traces, shard_batch=False,
                            hist=HistSpec())
        bd = sweep.residency_breakdown()
        assert bd["hist"] == 2 * sweep.sim.hist_spec.ring_bytes()


class TestServe:
    def test_class_key_splits_on_hist_spec(self):
        from graphite_tpu.serve import CampaignService, Job

        svc = CampaignService(batch_size=4)
        batch = _trace()
        j_off = Job("off", _config(), batch)
        j_a = Job("a", _config(), batch, hist=HistSpec())
        j_b = Job("b", _config(), batch,
                  hist=HistSpec(edges=(100, 1000)))
        j_a2 = Job("a2", _config(), batch, hist=HistSpec())
        keys = [svc.admission.class_key(j)
                for j in (j_off, j_a, j_b, j_a2)]
        assert keys[1] != keys[0]
        assert keys[1] != keys[2]
        assert keys[1] == keys[3]

    def test_job_validate_rejects_non_spec(self):
        from graphite_tpu.serve import Job

        with pytest.raises((TypeError, ValueError)):
            Job("bad", _config(), _trace(),
                hist={"log2_buckets": 16}).validate()

    def test_admission_bill_includes_hist_ring(self):
        from graphite_tpu.serve import CampaignService, Job

        svc = CampaignService(batch_size=2)
        job = Job("h", _config(), _trace(), hist=HistSpec())
        cls, _ = svc.admission.admit(job)
        assert cls.per_sim_bytes["hist"] == cls.hist.ring_bytes()
        assert "-hist" in svc._class_name(cls)

    def test_serve_cli_hist_out_writes_npz(self, tmp_path, capsys):
        from graphite_tpu.tools.serve import main as serve_main

        jobs = tmp_path / "jobs.jsonl"
        jobs.write_text(json.dumps({
            "id": "cli0", "tiles": 4, "seed": 1, "accesses": 8,
            "hist": {"log2_buckets": 24}}) + "\n")
        out_dir = tmp_path / "hists"
        assert serve_main(["--jobs", str(jobs), "--batch-size", "1",
                           "--hist-out", str(out_dir)]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
        row = next(r for r in lines if r.get("job") == "cli0")
        path = row["hist_file"]
        assert path == str(out_dir / "cli0.npz")
        saved = Hist.load(path)
        assert row["hist_events"] == sum(saved.totals().values())
        assert saved.total("l1d_lat_ps") > 0


class TestPerfetto:
    SPANS = [
        {"trace": "batch-0", "span": "batch", "start_us": 5,
         "dur_us": 900, "n_jobs": 1},
        {"trace": "j0", "span": "queue", "start_us": 0, "dur_us": 100},
    ]

    def test_unified_export_round_trip(self, tmp_path, capsys):
        from graphite_tpu.obs import TelemetrySpec
        from graphite_tpu.tools.report import main as report_main

        res = Simulator(
            _config(), _trace(),
            telemetry=TelemetrySpec(sample_interval_ps=QUANTUM_PS,
                                    n_samples=64),
            hist=HistSpec()).run()
        tl_path = str(tmp_path / "tl.npz")
        h_path = str(tmp_path / "hist.npz")
        res.telemetry.save(tl_path)
        res.hist.save(h_path)
        spans = tmp_path / "spans.jsonl"
        spans.write_text("".join(json.dumps(r) + "\n"
                                 for r in self.SPANS))
        out = str(tmp_path / "trace.json")
        assert report_main([tl_path, "--spans", str(spans),
                            "--hist", h_path,
                            "--perfetto", out]) == 0
        printed = json.loads(capsys.readouterr().out.strip())
        doc = json.load(open(out))
        assert doc["displayTimeUnit"] == "ns"
        evs = doc["traceEvents"]
        assert printed == {"perfetto": out, "events": len(evs)}

        # metadata first: both clock-track processes are named
        assert [e["ph"] for e in evs[:2]] == ["M", "M"]
        assert {e["pid"] for e in evs[:2]} == {1, 2}

        # host track: one X event per span row, us timestamps
        xs = [e for e in evs if e["ph"] == "X"]
        assert len(xs) == len(self.SPANS)
        assert all(e["pid"] == 1 for e in xs)
        assert {e["name"] for e in xs} == {"batch", "queue"}

        # sim track: telemetry counters + one instant per hist source
        cs = [e for e in evs if e["ph"] == "C"]
        assert cs and all(e["pid"] == 2 for e in cs)
        instants = {e["name"]: e for e in evs if e["ph"] == "i"}
        h = res.hist
        for s in h.sources:
            ev = instants[f"hist0.{s}"]
            assert ev["args"]["count"] == h.total(s)
            assert ev["args"]["p50"] == h.quantile(s, 0.5)
            assert ev["args"]["p99"] == h.quantile(s, 0.99)

        # the regress invariant: per-pid monotone timestamps
        for pid in (1, 2):
            ts = [e["ts"] for e in evs
                  if e["pid"] == pid and e["ph"] != "M"]
            assert ts == sorted(ts)

    def test_mode_validation(self, tmp_path):
        from graphite_tpu.tools.report import main as report_main

        h = tmp_path / "h.npz"
        Hist(sources=("lat",), edges=np.asarray([1], np.int64),
             counts=np.asarray([[0, 0]], np.int64),
             boundaries=0).save(str(h))
        # --hist outside perfetto mode is an argparse error
        with pytest.raises(SystemExit):
            report_main(["--hist", str(h)])
        # --perfetto with no inputs is an argparse error
        with pytest.raises(SystemExit):
            report_main(["--perfetto", str(tmp_path / "o.json")])
        # hist-only export works
        assert report_main(["--perfetto", str(tmp_path / "o.json"),
                            "--hist", str(h)]) == 0
