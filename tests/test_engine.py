"""End-to-end engine tests: core timing, messaging, sync, quantum loop.

Expected values are hand-derived from the reference semantics:
 - static costs + 1-IPC accumulation (`simple_core_model.cc:37-97`,
   `carbon_sim.cfg:189-200`);
 - one-bit branch predictor (`one_bit_branch_predictor.cc:13-24`) with
   14-cycle mispredict penalty (`carbon_sim.cfg:202-205`);
 - magic network = 1 cycle/packet (`network_model_magic.cc:15-22`);
 - emesh_hop_counter = hops*(router+link) + flits serialization
   (`network_model_emesh_hop_counter.cc:142-157`, `network_model.cc:143-149`);
 - netRecv clock = max(clock, arrival), RecvInstruction only when waiting
   (`network.cc:443-453`);
 - SimBarrier releases at max arrival time (`sync_server.cc:133-160`);
 - SimMutex handoff at unlock time (`sync_server.cc:27-57,185-240`).
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.engine.simulator import DeadlockError
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=4, user_net="magic", scheme="lax_barrier", extra=""):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
[network]
user = {user_net}
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
imul = 3
idiv = 18
falu = 3
fmul = 5
fdiv = 6
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = {scheme}
[clock_skew_management/lax_barrier]
quantum = 1000
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
{extra}
"""
    return SimConfig(ConfigFile.from_string(text))


def run(sc, builders_or_batch, **kw):
    batch = (
        builders_or_batch
        if isinstance(builders_or_batch, TraceBatch)
        else TraceBatch.from_builders(builders_or_batch)
    )
    return Simulator(sc, batch, **kw).run()


class TestCoreTiming:
    def test_static_costs_accumulate(self):
        # 10 ialu(1) + 2 imul(3) = 16 cycles @ 1 GHz = 16000 ps
        bs = []
        for t in range(4):
            b = TraceBuilder()
            for _ in range(10):
                b.instr(Op.IALU)
            for _ in range(2):
                b.instr(Op.IMUL)
            bs.append(b)
        r = run(make_config(), bs)
        assert r.clock_ps.tolist() == [16000] * 4
        assert r.instruction_count.tolist() == [12] * 4
        assert r.execution_stall_ps.tolist() == [16000] * 4

    def test_all_cost_classes(self):
        costs = {Op.GENERIC: 1, Op.MOV: 1, Op.IALU: 1, Op.IMUL: 3,
                 Op.IDIV: 18, Op.FALU: 3, Op.FMUL: 5, Op.FDIV: 6}
        b = TraceBuilder()
        for op in costs:
            b.instr(op)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == sum(costs.values()) * 1000

    def test_dynamic_stall_cost(self):
        b = TraceBuilder().dynamic(Op.STALL, cost_ps=12345)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == 12345
        assert r.instruction_count[0] == 1  # dynamic instrs count

    def test_core_frequency_scales_costs(self):
        # CORE domain at 2 GHz: 1 cycle = 500 ps (max_frequency must allow
        # the domain's initial frequency — DvfsParams validates)
        sc = make_config(extra='[general]\nmax_frequency = 2.0\n'
                         '[dvfs]\ndomains = "<2.0, CORE, L1_ICACHE, '
                         'L1_DCACHE, L2_CACHE, DIRECTORY, NETWORK_USER, '
                         'NETWORK_MEMORY>"\n')
        bs = [TraceBuilder().instr(Op.IALU) for _ in range(4)]
        r = run(sc, bs)
        assert r.clock_ps.tolist() == [500] * 4


class TestBranchPredictor:
    def test_one_bit_first_mispredicts(self):
        # table initialized 0 = predict not-taken; first taken mispredicts
        b = TraceBuilder()
        for _ in range(5):
            b.branch(True, pc=0x100)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == (14 + 4) * 1000
        assert int(r.bp_correct[0]) == 4
        assert int(r.bp_incorrect[0]) == 1

    def test_alternating_always_mispredicts(self):
        b = TraceBuilder()
        for i in range(6):
            b.branch(i % 2 == 0, pc=0x40)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert int(r.bp_incorrect[0]) == 6
        assert r.clock_ps[0] == 6 * 14 * 1000


class TestUserNetwork:
    def test_ping_pong_magic(self):
        sc = make_config(n_tiles=2)
        r = run(sc, synthetic.ping_pong_trace(2, n_rounds=3))
        # each direction costs 1000 ps (magic 1 cycle @ 1GHz)
        assert r.clock_ps.tolist() == [6000, 5000]
        assert r.packets_sent.tolist() == [3, 3]
        assert r.packets_received.tolist() == [3, 3]
        assert r.recv_stall_ps.tolist() == [6000, 5000]
        # every recv waited → counted as RecvInstruction (`network.cc:445-453`)
        assert r.recv_instructions.tolist() == [3, 3]

    def test_no_wait_recv_costs_nothing(self):
        # receiver arrives late: packet already there, no recv instruction
        sc = make_config(n_tiles=2)
        b0 = TraceBuilder().send(1, 8)
        b1 = TraceBuilder()
        for _ in range(10):
            b1.instr(Op.IALU)
        b1.recv(0)
        r = run(sc, [b0, b1])
        assert r.clock_ps[1] == 10000  # no added cost
        assert r.recv_instructions[1] == 0
        assert r.recv_stall_ps[1] == 0

    def test_emesh_hop_counter_latency(self):
        # 4 tiles = 2x2 mesh. tile0 -> tile3: hops = 2, hop_latency = 2 cyc
        # serialization: (64 hdr + 8 payload)*8 = 576 bits / 64 = 9 flits
        # total = 2*2 + 9 = 13 cycles = 13000 ps @ 1GHz
        sc = make_config(user_net="emesh_hop_counter")
        b0 = TraceBuilder().send(3, 8)
        b3 = TraceBuilder().recv(0)
        bs = [b0, TraceBuilder(), TraceBuilder(), b3]
        r = run(sc, bs)
        assert r.clock_ps[3] == 13000
        assert r.recv_stall_ps[3] == 13000

    def test_recv_any_takes_earliest(self):
        sc = make_config(n_tiles=4)
        # tiles 1,2 send to 0 at different times; ANY recv takes earliest
        b1 = TraceBuilder().send(0, 8)                       # arrives 1000
        b2 = TraceBuilder()
        for _ in range(5):
            b2.instr(Op.IALU)
        b2.send(0, 8)                                        # arrives 6000
        b0 = TraceBuilder().recv(-1).recv(-1)
        r = run(sc, [b0, b1, b2, TraceBuilder()])
        assert r.clock_ps[0] == 6000
        assert r.recv_stall_ps[0] == 1000 + 5000

    def test_mailbox_queue_in_order(self):
        sc = make_config(n_tiles=2)
        b0 = TraceBuilder()
        for _ in range(5):
            b0.send(1, 8)
        b1 = TraceBuilder()
        for _ in range(5):
            b1.recv(0)
        r = run(sc, [b0, b1])
        assert r.packets_received[1] == 5
        assert r.clock_ps[1] == 1000  # all arrive at 1000 (sends are free)


class TestSync:
    def test_barrier_releases_at_max_time(self):
        bs = []
        for t in range(4):
            b = TraceBuilder()
            if t == 0:
                b.barrier_init(0, 4)
            for _ in range((t + 1) * 2):
                b.instr(Op.IALU)
            b.barrier_wait(0)
            b.instr(Op.IALU)
            bs.append(b)
        r = run(make_config(), bs)
        assert r.clock_ps.tolist() == [9000] * 4
        assert r.sync_stall_ps.tolist() == [6000, 4000, 2000, 0]
        # last arriver pays nothing → not a sync instruction
        assert r.sync_instructions.tolist() == [1, 1, 1, 0]

    def test_barrier_reusable(self):
        # two rounds on the same barrier (SimBarrier resets after release)
        bs = []
        for t in range(2):
            b = TraceBuilder()
            if t == 0:
                b.barrier_init(0, 2)
            b.instr(Op.IALU)
            b.barrier_wait(0)
            for _ in range(t + 1):
                b.instr(Op.IALU)
            b.barrier_wait(0)
            bs.append(b)
        r = run(make_config(n_tiles=2), bs)
        assert r.clock_ps.tolist() == [3000, 3000]

    def test_mutex_contention_serializes(self):
        b0 = TraceBuilder().mutex_init(0).mutex_lock(0)
        for _ in range(10):
            b0.instr(Op.IALU)
        b0.mutex_unlock(0)
        b1 = TraceBuilder().instr(Op.IALU).mutex_lock(0).instr(Op.IALU)
        b1.mutex_unlock(0)
        bs = [b0, b1, TraceBuilder(), TraceBuilder()]
        r = run(make_config(), bs)
        # t1 blocks at 1000, granted at t0's unlock (10000), +1 cycle
        assert r.clock_ps[0] == 10000
        assert r.clock_ps[1] == 11000
        assert r.sync_stall_ps[1] == 9000
        assert r.sync_instructions[1] == 1

    def test_mutex_grant_order_by_time(self):
        # three contenders; grants must go in simulated-time order
        b0 = TraceBuilder().mutex_init(0).mutex_lock(0)
        for _ in range(4):
            b0.instr(Op.IALU)
        b0.mutex_unlock(0)  # unlock @4000
        b1 = TraceBuilder().instr(Op.IALU).mutex_lock(0)          # req @1000
        b1.instr(Op.IALU).mutex_unlock(0)
        b2 = TraceBuilder().instr(Op.IALU).instr(Op.IALU).mutex_lock(0)  # @2000
        b2.instr(Op.IALU).mutex_unlock(0)
        r = run(make_config(), [b0, b1, b2, TraceBuilder()])
        # t1 granted at 4000 → done 5000; t2 granted at 5000 → done 6000
        assert r.clock_ps[1] == 5000
        assert r.clock_ps[2] == 6000


class TestCondVars:
    """SimCond semantics (`sync_server.cc` SimCond::wait/signal/broadcast):
    wait releases the mutex and joins the FIFO; signal wakes the earliest
    waiter, who re-acquires the mutex; broadcast wakes all; a signal with
    no waiter is lost (pthread semantics)."""

    def test_wait_signal_producer_consumer(self):
        # consumer: lock, wait (releases mutex); producer: compute, lock,
        # compute, signal, unlock — consumer resumes at
        # max(signal time, mutex handoff time)
        b1 = TraceBuilder().mutex_init(0).cond_init(0).mutex_lock(0)
        b1.cond_wait(0, 0).instr(Op.IALU).mutex_unlock(0)
        b0 = TraceBuilder()
        for _ in range(3):
            b0.instr(Op.IALU)
        b0.mutex_lock(0)          # @3000 — proves wait released the mutex
        for _ in range(2):
            b0.instr(Op.IALU)
        b0.cond_signal(0).mutex_unlock(0)
        r = run(make_config(n_tiles=2), [b0, b1])
        assert r.clock_ps[0] == 5000
        # woken at 5000, +1 ialu = 6000
        assert r.clock_ps[1] == 6000
        assert r.sync_stall_ps[1] == 5000
        assert r.sync_instructions[1] >= 1

    def test_broadcast_wakes_all_serialized_relock(self):
        waiters = []
        for t in range(3):
            b = TraceBuilder()
            if t == 0:
                b.mutex_init(0).cond_init(0)
            b.mutex_lock(0).cond_wait(0, 0).instr(Op.IALU).mutex_unlock(0)
            waiters.append(b)
        b0 = TraceBuilder()
        for _ in range(5):
            b0.instr(Op.IALU)
        b0.mutex_lock(0).cond_broadcast(0).mutex_unlock(0)
        r = run(make_config(), [b0] + waiters)
        assert r.clock_ps[0] == 5000
        # woken together at 5000; mutex re-acquisition serializes in tile
        # order (deterministic FIFO key = (wake time, tile))
        assert r.clock_ps[1] == 6000
        assert r.clock_ps[2] == 7000
        assert r.clock_ps[3] == 8000

    def test_signal_without_waiter_is_lost(self):
        b0 = TraceBuilder().cond_init(0).mutex_init(0).cond_signal(0)
        b1 = TraceBuilder().instr(Op.IALU).mutex_lock(0).cond_wait(0, 0)
        with pytest.raises(DeadlockError):
            run(make_config(n_tiles=2), [b0, b1])

    def test_broadcast_resolves_with_poster_pinned_at_post_time(self):
        """A poster whose clock stays frozen exactly at the broadcast time
        (blocked on a join of the waiter) must not hold delivery forever."""
        b0 = TraceBuilder().mutex_init(0).cond_init(0)
        for _ in range(5):
            b0.instr(Op.IALU)
        b0.mutex_lock(0).cond_broadcast(0).mutex_unlock(0)
        b0.thread_join(1)     # clock pinned at 5000 until t1 exits
        b1 = TraceBuilder().mutex_lock(0).cond_wait(0, 0).mutex_unlock(0)
        r = run(make_config(n_tiles=2), [b0, b1])
        assert r.clock_ps[1] == 5000

    def test_broadcast_before_signal_orders_by_time(self):
        """Pending broadcast (t=3000) and pending signal (t=5000) on one
        cond resolve in simulated-time order: the waiter wakes at the
        broadcast time; the later signal finds no waiter and is lost."""
        # a slow third tile keeps min_active low so both park as pending
        b2 = TraceBuilder()
        b2.dynamic(Op.STALL, cost_ps=20_000)
        w = TraceBuilder().instr(Op.IALU).mutex_lock(0).cond_wait(0, 0)
        w.mutex_unlock(0)
        b0 = TraceBuilder().mutex_init(0).cond_init(0)
        for _ in range(3):
            b0.instr(Op.IALU)
        b0.cond_broadcast(0)
        for _ in range(2):
            b0.instr(Op.IALU)
        b0.cond_signal(0)
        r = run(make_config(), [b0, w, b2, TraceBuilder()])
        assert r.clock_ps[1] == 3000   # woken by the broadcast, not 5000

    def test_signal_wakes_fifo_earliest(self):
        # two waiters arriving at 1000 and 2000; one signal at 5000 wakes
        # the earlier one only; a second signal at 7000 wakes the other
        w1 = TraceBuilder().instr(Op.IALU).mutex_lock(0)
        w1.cond_wait(0, 0).mutex_unlock(0)
        w2 = TraceBuilder().instr(Op.IALU).instr(Op.IALU).mutex_lock(0)
        w2.cond_wait(0, 0).mutex_unlock(0)
        b0 = TraceBuilder().mutex_init(0).cond_init(0)
        for _ in range(5):
            b0.instr(Op.IALU)
        b0.cond_signal(0)
        for _ in range(2):
            b0.instr(Op.IALU)
        b0.cond_signal(0)
        r = run(make_config(), [b0, w1, w2, TraceBuilder()])
        assert r.clock_ps[1] == 5000   # woken by first signal
        assert r.clock_ps[2] == 7000   # woken by second signal


class TestThreads:
    def test_join_waits_for_target_exit(self):
        b0 = TraceBuilder().thread_spawn(1).thread_join(1).instr(Op.IALU)
        b1 = TraceBuilder()
        for _ in range(7):
            b1.instr(Op.IALU)
        r = run(make_config(n_tiles=2), [b0, b1])
        assert r.clock_ps[0] == 8000  # joined at 7000 + 1 cycle


class TestModelToggles:
    def test_disabled_models_cost_nothing(self):
        b = TraceBuilder()
        b.dynamic(Op.DISABLE_MODELS, 0)
        for _ in range(5):
            b.instr(Op.IALU)
        bs = [b] + [TraceBuilder() for _ in range(3)]
        # DISABLE event via builder._append path
        bs[0]._op[0] = int(Op.DISABLE_MODELS)
        r = run(make_config(), bs)
        assert r.clock_ps[0] == 0
        assert r.instruction_count[0] == 0


class TestSpawnAndDvfs:
    def test_spawn_sets_absolute_time(self):
        # SpawnInstruction sets the clock to the given absolute time
        # (`instruction.cc:72-83`), it does not add to it
        b = TraceBuilder()
        for _ in range(3):
            b.instr(Op.IALU)          # clock 3000
        b.dynamic(Op.SPAWN, 5000)     # max(3000, 5000) = 5000
        b.instr(Op.IALU)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == 6000

    def test_spawn_in_past_keeps_clock(self):
        b = TraceBuilder()
        for _ in range(3):
            b.instr(Op.IALU)
        b.dynamic(Op.SPAWN, 1000)     # behind current clock → no-op
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == 3000

    def test_dvfs_set_core_retunes_frequency(self):
        b = TraceBuilder().instr(Op.IALU)       # 1000 ps @ 1 GHz
        b.dvfs_set(0, 500)                      # CORE domain → 0.5 GHz
        b.instr(Op.IALU)                        # 2000 ps
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == 3000

    def test_dvfs_set_above_max_frequency_rejected(self):
        # [general] max_frequency is 1.0 GHz here: a 2 GHz request fails
        # (`dvfs.h` rc -4) and leaves the frequency unchanged
        b = TraceBuilder().instr(Op.IALU)
        b.dvfs_set(0, 2000)
        b.instr(Op.IALU)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(), bs)
        assert r.clock_ps[0] == 2000


class TestQuantumLoop:
    def test_lax_barrier_many_quanta(self):
        # 10000 cycles of work = 10 quanta of 1000ns... (1 cycle = 1ns)
        b = TraceBuilder()
        for _ in range(2500):
            b.instr(Op.IDIV)  # 18 cycles each -> 45000 ns total
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(scheme="lax_barrier"), bs)
        assert r.clock_ps[0] == 2500 * 18 * 1000
        assert r.n_quanta >= 45

    def test_lax_single_quantum(self):
        b = TraceBuilder()
        for _ in range(100):
            b.instr(Op.IDIV)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(scheme="lax"), bs)
        assert r.clock_ps[0] == 100 * 18 * 1000
        assert r.n_quanta == 1

    def test_deadlock_detected(self):
        # tile 0 recvs from tile 1, which never sends
        b0 = TraceBuilder().recv(1)
        bs = [b0] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        with pytest.raises(DeadlockError):
            run(make_config(), bs)

    def test_long_stall_fast_forwards_quanta(self):
        # a tile 5000 quanta ahead must not trigger a false deadlock, and
        # empty quanta must be skipped, not iterated (`simulator.run`)
        b = TraceBuilder().dynamic(Op.STALL, 5_000_000_000).instr(Op.IALU)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        r = run(make_config(scheme="lax_barrier"), bs)
        assert r.clock_ps[0] == 5_000_001_000
        assert r.n_quanta < 10

    def test_late_sender_does_not_false_deadlock(self):
        # sender crosses many quanta with one long stall, then sends; the
        # blocked receiver must wait, not deadlock
        sc = make_config(n_tiles=2)
        b0 = TraceBuilder().dynamic(Op.STALL, 5_000_000).send(1, 8)
        b1 = TraceBuilder().recv(0)
        r = run(sc, [b0, b1])
        assert r.clock_ps[1] == 5_001_000

    def test_cross_quantum_messaging(self):
        # sender does 5000 cycles of work (5 quanta) before sending
        sc = make_config(n_tiles=2)
        b0 = TraceBuilder()
        for _ in range(5000):
            b0.instr(Op.IALU)
        b0.send(1, 8)
        b1 = TraceBuilder().recv(0)
        r = run(sc, [b0, b1])
        assert r.clock_ps[1] == 5001 * 1000


class TestSyntheticTraces:
    @pytest.mark.parametrize("pattern", list(synthetic.TRAFFIC_PATTERNS))
    def test_traffic_patterns_complete(self, pattern):
        sc = make_config(n_tiles=16, scheme="lax")
        tb = synthetic.network_traffic_trace(
            16, pattern, total_packets=8, offered_load=1.0
        )
        r = Simulator(sc, tb, mailbox_depth=32).run()
        assert int(r.packets_sent.sum()) == 16 * 8
        assert int(r.packets_received.sum()) == 16 * 8

    def test_uniform_random_matrix_is_permutation_schedule(self):
        m = synthetic.uniform_random_matrix(8)
        assert m.shape == (8, 8)

    def test_memory_stress_trace_builds(self):
        tb = synthetic.memory_stress_trace(4, n_accesses=50)
        assert tb.n_tiles == 4

    def test_compute_mix_runs(self):
        sc = make_config(n_tiles=4, scheme="lax")
        r = run(sc, synthetic.compute_mix_trace(4, n_instructions=200))
        assert (r.instruction_count == 200).all()
        assert (r.clock_ps > 0).all()

    def test_bblock_compression_timing_identical(self):
        """A compressed trace must be cycle- and counter-identical to the
        per-instruction trace it compresses (the cost algebra over a
        straight-line run is associative)."""
        sc = make_config(n_tiles=16, scheme="lax")
        raw = synthetic.message_ring_batch(
            16, n_rounds=6, compute_per_round=10)
        comp = synthetic.message_ring_batch(
            16, n_rounds=6, compute_per_round=10, compressed=True)
        r_raw = Simulator(sc, raw).run()
        r_comp = Simulator(sc, comp).run()
        np.testing.assert_array_equal(r_raw.clock_ps, r_comp.clock_ps)
        np.testing.assert_array_equal(
            r_raw.instruction_count, r_comp.instruction_count)
        np.testing.assert_array_equal(
            r_raw.execution_stall_ps, r_comp.execution_stall_ps)
        np.testing.assert_array_equal(
            r_raw.total_packet_latency_ps, r_comp.total_packet_latency_ps)

    def test_bblock_models_disabled_zero_cost(self):
        sc = make_config(n_tiles=1, scheme="lax",
                         extra="[general]\n"
                               "trigger_models_within_application = true")
        b = TraceBuilder()
        b.bblock(100, 100)
        r = run(sc, [b])
        assert r.clock_ps[0] == 0
        assert r.instruction_count[0] == 0


class TestDeterminism:
    def test_bitwise_reproducible(self):
        sc = make_config(n_tiles=16, scheme="lax")
        tb = synthetic.network_traffic_trace(16, "uniform_random",
                                             total_packets=5, seed=3)
        r1 = Simulator(sc, tb, mailbox_depth=32).run()
        r2 = Simulator(sc, tb, mailbox_depth=32).run()
        assert r1.clock_ps.tolist() == r2.clock_ps.tolist()
        assert r1.instruction_count.tolist() == r2.instruction_count.tolist()
        assert r1.total_packet_latency_ps.tolist() == r2.total_packet_latency_ps.tolist()


def test_summary_renders():
    sc = make_config(n_tiles=2)
    r = run(sc, synthetic.ping_pong_trace(2, n_rounds=2))
    text = r.summary()
    assert "Tile 0 Summary" in text
    assert "Total Instructions" in text
    assert "Average Packet Latency" in text


class TestAutoMailboxDepth:
    """Trace-derived [T, T, depth] ring sizing (simulator.py
    auto_mailbox_depth): barrier-phased workloads get their exact
    in-flight bound, unphased streams hit the documented cap, and an
    auto-sized run is bit-identical to a generously-sized one."""

    def test_barrier_phased_traces_size_minimal(self):
        from graphite_tpu.engine.simulator import auto_mailbox_depth
        from graphite_tpu.trace.benchmarks import fft_trace

        assert auto_mailbox_depth(fft_trace(16, points_per_tile=64)) == 2
        assert auto_mailbox_depth(
            synthetic.memory_stress_trace(
                16, n_accesses=10, working_set_bytes=1 << 12,
                write_fraction=0.4, shared_fraction=0.5, seed=3)) == 2

    def test_unphased_stream_capped(self):
        from graphite_tpu.engine.simulator import auto_mailbox_depth

        b = synthetic.message_ring_batch(8, n_rounds=200,
                                         compute_per_round=1)
        assert auto_mailbox_depth(b) == 64

    def test_auto_depth_run_matches_explicit(self):
        sc = make_config(n_tiles=8, scheme="lax")
        tb = synthetic.message_ring_batch(8, n_rounds=4,
                                          compute_per_round=2)
        ra = Simulator(sc, tb).run()          # auto-sized
        rb = Simulator(sc, tb, mailbox_depth=32).run()
        assert ra.clock_ps.tolist() == rb.clock_ps.tolist()
        assert (ra.instruction_count.tolist()
                == rb.instruction_count.tolist())


class TestHostBarrier:
    """barrier_host: lax_barrier quanta driven host-side (the 1024-tile
    + memory-engine fallback) — identical semantics to the device loop."""

    def test_host_barrier_matches_device(self):
        b = TraceBuilder()
        for _ in range(1200):
            b.instr(Op.IDIV)
        bs = [b] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        batch = TraceBatch.from_builders(bs)
        sc = make_config(scheme="lax_barrier")
        r_dev = run(sc, batch)
        r_host = run(sc, batch, barrier_host=True)
        assert r_dev.clock_ps.tolist() == r_host.clock_ps.tolist()
        assert r_dev.n_quanta == r_host.n_quanta

    def test_host_barrier_coherence_exact(self):
        from graphite_tpu.config import ConfigFile, SimConfig
        from graphite_tpu.tools._template import config_text
        from graphite_tpu.trace import synthetic

        batch = synthetic.memory_stress_trace(
            8, n_accesses=40, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.6, seed=5)
        sc = SimConfig(ConfigFile.from_string(config_text(
            8, shared_mem=True, clock_scheme="lax_barrier")))
        r_dev = run(sc, batch)
        r_host = run(sc, batch, barrier_host=True)
        assert r_dev.clock_ps.tolist() == r_host.clock_ps.tolist()
        for k in r_dev.mem_counters:
            assert (np.asarray(r_dev.mem_counters[k])
                    == np.asarray(r_host.mem_counters[k])).all(), k

    def test_host_barrier_deadlock_detected(self):
        b0 = TraceBuilder().recv(1)
        bs = [b0] + [TraceBuilder().instr(Op.IALU) for _ in range(3)]
        with pytest.raises(DeadlockError):
            run(make_config(scheme="lax_barrier"), bs, barrier_host=True)
