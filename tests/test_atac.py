"""ATAC optical NoC tests (`network_model_atac.cc`).

Hand-derived latencies: intra-cluster sends ride the ENet (XY hops);
inter-cluster sends pay ENet-to-hub + send hub + optical link (waveguide +
E-O/O-E) + receive hub + receive net + serialization.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.models.network_atac import AtacParams
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=16, strategy="cluster_based", contention="false"):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
tile_width = 1.0
[network]
user = atac
memory = magic
[network/atac]
flit_width = 64
cluster_size = 4
receive_network_type = star
global_routing_strategy = {strategy}
unicast_distance_threshold = 4
[network/atac/queue_model]
enabled = {contention}
type = history_tree
[network/atac/enet/router]
delay = 1
[network/atac/onet/send_hub/router]
delay = 1
[network/atac/onet/receive_hub/router]
delay = 1
[network/atac/star_net/router]
delay = 1
[link_model/optical]
waveguide_delay_per_mm = 10e-3
E-O_conversion_delay = 1
O-E_conversion_delay = 1
[core/static_instruction_costs]
ialu = 1
[clock_skew_management]
scheme = lax
"""
    return SimConfig(ConfigFile.from_string(text))


def run(sc, builders):
    return Simulator(sc, TraceBatch.from_builders(builders)).run()


class TestAtacParams:
    def test_topology(self):
        p = AtacParams.from_config(make_config(16))
        assert p.n_clusters == 4
        assert p.cluster_size == 4
        # waveguide: 10e-3 ns/mm * (4+4) mm = 0.08 ns -> ceil 80 ps,
        # + E-O + O-E at 1 GHz = 2000 ps
        assert p.optical_link_ps == 80 + 2000


class TestAtacRouting:
    def test_intra_cluster_rides_enet(self):
        """tiles 0 -> 1 share cluster 0: 1 hop * 2 cycles + 2 flits."""
        sc = make_config(16)
        b0 = TraceBuilder().send(1, 8)
        b1 = TraceBuilder().recv(0, 8)
        bs = [b0, b1] + [TraceBuilder() for _ in range(14)]
        r = run(sc, bs)
        # (64+8)B = 576 bits -> 9 flits; 1 hop * 2cy + 9cy = 11 cycles
        assert r.total_packet_latency_ps[1] == 11_000

    def test_inter_cluster_rides_onet(self):
        """tile 0 (cluster 0) -> tile 15 (cluster 3) goes optical."""
        sc = make_config(16)
        b0 = TraceBuilder().send(15, 8)
        b15 = TraceBuilder().recv(0, 8)
        bs = [b0] + [TraceBuilder() for _ in range(14)] + [b15]
        r = run(sc, bs)
        # src 0 == hub(cluster 0): 0 enet hops; send hub 1cy; optical
        # 2080 ps; receive hub 1cy; star net 1cy; 9 flits ser
        expected = 1000 + 2080 + 1000 + 1000 + 9000
        assert r.total_packet_latency_ps[15] == expected

    def test_distance_based_short_unicast_stays_electrical(self):
        """distance_based: a 1-hop cross-cluster send stays on the ENet."""
        sc = make_config(16, strategy="distance_based")
        # tile 1 (cluster 0) -> tile 2 (cluster 0)? need cross-cluster but
        # short: tiles 1 and 2 are 1 hop apart; cluster of 1 is 0, of 2 is 0
        # (cluster = id//4)… use 3 -> 4: clusters 0 and 1, 4 hops in a
        # 4x4 mesh (3 is (3,0), 4 is (0,1): |3-0|+|0-1| = 4) <= threshold
        b3 = TraceBuilder().send(4, 8)
        b4 = TraceBuilder().recv(3, 8)
        bs = [TraceBuilder() for _ in range(16)]
        bs[3] = b3
        bs[4] = b4
        r = run(sc, bs)
        # ENet: 4 hops * 2cy + 9 flits = 17 cycles
        assert r.total_packet_latency_ps[4] == 17_000

    def test_contention_delays_hub(self):
        """Two same-cluster senders to remote clusters serialize at their
        shared send hub when contention is on (the second sender, offset
        one cycle so its packet queues behind the first, pays extra)."""
        sc_on = make_config(16, contention="true")
        sc_off = make_config(16, contention="false")

        def traffic():
            # 2x2 clustering on a 4x4 mesh: tiles 0 and 1 share cluster 0;
            # tiles 10/11 sit in cluster 3
            bs = [TraceBuilder() for _ in range(16)]
            bs[0] = TraceBuilder().send(10, 64)
            bs[1] = TraceBuilder().instr(Op.IALU).send(11, 64)
            bs[10] = TraceBuilder().recv(0, 64)
            bs[11] = TraceBuilder().recv(1, 64)
            return bs

        r_on = run(sc_on, traffic())
        r_off = run(sc_off, traffic())
        total_on = int(r_on.total_packet_latency_ps.sum())
        total_off = int(r_off.total_packet_latency_ps.sum())
        assert total_on > total_off

    def test_functional_completion_larger_mesh(self):
        """64 tiles, 16 clusters: all-to-neighbor-cluster traffic lands."""
        sc = make_config(64)
        bs = []
        for t in range(64):
            b = TraceBuilder()
            peer = (t + 4) % 64        # next cluster over
            b.send(peer, 8)
            b.recv((t - 4) % 64, 8)
            bs.append(b)
        r = run(sc, bs)
        assert int(r.packets_received.sum()) == 64


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


class TestAtacGolden:
    """Differential validation vs the serial _AtacNet oracle: the first
    independent check of ATAC's timing algebra (round-2 gap — ATAC was
    expectation-tested only)."""

    def _assert_exact(self, sc, builders):
        import numpy as np

        from graphite_tpu.golden import run_golden

        batch = TraceBatch.from_builders(builders)
        res = Simulator(sc, batch).run()
        gold = run_golden(sc, batch)
        np.testing.assert_array_equal(res.clock_ps, gold.clock_ps,
                                      err_msg="clock")
        return res

    def test_serialized_pingpong_exact(self):
        """Cross-cluster ping-pong (strictly serialized by the
        send/recv dependence): bit-exact incl. hub contention queues."""
        sc = make_config(16, contention="true")
        bs = [TraceBuilder() for _ in range(16)]
        for r in range(8):
            bs[0].send(15, 8)
            bs[15].recv(0, 8)
            bs[15].send(0, 8)
            bs[0].recv(15, 8)
        self._assert_exact(sc, bs)

    def test_serialized_mixed_routes_exact(self):
        """ENet (intra-cluster), ONet (cross-cluster), and self sends in
        one serialized chain, both routing strategies."""
        for strategy in ("cluster_based", "distance_based"):
            sc = make_config(16, strategy=strategy, contention="true")
            bs = [TraceBuilder() for _ in range(16)]
            chain = [(0, 1), (1, 12), (12, 3), (3, 3), (3, 0)]
            for (a, b) in chain:
                bs[a].send(b, 32)
                if a != b:
                    bs[b].recv(a, 32)
                else:
                    bs[a].recv(a, 32)
            self._assert_exact(sc, bs)

    def test_hub_queue_compounding_exact(self):
        """Back-to-back ONet packets from one cluster compound the send
        hub's queue.  The sends are PROGRAM-ordered on one tile (no
        round trips between them), so successive packets arrive inside
        the hub's busy tail — measured per-packet hub delays 16, 32, ...
        cycles — and the serial oracle must reproduce the compounding
        exactly (still deterministic: one sender, program order)."""
        sc = make_config(16, contention="true")
        bs = [TraceBuilder() for _ in range(16)]
        for r in range(6):
            bs[0].send(15, 64)
        for r in range(6):
            bs[15].recv(0, 64)
        res = self._assert_exact(sc, bs)
        # vacuity guard: with contention off the completion must be
        # strictly earlier (the queue delays above are real)
        bs2 = [TraceBuilder() for _ in range(16)]
        for r in range(6):
            bs2[0].send(15, 64)
        for r in range(6):
            bs2[15].recv(0, 64)
        r_off = run(make_config(16, contention="false"), bs2)
        assert res.completion_time_ps > r_off.completion_time_ps


def test_route_atac_matches_zeroload_on_idle_hubs():
    """atac_zeroload_ps (the memory net's latency/fan-out basis) must
    equal route_atac on fresh (idle) hub queues — the two formulas are
    written separately, so pin them together."""
    import jax.numpy as jnp

    from graphite_tpu.models.network_atac import (
        AtacParams, atac_zeroload_ps, init_atac_state, route_atac,
    )

    sc = make_config(16, strategy="cluster_based", contention="true")
    p = AtacParams.from_config(sc, "user")
    src = jnp.arange(16, dtype=jnp.int32)
    for dst_val in (0, 5, 10, 15):
        dst = jnp.full((16,), dst_val, jnp.int32)
        t0 = jnp.full((16,), 1_000_000, jnp.int64)
        st = init_atac_state(p)
        _, arrival, _ = route_atac(
            p, st, src, dst, jnp.full((16,), 512, jnp.int64), t0,
            jnp.ones(16, bool), True)
        zl = atac_zeroload_ps(p, src, dst, 512, True)
        assert (arrival == t0 + zl).all(), dst_val
