"""Differential testing of the memory hierarchy: vectorized MSI/MOSI
engine vs the sequential golden model (`golden/memory_model.py`).

Contract (see the golden model's ordering-discipline docstring):
 - bit-exact on serialized or line-disjoint workloads — clocks AND all
   memory counters (the message-carried-timestamp algebra makes disjoint
   transactions commutative, so iteration order cannot matter);
 - a quantified envelope on free-running racy workloads, where the
   engine's iteration interleaving and the oracle's clock ordering may
   resolve same-line races differently (BASELINE's <=2% divergence
   budget applied per tile).

Reference semantics under test: `l1_cache_cntlr.cc:90-180`,
`l2_cache_cntlr.cc:181-503`, `dram_directory_cntlr.cc:44-559`,
`directory_schemes/directory_entry_*.cc`.
"""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.golden import run_golden
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import TraceBatch, TraceBuilder

MSI = "pr_l1_pr_l2_dram_directory_msi"
MOSI = "pr_l1_pr_l2_dram_directory_mosi"


def make_config(n_tiles, proto=MSI, net="magic", extra=""):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = {net}
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[caching_protocol]
type = {proto}
[core/static_instruction_costs]
mov = 1
ialu = 1
{extra}
"""
    return SimConfig(ConfigFile.from_string(text))


def assert_exact(sc, batch):
    res = Simulator(sc, batch).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps,
                                  err_msg="clock")
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)
    return res, gold


# ---- workload builders ----------------------------------------------------


def mutex_rmw(n, rounds, base=0x900000, lines=1):
    """Mutex-serialized read-modify-write of shared lines: at any moment
    exactly one tile touches the shared data, so engine iteration order
    and oracle clock order coincide."""
    bs = [TraceBuilder() for _ in range(n)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, n)
    for b in bs:
        b.barrier_wait(9)
    for r in range(n * rounds):
        t = r % n
        addr = base + (r % lines) * 64
        bs[t].mutex_lock(0)
        bs[t].load(addr, 8)
        bs[t].store(addr, 8)
        bs[t].mutex_unlock(0)
    return TraceBatch.from_builders(bs)


def share_then_write(n, lines=4, rounds=2, base=0xA00000):
    """Readers build up a sharer list (serialized), then one writer
    triggers the INV multicast — exercises fan-out + scheme variants."""
    bs = [TraceBuilder() for _ in range(n)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, n)
    for b in bs:
        b.barrier_wait(9)
    for r in range(rounds):
        for li in range(lines):
            addr = base + li * 64
            for t in range(1, n):
                bs[t].mutex_lock(0)
                bs[t].load(addr, 8)
                bs[t].mutex_unlock(0)
            for b in bs:
                b.barrier_wait(9)
            bs[0].mutex_lock(0)
            bs[0].store(addr, 8)
            bs[0].mutex_unlock(0)
            for b in bs:
                b.barrier_wait(9)
    return TraceBatch.from_builders(bs)


def wb_pattern(rounds=6, base=0xB00000):
    """Alternating writer/reader on one line: SH on MODIFIED (the WB
    downgrade path; MSI M->S write-through, MOSI M->O c2c)."""
    bs = [TraceBuilder() for _ in range(2)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 2)
    for b in bs:
        b.barrier_wait(9)
    for r in range(rounds):
        bs[0].mutex_lock(0)
        bs[0].store(base, 8)
        bs[0].mutex_unlock(0)
        for b in bs:
            b.barrier_wait(9)
        bs[1].mutex_lock(0)
        bs[1].load(base, 8)
        bs[1].mutex_unlock(0)
        for b in bs:
            b.barrier_wait(9)
    return TraceBatch.from_builders(bs)


def line_stream(n_lines, base=0x100000, write_first=True):
    """Single tile streaming writes then reads over many lines — directory
    set conflicts (NULLIFY) and L2 evictions with a tiny directory."""
    b = TraceBuilder()
    for i in range(n_lines):
        (b.store if write_first else b.load)(base + i * 64, 8)
    for i in range(n_lines):
        b.load(base + i * 64, 8)
    return TraceBatch.from_builders([b])


# ---- bit-exact tests ------------------------------------------------------


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_single_tile_random(proto):
    sc = make_config(1, proto)
    batch = synthetic.memory_stress_trace(
        1, n_accesses=300, working_set_bytes=1 << 16, seed=3)
    assert_exact(sc, batch)


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_disjoint_working_sets(proto):
    sc = make_config(4, proto)
    batch = synthetic.memory_stress_trace(
        4, n_accesses=150, working_set_bytes=1 << 15, seed=5)
    assert_exact(sc, batch)


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_mutex_serialized_sharing(proto):
    res, gold = assert_exact(make_config(4, proto), mutex_rmw(4, 6))
    if proto == MSI:
        # MSI: the EX after a read-share INVs the old sharer.  MOSI
        # instead FLUSHes the owner (data travels with the invalidation),
        # which the invalidations counter deliberately excludes.
        assert gold.mem_counters["invalidations"].sum() > 0
    assert gold.mem_counters["l2_misses"].sum() > 0


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_wb_downgrade(proto):
    res, gold = assert_exact(make_config(2, proto), wb_pattern())
    if proto == MSI:
        # MSI writes WB data through to DRAM
        assert gold.mem_counters["dram_writes"].sum() > 0


@pytest.mark.parametrize("scheme", [
    "full_map", "limited_no_broadcast", "ackwise", "limited_broadcast",
    "limitless"])
@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_directory_scheme(scheme, proto):
    extra = (f"[dram_directory]\ndirectory_type = {scheme}\n"
             "max_hw_sharers = 2\n[limitless]\n"
             "software_trap_penalty = 200\n")
    res, gold = assert_exact(make_config(4, proto, extra=extra),
                             share_then_write(4))
    if scheme in ("ackwise", "limited_broadcast"):
        assert gold.mem_counters["dir_broadcasts"].sum() > 0


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_nullify_tiny_directory(proto):
    extra = "[dram_directory]\ntotal_entries = 16\nassociativity = 2\n"
    res, gold = assert_exact(make_config(1, proto, extra=extra),
                             line_stream(64))
    # 64 lines through 8 sets x 2 ways must have displaced entries
    assert gold.mem_counters["dir_accesses"].sum() > 64


def test_hop_counter_memory_net():
    assert_exact(make_config(4, MSI, net="emesh_hop_counter"),
                 mutex_rmw(4, 5))


def test_icache_modeling():
    extra = "enable_icache_modeling = true\n"
    sc = make_config(
        1, MSI, extra=f"[general]\n{extra}")
    from graphite_tpu.trace.schema import Op

    b = TraceBuilder()
    for i in range(200):
        b.instr(Op.IALU, pc=0x4000 + (i % 40) * 64)
    res, gold = assert_exact(sc, TraceBatch.from_builders([b]))
    assert gold.mem_counters["l1i_hits"].sum() > 0


# ---- envelope test on a racy workload -------------------------------------


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_racy_shared_envelope(proto):
    """Free-running tiles with a 30% shared-line mix: same-line races may
    resolve in different orders between the engine and the oracle — both
    are valid serializations of a workload on which the reference itself
    is nondeterministic (its lax schemes admit arbitrary cross-thread
    interleavings).  The envelope is pinned at 3% and documented in
    BASELINE.md ("racy-workload carve-out"); BASELINE's 2% budget applies
    to the deterministic contract, which test_memory_golden's
    serialized/disjoint cases hold BIT-EXACTLY.  Measured spread over
    {MSI, MOSI} x 6 seeds after the phase fusion: 5/12 bit-exact,
    median ~0.3%, tail 2.02% (MSI seed 11)."""
    sc = make_config(4, proto)
    batch = synthetic.memory_stress_trace(
        4, n_accesses=200, working_set_bytes=1 << 14,
        shared_fraction=0.3, seed=11)
    res = Simulator(sc, batch).run()
    gold = run_golden(sc, batch)
    rel = np.abs(res.clock_ps.astype(float) - gold.clock_ps.astype(float))
    rel = rel / np.maximum(gold.clock_ps.astype(float), 1.0)
    assert rel.max() <= 0.03, (
        f"clock divergence {rel.max():.4f} exceeds 3% envelope: "
        f"engine={res.clock_ps.tolist()} golden={gold.clock_ps.tolist()}")
    # functional + conservation invariants stay exact
    for k in ("l2_misses", "dram_reads", "dram_writes"):
        e = int(np.asarray(res.mem_counters[k]).sum())
        g = int(gold.mem_counters[k].sum())
        assert abs(e - g) <= max(2, 0.02 * max(e, g)), (
            f"{k}: engine {e} vs golden {g}")


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_round_robin_replacement(proto):
    """round_robin policy (`round_robin_replacement_policy.cc`): cycling
    per-set victim index, validity-blind, no-op hit updates — differential
    against the oracle, plus it must measurably differ from LRU."""
    extra = ("[l1_dcache/T1]\nreplacement_policy = round_robin\n"
             "[l2_cache/T1]\nreplacement_policy = round_robin\n")
    sc = make_config(1, proto, extra=extra)
    from graphite_tpu.memory.params import MemParams
    assert MemParams.from_config(sc).l1d.replacement == "round_robin"
    # thrash one L1 set: 6 lines into a 4-way set, re-touch line 0 between
    # fills (LRU would keep it hot; round_robin evicts it on schedule)
    b = TraceBuilder()
    lines = [0x400 + i * 128 for i in range(6)]   # all map to l1d set 0
    for r in range(4):
        for ln in lines:
            b.load(ln << 6, 8)
            b.load(lines[0] << 6, 8)
    batch = TraceBatch.from_builders([b])
    res, gold = assert_exact(sc, batch)
    res_lru, _ = assert_exact(make_config(1, proto), batch)
    assert not np.array_equal(res.clock_ps, res_lru.clock_ps), (
        "round_robin timing identical to LRU on a thrashing set")


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_heterogeneous_cache_geometries(proto):
    """Per-tile cache types (`misc/config.h:92-100` model_list): tiles
    0-1 run small T0 caches, tiles 2-3 big T1 — dense arrays pad to the
    max geometry with per-tile set moduli / way masks.  Differential vs
    the oracle (which builds each tile's true geometry independently)."""
    extra = """
[tile]
model_list = "<2, simple, T0, T0, T0><2, simple, T1, T1, T1>"
[l1_icache/T0]
cache_size = 4
associativity = 2
[l1_dcache/T0]
cache_size = 4
associativity = 2
data_access_time = 2
[l2_cache/T0]
cache_size = 32
associativity = 4
data_access_time = 5
tags_access_time = 2
"""
    sc = make_config(4, proto, extra=extra)
    from graphite_tpu.memory.params import MemParams
    mp = MemParams.from_config(sc)
    assert mp.l1d.tile_sets is not None and mp.l1d.tile_ways is not None
    assert mp.l1d.tile_sets[0] < mp.l1d.tile_sets[2]
    # both private working sets (evictions on the small tiles) and
    # mutex-serialized sharing between small- and big-cache tiles
    batch = synthetic.memory_stress_trace(
        4, n_accesses=150, working_set_bytes=1 << 14, seed=13)
    assert_exact(sc, batch)
    res, gold = assert_exact(make_config(4, proto, extra=extra),
                             mutex_rmw(4, 5))
    assert gold.mem_counters["l2_misses"].sum() > 0


# ---- shared-L2 protocols vs the GoldenShL2 oracle -------------------------

SHL2_MSI = "pr_l1_sh_l2_msi"
SHL2_MESI = "pr_l1_sh_l2_mesi"


@pytest.mark.parametrize("proto", [SHL2_MSI, SHL2_MESI])
def test_shl2_serialized_exact(proto):
    """Mutex-serialized shared-line RMWs through the shared-L2 engine:
    bit-exact clocks + counters vs the independent serial oracle."""
    sc = make_config(4, proto)
    assert_exact(sc, mutex_rmw(4, rounds=6, lines=2))


@pytest.mark.parametrize("proto", [SHL2_MSI, SHL2_MESI])
def test_shl2_disjoint_exact(proto):
    """Line-disjoint concurrent streams (capacity pressure on the L1s and
    slices): disjoint transactions commute, so bit-exact."""
    sc = make_config(4, proto)
    bs = [TraceBuilder() for _ in range(4)]
    for t, b in enumerate(bs):
        for i in range(80):
            addr = 0x100000 + (t * 80 + i) * 64
            (b.store if i % 3 == 0 else b.load)(addr, 8)
    res, gold = assert_exact(sc, TraceBatch.from_builders(bs))
    assert int(gold.mem_counters["l2_misses"].sum()) > 0


def test_shl2_mesi_exclusive_grant_and_promote():
    """MESI: a lone reader gets EXCLUSIVE (no messages on its later
    write); a second reader demotes via WB.  Serialized by mutex."""
    sc = make_config(4, SHL2_MESI)
    bs = [TraceBuilder() for _ in range(4)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 4)
    for b in bs:
        b.barrier_wait(9)
    bs[0].mutex_lock(0)
    bs[0].load(0x900000, 8)    # EXCL grant
    bs[0].store(0x900000, 8)   # silent E->M promote
    bs[0].mutex_unlock(0)
    bs[1].mutex_lock(0)
    bs[1].load(0x900000, 8)    # WB the owner, both SHARED
    bs[1].mutex_unlock(0)
    bs[2].mutex_lock(0)
    bs[2].store(0x900000, 8)   # INV sweep upgrade
    bs[2].mutex_unlock(0)
    assert_exact(sc, TraceBatch.from_builders(bs))


@pytest.mark.parametrize("proto", [SHL2_MSI, SHL2_MESI])
def test_shl2_slice_nullify_exact(proto):
    """Slice-victim replacement with live L1 copies (NULLIFY sweep then
    the original request resumes): tiny slice via config, serialized."""
    extra = "[l2_cache/T1]\ncache_size = 4\nassociativity = 1\n"
    sc = make_config(2, proto, extra=extra)
    bs = [TraceBuilder() for _ in range(2)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 2)
    for b in bs:
        b.barrier_wait(9)
    # walk lines that collide in the 1-way slice sets at home 0
    for i in range(6):
        bs[0].mutex_lock(0)
        bs[0].store(0x800000 + i * 2 * 64 * 64, 8)
        bs[0].mutex_unlock(0)
    assert_exact(sc, TraceBatch.from_builders(bs))


# ---- L2 miss-type classification (`cache.h:45-49`) ------------------------


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_miss_type_classification(proto):
    """COLD / CAPACITY / SHARING classification (`cache.cc getMissType`:
    evicted-set -> capacity, invalidated/fetched-set -> sharing, else
    cold), hashed-bucket model shared engine<->oracle.  A tiny L2 forces
    capacity re-misses; a writer invalidating a reader forces sharing
    misses; first touches are cold."""
    extra = ("[l2_cache/T1]\ncache_size = 4\nassociativity = 1\n"
             "track_miss_types = true\n")
    sc = make_config(2, proto, extra=extra)
    bs = [TraceBuilder() for _ in range(2)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 2)
    for b in bs:
        b.barrier_wait(9)
    # capacity: tile 0 streams lines that collide in the 1-way sets,
    # then re-touches them (evicted-set hits)
    for rep in range(2):
        for i in range(4):
            bs[0].mutex_lock(0)
            bs[0].load(0x100000 + i * 64 * 64, 8)
            bs[0].mutex_unlock(0)
    # sharing: tile 1 reads a line, tile 0 writes it (INV), tile 1
    # re-reads (invalidated-set hit)
    for b in bs:
        b.barrier_wait(9)
    for rep in range(3):
        bs[1].mutex_lock(0)
        bs[1].load(0x900000, 8)
        bs[1].mutex_unlock(0)
        for b in bs:
            b.barrier_wait(9)
        bs[0].mutex_lock(0)
        bs[0].store(0x900000, 8)
        bs[0].mutex_unlock(0)
        for b in bs:
            b.barrier_wait(9)
    res, gold = assert_exact(sc, TraceBatch.from_builders(bs))
    for k in ("l2_cold_misses", "l2_capacity_misses", "l2_sharing_misses"):
        assert int(gold.mem_counters[k].sum()) > 0, k
    # every classified miss is accounted exactly once
    total = sum(int(gold.mem_counters[k].sum())
                for k in ("l2_cold_misses", "l2_capacity_misses",
                          "l2_sharing_misses"))
    assert total == int(gold.mem_counters["l2_misses"].sum())


def test_miss_types_off_by_default():
    sc = make_config(2, MSI)
    res, _ = assert_exact(sc, mutex_rmw(2, 3))
    assert int(np.asarray(res.mem_counters["l2_cold_misses"]).sum()) == 0


def test_requester_unroll_bit_exact():
    """`[general] requester_unroll` packs several L1-hitting slots of one
    record into a single engine iteration; slot times are measured from
    the record's base clock, so timing must be BIT-identical to the
    oracle (and to unroll=1) on serialized workloads."""
    extra = "[general]\nrequester_unroll = 3\n"
    sc = make_config(4, MSI, extra=extra)
    assert_exact(sc, mutex_rmw(4, rounds=5, lines=2))


# ---- directory write-staging (MemParams.dir_stage_cap) ---------------------
# The staged path accumulates sharers writes in the small unique-key table
# and flushes once per inner block (engine._stage_put / dir_stage_flush);
# these pin bit-exactness vs both the oracle and the direct-scatter path,
# with inner_block=4 so runs cross MANY flush boundaries and reads hit
# staged-but-unflushed entries.


def assert_exact_staged(sc, batch):
    res = Simulator(sc, batch, dir_stage=True, inner_block=4).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps,
                                  err_msg="clock")
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)
    return res, gold


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_staged_serialized_exact(proto):
    assert_exact_staged(make_config(6, proto), mutex_rmw(6, rounds=4))


def test_staged_limited_no_broadcast_exact():
    """5 staged writes/iteration (the two extra capacity-displacement
    updates) + overwrite-in-place dedup on the same entry."""
    extra = ("[dram_directory]\ndirectory_type = limited_no_broadcast\n"
             "max_hw_sharers = 2\n")
    assert_exact_staged(make_config(6, MSI, extra=extra),
                        mutex_rmw(6, rounds=4))


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_staged_nullify_tiny_directory(proto):
    """Directory capacity pressure: NULLIFY victim reads must see staged
    entries (the victim may have been written this block)."""
    extra = "[dram_directory]\ntotal_entries = 16\nassociativity = 2\n"
    assert_exact_staged(make_config(4, proto, extra=extra),
                        mutex_rmw(4, rounds=4, lines=3))


def test_staged_matches_direct_racy():
    """On free-running racy traffic the engine diverges from the oracle
    (documented envelope) but the staged and direct programs must stay
    BIT-IDENTICAL to each other: staging is pure mechanism, not policy."""
    batch = synthetic.memory_stress_trace(
        8, n_accesses=80, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.6, seed=11)
    sc = make_config(8)
    r0 = Simulator(sc, batch, dir_stage=False).run()
    r1 = Simulator(sc, batch, dir_stage=True, inner_block=4).run()
    np.testing.assert_array_equal(np.asarray(r0.clock_ps),
                                  np.asarray(r1.clock_ps))
    for k in r0.mem_counters:
        np.testing.assert_array_equal(np.asarray(r0.mem_counters[k]),
                                      np.asarray(r1.mem_counters[k]),
                                      err_msg=k)


# ---- L2 cache-line utilization (cache_line_utilization.h) -----------------


@pytest.mark.parametrize("proto", [MSI, MOSI])
def test_cache_line_utilization_exact(proto):
    """Per-line read/write counters incremented on L2 accesses and
    histogram-classified when the line departs (eviction, upgrade
    invalidate, INV/FLUSH service) — bit-exact engine vs oracle,
    including the classified totals (`cache/cache_line_utilization.h`;
    the MOSI L2 controller's harvest points,
    `mosi/l2_cache_cntlr.cc:120`)."""
    # tiny 1-way L1-D so repeated accesses MISS the L1 and re-touch the
    # L2 (building utilization); small 1-way L2 so capacity evictions
    # classify lines too
    extra = ("[l1_dcache/T1]\ncache_size = 1\nassociativity = 1\n"
             "[l2_cache/T1]\ncache_size = 4\nassociativity = 1\n"
             "track_cache_line_utilization = true\n")
    sc = make_config(4, proto, extra=extra)
    bs = [TraceBuilder() for _ in range(4)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, 4)
    for b in bs:
        b.barrier_wait(9)
    # X and Y collide in the 16-set 1-way L1 but land in different L2
    # sets: alternating them L1-misses every time while the L2 serves
    # hits, accumulating per-line counts; the store then upgrades
    # (classify via the upgrade path) and cross-tile INVs classify the
    # other tiles' copies
    X, Y = 0x900000, 0x900000 + 16 * 64
    for rep in range(2):
        for t, b in enumerate(bs):
            b.mutex_lock(0)
            for i in range(3):
                b.load(X, 8)
                b.load(Y, 8)
            b.store(X, 8)
            for i in range(3):
                b.load(0x100000 + t * 64 + i * 64 * 64, 8)  # capacity
            b.mutex_unlock(0)
    res, gold = assert_exact(sc, TraceBatch.from_builders(bs))
    hist = np.asarray(res.mem_counters["line_util_hist"])
    assert hist.sum() > 0, "no lines were classified"
    # multi-access lines must appear in buckets >= 2 (2-3 accesses)
    assert hist[:, 2:].sum() > 0
    assert int(np.asarray(res.mem_counters["line_util_reads"]).sum()) > 0
    assert int(np.asarray(res.mem_counters["line_util_writes"]).sum()) > 0


def test_cache_line_utilization_staged_and_summary():
    """The staged-directory program carries the same utilization
    machinery, and the sim.out summary renders the histogram."""
    extra = ("[l2_cache/T1]\ncache_size = 4\nassociativity = 1\n"
             "track_cache_line_utilization = true\n")
    sc = make_config(4, MSI, extra=extra)
    batch = mutex_rmw(4, rounds=4, lines=3)
    r0 = Simulator(sc, batch).run()
    r1 = Simulator(sc, batch, dir_stage=True, inner_block=4).run()
    for k in ("line_util_hist", "line_util_reads", "line_util_writes"):
        np.testing.assert_array_equal(np.asarray(r0.mem_counters[k]),
                                      np.asarray(r1.mem_counters[k]),
                                      err_msg=k)
    assert "Cache Line Utilization (L2):" in r0.summary()
