"""Memory-subsystem tests: cache arrays + MSI dram-directory protocol.

Modeled on the reference's Pin-less shared-memory unit tests
(`tests/unit/shared_mem_test1/shared_mem_test1.cc:21-59`: write on core 0,
read on core 1, values must propagate through the coherence protocol) plus
cycle-accounting checks that document the exact latency algebra of the
reference's timing path (`l1_cache_cntlr.cc:90-180`,
`dram_directory_cntlr.cc:44-559`, `dram_perf_model.cc:80-115`).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.memory import MemParams
from graphite_tpu.memory import cache_array as ca
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=2, **over):
    extra = "\n".join(f"{k} = {v}" for k, v in over.items())
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
{extra}
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def run(sc, builders, **kw):
    batch = TraceBatch.from_builders(builders)
    sim = Simulator(sc, batch, **kw)
    return sim.run()


# --------------------------------------------------------------------------
# cache-array unit tests


class TestCacheArrays:
    def test_lookup_miss_then_insert_hit(self):
        c = ca.make_cache(2, 4, 2)
        line = jnp.asarray([5, 9], jnp.int32)
        hit, way, st = ca.lookup(c, line)
        assert not bool(hit.any())
        mask = jnp.asarray([True, True])
        way, v_valid, _, _ = ca.pick_victim(c, line)
        assert not bool(v_valid.any())
        c = ca.insert_at(c, line, way, ca.SHARED, mask)
        hit, _, st = ca.lookup(c, line)
        assert bool(hit.all())
        assert st.tolist() == [ca.SHARED, ca.SHARED]
        # tile 1 never inserted line 5
        hit5, _, _ = ca.lookup(c, jnp.asarray([9, 5], jnp.int32))
        assert hit5.tolist() == [False, False]

    def test_lru_eviction_order(self):
        # 1 set x 2 ways: inserting 3 lines evicts the least recently used
        c = ca.make_cache(1, 1, 2)
        m = jnp.asarray([True])
        for line in (1, 2):
            ln = jnp.asarray([line], jnp.int32)
            way, _, _, _ = ca.pick_victim(c, ln)
            c = ca.insert_at(c, ln, way, ca.MODIFIED, m)
        # touch line 1 -> line 2 becomes LRU
        hit, way, _ = ca.lookup(c, jnp.asarray([1], jnp.int32))
        assert bool(hit.all())
        c = ca.touch_lru(c, jnp.asarray([1], jnp.int32), way, m)
        ln = jnp.asarray([3], jnp.int32)
        way, v_valid, v_line, v_state = ca.pick_victim(c, ln)
        assert bool(v_valid.all())
        assert v_line.tolist() == [2]
        assert v_state.tolist() == [ca.MODIFIED]

    def test_state_predicates(self):
        st = jnp.asarray(
            [ca.INVALID, ca.SHARED, ca.MODIFIED, ca.EXCLUSIVE, ca.OWNED],
            jnp.uint8)
        assert ca.state_readable(st).tolist() == [False, True, True, True, True]
        assert ca.state_writable(st).tolist() == [False, False, True, True, False]

    def test_invalidate(self):
        c = ca.make_cache(1, 2, 2)
        ln = jnp.asarray([4], jnp.int32)
        way, _, _, _ = ca.pick_victim(c, ln)
        c = ca.insert_at(c, ln, way, ca.SHARED, jnp.asarray([True]))
        c = ca.invalidate(c, ln, jnp.asarray([True]))
        hit, _, _ = ca.lookup(c, ln)
        assert not bool(hit.any())


# --------------------------------------------------------------------------
# MemParams resolution


class TestMemParams:
    def test_default_t1_geometry(self):
        mp = MemParams.from_config(make_config(4))
        # T1 caches (`carbon_sim.cfg:207-230`): L1-I 16KB/4w, L1-D 32KB/4w,
        # L2 512KB/8w, 64B lines
        assert mp.line_size == 64
        assert (mp.l1i.num_sets, mp.l1i.num_ways) == (64, 4)
        assert (mp.l1d.num_sets, mp.l1d.num_ways) == (128, 4)
        assert (mp.l2.num_sets, mp.l2.num_ways) == (1024, 8)
        assert mp.l2.tags_cycles == 3
        assert mp.l2.data_and_tags_cycles == 8  # parallel model
        assert mp.mc_tiles == (0, 1, 2, 3)
        assert mp.dram_processing_ns == 13  # 64B / 5GBps + 1
        # all modules in one default DVFS domain -> no sync delays
        assert mp.sync_cycles(0, 3) == 0

    def test_sequential_perf_model(self):
        sc = make_config(2)
        sc.cfg.set("l2_cache/T1/perf_model_type", "sequential")
        mp = MemParams.from_config(sc)
        assert mp.l2.data_and_tags_cycles == 8 + 3

    def test_directory_autosizing(self):
        mp = MemParams.from_config(make_config(4))
        # num_sets = ceil(2*512KB*4 / (64*16*4)) = 1024 -> pow2 1024
        assert mp.dir_sets == 1024
        assert mp.dir_ways == 16


# --------------------------------------------------------------------------
# protocol end-to-end


class TestMSIProtocol:
    def test_cold_store_exact_latency_single_tile(self):
        """Documents the full cold-miss latency algebra (1 tile, magic net).

        store: core->L1D sync(0) + L1 tags(1) + L2 tags(3) | net(1) |
        dir access(6, 128KB auto staircase) + dram(100+13 ns) | net(1) |
        L2 fill(8) + L1 fill(1)  = 134 ns; +1 cycle mov cost = 135 ns.
        """
        sc = make_config(1)
        b = TraceBuilder()
        b.store_value(0x1000, 7)
        res = run(sc, [b])
        assert res.func_errors == 0
        assert res.clock_ps[0] == 135_000
        assert res.memory_stall_ps[0] == 134_000
        mc = res.mem_counters
        assert mc["l1d_write_misses"][0] == 1
        assert mc["l2_misses"][0] == 1
        assert mc["dram_reads"][0] == 1

    def test_l1_hit_after_fill(self):
        sc = make_config(1)
        b = TraceBuilder()
        b.store_value(0x1000, 7)       # cold: 134 ns stall
        b.store_value(0x1000, 8)       # L1 hit (M): 1 cycle
        b.load_check(0x1000, 8)        # L1 hit: 1 cycle
        res = run(sc, [b])
        assert res.func_errors == 0
        # 135 + (1 stall + 1 cost) + (1 + 1) ns
        assert res.clock_ps[0] == 139_000
        mc = res.mem_counters
        assert mc["l1d_write_hits"][0] == 1
        assert mc["l1d_read_hits"][0] == 1

    def test_producer_consumer_shared_mem_test1(self):
        """shared_mem_test1 analog: write on tile 0, read on tile 1."""
        sc = make_config(2)
        addr = 0x0  # line 0 -> home tile 0
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 42)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.load_check(addr, 42)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0
        mc = res.mem_counters
        # tile 1 missed everywhere; home had to WB the M line from tile 0
        assert mc["l1d_read_misses"][1] == 1
        assert mc["l2_misses"][1] == 1
        assert mc["dram_writes"].sum() >= 1  # WB_REP wrote the line back

    def test_write_invalidation_ping_pong(self):
        """Alternating writers to one line exercise INV + FLUSH + upgrade."""
        sc = make_config(2)
        addr = 0x40  # line 1 -> home tile 1
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.store_value(addr, 1)     # EX (cold)
        b0.barrier_wait(0)
        b0.barrier_wait(0)
        b0.load_check(addr, 2)      # tile 1's write must be visible
        b1 = TraceBuilder()
        b1.barrier_wait(0)
        b1.store_value(addr, 2)     # EX: FLUSH tile 0's M copy
        b1.barrier_wait(0)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0

    def test_read_sharers_then_upgrade(self):
        """Both tiles read (S everywhere), then tile 0 writes: the upgrade
        sends INV_REP for its own copy + the directory invalidates tile 1
        (`l2_cache_cntlr.cc:261-282`, `processExReqFromL2Cache` SHARED)."""
        sc = make_config(2)
        addr = 0x80
        b0 = TraceBuilder()
        b0.barrier_init(0, 2)
        b0.load_check(addr, 0)
        b0.barrier_wait(0)
        b0.store_value(addr, 5)
        b0.barrier_wait(0)
        b1 = TraceBuilder()
        b1.load_check(addr, 0)
        b1.barrier_wait(0)
        b1.barrier_wait(0)
        b1.load_check(addr, 5)
        res = run(sc, [b0, b1])
        assert res.func_errors == 0
        mc = res.mem_counters
        assert mc["invalidations"].sum() >= 1

    def test_capacity_evictions(self):
        """March over > L1D capacity worth of lines; protocol stays sound."""
        sc = make_config(1)
        b = TraceBuilder()
        n_lines = 128 * 4 + 8  # L1D lines + a few
        for i in range(n_lines):
            b.store_value(i * 64, i)
        for i in range(0, n_lines, 7):
            b.load_check(i * 64, i)
        res = run(sc, [b])
        assert res.func_errors == 0

    def test_l2_capacity_evictions_tiny_l2(self):
        """Tiny L2 forces L2 evictions with FLUSH_REP messages to the home."""
        sc = make_config(1)
        sc.cfg.set("l2_cache/T1/cache_size", "1")       # 1KB: 16 lines
        sc.cfg.set("l1_dcache/T1/cache_size", "1")      # 4 sets x 4 ways
        sc.cfg.set("l1_icache/T1/cache_size", "1")
        b = TraceBuilder()
        for i in range(64):
            b.store_value(i * 64, i)
        for i in range(64):
            b.load_check(i * 64, i)
        res = run(sc, [b])
        assert res.func_errors == 0
        assert res.mem_counters["evictions"][0] > 0
        assert res.mem_counters["dram_writes"][0] > 0

    def test_directory_nullify(self):
        """A tiny directory forces entry replacement (NULLIFY_REQ path,
        `processDirectoryEntryAllocationReq`)."""
        sc = make_config(1)
        sc.cfg.set("dram_directory/total_entries", "4")
        sc.cfg.set("dram_directory/associativity", "2")
        b = TraceBuilder()
        for i in range(16):
            b.store_value(i * 64, i)
        for i in range(16):
            b.load_check(i * 64, i)
        res = run(sc, [b])
        assert res.func_errors == 0

    def test_four_tile_all_to_one_line(self):
        """Four writers to one hot line, serialized by barriers."""
        sc = make_config(4)
        addr = 0x100
        builders = []
        for t in range(4):
            b = TraceBuilder()
            if t == 0:
                b.barrier_init(0, 4)
            for r in range(4):
                if r == t:
                    b.store_value(addr, 100 + r)
                b.barrier_wait(0)
            b.load_check(addr, 103)
            builders.append(b)
        res = run(sc, builders)
        assert res.func_errors == 0

    def test_models_disabled_zero_latency(self):
        """trigger_models_within_application: before ENABLE_MODELS the
        protocol runs functionally with zero latency (`simulator.cc:399-413`)."""
        sc = make_config(1, trigger_models_within_application="true")
        b = TraceBuilder()
        b.store_value(0x40, 9)
        b.load_check(0x40, 9)
        res = run(sc, [b])
        assert res.func_errors == 0
        assert res.clock_ps[0] == 0
        assert res.memory_stall_ps[0] == 0

    def test_mem_disabled_when_no_shared_mem(self):
        sc = make_config(1, enable_shared_mem="false")
        b = TraceBuilder()
        b.store_value(0x40, 9)
        b.instr(Op.IALU)
        res = run(sc, [b])
        assert res.mem_counters is None
        assert res.clock_ps[0] == 2_000  # two 1-cycle instructions only


# --------------------------------------------------------------------------
# icache modeling


class TestICache:
    def test_icache_instruction_buffer(self):
        """With icache modeling on, same-line fetches hit the instruction
        buffer (1 cycle, `core.cc:205-220`); the first fetch misses L1-I
        and walks the protocol."""
        sc = make_config(1, enable_icache_modeling="true")
        b = TraceBuilder()
        b.instr(Op.IALU, pc=0x400)
        b.instr(Op.IALU, pc=0x404)  # same line: buffer hit
        b.instr(Op.IALU, pc=0x408)
        res = run(sc, [b])
        mc = res.mem_counters
        assert mc["l1i_misses"][0] == 1
        assert mc["l1i_hits"][0] == 2
        # fetch1 cold-miss (134ns) + 3x ialu (1 cyc) + 2x buffer hit (1 cyc)
        assert res.clock_ps[0] == 134_000 + 3_000 + 2_000


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
