"""Differential testing: vectorized engine vs the golden event-driven
oracle on random traces (the cycle-parity harness role of SURVEY §4 —
two independent implementations of the same semantics must agree
bit-exactly on clocks and counters)."""

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.golden import run_golden
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles, network="magic"):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = false
[network]
user = {network}
memory = magic
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
imul = 3
falu = 3
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 64
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


def diff(sc, builders, **kw):
    batch = TraceBatch.from_builders(builders)
    res = Simulator(sc, batch, **kw).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps, err_msg="clock")
    # the engine folds charged recv/sync waits into instruction_count
    # (`RecvInstruction`/`SyncInstruction` are dynamic instructions)
    np.testing.assert_array_equal(
        res.instruction_count,
        gold.instruction_count + gold.recv_instructions
        + gold.sync_instructions,
        err_msg="instrs")
    np.testing.assert_array_equal(res.recv_instructions,
                                  gold.recv_instructions, err_msg="recvs")
    np.testing.assert_array_equal(res.sync_instructions,
                                  gold.sync_instructions, err_msg="syncs")
    np.testing.assert_array_equal(res.bp_correct, gold.bp_correct,
                                  err_msg="bp")
    return res, gold


def random_trace(rng, n_tiles, length, *, barriers=True, mutexes=True,
                 messages=True):
    """A random-but-deadlock-free workload: compute, branches, neighbor
    ring messaging (each round: send to right, recv from left), barrier
    episodes, and mutex critical sections."""
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(0, n_tiles)
    builders[0].mutex_init(0)
    builders[0].mutex_init(1)
    # ensure init lands before use everywhere: one barrier round
    for b in builders:
        b.barrier_wait(0)
    rounds = length
    for r in range(rounds):
        kind = rng.integers(0, 6)
        if kind == 0:
            for t, b in enumerate(builders):
                for _ in range(int(rng.integers(1, 6))):
                    op = [Op.IALU, Op.IMUL, Op.FALU][int(rng.integers(3))]
                    b.instr(op)
        elif kind == 1:
            for t, b in enumerate(builders):
                b.branch(bool(rng.integers(2)), pc=int(rng.integers(256)))
                b.bblock(int(rng.integers(1, 30)), int(rng.integers(1, 40)))
        elif kind == 2 and messages:
            for t, b in enumerate(builders):
                b.send((t + 1) % n_tiles, int(rng.integers(4, 64)))
            for t, b in enumerate(builders):
                b.recv((t - 1) % n_tiles, 8)
        elif kind == 3 and mutexes:
            for t, b in enumerate(builders):
                m = int(rng.integers(2))
                b.mutex_lock(m)
                b.instr(Op.IALU)
                b.mutex_unlock(m)
        elif kind == 4 and barriers:
            for t, b in enumerate(builders):
                if rng.integers(2):
                    b.instr(Op.IMUL)
                b.barrier_wait(0)
        elif kind == 5 and mutexes:
            # nested critical sections in a fixed order (no deadlock):
            # lock(0) then lock(1) on every tile that participates
            for t, b in enumerate(builders):
                if rng.integers(2):
                    for _ in range(int(rng.integers(0, 4))):
                        b.instr(Op.IALU)
                    b.mutex_lock(0)
                    b.mutex_lock(1)
                    b.instr(Op.IALU)
                    b.mutex_unlock(1)
                    b.mutex_unlock(0)
    return builders


class TestGoldenDifferential:
    @pytest.mark.parametrize("seed", [1, 2, 3, 4])
    def test_random_magic(self, seed):
        rng = np.random.default_rng(seed)
        sc = make_config(4)
        diff(sc, random_trace(rng, 4, 12))

    @pytest.mark.parametrize("seed", [11, 12])
    def test_random_emesh(self, seed):
        rng = np.random.default_rng(seed)
        sc = make_config(8, network="emesh_hop_counter")
        diff(sc, random_trace(rng, 8, 10))

    def test_ping_pong_exact(self):
        sc = make_config(2)
        b0 = TraceBuilder()
        b1 = TraceBuilder()
        for r in range(20):
            b0.send(1, 8)
            b0.recv(1, 8)
            b1.recv(0, 8)
            b1.send(0, 8)
        diff(sc, [b0, b1])

    def test_mutex_contention_order(self):
        """Three tiles race for one mutex from staggered clocks; grant
        order must be identical (earliest sim-time wins)."""
        sc = make_config(3)
        builders = [TraceBuilder() for _ in range(3)]
        builders[0].mutex_init(0)
        builders[0].barrier_init(1, 3)
        for b in builders:
            b.barrier_wait(1)
        for t, b in enumerate(builders):
            for _ in range(t * 3):
                b.instr(Op.IALU)   # stagger arrival times
            b.mutex_lock(0)
            for _ in range(5):
                b.instr(Op.IALU)
            b.mutex_unlock(0)
        diff(sc, builders)

    def test_cross_mutex_time_order(self):
        """A lane blocked on one mutex must not lose another mutex to a
        later-simulated-time request: tile 0 does lock(1);lock(0) from
        t=3ns, tile 1 does lock(0) at t=10ns — tile 0's earlier request
        wins mutex 0 (the grant guard's completeness case)."""
        sc = make_config(2)
        b0 = TraceBuilder()
        b0.mutex_init(0).mutex_init(1)
        for _ in range(3):
            b0.instr(Op.IALU)
        b0.mutex_lock(1)
        b0.mutex_lock(0)
        b0.mutex_unlock(0)
        b0.mutex_unlock(1)
        b1 = TraceBuilder()
        for _ in range(10):
            b1.instr(Op.IALU)
        b1.mutex_lock(0)
        b1.mutex_unlock(0)
        res, gold = diff(sc, [b0, b1])
        assert res.clock_ps[0] == 3_000  # never waited

    def test_syscall_and_toggles(self):
        sc = make_config(2)
        b0 = TraceBuilder()
        b0.instr(Op.IALU)
        b0.syscall(0)
        b0.instr(Op.IALU)
        b1 = TraceBuilder()
        b1.instr(Op.IALU)
        diff(sc, [b0, b1])

    def test_join_semantics(self):
        sc = make_config(2)
        b0 = TraceBuilder().thread_spawn(1).thread_join(1).instr(Op.IALU)
        b1 = TraceBuilder()
        for _ in range(9):
            b1.instr(Op.IALU)
        diff(sc, [b0, b1])
