"""Benchmark trace programs replay end-to-end through the full stack
(SURVEY §4 tier 3 — the SPLASH-2/PARSEC benchmark tier, small sizes)."""

import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.trace.benchmarks import (
    BENCHMARKS,
    blackscholes_trace,
    canneal_trace,
    fft_trace,
    radix_trace,
)


def make_config(n_tiles, shared_mem=False, network="emesh_hop_counter"):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = {"true" if shared_mem else "false"}
[network]
user = {network}
memory = {network}
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
falu = 3
fmul = 5
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax_barrier
[clock_skew_management/lax_barrier]
quantum = 1000
"""
    return SimConfig(ConfigFile.from_string(text))


class TestBenchmarkTraces:
    def test_fft_completes_and_balances(self):
        res = Simulator(make_config(8),
                        fft_trace(8, points_per_tile=64)).run()
        assert res.func_errors == 0
        # all-to-all + barriers: every tile finishes within one barrier
        # epoch of the others
        assert res.clock_ps.min() > 0
        assert res.packets_sent.sum() >= 8 * 7 * 3  # 3 transposes

    def test_radix_tree_prefix_sum(self):
        res = Simulator(make_config(8),
                        radix_trace(8, keys_per_tile=64)).run()
        assert res.func_errors == 0
        assert res.packets_sent.sum() > 0

    def test_blackscholes_parallel(self):
        res = Simulator(make_config(4),
                        blackscholes_trace(4, options_per_tile=16,
                                           sweeps=2)).run()
        assert res.func_errors == 0
        # uniform work: clocks nearly equal across tiles
        assert res.clock_ps.max() - res.clock_ps.min() <= 2_000_000

    def test_canneal_memory_stress(self):
        res = Simulator(
            make_config(4, shared_mem=True, network="magic"),
            canneal_trace(4, footprint_lines=256, swaps_per_tile=8),
        ).run()
        assert res.func_errors == 0
        mc = res.mem_counters
        assert mc["l1d_read_misses"].sum() > 0  # random access misses

    def test_all_generators_registered(self):
        assert set(BENCHMARKS) >= {"fft", "radix", "blackscholes",
                                   "canneal"}


class TestNewKernels:
    def test_all_registered(self):
        # the full SPLASH-2 roster (13/13 of the reference's
        # `tests/benchmarks/Makefile:4` families that map to kernels)
        assert set(BENCHMARKS) >= {
            "fft", "radix", "blackscholes", "canneal", "lu", "ocean",
            "barnes", "water-nsquared", "cholesky", "water-spatial",
            "volrend", "raytrace", "radiosity", "fmm"}

    def test_new_kernels_run(self):
        """Every new skeleton replays end to end and advances clocks."""
        import numpy as np

        from graphite_tpu.engine.simulator import Simulator
        sc = make_config(8)
        for name in ("lu", "ocean", "barnes", "water-nsquared", "cholesky",
                     "water-spatial", "volrend", "raytrace", "radiosity",
                     "fmm"):
            batch = BENCHMARKS[name](8)
            res = Simulator(sc, batch).run()
            assert (np.asarray(res.clock_ps) > 0).all(), name
            assert res.total_instructions > 0, name

    def test_npz_roundtrip(self, tmp_path):
        import numpy as np

        from graphite_tpu.trace.io import load_trace_npz, save_trace_npz
        batch = BENCHMARKS["ocean"](4, rows_per_tile=8, cols=8,
                                    iterations=2)
        p = str(tmp_path / "trace.npz")
        save_trace_npz(p, batch)
        loaded = load_trace_npz(p)
        import dataclasses
        for f in dataclasses.fields(batch):
            np.testing.assert_array_equal(getattr(batch, f.name),
                                          getattr(loaded, f.name), f.name)

    def test_npz_minimal_capture(self, tmp_path):
        """An external capture with only op+aux columns replays."""
        import numpy as np

        from graphite_tpu.engine.simulator import Simulator
        from graphite_tpu.trace.io import load_trace_npz
        from graphite_tpu.trace.schema import Op
        op = np.full((2, 4), int(Op.IALU), np.uint8)
        op[:, -1] = int(Op.THREAD_EXIT)
        p = str(tmp_path / "min.npz")
        np.savez(p, op=op)
        batch = load_trace_npz(p)
        res = Simulator(make_config(2), batch).run()
        assert (np.asarray(res.instruction_count) == 3).all()

    def test_npz_rejects_garbage(self, tmp_path):
        import numpy as np
        import pytest

        from graphite_tpu.trace.io import load_trace_npz
        p = str(tmp_path / "bad.npz")
        np.savez(p, op=np.full((2, 2), 199, np.uint8))
        with pytest.raises(ValueError):
            load_trace_npz(p)
