"""Program identity: canonical fingerprints, structural diffs, the
program registry + PROGRAMS.lock (analysis/identity.py, registry.py).

Four layers under test: the canonical form itself (alpha/object-renaming
invariance on retraced programs, sensitivity to one changed literal or
trip count with the divergent equation named), identity of the REAL
audited programs (two independent lowerings of the same config must
fingerprint identically — the acceptance claim bit-identity tests key
off; the intentionally perturbed lock fixture must produce a
phase-attributed diff, not just a failed hash), the registry
(PROGRAMS.lock round-trip, drift/geometry/knob-signature checks,
budget entries resolved through registry keys with stale fingerprints
erroring loudly), and the lower-once plumbing (audit + cost +
fingerprint share ONE tracing per program — `lower_count` is the
probe).
"""

import json

import jax
import jax.numpy as jnp
import pytest

from graphite_tpu.analysis import cost, identity, registry
from graphite_tpu.analysis.audit import (
    DEFAULT_PROGRAM_NAMES, default_programs, gated_msi_simulator,
    spec_from_simulator,
)

TILES = 8


@pytest.fixture(scope="module")
def gated_spec():
    """The gated-MSI audited program, lowered once per module."""
    return default_programs(TILES, names=("gated-msi",))[0]


@pytest.fixture(scope="module")
def gated_spec_retraced():
    """A SECOND, independent lowering of the same config — different
    Simulator instance, different trace objects, same program."""
    return spec_from_simulator("gated-msi", gated_msi_simulator(TILES),
                               4096)


@pytest.fixture(scope="module")
def perturbed_spec():
    """The lock fixture: gated-MSI with one perturbed literal inside
    the requester phase cond (L2 data-access latency 8 -> 19)."""
    return registry.lock_regression_fixture(TILES)


# ---------------------------------------------------------------------------
# the canonical form: invariance + sensitivity on small programs
# ---------------------------------------------------------------------------


class TestFingerprint:
    def test_retrace_invariance(self):
        """Retracing a program through eval_jaxpr mints entirely fresh
        Var objects; the canonical numbering (first-appearance order
        per scope) must not see the difference."""
        def f(x):
            y = jnp.sin(x) * 2.0
            return jnp.where(x > 0, y, x).sum()

        c1 = jax.make_jaxpr(f)(jnp.ones(16))
        c2 = jax.make_jaxpr(
            lambda x: jax.core.eval_jaxpr(c1.jaxpr, c1.consts, x))(
            jnp.ones(16))
        assert c1.jaxpr.eqns[0].outvars[0] \
            is not c2.jaxpr.eqns[0].outvars[0]
        assert identity.fingerprint(c1) == identity.fingerprint(c2)
        assert identity.same_program(c1, c2)

    def test_literal_sensitivity_and_diff_names_eqn(self):
        c1 = jax.make_jaxpr(lambda x: jnp.sin(x) + 1.0)(jnp.ones(16))
        c2 = jax.make_jaxpr(lambda x: jnp.sin(x) + 2.0)(jnp.ones(16))
        assert identity.fingerprint(c1) != identity.fingerprint(c2)
        d = identity.structural_diff(c1, c2)
        assert d is not None and d.kind == "operands"
        assert "add" in d.site and "lit(1.0)" in d.detail \
            and "lit(2.0)" in d.detail

    def test_trip_count_sensitivity(self):
        def prog(n):
            def h(x):
                def step(c, _):
                    return c + 1.0, ()
                out, _ = jax.lax.scan(step, x, None, length=n)
                return out
            return jax.make_jaxpr(h)(jnp.ones(8))

        c10, c11 = prog(10), prog(11)
        assert identity.fingerprint(c10) != identity.fingerprint(c11)
        d = identity.structural_diff(c10, c11)
        assert d is not None and d.kind == "params"
        assert "length=10" in d.detail and "length=11" in d.detail

    def test_carried_aval_change_names_signature(self):
        """A widened while carry (the ballooned-buffer regression
        shape) is reported as a region-signature divergence with the
        aval sizes in the message."""
        def prog(n):
            def h(x):
                return jax.lax.while_loop(
                    lambda c: c.sum() < 10.0, lambda c: c + 1.0,
                    jnp.zeros(n) + x.sum())
            return jax.make_jaxpr(h)(jnp.ones(8))

        d = identity.structural_diff(prog(8), prog(1024))
        assert d is not None
        assert d.kind in ("signature", "operands", "outputs")
        assert "float64[8]" in str(d) and "float64[1024]" in str(d)

    def test_diff_none_on_identical(self):
        c = jax.make_jaxpr(lambda x: x * 2.0)(jnp.ones(4))
        assert identity.structural_diff(c, c) is None
        assert identity.diff_or_none(c, c) is None

    def test_fingerprint_scheme_prefix(self):
        c = jax.make_jaxpr(lambda x: x + 1.0)(jnp.ones(4))
        fp = identity.fingerprint(c)
        assert fp.startswith(identity.FINGERPRINT_SCHEME + ":")
        assert len(fp.split(":", 1)[1]) == 64

    def test_canonical_lines_are_var_name_free(self):
        """The canonical stream numbers variables by first appearance
        (v0, v1, ...) — jaxpr Var spellings never leak in."""
        c = jax.make_jaxpr(lambda x: jnp.sin(x) + x)(jnp.ones(4))
        lines = identity.canonical_lines(c)
        assert any("v0:" in ln for ln in lines)
        assert all("0x" not in ln for ln in lines)


# ---------------------------------------------------------------------------
# eqn-count divergences carry the containing phase (round-20 fix)
# ---------------------------------------------------------------------------


PHASE_NAMES = ("requester", "home_evict", "home_start", "sharer",
               "home_finish", "requester_fill")
PC_TILES = 4


class TestEqnCountPhaseAttribution:
    def test_deep_eqn_count_divergence_names_phase(self):
        """An extra trailing equation deep inside a phase cond arm (a
        nested jit region, mimicking the engine's lowering shape) is
        reported as eqn-count WITH the phase whose gating cond encloses
        it — here the third phase cond in program order."""
        def mk(extra):
            def phase(k, x, m, extra_here):
                def inner(v):
                    s = jnp.sum(v * (k + 1.0))
                    if extra_here:
                        s = s * 0.5
                    return s

                def t_arm(x, m):
                    s = jax.jit(inner)(x)
                    return (m + jnp.uint8(1),
                            jnp.int32(k)
                            + jnp.asarray(s, jnp.int32) * 0)

                def f_arm(x, m):
                    return (m, jnp.int32(0))
                return jax.lax.cond(x[0] > k, t_arm, f_arm, x, m)

            def body(c):
                x, m, i = c
                for k in range(4):
                    m, _p = phase(k, x, m, extra and k == 2)
                return (x * 0.99, m, i + 1)

            def fn(x, m):
                return jax.lax.while_loop(
                    lambda c: c[2] < 3, body, (x, m, jnp.int32(0)))
            return jax.make_jaxpr(fn)(
                jnp.ones((8,)),
                jnp.zeros((PC_TILES, PC_TILES), jnp.uint8))

        d = identity.structural_diff(mk(False), mk(True),
                                     n_tiles=PC_TILES,
                                     phase_names=PHASE_NAMES)
        assert d is not None and d.kind == "eqn-count"
        assert d.phase == "home_start"
        assert "cond/branches[1]" in d.site
        assert "extra equation" in d.detail

    def test_subprogram_count_divergence_names_owning_phase(self):
        """The round-20 fix proper: a phase cond whose BRANCH LIST
        changed length (the sub-jaxpr count divergence) must be
        attributed to that cond's OWN phase and reported as eqn-count
        — before the fix it reported kind 'params' with the ENCLOSING
        phase (None at top level), losing the attribution."""
        def t_arm(x, m):
            return (m + jnp.uint8(1), jnp.int32(1))

        def f_arm(x, m):
            return (m, jnp.int32(0))

        def fn(x, m):
            return jax.lax.cond(x[0] > 0, t_arm, f_arm, x, m)

        c = jax.make_jaxpr(fn)(
            jnp.ones((8,)),
            jnp.zeros((PC_TILES, PC_TILES), jnp.uint8))
        j = c.jaxpr
        k = next(i for i, e in enumerate(j.eqns)
                 if e.primitive.name == "cond")
        eqn = j.eqns[k]
        br = tuple(eqn.params["branches"])
        grown = j.replace(eqns=[
            e if i != k else eqn.replace(
                params={**eqn.params, "branches": br + (br[0],)})
            for i, e in enumerate(j.eqns)])
        c2 = jax.core.ClosedJaxpr(grown, c.consts)
        d = identity.structural_diff(c, c2, n_tiles=PC_TILES,
                                     phase_names=PHASE_NAMES)
        assert d is not None and d.kind == "eqn-count"
        assert d.phase == "requester"
        assert "2 sub-program(s) in A but 3 in B" in d.detail


# ---------------------------------------------------------------------------
# real-program identity: the acceptance claims
# ---------------------------------------------------------------------------


class TestRealProgramIdentity:
    def test_two_independent_lowerings_fingerprint_equal(
            self, gated_spec, gated_spec_retraced):
        """Acceptance: fingerprints are stable across two independent
        traces of the same config."""
        assert identity.fingerprint(gated_spec.closed) \
            == identity.fingerprint(gated_spec_retraced.closed)

    def test_perturbed_program_diff_is_phase_attributed(
            self, gated_spec, perturbed_spec):
        """Acceptance: the lock fixture's diff names the first
        divergent equation AND its protocol phase — "requester ...
        mul lit(8) -> lit(19)", not "hash changed"."""
        assert identity.fingerprint(gated_spec.closed) \
            != identity.fingerprint(perturbed_spec.closed)
        d = identity.diff_or_none(
            gated_spec.closed, perturbed_spec.closed,
            n_tiles=gated_spec.n_tiles,
            phase_names=gated_spec.phase_names)
        assert d is not None
        assert d.phase == "requester"
        assert d.kind == "operands" and "mul" in d.site
        assert "lit(8)" in d.detail and "lit(19)" in d.detail


# ---------------------------------------------------------------------------
# the registry + PROGRAMS.lock
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_record_round_trip(self, gated_spec, tmp_path):
        rec = registry.record_from_spec(gated_spec)
        p = str(tmp_path / "lock.json")
        registry.save_lock([rec], p)
        loaded = registry.load_lock(p)
        assert loaded["gated-msi"] == rec
        assert registry.check_lock([gated_spec], loaded) == []

    def test_save_lock_merges_subset_runs(self, gated_spec, tmp_path):
        p = str(tmp_path / "lock.json")
        other = registry.ProgramRecord("other", "gfp1:" + "a" * 64, 8)
        registry.save_lock([other], p)
        registry.save_lock([registry.record_from_spec(gated_spec)], p)
        loaded = registry.load_lock(p)
        assert set(loaded) == {"other", "gated-msi"}

    def test_drift_geometry_and_knob_checks(self, gated_spec):
        rec = registry.record_from_spec(gated_spec)
        import dataclasses

        drifted = {"gated-msi": dataclasses.replace(
            rec, fingerprint="gfp1:" + "0" * 64)}
        fs = registry.check_lock([gated_spec], drifted)
        assert len(fs) == 1 and "drifted" in fs[0].message

        wrong_tiles = {"gated-msi": dataclasses.replace(rec, tiles=16)}
        fs = registry.check_lock([gated_spec], wrong_tiles)
        assert len(fs) == 1 and "tiles" in fs[0].message

        wrong_knobs = {"gated-msi": dataclasses.replace(
            rec, knobs=("dram_latency_ns",))}
        fs = registry.check_lock([gated_spec], wrong_knobs)
        assert any("knob signature" in f.message for f in fs)

    def test_unregistered_and_stale_entries_error(self, gated_spec):
        fs = registry.check_lock([gated_spec], {})
        assert len(fs) == 1 and "not registered" in fs[0].message
        rec = registry.record_from_spec(gated_spec)
        stale = registry.ProgramRecord("ghost", "gfp1:" + "b" * 64, 8)
        fs = registry.check_lock(
            [gated_spec], {"gated-msi": rec, "ghost": stale},
            expect_complete=True)
        assert len(fs) == 1 and "ghost" in fs[0].message
        # without expect_complete a subset audit ignores the extras
        assert registry.check_lock(
            [gated_spec], {"gated-msi": rec, "ghost": stale}) == []

    def test_checked_in_lock_covers_all_default_programs(self):
        lock = registry.load_lock()
        assert set(DEFAULT_PROGRAM_NAMES) <= set(lock)
        for name in DEFAULT_PROGRAM_NAMES:
            assert lock[name].fingerprint.startswith("gfp1:")
            assert lock[name].tiles == TILES
        # the campaigns register their sweep-knob signature too
        assert lock["sweep-b4"].knobs is not None
        assert "dram_latency_ns" in lock["sweep-b4"].knobs


# ---------------------------------------------------------------------------
# budgets resolve THROUGH the registry
# ---------------------------------------------------------------------------


class TestLockBudgetConsistency:
    def test_checked_in_budgets_match_checked_in_lock(self):
        """CI-consistency acceptance: every BUDGETS.json entry records
        the fingerprint of the program it was measured at, and it
        matches the registered identity under the same key."""
        lock = registry.load_lock()
        budgets = cost.load_budgets()
        for name in DEFAULT_PROGRAM_NAMES:
            rec = lock[name]
            entry = budgets[rec.budget_key]
            assert entry.get("fingerprint") == rec.fingerprint, name

    def test_stale_fingerprint_budget_entry_errors(self, gated_spec):
        rep = cost.cost_report(gated_spec)
        rec = registry.record_from_spec(gated_spec)
        budgets = {"gated-msi": {
            "tiles": TILES, "measured": rep.metrics(),
            "ceiling": {k: v * 2 for k, v in rep.metrics().items()},
            "fingerprint": "gfp1:" + "0" * 64,
        }}
        fs = cost.check_budgets([rep], budgets,
                                registry={"gated-msi": rec})
        assert len(fs) == 1 and "STALE" in fs[0].message
        # matching fingerprint: same ceilings pass
        budgets["gated-msi"]["fingerprint"] = rec.fingerprint
        assert cost.check_budgets([rep], budgets,
                                  registry={"gated-msi": rec}) == []
        # a registered program whose entry has NO fingerprint cannot
        # be staleness-checked — loud error, not silent inheritance
        del budgets["gated-msi"]["fingerprint"]
        fs = cost.check_budgets([rep], budgets,
                                registry={"gated-msi": rec})
        assert len(fs) == 1 and "no fingerprint" in fs[0].message
        # without a registry (pre-round-11 path) it stays lenient
        assert cost.check_budgets([rep], budgets) == []

    def test_budget_key_resolves_renamed_program(self, gated_spec):
        """A registry rename keeps old ceilings reachable through
        budget_key — and the entry is still fingerprint-checked."""
        import dataclasses

        rep = cost.cost_report(gated_spec)
        rep = dataclasses.replace(rep, program="renamed-msi")
        rec = dataclasses.replace(
            registry.record_from_spec(gated_spec), name="renamed-msi",
            budget_key="gated-msi")
        budgets = {"gated-msi": {
            "tiles": TILES, "measured": rep.metrics(),
            "ceiling": {k: v * 2 for k, v in rep.metrics().items()},
            "fingerprint": rec.fingerprint,
        }}
        assert cost.check_budgets([rep], budgets,
                                  registry={"renamed-msi": rec}) == []

    def test_refresh_paths_respect_budget_key(self, gated_spec,
                                              tmp_path):
        """The rename workflow end-to-end: a hand-set budget_key
        survives a --lock-update refresh (record_from_spec only knows
        the name), and save_budgets writes the entry under the SAME
        key check_budget resolves — a refresh after a rename replaces
        the gated entry instead of orphaning a new-name copy."""
        import dataclasses

        lock_p = str(tmp_path / "lock.json")
        rec = dataclasses.replace(registry.record_from_spec(gated_spec),
                                  budget_key="legacy-key")
        registry.save_lock([rec], lock_p)
        registry.save_lock([registry.record_from_spec(gated_spec)],
                           lock_p)
        lock = registry.load_lock(lock_p)
        assert lock["gated-msi"].budget_key == "legacy-key"
        bud_p = str(tmp_path / "budgets.json")
        rep = cost.cost_report(gated_spec)
        cost.save_budgets(
            [rep], bud_p,
            fingerprints={"gated-msi": lock["gated-msi"].fingerprint},
            registry=lock)
        budgets = cost.load_budgets(bud_p)
        assert set(budgets) == {"legacy-key"}
        assert cost.check_budgets([rep], budgets, registry=lock) == []


# ---------------------------------------------------------------------------
# lower-once: one tracing serves audit + cost + fingerprint
# ---------------------------------------------------------------------------


class TestLowerOnce:
    def test_simulator_traces_once_across_consumers(self):
        """The round-11 bugfix: spec building, the cost model, the
        fingerprint and the registry record all consume ONE tracing —
        `lower_count` is the probe."""
        sim = gated_msi_simulator(TILES)
        assert sim.lower_count == 0
        spec = spec_from_simulator("gated-msi", sim, 4096)
        assert sim.lower_count == 1
        closed, paths = sim.lower(4096)          # cache hit
        assert closed is spec.closed
        cost.cost_report(spec)
        identity.fingerprint(spec.closed)
        registry.record_from_spec(spec)
        assert sim.lower_count == 1
        # a different static bound is a different program: new trace
        sim.lower(512)
        assert sim.lower_count == 2

    def test_attach_telemetry_invalidates_lowering_cache(self):
        from graphite_tpu.obs import TelemetrySpec

        sim = gated_msi_simulator(TILES)
        c1, _ = sim.lower(512)
        sim.attach_telemetry(TelemetrySpec(
            sample_interval_ps=1_000_000, n_samples=16))
        c2, _ = sim.lower(512)
        assert sim.lower_count == 2
        assert not identity.same_program(c1, c2)

    def test_sweep_runner_traces_once(self):
        from graphite_tpu.config import ConfigFile, SimConfig
        from graphite_tpu.sweep import SweepRunner
        from graphite_tpu.tools._template import config_text
        from graphite_tpu.trace import synthetic

        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, shared_mem=True, clock_scheme="lax_barrier")))
        traces = [synthetic.memory_stress_trace(
            TILES, n_accesses=8, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.5, seed=s)
            for s in (1, 2)]
        runner = SweepRunner(sc, traces, shard_batch=False)
        c1, _ = runner.lower(4096)
        c2, _ = runner.lower(4096)
        assert c1 is c2 and runner.lower_count == 1

    def test_attach_telemetry_invalidates_sweep_runner_caches(self):
        """attach_telemetry on the WRAPPED sim changes the program the
        campaign executes; a runner built earlier must drop its cached
        lowering (and jitted runner / broadcast states) or lower()
        certifies a different artifact than run() executes."""
        from graphite_tpu.config import ConfigFile, SimConfig
        from graphite_tpu.obs import TelemetrySpec
        from graphite_tpu.sweep import SweepRunner
        from graphite_tpu.tools._template import config_text
        from graphite_tpu.trace import synthetic

        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, shared_mem=True, clock_scheme="lax_barrier")))
        traces = [synthetic.memory_stress_trace(
            TILES, n_accesses=8, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.5, seed=s)
            for s in (1, 2)]
        runner = SweepRunner(sc, traces, shard_batch=False)
        c1, _ = runner.lower(4096)
        runner.sim.attach_telemetry(TelemetrySpec(
            sample_interval_ps=1_000_000, n_samples=16))
        c2, _ = runner.lower(4096)
        assert runner.lower_count == 2
        assert not identity.same_program(c1, c2)


# ---------------------------------------------------------------------------
# CLI: --lock / --lock-update / --lock-fixture
# ---------------------------------------------------------------------------


class TestLockCLI:
    def test_lock_update_then_gate_round_trip(self, tmp_path):
        """--lock-update writes a lock --lock then passes against;
        tampering the registered fingerprint makes the SAME run exit
        nonzero (the gate is live, not decorative)."""
        from graphite_tpu.tools.audit import main

        p = str(tmp_path / "lock.json")
        assert main(["--programs", "gated-msi", "--lock-update",
                     "--lock-file", p]) == 0
        assert main(["--programs", "gated-msi", "--lock",
                     "--lock-file", p]) == 0
        data = json.load(open(p))
        data["gated-msi"]["fingerprint"] = "gfp1:" + "f" * 64
        json.dump(data, open(p, "w"))
        assert main(["--programs", "gated-msi", "--lock",
                     "--lock-file", p]) == 1

    def test_lock_update_refreshes_registry_for_combined_run(
            self, tmp_path):
        """--lock-update --budget in ONE invocation must gate budgets
        against the registry JUST written: ceilings recorded at a
        different fingerprint trip immediately, not only on the next
        plain --budget run."""
        from graphite_tpu.tools.audit import main

        lock_p = str(tmp_path / "lock.json")
        bud_p = str(tmp_path / "budgets.json")
        assert main(["--programs", "gated-msi",
                     "--lock-update", "--lock-file", lock_p,
                     "--budget-update", "--budgets-file", bud_p]) == 0
        data = json.load(open(bud_p))
        data["gated-msi"]["fingerprint"] = "gfp1:" + "0" * 64
        json.dump(data, open(bud_p, "w"))
        assert main(["--programs", "gated-msi",
                     "--lock-update", "--lock-file", lock_p,
                     "--budget", "--budgets-file", bud_p]) == 1

    def test_fixture_excludes_the_other_gate(self):
        """Each fixture self-tests ONE gate: arming the other alongside
        would let its finding carry the nonzero exit even when the gate
        under test is broken (a vacuously green CI self-test)."""
        from graphite_tpu.tools.audit import main

        for argv in (["--regression-fixture", "--lock"],
                     ["--lock-fixture", "--budget"]):
            with pytest.raises(SystemExit) as e:
                main(argv)
            assert e.value.code == 2

    def test_lock_fixture_cli_exits_nonzero(self, capsys):
        """CLI-level acceptance: `--lock-fixture` must exit nonzero
        against the real checked-in PROGRAMS.lock, and the emitted
        diff row must name the divergent equation and its phase."""
        from graphite_tpu.tools.audit import main

        assert main(["--lock-fixture"]) == 1
        rows = [json.loads(ln) for ln in
                capsys.readouterr().out.splitlines() if ln]
        diff = next(r for r in rows if r.get("lock_diff"))
        assert diff["phase"] == "requester"
        assert "mul" in diff["site"]
        lock_rows = [r for r in rows if r.get("rule") == "lock"]
        assert lock_rows and "requester" in lock_rows[0]["message"]
