"""Device-resident telemetry timelines (graphite_tpu/obs/, round 9).

The contract pins:
 - `telemetry=None` (the default) lowers the HISTORICAL program — jaxpr
   string-identical to calling `run_simulation` with no telemetry at
   all, and free of telemetry invars (the knobs=None contract, also
   enforced by the `telemetry-off` audit lint);
 - recording is pure observability: a telemetry-enabled run's
   SimResults are bit-equal to its telemetry=None twin;
 - the recorded rows match a hand-stepped chunked oracle (run_chunk(1)
   + host-side differencing) sample for sample;
 - the ring wraps at S exhaustion keeping the LAST S samples;
 - vmapped campaigns demux [B, S, n_series] per-sim timelines equal to
   sequential telemetry runs (shard_map campaigns gather per-device
   buffers through the same demux);
 - the StatisticsManager device backend writes byte-identical `.trace`
   files to the chunked backend on the same run.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphite_tpu.analysis import rules
from graphite_tpu.analysis.audit import spec_from_simulator
from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.obs import (
    CORE_SERIES, LEVEL_SERIES, Timeline, TelemetrySpec, available_series,
)
from graphite_tpu.tools._template import config_text
from graphite_tpu.trace import synthetic

TILES = 8
QUANTUM_PS = 1_000_000   # config_text default: 1000 ns lax_barrier


def _config(extra: str = ""):
    return SimConfig(ConfigFile.from_string(config_text(
        TILES, shared_mem=True, clock_scheme="lax_barrier") + extra))


def _trace(seed=7, n=24):
    return synthetic.memory_stress_trace(
        TILES, n_accesses=n, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=seed)


def _spec(interval=QUANTUM_PS, s=64, series=None):
    return TelemetrySpec(sample_interval_ps=interval, n_samples=s,
                         series=series)


class TestSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="positive"):
            TelemetrySpec(sample_interval_ps=0)
        with pytest.raises(ValueError, match="positive"):
            TelemetrySpec(sample_interval_ps=1, n_samples=0)

    def test_resolve_selects_and_orders(self):
        sim = Simulator(_config(), _trace())
        spec = _spec(series=("instructions", "l2_misses")).resolve(
            sim.params)
        # time_ps is forced first (the demux key)
        assert spec.series == ("time_ps", "instructions", "l2_misses")
        assert spec.n_series == 3
        assert spec.buffer_sig() == ((64, 3), "int64")

    def test_resolve_rejects_unknown_series(self):
        sim = Simulator(_config(), _trace())
        with pytest.raises(ValueError, match="unavailable telemetry"):
            _spec(series=("no_such_series",)).resolve(sim.params)

    def test_dense_series_set_and_skip_names_from_engine(self):
        from graphite_tpu.engine.simulator import mem_phase_names

        sim = Simulator(_config(), _trace())
        avail = available_series(sim.params)
        assert set(CORE_SERIES) <= set(avail)
        # skip_* names come from the engine's own phase-name table —
        # one source of truth, no parallel list
        assert tuple("skip_" + n for n in mem_phase_names(sim.params)) \
            == tuple(s for s in avail if s.startswith("skip_"))

    def test_memoryless_program_offers_core_series_only(self):
        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, clock_scheme="lax_barrier")))
        batch = synthetic.message_ring_batch(TILES, n_rounds=4,
                                             compute_per_round=8)
        sim = Simulator(sc, batch)
        assert available_series(sim.params) == CORE_SERIES
        with pytest.raises(ValueError, match="unavailable"):
            _spec(series=("l2_misses",)).resolve(sim.params)

    def test_attach_rejects_stream_and_requires_spec(self):
        sim = Simulator(_config(), _trace(), stream=True)
        with pytest.raises(ValueError, match="single-device resident"):
            sim.attach_telemetry(_spec())
        sim2 = Simulator(_config(), _trace())
        with pytest.raises(TypeError, match="TelemetrySpec"):
            sim2.attach_telemetry({"sample_interval_ps": 1})


class TestProgramIdentity:
    def test_telemetry_none_is_the_baseline_program(self):
        """telemetry=None must lower jaxpr-identically to the legacy
        entry point that never heard of telemetry (knobs=None contract),
        with zero telemetry invars."""
        from graphite_tpu.engine.step import run_simulation

        sim = Simulator(_config(), _trace())
        closed_none, paths = sim.lower(max_quanta=512)
        params, qps = sim.params, sim.quantum_ps

        def legacy(st, tr):
            return run_simulation(params, tr, st, qps, 512)

        closed_legacy = jax.make_jaxpr(legacy)(sim.state, sim.device_trace)
        # canonical structural equality (analysis/identity.py) — the
        # ONE definition of "same program" the CI lock gate also uses,
        # replacing the old ad-hoc str(jaxpr) comparison
        from graphite_tpu.analysis.identity import same_program

        assert same_program(closed_none, closed_legacy)
        assert not any("telemetry" in p for p in paths)
        assert not rules.telemetry_off(closed_none, paths)

    def test_telemetry_off_lint_fires_on_recording_program(self):
        """Known-bad fixture: the lint must catch a program that DOES
        carry the recording machinery."""
        simt = Simulator(_config(), _trace(), telemetry=_spec())
        closed, paths = simt.lower(max_quanta=512)
        fs = rules.telemetry_off(
            closed, paths, ring_sigs=(simt.telemetry_spec.buffer_sig(),))
        assert fs
        assert all(f.rule == "telemetry-off" for f in fs)
        assert any("invar" in f.message for f in fs)

    def test_telemetry_off_lint_catches_internal_ring(self):
        """A ring materialized INSIDE the program (no invar) is caught
        by the aval scan."""
        S, n = 16, 4

        def bad(x):
            buf = jnp.zeros((S, n), jnp.int64)
            return buf.at[0, 0].set(x)

        closed = jax.make_jaxpr(bad)(jnp.asarray(1, jnp.int64))
        fs = rules.telemetry_off(closed, ["x"], ring_sigs=(((S, n),
                                                            "int64"),))
        assert fs and fs[0].data["shape"] == [S, n]

    def test_ring_buffer_forbidden_in_conds(self):
        """Telemetry-on programs add the ring aval to the cond-payload
        forbidden set; the real program passes, a toy cond carrying the
        ring fires."""
        simt = Simulator(_config(), _trace(), phase_gate=True,
                         mem_gate_bytes=0, telemetry=_spec())
        spec = spec_from_simulator("tel", simt, max_quanta=512)
        assert simt.telemetry_spec.buffer_sig() in \
            spec.forbidden_cond_avals
        assert spec.expect_telemetry
        assert not rules.cond_payload(
            spec.closed, forbidden=spec.forbidden_cond_avals)

        sig = simt.telemetry_spec.buffer_sig()

        def bad(p, buf):
            return jax.lax.cond(p, lambda b: b + 1, lambda b: b, buf)

        closed = jax.make_jaxpr(bad)(
            True, jnp.zeros(sig[0], jnp.int64))
        assert rules.cond_payload(closed, forbidden=(sig,))

    def test_audit_default_programs_include_telemetry(self):
        from graphite_tpu.analysis.audit import (
            DEFAULT_PROGRAM_NAMES, audit, default_programs,
        )

        assert "gated-msi-tel" in DEFAULT_PROGRAM_NAMES
        specs = default_programs(
            TILES, max_quanta=512, names=("gated-msi", "gated-msi-tel"))
        # telemetry-OFF specs carry the canonical dense ring sig so the
        # telemetry-off AVAL scan is live, not just the invar check
        off = next(s for s in specs if s.name == "gated-msi")
        assert not off.expect_telemetry
        assert off.telemetry_sig is not None
        report = audit(specs)
        assert report.ok, [str(f) for f in report.errors]
        assert {r.rule for r in report.results
                if r.program == "gated-msi"} >= {"telemetry-off"}
        assert "telemetry-off" not in {
            r.rule for r in report.results if r.program == "gated-msi-tel"}


class TestRecording:
    def test_results_bit_equal_and_timeline_attached(self):
        batch = _trace()
        r_off = Simulator(_config(), batch).run()
        sim = Simulator(_config(), batch, telemetry=_spec())
        r_on = sim.run()
        np.testing.assert_array_equal(r_on.clock_ps, r_off.clock_ps)
        np.testing.assert_array_equal(r_on.instruction_count,
                                      r_off.instruction_count)
        for k in r_off.mem_counters:
            np.testing.assert_array_equal(r_on.mem_counters[k],
                                          r_off.mem_counters[k], err_msg=k)
        assert r_on.n_quanta == r_off.n_quanta
        assert r_off.telemetry is None
        tl = r_on.telemetry
        assert isinstance(tl, Timeline)
        assert len(tl) > 0 and not tl.wrapped
        assert tl.data.shape[1] == sim.telemetry_spec.n_series
        # Simulator.telemetry reads the same state
        np.testing.assert_array_equal(sim.telemetry.data, tl.data)
        # the final row is the completion sample: its time is the run's
        # completion time, and the delta series sum to the run totals
        assert int(tl.col("time_ps")[-1]) == r_on.completion_time_ps
        assert int(tl.col("instructions").sum()) == r_on.total_instructions
        assert int(tl.col("quanta").sum()) == r_on.n_quanta

    def test_rows_match_chunked_oracle(self):
        """Sample-boundary correctness: step the SAME sim quantum by
        quantum from the host (run_chunk(1)), difference the fetched
        counters by hand, and require the device rows to match
        exactly."""
        batch = _trace()
        series = ("quanta", "instructions", "packets_sent",
                  "clock_min_ps", "clock_max_ps", "clock_mean_ps",
                  "l2_misses", "skip_requester")
        interval = 1_500_000   # 1.5 quanta — forces skipped boundaries
        simt = Simulator(_config(), batch,
                         telemetry=_spec(interval=interval, series=series))
        tl = simt.run().telemetry
        order = simt.telemetry_spec.series

        ref = Simulator(_config(), batch)
        prev = np.zeros(len(order), np.int64)
        next_ps = interval
        quanta = 0
        rows = []
        for _ in range(10_000):
            done, nq = ref.run_chunk(1)
            quanta += nq
            st = ref.state
            clocks, done_mask, instr, sent, mc, skips = jax.device_get(
                (st.core.clock_ps, st.done, st.core.instruction_count,
                 st.net.packets_sent, st.mem.counters.l2_misses,
                 st.mem.phase_skips))
            pending = clocks[~done_mask]
            sim_time = int(pending.min() if pending.size else clocks.max())
            cur = {
                "time_ps": sim_time,
                "quanta": quanta,
                "instructions": int(instr.sum()),
                "packets_sent": int(sent.sum()),
                "clock_min_ps": int(clocks.min()),
                "clock_max_ps": int(clocks.max()),
                "clock_mean_ps": int(clocks.sum()) // TILES,
                "l2_misses": int(mc.sum()),
                "skip_requester": int(skips[0]),
            }
            cur = np.array([cur[s] for s in order], np.int64)
            if sim_time >= next_ps or done:
                delta = np.array(
                    [c if s in LEVEL_SERIES else c - p
                     for s, c, p in zip(order, cur, prev)], np.int64)
                rows.append(delta)
                prev = cur
                next_ps = (sim_time // interval + 1) * interval
            if done:
                break
        assert done
        np.testing.assert_array_equal(tl.data, np.array(rows))

    def test_ring_wraparound_keeps_last_samples(self):
        batch = _trace()
        big = Simulator(_config(), batch, telemetry=_spec(s=64))
        tl_big = big.run().telemetry
        assert tl_big.n_total > 2   # the run takes > 2 samples
        small = Simulator(_config(), batch, telemetry=_spec(s=2))
        tl = small.run().telemetry
        assert tl.wrapped and tl.n_total == tl_big.n_total
        assert len(tl) == 2
        np.testing.assert_array_equal(tl.data, tl_big.data[-2:])

    def test_barrier_host_dispatch_records_identically(self):
        """The batched host-barrier dispatch path samples the same
        timeline as the single-region device loop (the sampling cursor
        rides the carry across dispatches)."""
        batch = _trace()
        tl_dev = Simulator(_config(), batch,
                           telemetry=_spec()).run().telemetry
        sim_hb = Simulator(_config(), batch, barrier_host=True,
                           barrier_batch=2, telemetry=_spec())
        tl_hb = sim_hb.run().telemetry
        assert tl_hb.n_total == tl_dev.n_total
        np.testing.assert_array_equal(tl_hb.data, tl_dev.data)

    def test_save_load_roundtrip_and_report(self, tmp_path, capsys):
        import json

        from graphite_tpu.tools.report import main as report_main

        tl = Simulator(_config(), _trace(),
                       telemetry=_spec()).run().telemetry
        path = str(tmp_path / "tl.npz")
        tl.save(path)
        back = Timeline.load(path)
        assert back.series == tl.series
        assert back.n_total == tl.n_total
        np.testing.assert_array_equal(back.data, tl.data)

        assert report_main([path]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
        assert len(lines) == len(tl) + 1   # rows + summary
        assert lines[-1]["samples"] == len(tl)
        assert report_main([path, "--format", "text", "--summary"]) == 0
        assert "mean_clock_spread_ps" in capsys.readouterr().out


class TestEnergySeries:
    """The round-14 `energy_pj` series: cumulative event energy priced
    from the carry's own counters (opt-in via EnergyPrices — the dense
    default selection, and every locked program, is unchanged)."""

    PRICES = None   # built lazily (EnergyPrices import at class scope)

    def _prices(self):
        from graphite_tpu.obs import EnergyPrices

        return EnergyPrices(
            instruction_pj=3, l1i_access_pj=1, l1d_access_pj=2,
            l2_access_pj=9, l2_miss_pj=120, invalidation_pj=15,
            eviction_pj=20, dram_access_pj=500, packet_pj=7)

    def _energy_of(self, instr, sent, mc):
        """The hand-stepped power-model sum: every counter priced by
        the same pJ table the device row folds in."""
        return (3 * int(instr.sum()) + 7 * int(sent.sum())
                + 1 * int(mc.l1i_hits.sum() + mc.l1i_misses.sum())
                + 2 * int(mc.l1d_read_hits.sum()
                          + mc.l1d_read_misses.sum()
                          + mc.l1d_write_hits.sum()
                          + mc.l1d_write_misses.sum())
                + 9 * int(mc.l2_hits.sum() + mc.l2_misses.sum())
                + 120 * int(mc.l2_misses.sum())
                + 15 * int(mc.invalidations.sum())
                + 20 * int(mc.evictions.sum())
                + 500 * int(mc.dram_reads.sum()
                            + mc.dram_writes.sum()))

    def test_energy_rows_match_hand_stepped_power_sum(self):
        """Oracle: step the same sim quantum by quantum from the host,
        price the fetched counters by hand, difference, and require the
        device energy column to match exactly — and the telemetry run's
        SimResults to stay bit-equal to the plain run's."""
        batch = _trace()
        spec = _spec(series=("instructions", "energy_pj"))
        spec = TelemetrySpec(
            sample_interval_ps=spec.sample_interval_ps,
            n_samples=spec.n_samples, series=spec.series,
            energy_prices=self._prices())
        simt = Simulator(_config(), batch, telemetry=spec)
        res = simt.run()
        tl = res.telemetry
        assert tl.series == ("time_ps", "instructions", "energy_pj")

        ref = Simulator(_config(), batch)
        prev_e = 0
        rows = []
        interval = QUANTUM_PS
        next_ps = interval
        for _ in range(10_000):
            done, _ = ref.run_chunk(1)
            st = ref.state
            clocks, done_mask, instr, sent = jax.device_get(
                (st.core.clock_ps, st.done, st.core.instruction_count,
                 st.net.packets_sent))
            mc = jax.device_get(st.mem.counters)
            pending = clocks[~done_mask]
            sim_time = int(pending.min() if pending.size
                           else clocks.max())
            cur_e = self._energy_of(instr, sent, mc)
            if sim_time >= next_ps or done:
                rows.append(cur_e - prev_e)
                prev_e = cur_e
                next_ps = (sim_time // interval + 1) * interval
            if done:
                break
        assert done
        np.testing.assert_array_equal(tl.col("energy_pj"),
                                      np.array(rows, np.int64))
        # pure observability: the priced run's results are bit-equal
        r_off = Simulator(_config(), batch).run()
        np.testing.assert_array_equal(res.clock_ps, r_off.clock_ps)
        for k in r_off.mem_counters:
            np.testing.assert_array_equal(
                res.mem_counters[k], r_off.mem_counters[k], err_msg=k)

    def test_telemetry_off_lint_covers_energy_ring(self):
        """Telemetry-OFF specs carry the dense-plus-energy ring sig
        (one series wider), and the aval scan fires on a program that
        materializes it."""
        from graphite_tpu.analysis.audit import spec_from_simulator

        sim = Simulator(_config(), _trace())
        spec = spec_from_simulator("off", sim, max_quanta=512)
        assert spec.telemetry_extra_sigs
        (S, n), dt = spec.telemetry_sig
        assert spec.telemetry_extra_sigs[0] == ((S, n + 1), dt)

        def bad(x):
            buf = jnp.zeros((S, n + 1), jnp.int64)
            return buf.at[0, 0].set(x)

        closed = jax.make_jaxpr(bad)(jnp.asarray(1, jnp.int64))
        fs = rules.telemetry_off(closed, ["x"],
                                 ring_sigs=spec.telemetry_extra_sigs)
        assert fs and fs[0].data["shape"] == [S, n + 1]
        # ... and the real telemetry-off program still passes with the
        # widened sig set (no false positive from the extra aval)
        assert not rules.telemetry_off(
            spec.closed, spec.invar_paths,
            ring_sigs=(spec.telemetry_sig,) + spec.telemetry_extra_sigs)

    def test_energy_program_passes_audit(self):
        """An energy-recording program clears every lint: the widened
        ring rides no cond, no host sync, gates intact."""
        from graphite_tpu.analysis.audit import audit, \
            spec_from_simulator

        spec_tel = TelemetrySpec(sample_interval_ps=QUANTUM_PS,
                                 n_samples=32,
                                 energy_prices=self._prices())
        simt = Simulator(_config(), _trace(), phase_gate=True,
                         mem_gate_bytes=0, telemetry=spec_tel)
        spec = spec_from_simulator("tel-energy", simt, max_quanta=512)
        assert spec.expect_telemetry
        report = audit([spec])
        assert report.ok, [str(f) for f in report.errors]


class TestSweepDemux:
    def test_vmap_campaign_demuxes_per_sim_timelines(self):
        from graphite_tpu.sweep import SweepRunner

        seeds = (1, 2, 3)
        traces = [_trace(seed=s) for s in seeds]
        sweep = SweepRunner(_config(), traces, shard_batch=False,
                            telemetry=_spec())
        out = sweep.run()
        assert out.timelines is not None and len(out.timelines) == 3
        n_series = sweep.sim.telemetry_spec.n_series
        for b in range(3):
            tl = out.timelines[b]
            assert tl.data.shape[1] == n_series
            assert out.results[b].telemetry is tl
            # bit-identical to this sim's own sequential telemetry run
            # (the vmapped program runs ungated — match it)
            solo = Simulator(_config(), traces[b],
                             mailbox_depth=sweep.mailbox_depth,
                             phase_gate=False, mem_gate_bytes=0,
                             telemetry=_spec()).run().telemetry
            assert tl.n_total == solo.n_total
            np.testing.assert_array_equal(tl.data, solo.data,
                                          err_msg=f"sim {b}")

    def test_shard_map_campaign_gathers_device_buffers(self):
        from graphite_tpu.sweep import SweepRunner

        if len(jax.devices()) < 2:
            pytest.skip("needs the multi-device CPU platform")
        B = len(jax.devices())
        traces = [_trace(seed=s) for s in range(B)]
        sweep = SweepRunner(_config(), traces, shard_batch=True,
                            telemetry=_spec())
        out = sweep.run()
        assert len(out.timelines) == B
        for b in (0, B - 1):
            # one sim per device runs the plain gated program
            solo = Simulator(_config(), traces[b],
                             mailbox_depth=sweep.mailbox_depth,
                             telemetry=_spec()).run().telemetry
            assert out.timelines[b].n_total == solo.n_total
            np.testing.assert_array_equal(out.timelines[b].data,
                                          solo.data, err_msg=f"sim {b}")


class TestStatisticsBackends:
    STATS = """
[statistics_trace]
enabled = true
statistics = network_utilization
sampling_interval = 500
"""

    def _traces_equal(self, d1, d2):
        import os

        f1 = sorted(os.listdir(d1))
        f2 = sorted(os.listdir(d2))
        assert f1 == f2 and f1, (f1, f2)
        for name in f1:
            a = open(os.path.join(d1, name)).read()
            b = open(os.path.join(d2, name)).read()
            assert a == b, f"{name} differs:\n--- chunked\n{a}\n--- device\n{b}"

    def test_device_backend_matches_chunked_files(self, tmp_path):
        from graphite_tpu.system.statistics import StatisticsManager

        batch = _trace()
        m_ch = StatisticsManager(
            Simulator(_config(self.STATS), batch),
            output_dir=str(tmp_path / "chunked"), backend="chunked")
        r_ch = m_ch.run()
        m_dev = StatisticsManager(
            Simulator(_config(self.STATS), batch),
            output_dir=str(tmp_path / "device"), backend="device")
        r_dev = m_dev.run()
        assert r_dev.n_quanta == r_ch.n_quanta
        np.testing.assert_array_equal(r_dev.clock_ps, r_ch.clock_ps)
        self._traces_equal(str(tmp_path / "chunked"),
                           str(tmp_path / "device"))

    def test_device_backend_matches_chunked_user_net(self, tmp_path):
        """A SEND-carrying memoryless trace exercises the USER-network
        injection rows with nonzero rates."""
        from graphite_tpu.system.statistics import StatisticsManager

        sc = SimConfig(ConfigFile.from_string(config_text(
            TILES, clock_scheme="lax_barrier") + self.STATS))
        batch = synthetic.message_ring_batch(TILES, n_rounds=6,
                                             compute_per_round=16)
        m_ch = StatisticsManager(Simulator(sc, batch),
                                 output_dir=str(tmp_path / "chunked"),
                                 backend="chunked")
        m_ch.run()
        m_dev = StatisticsManager(Simulator(sc, batch),
                                  output_dir=str(tmp_path / "device"),
                                  backend="device")
        m_dev.run()
        rows = open(tmp_path / "device" /
                    "network_utilization_user.trace").read()
        assert any(float(ln.split()[1]) > 0
                   for ln in rows.strip().splitlines())
        self._traces_equal(str(tmp_path / "chunked"),
                           str(tmp_path / "device"))

    def test_auto_falls_back_for_state_snapshot_stats(self):
        from graphite_tpu.system.statistics import StatisticsManager

        stats = self.STATS.replace(
            "statistics = network_utilization",
            "statistics = cache_line_replication, network_utilization")
        m = StatisticsManager(Simulator(_config(stats), _trace()))
        assert m.backend == "auto" and not m.device_supported()
        with pytest.raises(ValueError, match="counter-derived"):
            StatisticsManager(Simulator(_config(stats), _trace()),
                              backend="device")

    def test_auto_falls_back_for_meshed_sims(self):
        """A meshed sim must keep the chunked loop under backend='auto'
        even when every enabled statistic is counter-derived — the
        telemetry ring is not threaded through the multi-chip
        exchange, and attach_telemetry would raise."""
        from graphite_tpu.parallel.mesh import make_tile_mesh
        from graphite_tpu.system.statistics import StatisticsManager

        if len(jax.devices()) < 2:
            pytest.skip("needs the multi-device CPU platform")
        sim = Simulator(_config(self.STATS), _trace(),
                        mesh=make_tile_mesh(len(jax.devices())))
        m = StatisticsManager(sim)
        assert m.backend == "auto" and not m.device_supported()
