"""Time/Latency semantics vs `common/misc/time_types.h:81-119`."""

import math

import jax.numpy as jnp
import pytest

from graphite_tpu.time_types import (
    Latency,
    Time,
    cycles_to_ps,
    ghz_to_mhz,
    ps_to_cycles,
    ps_to_ns,
)


def ref_latency_to_ps(cycles: int, freq_ghz: float) -> int:
    """The reference's double-based ceil (`time_types.h:81-86`)."""
    return int(math.ceil((1000.0 * cycles) / freq_ghz))


def ref_time_to_cycles(ps: int, freq_ghz: float) -> int:
    """`time_types.h:104-109`."""
    return int(math.ceil((float(ps) * freq_ghz) / 1.0e3))


@pytest.mark.parametrize("freq_ghz", [0.5, 1.0, 1.5, 2.0, 2.5, 3.3])
@pytest.mark.parametrize("cycles", [0, 1, 2, 3, 7, 100, 999, 12345])
def test_cycles_to_ps_matches_reference(freq_ghz, cycles):
    got = cycles_to_ps(cycles, ghz_to_mhz(freq_ghz))
    want = ref_latency_to_ps(cycles, freq_ghz)
    assert got == want


@pytest.mark.parametrize("freq_ghz", [0.5, 1.0, 2.0, 2.5])
@pytest.mark.parametrize("ps", [0, 1, 499, 500, 501, 1000, 123456, 10**9])
def test_ps_to_cycles_matches_reference(freq_ghz, ps):
    got = ps_to_cycles(ps, ghz_to_mhz(freq_ghz))
    want = ref_time_to_cycles(ps, freq_ghz)
    assert got == want


def test_ps_to_ns_is_ceil():
    # `time_types.h:111-114`
    assert ps_to_ns(0) == 0
    assert ps_to_ns(1) == 1
    assert ps_to_ns(1000) == 1
    assert ps_to_ns(1001) == 2


def test_vectorized_matches_scalar():
    cycles = jnp.array([0, 1, 3, 999, 12345], dtype=jnp.int64)
    out = cycles_to_ps(cycles, ghz_to_mhz(2.0))
    assert out.dtype == jnp.int64
    for c, o in zip([0, 1, 3, 999, 12345], out.tolist()):
        assert o == ref_latency_to_ps(c, 2.0)


def test_time_latency_host_types():
    t = Time.from_ns(5)
    assert t.ps == 5000
    t2 = t + Latency(cycles=8, freq_mhz=1000)
    assert t2.ps == 5000 + 8000
    assert (t2 - t).ps == 8000
    assert t2.to_ns() == 13
    assert Time(1500).to_ns() == 2  # ceil


def test_latency_add_requires_same_frequency():
    with pytest.raises(ValueError):
        Latency(1, 1000) + Latency(1, 2000)
    assert (Latency(2, 1000) + Latency(3, 1000)).cycles == 5


def test_int64_no_overflow():
    # 10 seconds of simulated time in ps exceeds int32
    t = jnp.asarray(10**13, dtype=jnp.int64)
    assert int(ps_to_ns(t)) == 10**10
