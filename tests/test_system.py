"""System services: checkpoint/resume, statistics traces, log, sim.out.

Checkpoint/resume is bitwise-exact (SURVEY §5 improvement over the
reference, which has none); statistics sampling mirrors
statistics_manager.cc trace output; Log mirrors misc/log.h filters.
"""

import os

import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine import Simulator
from graphite_tpu.system import (
    Log, StatisticsManager, load_checkpoint, save_checkpoint,
)
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder


def make_config(n_tiles=4, scheme="lax_barrier", extra=""):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = magic
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
[clock_skew_management]
scheme = {scheme}
[clock_skew_management/lax_barrier]
quantum = 1000
{extra}
"""
    return SimConfig(ConfigFile.from_string(text))


def mem_workload(n_tiles=4, n=40):
    builders = []
    for t in range(n_tiles):
        b = TraceBuilder()
        for i in range(n):
            b.store_value(t * 0x10000 + i * 64, i)
            b.load_check(t * 0x10000 + i * 64, i)
        builders.append(b)
    return TraceBatch.from_builders(builders)


class TestCheckpoint:
    def test_resume_is_bitwise_identical(self, tmp_path):
        sc = make_config()
        batch = mem_workload()
        # uninterrupted reference run
        ref = Simulator(sc, batch)
        r_ref = ref.run()

        # checkpointed run: a few quanta, save, restore into a NEW sim
        sim1 = Simulator(sc, batch)
        done, nq = sim1.run_chunk(3)
        assert not done
        ckpt = str(tmp_path / "ckpt.npz")
        save_checkpoint(sim1, ckpt, n_quanta=nq)

        sim2 = Simulator(sc, batch)
        resumed_quanta = load_checkpoint(sim2, ckpt)
        assert resumed_quanta == nq
        r2 = sim2.run()
        np.testing.assert_array_equal(r_ref.clock_ps, r2.clock_ps)
        np.testing.assert_array_equal(
            r_ref.instruction_count, r2.instruction_count)
        for k in r_ref.mem_counters:
            np.testing.assert_array_equal(
                r_ref.mem_counters[k], r2.mem_counters[k], err_msg=k)

    def test_round6_state_roundtrips_bitwise(self, tmp_path):
        """Explicit save -> load -> continue hardening for the round-6
        state additions: per-phase gate skip counters (mem.phase_skips)
        and the directory write-staging fields (directory.skey/sval/sn).
        The loaded state must equal the saved one leaf-for-leaf, and the
        continued run must finish bit-identical to an uninterrupted one
        — including the skip counters themselves."""
        import jax

        sc = make_config()
        batch = mem_workload()
        # force the round-6 machinery on: per-phase conds live (the
        # whole-engine gate off) + the staging table allocated
        kw = dict(dir_stage=True, phase_gate=True, mem_gate_bytes=0)
        ref = Simulator(sc, batch, **kw)
        r_ref = ref.run()
        ref_skips = ref.last_phase_skips

        sim1 = Simulator(sc, batch, **kw)
        done, nq = sim1.run_chunk(3)
        assert not done
        ckpt = str(tmp_path / "ckpt6.npz")
        save_checkpoint(sim1, ckpt, n_quanta=nq)
        sim2 = Simulator(sc, batch, **kw)
        load_checkpoint(sim2, ckpt)

        # staging is genuinely present in this state (the fields the
        # round-6 work added must be exercised, not None-elided)
        assert sim1.state.mem.directory.skey is not None
        assert sim1.state.mem.directory.sn is not None
        assert sim1.state.mem.phase_skips is not None

        # leaf-for-leaf bit equality of the restored tree
        flat1, _ = jax.tree_util.tree_flatten_with_path(sim1.state)
        flat2, _ = jax.tree_util.tree_flatten_with_path(sim2.state)
        assert len(flat1) == len(flat2)
        for (p1, l1), (p2, l2) in zip(flat1, flat2):
            assert p1 == p2
            np.testing.assert_array_equal(
                np.asarray(l1), np.asarray(l2), err_msg=str(p1))
            assert np.asarray(l1).dtype == np.asarray(l2).dtype, p1

        # continue: bit-identical completion, counters AND skip counters
        r2 = sim2.run()
        np.testing.assert_array_equal(r_ref.clock_ps, r2.clock_ps)
        np.testing.assert_array_equal(
            r_ref.instruction_count, r2.instruction_count)
        for k in r_ref.mem_counters:
            np.testing.assert_array_equal(
                r_ref.mem_counters[k], r2.mem_counters[k], err_msg=k)
        assert sim2.last_phase_skips == ref_skips

    def test_checkpoint_rejects_wrong_topology(self, tmp_path):
        sim4 = Simulator(make_config(4), mem_workload(4))
        ckpt = str(tmp_path / "c.npz")
        save_checkpoint(sim4, ckpt)
        sim2 = Simulator(make_config(2), mem_workload(2))
        with pytest.raises(ValueError):
            load_checkpoint(sim2, ckpt)


class TestStatistics:
    def test_sampled_run_writes_traces(self, tmp_path):
        extra = """
[statistics_trace]
enabled = true
statistics = "cache_line_replication, network_utilization"
sampling_interval = 2000
[progress_trace]
enabled = true
"""
        sc = make_config(extra=extra)
        sim = Simulator(sc, mem_workload())
        stats = StatisticsManager(sim, output_dir=str(tmp_path))
        results = stats.run()
        assert results.func_errors == 0
        rep = (tmp_path / "cache_line_replication.trace").read_text()
        assert len(rep.strip().splitlines()) >= 1
        net = (tmp_path / "network_utilization_memory.trace").read_text()
        assert len(net.strip().splitlines()) >= 1
        prog = (tmp_path / "progress.trace").read_text()
        assert len(prog.strip().splitlines()) >= 1

    def test_replication_histogram_counts_sharers(self):
        """All tiles read one line: its replication count = n_tiles."""
        sc = make_config(4)
        builders = []
        for t in range(4):
            b = TraceBuilder()
            if t == 0:
                b.barrier_init(0, 4)
                b.store_value(0x40, 7)
            b.barrier_wait(0)
            b.load_check(0x40, 7)
            builders.append(b)
        sim = Simulator(sc, TraceBatch.from_builders(builders))
        sim.run()
        stats = StatisticsManager(sim)
        hist = stats.replication_histogram()
        # the shared line is cached by all 4 tiles
        assert hist[3] >= 1

    def test_memory_message_count_approximation_pinned(self):
        """Pin the protocol-message approximation (2x misses req+rep +
        2x invalidations + evictions) before the round-9 backend split:
        the device-timeline backend reproduces the same formula over
        recorded deltas, so a silent constant change would desync the
        two backends' network_utilization_memory rows."""
        sim = Simulator(make_config(), mem_workload())
        stats = StatisticsManager(sim)
        mc = {"l2_misses": np.array([3, 1]),
              "invalidations": np.array([2, 0]),
              "evictions": np.array([5])}
        assert stats._memory_message_count(mc) == 2 * 4 + 2 * 2 + 5
        assert stats._memory_message_count(None) == 0.0

    def test_chunked_sampling_interval_arithmetic_pinned(self):
        """Pin the chunked loop's interval -> quanta arithmetic
        (sampling_interval floor-divided by the barrier quantum, never
        below one quantum)."""
        from graphite_tpu.system.statistics import chunk_quanta

        assert chunk_quanta(10000, 1_000_000) == 10   # the defaults
        assert chunk_quanta(2500, 1_000_000) == 2     # floor division
        assert chunk_quanta(500, 1_000_000) == 1      # sub-quantum
        assert chunk_quanta(1000, 1_000_000) == 1     # exactly one


class TestLogAndOutput:
    def test_log_filters_and_files(self, tmp_path):
        cfg = ConfigFile.from_string("""
[log]
enabled = true
disabled_modules = "network"
""")
        log = Log(cfg, output_dir=str(tmp_path))
        assert log.is_logging_enabled("core")
        assert not log.is_logging_enabled("network")
        log.log("core", "hello", tile_id=2, sim_time_ns=123)
        log.log("network", "dropped", tile_id=2)
        log.close()
        text = (tmp_path / "tile_2.log").read_text()
        assert "hello" in text and "[123ns]" in text
        assert "dropped" not in text
        with pytest.raises(AssertionError):
            log.assert_error(False, "core", "boom")

    def test_sim_out_written(self, tmp_path):
        sc = make_config()
        sim = Simulator(sc, mem_workload())
        results = sim.run()
        out = sim.write_output(results, output_dir=str(tmp_path))
        text = open(out).read()
        assert "Simulation Summary" in text
        assert "Tile 0 Summary" in text
        assert (tmp_path / "carbon_sim.cfg").exists()


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


class TestPowerTrace:
    def test_periodic_power_sampling(self, tmp_path):
        """[runtime_energy_modeling/power_trace] writes per-interval
        per-tile energy:power rows (`tile_energy_monitor.h:29`)."""
        extra = """
[statistics_trace]
enabled = false
sampling_interval = 2000
[runtime_energy_modeling/power_trace]
enabled = true
"""
        sc = make_config(extra=extra)
        sim = Simulator(sc, mem_workload())
        stats = StatisticsManager(sim, output_dir=str(tmp_path))
        stats.run()
        rows = (tmp_path / "power.trace").read_text().strip().splitlines()
        assert len(rows) >= 1
        t, first = rows[-1].split(" ", 1)
        cells = first.split()
        assert len(cells) == sim.params.n_tiles
        e, p = cells[0].split(":")
        assert float(e) > 0.0   # cumulative energy
        assert float(p) >= 0.0  # interval power
