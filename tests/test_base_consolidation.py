"""Round-12 base consolidation: one packed directory working-set gather
and one merged row scatter per engine iteration, bit-identical to the
round-11 per-phase layout, plus the budget ratchet that locks the win in.

The structural claims are jaxpr-level (via the shared analysis/walk
traversal) at a 1024-tile shape — the config-5 regime the consolidation
exists for; the equivalence claims are randomized-trace bit-identity
(consolidated vs round-11 layout) and serialized-trace golden-oracle
exactness for both memory engines.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from graphite_tpu.config import ConfigFile, SimConfig
from graphite_tpu.engine.simulator import Simulator
from graphite_tpu.golden import run_golden
from graphite_tpu.trace import synthetic
from graphite_tpu.trace.schema import TraceBatch, TraceBuilder

MSI = "pr_l1_pr_l2_dram_directory_msi"
MOSI = "pr_l1_pr_l2_dram_directory_mosi"
SHL2_MESI = "pr_l1_sh_l2_mesi"


def make_config(n_tiles, proto=MSI, extra=""):
    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = magic
[caching_protocol]
type = {proto}
[core/static_instruction_costs]
mov = 1
ialu = 1
{extra}
"""
    return SimConfig(ConfigFile.from_string(text))


def mutex_rmw(n, rounds, base=0x900000, lines=2):
    bs = [TraceBuilder() for _ in range(n)]
    bs[0].mutex_init(0)
    bs[0].barrier_init(9, n)
    for b in bs:
        b.barrier_wait(9)
    for r in range(n * rounds):
        t = r % n
        addr = base + (r % lines) * 64
        bs[t].mutex_lock(0)
        bs[t].load(addr, 8)
        bs[t].store(addr, 8)
        bs[t].mutex_unlock(0)
    return TraceBatch.from_builders(bs)


def _assert_results_equal(ra, rb):
    np.testing.assert_array_equal(np.asarray(ra.clock_ps),
                                  np.asarray(rb.clock_ps))
    np.testing.assert_array_equal(np.asarray(ra.instruction_count),
                                  np.asarray(rb.instruction_count))
    for k in ra.mem_counters:
        np.testing.assert_array_equal(np.asarray(ra.mem_counters[k]),
                                      np.asarray(rb.mem_counters[k]),
                                      err_msg=k)


# ---- program structure at the 1024-tile shape -----------------------------

# same unique-aval geometry trick as test_phase_gating: the directory
# entry/sharers avals must not collide with any cache meta array
GEOM = """
[l1_icache/T1]
cache_size = 4
associativity = 2
[l1_dcache/T1]
cache_size = 8
associativity = 4
[l2_cache/T1]
cache_size = 32
associativity = 8
[dram_directory]
total_entries = 64
associativity = 4
"""


def _big_shape_sim(T=1024, **kw):
    sc = make_config(T, MSI, extra=GEOM)
    bs = []
    for t in range(T):
        b = TraceBuilder()
        b.load(0x100000 + t * 64, 8)
        b.store(0x100000 + (t % 7) * 64, 8)
        bs.append(b)
    batch = TraceBatch.from_builders(bs)
    sim = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0, **kw)
    assert sim.params.mem_gate is False
    return sim


def _iteration_jaxpr(sim):
    from graphite_tpu.engine.step import subquantum_iteration

    qend = jnp.asarray(2**61, jnp.int64)
    return jax.make_jaxpr(
        lambda st: subquantum_iteration(sim.params, sim.device_trace,
                                        st, qend))(sim.state)


def _store_ops(closed, sig):
    """(gathers, scatters) on the store with aval signature `sig` at any
    depth of the iteration program."""
    from graphite_tpu.analysis.walk import aval_sig, iter_eqns

    gathers, scatters = 0, 0
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        in_sigs = [aval_sig(v.aval) for v in eqn.invars
                   if not isinstance(v, jax.core.Literal)]
        if name == "gather" and in_sigs and in_sigs[0] == sig:
            gathers += 1
        if name.startswith("scatter") and in_sigs and in_sigs[0] == sig:
            scatters += 1
    return gathers, scatters


def test_one_gather_one_merged_scatter_1024_shape():
    """The consolidated iteration touches each big directory store
    exactly once in each direction: ONE packed working-set row gather up
    front, ONE merged row scatter at the end — for the sharers store AND
    the packed entry-word store."""
    sim = _big_shape_sim()
    closed = _iteration_jaxpr(sim)
    d = sim.state.mem.directory
    sharers_sig = (tuple(d.sharers.shape), str(d.sharers.dtype))
    entry_sig = (tuple(d.entry.shape), str(d.entry.dtype))

    g, s = _store_ops(closed, sharers_sig)
    assert (g, s) == (1, 1), (
        f"sharers store: expected exactly one row gather and one merged "
        f"row scatter per iteration, found {g} gather(s), {s} "
        f"scatter(s)")
    g, s = _store_ops(closed, entry_sig)
    assert (g, s) == (1, 1), (
        f"entry store: expected exactly one row gather and one merged "
        f"row scatter per iteration, found {g} gather(s), {s} "
        f"scatter(s)")


def test_staged_iteration_has_no_sharers_scatter_1024_shape():
    """With directory write-staging the iteration still gathers the
    sharers store exactly once (overlaying the per-lane staging rows)
    but never scatters it — the amortized flush outside the iteration
    is the store's only writer."""
    sim = _big_shape_sim(dir_stage=True, inner_block=4)
    closed = _iteration_jaxpr(sim)
    d = sim.state.mem.directory
    sharers_sig = (tuple(d.sharers.shape), str(d.sharers.dtype))
    g, s = _store_ops(closed, sharers_sig)
    assert (g, s) == (1, 0), (g, s)


def test_phase_conds_survive_consolidation_1024_shape():
    """The six per-phase gating conds are unchanged in count — the
    consolidation moves the big-store traffic out of the phases, not
    the phases themselves."""
    from graphite_tpu.analysis.rules import phase_conds

    sim = _big_shape_sim()
    closed = _iteration_jaxpr(sim)
    assert len(phase_conds(closed, 1024)) == 6


# ---- bit-identity: consolidated vs round-11 layout ------------------------


@pytest.mark.parametrize("proto", [MSI, MOSI])
@pytest.mark.parametrize("gate", [True, False])
def test_consolidated_matches_round11_randomized(proto, gate):
    """Randomized coherence traffic: the consolidated base must be
    bit-identical to the round-11 per-phase layout, gated and ungated."""
    sc = make_config(8, proto)
    for seed in (3, 11):
        batch = synthetic.memory_stress_trace(
            8, n_accesses=40, working_set_bytes=1 << 12,
            write_fraction=0.4, shared_fraction=0.6, seed=seed)
        r_new = Simulator(sc, batch, phase_gate=gate,
                          mem_gate_bytes=0).run()
        r_old = Simulator(sc, batch, phase_gate=gate, mem_gate_bytes=0,
                          base_consolidate=False).run()
        _assert_results_equal(r_new, r_old)


def test_consolidated_staged_matches_round11():
    """Consolidation composes with directory write-staging (per-lane
    rows, round 12): staged consolidated == staged round-11 layout ==
    unstaged, on shared-line traffic crossing many flush boundaries."""
    sc = make_config(8, MSI)
    batch = synthetic.memory_stress_trace(
        8, n_accesses=40, working_set_bytes=1 << 12,
        write_fraction=0.5, shared_fraction=0.7, seed=5)
    r_new = Simulator(sc, batch, mem_gate_bytes=0, dir_stage=True,
                      inner_block=4).run()
    r_old = Simulator(sc, batch, mem_gate_bytes=0, dir_stage=True,
                      inner_block=4, base_consolidate=False).run()
    r_uns = Simulator(sc, batch, mem_gate_bytes=0, dir_stage=False,
                      inner_block=4).run()
    _assert_results_equal(r_new, r_old)
    _assert_results_equal(r_new, r_uns)


# ---- sharded staging: the standing dir_stage gap, closed ------------------


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs 8 virtual devices")
def test_sharded_dir_stage_matches_single_device():
    """Round 12 closes the "dir_stage is single-device" gap: the
    per-lane staging rows shard with the directory and ride the
    consolidated working-set gather block-locally, so a meshed staged
    run must be bit-identical to the single-device staged (and
    unstaged) runs."""
    from graphite_tpu.parallel.mesh import make_tile_mesh
    from graphite_tpu.tools._template import coherence_stress_workload

    sc, batch = coherence_stress_workload(64, protocol=MSI)
    r_solo = Simulator(sc, batch, dir_stage=True, inner_block=4).run()
    r_mesh = Simulator(sc, batch, dir_stage=True, inner_block=4,
                       mesh=make_tile_mesh(8)).run()
    r_uns = Simulator(sc, batch, dir_stage=False, inner_block=4).run()
    _assert_results_equal(r_solo, r_mesh)
    _assert_results_equal(r_solo, r_uns)
    assert int(np.asarray(r_solo.mem_counters["l2_misses"]).sum()) > 0


def test_legacy_layout_refuses_sharded_staging():
    from graphite_tpu.parallel.mesh import make_tile_mesh
    from graphite_tpu.tools._template import coherence_stress_workload

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    sc, batch = coherence_stress_workload(64, protocol=MSI)
    with pytest.raises(ValueError, match="base_consolidate"):
        Simulator(sc, batch, dir_stage=True, mesh=make_tile_mesh(8),
                  base_consolidate=False)


# ---- golden-oracle exactness (serialized traffic) -------------------------


@pytest.mark.parametrize("proto", [MSI, MOSI, SHL2_MESI])
def test_consolidated_golden_exact(proto):
    """Serialized RMW traffic: the consolidated engines (private-L2 MSI/
    MOSI and shared-L2 MESI) stay bit-exact vs the golden interpreters."""
    sc = make_config(4, proto)
    batch = mutex_rmw(4, 4, lines=3)
    res = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps)
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)


def test_consolidated_staged_golden_exact():
    sc = make_config(4, MSI)
    batch = mutex_rmw(4, 4, lines=3)
    res = Simulator(sc, batch, phase_gate=True, mem_gate_bytes=0,
                    dir_stage=True, inner_block=4).run()
    gold = run_golden(sc, batch)
    np.testing.assert_array_equal(res.clock_ps, gold.clock_ps)
    for k, g in gold.mem_counters.items():
        np.testing.assert_array_equal(np.asarray(res.mem_counters[k]), g,
                                      err_msg=k)


# ---- the budget ratchet ---------------------------------------------------


def _fake_report(name="gated-msi", kernels=100, tiles=8):
    from graphite_tpu.analysis.cost import CostReport

    return CostReport(
        program=name, tiles=tiles, n_eqns_total=kernels,
        kernels_per_iter=kernels, bytes_per_iter=10 * kernels,
        arg_bytes=64, out_bytes=64, peak_bytes=1024)


def test_ratchet_refuses_raised_ceiling(tmp_path):
    from graphite_tpu.analysis.cost import (
        BudgetRatchetError, load_budgets, save_budgets,
    )

    path = str(tmp_path / "budgets.json")
    save_budgets([_fake_report(kernels=100)], path)
    # a lower re-measurement ratchets down fine
    save_budgets([_fake_report(kernels=50)], path, ratchet=True)
    assert load_budgets(path)["gated-msi"]["measured"][
        "kernels_per_iter"] == 50
    # a higher one is refused, and the file is untouched
    with pytest.raises(BudgetRatchetError) as e:
        save_budgets([_fake_report(kernels=90)], path, ratchet=True)
    assert "kernels_per_iter" in str(e.value)
    assert load_budgets(path)["gated-msi"]["measured"][
        "kernels_per_iter"] == 50
    # unless the raised metrics are named explicitly
    save_budgets([_fake_report(kernels=90)], path, ratchet=True,
                 allow_increase=("kernels_per_iter", "n_eqns_total",
                                 "bytes_per_iter"))
    assert load_budgets(path)["gated-msi"]["measured"][
        "kernels_per_iter"] == 90


def test_ratchet_cli_self_test(tmp_path, capsys):
    """The CLI fixture: a ratcheted --budget-update against ceilings
    tightened below the real program's cost MUST exit nonzero and write
    nothing — the refusal is the self-test that the ratchet gates."""
    from graphite_tpu.tools.audit import main

    budgets = str(tmp_path / "budgets.json")
    no_lock = str(tmp_path / "absent.lock")
    rc = main(["--programs", "gated-msi", "--budget-update",
               "--budgets-file", budgets, "--lock-file", no_lock])
    assert rc == 0
    with open(budgets) as f:
        data = json.load(f)
    # tighten every ceiling below what the program actually measures
    for m, v in data["gated-msi"]["measured"].items():
        data["gated-msi"]["ceiling"][m] = max(int(v) - 1, 0)
    with open(budgets, "w") as f:
        json.dump(data, f)
    rc = main(["--programs", "gated-msi", "--budget-update", "--ratchet",
               "--budgets-file", budgets, "--lock-file", no_lock])
    out = capsys.readouterr().out
    assert rc == 1
    assert "budget_ratchet_refused" in out
    with open(budgets) as f:
        after = json.load(f)
    assert after["gated-msi"]["ceiling"] == data["gated-msi"]["ceiling"]
    # naming every metric lets the refresh through
    rc = main(["--programs", "gated-msi", "--budget-update", "--ratchet",
               "--budgets-file", budgets, "--lock-file", no_lock]
              + sum((["--allow-increase", m] for m in
                     data["gated-msi"]["measured"]), []))
    assert rc == 0


def test_ratchet_flag_combinations():
    from graphite_tpu.tools.audit import main

    with pytest.raises(SystemExit):
        main(["--ratchet"])                       # needs --budget-update
    with pytest.raises(SystemExit):
        main(["--budget-update", "--allow-increase",
              "kernels_per_iter"])                # needs --ratchet
    with pytest.raises(SystemExit):
        main(["--budget-update", "--ratchet", "--allow-increase",
              "not_a_metric"])                    # unknown metric
